"""Model-family smoke/learning tests (tiny real computations on CPU —
the reference's test trick, SURVEY.md §4: no mocked math, just small real
models)."""

import numpy as np
import pytest

import jax
import jax.flatten_util
import jax.numpy as jnp


class TestMNIST:
  def test_mlp_learns(self):
    from tensorflowonspark_tpu.models import mnist
    images, labels = mnist.synthetic_dataset(256, seed=1)
    state = mnist.create_state(jax.random.PRNGKey(0))
    first = last = None
    for step in range(20):
      state, loss = mnist.train_step(state, images[:64], labels[:64])
      first = float(loss) if first is None else first
      last = float(loss)
    assert last < first * 0.5

  def test_cnn_shapes(self):
    from tensorflowonspark_tpu.models import mnist
    state = mnist.create_state(jax.random.PRNGKey(0), model=mnist.CNN())
    images, labels = mnist.synthetic_dataset(8)
    state, loss = mnist.train_step(state, images, labels)
    assert np.isfinite(float(loss))

  def test_eval_accuracy_on_learnable_data(self):
    from tensorflowonspark_tpu.models import mnist
    images, labels = mnist.synthetic_dataset(128, seed=2)
    state = mnist.create_state(jax.random.PRNGKey(0))
    for _ in range(30):
      state, _ = mnist.train_step(state, images, labels)
    _, acc = mnist.eval_step(state, images, labels)
    assert float(acc) > 0.9


class TestResNet:
  def test_resnet56_cifar_step(self):
    from tensorflowonspark_tpu.models import resnet
    model = resnet.ResNet56CIFAR()
    state = resnet.create_state(jax.random.PRNGKey(0), model,
                                image_shape=(32, 32, 3),
                                learning_rate=0.01)
    rng = np.random.RandomState(0)
    images = jnp.asarray(rng.rand(8, 32, 32, 3), jnp.float32)
    labels = jnp.asarray(rng.randint(0, 10, 8), jnp.int32)
    state, loss = resnet.train_step(state, images, labels)
    assert np.isfinite(float(loss))
    # batch stats must have been updated by the step
    stem_mean = state.batch_stats["stem_bn"]["mean"]
    assert float(jnp.abs(stem_mean).sum()) > 0

  @pytest.mark.slow
  def test_resnet50_forward_shape(self):
    # Marked slow (tier-1 budget audit): ~15 s to build/init ResNet-50
    # for a shape-only assertion; test_resnet56_cifar_step trains a
    # real residual net in tier-1. Runs via `make test`.
    from tensorflowonspark_tpu.models import resnet
    model = resnet.ResNet50(num_classes=1000)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, 64, 64, 3)), train=False)
    logits = model.apply(variables, jnp.zeros((2, 64, 64, 3)), train=False)
    assert logits.shape == (2, 1000)


class TestSegmentation:
  def test_unet_learns_circles(self):
    from tensorflowonspark_tpu.models import segmentation as seg
    images, masks = seg.synthetic_dataset(16, size=64, seed=0)
    state = seg.create_state(jax.random.PRNGKey(0),
                             model=seg.UNet(encoder_filters=(8, 16)),
                             image_shape=(64, 64, 3))
    first = last = None
    for _ in range(10):
      state, loss = seg.train_step(state, jnp.asarray(images),
                                   jnp.asarray(masks))
      first = float(loss) if first is None else first
      last = float(loss)
    assert last < first


class TestTransformer:
  def test_remat_policy_numerics_invariant(self):
    """remat is a memory/compute trade, never a numerics one: loss and
    grads agree across remat off / full recompute / dots-saveable
    (selective) policies at identical params."""
    import dataclasses
    from tensorflowonspark_tpu.models import transformer as tfm
    base = tfm.TransformerConfig(vocab_size=32, num_layers=2, num_heads=2,
                                 d_model=32, d_ff=64, max_seq_len=16,
                                 remat=False, dtype=jnp.float32)
    state = tfm.create_state(jax.random.PRNGKey(0), base, seq_len=16)
    tokens = jnp.asarray(
        np.random.RandomState(0).randint(0, 32, (2, 16)), jnp.int32)

    def lossgrad(cfg):
      def loss(p):
        return tfm.causal_lm_loss(
            tfm.Transformer(cfg, None).apply({"params": p}, tokens),
            tokens)
      return jax.value_and_grad(loss)(state.params)

    l0, g0 = lossgrad(base)
    for policy in ("none", "dots"):
      cfg = dataclasses.replace(base, remat=True, remat_policy=policy)
      l1, g1 = lossgrad(cfg)
      np.testing.assert_allclose(float(l0), float(l1), rtol=1e-6)
      f0, _ = jax.flatten_util.ravel_pytree(g0)
      f1, _ = jax.flatten_util.ravel_pytree(g1)
      np.testing.assert_allclose(np.asarray(f0), np.asarray(f1),
                                 atol=1e-5, rtol=1e-5)
    with pytest.raises(ValueError, match="remat_policy"):
      tfm.TransformerConfig(remat_policy="everything")

  def test_greedy_generate_learns_cycle(self):
    """Train on a repeating token cycle; generation must continue it."""
    from tensorflowonspark_tpu.models import transformer as tfm
    cfg = tfm.TransformerConfig(vocab_size=16, num_layers=2, num_heads=2,
                                d_model=64, d_ff=128, remat=False)
    state = tfm.create_state(jax.random.PRNGKey(0), cfg,
                             learning_rate=3e-3, seq_len=24)
    cycle = np.tile(np.arange(8), 10)
    tokens = jnp.asarray(np.stack([cycle[i:i + 24] for i in range(8)]),
                         jnp.int32)

    @jax.jit
    def step(state, tokens):
      def loss_fn(p):
        return tfm.causal_lm_loss(
            state.apply_fn({"params": p}, tokens), tokens)
      loss, grads = jax.value_and_grad(loss_fn)(state.params)
      return state.apply_gradients(grads=grads), loss

    for _ in range(150):
      state, loss = step(state, tokens)
    assert float(loss) < 0.1, float(loss)

    prompt = jnp.asarray([[0, 1, 2, 3]], jnp.int32)
    out = tfm.greedy_generate(state.params, cfg, prompt, num_steps=8)
    generated = np.asarray(out[0, 4:])
    np.testing.assert_array_equal(generated,
                                  [4, 5, 6, 7, 0, 1, 2, 3])

  def test_kv_cache_generate_matches_recompute(self):
    """The KV-cache decode path must agree with full-recompute decoding:
    logits numerically close on the prefill, token streams identical on a
    trained (decisive-logits) model."""
    from tensorflowonspark_tpu.models import transformer as tfm
    cfg = tfm.TransformerConfig(vocab_size=16, num_layers=2, num_heads=2,
                                d_model=64, d_ff=128, max_seq_len=32,
                                remat=False, dtype=jnp.float32)
    state = tfm.create_state(jax.random.PRNGKey(3), cfg,
                             learning_rate=3e-3, seq_len=24)

    # prefill logits: decode path vs normal forward
    model = tfm.Transformer(cfg)
    prompt = jnp.asarray([[5, 9, 2, 11], [1, 1, 7, 0]], jnp.int32)
    ref_logits = model.apply({"params": state.params}, prompt)
    cache = jax.tree.map(
        jnp.zeros_like,
        model.init(jax.random.PRNGKey(0), jnp.zeros((2, 1), jnp.int32),
                   decode=True)["cache"])
    kv_logits, _ = model.apply({"params": state.params, "cache": cache},
                               prompt, decode=True, mutable=["cache"])
    np.testing.assert_allclose(np.asarray(kv_logits),
                               np.asarray(ref_logits), atol=1e-4,
                               rtol=1e-4)

    # train until the model is decisive, then token streams must be equal
    cycle = np.tile(np.arange(8), 10)
    tokens = jnp.asarray(np.stack([cycle[i:i + 24] for i in range(8)]),
                         jnp.int32)

    @jax.jit
    def step(state, tokens):
      def loss_fn(p):
        return tfm.causal_lm_loss(
            state.apply_fn({"params": p}, tokens), tokens)
      loss, grads = jax.value_and_grad(loss_fn)(state.params)
      return state.apply_gradients(grads=grads), loss

    for _ in range(150):
      state, _ = step(state, tokens)
    prompt = jnp.asarray([[0, 1, 2, 3]], jnp.int32)
    full = tfm.greedy_generate(state.params, cfg, prompt, num_steps=10)
    kv = tfm.greedy_generate_kv(state.params, cfg, prompt, num_steps=10)
    np.testing.assert_array_equal(np.asarray(kv), np.asarray(full))

  def test_eos_early_stop_matches_plain_decode(self):
    """greedy_generate_kv(eos_id=...) agrees with the eos-free decode up
    to (and including) each row's stop position; every later position is
    the pad id — the per-sequence-stop satellite, and the primitive the
    serving engine's slot-free logic reuses."""
    from tensorflowonspark_tpu.models import transformer as tfm
    cfg = tfm.TransformerConfig(vocab_size=16, num_layers=2, num_heads=2,
                                d_model=32, d_ff=64, max_seq_len=32,
                                remat=False, dtype=jnp.float32)
    state = tfm.create_state(jax.random.PRNGKey(5), cfg, seq_len=16)
    prompt = jnp.asarray([[5, 9, 2, 11], [1, 1, 7, 0], [3, 3, 3, 3]],
                         jnp.int32)
    steps, pad = 12, 15
    plain = np.asarray(tfm.greedy_generate_kv(state.params, cfg, prompt,
                                              steps))
    # pick an eos that actually fires for at least one row mid-stream
    gen = plain[:, 4:]
    eos = int(gen[0, steps // 2])
    assert eos != pad
    out = np.asarray(tfm.greedy_generate_kv(state.params, cfg, prompt,
                                            steps, eos_id=eos, pad_id=pad))
    fired = 0
    for row in range(prompt.shape[0]):
      stops = np.where(gen[row] == eos)[0]
      stop = (int(stops[0]) + 1) if len(stops) else steps
      np.testing.assert_array_equal(out[row, :4 + stop],
                                    plain[row, :4 + stop])
      assert (out[row, 4 + stop:] == pad).all(), (row, out[row])
      fired += bool(len(stops))
    assert fired >= 1, "chosen eos never fired; test proves nothing"

  def test_eos_pad_collision_rejected(self):
    from tensorflowonspark_tpu.models import transformer as tfm
    cfg = tfm.TransformerConfig(vocab_size=16, num_layers=1, num_heads=2,
                                d_model=32, d_ff=64, max_seq_len=16,
                                remat=False)
    state = tfm.create_state(jax.random.PRNGKey(0), cfg, seq_len=8)
    with pytest.raises(ValueError, match="eos_id and pad_id"):
      tfm.greedy_generate_kv(state.params, cfg,
                             jnp.asarray([[1, 2]], jnp.int32), 4,
                             eos_id=0, pad_id=0)

  def test_chunked_prefill_into_warm_cache_matches(self):
    """The idx > 0 chunked-prefill decode path: pushing a prompt through
    the cache in two apply calls (fresh-cache chunk, then a warm-cache
    insert) produces the same last-position logits and the same
    subsequent greedy stream as one whole-prompt prefill."""
    from tensorflowonspark_tpu.models import transformer as tfm
    cfg = tfm.TransformerConfig(vocab_size=16, num_layers=2, num_heads=2,
                                d_model=32, d_ff=64, max_seq_len=32,
                                remat=False, dtype=jnp.float32)
    state = tfm.create_state(jax.random.PRNGKey(3), cfg, seq_len=16)
    model = tfm.Transformer(cfg)
    prompt = jnp.asarray([[5, 9, 2, 11, 4, 1, 8, 14, 2, 6, 0, 12]],
                         jnp.int32)

    whole, _ = model.apply(
        {"params": state.params, "cache": tfm._zero_cache(model, 1)},
        prompt, decode=True, mutable=["cache"])
    l1, mut = model.apply(
        {"params": state.params, "cache": tfm._zero_cache(model, 1)},
        prompt[:, :8], decode=True, mutable=["cache"])
    l2, mut = model.apply({"params": state.params, "cache": mut["cache"]},
                          prompt[:, 8:], decode=True, mutable=["cache"])
    np.testing.assert_allclose(np.asarray(l2[:, -1]),
                               np.asarray(whole[:, -1]),
                               atol=1e-4, rtol=1e-4)
    # greedy continuation from the chunk-filled cache matches the
    # single-prefill serving decode stream
    cache, toks = mut["cache"], []
    tok = jnp.argmax(l2[:, -1], -1).astype(jnp.int32)
    toks.append(int(tok[0]))
    for _ in range(5):
      lg, mut = model.apply({"params": state.params, "cache": cache},
                            tok[:, None], decode=True, mutable=["cache"])
      cache = mut["cache"]
      tok = jnp.argmax(lg[:, -1], -1).astype(jnp.int32)
      toks.append(int(tok[0]))
    ref = np.asarray(tfm.greedy_generate_kv(state.params, cfg, prompt,
                                            6))[0, prompt.shape[1]:]
    np.testing.assert_array_equal(np.asarray(toks, np.int32), ref)

  def test_moe_transformer_learns(self):
    """MoE layers inside the flagship model: trains, and the aux loss is
    exposed through intermediates."""
    from tensorflowonspark_tpu.models import transformer as tfm
    cfg = tfm.TransformerConfig(vocab_size=32, num_layers=2, num_heads=2,
                                d_model=32, d_ff=64, remat=False,
                                dtype=jnp.float32, moe_experts=4,
                                moe_top_k=2, moe_every=2)
    state = tfm.create_state(jax.random.PRNGKey(0), cfg,
                             learning_rate=3e-3, seq_len=16)
    assert "moe" in state.params["layer_1"]      # layer 1 is the MoE layer
    assert "mlp" in state.params["layer_0"]
    tokens = jnp.asarray(np.tile(np.arange(16) % 8, (4, 1)), jnp.int32)

    @jax.jit
    def step(state, tokens):
      def loss_fn(p):
        logits, inter = state.apply_fn(
            {"params": p}, tokens, mutable=["intermediates"])
        aux = sum(jax.tree.leaves(inter["intermediates"]))
        return tfm.causal_lm_loss(logits, tokens) + 0.01 * aux
      loss, grads = jax.value_and_grad(loss_fn)(state.params)
      return state.apply_gradients(grads=grads), loss

    losses = []
    for _ in range(30):
      state, loss = step(state, tokens)
      losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.8, losses[:3] + losses[-3:]

  def test_sampling_generation(self):
    from tensorflowonspark_tpu.models import transformer as tfm
    cfg = tfm.TransformerConfig(vocab_size=32, num_layers=1, num_heads=2,
                                d_model=32, d_ff=64, max_seq_len=32,
                                remat=False, dtype=jnp.float32)
    state = tfm.create_state(jax.random.PRNGKey(0), cfg, seq_len=8)
    prompt = jnp.asarray([[1, 2, 3]], jnp.int32)
    a = tfm.greedy_generate_kv(state.params, cfg, prompt, 10,
                               temperature=1.0, top_k=5,
                               rng=jax.random.PRNGKey(1))
    b = tfm.greedy_generate_kv(state.params, cfg, prompt, 10,
                               temperature=1.0, top_k=5,
                               rng=jax.random.PRNGKey(2))
    assert a.shape == (1, 13)
    # different rng -> (almost surely) different samples; same rng -> same
    c = tfm.greedy_generate_kv(state.params, cfg, prompt, 10,
                               temperature=1.0, top_k=5,
                               rng=jax.random.PRNGKey(1))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(c))
    assert not np.array_equal(np.asarray(a), np.asarray(b))

  def test_sharded_decode_matches_single_device(self):
    """Tensor-parallel KV-cache decode (heads + cache over the `tensor`
    axis, batch over `data`) produces token-for-token the single-device
    result — the multi-chip serving path (reference TFModel.scala:245-292
    scaled past one chip, round-4 verdict item 4)."""
    from tensorflowonspark_tpu.models import transformer as tfm
    from tensorflowonspark_tpu.parallel import mesh as mesh_lib
    cfg = tfm.TransformerConfig(vocab_size=128, num_layers=2, num_heads=4,
                                num_kv_heads=2, d_model=64, d_ff=128,
                                max_seq_len=32, remat=False,
                                dtype=jnp.float32)
    state = tfm.create_state(jax.random.PRNGKey(0), cfg, seq_len=16)
    prompt = jnp.asarray(
        np.random.RandomState(0).randint(0, 128, (4, 8)), jnp.int32)
    ref = tfm.greedy_generate_kv(state.params, cfg, prompt, 6)
    mesh = mesh_lib.build_mesh(mesh_lib.MeshSpec(data=-1, tensor=2))
    out = tfm.greedy_generate_kv(state.params, cfg, prompt, 6, mesh=mesh)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(out))
    # a RAGGED batch (3 rows on a data=4 mesh — pipeline.yield_batch's
    # final-batch shape) pads through and slices back, matching row-wise
    out3 = tfm.greedy_generate_kv(state.params, cfg, prompt[:3], 6,
                                  mesh=mesh)
    np.testing.assert_array_equal(np.asarray(ref)[:3], np.asarray(out3))
    # forced-flash + long prompt on the mesh: the prefill kernel runs
    # shard_mapped (heads over tensor, batch over data) and must match
    # the dense meshed decode token-for-token at this logit scale
    cfg_f = tfm.TransformerConfig(
        vocab_size=128, num_layers=2, num_heads=4, num_kv_heads=2,
        d_model=64, d_ff=128, max_seq_len=160, remat=False,
        dtype=jnp.float32, attention_impl="flash")
    cfg_d = tfm.TransformerConfig(
        vocab_size=128, num_layers=2, num_heads=4, num_kv_heads=2,
        d_model=64, d_ff=128, max_seq_len=160, remat=False,
        dtype=jnp.float32, attention_impl="dense")
    state_l = tfm.create_state(jax.random.PRNGKey(1), cfg_d, seq_len=16)
    long_prompt = jnp.asarray(
        np.random.RandomState(5).randint(0, 128, (4, 128)), jnp.int32)

    def meshed_prefill_logits(cfg):
      model = tfm.Transformer(cfg, mesh=mesh)
      cache = jax.tree.map(
          jnp.zeros_like,
          model.init(jax.random.PRNGKey(0), jnp.zeros((4, 1), jnp.int32),
                     decode=True)["cache"])
      logits, _ = model.apply(
          {"params": state_l.params, "cache": cache}, long_prompt,
          decode=True, mutable=["cache"])
      return np.asarray(logits)

    # logits, not tokens: blockwise softmax reorders sums (near-tied
    # argmax flips would make token equality environment-fragile)
    np.testing.assert_allclose(meshed_prefill_logits(cfg_f),
                               meshed_prefill_logits(cfg_d),
                               atol=1e-4, rtol=1e-4)

  # plen=128 marked slow (tier-1 budget audit): same kernel path at a
  # second block multiple — the 64 leg keeps the contract tier-1-pinned,
  # 128 runs via `make test`.
  @pytest.mark.parametrize(
      "plen", [64, pytest.param(128, marks=pytest.mark.slow)])
  def test_flash_prefill_matches_dense_decode(self, plen):
    """The serving prefill fast path is a pure substitution: the prefill
    LOGITS through the GQA flash kernel (forced flash = interpret mode on
    CPU) match the dense cache path within numerics (blockwise online
    softmax reorders the sums, so exact token equality would be an
    environment-fragile assertion on near-tied logits). plen=64 also pins
    that forcing flash engages below 128 — _flash_eligible's own
    divisibility rule decides, not a duplicated block constant."""
    from tensorflowonspark_tpu.models import transformer as tfm
    base = dict(vocab_size=64, num_layers=2, num_heads=4, num_kv_heads=2,
                d_model=32, d_ff=64, max_seq_len=160, remat=False,
                dtype=jnp.float32)
    cfg_flash = tfm.TransformerConfig(attention_impl="flash", **base)
    cfg_dense = tfm.TransformerConfig(attention_impl="dense", **base)
    state = tfm.create_state(jax.random.PRNGKey(0), cfg_dense, seq_len=16)
    prompt = jnp.asarray(
        np.random.RandomState(3).randint(0, 64, (2, plen)), jnp.int32)

    def prefill_logits(cfg):
      model = tfm.Transformer(cfg)
      cache = jax.tree.map(
          jnp.zeros_like,
          model.init(jax.random.PRNGKey(0), jnp.zeros((2, 1), jnp.int32),
                     decode=True)["cache"])
      logits, _ = model.apply({"params": state.params, "cache": cache},
                              prompt, decode=True, mutable=["cache"])
      return np.asarray(logits)

    np.testing.assert_allclose(prefill_logits(cfg_flash),
                               prefill_logits(cfg_dense),
                               atol=1e-4, rtol=1e-4)

  def test_speculative_decode_exactly_greedy(self):
    """Greedy speculative decoding is LOSSLESS: whatever the draft
    proposes, the emitted tokens are exactly the target's own greedy
    decode — checked with (a) the target as its own draft (full
    acceptance every round) and (b) an unrelated random draft (mostly
    rejections, exercising the bonus-token and rollback paths)."""
    from tensorflowonspark_tpu.models import transformer as tfm
    base = dict(vocab_size=32, num_layers=2, num_heads=2, d_model=32,
                d_ff=64, max_seq_len=64, remat=False, dtype=jnp.float32)
    cfg = tfm.TransformerConfig(**base)
    dcfg = tfm.TransformerConfig(**{**base, "num_layers": 1})
    state = tfm.create_state(jax.random.PRNGKey(0), cfg, seq_len=8)
    draft_other = tfm.create_state(jax.random.PRNGKey(9), dcfg, seq_len=8)
    prompt = jnp.asarray(
        np.random.RandomState(2).randint(0, 32, (2, 8)), jnp.int32)
    ref = np.asarray(tfm.greedy_generate_kv(state.params, cfg, prompt, 12))

    self_spec = tfm.speculative_generate_kv(
        state.params, cfg, state.params, cfg, prompt, 12, draft_k=4)
    np.testing.assert_array_equal(np.asarray(self_spec), ref)

    cross_spec = tfm.speculative_generate_kv(
        draft_other.params, dcfg, state.params, cfg, prompt, 12,
        draft_k=3)
    np.testing.assert_array_equal(np.asarray(cross_spec), ref)

    # composes with the int8 cache: exactness is vs the int8-cache
    # greedy (quantization shifts logits identically in both paths)
    cfg8 = tfm.TransformerConfig(kv_cache_dtype="int8", **base)
    ref8 = np.asarray(
        tfm.greedy_generate_kv(state.params, cfg8, prompt, 12))
    spec8 = tfm.speculative_generate_kv(
        draft_other.params, dcfg, state.params, cfg8, prompt, 12,
        draft_k=3)
    np.testing.assert_array_equal(np.asarray(spec8), ref8)

  def test_int8_kv_cache_close_and_compact(self):
    """kv_cache_dtype='int8': the cache leaves really are int8 (the
    serving-memory/HBM claim), decode runs end-to-end, and prefill logits
    stay within the ~0.4%-per-entry quantization envelope of the
    full-precision cache."""
    from tensorflowonspark_tpu.models import transformer as tfm
    base = dict(vocab_size=64, num_layers=2, num_heads=4, num_kv_heads=2,
                d_model=32, d_ff=64, max_seq_len=64, remat=False,
                dtype=jnp.float32)
    cfg8 = tfm.TransformerConfig(kv_cache_dtype="int8", **base)
    cfgm = tfm.TransformerConfig(**base)
    state = tfm.create_state(jax.random.PRNGKey(0), cfgm, seq_len=16)
    prompt = jnp.asarray(
        np.random.RandomState(7).randint(0, 64, (2, 16)), jnp.int32)

    cache8 = tfm.Transformer(cfg8).init(
        jax.random.PRNGKey(0), jnp.zeros((2, 1), jnp.int32),
        decode=True)["cache"]
    dtypes = {np.dtype(leaf.dtype) for leaf in jax.tree.leaves(cache8)}
    assert np.dtype(np.int8) in dtypes       # quantized values
    assert np.dtype(np.float32) in dtypes    # scales

    def prefill_logits(cfg):
      model = tfm.Transformer(cfg)
      cache = jax.tree.map(
          jnp.zeros_like,
          model.init(jax.random.PRNGKey(0), jnp.zeros((2, 1), jnp.int32),
                     decode=True)["cache"])
      logits, _ = model.apply({"params": state.params, "cache": cache},
                              prompt, decode=True, mutable=["cache"])
      return np.asarray(logits)

    np.testing.assert_allclose(prefill_logits(cfg8), prefill_logits(cfgm),
                               atol=0.15, rtol=0.15)
    out = tfm.greedy_generate_kv(state.params, cfg8, prompt, 6)
    assert out.shape == (2, 22)

  def test_kv_cache_respects_max_len(self):
    from tensorflowonspark_tpu.models import transformer as tfm
    cfg = tfm.TransformerConfig(vocab_size=8, num_layers=1, num_heads=2,
                                d_model=16, d_ff=32, max_seq_len=8,
                                remat=False)
    state = tfm.create_state(jax.random.PRNGKey(0), cfg, seq_len=4)
    with pytest.raises(ValueError, match="max_seq_len"):
      tfm.greedy_generate_kv(state.params, cfg,
                             jnp.zeros((1, 4), jnp.int32), num_steps=8)

  def test_single_device_learns(self):
    from tensorflowonspark_tpu.models import transformer as tfm
    cfg = tfm.TransformerConfig(vocab_size=32, num_layers=1, num_heads=2,
                                d_model=32, d_ff=64, remat=False)
    state = tfm.create_state(jax.random.PRNGKey(0), cfg,
                             learning_rate=1e-2, seq_len=16)
    tokens = jnp.asarray(np.tile(np.arange(16) % 8, (4, 1)), jnp.int32)

    @jax.jit
    def step(state, tokens):
      def loss_fn(p):
        return tfm.causal_lm_loss(
            state.apply_fn({"params": p}, tokens), tokens)
      loss, grads = jax.value_and_grad(loss_fn)(state.params)
      return state.apply_gradients(grads=grads), loss

    losses = [None]
    for _ in range(10):
      state, loss = step(state, tokens)
      losses.append(float(loss))
    assert losses[-1] < losses[1] * 0.8

  def test_forced_flash_matches_dense_in_model(self):
    """attention_impl="flash" trains the model through the Pallas kernels
    (interpret mode off-TPU) on the same trajectory as dense attention —
    the production attention path exercised by CPU CI."""
    from tensorflowonspark_tpu.models import transformer as tfm

    tokens = jnp.asarray(np.tile(np.arange(32) % 8, (4, 1)), jnp.int32)
    losses = {}
    for impl in ("dense", "flash"):
      cfg = tfm.TransformerConfig(vocab_size=32, num_layers=2, num_heads=2,
                                  d_model=32, d_ff=64, max_seq_len=32,
                                  remat=False, dtype=jnp.float32,
                                  attention_impl=impl)
      state = tfm.create_state(jax.random.PRNGKey(0), cfg,
                               learning_rate=1e-2, seq_len=32)

      @jax.jit
      def step(state, tokens):
        def loss_fn(p):
          return tfm.causal_lm_loss(
              state.apply_fn({"params": p}, tokens), tokens)
        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        return state.apply_gradients(grads=grads), loss

      traj = []
      for _ in range(4):
        state, loss = step(state, tokens)
        traj.append(float(loss))
      losses[impl] = traj
    np.testing.assert_allclose(losses["flash"], losses["dense"],
                               atol=2e-4, rtol=2e-4)

  def test_forced_flash_rejects_indivisible_seq(self):
    """attention_impl='flash' must fail loudly, never silently fall back
    to dense, when the sequence doesn't divide into kernel blocks."""
    import pytest
    from tensorflowonspark_tpu.models import transformer as tfm
    cfg = tfm.TransformerConfig(vocab_size=32, num_layers=1, num_heads=2,
                                d_model=32, d_ff=64, max_seq_len=192,
                                remat=False, attention_impl="flash")
    with pytest.raises(ValueError, match="divide into kernel blocks"):
      tfm.create_state(jax.random.PRNGKey(0), cfg, seq_len=192)

  def test_forced_flash_model_still_generates_unaligned_lengths(self):
    """greedy_generate's buffer (plen + num_steps) is an internal shape —
    a forced-flash model must generate at any length (the generate path
    degrades to auto/dense for unaligned buffers instead of raising)."""
    from tensorflowonspark_tpu.models import transformer as tfm
    cfg = tfm.TransformerConfig(vocab_size=32, num_layers=1, num_heads=2,
                                d_model=32, d_ff=64, max_seq_len=256,
                                remat=False, attention_impl="flash")
    state = tfm.create_state(jax.random.PRNGKey(0), cfg, seq_len=128)
    prompt = jnp.zeros((1, 2), jnp.int32)
    out = tfm.greedy_generate(state.params, cfg, prompt, num_steps=131)
    assert out.shape == (1, 133)          # 133 % 128 != 0: dense fallback

  def test_config_rejects_unknown_impls(self):
    import pytest
    from tensorflowonspark_tpu.models import transformer as tfm
    with pytest.raises(ValueError, match="attention_impl"):
      tfm.TransformerConfig(attention_impl="Flash")
    with pytest.raises(ValueError, match="layer_norm_impl"):
      tfm.TransformerConfig(layer_norm_impl="pallas")

  def test_gqa_config_validation(self):
    import pytest
    from tensorflowonspark_tpu.models import transformer as tfm
    with pytest.raises(ValueError, match="num_kv_heads"):
      tfm.TransformerConfig(num_heads=12, num_kv_heads=5)
    assert tfm.TransformerConfig(num_heads=12, num_kv_heads=4).kv_heads == 4
    assert tfm.TransformerConfig(num_heads=12).kv_heads == 12

  def test_gqa_cache_holds_only_kv_heads(self):
    """Under GQA the per-layer KV cache stores kv_heads heads — the
    num_heads/num_kv_heads serving-memory reduction is the point."""
    from tensorflowonspark_tpu.models import transformer as tfm
    cfg = tfm.TransformerConfig(vocab_size=16, num_layers=2, num_heads=4,
                                num_kv_heads=2, d_model=64, d_ff=128,
                                max_seq_len=32, remat=False,
                                dtype=jnp.float32)
    model = tfm.Transformer(cfg)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, 1), jnp.int32), decode=True)
    kv_arrays = [leaf for leaf in jax.tree.leaves(variables["cache"])
                 if getattr(leaf, "ndim", 0) == 4]
    assert kv_arrays, "no KV cache arrays found"
    for leaf in kv_arrays:
      assert leaf.shape[2] == 2, leaf.shape

  def test_gqa_kv_cache_matches_recompute(self):
    """GQA decode through the grouped-einsum cache path must agree with
    the full-recompute forward (which expands KV heads per group)."""
    from tensorflowonspark_tpu.models import transformer as tfm
    cfg = tfm.TransformerConfig(vocab_size=16, num_layers=2, num_heads=4,
                                num_kv_heads=1, d_model=64, d_ff=128,
                                max_seq_len=32, remat=False,
                                dtype=jnp.float32)
    state = tfm.create_state(jax.random.PRNGKey(3), cfg,
                             learning_rate=3e-3, seq_len=24)
    model = tfm.Transformer(cfg)
    prompt = jnp.asarray([[5, 9, 2, 11], [1, 1, 7, 0]], jnp.int32)
    ref_logits = model.apply({"params": state.params}, prompt)
    cache = jax.tree.map(
        jnp.zeros_like,
        model.init(jax.random.PRNGKey(0), jnp.zeros((2, 1), jnp.int32),
                   decode=True)["cache"])
    kv_logits, _ = model.apply({"params": state.params, "cache": cache},
                               prompt, decode=True, mutable=["cache"])
    np.testing.assert_allclose(np.asarray(kv_logits),
                               np.asarray(ref_logits), atol=1e-4,
                               rtol=1e-4)

  def test_gqa_learns_and_generates(self):
    """A grouped-KV model trains to a decisive solution and the KV-cache
    token stream equals full recompute."""
    from tensorflowonspark_tpu.models import transformer as tfm
    cfg = tfm.TransformerConfig(vocab_size=16, num_layers=2, num_heads=4,
                                num_kv_heads=2, d_model=64, d_ff=128,
                                max_seq_len=32, remat=False)
    state = tfm.create_state(jax.random.PRNGKey(0), cfg,
                             learning_rate=3e-3, seq_len=24)
    cycle = np.tile(np.arange(8), 10)
    tokens = jnp.asarray(np.stack([cycle[i:i + 24] for i in range(8)]),
                         jnp.int32)

    @jax.jit
    def step(state, tokens):
      def loss_fn(p):
        return tfm.causal_lm_loss(
            state.apply_fn({"params": p}, tokens), tokens)
      loss, grads = jax.value_and_grad(loss_fn)(state.params)
      return state.apply_gradients(grads=grads), loss

    for _ in range(150):
      state, loss = step(state, tokens)
    assert float(loss) < 0.1, float(loss)
    prompt = jnp.asarray([[0, 1, 2, 3]], jnp.int32)
    full = tfm.greedy_generate(state.params, cfg, prompt, num_steps=8)
    kv = tfm.greedy_generate_kv(state.params, cfg, prompt, num_steps=8)
    np.testing.assert_array_equal(np.asarray(kv), np.asarray(full))

  def test_fused_qkv_trains_and_decodes(self):
    """fuse_qkv=True (one projection matmul, sliced) must train to the
    cycle task and keep the KV-cache decode agreeing with recompute,
    composed with GQA."""
    from tensorflowonspark_tpu.models import transformer as tfm
    cfg = tfm.TransformerConfig(vocab_size=16, num_layers=2, num_heads=4,
                                num_kv_heads=2, d_model=64, d_ff=128,
                                max_seq_len=32, remat=False, fuse_qkv=True)
    state = tfm.create_state(jax.random.PRNGKey(0), cfg,
                             learning_rate=3e-3, seq_len=24)
    assert any("qkv" in "/".join(map(str, p))
               for p, _ in jax.tree_util.tree_flatten_with_path(
                   state.params)[0])
    cycle = np.tile(np.arange(8), 10)
    tokens = jnp.asarray(np.stack([cycle[i:i + 24] for i in range(8)]),
                         jnp.int32)

    @jax.jit
    def step(state, tokens):
      def loss_fn(p):
        return tfm.causal_lm_loss(
            state.apply_fn({"params": p}, tokens), tokens)
      loss, grads = jax.value_and_grad(loss_fn)(state.params)
      return state.apply_gradients(grads=grads), loss

    for _ in range(150):
      state, loss = step(state, tokens)
    assert float(loss) < 0.1, float(loss)
    prompt = jnp.asarray([[0, 1, 2, 3]], jnp.int32)
    full = tfm.greedy_generate(state.params, cfg, prompt, num_steps=8)
    kv = tfm.greedy_generate_kv(state.params, cfg, prompt, num_steps=8)
    np.testing.assert_array_equal(np.asarray(kv), np.asarray(full))

  def test_blocked_loss_matches_full(self):
    """causal_lm_loss_blocked (fused projection+xent, [B,chunk,V] peak
    memory) matches causal_lm_loss exactly in f32, including value AND
    gradients, at a sequence length that doesn't divide the chunk."""
    from tensorflowonspark_tpu.models import transformer as tfm
    cfg = tfm.TransformerConfig(vocab_size=97, num_layers=2, num_heads=2,
                                d_model=32, d_ff=64, max_seq_len=50,
                                dtype=jnp.float32)
    state = tfm.create_state(jax.random.PRNGKey(0), cfg, seq_len=50)
    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, 97, (3, 50)), jnp.int32)

    def loss_full(params):
      return tfm.causal_lm_loss(
          state.apply_fn({"params": params}, tokens), tokens)

    def loss_blocked(params):
      hidden = state.apply_fn({"params": params}, tokens,
                              return_hidden=True)
      return tfm.causal_lm_loss_blocked(
          hidden, tfm.tied_embedding_table(params), tokens, chunk=16)

    l1, g1 = jax.value_and_grad(loss_full)(state.params)
    l2, g2 = jax.value_and_grad(loss_blocked)(state.params)
    assert abs(float(l1) - float(l2)) < 1e-5, (float(l1), float(l2))
    err = max(jax.tree.leaves(jax.tree.map(
        lambda a, b: float(jnp.max(jnp.abs(a - b))), g1, g2)))
    assert err < 1e-4, err

  def test_z_loss_matches_between_full_and_blocked(self):
    """The z-loss term (z·mean(logsumexp²), the PaLM/T5X logit
    stabilizer) raises the loss and agrees between the full and the
    blocked (fused-projection) implementations."""
    from tensorflowonspark_tpu.models import transformer as tfm
    cfg = tfm.TransformerConfig(vocab_size=97, num_layers=2, num_heads=2,
                                d_model=32, d_ff=64, max_seq_len=50,
                                dtype=jnp.float32)
    state = tfm.create_state(jax.random.PRNGKey(0), cfg, seq_len=50)
    rng = np.random.RandomState(1)
    tokens = jnp.asarray(rng.randint(0, 97, (3, 50)), jnp.int32)
    logits = state.apply_fn({"params": state.params}, tokens)
    hidden = state.apply_fn({"params": state.params}, tokens,
                            return_hidden=True)
    table = tfm.tied_embedding_table(state.params)

    base = float(tfm.causal_lm_loss(logits, tokens))
    zf = float(tfm.causal_lm_loss(logits, tokens, z_loss=1e-2))
    zb = float(tfm.causal_lm_loss_blocked(hidden, table, tokens,
                                          chunk=16, z_loss=1e-2))
    assert zf > base
    assert abs(zf - zb) < 1e-4, (zf, zb)

  def test_blocked_loss_trains(self):
    """A model trained with the blocked loss learns the same cyclic task
    the full-loss test uses (end-to-end through jax.checkpoint+scan)."""
    from tensorflowonspark_tpu.models import transformer as tfm
    cfg = tfm.TransformerConfig(vocab_size=16, num_layers=2, num_heads=2,
                                d_model=64, d_ff=128, remat=False)
    state = tfm.create_state(jax.random.PRNGKey(0), cfg,
                             learning_rate=3e-3, seq_len=24)
    cycle = np.tile(np.arange(8), 10)
    tokens = jnp.asarray(np.stack([cycle[i:i + 24] for i in range(8)]),
                         jnp.int32)

    @jax.jit
    def step(state, tokens):
      def loss_fn(p):
        hidden = state.apply_fn({"params": p}, tokens, return_hidden=True)
        return tfm.causal_lm_loss_blocked(
            hidden, tfm.tied_embedding_table(p), tokens, chunk=8)
      loss, grads = jax.value_and_grad(loss_fn)(state.params)
      return state.apply_gradients(grads=grads), loss

    for _ in range(150):
      state, loss = step(state, tokens)
    assert float(loss) < 0.1, float(loss)


class TestTransformerPipelineFused:
  def test_pipeline_step_with_fusions_and_gqa(self):
    """The 1F1B full-model step composes with the round-4 config surface
    (GQA + fuse_qkv + ln/act fusions run mesh-free inside the stage
    bodies): loss/grads stay finite and match the same config's dense
    sequential AD."""
    from tensorflowonspark_tpu.models import transformer as tfm
    from tensorflowonspark_tpu.parallel import mesh as M

    mesh = M.build_mesh(M.MeshSpec(data=2, pipeline=2),
                        devices=jax.devices()[:4])
    cfg = tfm.TransformerConfig(
        vocab_size=64, num_layers=2, num_heads=4, num_kv_heads=2,
        d_model=32, d_ff=64, max_seq_len=8, dtype=jnp.float32,
        remat=False, fuse_qkv=True, ln_matmul_impl="fused",
        act_matmul_impl="fused")
    state = tfm.create_state(jax.random.PRNGKey(0), cfg, seq_len=8)
    tokens = jnp.asarray(
        np.random.RandomState(0).randint(0, 64, (8, 8)), jnp.int32)
    lm_step = tfm.make_pipeline_train_step(cfg, mesh, num_microbatches=2)
    loss, grads = jax.jit(lm_step)(state.params, tokens)

    def dense_loss(p):
      return tfm.causal_lm_loss(
          tfm.Transformer(cfg, None).apply({"params": p}, tokens), tokens)

    ref_l, ref_g = jax.value_and_grad(dense_loss)(state.params)
    np.testing.assert_allclose(float(loss), float(ref_l), atol=1e-5,
                               rtol=1e-5)
    f0, _ = jax.flatten_util.ravel_pytree(grads)
    f1, _ = jax.flatten_util.ravel_pytree(ref_g)
    np.testing.assert_allclose(np.asarray(f0), np.asarray(f1),
                               atol=1e-4, rtol=1e-4)


class TestTransformerPipeline:
  """Full-model 1F1B pipeline training (make_pipeline_train_step): loss
  and EVERY grad — tied embed table (both stage contributions), blocks,
  final norm — must match single-device dense AD."""

  def _setup(self):
    from tensorflowonspark_tpu.models import transformer as tfm
    cfg = tfm.TransformerConfig(vocab_size=128, num_layers=4, num_heads=4,
                                d_model=64, d_ff=128, max_seq_len=16,
                                dtype=jnp.float32, remat=False)
    state = tfm.create_state(jax.random.PRNGKey(0), cfg, seq_len=16)
    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, 128, (8, 16)), jnp.int32)

    def ref_loss(p):
      logits = tfm.Transformer(cfg, None).apply({"params": p}, tokens)
      return tfm.causal_lm_loss(logits, tokens)

    return tfm, cfg, state.params, tokens, ref_loss

  @pytest.mark.parametrize("n_stages,n_micro", [(4, 4), (2, 2), (4, 2)])
  def test_matches_dense_ad(self, n_stages, n_micro):
    from tensorflowonspark_tpu.parallel import mesh as M
    tfm, cfg, params, tokens, ref_loss = self._setup()
    l_ref, g_ref = jax.value_and_grad(ref_loss)(params)
    mesh = M.build_mesh(M.MeshSpec(pipeline=n_stages),
                        devices=jax.devices()[:n_stages])
    step = tfm.make_pipeline_train_step(cfg, mesh, num_microbatches=n_micro)
    loss, grads = jax.jit(step)(params, tokens)
    np.testing.assert_allclose(float(loss), float(l_ref),
                               atol=1e-5, rtol=1e-5)
    flat_p, _ = jax.flatten_util.ravel_pytree(grads)
    flat_r, _ = jax.flatten_util.ravel_pytree(g_ref)
    np.testing.assert_allclose(np.asarray(flat_p), np.asarray(flat_r),
                               atol=2e-4, rtol=2e-4)

  def test_dp_x_pp(self):
    from tensorflowonspark_tpu.parallel import mesh as M
    tfm, cfg, params, tokens, ref_loss = self._setup()
    l_ref, g_ref = jax.value_and_grad(ref_loss)(params)
    mesh = M.build_mesh(M.MeshSpec(data=2, pipeline=4),
                        devices=jax.devices())
    step = tfm.make_pipeline_train_step(cfg, mesh, num_microbatches=4)
    loss, grads = jax.jit(step)(params, tokens)
    np.testing.assert_allclose(float(loss), float(l_ref),
                               atol=1e-5, rtol=1e-5)
    flat_p, _ = jax.flatten_util.ravel_pytree(grads)
    flat_r, _ = jax.flatten_util.ravel_pytree(g_ref)
    np.testing.assert_allclose(np.asarray(flat_p), np.asarray(flat_r),
                               atol=2e-4, rtol=2e-4)

  def test_partition_roundtrip(self):
    tfm, cfg, params, _, _ = self._setup()
    outer, stage = tfm.pipeline_partition_params(params, 2)
    rebuilt = tfm.pipeline_unpartition_grads(outer, stage, 4)
    flat_a, _ = jax.flatten_util.ravel_pytree(params)
    flat_b, _ = jax.flatten_util.ravel_pytree(rebuilt)
    np.testing.assert_array_equal(np.asarray(flat_a), np.asarray(flat_b))

  def test_remat_stages_match(self):
    """cfg.remat=True must checkpoint stage blocks without changing math."""
    import dataclasses
    from tensorflowonspark_tpu.parallel import mesh as M
    tfm, cfg, params, tokens, ref_loss = self._setup()
    cfg_r = dataclasses.replace(cfg, remat=True)
    mesh = M.build_mesh(M.MeshSpec(pipeline=4), devices=jax.devices()[:4])
    step = tfm.make_pipeline_train_step(cfg_r, mesh, num_microbatches=4)
    loss, grads = jax.jit(step)(params, tokens)
    l_ref, g_ref = jax.value_and_grad(ref_loss)(params)
    np.testing.assert_allclose(float(loss), float(l_ref),
                               atol=1e-5, rtol=1e-5)
    flat_p, _ = jax.flatten_util.ravel_pytree(grads)
    flat_r, _ = jax.flatten_util.ravel_pytree(g_ref)
    np.testing.assert_allclose(np.asarray(flat_p), np.asarray(flat_r),
                               atol=2e-4, rtol=2e-4)


class TestSlidingWindowModel:
  def test_windowed_flash_matches_dense_impl(self):
    """attention_window at the model level: the forced-flash production
    path and the dense path produce the same logits."""
    from tensorflowonspark_tpu.models import transformer as tfm
    cfg_kw = dict(vocab_size=32, num_layers=2, num_heads=2, d_model=32,
                  d_ff=64, max_seq_len=128, remat=False,
                  dtype=jnp.float32, attention_window=24)
    flash_cfg = tfm.TransformerConfig(attention_impl="flash", **cfg_kw)
    dense_cfg = tfm.TransformerConfig(attention_impl="dense", **cfg_kw)
    tokens = jnp.asarray(
        np.random.RandomState(0).randint(0, 32, (2, 128)), jnp.int32)
    params = tfm.create_state(jax.random.PRNGKey(0), flash_cfg,
                              seq_len=128).params
    lf = tfm.Transformer(flash_cfg).apply({"params": params}, tokens)
    ld = tfm.Transformer(dense_cfg).apply({"params": params}, tokens)
    np.testing.assert_allclose(np.asarray(lf), np.asarray(ld), atol=1e-4,
                               rtol=1e-4)
    # and the window actually changes the result vs full attention
    full_cfg = tfm.TransformerConfig(attention_impl="dense",
                                     **dict(cfg_kw, attention_window=0))
    lfull = tfm.Transformer(full_cfg).apply({"params": params}, tokens)
    assert float(jnp.max(jnp.abs(ld - lfull))) > 1e-3

  def test_windowed_kv_decode_matches_recompute(self):
    """KV-cache decode with a sliding window must match full-recompute
    windowed decoding token for token."""
    from tensorflowonspark_tpu.models import transformer as tfm
    cfg = tfm.TransformerConfig(vocab_size=16, num_layers=2, num_heads=2,
                                d_model=64, d_ff=128, max_seq_len=32,
                                remat=False, dtype=jnp.float32,
                                attention_window=6)
    state = tfm.create_state(jax.random.PRNGKey(3), cfg,
                             learning_rate=3e-3, seq_len=24)
    cycle = np.tile(np.arange(8), 10)
    tokens = jnp.asarray(np.stack([cycle[i:i + 24] for i in range(8)]),
                         jnp.int32)

    @jax.jit
    def step(state, tokens):
      def loss_fn(p):
        return tfm.causal_lm_loss(
            state.apply_fn({"params": p}, tokens), tokens)
      loss, grads = jax.value_and_grad(loss_fn)(state.params)
      return state.apply_gradients(grads=grads), loss

    for _ in range(150):
      state, _ = step(state, tokens)
    prompt = jnp.asarray([[0, 1, 2, 3]], jnp.int32)
    full = tfm.greedy_generate(state.params, cfg, prompt, num_steps=10)
    kv = tfm.greedy_generate_kv(state.params, cfg, prompt, num_steps=10)
    np.testing.assert_array_equal(np.asarray(kv), np.asarray(full))
