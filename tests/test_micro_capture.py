"""Micro-capture queue + bench bank: the round's claim-window machinery.

These paths decide what BENCH_r05.json says if the driver's bench run
lands in a claim-service outage, so they are pinned as carefully as the
framework itself: bank provenance (never CPU/smoke numbers), staleness
bounds, honest exit codes, and the queue's window-closed-vs-real-error
discrimination.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), os.pardir))

sys.path.insert(0, os.path.join(REPO, "tools"))
import micro_capture  # noqa: E402


# ---------------------------------------------------------------- queue

def test_pending_skips_done_and_error_and_rotates_timeouts():
  st = {
      "smoke": {"status": "done"},
      "kern_lnmm_1": {"status": "error"},
      "kern_gelu_1": {"status": "retry", "timeouts": 2},
      "kern_gqa_0": {"status": "retry_down", "timeouts": 0},
  }
  names = [it[0] for it in micro_capture.pending(st)]
  assert "smoke" not in names
  assert "kern_lnmm_1" not in names
  # fewer timeouts sorts first; the 2-timeout item rotates behind
  assert names.index("kern_gqa_0") < names.index("kern_gelu_1")
  # everything not recorded is pending
  assert "bench_resnet" in names


def test_run_item_statuses(tmp_path, monkeypatch):
  monkeypatch.setattr(micro_capture, "MICRO", str(tmp_path))
  monkeypatch.setattr(micro_capture, "STATE",
                      str(tmp_path / "state.json"))
  monkeypatch.setattr(micro_capture, "LOG", str(tmp_path / "log"))

  st = {}
  ok = micro_capture.run_item(
      "ok", [sys.executable, "-c", "print('fine')"], 30, {}, st)
  assert ok == "done" and st["ok"]["tail"] == "fine"

  # nonzero exit while the "chip" is still up -> permanent error
  monkeypatch.setattr(micro_capture, "probe", lambda t: (True, "tpu 1"))
  bad = micro_capture.run_item(
      "bad", [sys.executable, "-c", "raise SystemExit(7)"], 30, {}, st)
  assert bad == "error" and st["bad"]["last_rc"] == 7

  # same exit with the window gone -> retryable, probe already consumed
  monkeypatch.setattr(micro_capture, "probe", lambda t: (False, "down"))
  lost = micro_capture.run_item(
      "lost", [sys.executable, "-c", "raise SystemExit(7)"], 30, {}, st)
  assert lost == "retry_down" and st["lost"]["timeouts"] == 1

  # parent-timeout kill -> retry (drain decides with its own probe)
  hung = micro_capture.run_item(
      "hung", [sys.executable, "-c", "import time; time.sleep(60)"],
      2, {}, st)
  assert hung == "retry" and st["hung"]["timeouts"] == 1


def test_aggregate_keeps_latest_row_per_kernel(tmp_path, monkeypatch,
                                               capsys):
  monkeypatch.setattr(micro_capture, "KERNELS_JSONL",
                      str(tmp_path / "kernels.jsonl"))
  monkeypatch.setattr(micro_capture, "REPO", str(tmp_path))
  rows = [dict(kernel="a", ok=False, error="first try"),
          dict(kernel="b", ok=True),
          dict(kernel="a", ok=True)]   # later row supersedes
  with open(tmp_path / "kernels.jsonl", "w") as f:
    for r in rows:
      f.write(json.dumps(r) + "\n")
  assert micro_capture.aggregate() == 0
  doc = json.load(open(tmp_path / "TPU_KERNELS.json"))
  by = {r["kernel"]: r for r in doc["results"]}
  assert len(doc["results"]) == 2 and by["a"]["ok"]


def test_cache_env_honors_override_and_disable(monkeypatch):
  monkeypatch.delenv("TOS_BENCH_CACHE_DIR", raising=False)
  assert micro_capture._cache_env()["JAX_COMPILATION_CACHE_DIR"].endswith(
      "xla_cache")
  monkeypatch.setenv("TOS_BENCH_CACHE_DIR", "/elsewhere")
  assert (micro_capture._cache_env()["JAX_COMPILATION_CACHE_DIR"]
          == "/elsewhere")
  monkeypatch.setenv("TOS_BENCH_CACHE_DIR", "")
  assert micro_capture._cache_env() == {}


def test_drain_stops_on_window_close_and_completes_queue(monkeypatch,
                                                         tmp_path):
  # drain logs through _log: point it at a scratch file or the fake
  # events ("probe OK") land in the REAL MICRO_CAPTURE.log and read as
  # chip contact (this happened; the log was scrubbed)
  monkeypatch.setattr(micro_capture, "LOG", str(tmp_path / "log"))
  monkeypatch.setattr(micro_capture, "_foreign_bench_running",
                      lambda: False)
  calls = []

  def fake_items():
    return [("a", ["x"], 5, {}), ("b", ["x"], 5, {}), ("c", ["x"], 5, {})]

  monkeypatch.setattr(micro_capture, "_items", fake_items)

  # window closes during item b (probe-confirmed): drain returns without
  # touching c
  st = {}
  outcomes = {"a": "done", "b": "retry_down"}

  def fake_run(name, argv, budget, env_extra, state):
    calls.append(name)
    state[name] = {"status": outcomes.get(name, "done")}
    return outcomes.get(name, "done")

  monkeypatch.setattr(micro_capture, "run_item", fake_run)
  n_done, empty = micro_capture.drain(st)
  assert (n_done, empty) == (1, False) and calls == ["a", "b"]

  # next window: b retries and succeeds, c runs -> queue complete
  calls.clear()
  outcomes["b"] = "done"
  n_done, empty = micro_capture.drain(st)
  assert empty and calls == ["b", "c"]

  # a timeout with the probe still up keeps draining the next item
  st2 = {}
  outcomes2 = {"a": "retry"}

  def fake_run2(name, argv, budget, env_extra, state):
    calls.append(name)
    state[name] = {"status": outcomes2.get(name, "done"),
                   "timeouts": 1 if outcomes2.get(name) else 0}
    return outcomes2.get(name, "done")

  calls.clear()
  monkeypatch.setattr(micro_capture, "run_item", fake_run2)
  monkeypatch.setattr(micro_capture, "probe", lambda t: (True, "tpu 1"))
  n_done, empty = micro_capture.drain(st2, max_items=2)
  # a rotates behind b/c after its timeout but is still pending
  assert n_done == 2 and calls == ["a", "b", "c"]
  assert st2["a"]["status"] == "retry"


# ------------------------------------------------------------ bench bank

def _run_bench(tmp_path, bank=None, env_extra=None):
  """Run bench.py with an unreachable device and a controlled bank."""
  bank_path = tmp_path / "bench_bank.json"
  if bank is not None:
    bank_path.write_text(json.dumps(bank))
  env = {k: v for k, v in os.environ.items()
         if k != "PALLAS_AXON_POOL_IPS"}
  env.update({"TOS_BENCH_PREFLIGHT_BUDGET": "10",
              "TOS_BENCH_BANK_PATH": str(bank_path),
              "JAX_PLATFORMS": "axon"})   # unregistered -> fails fast
  env.update(env_extra or {})
  res = subprocess.run([sys.executable, "bench.py"], cwd=REPO, env=env,
                       capture_output=True, text=True, timeout=120)
  line = res.stdout.strip().splitlines()[-1] if res.stdout.strip() else ""
  return res.returncode, (json.loads(line) if line else None)


@pytest.fixture()
def fresh_ts():
  import datetime
  return datetime.datetime.now().isoformat(timespec="seconds")


def test_bank_fallback_emits_banked_value(tmp_path, fresh_ts):
  rc, out = _run_bench(tmp_path, bank={
      "value": 321.5, "value_captured": fresh_ts,
      "extra": {"transformer_mfu": 0.5}})
  assert rc == 0
  assert out["value"] == 321.5
  assert out["extra"]["banked_measurement"] is True
  assert "REAL-CHIP" in out["note"]


@pytest.mark.slow
def test_stale_bank_is_refused(tmp_path):
  # Marked slow (tier-1 budget audit): every _run_bench pays the fixed
  # ~10 s capture-window subprocess; tier-1 keeps the fallback happy
  # path (test_bank_fallback_emits_banked_value) and the no-bank
  # failure exit (test_no_bank_plain_failure) — staleness refusal and
  # the extras-only branch run via `make test`.
  rc, out = _run_bench(tmp_path, bank={
      "value": 321.5, "value_captured": "2026-07-01T00:00:00"})
  assert rc == 3
  assert out["value"] == 0.0
  assert "preflight failed" in out["note"]


@pytest.mark.slow
def test_extras_only_bank_keeps_failure_exit(tmp_path, fresh_ts):
  # Marked slow: see test_stale_bank_is_refused.
  rc, out = _run_bench(tmp_path, bank={
      "extra": {"transformer_tokens_per_sec": 9},
      "extra_captured": fresh_ts})
  assert rc == 3
  assert out["value"] == 0.0
  assert out["extra"]["banked_measurement"] is True
  assert out["extra"]["transformer_tokens_per_sec"] == 9


def test_no_bank_plain_failure(tmp_path):
  rc, out = _run_bench(tmp_path)
  assert rc == 3
  assert out["value"] == 0.0 and "extra" not in out
