"""Minimal in-process pyspark stand-in for SparkEngine contract tests.

pyspark is not installed in this image, but an untested Spark adapter is a
claim rather than a capability (the reference's whole identity is driving
Spark — TFCluster.py:215-385). This stub implements exactly the RDD surface
SparkEngine touches, with Spark-faithful semantics where they matter to the
engine contract:

- ``parallelize(data, n)`` slices like Spark (one contiguous slice per
  partition; n elements into n slices → one element each);
- ``mapPartitions`` is lazy; ``collect`` runs partitions concurrently in
  threads and preserves partition order;
- ``rdd.barrier().mapPartitions`` gang-runs all partitions with a real
  threading.Barrier behind ``BarrierTaskContext.barrier()`` and placement
  info via ``getTaskInfos()``.

Install with ``sys.modules["pyspark"] = tests.pyspark_stub`` (see
test_engine.py's fixture) so SparkEngine's ``from pyspark import ...``
resolves here.
"""

import threading
from concurrent.futures import ThreadPoolExecutor

_COLLECT_TIMEOUT = 60


def _slices(data, n):
  """Spark's ParallelCollectionRDD slicing: contiguous, size-balanced."""
  data = list(data)
  n = max(1, n)
  return [data[len(data) * i // n: len(data) * (i + 1) // n]
          for i in range(n)]


class _TaskInfo:
  def __init__(self, address):
    self.address = address


class BarrierTaskContext:
  """Thread-local barrier context, like pyspark's per-task singleton."""

  _local = threading.local()

  def __init__(self, partition_id, infos, barrier):
    self._partition_id = partition_id
    self._infos = infos
    self._barrier = barrier

  @classmethod
  def get(cls):
    return cls._local.ctx

  def partitionId(self):
    return self._partition_id

  def getTaskInfos(self):
    return list(self._infos)

  def barrier(self):
    self._barrier.wait(timeout=_COLLECT_TIMEOUT)


class StubRDD:
  """An RDD as a list of per-partition thunks (lazy until collect)."""

  def __init__(self, sc, part_fns):
    self.sc = sc
    self._part_fns = part_fns

  def getNumPartitions(self):
    return len(self._part_fns)

  def mapPartitions(self, fn):
    return StubRDD(self.sc, [
        (lambda pf=pf: fn(iter(list(pf())))) for pf in self._part_fns])

  def _run_partitions(self, thunks):
    with ThreadPoolExecutor(max_workers=max(1, len(thunks))) as ex:
      futures = [ex.submit(lambda t=t: list(t())) for t in thunks]
      return [f.result(timeout=_COLLECT_TIMEOUT) for f in futures]

  def collect(self):
    return [row for part in self._run_partitions(self._part_fns)
            for row in part]

  def toLocalIterator(self):
    """Rows one partition at a time (like Spark: the driver holds at most
    one partition)."""
    for pf in self._part_fns:
      for row in list(pf()):
        yield row

  def foreachPartition(self, fn):
    self._run_partitions([
        (lambda pf=pf: (fn(iter(list(pf()))), ())[1])
        for pf in self._part_fns])

  def barrier(self):
    return _StubBarrierRDD(self)


class _StubBarrierRDD:
  def __init__(self, rdd):
    self._rdd = rdd

  def mapPartitions(self, fn):
    rdd = self._rdd
    n = rdd.getNumPartitions()
    gate = threading.Barrier(n)
    infos = [_TaskInfo("stub-host:%d" % (40000 + i)) for i in range(n)]

    def _bind(pid, pf):
      def _run():
        BarrierTaskContext._local.ctx = BarrierTaskContext(pid, infos, gate)
        try:
          return fn(iter(list(pf())))
        finally:
          BarrierTaskContext._local.ctx = None
      return _run

    return StubRDD(rdd.sc, [
        _bind(i, pf) for i, pf in enumerate(rdd._part_fns)])


class _Conf:
  def __init__(self, values=None):
    self._values = values or {}

  def get(self, key, default=None):
    return self._values.get(key, default)


class SparkContext:
  _active = None

  def __init__(self, num_executors=2, conf_values=None):
    self.defaultParallelism = num_executors
    self._conf = _Conf(conf_values)
    SparkContext._active = self

  @classmethod
  def getOrCreate(cls):
    return cls._active or cls()

  def getConf(self):
    return self._conf

  def parallelize(self, data, numSlices=None):
    n = numSlices if numSlices is not None else self.defaultParallelism
    return StubRDD(self, [
        (lambda s=s: iter(s)) for s in _slices(data, n)])

  def stop(self):
    if SparkContext._active is self:
      SparkContext._active = None
