"""Minimal in-process pyspark stand-in for SparkEngine contract tests.

pyspark is not installed in this image, but an untested Spark adapter is a
claim rather than a capability (the reference's whole identity is driving
Spark — TFCluster.py:215-385). This stub implements exactly the RDD surface
SparkEngine touches, with Spark-faithful semantics where they matter to the
engine contract:

- ``parallelize(data, n)`` slices like Spark (one contiguous slice per
  partition; n elements into n slices → one element each);
- ``mapPartitions`` is lazy; ``collect`` runs partitions concurrently in
  threads and preserves partition order;
- ``rdd.barrier().mapPartitions`` gang-runs all partitions with a real
  threading.Barrier behind ``BarrierTaskContext.barrier()`` and placement
  info via ``getTaskInfos()``;
- TASK RETRY: a failing task attempt is re-run up to
  ``spark.task.maxFailures`` (default 4, like Spark's TaskSetManager);
  the attempt number is visible through ``TaskContext.attemptNumber()``.
  Barrier stages retry the WHOLE gang (Spark aborts and resubmits every
  barrier task when one fails);
- SPECULATION: with ``spark.speculation=true``, ``collect`` launches a
  duplicate attempt of each task and takes the first result — BOTH
  attempts run their side effects, which is exactly the hazard the
  framework's duplicate-registration defenses exist for (never enabled
  for barrier stages, as in Spark).

Install with ``sys.modules["pyspark"] = tests.pyspark_stub`` (see
test_engine.py's fixture) so SparkEngine's ``from pyspark import ...``
resolves here.

pyspark itself cannot be installed in this image (no package installs
permitted); see tests/SPARK_VALIDATION.md for what that means for the
validation tier and what this stub does/doesn't prove.
"""

import threading
from concurrent.futures import ThreadPoolExecutor

_COLLECT_TIMEOUT = 60


class TaskContext:
  """Per-task-attempt context (thread-local, like pyspark's)."""

  _local = threading.local()

  def __init__(self, partition_id, attempt_number):
    self._partition_id = partition_id
    self._attempt_number = attempt_number

  @classmethod
  def get(cls):
    return getattr(cls._local, "ctx", None)

  def partitionId(self):
    return self._partition_id

  def attemptNumber(self):
    return self._attempt_number


def _slices(data, n):
  """Spark's ParallelCollectionRDD slicing: contiguous, size-balanced."""
  data = list(data)
  n = max(1, n)
  return [data[len(data) * i // n: len(data) * (i + 1) // n]
          for i in range(n)]


class _TaskInfo:
  def __init__(self, address):
    self.address = address


class BarrierTaskContext:
  """Thread-local barrier context, like pyspark's per-task singleton."""

  _local = threading.local()

  def __init__(self, partition_id, infos, barrier):
    self._partition_id = partition_id
    self._infos = infos
    self._barrier = barrier

  @classmethod
  def get(cls):
    return cls._local.ctx

  def partitionId(self):
    return self._partition_id

  def getTaskInfos(self):
    return list(self._infos)

  def barrier(self):
    self._barrier.wait(timeout=_COLLECT_TIMEOUT)


class StubRDD:
  """An RDD as a list of per-partition thunks (lazy until collect)."""

  def __init__(self, sc, part_fns):
    self.sc = sc
    self._part_fns = part_fns

  def getNumPartitions(self):
    return len(self._part_fns)

  def mapPartitions(self, fn):
    return StubRDD(self.sc, [
        (lambda pf=pf: fn(iter(list(pf())))) for pf in self._part_fns])

  def _max_failures(self):
    return int(self.sc.getConf().get("spark.task.maxFailures", "4"))

  def _speculative(self):
    return self.sc.getConf().get("spark.speculation",
                                 "false").lower() == "true"

  def _run_attempt(self, pid, attempt, thunk):
    TaskContext._local.ctx = TaskContext(pid, attempt)
    try:
      return list(thunk())
    finally:
      TaskContext._local.ctx = None

  def _run_task(self, pid, thunk, max_failures):
    """One logical task = up to ``max_failures`` attempts (TaskSetManager
    semantics: the task fails only when every attempt failed)."""
    last = None
    for attempt in range(max_failures):
      try:
        return self._run_attempt(pid, attempt, thunk)
      except Exception as e:  # noqa: BLE001 - retried like a Spark task
        last = e
    raise RuntimeError(
        "Task %d in stage failed %d times, most recent failure: %r"
        % (pid, max_failures, last)) from last

  def _run_partitions(self, thunks):
    max_failures = self._max_failures()
    speculative = self._speculative()
    with ThreadPoolExecutor(max_workers=max(1, len(thunks) * 2)) as ex:
      futures = [ex.submit(self._run_task, i, t, max_failures)
                 for i, t in enumerate(thunks)]
      if speculative:
        # a speculative copy of every task: the first SUCCESSFUL attempt
        # chain wins the result slot (Spark marks the task successful if
        # any attempt survives), but both attempts RUN (side effects
        # included) — Spark's hazard, surfaced deliberately
        import concurrent.futures as cf
        copies = [ex.submit(self._run_task, i, t, max_failures)
                  for i, t in enumerate(thunks)]
        out = []
        for f, c in zip(futures, copies):
          done, pending = cf.wait([f, c], timeout=_COLLECT_TIMEOUT,
                                  return_when=cf.FIRST_COMPLETED)
          winner = next((x for x in done if x.exception() is None), None)
          if winner is None and pending:
            done2, _ = cf.wait(pending, timeout=_COLLECT_TIMEOUT)
            winner = next((x for x in done2 if x.exception() is None), None)
          if winner is None:
            raise next(iter(done)).exception()
          out.append(winner.result())
        return out
      return [f.result(timeout=_COLLECT_TIMEOUT) for f in futures]

  def collect(self):
    return [row for part in self._run_partitions(self._part_fns)
            for row in part]

  def toLocalIterator(self):
    """Rows one partition at a time (like Spark: the driver holds at most
    one partition)."""
    for pf in self._part_fns:
      for row in list(pf()):
        yield row

  def foreachPartition(self, fn):
    self._run_partitions([
        (lambda pf=pf: (fn(iter(list(pf()))), ())[1])
        for pf in self._part_fns])

  def union(self, other):
    """Concatenate partitions, like Spark's UnionRDD (the epochs idiom:
    ``sc.union([rdd]*N)``, reference TFCluster.py:90-94)."""
    return StubRDD(self.sc, list(self._part_fns) + list(other._part_fns))

  def barrier(self):
    return _StubBarrierRDD(self)


class _GangRDD(StubRDD):
  """Barrier-stage result RDD: one task failing aborts and re-runs the
  WHOLE gang (Spark resubmits every task of a failed barrier stage), and
  speculation never applies to barrier stages."""

  def __init__(self, sc, make_gang):
    gate, thunks = make_gang()
    super().__init__(sc, thunks)
    self._gate = gate
    self._make_gang = make_gang

  def _run_partitions(self, thunks):
    import concurrent.futures as cf
    max_failures = self._max_failures()
    last = None
    gate = self._gate
    for stage_attempt in range(max_failures):
      if stage_attempt:
        gate, thunks = self._make_gang()
      with ThreadPoolExecutor(max_workers=max(1, len(thunks))) as ex:
        futures = [ex.submit(self._run_attempt, i, stage_attempt, t)
                   for i, t in enumerate(thunks)]
        cf.wait(futures, timeout=_COLLECT_TIMEOUT,
                return_when=cf.FIRST_EXCEPTION)
        errs = [f.exception() for f in futures if f.done()
                and f.exception() is not None]
        if not errs and all(f.done() for f in futures):
          return [f.result() for f in futures]
        # abort the barrier so gang members blocked in barrier() stop NOW
        # (Spark kills the surviving tasks of a failed barrier stage)
        gate.abort()
        for f in futures:
          f.cancel() or f.exception(timeout=_COLLECT_TIMEOUT)
        last = errs[0] if errs else TimeoutError("barrier gang timed out")
    raise RuntimeError(
        "Barrier stage failed %d times, most recent failure: %r"
        % (max_failures, last)) from last


class _StubBarrierRDD:
  def __init__(self, rdd):
    self._rdd = rdd

  def mapPartitions(self, fn):
    rdd = self._rdd
    n = rdd.getNumPartitions()

    def _make_gang():
      # a FRESH barrier per stage attempt — a broken barrier from a failed
      # attempt must not poison the retry
      gate = threading.Barrier(n)
      infos = [_TaskInfo("stub-host:%d" % (40000 + i)) for i in range(n)]

      def _bind(pid, pf):
        def _run():
          BarrierTaskContext._local.ctx = BarrierTaskContext(pid, infos,
                                                             gate)
          try:
            return fn(iter(list(pf())))
          finally:
            BarrierTaskContext._local.ctx = None
        return _run

      return gate, [_bind(i, pf) for i, pf in enumerate(rdd._part_fns)]

    return _GangRDD(rdd.sc, _make_gang)


class StubDStream:
  """A queue-backed discretized stream (pyspark.streaming surface).

  Micro-batch dispatch mirrors Spark Streaming's driver-side JobGenerator:
  ``foreachRDD`` callbacks run sequentially on one scheduler thread, one
  micro-batch at a time, in arrival order.
  """

  def __init__(self, ssc, rdds):
    self._ssc = ssc
    self._rdds = list(rdds)
    self._hooks = []

  def foreachRDD(self, fn):
    self._hooks.append(fn)


class StreamingContext:
  """Minimal StreamingContext: queueStream + start/stop/awaitTermination."""

  def __init__(self, sc, batchDuration=0.01):
    self.sc = sc
    self._interval = batchDuration
    self._streams = []
    self._thread = None
    self._stop_event = threading.Event()

  def queueStream(self, rdds, oneAtATime=True):
    ds = StubDStream(self, rdds)
    self._streams.append(ds)
    return ds

  def start(self):
    def _generate():
      pending = [list(ds._rdds) for ds in self._streams]
      while not self._stop_event.is_set() and any(pending):
        for ds, queue in zip(self._streams, pending):
          if queue and not self._stop_event.is_set():
            rdd = queue.pop(0)
            for hook in ds._hooks:
              hook(rdd)
        self._stop_event.wait(self._interval)
    self._thread = threading.Thread(target=_generate, daemon=True,
                                    name="stub-streaming-scheduler")
    self._thread.start()

  def awaitTermination(self, timeout=None):
    if self._thread is not None:
      self._thread.join(timeout)

  def stop(self, stopSparkContext=True, stopGraceFully=False):
    self._stop_event.set()
    if self._thread is not None:
      self._thread.join(_COLLECT_TIMEOUT)
    if stopSparkContext:
      self.sc.stop()


class _Conf:
  def __init__(self, values=None):
    self._values = values or {}

  def get(self, key, default=None):
    return self._values.get(key, default)


class SparkContext:
  _active = None

  def __init__(self, num_executors=2, conf_values=None):
    self.defaultParallelism = num_executors
    self._conf = _Conf(conf_values)
    SparkContext._active = self

  @classmethod
  def getOrCreate(cls):
    return cls._active or cls()

  def getConf(self):
    return self._conf

  def parallelize(self, data, numSlices=None):
    n = numSlices if numSlices is not None else self.defaultParallelism
    return StubRDD(self, [
        (lambda s=s: iter(s)) for s in _slices(data, n)])

  def stop(self):
    if SparkContext._active is self:
      SparkContext._active = None
