"""DataFeed unit tests against a real local feed hub.

Port of the reference's tests/test_TFNode.py:27-58 (batch/end-of-feed
semantics against a real local TFManager) plus EndPartition inference
semantics, input_mapping transposition, terminate drain, and array
staging.
"""

import threading
import time

import numpy as np
import pytest

from tensorflowonspark_tpu.control import feedhub
from tensorflowonspark_tpu.control.marker import EndPartition
from tensorflowonspark_tpu.datafeed import DataFeed


@pytest.fixture()
def hub():
  h = feedhub.start(b"k", ["input", "output", "error"], mode="local")
  yield h
  h.shutdown()


class TestDataFeed:
  def test_batches_and_end_of_feed(self, hub):
    q = hub.get_queue("input")
    q.put_many(list(range(10)) + [None])
    feed = DataFeed(hub, train_mode=True)
    assert feed.next_batch(4) == [0, 1, 2, 3]
    assert not feed.should_stop()
    assert feed.next_batch(4) == [4, 5, 6, 7]
    last = feed.next_batch(4)
    assert last == [8, 9]
    assert feed.should_stop()
    assert feed.next_batch(4) == []

  def test_end_partition_skipped_in_train_mode(self, hub):
    q = hub.get_queue("input")
    q.put_many([1, 2, EndPartition(), 3, 4, None])
    feed = DataFeed(hub, train_mode=True)
    assert feed.next_batch(10) == [1, 2, 3, 4]

  def test_end_partition_ends_batch_in_inference(self, hub):
    q = hub.get_queue("input")
    q.put_many([1, 2, EndPartition(), 3, None])
    feed = DataFeed(hub, train_mode=False)
    assert feed.next_batch(10) == [1, 2]     # batch aligned to partition
    assert feed.next_batch(10) == [3]
    assert feed.should_stop()

  def test_input_mapping_columns(self, hub):
    q = hub.get_queue("input")
    q.put_many([(1, "a"), (2, "b"), None])
    feed = DataFeed(hub, input_mapping={"col_x": "x", "col_y": "y"})
    batch = feed.next_batch(5)
    assert batch == {"x": [1, 2], "y": ["a", "b"]}


class TestColumnarFeed:
  """The columnar fast path: chunk envelopes assembled into batches by
  column slicing/concatenation (no per-row loop), with marker semantics
  and the row-list fallback pinned."""

  def _feed_chunks(self, hub, chunks, end=True, pipeline_depth=0,
                   **feed_kwargs):
    from tensorflowonspark_tpu.node import put_rows_chunk
    q = hub.get_queue("input")
    for chunk in chunks:
      put_rows_chunk(q, chunk, timeout=5)
    if end:
      q.put(None)
    return DataFeed(hub, pipeline_depth=pipeline_depth, **feed_kwargs)

  @pytest.mark.parametrize("pipeline_depth", [0, 2])
  def test_batch_spans_chunk_boundaries(self, hub, pipeline_depth):
    chunks = [[(np.full(3, 4 * c + i, np.float32), 4 * c + i)
               for i in range(4)] for c in range(3)]   # 3 chunks x 4 rows
    feed = self._feed_chunks(hub, chunks, pipeline_depth=pipeline_depth,
                             input_mapping={"a_x": "x", "b_y": "y"})
    batch = feed.next_batch_arrays(6)                  # spans chunks 0+1
    assert isinstance(batch["x"], np.ndarray) and batch["x"].shape == (6, 3)
    np.testing.assert_array_equal(batch["y"], np.arange(6))
    np.testing.assert_array_equal(batch["x"][5], np.full(3, 5, np.float32))
    batch = feed.next_batch_arrays(6)                  # chunks 1(tail)+2
    np.testing.assert_array_equal(batch["y"], np.arange(6, 12))
    assert feed.stats["columnar_chunks"] == 3

  def test_partial_final_batch_and_end_of_feed(self, hub):
    chunks = [[(np.ones(2, np.float32) * i,) for i in range(4)]]
    feed = self._feed_chunks(hub, chunks,
                             input_mapping={"only": "x"})
    batch = feed.next_batch_arrays(3)
    assert len(batch["x"]) == 3
    batch = feed.next_batch_arrays(3)                  # 1 row + end marker
    assert len(batch["x"]) == 1
    assert feed.should_stop()

  def test_marker_at_chunk_boundary_train_skips(self, hub):
    from tensorflowonspark_tpu.node import put_rows_chunk
    q = hub.get_queue("input")
    put_rows_chunk(q, [(np.float32(i) * np.ones(2),) for i in range(3)],
                   timeout=5)
    q.put(EndPartition())
    put_rows_chunk(q, [(np.float32(10 + i) * np.ones(2),) for i in range(3)],
                   timeout=5)
    q.put(None)
    feed = DataFeed(hub, train_mode=True, pipeline_depth=0,
                    input_mapping={"only": "x"})
    batch = feed.next_batch_arrays(6)                  # marker skipped
    np.testing.assert_array_equal(batch["x"][:, 0], [0, 1, 2, 10, 11, 12])

  def test_marker_at_chunk_boundary_inference_ends_batch(self, hub):
    from tensorflowonspark_tpu.node import put_rows_chunk
    q = hub.get_queue("input")
    put_rows_chunk(q, [(np.float32(i) * np.ones(2),) for i in range(3)],
                   timeout=5)
    q.put(EndPartition())
    put_rows_chunk(q, [(np.float32(7),) for _ in range(2)], timeout=5)
    q.put(None)
    feed = DataFeed(hub, train_mode=False, pipeline_depth=0,
                    input_mapping={"only": "x"})
    assert len(feed.next_batch_arrays(10)["x"]) == 3   # partition-aligned
    assert len(feed.next_batch_arrays(10)["x"]) == 2
    assert feed.should_stop()

  def test_inference_empty_boundary_batch_when_batch_divides_partition(
      self, hub):
    # when batch_size exactly divides the partition, the row path returns
    # an EMPTY batch at the partition boundary (main_fns key per-partition
    # output on it) — the columnar path must not swallow it
    from tensorflowonspark_tpu.node import put_rows_chunk
    q = hub.get_queue("input")
    put_rows_chunk(q, [(np.float32(i) * np.ones(2),) for i in range(3)],
                   timeout=5)
    q.put(EndPartition())
    put_rows_chunk(q, [(np.float32(7),) for _ in range(2)], timeout=5)
    q.put(None)
    feed = DataFeed(hub, train_mode=False, pipeline_depth=0,
                    input_mapping={"only": "x"})
    assert len(feed.next_batch_arrays(3)["x"]) == 3
    assert len(feed.next_batch_arrays(3)["x"]) == 0    # boundary batch
    assert len(feed.next_batch_arrays(3)["x"]) == 2
    assert feed.should_stop()

  def test_stall_raise_retires_fetch_thread(self, hub):
    # abandoning a feed via FeedStalledError must stop the pipeline
    # thread, or it keeps polling + eagerly acking the hub and steals
    # chunks from any replacement DataFeed
    from tensorflowonspark_tpu.datafeed import FeedStalledError
    feed = DataFeed(hub, train_mode=True, pipeline_depth=2,
                    liveness_timeout=1.5)
    with pytest.raises(FeedStalledError):
      feed.next_batch(4)
    assert feed._pipeline is None
    assert not any(t.name == "tos-feed-fetch"
                   for t in threading.enumerate())

  def test_input_mapping_column_ordering(self, hub):
    # sorted(input_mapping) keys map to tuple positions in order: the
    # FIRST sorted key names column 0 regardless of insertion order
    chunks = [[(np.float32(i) * np.ones(1), 100 + i) for i in range(4)]]
    feed = self._feed_chunks(hub, chunks,
                             input_mapping={"z_second": "y", "a_first": "x"})
    batch = feed.next_batch_arrays(4)
    np.testing.assert_array_equal(batch["x"][:, 0], [0, 1, 2, 3])
    np.testing.assert_array_equal(batch["y"], [100, 101, 102, 103])

  def test_row_list_api_on_columnar_chunks_unchanged(self, hub):
    # next_batch (no mapping) materializes rows: same types/values as the
    # legacy decode path, rows writable
    chunks = [[(np.full(2, i, np.float32), i) for i in range(4)]]
    feed = self._feed_chunks(hub, chunks)
    rows = feed.next_batch(10)
    assert len(rows) == 4
    arr, label = rows[2]
    assert isinstance(arr, np.ndarray) and label == 2
    arr /= 2.0                                         # writable (parity)
    np.testing.assert_array_equal(rows[3][0], np.full(2, 3, np.float32))

  def test_mixed_columnar_and_raw_rows_fall_back(self, hub):
    from tensorflowonspark_tpu.node import put_rows_chunk
    q = hub.get_queue("input")
    put_rows_chunk(q, [(np.float32(1),), (np.float32(2),)], timeout=5)
    q.put_many([(np.float32(3),), None])               # legacy raw rows
    feed = DataFeed(hub, pipeline_depth=0, input_mapping={"only": "x"})
    batch = feed.next_batch(5)
    assert [float(v[0]) if isinstance(v, np.ndarray) else float(v)
            for v in batch["x"]] == [1.0, 2.0, 3.0]
    assert feed.should_stop()

  def test_single_column_chunks_next_batch_arrays(self, hub):
    from tensorflowonspark_tpu.node import put_rows_chunk
    q = hub.get_queue("input")
    put_rows_chunk(q, [np.full(3, i, np.float32) for i in range(5)],
                   timeout=5)
    q.put(None)
    feed = DataFeed(hub, pipeline_depth=0)
    arr = feed.next_batch_arrays(4)
    assert isinstance(arr, np.ndarray) and arr.shape == (4, 3)
    np.testing.assert_array_equal(arr[2], np.full(3, 2, np.float32))

  def test_pipeline_depth_env_knob(self, hub, monkeypatch):
    from tensorflowonspark_tpu.datafeed import ENV_FEED_PIPELINE
    monkeypatch.setenv(ENV_FEED_PIPELINE, "0")
    feed = DataFeed(hub)
    assert feed._pipeline_depth == 0
    monkeypatch.setenv(ENV_FEED_PIPELINE, "3")
    assert DataFeed(hub)._pipeline_depth == 3

  def test_terminate_fast_on_empty_queue(self, hub):
    feed = DataFeed(hub)
    t0 = time.monotonic()
    feed.terminate()
    assert time.monotonic() - t0 < 1.5   # was >= 3s with 3x1.0s fixed polls
    assert feed.should_stop()

  def test_drain_keeps_markers_for_inference_recovery(self, hub):
    from tensorflowonspark_tpu.datafeed import drain_pending_rows
    from tensorflowonspark_tpu.node import put_rows_chunk
    q = hub.get_queue("input")
    put_rows_chunk(q, [1, 2], timeout=5)
    q.put(EndPartition())
    put_rows_chunk(q, [3], timeout=5)
    q.put(None)
    rows = drain_pending_rows(hub, keep_markers=True)
    assert rows[:2] == [1, 2] and rows[3] == 3
    assert isinstance(rows[2], EndPartition)           # position preserved
    assert q.join(timeout=5)
    # default still drops markers (train refeed semantics)
    put_rows_chunk(q, [4], timeout=5)
    q.put(EndPartition())
    q.put(None)
    assert drain_pending_rows(hub) == [4]


class TestLiveness:
  """A dead feeder must raise, not hang (VERDICT r2 weakness 6; consumer-
  side extension of the reference's feeder error polling,
  TFSparkNode.py:508-515)."""

  def test_worker_error_surfaces_in_next_batch(self, hub):
    hub.get_queue("error").put("Traceback: boom in feeder")
    feed = DataFeed(hub, liveness_timeout=30.0)
    t0 = time.monotonic()
    with pytest.raises(RuntimeError, match="boom in feeder"):
      feed.next_batch(4)
    assert time.monotonic() - t0 < 10.0
    # peek-and-put-back: shutdown's check must still see the error
    assert hub.get_queue("error").get_many(4, block=False) \
        == ["Traceback: boom in feeder"]

  def test_silent_feeder_death_raises_after_deadline(self, hub):
    from tensorflowonspark_tpu.datafeed import FeedStalledError
    feed = DataFeed(hub, liveness_timeout=2.5)
    t0 = time.monotonic()
    with pytest.raises(FeedStalledError, match="presumed dead"):
      feed.next_batch(4)
    elapsed = time.monotonic() - t0
    assert 2.0 < elapsed < 15.0

  def test_error_mid_feed_after_some_batches(self, hub):
    """Feeder delivers data, then dies with a traceback: the consumer gets
    the delivered batch, then the error — within seconds, not never."""
    q = hub.get_queue("input")
    q.put_many([1, 2, 3, 4])
    feed = DataFeed(hub, liveness_timeout=30.0)
    assert feed.next_batch(4) == [1, 2, 3, 4]

    def _die_late():
      time.sleep(0.5)
      hub.get_queue("error").put("worker exploded")

    threading.Thread(target=_die_late, daemon=True).start()
    t0 = time.monotonic()
    with pytest.raises(RuntimeError, match="worker exploded"):
      feed.next_batch(4)
    assert time.monotonic() - t0 < 10.0

  def test_terminating_state_stops_instead_of_raising(self, hub):
    feed = DataFeed(hub, liveness_timeout=30.0)

    def _terminate_late():
      time.sleep(0.5)
      hub.set("state", "terminating")

    threading.Thread(target=_terminate_late, daemon=True).start()
    assert feed.next_batch(4) == []
    assert feed.should_stop()

  def test_batch_results_roundtrip(self, hub):
    feed = DataFeed(hub, train_mode=False)
    feed.batch_results([10, 20, 30])
    out = hub.get_queue("output")
    assert out.get_many(5) == [10, 20, 30]

  def test_queue_full_pickle_roundtrip(self):
    # BaseManager proxies pickle server-side exceptions back to callers;
    # without __reduce__ the reconstruction replayed __init__ with the
    # formatted message and clients got a TypeError instead of QueueFull
    import pickle
    e = pickle.loads(pickle.dumps(feedhub.QueueFull(3)))
    assert isinstance(e, feedhub.QueueFull)
    assert e.admitted == 3

  def test_batch_results_stalled_collector_raises(self):
    from tensorflowonspark_tpu.datafeed import FeedStalledError
    h = feedhub.start(b"k", ["input", "output", "error"], qmax=4)
    try:
      feed = DataFeed(h, train_mode=False)
      feed.batch_results([1, 2, 3])            # fits (3 of 4)
      with pytest.raises(FeedStalledError) as ei:
        feed.batch_results([4, 5], timeout=0.5)   # admits 1, then full
      # the admitted prefix reached the queue; retries must skip it
      assert ei.value.admitted == 1
      assert h.get_queue("output").get_many(10) == [1, 2, 3, 4]
    finally:
      h.shutdown()

  def test_terminate_drains_and_flags(self, hub):
    q = hub.get_queue("input")
    q.put_many(list(range(500)))
    feed = DataFeed(hub)
    feed.next_batch(10)
    feed.terminate()
    assert feed.should_stop()
    assert hub.get("state") == "terminating"
    assert q.qsize() == 0          # drained so blocked feeders can finish
    assert q.join(timeout=2)       # and every item was accounted

  def test_next_batch_arrays(self, hub):
    q = hub.get_queue("input")
    q.put_many([([1.0, 2.0],), ([3.0, 4.0],), None])
    feed = DataFeed(hub, input_mapping={"features": "x"})
    arrays = feed.next_batch_arrays(5, dtype="float32")
    np.testing.assert_allclose(arrays["x"], [[1, 2], [3, 4]])

  def test_blocking_next_batch_waits_for_feeder(self, hub):
    feed = DataFeed(hub)
    got = []

    def consumer():
      got.extend(feed.next_batch(3))

    t = threading.Thread(target=consumer)
    t.start()
    time.sleep(0.3)
    hub.get_queue("input").put_many([7, 8, 9])
    t.join(timeout=5)
    assert got == [7, 8, 9]

  def test_synced_batch_single_process(self, hub):
    # with one jax process the vote degenerates to the local condition
    q = hub.get_queue("input")
    q.put_many([1, 2, 3, None])
    feed = DataFeed(hub)
    assert feed.next_batch_synced(2) == [1, 2]
    # only one row left -> everyone (of 1) agrees to stop; partial dropped
    assert feed.next_batch_synced(2) == []
    assert feed.should_stop()

  def test_prefetch_to_device_order_and_drain(self):
    """prefetch_to_device yields every batch exactly once, in order, with
    at most `size` device transfers in flight, and drains its buffer when
    the source ends."""
    from tensorflowonspark_tpu.datafeed import prefetch_to_device
    batches = [np.full((2, 2), i, "float32") for i in range(5)]
    it = prefetch_to_device(iter(batches), size=2)
    first = next(it)
    np.testing.assert_array_equal(np.asarray(first), batches[0])
    out = [first] + list(it)
    assert len(out) == 5
    for got, want in zip(out, batches):
      np.testing.assert_array_equal(np.asarray(got), want)
    # size=1 degrades to plain device_put per batch
    out1 = list(prefetch_to_device(iter(batches), size=1))
    assert len(out1) == 5


class TestStalledFeedGaugeMirroring:
  def test_stage_gauges_keep_moving_during_a_stall(self, hub):
    """THE feed-stall-detector prerequisite: a consumer delivering ZERO
    batches must still mirror its live stage seconds into the registry
    gauges (batch-boundary mirroring alone freezes exactly when the
    detector needs fetch_s to keep moving)."""
    from tensorflowonspark_tpu.obs import metrics as obs_metrics
    reg = obs_metrics.activate()
    try:
      feed = DataFeed(hub, train_mode=True, pipeline_depth=0)
      assert feed._obs_m is not None
      # nothing enqueued: the fetch attempt comes back empty — a stall
      feed._obs_stage_t = 0.0
      assert feed._fetch(timeout=0.05) is False
      mirrored = reg.snapshot()["feed.fetch_s"]["value"]
      assert mirrored == pytest.approx(feed.stats["fetch_s"])
      assert mirrored > 0.0
      # throttled: an immediate second empty poll does not re-mirror
      feed.stats["fetch_s"] += 100.0
      assert feed._fetch(timeout=0.01) is False
      assert reg.snapshot()["feed.fetch_s"]["value"] == \
          pytest.approx(mirrored)
      # past the throttle window it catches up
      feed._obs_stage_t = 0.0
      assert feed._fetch(timeout=0.01) is False
      assert reg.snapshot()["feed.fetch_s"]["value"] == \
          pytest.approx(feed.stats["fetch_s"])
    finally:
      obs_metrics.deactivate()


class TestSlabFeed:
  """Slab assembly for the fused train loop: K batches as ONE columnar
  stretch (still a single concatenate per column), partial tails falling
  back to flat per-batch arrays, and markers keeping their exact
  per-batch semantics inside a slab."""

  def _feed_chunks(self, hub, chunks, end=True, **feed_kwargs):
    from tensorflowonspark_tpu.node import put_rows_chunk
    q = hub.get_queue("input")
    for chunk in chunks:
      put_rows_chunk(q, chunk, timeout=5)
    if end:
      q.put(None)
    return DataFeed(hub, pipeline_depth=0, **feed_kwargs)

  def test_slab_spans_chunk_boundaries(self, hub):
    from tensorflowonspark_tpu.data.readers import Slab
    chunks = [[(np.full(3, 4 * c + i, np.float32), 4 * c + i)
               for i in range(4)] for c in range(3)]   # 3 chunks x 4 rows
    feed = self._feed_chunks(hub, chunks,
                             input_mapping={"a_x": "x", "b_y": "y"})
    slab = feed.next_slab_arrays(3, 2)                 # spans chunks 0+1
    assert isinstance(slab, Slab)
    assert slab.data["x"].shape == (2, 3, 3)
    np.testing.assert_array_equal(slab.data["y"],
                                  np.arange(6).reshape(2, 3))
    slab = feed.next_slab_arrays(3, 2)                 # chunks 1(tail)+2
    np.testing.assert_array_equal(slab.data["y"],
                                  np.arange(6, 12).reshape(2, 3))

  def test_partial_tail_returns_flat_arrays(self, hub):
    from tensorflowonspark_tpu.data.readers import Slab
    chunks = [[(np.ones(2, np.float32) * i,) for i in range(10)]]
    feed = self._feed_chunks(hub, chunks, input_mapping={"only": "x"})
    slab = feed.next_slab_arrays(2, 4)                 # 8 of 10 rows
    assert isinstance(slab, Slab) and slab.data["x"].shape == (4, 2, 2)
    tail = feed.next_slab_arrays(2, 4)                 # 2 rows + marker
    assert not isinstance(tail, Slab)
    assert tail["x"].shape == (2, 2)
    assert feed.should_stop()

  def test_end_partition_inside_slab_skipped_in_train(self, hub):
    """Train mode skips EndPartition inside a slab stretch exactly like
    per-batch assembly does."""
    from tensorflowonspark_tpu.data.readers import Slab
    from tensorflowonspark_tpu.node import put_rows_chunk
    q = hub.get_queue("input")
    put_rows_chunk(q, [(np.float32(i) * np.ones(2),) for i in range(3)],
                   timeout=5)
    q.put(EndPartition())
    put_rows_chunk(q, [(np.float32(10 + i) * np.ones(2),) for i in range(3)],
                   timeout=5)
    q.put(None)
    feed = DataFeed(hub, train_mode=True, pipeline_depth=0,
                    input_mapping={"only": "x"})
    slab = feed.next_slab_arrays(3, 2)
    assert isinstance(slab, Slab)
    np.testing.assert_array_equal(slab.data["x"][:, :, 0],
                                  [[0, 1, 2], [10, 11, 12]])

  def test_single_column_no_mapping(self, hub):
    from tensorflowonspark_tpu.data.readers import Slab
    chunks = [[np.float32(i) * np.ones(3, np.float32) for i in range(8)]]
    feed = self._feed_chunks(hub, chunks)
    slab = feed.next_slab_arrays(2, 4)
    assert isinstance(slab, Slab)
    assert isinstance(slab.data, np.ndarray)
    assert slab.data.shape == (4, 2, 3)

  def test_unroll_one_is_next_batch_arrays(self, hub):
    chunks = [[(np.float32(i) * np.ones(2),) for i in range(4)]]
    feed = self._feed_chunks(hub, chunks, input_mapping={"only": "x"})
    got = feed.next_slab_arrays(2, 1)
    assert isinstance(got, dict) and got["x"].shape == (2, 2)

  def test_slab_batches_order_matches_feed_batches(self, hub):
    """slab_batches yields full Slabs then the tail as plain batches —
    the flattened row order is EXACTLY feed_batches', which is what the
    fused loop's bit-identical-trajectory contract stands on."""
    from tensorflowonspark_tpu.data.readers import Slab, slab_batches
    chunks = [[(np.full(2, 5 * c + i, np.float32), 5 * c + i)
               for i in range(5)] for c in range(3)]   # 15 rows
    feed = self._feed_chunks(hub, chunks,
                             input_mapping={"a_x": "x", "b_y": "y"})
    items = list(slab_batches(feed, 2, 3))             # 15 rows, B=2, K=3
    assert [isinstance(i, Slab) for i in items] == \
        [True, True, False, False]
    flat = []
    for item in items:
      y = item.data["y"] if isinstance(item, Slab) else item["y"]
      flat.extend(np.asarray(y).reshape(-1).tolist())
    assert flat == list(range(15))
    # full slabs of 2x3, then per-batch tail: 2 rows + the 1-row rest
    assert items[2]["y"].shape == (2,)
    assert items[3]["y"].shape == (1,)

  def test_slab_batches_unroll_one_passthrough(self, hub):
    from tensorflowonspark_tpu.data.readers import Slab, slab_batches
    chunks = [[(np.float32(i) * np.ones(2), i) for i in range(5)]]
    feed = self._feed_chunks(hub, chunks,
                             input_mapping={"a_x": "x", "b_y": "y"})
    items = list(slab_batches(feed, 2, 1))
    assert all(not isinstance(i, Slab) for i in items)
    assert [len(i["y"]) for i in items] == [2, 2, 1]

  def test_slab_is_a_pytree_for_device_prefetch(self, hub):
    """Slab rides device_prefetch/device_put untouched (it IS a jax
    pytree), so slab k+1 stages under slab k's compute."""
    import jax
    from tensorflowonspark_tpu.data.readers import Slab, slab_batches
    from tensorflowonspark_tpu.datafeed import prefetch_to_device
    chunks = [[(np.full(2, 4 * c + i, np.float32),)
               for i in range(4)] for c in range(2)]   # 8 rows
    feed = self._feed_chunks(hub, chunks, input_mapping={"only": "x"})
    out = list(prefetch_to_device(slab_batches(feed, 2, 2), size=2))
    assert len(out) == 2
    for item in out:
      assert isinstance(item, Slab)
      assert isinstance(item.data["x"], jax.Array)
      assert item.data["x"].shape == (2, 2, 2)


class TestWirePlane:
  """put_rows_chunk encoded-size splitting + the OversizedRowError
  contract, adaptive chunk sizing bounds, and the aligned zero-copy
  assembly fast path (parity across chunk-boundary / partial-tail
  shapes)."""

  @pytest.fixture(autouse=True)
  def _fresh_stream(self):
    # each test models a fresh feeder stream: probe backoff left by a
    # previous test's columns must not leak in (matches _feed_plan's
    # per-stream reset)
    from tensorflowonspark_tpu.control import chunkcodec
    chunkcodec._probe_backoff.clear()
    yield
    chunkcodec._probe_backoff.clear()

  class _Sink:
    """Stub channel recording (rows, encoded bytes) per envelope."""

    def __init__(self):
      self.envelopes = []

    def put_chunk(self, n, payload, block=True, timeout=None):
      self.envelopes.append((n, len(payload)))

  def test_oversized_chunk_splits_on_encoded_size(self, hub):
    from tensorflowonspark_tpu.control import chunkcodec
    from tensorflowonspark_tpu.node import put_rows_chunk
    rng = np.random.default_rng(3)
    # 20 MiB of incompressible float32: must split into >= 5 envelopes,
    # every one within the encoded bound, rows in order
    rows = [rng.standard_normal(1 << 18).astype(np.float32)
            for _ in range(20)]
    sink = self._Sink()
    nbytes = put_rows_chunk(sink, rows, timeout=10)
    assert nbytes >= 20 * (1 << 20)
    assert len(sink.envelopes) >= 5
    assert all(b <= chunkcodec.MAX_PAYLOAD for _, b in sink.envelopes)
    assert sum(n for n, _ in sink.envelopes) == 20
    # the same rows round-trip through a real hub queue
    q = hub.get_queue("input")
    put_rows_chunk(q, rows, timeout=10)
    q.put(None)
    feed = DataFeed(hub, pipeline_depth=0, input_mapping={"only": "x"})
    batch = feed.next_batch_arrays(20)["x"]
    np.testing.assert_array_equal(batch, np.stack(rows))

  def test_compression_widens_the_envelope_budget(self, hub):
    from tensorflowonspark_tpu.node import put_rows_chunk
    # 20 MiB raw, but all-zero: the zlib-encoded payload fits ONE envelope
    rows = [np.zeros(1 << 18, np.float32) for _ in range(20)]
    stats = {}
    sink = self._Sink()
    nbytes = put_rows_chunk(sink, rows, timeout=10, stats=stats)
    assert len(sink.envelopes) == 1
    assert stats.get("zlib", 0) == 1
    assert nbytes < 1 << 20
    q = hub.get_queue("input")
    put_rows_chunk(q, rows, timeout=10)
    q.put(None)
    feed = DataFeed(hub, pipeline_depth=0, input_mapping={"only": "x"})
    np.testing.assert_array_equal(feed.next_batch_arrays(20)["x"],
                                  np.zeros((20, 1 << 18), np.float32))

  def test_single_unencodable_row_is_a_structured_error(self, hub):
    from tensorflowonspark_tpu.control import chunkcodec
    from tensorflowonspark_tpu.node import put_rows_chunk
    q = hub.get_queue("input")
    rng = np.random.default_rng(5)
    row = rng.standard_normal(chunkcodec.MAX_PAYLOAD // 4 + 4096)
    with pytest.raises(chunkcodec.OversizedRowError, match="MAX_PAYLOAD"):
      put_rows_chunk(q, [row.astype(np.float32)], timeout=5)
    assert q.qsize() == 0   # nothing partial shipped

  def test_sizer_converges_to_byte_budget(self):
    from tensorflowonspark_tpu.node import _ChunkSizer
    sizer = _ChunkSizer(256, 1 << 19)
    for _ in range(8):
      sizer.observe(sizer.rows, sizer.rows * 100)   # 100 B/row observed
    target_rows = (1 << 19) // 100
    assert abs(sizer.rows - target_rows) <= target_rows * 0.01

  def test_sizer_clamps_both_ways(self):
    from tensorflowonspark_tpu import node
    fat = node._ChunkSizer(256, 1024)
    for _ in range(8):
      fat.observe(256, 256 * 100_000)         # 100 KB/row, tiny budget
    assert fat.rows == node._ADAPT_MIN_ROWS
    thin = node._ChunkSizer(256, 1 << 30)
    for _ in range(8):
      thin.observe(256, 256)                  # 1 B/row, huge budget
    assert thin.rows == node._ADAPT_MAX_ROWS

  def test_feed_plan_resolves_target_from_meta_over_env(self, monkeypatch):
    from tensorflowonspark_tpu import node
    monkeypatch.setenv(node.ENV_FEED_TARGET_BYTES, "4096")
    _, seg, sizer = node._feed_plan({"feed_chunk_size": 64,
                                     "feed_target_bytes": 1 << 20}, None)
    assert seg is None and sizer is not None and sizer.target == 1 << 20
    _, _, sizer = node._feed_plan({"feed_chunk_size": 64}, None)
    assert sizer is not None and sizer.target == 4096
    monkeypatch.delenv(node.ENV_FEED_TARGET_BYTES)
    _, _, sizer = node._feed_plan({"feed_chunk_size": 64}, None)
    assert sizer is None   # no budget -> fixed row count

  def _fill(self, hub, chunks):
    from tensorflowonspark_tpu.node import put_rows_chunk
    q = hub.get_queue("input")
    for c in chunks:
      put_rows_chunk(q, c, timeout=5)
    q.put(None)

  def test_aligned_batch_is_zero_copy_and_read_only(self, hub):
    chunks = [[(np.full(3, i, np.float32), i) for i in range(8)]]
    self._fill(hub, chunks)
    feed = DataFeed(hub, pipeline_depth=0,
                    input_mapping={"c0": "x", "c1": "y"})
    batch = feed.next_batch_arrays(4)
    assert feed.stats["aligned_batches"] == 1
    assert not batch["x"].flags.writeable
    assert batch["x"].base is not None    # a view, not the hand-off copy
    np.testing.assert_array_equal(batch["y"], np.arange(4))
    tail = feed.next_batch_arrays(4)      # second half of the same chunk
    assert feed.stats["aligned_batches"] == 2
    np.testing.assert_array_equal(tail["y"], np.arange(4, 8))
    # sibling batches share the chunk buffer but never overlap
    np.testing.assert_array_equal(batch["x"][:, 0], np.arange(4))

  def test_spanning_batch_still_copies_and_matches(self, hub):
    chunks = [[(np.full(3, 4 * c + i, np.float32), 4 * c + i)
               for i in range(4)] for c in range(3)]
    self._fill(hub, chunks)
    feed = DataFeed(hub, pipeline_depth=0,
                    input_mapping={"c0": "x", "c1": "y"})
    span = feed.next_batch_arrays(6)      # crosses the chunk 0/1 boundary
    assert feed.stats["aligned_batches"] == 0
    assert span["x"].flags.writeable      # the hand-off copy, as before
    np.testing.assert_array_equal(span["y"], np.arange(6))
    aligned = feed.next_batch_arrays(2)   # inside chunk 1's tail
    assert feed.stats["aligned_batches"] == 1
    np.testing.assert_array_equal(aligned["y"], [6, 7])
    rest = feed.next_batch_arrays(6)      # chunk 2 + end-of-feed tail
    np.testing.assert_array_equal(rest["y"], np.arange(8, 12))
    assert feed.should_stop()

  @pytest.mark.parametrize("batch_size", [2, 4, 5, 8, 12])
  def test_assembly_parity_across_shapes(self, batch_size):
    """Aligned and spanning paths must hand out identical values for
    every batch/chunk alignment, partial tail included."""
    chunks = [[(np.full(3, 4 * c + i, np.float32), 4 * c + i)
               for i in range(4)] for c in range(3)]
    h = feedhub.start(b"k", ["input", "output", "error"], mode="local")
    try:
      self._fill(h, chunks)
      feed = DataFeed(h, pipeline_depth=0,
                      input_mapping={"c0": "x", "c1": "y"})
      seen = []
      while not feed.should_stop():
        batch = feed.next_batch_arrays(batch_size)
        if batch:
          assert len(batch["y"]) <= batch_size
          seen.extend(np.asarray(batch["y"]).tolist())
    finally:
      h.shutdown()
    assert seen == list(range(12))

  def test_wire_counters_reach_the_obs_registry(self, hub):
    from tensorflowonspark_tpu.node import put_rows_chunk
    from tensorflowonspark_tpu.obs import metrics as obs_metrics
    reg = obs_metrics.activate(obs_metrics.MetricsRegistry())
    try:
      put_rows_chunk(hub.get_queue("input"),
                     [(np.arange(784, dtype=np.int32) % 16, i % 5)
                      for i in range(256)], timeout=5)
    finally:
      obs_metrics.deactivate()
    snap = reg.snapshot()
    assert snap["feed.wire_rows"]["value"] == 256
    assert snap["feed.wire_bytes"]["value"] > 0
    assert snap["feed.wire_enc.dict"]["value"] >= 1
