"""Pallas kernel tests (interpret mode on CPU; the same kernels compile to
MXU/VPU code on TPU)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tensorflowonspark_tpu.ops import flash_attention
from tensorflowonspark_tpu.parallel import ring_attention as ra


class TestFlashAttention:
  @pytest.mark.parametrize("causal", [True, False])
  def test_matches_reference(self, causal):
    rng = np.random.RandomState(0)
    B, S, H, D = 2, 128, 4, 32
    q, k, v = (jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
               for _ in range(3))
    ref = ra.full_attention(q, k, v, causal=causal)
    out = flash_attention(q, k, v, causal=causal, blk_q=32, blk_k=32,
                          interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)

  def test_single_block(self):
    rng = np.random.RandomState(1)
    q, k, v = (jnp.asarray(rng.randn(1, 16, 2, 8), jnp.float32)
               for _ in range(3))
    ref = ra.full_attention(q, k, v, causal=True)
    out = flash_attention(q, k, v, causal=True, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)

  def test_bfloat16_inputs(self):
    rng = np.random.RandomState(2)
    q, k, v = (jnp.asarray(rng.randn(1, 64, 2, 16), jnp.bfloat16)
               for _ in range(3))
    ref = ra.full_attention(q, k, v, causal=True)
    out = flash_attention(q, k, v, causal=True, blk_q=32, blk_k=32,
                          interpret=True)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32),
                               atol=3e-2, rtol=3e-2)

  def test_indivisible_seq_raises(self):
    q = jnp.zeros((1, 100, 2, 8))
    with pytest.raises(AssertionError, match="not divisible"):
      flash_attention(q, q, q, blk_q=32, blk_k=32, interpret=True)
