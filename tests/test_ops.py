"""Pallas kernel tests (interpret mode on CPU; the same kernels compile to
MXU/VPU code on TPU)."""

import numpy as np
import pytest

import jax
import jax.flatten_util
import jax.numpy as jnp

from tensorflowonspark_tpu.ops import flash_attention, layer_norm
from tensorflowonspark_tpu.parallel import ring_attention as ra


class TestLayerNorm:
  def _ref(self, x, w, eps=1e-6):
    xf = x.astype(jnp.float32)
    mu = xf.mean(-1, keepdims=True)
    var = ((xf - mu) ** 2).mean(-1, keepdims=True)
    return ((xf - mu) * jax.lax.rsqrt(var + eps) *
            w.astype(jnp.float32)).astype(x.dtype)

  def test_forward_matches_reference(self):
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(4, 64, 128), jnp.float32)
    w = jnp.asarray(rng.rand(128) + 0.5, jnp.float32)
    out = layer_norm(x, w, blk_rows=32, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(self._ref(x, w)),
                               atol=1e-5, rtol=1e-5)

  def test_gradients_match_reference(self):
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(2, 32, 64), jnp.float32)
    w = jnp.asarray(rng.rand(64) + 0.5, jnp.float32)
    t = jnp.asarray(rng.randn(2, 32, 64), jnp.float32)

    gk = jax.grad(lambda x, w: jnp.sum(
        t * layer_norm(x, w, blk_rows=16, interpret=True)),
        argnums=(0, 1))(x, w)
    gr = jax.grad(lambda x, w: jnp.sum(t * self._ref(x, w)),
                  argnums=(0, 1))(x, w)
    np.testing.assert_allclose(np.asarray(gk[0]), np.asarray(gr[0]),
                               atol=1e-4, rtol=1e-4)
    np.testing.assert_allclose(np.asarray(gk[1]), np.asarray(gr[1]),
                               atol=1e-4, rtol=1e-4)

  def test_indivisible_rows_handled(self):
    # 300 rows with blk_rows=128: block auto-shrinks to a divisor
    rng = np.random.RandomState(3)
    x = jnp.asarray(rng.randn(3, 100, 64), jnp.float32)
    w = jnp.ones((64,), jnp.float32)
    out = layer_norm(x, w, blk_rows=128, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(self._ref(x, w)),
                               atol=1e-5, rtol=1e-5)

  def test_bfloat16(self):
    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.randn(8, 128), jnp.bfloat16)
    w = jnp.ones((128,), jnp.bfloat16)
    out = layer_norm(x, w, interpret=True)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(self._ref(x, w), np.float32),
                               atol=3e-2, rtol=3e-2)

  def test_sharded_matches_dense(self):
    """Per-shard kernel over a data×sequence mesh == unsharded kernel."""
    from tensorflowonspark_tpu.ops import layer_norm_sharded
    from tensorflowonspark_tpu.parallel import mesh as M

    if len(jax.devices()) < 4:
      pytest.skip("needs 4 virtual devices")
    mesh = M.build_mesh(M.MeshSpec(data=2, sequence=2),
                        devices=jax.devices()[:4])
    rng = np.random.RandomState(7)
    x = jnp.asarray(rng.randn(4, 32, 64), jnp.float32)
    w = jnp.asarray(rng.rand(64) + 0.5, jnp.float32)
    out = jax.jit(lambda x, w: layer_norm_sharded(
        x, w, mesh, interpret=True))(x, w)
    np.testing.assert_allclose(np.asarray(out), np.asarray(self._ref(x, w)),
                               atol=1e-5, rtol=1e-5)

  def test_sharded_gradients_match_dense(self):
    from tensorflowonspark_tpu.ops import layer_norm_sharded
    from tensorflowonspark_tpu.parallel import mesh as M

    if len(jax.devices()) < 4:
      pytest.skip("needs 4 virtual devices")
    mesh = M.build_mesh(M.MeshSpec(data=2, sequence=2),
                        devices=jax.devices()[:4])
    rng = np.random.RandomState(8)
    x = jnp.asarray(rng.randn(2, 16, 32), jnp.float32)
    w = jnp.asarray(rng.rand(32) + 0.5, jnp.float32)
    t = jnp.asarray(rng.randn(2, 16, 32), jnp.float32)

    gs = jax.jit(jax.grad(lambda x, w: jnp.sum(
        t * layer_norm_sharded(x, w, mesh, interpret=True)),
        argnums=(0, 1)))(x, w)
    gr = jax.grad(lambda x, w: jnp.sum(t * self._ref(x, w)),
                  argnums=(0, 1))(x, w)
    np.testing.assert_allclose(np.asarray(gs[0]), np.asarray(gr[0]),
                               atol=1e-4, rtol=1e-4)
    np.testing.assert_allclose(np.asarray(gs[1]), np.asarray(gr[1]),
                               atol=1e-4, rtol=1e-4)


class TestFlashAttention:
  @pytest.mark.parametrize("causal", [True, False])
  def test_matches_reference(self, causal):
    rng = np.random.RandomState(0)
    B, S, H, D = 2, 128, 4, 32
    q, k, v = (jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
               for _ in range(3))
    ref = ra.full_attention(q, k, v, causal=causal)
    out = flash_attention(q, k, v, causal=causal, blk_q=32, blk_k=32,
                          interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)

  def test_single_block(self):
    rng = np.random.RandomState(1)
    q, k, v = (jnp.asarray(rng.randn(1, 16, 2, 8), jnp.float32)
               for _ in range(3))
    ref = ra.full_attention(q, k, v, causal=True)
    out = flash_attention(q, k, v, causal=True, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)

  def test_bfloat16_inputs(self):
    rng = np.random.RandomState(2)
    q, k, v = (jnp.asarray(rng.randn(1, 64, 2, 16), jnp.bfloat16)
               for _ in range(3))
    ref = ra.full_attention(q, k, v, causal=True)
    out = flash_attention(q, k, v, causal=True, blk_q=32, blk_k=32,
                          interpret=True)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32),
                               atol=3e-2, rtol=3e-2)

  @pytest.mark.parametrize("mode", ["fused", "split"])
  def test_backward_modes_match_dense(self, mode):
    """Both backward plans — fused single-pass (default) and split
    two-kernel (TFOS_TPU_FLASH_BWD=split fallback) — produce dense-XLA
    gradients for q, k and v."""
    rng = np.random.RandomState(3)
    B, S, H, D = 2, 128, 4, 32
    q, k, v = (jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
               for _ in range(3))
    t = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
    for causal in (True, False):
      ref = jax.grad(
          lambda q, k, v: jnp.sum(t * ra.full_attention(
              q, k, v, causal=causal)), argnums=(0, 1, 2))(q, k, v)
      got = jax.grad(
          lambda q, k, v: jnp.sum(t * flash_attention(
              q, k, v, causal=causal, blk_q=32, blk_k=32,
              blk_bwd_q=32, blk_bwd_k=32,
              interpret=True, bwd=mode)), argnums=(0, 1, 2))(q, k, v)
      for a, b in zip(got, ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-4, rtol=2e-4)

  def test_indivisible_seq_shrinks_blocks(self):
    # 100 doesn't divide by 32: blocks shrink to the largest divisor (25)
    # instead of asserting, and the result still matches dense attention
    key = jax.random.PRNGKey(5)
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (1, 100, 2, 8), jnp.float32)
    k = jax.random.normal(kk, (1, 100, 2, 8), jnp.float32)
    v = jax.random.normal(kv, (1, 100, 2, 8), jnp.float32)
    out = flash_attention(q, k, v, blk_q=32, blk_k=32, interpret=True)
    ref = ra.full_attention(q, k, v, causal=True)
    assert jnp.max(jnp.abs(out - ref)) < 2e-5


class TestFlashAttentionGQA:
  """Grouped-query attention consumed natively by the flash kernels:
  grouped KV read straight through the remapped BlockSpec (no g× HBM
  expansion) and dK/dV accumulated across the query-head group inside
  the backward grid (round-3 verdict item 5 / ROADMAP deferral)."""

  def _data(self, B=2, S=128, H=8, HK=2, D=16, seed=0):
    rng = np.random.RandomState(seed)
    q = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
    k = jnp.asarray(rng.randn(B, S, HK, D), jnp.float32)
    v = jnp.asarray(rng.randn(B, S, HK, D), jnp.float32)
    t = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
    return q, k, v, t

  @pytest.mark.parametrize("causal", [True, False])
  def test_forward_matches_expanded(self, causal):
    q, k, v, _ = self._data()
    H = q.shape[2]
    ref = ra.full_attention(q, ra.expand_heads(k, H), ra.expand_heads(v, H),
                            causal=causal)
    out = flash_attention(q, k, v, causal=causal, blk_q=32, blk_k=32,
                          interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)

  @pytest.mark.parametrize("bwd", ["split", "fused"])
  def test_grads_match_expanded(self, bwd):
    """dK/dV arrive GROUPED (summed over each KV head's query group),
    matching AD through an explicit expand of the dense reference."""
    q, k, v, t = self._data(seed=1)
    H = q.shape[2]

    def loss_flash(q, k, v):
      return jnp.sum(t * flash_attention(q, k, v, causal=True, blk_q=32,
                                         blk_k=32, interpret=True, bwd=bwd))

    def loss_ref(q, k, v):
      return jnp.sum(t * ra.full_attention(
          q, ra.expand_heads(k, H), ra.expand_heads(v, H), causal=True))

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    assert gf[1].shape == k.shape and gf[2].shape == v.shape
    for a, b in zip(gf, gr):
      np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                 atol=2e-5, rtol=2e-5)

  def test_mqa_single_kv_head(self):
    """MQA (one KV head for all queries) is the extreme group."""
    q, k, v, t = self._data(HK=1, seed=2)
    H = q.shape[2]
    ref = ra.full_attention(q, ra.expand_heads(k, H), ra.expand_heads(v, H),
                            causal=True)
    out = flash_attention(q, k, v, causal=True, blk_q=32, blk_k=32,
                          interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)

  def test_indivisible_heads_raise(self):
    q, k, v, _ = self._data(H=8, HK=3)
    with pytest.raises(ValueError, match="divide"):
      flash_attention(q, k, v, interpret=True)

  def test_fused_vmem_guard(self):
    """The grouped fused backward falls back to the split plan when its
    resident dK/dV + dQ blocks exceed the VMEM budget."""
    from tensorflowonspark_tpu.ops.flash_attention import _gqa_fused_fits
    assert _gqa_fused_fits(1024, 1024, 64, 2)       # bench GQA shape
    assert not _gqa_fused_fits(8192, 8192, 128, 2)  # long-context: split


class TestGeluMatmul:
  """Fused GELU + matmul (ops.gelu_matmul): gelu(x) @ W in one kernel —
  the MLP down-projection fusion (the [rows, d_ff] activated tensor, the
  block's widest, never round-trips HBM)."""

  def _ref(self, x, W):
    a = jax.nn.gelu(x.astype(jnp.float32), approximate=True)
    return (a.astype(x.dtype) @ W).astype(x.dtype)

  def test_forward_matches_reference(self):
    from tensorflowonspark_tpu.ops.act_matmul import gelu_matmul
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(4, 32, 256), jnp.float32)
    W = jnp.asarray(rng.randn(256, 64) * 0.1, jnp.float32)
    out = gelu_matmul(x, W, blk_rows=32, blk_cols=64, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(self._ref(x, W)),
                               atol=1e-4, rtol=1e-4)

  def test_gradients_match_reference(self):
    from tensorflowonspark_tpu.ops.act_matmul import gelu_matmul
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(48, 96), jnp.float32)
    W = jnp.asarray(rng.randn(96, 80) * 0.1, jnp.float32)
    gk = jax.grad(lambda *a: jnp.sum(
        gelu_matmul(*a, interpret=True) ** 2), argnums=(0, 1))(x, W)
    gr = jax.grad(lambda *a: jnp.sum(
        self._ref(*a) ** 2), argnums=(0, 1))(x, W)
    for a, b in zip(gk, gr):
      np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                 atol=2e-3, rtol=2e-3)

  def test_bfloat16(self):
    from tensorflowonspark_tpu.ops.act_matmul import gelu_matmul
    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.randn(2, 16, 128), jnp.bfloat16)
    W = jnp.asarray(rng.randn(128, 64) * 0.1, jnp.bfloat16)
    out = gelu_matmul(x, W, interpret=True)
    assert out.dtype == jnp.bfloat16 and out.shape == (2, 16, 64)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(self._ref(x, W),
                                                np.float32), atol=0.1)

  def test_sharded_matches_dense(self):
    """Per-shard kernel with the CONTRACTED dim (d_ff) tensor-sharded:
    each device contracts its local F/t slice and the partials psum over
    the tensor axis — the Megatron down-proj layout."""
    from tensorflowonspark_tpu.ops.act_matmul import gelu_matmul_sharded
    from tensorflowonspark_tpu.parallel import mesh as M

    if len(jax.devices()) < 8:
      pytest.skip("needs 8 virtual devices")
    mesh = M.build_mesh(M.MeshSpec(data=2, sequence=2, tensor=2),
                        devices=jax.devices()[:8])
    rng = np.random.RandomState(11)
    x = jnp.asarray(rng.randn(4, 16, 64), jnp.float32)
    W = jnp.asarray(rng.randn(64, 48) * 0.1, jnp.float32)
    out = jax.jit(lambda x, W: gelu_matmul_sharded(
        x, W, mesh, interpret=True))(x, W)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(self._ref(x, W)),
                               atol=1e-4, rtol=1e-4)

  def test_sharded_gradients_match_dense(self):
    from tensorflowonspark_tpu.ops.act_matmul import gelu_matmul_sharded
    from tensorflowonspark_tpu.parallel import mesh as M

    if len(jax.devices()) < 4:
      pytest.skip("needs 4 virtual devices")
    mesh = M.build_mesh(M.MeshSpec(data=2, tensor=2),
                        devices=jax.devices()[:4])
    rng = np.random.RandomState(12)
    x = jnp.asarray(rng.randn(2, 8, 32), jnp.float32)
    W = jnp.asarray(rng.randn(32, 24) * 0.1, jnp.float32)
    gs = jax.jit(jax.grad(lambda *a: jnp.sum(gelu_matmul_sharded(
        *a, mesh, interpret=True) ** 2), argnums=(0, 1)))(x, W)
    gr = jax.grad(lambda *a: jnp.sum(
        self._ref(*a) ** 2), argnums=(0, 1))(x, W)
    for a, b in zip(gs, gr):
      np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                 atol=2e-3, rtol=2e-3)

  def test_model_fused_matches_unfused(self):
    """act_matmul_impl='fused' changes neither the param tree nor the
    loss/grads; with ln_matmul also fused the whole MLP is two kernels."""
    import dataclasses
    from tensorflowonspark_tpu.models import transformer as tfm
    cfg = tfm.TransformerConfig(vocab_size=64, num_layers=2, num_heads=4,
                                d_model=64, d_ff=128, max_seq_len=16,
                                dtype=jnp.float32, remat=False)
    cfg_f = dataclasses.replace(cfg, act_matmul_impl="fused",
                                ln_matmul_impl="fused")
    state = tfm.create_state(jax.random.PRNGKey(0), cfg, seq_len=16)
    state_f = tfm.create_state(jax.random.PRNGKey(0), cfg_f, seq_len=16)
    assert (jax.tree.structure(state.params)
            == jax.tree.structure(state_f.params))

    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, 64, (4, 16)), jnp.int32)

    def loss(c, p):
      return tfm.causal_lm_loss(
          tfm.Transformer(c, None).apply({"params": p}, tokens), tokens)

    l0, g0 = jax.value_and_grad(lambda p: loss(cfg, p))(state.params)
    l1, g1 = jax.value_and_grad(lambda p: loss(cfg_f, p))(state.params)
    np.testing.assert_allclose(float(l0), float(l1), atol=1e-5, rtol=1e-5)
    f0, _ = jax.flatten_util.ravel_pytree(g0)
    f1, _ = jax.flatten_util.ravel_pytree(g1)
    np.testing.assert_allclose(np.asarray(f0), np.asarray(f1),
                               atol=2e-4, rtol=2e-4)


class TestBlockPickers:
  """Mosaic accepts a last-dim block only when it is a multiple of 128
  (lanes) — or the whole dim — and a second-minor block only when a
  multiple of 8 (sublanes) or the whole dim. The pickers must never snap
  to a bare divisor violating that: caught by the deviceless gate on the
  GQA fused-QKV sweep config (N = 20 heads · 64 = 1280 snapped to 320 and
  failed real TPU lowering)."""

  def test_col_picker_lane_aligned(self):
    from tensorflowonspark_tpu.ops.ln_matmul import _pick_col_block
    assert _pick_col_block(1280, 512) == 256    # not 320
    assert _pick_col_block(768, 192) == 128     # 192 divides, but %128!=0
    assert _pick_col_block(3072, 512) == 512
    assert _pick_col_block(96, 512) == 96       # < 128: full dim only
    assert _pick_col_block(1152, 512) == 384
    # request below the lane floor snaps UP to the smallest aligned
    # divisor, never to the whole dimension
    assert _pick_col_block(3072, 64) == 128

  def test_row_picker_sublane_aligned(self):
    from tensorflowonspark_tpu.ops.layer_norm import _pick_block
    assert _pick_block(16384, 128, 768) == 128
    assert _pick_block(96, 64, 768) == 48
    # no 8-aligned divisor (100 = 4*25): one full-dim block, never 50
    assert _pick_block(100, 64, 768) == 100
    # sub-floor request snaps UP to 8, not to the whole dimension
    assert _pick_block(16384, 4, 768) == 8


class TestLNMatmul:
  """Fused LayerNorm + matmul (ops.ln_matmul): LN(x) @ W in one kernel."""

  def _ref(self, x, w, W, eps=1e-6):
    xf = x.astype(jnp.float32)
    mu = xf.mean(-1, keepdims=True)
    var = ((xf - mu) ** 2).mean(-1, keepdims=True)
    y = ((xf - mu) * jax.lax.rsqrt(var + eps) * w.astype(jnp.float32))
    return (y.astype(x.dtype) @ W).astype(x.dtype)

  def test_forward_matches_reference(self):
    from tensorflowonspark_tpu.ops.ln_matmul import ln_matmul
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(4, 32, 128), jnp.float32)
    w = jnp.asarray(rng.rand(128) + 0.5, jnp.float32)
    W = jnp.asarray(rng.randn(128, 256) * 0.1, jnp.float32)
    out = ln_matmul(x, w, W, blk_rows=32, blk_cols=128, interpret=True)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(self._ref(x, w, W)),
                               atol=1e-4, rtol=1e-4)

  def test_gradients_match_reference(self):
    from tensorflowonspark_tpu.ops.ln_matmul import ln_matmul
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(48, 96), jnp.float32)
    w = jnp.asarray(rng.rand(96) + 0.5, jnp.float32)
    W = jnp.asarray(rng.randn(96, 80) * 0.1, jnp.float32)
    gk = jax.grad(lambda *a: jnp.sum(
        ln_matmul(*a, interpret=True) ** 2), argnums=(0, 1, 2))(x, w, W)
    gr = jax.grad(lambda *a: jnp.sum(
        self._ref(*a) ** 2), argnums=(0, 1, 2))(x, w, W)
    for a, b in zip(gk, gr):
      np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                 atol=2e-3, rtol=2e-3)

  def test_bfloat16(self):
    from tensorflowonspark_tpu.ops.ln_matmul import ln_matmul
    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.randn(2, 16, 128), jnp.bfloat16)
    w = jnp.asarray(rng.rand(128) + 0.5, jnp.float32)
    W = jnp.asarray(rng.randn(128, 256) * 0.1, jnp.bfloat16)
    out = ln_matmul(x, w, W, interpret=True)
    assert out.dtype == jnp.bfloat16 and out.shape == (2, 16, 256)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(self._ref(x, w, W),
                                                np.float32), atol=0.1)

  def test_sharded_matches_dense(self):
    """Per-shard kernel over a data×sequence×tensor mesh == unsharded:
    rows split over data/sequence, W's columns over tensor (the MLP-up /
    QKV layouts), H contracted fully on-device — no collectives."""
    from tensorflowonspark_tpu.ops.ln_matmul import ln_matmul_sharded
    from tensorflowonspark_tpu.parallel import mesh as M

    if len(jax.devices()) < 8:
      pytest.skip("needs 8 virtual devices")
    mesh = M.build_mesh(M.MeshSpec(data=2, sequence=2, tensor=2),
                        devices=jax.devices()[:8])
    rng = np.random.RandomState(11)
    x = jnp.asarray(rng.randn(4, 16, 64), jnp.float32)
    w = jnp.asarray(rng.rand(64) + 0.5, jnp.float32)
    W = jnp.asarray(rng.randn(64, 96) * 0.1, jnp.float32)
    out = jax.jit(lambda x, w, W: ln_matmul_sharded(
        x, w, W, mesh, interpret=True))(x, w, W)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(self._ref(x, w, W)),
                               atol=1e-4, rtol=1e-4)

  def test_sharded_gradients_match_dense(self):
    """dW / dw_ln must sum over the row shards (shard_map transpose
    psums over data/sequence), matching plain AD of the dense pair."""
    from tensorflowonspark_tpu.ops.ln_matmul import ln_matmul_sharded
    from tensorflowonspark_tpu.parallel import mesh as M

    if len(jax.devices()) < 8:
      pytest.skip("needs 8 virtual devices")
    mesh = M.build_mesh(M.MeshSpec(data=2, sequence=2, tensor=2),
                        devices=jax.devices()[:8])
    rng = np.random.RandomState(12)
    x = jnp.asarray(rng.randn(2, 8, 32), jnp.float32)
    w = jnp.asarray(rng.rand(32) + 0.5, jnp.float32)
    W = jnp.asarray(rng.randn(32, 48) * 0.1, jnp.float32)
    gs = jax.jit(jax.grad(lambda *a: jnp.sum(ln_matmul_sharded(
        *a, mesh, interpret=True) ** 2), argnums=(0, 1, 2)))(x, w, W)
    gr = jax.grad(lambda *a: jnp.sum(
        self._ref(*a) ** 2), argnums=(0, 1, 2))(x, w, W)
    for a, b in zip(gs, gr):
      np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                 atol=2e-3, rtol=2e-3)

  def test_sharded_indivisible_columns_replicate(self):
    """A column count the tensor axis cannot divide keeps W replicated
    instead of failing the shard_map split."""
    from tensorflowonspark_tpu.ops.ln_matmul import ln_matmul_sharded
    from tensorflowonspark_tpu.parallel import mesh as M

    if len(jax.devices()) < 4:
      pytest.skip("needs 4 virtual devices")
    mesh = M.build_mesh(M.MeshSpec(data=2, tensor=2),
                        devices=jax.devices()[:4])
    rng = np.random.RandomState(13)
    x = jnp.asarray(rng.randn(4, 8, 32), jnp.float32)
    w = jnp.asarray(rng.rand(32) + 0.5, jnp.float32)
    W = jnp.asarray(rng.randn(32, 33) * 0.1, jnp.float32)   # 33 % 2 != 0
    out = jax.jit(lambda x, w, W: ln_matmul_sharded(
        x, w, W, mesh, interpret=True))(x, w, W)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(self._ref(x, w, W)),
                               atol=1e-4, rtol=1e-4)

  def test_model_fused_matches_unfused(self):
    """ln_matmul_impl='fused' changes neither the param tree nor the
    math of the Transformer (ln2+up as one kernel)."""
    import dataclasses
    from tensorflowonspark_tpu.models import transformer as tfm
    cfg = tfm.TransformerConfig(vocab_size=64, num_layers=2, num_heads=4,
                                d_model=64, d_ff=128, max_seq_len=16,
                                dtype=jnp.float32, remat=False)
    cfg_f = dataclasses.replace(cfg, ln_matmul_impl="fused")
    state = tfm.create_state(jax.random.PRNGKey(0), cfg, seq_len=16)
    state_f = tfm.create_state(jax.random.PRNGKey(0), cfg_f, seq_len=16)
    assert (jax.tree.structure(state.params)
            == jax.tree.structure(state_f.params))

    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, 64, (4, 16)), jnp.int32)

    def loss(c, p):
      return tfm.causal_lm_loss(
          tfm.Transformer(c, None).apply({"params": p}, tokens), tokens)

    l0, g0 = jax.value_and_grad(lambda p: loss(cfg, p))(state.params)
    l1, g1 = jax.value_and_grad(lambda p: loss(cfg_f, p))(state.params)
    np.testing.assert_allclose(float(l0), float(l1), atol=1e-5, rtol=1e-5)
    f0, _ = jax.flatten_util.ravel_pytree(g0)
    f1, _ = jax.flatten_util.ravel_pytree(g1)
    np.testing.assert_allclose(np.asarray(f0), np.asarray(f1),
                               atol=2e-4, rtol=2e-4)

  def test_model_fused_qkv_ln_matches_unfused(self):
    """ln_matmul_impl='fused' + fuse_qkv: ln1+QKV and ln2+up both run
    fused; params, loss and grads match the unfused graph, and the decode
    path (which takes the unfused branch) serves fused-trained params."""
    import dataclasses
    from tensorflowonspark_tpu.models import transformer as tfm
    cfg = tfm.TransformerConfig(vocab_size=64, num_layers=2, num_heads=4,
                                d_model=64, d_ff=128, max_seq_len=16,
                                dtype=jnp.float32, remat=False,
                                fuse_qkv=True)
    cfg_f = dataclasses.replace(cfg, ln_matmul_impl="fused")
    state = tfm.create_state(jax.random.PRNGKey(0), cfg, seq_len=16)
    state_f = tfm.create_state(jax.random.PRNGKey(0), cfg_f, seq_len=16)
    assert (jax.tree.structure(state.params)
            == jax.tree.structure(state_f.params))

    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, 64, (4, 16)), jnp.int32)

    def loss(c, p):
      return tfm.causal_lm_loss(
          tfm.Transformer(c, None).apply({"params": p}, tokens), tokens)

    l0, g0 = jax.value_and_grad(lambda p: loss(cfg, p))(state.params)
    l1, g1 = jax.value_and_grad(lambda p: loss(cfg_f, p))(state.params)
    np.testing.assert_allclose(float(l0), float(l1), atol=1e-5, rtol=1e-5)
    f0, _ = jax.flatten_util.ravel_pytree(g0)
    f1, _ = jax.flatten_util.ravel_pytree(g1)
    np.testing.assert_allclose(np.asarray(f0), np.asarray(f1),
                               atol=2e-4, rtol=2e-4)

    # a fused-config model must still generate (decode branch is unfused)
    prompt = jnp.asarray([[1, 2, 3, 4]], jnp.int32)
    out = tfm.greedy_generate(state_f.params, cfg_f, prompt, num_steps=4)
    assert out.shape == (1, 8)


class TestSlidingWindow:
  """Sliding-window attention (window = last W positions, self included):
  the kernels must equal the dense windowed mask exactly while bounding
  their block loops to the window (the O(seq·window) claim)."""

  def _qkv(self, B=2, S=128, H=4, D=16, seed=0):
    rng = np.random.RandomState(seed)
    return tuple(jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
                 for _ in range(3))

  @pytest.mark.parametrize("window", [1, 16, 40, 128, 500])
  def test_forward_matches_dense_window(self, window):
    q, k, v = self._qkv()
    out = flash_attention(q, k, v, causal=True, blk_q=32, blk_k=32,
                          interpret=True, window=window)
    ref = ra.full_attention(q, k, v, causal=True, window=window)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)

  @pytest.mark.parametrize("bwd", ["fused", "split"])
  def test_grads_match_dense_window(self, bwd):
    q, k, v = self._qkv()
    t = jnp.asarray(np.random.RandomState(9).randn(*q.shape), jnp.float32)
    ref = jax.grad(
        lambda q, k, v: jnp.sum(t * ra.full_attention(
            q, k, v, causal=True, window=40)), argnums=(0, 1, 2))(q, k, v)
    got = jax.grad(
        lambda q, k, v: jnp.sum(t * flash_attention(
            q, k, v, causal=True, blk_q=32, blk_k=32, blk_bwd_q=32,
            blk_bwd_k=32, interpret=True, bwd=bwd,
            window=40)), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(got, ref):
      np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                 atol=2e-4, rtol=2e-4)

  @pytest.mark.parametrize("bwd", ["fused", "split"])
  def test_gqa_windowed_grads(self, bwd):
    q, k, v = self._qkv()
    kg, vg = k[:, :, :2, :], v[:, :, :2, :]
    t = jnp.asarray(np.random.RandomState(9).randn(*q.shape), jnp.float32)
    ref = jax.grad(
        lambda q, kk, vv: jnp.sum(t * ra.full_attention(
            q, ra.expand_heads(kk, 4), ra.expand_heads(vv, 4),
            causal=True, window=24)), argnums=(0, 1, 2))(q, kg, vg)
    got = jax.grad(
        lambda q, kk, vv: jnp.sum(t * flash_attention(
            q, kk, vv, causal=True, blk_q=32, blk_k=32, blk_bwd_q=32,
            blk_bwd_k=32, interpret=True, bwd=bwd,
            window=24)), argnums=(0, 1, 2))(q, kg, vg)
    for a, b in zip(got, ref):
      np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                 atol=2e-4, rtol=2e-4)

  def test_ring_block_partials_merge_across_window(self):
    """Two sequence shards with the window straddling the boundary: the
    merged block partials must equal the dense windowed reference (the
    ring-attention composition path)."""
    from tensorflowonspark_tpu.ops import (flash_attention_block,
                                           merge_partials)
    q, k, v = self._qkv()
    half = 64
    o1, l1 = flash_attention_block(q[:, half:], k[:, :half], v[:, :half],
                                   half, 0, causal=True, blk_q=32,
                                   blk_k=32, interpret=True, window=40)
    o2, l2 = flash_attention_block(q[:, half:], k[:, half:], v[:, half:],
                                   half, half, causal=True, blk_q=32,
                                   blk_k=32, interpret=True, window=40)
    merged, _ = merge_partials(o1, l1, o2, l2)
    ref = ra.full_attention(q, k, v, causal=True, window=40)[:, half:]
    np.testing.assert_allclose(np.asarray(merged), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)

  def test_out_of_window_block_is_fully_masked(self):
    """A remote KV block entirely behind the window contributes nothing:
    lse = NEG_INF everywhere, so merge_partials ignores it."""
    from tensorflowonspark_tpu.ops import flash_attention_block
    from tensorflowonspark_tpu.ops.flash_attention import NEG_INF
    q, k, v = self._qkv(S=64)
    # queries at absolute positions [1024, 1088); KV at [0, 64): with
    # window 128 every pair is out of range
    _, lse = flash_attention_block(q, k, v, 1024, 0, causal=True,
                                   blk_q=32, blk_k=32, interpret=True,
                                   window=128)
    assert np.all(np.asarray(lse) <= NEG_INF)

  def test_window_requires_causal(self):
    q, k, v = self._qkv(S=32)
    with pytest.raises(ValueError, match="causal"):
      flash_attention(q, k, v, causal=False, interpret=True, window=8)

  def test_loop_bounds_scale_with_window(self):
    """The windowed kernel must do O(window), not O(seq), work: check the
    block-loop bounds directly (lo..hi spans ≤ window/blk_k + 2 blocks)."""
    from tensorflowonspark_tpu.ops.flash_attention import (_causal_k_hi,
                                                           _window_k_lo)
    blk_q = blk_k = 32
    n_kblocks = 64   # seq 2048
    window = 128
    for qi in range(64):
      hi = int(_causal_k_hi(qi, 0, 0, blk_q, blk_k, n_kblocks))
      lo = int(_window_k_lo(qi, 0, 0, blk_q, blk_k, window, n_kblocks))
      visited = hi - lo
      assert visited <= window // blk_k + 2
      # every visited block must contain at least one unmasked pair
      assert lo * blk_k <= qi * blk_q                      # not past diag
      assert (hi * blk_k) > qi * blk_q - window            # window reaches
