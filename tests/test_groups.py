"""Elastic multi-group training tests (parallel/groups.py).

The sync plane's round protocol (deadline-bounded merges, miss-driven
eviction, catch-up re-admission) is unit-tested on a fake clock; the
GroupSet runtime is driven end to end on CPU groups — including the
chaos drives behind `make elastic-chaos`: whole-group kill mid-training
with no global stall, eviction + re-admit, and the resharded restore
(checkpoint saved at one group count, resumed at another, step counter
and loss trajectory intact). Supervisor resize paths (commit-shrink /
readmit) run against a stub ClusterSupervisor (the test_cluster idiom).
"""

import threading
import time

import numpy as np
import pytest

from tensorflowonspark_tpu.control import rendezvous
from tensorflowonspark_tpu.parallel import groups as G
from tensorflowonspark_tpu.utils import chaos


@pytest.fixture(autouse=True)
def _fresh_chaos_counters():
  chaos.reset()
  yield
  chaos.reset()


def _leaf(arr):
  a = np.asarray(arr)
  return {"dtype": str(a.dtype), "shape": list(a.shape), "data": a.tobytes()}


def _leaf_np(rec):
  return np.frombuffer(rec["data"], dtype=rec["dtype"]).reshape(rec["shape"])


def _harness(dim=8, batch=4, lr=0.05):
  """Tiny linear-regression build_fn/batch_fn pair: deterministic data
  keyed by (group_id, step) — the GroupSet data-position contract."""
  import jax.numpy as jnp
  import optax
  from flax.training import train_state

  def build_fn(mesh):
    del mesh
    params = {"w": jnp.zeros((dim,), jnp.float32)}
    state = train_state.TrainState.create(apply_fn=None, params=params,
                                          tx=optax.sgd(lr))

    def loss_fn(p, b):
      pred = b["x"] @ p["w"]
      return jnp.mean((pred - b["y"]) ** 2)

    return state, loss_fn

  w_true = np.arange(dim, dtype="float32") / dim

  def batch_fn(group_id, step):
    rng = np.random.RandomState(1000 * group_id + step)
    x = rng.rand(batch, dim).astype("float32")
    return {"x": x, "y": x @ w_true}

  return build_fn, batch_fn


# ---------------------------------------------------------------------------
# payload codec + merge
# ---------------------------------------------------------------------------


class TestCodec:
  def test_pack_unpack_roundtrip(self):
    tree = {"a": np.arange(6, dtype="float32").reshape(2, 3),
            "b": {"c": np.array(7, dtype="int32")}}
    out = G.unpack_tree(G.pack_tree(tree), tree)
    np.testing.assert_array_equal(out["a"], tree["a"])
    np.testing.assert_array_equal(out["b"]["c"], tree["b"]["c"])

  def test_merge_weighted_mean_float_first_wins_rest(self):
    a = [_leaf(np.array([1.0, 2.0], "float32")), _leaf(np.array(3, "int32"))]
    b = [_leaf(np.array([3.0, 6.0], "float32")), _leaf(np.array(9, "int32"))]
    merged = G.merge_payloads([(1.0, a), (3.0, b)])
    # (1*[1,2] + 3*[3,6]) / 4 = [2.5, 5.0]
    np.testing.assert_allclose(_leaf_np(merged[0]), [2.5, 5.0])
    assert int(_leaf_np(merged[1])) == 3        # non-float: first wins

  def test_unpack_leaf_count_mismatch_raises(self):
    tree = {"a": np.zeros(2, "float32")}
    with pytest.raises(ValueError, match="leaves"):
      G.unpack_tree(G.pack_tree(tree) * 2, tree)


# ---------------------------------------------------------------------------
# SyncPlane round protocol (fake clock)
# ---------------------------------------------------------------------------


class TestSyncPlane:
  def _plane(self, **kw):
    clk = [0.0]
    kw.setdefault("sync_timeout", 5.0)
    plane = G.SyncPlane(time_fn=lambda: clk[0], **kw)
    return plane, clk

  def test_round_completes_when_all_members_contribute(self):
    plane, _ = self._plane()
    plane.join(0)
    plane.join(1)
    p = [_leaf(np.array([2.0], "float32"))]
    q = [_leaf(np.array([4.0], "float32"))]
    plane.contribute(0, 1, p, weight=1.0, step=4)
    assert not plane.poll(1)["done"]
    plane.contribute(1, 1, q, weight=1.0, step=4)
    resp = plane.poll(1)
    assert resp["done"] and resp["members"] == [0, 1]
    np.testing.assert_allclose(_leaf_np(resp["payload"][0]), [3.0])
    assert plane.status()["step"] == 4

  def test_deadline_merges_with_whoever_showed_up(self):
    plane, clk = self._plane(sync_timeout=5.0)
    plane.join(0)
    plane.join(1)
    plane.contribute(0, 1, [_leaf(np.array([2.0], "float32"))], step=4)
    assert not plane.poll(1)["done"]
    clk[0] = 6.0          # past the deadline armed by the 1st contribution
    resp = plane.poll(1)
    assert resp["done"] and resp["denominator"] == 1

  def test_miss_limit_evicts_and_rejects_stale_contribution(self):
    plane, clk = self._plane(sync_timeout=5.0, miss_limit=2)
    plane.join(0)
    plane.join(1)
    for rnd in (1, 2):
      plane.contribute(0, rnd, [_leaf(np.array([1.0], "float32"))], step=rnd)
      clk[0] += 6.0
      assert plane.poll(rnd)["done"]
    assert 1 in plane.lost
    stale = plane.contribute(1, 3, [_leaf(np.array([9.0], "float32"))])
    assert stale["lost"] and not stale["accepted"]
    # re-join clears the eviction and hands back the catch-up payload
    resp = plane.join(1)
    assert resp["payload"] is not None and 1 in plane.active

  def test_mid_round_join_does_not_stall_open_round(self):
    plane, _ = self._plane()
    plane.join(0)
    plane.contribute(0, 1, [_leaf(np.array([1.0], "float32"))])
    plane.join(1)         # joins mid-round: participates from round 2
    resp = plane.poll(1)
    assert resp["done"] and resp["members"] == [0]

  def test_seed_primes_step_and_catch_up(self):
    plane, _ = self._plane()
    payload = [_leaf(np.array([5.0], "float32"))]
    plane.seed(12, payload)
    resp = plane.join(3)
    assert resp["step"] == 12
    np.testing.assert_allclose(_leaf_np(resp["payload"][0]), [5.0])


# ---------------------------------------------------------------------------
# the SYNC/SYNCQ/GROUP verbs over a live server
# ---------------------------------------------------------------------------


class TestSyncWire:
  def test_two_clients_sync_through_live_server(self):
    server = rendezvous.Server(1)
    server.start()
    try:
      G.attach_sync_plane(server, sync_timeout=10.0)
      results = {}

      def member(gid, value, weight):
        c = G.GroupSyncClient(server.addr, gid, request_timeout=5.0)
        try:
          tree = {"w": np.array([value], "float32")}
          results[gid] = c.sync(1, tree, weight=weight, step=4, timeout=15.0)
        finally:
          c.close()

      threads = [threading.Thread(target=member, args=(0, 2.0, 1.0)),
                 threading.Thread(target=member, args=(1, 6.0, 3.0))]
      for t in threads:
        t.start()
      for t in threads:
        t.join(timeout=30)
      merged0, members0 = results[0]
      merged1, _ = results[1]
      # (1*2 + 3*6) / 4 = 5.0, identical on both sides
      np.testing.assert_allclose(merged0["w"], [5.0])
      np.testing.assert_allclose(merged1["w"], [5.0])
      assert members0 == [0, 1]
      assert server.sync_plane.status()["rounds_completed"] == 1
    finally:
      server.stop()

  def test_sync_verbs_error_without_attached_plane(self):
    server = rendezvous.Server(1)
    server.start()
    try:
      client = rendezvous.Client(server.addr, timeout=5.0)
      resp = client._request({"type": "GROUP", "action": "join",
                              "group_id": 0})
      assert resp["type"] == "ERROR"
      client.close()
    finally:
      server.stop()


# ---------------------------------------------------------------------------
# GroupSet end to end
# ---------------------------------------------------------------------------


class TestGroupSet:
  def test_groups_converge_and_agree_at_boundaries(self):
    build_fn, batch_fn = _harness()
    gs = G.GroupSet(build_fn, batch_fn, num_groups=2, sync_every=4,
                    sync_timeout=15.0)
    try:
      gs.run(16)
      assert gs.wait(timeout=120)
      for g in gs.groups.values():
        assert g.exit_reason == "completed" and g.steps == 16
      p0 = G.pack_tree(gs.groups[0].state.params)
      p1 = G.pack_tree(gs.groups[1].state.params)
      assert all(a["data"] == b["data"] for a, b in zip(p0, p1)), \
          "post-sync params must be bit-identical across groups"
      assert gs.plane.status()["rounds_completed"] == 4
      losses = gs.groups[0].losses
      assert losses[-1] < losses[0], "training must actually converge"
    finally:
      gs.close()

  @pytest.mark.chaos
  def test_group_kill_no_global_stall_then_readmit(self, monkeypatch):
    """The headline chaos drive: a whole group dies mid-training (no
    goodbye, no contribution) — the survivor keeps stepping to completion
    with the sync denominator shrunk (never a global stall), the plane
    evicts the dead group, and readmit() brings it back caught-up."""
    monkeypatch.setenv(chaos.ENV_GROUP, "kill@1#2")
    build_fn, batch_fn = _harness()
    gs = G.GroupSet(build_fn, batch_fn, num_groups=2, sync_every=4,
                    sync_timeout=1.0, miss_limit=2)
    try:
      gs.run(24)
      assert gs.wait(timeout=120)
      assert gs.groups[1].exit_reason == "chaos-kill"
      assert gs.groups[0].exit_reason == "completed"
      assert gs.groups[0].steps == 24, "survivor must reach the target"
      assert 1 in gs.plane.lost
      kinds = [e["event"] for e in gs.events]
      assert "group-killed" in kinds and "plane-lost" in kinds
      # re-admit: fresh group pulls current weights and finishes the run
      monkeypatch.delenv(chaos.ENV_GROUP)
      chaos.reset()
      g = gs.readmit(1)
      assert g.steps >= 20, "readmitted group must catch up, not rewind"
      assert gs.wait(timeout=120)
      assert gs.groups[1].exit_reason == "completed"
      assert gs.plane.status()["groups_active"] == 2
    finally:
      gs.close()

  @pytest.mark.chaos
  def test_stalled_group_misses_deadline_and_self_readmits(self, monkeypatch):
    """A mid-sync stall: group 1 sleeps through round 1, the survivor's
    round merges at the deadline (denominator 1), the plane evicts the
    straggler at miss_limit, and its stale contribution is rejected —
    it self-readmits via the join catch-up and both groups finish."""
    monkeypatch.setenv(chaos.ENV_GROUP, "stall@1#1:2.0")
    build_fn, batch_fn = _harness()
    gs = G.GroupSet(build_fn, batch_fn, num_groups=2, sync_every=4,
                    sync_timeout=0.5, miss_limit=1)
    try:
      gs.run(8)
      assert gs.wait(timeout=120)
      for g in gs.groups.values():
        assert g.exit_reason == "completed" and g.steps == 8
      kinds = [e["event"] for e in gs.events]
      assert "plane-lost" in kinds, "the straggler must get evicted"
      assert "group-readmitted" in kinds, \
          "eviction must resolve via the catch-up re-join, not a wedge"
    finally:
      gs.close()

  @pytest.mark.chaos
  def test_reshard_restore_step_counter_and_loss_continuity(self, tmp_path):
    """Save at 2 groups, restore at 3 and at 1: every topology resumes
    from the same step with the same weights (restore = broadcast —
    group interchangeability), and the chief group's post-restore loss
    trajectory is BIT-IDENTICAL across topologies (the loss-continuity
    pin: same step counter -> same batches -> same losses)."""
    from tensorflowonspark_tpu.utils.checkpoint import CheckpointManager
    build_fn, batch_fn = _harness()
    gs = G.GroupSet(build_fn, batch_fn, num_groups=2, sync_every=4,
                    sync_timeout=15.0)
    try:
      gs.run(8)
      assert gs.wait(timeout=120)
      mgr = CheckpointManager(str(tmp_path / "ck"), save_interval_steps=1)
      assert gs.save(mgr, force=True)
      mgr.wait()
      assert mgr.manifest() == {
          "schema": 1, "kind": "groupset", "num_groups": 2,
          "groups": [0, 1], "step": 8, "sync_every": 4, "sync_round": 2}
      saved = G.pack_tree(gs.groups[0].state.params)
    finally:
      gs.close()

    trajectories = {}
    for n in (3, 1):
      gs2 = G.GroupSet(build_fn, batch_fn, num_groups=n, sync_every=4,
                       sync_timeout=15.0)
      try:
        mgr2 = CheckpointManager(str(tmp_path / "ck"), save_interval_steps=1)
        next_step = gs2.restore_or(mgr2)
        assert next_step == 9, "step counter must survive the reshard"
        for g in gs2.groups.values():
          assert g.steps == 8
          restored = G.pack_tree(g.state.params)
          assert all(a["data"] == b["data"]
                     for a, b in zip(saved, restored)), \
              "every group must adopt the checkpointed weights bitwise"
        gs2.run(12)
        assert gs2.wait(timeout=120)
        assert all(g.exit_reason == "completed" and g.steps == 12
                   for g in gs2.groups.values())
        trajectories[n] = list(gs2.groups[0].losses)
      finally:
        gs2.close()
    assert trajectories[3] == trajectories[1], \
        "chief-group loss continuity must not depend on the group count"


# ---------------------------------------------------------------------------
# chaos grammar (TOS_CHAOS_GROUP)
# ---------------------------------------------------------------------------


class TestGroupChaosGrammar:
  def test_malformed_spec_raises_at_first_consult(self, monkeypatch):
    monkeypatch.setenv(chaos.ENV_GROUP, "explode@1#2")
    with pytest.raises(ValueError, match="malformed group spec"):
      chaos.check_config()

  def test_kill_verdict_counts_per_group(self, monkeypatch):
    monkeypatch.setenv(chaos.ENV_GROUP, "kill@1#2")
    assert chaos.group_fault(0) is None
    assert chaos.group_fault(1) is None       # @1 occurrence 1
    assert chaos.group_fault(1) == "kill"     # @1 occurrence 2
    assert chaos.group_fault(1) is None       # budget spent

  def test_global_count_and_stall(self, monkeypatch):
    monkeypatch.setenv(chaos.ENV_GROUP, "kill#3,stall#1:0.2")
    t0 = time.monotonic()
    assert chaos.group_fault(0) is None       # 1st overall: stalls
    assert time.monotonic() - t0 >= 0.2
    assert chaos.group_fault(1) is None
    assert chaos.group_fault(0) == "kill"     # 3rd overall

  def test_disarmed_is_noop(self, monkeypatch):
    monkeypatch.delenv(chaos.ENV_GROUP, raising=False)
    assert chaos.group_fault(5) is None


# ---------------------------------------------------------------------------
# supervisor resize paths (stub ClusterSupervisor — test_cluster idiom)
# ---------------------------------------------------------------------------


def _stub_supervisor(server, meta):
  from tensorflowonspark_tpu.cluster import ClusterSupervisor
  return ClusterSupervisor(engine=None, server=server, node_job=None,
                           cluster_meta=meta, cluster_info=[],
                           engine_ids=[0, 1], tf_status={"error": None},
                           max_restarts=2)


class TestSupervisorResize:
  def test_commit_shrink_evicts_group_and_is_fatal_only_when_empty(self):
    server = rendezvous.Server(2)
    plane = G.attach_sync_plane(server, sync_timeout=5.0)
    plane.join(0)
    plane.join(1)
    sup = _stub_supervisor(server, {"group_map": {0: 0, 1: 1},
                                    "elastic": True})
    sup._commit_shrink(1, 1, attempts=2)
    assert 1 in plane.lost
    ev = [e for e in sup.events if e["kind"] == "resize-shrink"][0]
    assert ev["executor_id"] == 1 and ev["group"] == 1
    assert ev["attempts"] == 2 and ev["groups_active"] == 1
    assert sup.tf_status["error"] is None, \
        "a survivable shrink must not fail the job"
    sup._commit_shrink(0, 0, attempts=2)
    assert "all training groups lost" in sup.tf_status["error"]

  def test_recover_give_up_becomes_shrink_only_in_elastic_mode(self):
    for elastic in (True, False):
      server = rendezvous.Server(2)
      plane = G.attach_sync_plane(server, sync_timeout=5.0)
      plane.join(0)
      plane.join(1)
      meta = {"group_map": {0: 0, 1: 1}, "elastic": elastic,
              "cluster_template": {"worker": [0, 1]}}
      sup = _stub_supervisor(server, meta)
      sup._attempts[1] = sup.max_restarts        # budget already spent
      sup._recover(1)
      kinds = [e["kind"] for e in sup.events]
      if elastic:
        assert "resize-shrink" in kinds and "gave-up" not in kinds
        assert sup.tf_status["error"] is None
      else:
        assert "gave-up" in kinds and "resize-shrink" not in kinds
        gave = [e for e in sup.events if e["kind"] == "gave-up"][0]
        assert gave["attempts"] == 2 and gave["group"] == 1
        assert "restart budget" in sup.tf_status["error"]

  def test_readmit_resets_budget_and_rearms_liveness(self):
    server = rendezvous.Server(2, heartbeat_interval=0.1)
    G.attach_sync_plane(server, sync_timeout=5.0)
    sup = _stub_supervisor(server, {"group_map": {0: 0, 1: 1},
                                    "elastic": True})
    sup._given_up.add(1)
    sup._attempts[1] = 2
    # an old-incarnation beat confirmed the executor: without the rearm
    # the strict deadline would re-declare death mid-bring-up
    server.liveness.beat(1)
    assert 1 in server.liveness._confirmed
    sup.readmit(1)
    assert 1 not in sup._given_up and 1 not in sup._attempts
    assert 1 not in server.liveness._confirmed, \
        "readmit must re-arm the startup grace (drop confirmation)"
    ev = [e for e in sup.events if e["kind"] == "resize-readmit"][0]
    assert ev["executor_id"] == 1 and ev["group"] == 1
