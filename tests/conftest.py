"""Test configuration: force a virtual 8-device CPU platform.

Mirrors the reference's test strategy (reference tox.ini: a 2-worker Spark
standalone cluster on one host): multi-device behavior is tested on one host
by splitting the CPU into 8 virtual XLA devices. Must run before jax's
backend initializes — the shared helper raises if it's too late.

CRITICAL for this container: a sitecustomize hook registers a remote-TPU
PJRT plugin whenever PALLAS_AXON_POOL_IPS is set; see
tensorflowonspark_tpu/utils/platform_env.py for the full story.
"""

import os
import sys

sys.path.insert(0, os.path.abspath(os.path.join(os.path.dirname(__file__),
                                                os.pardir)))

from tensorflowonspark_tpu.utils.platform_env import force_cpu_platform

force_cpu_platform(8)
# keep subprocesses (LocalEngine executors) on CPU too
os.environ.setdefault("TOS_TPU_TEST_MODE", "1")


def pytest_configure(config):
  config.addinivalue_line(
      "markers",
      "chaos: fault-injection recovery tests (utils.chaos). Part of the "
      "tier-1 'not slow' selection — keep per-test deadlines tight (<10s); "
      "run alone via `make chaos`.")
  config.addinivalue_line(
      "markers", "slow: long-running tests excluded from the tier-1 "
      "selection (`-m 'not slow'`).")
