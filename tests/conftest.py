"""Test configuration: force a virtual 8-device CPU platform.

Mirrors the reference's test strategy (reference tox.ini: a 2-worker Spark
standalone cluster on one host): multi-device behavior is tested on one host
by splitting the CPU into 8 virtual XLA devices. Must run before jax import.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
  os.environ["XLA_FLAGS"] = (
      flags + " --xla_force_host_platform_device_count=8").strip()
# keep subprocesses (LocalEngine executors) on CPU too
os.environ.setdefault("TOS_TPU_TEST_MODE", "1")
