"""Test configuration: force a virtual 8-device CPU platform.

Mirrors the reference's test strategy (reference tox.ini: a 2-worker Spark
standalone cluster on one host): multi-device behavior is tested on one host
by splitting the CPU into 8 virtual XLA devices. Must run before jax import.
"""

import os

# CRITICAL for this container: a sitecustomize hook registers a remote-TPU
# PJRT plugin whenever PALLAS_AXON_POOL_IPS is set, and xla_bridge initializes
# it even under JAX_PLATFORMS=cpu — every test process would then dial the
# single remote TPU for a claim (hanging, and wedging the claim service under
# concurrency). Tests are CPU-only: drop the trigger before any jax import;
# child processes inherit this environment.
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ["JAX_PLATFORMS"] = "cpu"
# The plugin's register() (already executed by sitecustomize in THIS
# process) force-sets jax.config jax_platforms="axon,cpu", overriding the
# env var — undo that so in-process jax stays CPU-only too.
try:
  import jax
  jax.config.update("jax_platforms", "cpu")
except Exception:  # noqa: BLE001 - no jax yet means nothing to undo
  pass
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
  os.environ["XLA_FLAGS"] = (
      flags + " --xla_force_host_platform_device_count=8").strip()
# keep subprocesses (LocalEngine executors) on CPU too
os.environ.setdefault("TOS_TPU_TEST_MODE", "1")
