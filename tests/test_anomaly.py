"""Online anomaly detection + compile/device tier tests.

Unit level: each detector in ``obs.anomaly`` driven with synthetic
ObsSink aggregates and an injected clock — one firing case and one
just-below-threshold negative case per detector. Device tier: the
jax.monitoring recompile sentinel and the per-seam trace counters,
including THE pin this PR exists for — a steady-state train loop reports
ZERO post-warmup compiles (jit-cache hygiene used to be unpinned and
would regress silently).

Integration (chaos marker): a ``TOS_CHAOS_STALL``-injured executor in a
real 2-process LocalEngine cluster trips the straggler alert, visible in
(a) the supervisor event stream, (b) the driver JSONL via
``obs_report --alerts`` machinery, and (c) the rendezvous HEALTH wire
that ``tools/obs_top.py`` polls.
"""

import os
import sys
import time

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tensorflowonspark_tpu.obs import anomaly, metrics, spans
from tensorflowonspark_tpu.obs import device as obs_device


@pytest.fixture(autouse=True)
def clean_active():
  """No test here may leak the process-global registry/tracer: the
  cluster-driving tests set TOS_OBS=1, which lazily installs both in
  THIS process (the driver side)."""
  yield
  metrics.deactivate()
  spans.deactivate()


class FakeSink(object):
  """The minimal sink surface the detector reads: ``executors`` keys and
  ``metrics(eid)`` snapshots."""

  def __init__(self, eids=(0, 1)):
    self.executors = {e: {} for e in eids}
    self.data = {e: {} for e in eids}

  def metrics(self, eid):
    return self.data[eid]

  def set(self, eid, **values):
    snap = {}
    for name, v in values.items():
      snap[name.replace("__", ".")] = {"type": "counter", "value": float(v)}
    self.data[eid] = snap


def _detector(sink, **kw):
  kw.setdefault("interval", 0.5)
  kw.setdefault("window", 10.0)
  kw.setdefault("registry", metrics.MetricsRegistry())
  kw.setdefault("recorder", None)
  return anomaly.AnomalyDetector(sink, **kw)


class TestStragglerDetector:
  def test_fires_on_slow_executor(self):
    sink = FakeSink()
    det = _detector(sink)
    sink.set(0, train__steps=0)
    sink.set(1, train__steps=0)
    assert det.poll(now=0.0) == []
    sink.set(0, train__steps=100)
    sink.set(1, train__steps=10)          # 90% behind: well past 50%
    alerts = det.poll(now=10.0)
    assert [a["alert"] for a in alerts] == ["straggler"]
    assert alerts[0]["executor_id"] == 1
    assert alerts[0]["evidence"]["cluster_median"] == pytest.approx(10.0)
    # counted into the registry + the bounded ring + the summary
    assert det.recent_alerts()[0]["alert"] == "straggler"
    assert det.summary()["by_kind"] == {"straggler": 1}
    reg_snap = det._reg.snapshot()
    assert reg_snap["obs.alerts"]["value"] == 1
    assert reg_snap["obs.alerts.straggler"]["value"] == 1

  def test_just_below_threshold_stays_quiet(self):
    sink = FakeSink()
    det = _detector(sink)
    sink.set(0, train__steps=0)
    sink.set(1, train__steps=0)
    det.poll(now=0.0)
    sink.set(0, train__steps=100)
    sink.set(1, train__steps=60)          # 40% behind < the 50% threshold
    assert det.poll(now=10.0) == []

  def test_single_executor_never_straggles(self):
    sink = FakeSink(eids=(0,))
    det = _detector(sink)
    sink.set(0, train__steps=0)
    det.poll(now=0.0)
    sink.set(0, train__steps=0)           # fully stalled — but alone
    assert det.poll(now=10.0) == []

  def test_idle_cluster_rates_are_noise(self):
    """Below MIN_WINDOW_STEPS for the median executor nothing fires —
    a cluster that is barely stepping has no step-rate signal."""
    sink = FakeSink()
    det = _detector(sink)
    sink.set(0, train__steps=0)
    sink.set(1, train__steps=0)
    det.poll(now=0.0)
    sink.set(0, train__steps=3)           # 3 < MIN_WINDOW_STEPS
    sink.set(1, train__steps=0)
    assert det.poll(now=10.0) == []

  def test_cooldown_suppresses_refire(self):
    sink = FakeSink()
    det = _detector(sink)
    det.cooldown = 100.0
    sink.set(0, train__steps=0)
    sink.set(1, train__steps=0)
    det.poll(now=0.0)
    sink.set(0, train__steps=100)
    sink.set(1, train__steps=0)
    assert len(det.poll(now=10.0)) == 1
    sink.set(0, train__steps=200)
    assert det.poll(now=20.0) == []       # inside the cooldown
    sink.set(0, train__steps=2000)
    assert len(det.poll(now=120.0)) == 1  # past it


class TestFeedStallDetector:
  def test_fires_with_stage_attribution(self):
    """Mid-run starvation: batches delivered before, ZERO fresh batches
    across the window, the feed plane dominating it — input-bound."""
    sink = FakeSink(eids=(0,))
    det = _detector(sink)
    sink.set(0, feed__batches=5, feed__fetch_s=0.0, feed__decode_s=0.0,
             feed__assemble_s=0.0)
    det.poll(now=0.0)
    sink.set(0, feed__batches=5, feed__fetch_s=8.0, feed__decode_s=0.5,
             feed__assemble_s=0.1)
    alerts = det.poll(now=10.0)
    assert [a["alert"] for a in alerts] == ["feed_stall"]
    assert alerts[0]["evidence"]["stage"] == "fetch_s"

  def test_flowing_batches_stay_quiet_despite_fetch_time(self):
    """The fetch PIPELINE thread accrues fetch_s even while batches flow
    (healthy overlap) — seen firing falsely in the bring-up drive; the
    detector must key on zero FRESH batches, not stage seconds alone."""
    sink = FakeSink(eids=(0,))
    det = _detector(sink)
    sink.set(0, feed__batches=5, feed__fetch_s=0.0, feed__decode_s=0.0,
             feed__assemble_s=0.0)
    det.poll(now=0.0)
    sink.set(0, feed__batches=50, feed__fetch_s=9.5, feed__decode_s=0.5,
             feed__assemble_s=0.1)
    assert det.poll(now=10.0) == []

  def test_below_fraction_stays_quiet(self):
    sink = FakeSink(eids=(0,))
    det = _detector(sink)
    sink.set(0, feed__batches=5, feed__fetch_s=0.0, feed__decode_s=0.0,
             feed__assemble_s=0.0)
    det.poll(now=0.0)
    sink.set(0, feed__batches=5, feed__fetch_s=5.0, feed__decode_s=0.5,
             feed__assemble_s=0.1)        # 56% < the 60% default
    assert det.poll(now=10.0) == []

  def test_buffered_progress_stays_quiet(self):
    """No fresh batches but the consumer kept stepping on buffered
    chunks: not starved (yet)."""
    sink = FakeSink(eids=(0,))
    det = _detector(sink)
    sink.set(0, train__steps=10, feed__batches=5, feed__fetch_s=0.0,
             feed__decode_s=0.0, feed__assemble_s=0.0)
    det.poll(now=0.0)
    sink.set(0, train__steps=30, feed__batches=5, feed__fetch_s=9.0,
             feed__decode_s=0.0, feed__assemble_s=0.0)
    assert det.poll(now=10.0) == []

  def test_never_delivered_is_bringup_not_stall(self):
    sink = FakeSink(eids=(0,))
    det = _detector(sink)
    sink.set(0, feed__batches=0, feed__fetch_s=0.0, feed__decode_s=0.0,
             feed__assemble_s=0.0)
    det.poll(now=0.0)
    sink.set(0, feed__batches=0, feed__fetch_s=9.0, feed__decode_s=0.0,
             feed__assemble_s=0.0)
    assert det.poll(now=10.0) == []

  def test_no_datafeed_executor_is_exempt(self):
    sink = FakeSink(eids=(0,))
    det = _detector(sink)
    sink.set(0, train__steps=0)           # FILES mode: no feed metrics
    det.poll(now=0.0)
    sink.set(0, train__steps=0)
    assert det.poll(now=10.0) == []

  def test_graph_stage_attribution_names_the_starved_transform(self):
    """Under a ``data.datapipe`` graph the per-stage busy gauges
    (``feed.stage.<name>.busy_s``) join the attribution set: the alert
    must name the dominant GRAPH stage (``pipe:map0``), not just the
    classic fetch/decode/assemble trio (which stay ~zero in graph
    mode)."""
    sink = FakeSink(eids=(0,))
    det = _detector(sink)
    sink.set(0, feed__batches=5, feed__fetch_s=0.0, feed__decode_s=0.0,
             feed__assemble_s=0.0, feed__stage__src__busy_s=0.0,
             feed__stage__map0__busy_s=0.0,
             feed__stage__assemble__busy_s=0.0)
    det.poll(now=0.0)
    sink.set(0, feed__batches=5, feed__fetch_s=0.1, feed__decode_s=0.0,
             feed__assemble_s=0.0, feed__stage__src__busy_s=0.4,
             feed__stage__map0__busy_s=8.0,
             feed__stage__assemble__busy_s=0.2)
    alerts = det.poll(now=10.0)
    assert [a["alert"] for a in alerts] == ["feed_stall"]
    assert alerts[0]["evidence"]["stage"] == "pipe:map0"

  def test_graph_flowing_batches_stay_quiet_despite_stage_busy(self):
    """Detector negative: a saturated-but-DELIVERING graph stage accrues
    busy seconds by design (that is what the autotuner feeds on) — with
    fresh batches flowing the stall detector must stay quiet."""
    sink = FakeSink(eids=(0,))
    det = _detector(sink)
    sink.set(0, feed__batches=5, feed__fetch_s=0.0, feed__decode_s=0.0,
             feed__assemble_s=0.0, feed__stage__map0__busy_s=0.0)
    det.poll(now=0.0)
    sink.set(0, feed__batches=60, feed__fetch_s=0.0, feed__decode_s=0.0,
             feed__assemble_s=0.0, feed__stage__map0__busy_s=9.5)
    assert det.poll(now=10.0) == []

  def test_graph_below_fraction_stays_quiet(self):
    """Detector negative: starved window but the graph stages were NOT
    the reason (busy fraction under the threshold — consumer-side
    pause, not an input-bound pipeline)."""
    sink = FakeSink(eids=(0,))
    det = _detector(sink)
    sink.set(0, feed__batches=5, feed__fetch_s=0.0, feed__decode_s=0.0,
             feed__assemble_s=0.0, feed__stage__src__busy_s=0.0,
             feed__stage__map0__busy_s=0.0)
    det.poll(now=0.0)
    sink.set(0, feed__batches=5, feed__fetch_s=0.0, feed__decode_s=0.0,
             feed__assemble_s=0.0, feed__stage__src__busy_s=2.0,
             feed__stage__map0__busy_s=3.0)   # 50% < the 60% default
    assert det.poll(now=10.0) == []


class TestWindowGuards:
  def test_sub_minimum_window_never_evaluates(self):
    """Startup skew in a sub-second window must not read as a straggler
    (the bring-up drive's false positive: one executor stepped before
    the other's first sample)."""
    sink = FakeSink()
    det = _detector(sink)                 # window 10 → min_span 5
    sink.set(0, train__steps=0)
    sink.set(1, train__steps=0)
    det.poll(now=0.0)
    sink.set(0, train__steps=50)
    sink.set(1, train__steps=0)
    assert det.poll(now=1.0) == []        # span 1 < min_span 5
    assert det.poll(now=6.0) != []        # span 6 ≥ 5: now it's real


class TestRecompileStormDetector:
  def test_fires_after_warmup(self):
    sink = FakeSink(eids=(0,))
    det = _detector(sink)
    det.compile_warmup = 5.0
    sink.set(0, xla__compiles=10)
    det.poll(now=0.0)
    sink.set(0, xla__compiles=14)         # 4 >= limit 3, past warmup
    alerts = det.poll(now=10.0)
    assert [a["alert"] for a in alerts] == ["recompile_storm"]
    assert alerts[0]["evidence"]["compiles"] == 4

  def test_warmup_compiles_are_free(self):
    sink = FakeSink(eids=(0,))
    det = _detector(sink)
    det.compile_warmup = 60.0
    sink.set(0, xla__compiles=0)
    det.poll(now=0.0)
    sink.set(0, xla__compiles=50)         # inside warmup: expected burst
    assert det.poll(now=10.0) == []

  def test_below_limit_stays_quiet(self):
    sink = FakeSink(eids=(0,))
    det = _detector(sink)
    det.compile_warmup = 5.0
    sink.set(0, xla__compiles=10)
    det.poll(now=0.0)
    sink.set(0, xla__compiles=12)         # 2 < limit 3
    assert det.poll(now=10.0) == []


class TestServingSaturationDetector:
  def test_fires_on_saturated_engine(self):
    sink = FakeSink(eids=(0,))
    det = _detector(sink)
    sink.set(0, serve__queue_depth=0, serve__occupancy=0.5)
    det.poll(now=0.0)
    sink.set(0, serve__queue_depth=12, serve__occupancy=0.97)
    alerts = det.poll(now=10.0)
    assert [a["alert"] for a in alerts] == ["serving_saturated"]

  def test_deep_queue_with_low_occupancy_stays_quiet(self):
    """A deep queue while slots idle is a scheduling bug, not
    saturation — the alert must not cry wolf on it."""
    sink = FakeSink(eids=(0,))
    det = _detector(sink)
    sink.set(0, serve__queue_depth=0, serve__occupancy=0.5)
    det.poll(now=0.0)
    sink.set(0, serve__queue_depth=12, serve__occupancy=0.5)
    assert det.poll(now=10.0) == []


class TestServeCrashLoopDetector:
  def test_fires_on_restart_burst(self):
    """TOS_OBS_CRASH_LOOP (default 2) engine restarts inside one window
    = a crash loop: one self-heal is routine, repeated ones mean a
    poison request slipped detection or the device is failing."""
    sink = FakeSink(eids=(0,))
    det = _detector(sink)
    sink.set(0, serve__engine_restarts=1, serve__replays=3)
    det.poll(now=0.0)
    sink.set(0, serve__engine_restarts=3, serve__replays=9)
    alerts = det.poll(now=10.0)
    assert [a["alert"] for a in alerts] == ["serve_crash_loop"]
    assert alerts[0]["evidence"]["restarts"] == 2
    assert alerts[0]["evidence"]["replays"] == 6
    assert alerts[0]["evidence"]["total_restarts"] == 3

  def test_single_recovery_stays_quiet(self):
    """ONE crash-replay inside a window is the self-healing design
    working — just below the threshold, no alert."""
    sink = FakeSink(eids=(0,))
    det = _detector(sink)
    sink.set(0, serve__engine_restarts=0)
    det.poll(now=0.0)
    sink.set(0, serve__engine_restarts=1)
    assert det.poll(now=10.0) == []

  def test_no_serving_executor_is_exempt(self):
    sink = FakeSink(eids=(0,))
    det = _detector(sink)
    sink.set(0, train__steps=0)
    det.poll(now=0.0)
    sink.set(0, train__steps=50)
    assert det.poll(now=10.0) == []


class TestKvPagesExhaustedDetector:
  def test_fires_when_pinned_at_zero_with_queue(self):
    """Free pages at 0 for EVERY sample in the window while requests
    queue = the paged KV pool is the admission bottleneck."""
    sink = FakeSink(eids=(0,))
    det = _detector(sink)
    sink.set(0, serve__kv_pages_free=0, serve__kv_pages_in_use=36,
             serve__queue_depth=5)
    det.poll(now=0.0)
    sink.set(0, serve__kv_pages_free=0, serve__kv_pages_in_use=36,
             serve__queue_depth=7)
    alerts = det.poll(now=10.0)
    assert [a["alert"] for a in alerts] == ["kv_pages_exhausted"]
    assert alerts[0]["evidence"]["queue_depth"] == 7
    assert alerts[0]["evidence"]["pages_in_use"] == 36

  def test_transient_zero_stays_quiet(self):
    """Any sample above 0 inside the window clears the verdict: dipping
    to 0 between completions is the pool doing its job, not exhaustion
    — just below the pinned-all-window threshold."""
    sink = FakeSink(eids=(0,))
    det = _detector(sink)
    sink.set(0, serve__kv_pages_free=1, serve__kv_pages_in_use=35,
             serve__queue_depth=5)
    det.poll(now=0.0)
    sink.set(0, serve__kv_pages_free=0, serve__kv_pages_in_use=36,
             serve__queue_depth=7)
    assert det.poll(now=10.0) == []

  def test_empty_queue_stays_quiet(self):
    """A full pool with nothing waiting is just a full pool — the alert
    is about ADMISSION being blocked, not utilization."""
    sink = FakeSink(eids=(0,))
    det = _detector(sink)
    sink.set(0, serve__kv_pages_free=0, serve__kv_pages_in_use=36,
             serve__queue_depth=0)
    det.poll(now=0.0)
    sink.set(0, serve__kv_pages_free=0, serve__kv_pages_in_use=36,
             serve__queue_depth=0)
    assert det.poll(now=10.0) == []

  def test_unpaged_executor_is_exempt(self):
    sink = FakeSink(eids=(0,))
    det = _detector(sink)
    sink.set(0, serve__queue_depth=9, serve__occupancy=0.2)
    det.poll(now=0.0)
    sink.set(0, serve__queue_depth=9, serve__occupancy=0.2)
    assert det.poll(now=10.0) == []


class TestFleetDetectors:
  def test_degraded_fires_below_full_strength(self):
    """A ServingFleet running fewer active replicas than configured =
    an ejection happened — visible online, not just in the event log."""
    sink = FakeSink(eids=(0,))
    det = _detector(sink)
    sink.set(0, fleet__replicas_total=3, fleet__replicas_active=3)
    det.poll(now=0.0)
    sink.set(0, fleet__replicas_total=3, fleet__replicas_active=2)
    alerts = det.poll(now=10.0)
    assert [a["alert"] for a in alerts] == ["fleet_degraded"]
    assert alerts[0]["evidence"]["replicas_active"] == 2
    assert alerts[0]["evidence"]["replicas_total"] == 3

  def test_full_strength_stays_quiet(self):
    sink = FakeSink(eids=(0,))
    det = _detector(sink)
    sink.set(0, fleet__replicas_total=3, fleet__replicas_active=3,
             fleet__queue_depth=0, fleet__occupancy=0.4)
    det.poll(now=0.0)
    sink.set(0, fleet__replicas_total=3, fleet__replicas_active=3,
             fleet__queue_depth=0, fleet__occupancy=0.4)
    assert det.poll(now=10.0) == []

  def test_saturated_fires_scale_up_signal(self):
    """At FULL strength with every replica goodput-bound (the
    serving_saturated thresholds applied to the fleet aggregate), the
    detector says scale up — add a replica."""
    sink = FakeSink(eids=(0,))
    det = _detector(sink)            # queue_sat default 8, per replica
    sink.set(0, fleet__replicas_total=2, fleet__replicas_active=2,
             fleet__queue_depth=16, fleet__occupancy=0.95)
    det.poll(now=0.0)
    sink.set(0, fleet__replicas_total=2, fleet__replicas_active=2,
             fleet__queue_depth=20, fleet__occupancy=0.97)
    alerts = det.poll(now=10.0)
    assert [a["alert"] for a in alerts] == ["fleet_saturated"]
    assert "add a replica" in alerts[0]["message"]

  def test_rolling_swap_in_progress_stays_quiet(self):
    """A DRAINING replica is a healthy operator-initiated swap, not
    lost capacity — firing fleet_degraded on every rolling swap would
    train operators to ignore the real ejection signal (and mid-swap
    saturation readings are suppressed too)."""
    sink = FakeSink(eids=(0,))
    det = _detector(sink)
    sink.set(0, fleet__replicas_total=3, fleet__replicas_active=2,
             fleet__replicas_draining=1, fleet__queue_depth=99,
             fleet__occupancy=1.0)
    det.poll(now=0.0)
    sink.set(0, fleet__replicas_total=3, fleet__replicas_active=2,
             fleet__replicas_draining=1, fleet__queue_depth=99,
             fleet__occupancy=1.0)
    assert det.poll(now=10.0) == []

  def test_saturated_below_per_replica_queue_stays_quiet(self):
    """Just below the aggregate bound: queue_sat × active − 1."""
    sink = FakeSink(eids=(0,))
    det = _detector(sink)
    sink.set(0, fleet__replicas_total=2, fleet__replicas_active=2,
             fleet__queue_depth=15, fleet__occupancy=0.97)
    det.poll(now=0.0)
    sink.set(0, fleet__replicas_total=2, fleet__replicas_active=2,
             fleet__queue_depth=15, fleet__occupancy=0.97)
    assert det.poll(now=10.0) == []

  def test_no_fleet_executor_is_exempt(self):
    sink = FakeSink(eids=(0,))
    det = _detector(sink)
    sink.set(0, serve__queue_depth=9)
    det.poll(now=0.0)
    sink.set(0, serve__queue_depth=9)
    assert det.poll(now=10.0) == []

  def test_degraded_wins_over_saturated(self):
    """A degraded fleet that is ALSO saturated reports degraded — the
    remedy (restore the ejected replica) subsumes the scale-up advice."""
    sink = FakeSink(eids=(0,))
    det = _detector(sink)
    sink.set(0, fleet__replicas_total=3, fleet__replicas_active=2,
             fleet__queue_depth=99, fleet__occupancy=1.0)
    det.poll(now=0.0)
    sink.set(0, fleet__replicas_total=3, fleet__replicas_active=2,
             fleet__queue_depth=99, fleet__occupancy=1.0)
    alerts = det.poll(now=10.0)
    assert [a["alert"] for a in alerts] == ["fleet_degraded"]


class TestHostLostDetector:
  def test_host_lost_fires_when_a_serving_host_stops_syncing(self):
    """The cross-host serving plane syncing fewer ServingHosts than it
    registered = an executor host died or is partitioned past
    TOS_HOST_TIMEOUT — lost capacity (restore the host), distinct from
    fleet saturation (add a replica)."""
    sink = FakeSink(eids=(0,))
    det = _detector(sink)
    sink.set(0, serve__hosts_total=2, serve__hosts_alive=2)
    det.poll(now=0.0)
    sink.set(0, serve__hosts_total=2, serve__hosts_alive=1,
             fleet__queue_depth=4, fleet__occupancy=0.8)
    alerts = det.poll(now=10.0)
    assert [a["alert"] for a in alerts] == ["host_lost"]
    assert alerts[0]["evidence"]["hosts_alive"] == 1
    assert alerts[0]["evidence"]["hosts_total"] == 2
    assert "lost capacity" in alerts[0]["message"]

  def test_all_hosts_alive_stays_quiet(self):
    sink = FakeSink(eids=(0,))
    det = _detector(sink)
    sink.set(0, serve__hosts_total=2, serve__hosts_alive=2)
    det.poll(now=0.0)
    sink.set(0, serve__hosts_total=2, serve__hosts_alive=2)
    assert det.poll(now=10.0) == []

  def test_no_serving_plane_is_exempt(self):
    """Executors without the plane's gauges never trip the detector."""
    sink = FakeSink(eids=(0,))
    det = _detector(sink)
    sink.set(0, serve__queue_depth=3)
    det.poll(now=0.0)
    sink.set(0, serve__queue_depth=3)
    assert det.poll(now=10.0) == []


class TestGroupDetectors:
  def test_group_lost_fires_below_full_strength(self):
    """An elastic GroupSet running fewer active groups than it has ever
    had = a group died or was evicted (parallel.groups) — surviving
    groups keep stepping degraded, and that must be visible online."""
    sink = FakeSink(eids=(0,))
    det = _detector(sink)
    sink.set(0, training__groups_total=3, training__groups_active=3)
    det.poll(now=0.0)
    sink.set(0, training__groups_total=3, training__groups_active=2)
    alerts = det.poll(now=10.0)
    assert [a["alert"] for a in alerts] == ["group_lost"]
    assert alerts[0]["evidence"]["groups_active"] == 2
    assert alerts[0]["evidence"]["groups_total"] == 3
    assert "re-admit" in alerts[0]["message"]

  def test_full_group_strength_stays_quiet(self):
    sink = FakeSink(eids=(0,))
    det = _detector(sink)
    sink.set(0, training__groups_total=3, training__groups_active=3,
             training__sync_ms=40.0)
    det.poll(now=0.0)
    sink.set(0, training__groups_total=3, training__groups_active=3,
             training__sync_ms=40.0)
    assert det.poll(now=10.0) == []

  def test_sync_lag_fires_at_threshold(self):
    """A sync round that ran at/over TOS_OBS_SYNC_LAG_MS means a slow or
    stalled group is dragging every boundary toward the round deadline
    (and past the miss limit the plane will evict it)."""
    sink = FakeSink(eids=(0,))
    det = _detector(sink)
    det.sync_lag_ms = 100.0
    sink.set(0, training__groups_total=2, training__groups_active=2,
             training__sync_ms=10.0)
    det.poll(now=0.0)
    sink.set(0, training__groups_total=2, training__groups_active=2,
             training__sync_ms=150.0)
    alerts = det.poll(now=10.0)
    assert [a["alert"] for a in alerts] == ["sync_lag"]
    assert alerts[0]["evidence"]["sync_ms"] == 150.0
    assert alerts[0]["evidence"]["threshold_ms"] == 100.0

  def test_sync_lag_below_threshold_stays_quiet(self):
    sink = FakeSink(eids=(0,))
    det = _detector(sink)
    det.sync_lag_ms = 100.0
    sink.set(0, training__groups_total=2, training__groups_active=2,
             training__sync_ms=10.0)
    det.poll(now=0.0)
    sink.set(0, training__groups_total=2, training__groups_active=2,
             training__sync_ms=99.0)
    assert det.poll(now=10.0) == []

  def test_ungrouped_executor_is_exempt(self):
    sink = FakeSink(eids=(0,))
    det = _detector(sink)
    sink.set(0, train__steps=1)
    det.poll(now=0.0)
    sink.set(0, train__steps=2)
    assert det.poll(now=10.0) == []


class TestMemorySlopeDetector:
  def test_fires_on_monotonic_creep(self):
    sink = FakeSink(eids=(0,))
    det = _detector(sink)
    base = 1000 * 1000 * 1000
    fired = []
    for i, t in enumerate((0.0, 3.0, 6.0, 9.0)):
      sink.set(0, device__bytes_in_use=base * (1 + 0.05 * i))
      fired.extend(det.poll(now=t))
    assert [a["alert"] for a in fired] == ["mem_slope"]
    assert fired[0]["evidence"]["growth_pct"] >= 10.0

  def test_below_slope_stays_quiet(self):
    sink = FakeSink(eids=(0,))
    det = _detector(sink)
    base = 1000 * 1000 * 1000
    for i, t in enumerate((0.0, 3.0, 6.0, 9.0)):
      sink.set(0, device__bytes_in_use=base * (1 + 0.02 * i))
      alerts = det.poll(now=t)
    assert alerts == []                   # 6% < the 10% default

  def test_peak_then_shrink_is_not_a_leak(self):
    sink = FakeSink(eids=(0,))
    det = _detector(sink)
    for v, t in ((100, 0.0), (200, 3.0), (150, 6.0), (160, 9.0)):
      sink.set(0, device__bytes_in_use=v * 1e6)
      alerts = det.poll(now=t)
    assert alerts == []


class TestDetectorPlumbing:
  def test_supervisor_event_mirroring(self):
    class Sup(object):
      def __init__(self):
        self.events = []

      def _event(self, kind, **fields):
        self.events.append(dict(fields, kind=kind))

    sink = FakeSink()
    sup = Sup()
    det = _detector(sink, supervisor=sup)
    sink.set(0, train__steps=0)
    sink.set(1, train__steps=0)
    det.poll(now=0.0)
    sink.set(0, train__steps=100)
    sink.set(1, train__steps=0)
    det.poll(now=10.0)
    assert [e["kind"] for e in sup.events] == ["alert-straggler"]
    assert sup.events[0]["executor_id"] == 1

  def test_jsonl_appends_survive_for_postmortem(self, tmp_path):
    from tensorflowonspark_tpu.obs import export
    sink = FakeSink()
    log = export.ProcessLog(str(tmp_path), label="driver", executor_id=0)
    det = _detector(sink, jsonl=log)
    sink.set(0, train__steps=0)
    sink.set(1, train__steps=0)
    det.poll(now=0.0)
    sink.set(0, train__steps=100)
    sink.set(1, train__steps=0)
    det.poll(now=10.0)
    procs = export.merge_jsonl(export.find_logs(str(tmp_path)))
    assert len(procs) == 1
    assert [a["alert"] for a in procs[0]["alerts"]] == ["straggler"]
    # and the report surfaces the counts (obs_report --alerts machinery)
    from tools import obs_report
    result, _ = obs_report.build_report(str(tmp_path))
    assert result["alerts_total"] == 1
    assert result["alerts_by_kind"] == {"straggler": 1}

  def test_wait_alert_blocks_bounded(self):
    sink = FakeSink(eids=(0,))
    det = _detector(sink)
    t0 = time.monotonic()
    assert det.wait_alert(timeout=0.3) is None
    assert time.monotonic() - t0 < 5.0
    sink.set(0, serve__queue_depth=0, serve__occupancy=0.5)
    det.poll(now=0.0)
    sink.set(0, serve__queue_depth=12, serve__occupancy=0.99)
    det.poll(now=10.0)
    got = det.wait_alert(timeout=1.0, kind="serving_saturated")
    assert got and got["alert"] == "serving_saturated"

  def test_eval_failure_counted_not_raised(self):
    class BrokenSink(object):
      executors = {0: {}}

      def metrics(self, eid):
        raise RuntimeError("boom")

    det = _detector(BrokenSink())
    assert det.poll(now=0.0) == []
    assert det.eval_failures == 1

  def test_loop_thread_starts_and_stops(self):
    sink = FakeSink(eids=(0,))
    det = _detector(sink, interval=0.05).start()
    time.sleep(0.2)
    det.stop(timeout=5.0)
    assert det._thread is None


# --- compile/device tier -----------------------------------------------------


class TestDeviceTier:
  def test_note_trace_counts_once_per_jit_cache_entry(self, clean_active):
    import jax
    import jax.numpy as jnp
    reg = metrics.activate()

    def impl(x):
      obs_device.note_trace("unit.seam")
      return x * 2

    fn = jax.jit(impl)
    for _ in range(5):
      fn(jnp.ones((4,)))
    assert reg.snapshot()["xla.compiles.unit.seam"]["value"] == 1
    fn(jnp.ones((8,)))                    # new shape: one more trace
    assert reg.snapshot()["xla.compiles.unit.seam"]["value"] == 2

  def test_monitoring_listener_counts_backend_compiles(self, clean_active):
    import jax
    import jax.numpy as jnp
    reg = metrics.activate()
    if not obs_device.install_compile_listener():
      pytest.skip("jax.monitoring unavailable on this jax")
    before = reg.snapshot().get("xla.compiles", {}).get("value", 0)
    jax.jit(lambda x: x + 1)(jnp.ones((3,)))
    snap = reg.snapshot()
    assert snap["xla.compiles"]["value"] > before
    assert snap["xla.compile_ms"]["count"] >= 1

  def test_steady_state_train_loop_zero_postwarmup_compiles(
      self, clean_active, monkeypatch):
    """THE jit-cache hygiene pin: after warmup, a fixed-shape train loop
    through the real sharded train-step seam must never compile again —
    globally (jax.monitoring) and at the seam (its trace counter)."""
    import jax
    import jax.numpy as jnp
    monkeypatch.setenv(metrics.ENV_OBS, "1")
    reg = metrics.activate()
    obs_device.install_compile_listener()
    obs_device.reset_cost_cache()
    from flax.training import train_state as ts
    import optax
    from tensorflowonspark_tpu.parallel import mesh as mesh_lib
    from tensorflowonspark_tpu.parallel import sharding

    mesh = mesh_lib.build_mesh(mesh_lib.MeshSpec(data=-1),
                               devices=jax.devices()[:1])

    def loss_fn(params, batch):
      pred = batch["x"] @ params["w"]
      return jnp.mean((pred - batch["y"]) ** 2)

    step = sharding.make_train_step(loss_fn, mesh, donate_state=False)
    state = ts.TrainState.create(
        apply_fn=None, params={"w": jnp.ones((4, 2))},
        tx=optax.sgd(1e-2))
    batch = {"x": jnp.ones((8, 4)), "y": jnp.zeros((8, 2))}
    for _ in range(2):                     # warmup: compiles expected
      state, _ = step(state, batch)
    snap = reg.snapshot()
    warm_global = snap.get("xla.compiles", {}).get("value", 0)
    warm_seam = snap["xla.compiles.train.step"]["value"]
    assert warm_seam >= 1
    for _ in range(20):                    # steady state: ZERO compiles
      state, loss = step(state, batch)
    jax.block_until_ready(loss)
    snap = reg.snapshot()
    assert snap.get("xla.compiles", {}).get("value", 0) == warm_global
    assert snap["xla.compiles.train.step"]["value"] == warm_seam
    # the device tier captured the train step's HLO cost exactly once
    assert snap["xla.cost.captures"]["value"] + \
        snap.get("xla.cost.failures", {}).get("value", 0) >= 1

  def test_capture_cost_once_per_shape(self, clean_active):
    import jax
    import jax.numpy as jnp
    reg = metrics.activate()
    obs_device.reset_cost_cache()
    fn = jax.jit(lambda x: (x * 2).sum())
    x = jnp.ones((16, 16))
    got = obs_device.capture_cost("unit.cost", fn, x)
    if got is None:                        # backend without HLO properties
      assert reg.snapshot()["xla.cost.failures"]["value"] >= 1
      return
    assert got["flops"] > 0
    assert obs_device.capture_cost("unit.cost", fn, x) is None   # memoized
    assert obs_device.capture_cost(
        "unit.cost", fn, jnp.ones((8, 8))) is not None           # new shape
    snap = reg.snapshot()
    assert snap["xla.cost.unit.cost.flops"]["value"] > 0
    assert snap["xla.cost.captures"]["value"] == 2

  def test_memory_sampler_sets_gauges(self):
    reg = metrics.MetricsRegistry()
    fake = {"0": {"bytes_in_use": 100, "peak_bytes_in_use": 150,
                  "bytes_limit": 1000},
            "1": {"bytes_in_use": 50, "peak_bytes_in_use": 80,
                  "bytes_limit": 1000}}
    sampler = obs_device.make_memory_sampler(reg, stats_fn=lambda: fake)
    sampler()
    snap = reg.snapshot()
    assert snap["device.bytes_in_use"]["value"] == 150
    assert snap["device.peak_bytes"]["value"] == 150
    assert snap["device.bytes_limit"]["value"] == 2000
    assert snap["device.mem_samples"]["value"] == 1
    # STATIC memory touches nothing (else the per-round counter bump
    # alone would wake the shipper's idle wire every interval forever)
    sampler()
    assert reg.snapshot()["device.mem_samples"]["value"] == 1
    fake["0"]["bytes_in_use"] = 200                # movement counts again
    sampler()
    assert reg.snapshot()["device.mem_samples"]["value"] == 2
    assert reg.snapshot()["device.bytes_in_use"]["value"] == 250
    # a stats-less backend leaves the gauges untouched
    sampler2 = obs_device.make_memory_sampler(reg, stats_fn=dict)
    sampler2()
    assert reg.snapshot()["device.mem_samples"]["value"] == 2


class TestStragglerBurstTolerance:
  """Fused train loops (make_train_loop) land steps K at a time: an
  executor whose slab dispatch straddles the window edge shows up to one
  burst fewer steps than its peers — quantization, not straggling. The
  detector discounts exactly one burst (the shipped ``train.unroll``
  gauge), no more."""

  def _window(self, det, sink, fast_steps, slow_steps, unroll):
    sink.set(0, train__steps=0, train__unroll=unroll)
    sink.set(1, train__steps=0, train__unroll=unroll)
    det.poll(now=0.0)
    sink.set(0, train__steps=fast_steps, train__unroll=unroll)
    sink.set(1, train__steps=slow_steps, train__unroll=unroll)
    return det.poll(now=10.0)

  def test_behind_by_one_burst_stays_quiet(self):
    """unroll=8, 10 vs 16 steps: 37.5%... below rate threshold needs
    >50% behind — use 6 vs 16 (62.5% behind, past the 50% threshold)
    but with unroll=16 the 10-step gap is within ONE burst: quiet."""
    sink = FakeSink()
    det = _detector(sink)
    assert self._window(det, sink, fast_steps=16, slow_steps=6,
                        unroll=16) == []

  def test_behind_by_more_than_one_burst_fires(self):
    """The just-above pair: the same 62.5%-behind executor with
    unroll=8 is 10 steps behind — more than one burst: fires."""
    sink = FakeSink()
    det = _detector(sink)
    alerts = self._window(det, sink, fast_steps=16, slow_steps=6,
                          unroll=8)
    assert [a["alert"] for a in alerts] == ["straggler"]
    assert alerts[0]["executor_id"] == 1

  def test_no_unroll_metric_means_burst_of_one(self):
    """Per-step clusters (no train.unroll shipped) keep the old
    behavior: any >threshold gap beyond a single step fires."""
    sink = FakeSink()
    det = _detector(sink)
    sink.set(0, train__steps=0)
    sink.set(1, train__steps=0)
    det.poll(now=0.0)
    sink.set(0, train__steps=16)
    sink.set(1, train__steps=6)
    alerts = det.poll(now=10.0)
    assert [a["alert"] for a in alerts] == ["straggler"]


class TestFusedLoopDeviceTier:
  def test_steady_state_fused_loop_zero_postwarmup_compiles(
      self, clean_active, monkeypatch):
    """The fused-loop mirror of THE jit-cache pin: slabs + full-size
    tail batches re-dispatch forever on exactly two cache entries —
    zero post-warmup compiles globally and at both seams."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    monkeypatch.setenv(metrics.ENV_OBS, "1")
    reg = metrics.activate()
    obs_device.install_compile_listener()
    obs_device.reset_cost_cache()
    from flax.training import train_state as ts
    import optax
    from tensorflowonspark_tpu.data.readers import Slab
    from tensorflowonspark_tpu.parallel import mesh as mesh_lib
    from tensorflowonspark_tpu.parallel import sharding

    mesh = mesh_lib.build_mesh(mesh_lib.MeshSpec(data=-1),
                               devices=jax.devices()[:1])

    def loss_fn(params, batch):
      pred = batch["x"] @ params["w"]
      return jnp.mean((pred - batch["y"]) ** 2)

    loop = sharding.make_train_loop(loss_fn, mesh, donate_state=False,
                                    unroll=4)

    def state():
      return ts.TrainState.create(apply_fn=None,
                                  params={"w": jnp.ones((4, 2))},
                                  tx=optax.sgd(1e-2))

    batch = {"x": np.ones((8, 4), "float32"),
             "y": np.zeros((8, 2), "float32")}
    slab = Slab({k: np.stack([v] * 4) for k, v in batch.items()})
    st = state()
    st, _ = loop(st, slab)                 # warmup: both entries compile
    st, _ = loop(st, batch)
    snap = reg.snapshot()
    warm_global = snap.get("xla.compiles", {}).get("value", 0)
    assert snap["xla.compiles.train.loop"]["value"] == 1
    assert snap["xla.compiles.train.step"]["value"] == 1
    for _ in range(10):                    # steady state: ZERO compiles
      st, _ = loop(st, slab)
      st, losses = loop(st, batch)
    jax.block_until_ready(losses)
    snap = reg.snapshot()
    assert snap.get("xla.compiles", {}).get("value", 0) == warm_global
    assert snap["xla.compiles.train.loop"]["value"] == 1
    assert snap["xla.compiles.train.step"]["value"] == 1
    assert snap["train.steps"]["value"] == 4 + 1 + 10 * 5

  def test_cache_hit_not_counted_as_fresh_compile(self, clean_active):
    """TOS_COMPILE_CACHE hits fire jax's cache-hit event INSIDE the
    compile-duration region — the paired duration event must count as a
    load (xla.cache_hits), never as a fresh compile, or a relaunched
    executor's warm bring-up reads as a recompile storm."""
    reg = metrics.activate()
    # simulate the exact event order jax emits on a persistent-cache hit
    obs_device._on_event("/jax/compilation_cache/cache_hits")
    obs_device._on_compile_duration(
        "/jax/core/compile/backend_compile_duration", 0.01)
    snap = reg.snapshot()
    assert snap["xla.cache_hits"]["value"] == 1
    assert "xla.compiles" not in snap
    # a duration event WITHOUT a pending hit is a real compile
    obs_device._on_compile_duration(
        "/jax/core/compile/backend_compile_duration", 0.01)
    snap = reg.snapshot()
    assert snap["xla.compiles"]["value"] == 1
    assert snap["xla.cache_hits"]["value"] == 1


# --- chaos integration -------------------------------------------------------


def _straggler_main_fn(args, ctx):
  """ENGINE-mode train loop; the armed executor stalls AFTER its first
  step — the mid-run straggler shape (heartbeats keep flowing from their
  own thread, so liveness stays green while the step rate craters)."""
  import time as _time
  from tensorflowonspark_tpu.obs.profiler import StepTimer
  from tensorflowonspark_tpu.utils import chaos as _chaos

  timer = StepTimer(warmup=0)
  feed = ctx.get_data_feed(train_mode=True)
  step = 0
  while not feed.should_stop():
    batch = feed.next_batch(16)
    if not batch:
      continue
    with timer.step(items=len(batch)):
      sum(batch)
      _time.sleep(0.02)
    step += 1
    ctx.report_progress(step)
    _chaos.stall_point("post-step", index=ctx.executor_id)


@pytest.mark.chaos
def test_chaos_stalled_executor_trips_straggler_alert(tmp_path, monkeypatch):
  """Acceptance path: a TOS_CHAOS_STALL-injured executor trips the
  straggler alert, visible in (a) the supervisor event stream, (b) the
  driver JSONL post-mortem, and (c) the HEALTH wire obs_top polls."""
  from tensorflowonspark_tpu import cluster as tos_cluster
  from tensorflowonspark_tpu.cluster import InputMode
  from tensorflowonspark_tpu.engine import LocalEngine
  from tensorflowonspark_tpu.obs import export
  from tensorflowonspark_tpu.utils import chaos

  chaos.reset()
  obs_dir = str(tmp_path / "obs")
  monkeypatch.setenv(metrics.ENV_OBS, "1")
  monkeypatch.setenv(export.ENV_OBS_DIR, obs_dir)
  monkeypatch.setenv(anomaly.ENV_OBS_DETECT_INTERVAL, "0.25")
  monkeypatch.setenv(anomaly.ENV_OBS_WINDOW, "2.0")
  from tensorflowonspark_tpu.obs import collector
  monkeypatch.setenv(collector.ENV_OBS_INTERVAL, "0.2")

  engine = LocalEngine(
      num_executors=2,
      env={chaos.ENV_STALL: "post-step@1:4",    # executor 1 stalls 4 s
           metrics.ENV_OBS: "1",
           collector.ENV_OBS_INTERVAL: "0.2",
           export.ENV_OBS_DIR: obs_dir})
  try:
    c = tos_cluster.run(engine, _straggler_main_fn,
                        input_mode=InputMode.ENGINE, reservation_timeout=60,
                        heartbeat_interval=0.5)
    assert c.detector is not None
    import threading
    data = list(range(4800))
    feeder = threading.Thread(
        target=lambda: c.train([data[i::8] for i in range(8)],
                               num_epochs=1, feed_timeout=300),
        daemon=True)
    feeder.start()
    # (the detector loop is live) wait for the alert itself, bounded
    alert = c.detector.wait_alert(timeout=60, kind="straggler")
    assert alert is not None, "straggler alert never fired"
    assert alert["executor_id"] == 1

    # (c) the HEALTH wire an out-of-process obs_top would poll
    from tools import obs_top
    reply, client = obs_top.poll_health(tuple(c.server_addr))
    client.close()
    wire_alerts = reply.get("alerts") or []
    assert any(a["alert"] == "straggler" and a["executor_id"] == 1
               for a in wire_alerts), wire_alerts
    snap = obs_top.build_snapshot(reply)
    assert snap["has_alert_ring"] and snap["alerts"]

    feeder.join(timeout=300)
    c.shutdown(timeout=600)

    # (a) the supervisor event stream: alerts land next to recoveries
    kinds = [e["kind"] for e in c.supervisor.events]
    assert "alert-straggler" in kinds, kinds
    # (b) the driver JSONL post-mortem via the obs_report machinery
    from tools import obs_report
    result, procs = obs_report.build_report(obs_dir)
    assert result["alerts_by_kind"].get("straggler", 0) >= 1, result
  finally:
    engine.stop()
    chaos.reset()


class TestCanaryDegradedDetector:
  """``canary_degraded``: the online rollout signal — fires only while a
  canary is actually live (deploy.state at CANARY/VERIFY), on parity
  divergence or a TTFT ratio blowout, keyed per candidate version."""

  def test_parity_divergence_fires(self):
    sink = FakeSink(eids=(0,))
    det = _detector(sink)
    sink.set(0, deploy__state=2, deploy__candidate=5,
             deploy__parity_failures=0)
    assert det.poll(now=0.0) == []
    sink.set(0, deploy__state=2, deploy__candidate=5,
             deploy__parity_failures=2)
    alerts = det.poll(now=10.0)
    assert [a["alert"] for a in alerts] == ["canary_degraded"]
    assert alerts[0]["evidence"]["candidate"] == 5
    assert alerts[0]["evidence"]["parity_failures"] == 2

  def test_ttft_ratio_fires_with_own_cooldown_key(self):
    sink = FakeSink(eids=(0,))
    det = _detector(sink)
    sink.set(0, deploy__state=1, deploy__candidate=7,
             deploy__canary_ttft_ratio=1.0)
    assert det.poll(now=0.0) == []
    sink.set(0, deploy__state=1, deploy__candidate=7,
             deploy__canary_ttft_ratio=12.5)   # >= the 10x default
    alerts = det.poll(now=10.0)
    assert [a["alert"] for a in alerts] == ["canary_degraded"]
    assert alerts[0]["evidence"]["ttft_ratio"] == 12.5

  def test_idle_controller_stays_quiet(self):
    # a moving parity counter with NO live canary (state idle) is
    # post-rollback residue, not a new incident
    sink = FakeSink(eids=(0,))
    det = _detector(sink)
    sink.set(0, deploy__state=0, deploy__candidate=5,
             deploy__parity_failures=0)
    det.poll(now=0.0)
    sink.set(0, deploy__state=0, deploy__candidate=5,
             deploy__parity_failures=3)
    assert det.poll(now=10.0) == []

  def test_below_ratio_stays_quiet(self):
    sink = FakeSink(eids=(0,))
    det = _detector(sink)
    sink.set(0, deploy__state=1, deploy__candidate=7,
             deploy__canary_ttft_ratio=1.0)
    det.poll(now=0.0)
    sink.set(0, deploy__state=1, deploy__candidate=7,
             deploy__canary_ttft_ratio=9.9)
    assert det.poll(now=10.0) == []

  def test_deploy_status_surfaces_newest_sample(self):
    sink = FakeSink(eids=(0,))
    det = _detector(sink)
    assert det.deploy_status() is None       # no deploy.* shipped yet
    sink.set(0, deploy__state=1, deploy__version=4, deploy__candidate=5,
             deploy__canary_ttft_ratio=1.2, deploy__canaries=1,
             deploy__promotions=3, deploy__rollbacks=1,
             deploy__parity_failures=0)
    det.poll(now=0.0)
    st = det.deploy_status()
    assert st["state"] == "canary" and st["state_code"] == 1
    assert st["version"] == 4 and st["candidate"] == 5
    assert st["ttft_ratio"] == pytest.approx(1.2)
    assert st["promotions"] == 3 and st["rollbacks"] == 1
