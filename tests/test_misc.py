"""Cross-cutting coverage: the inference CLI as a real subprocess, bundle
export/import round-trips, rendezvous protocol verbs, and small API
surfaces not covered elsewhere."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest


class TestTensorboardDiscovery:
  """Parity: the reference's three-step search and spawn
  (TFSparkNode.py:292-329)."""

  def test_finds_executable(self, tmp_path):
    from tensorflowonspark_tpu import node
    (tmp_path / "tensorboard").write_text("# fake executable")
    assert node._find_tensorboard(str(tmp_path)) == \
        str(tmp_path / "tensorboard")

  def test_falls_back_to_module_main(self, tmp_path):
    from tensorflowonspark_tpu import node
    pkg = tmp_path / "tensorboard"
    pkg.mkdir()
    (pkg / "main.py").write_text("# fake module entry")
    assert node._find_tensorboard(str(tmp_path)) == str(pkg / "main.py")

  def test_executable_takes_precedence(self, tmp_path):
    from tensorflowonspark_tpu import node
    d1, d2 = tmp_path / "a", tmp_path / "b"
    d1.mkdir(), d2.mkdir()
    (d2 / "tensorboard").write_text("# exe")
    pkg = d1 / "tensorboard"
    pkg.mkdir()
    (pkg / "main.py").write_text("# module")
    search = os.pathsep.join([str(d1), str(d2)])
    assert node._find_tensorboard(search) == str(d2 / "tensorboard")

  def test_default_search_covers_pythonpath(self, tmp_path, monkeypatch):
    from tensorflowonspark_tpu import node
    pkg = tmp_path / "tensorboard"
    pkg.mkdir()
    (pkg / "main.py").write_text("# via PYTHONPATH")
    monkeypatch.setenv("PATH", str(tmp_path / "nothing_here"))
    monkeypatch.setenv("PYTHONPATH", str(tmp_path))
    found = node._find_tensorboard()
    # the default search also covers the interpreter's bin dir and
    # sys.path, and some images ship a tensorboard launcher there — any
    # hit (executable or module-form main.py) proves the default search
    # string includes the env-derived entries
    assert found
    assert str(found).endswith(os.path.join("tensorboard", "main.py")) \
        or os.path.basename(str(found)) == "tensorboard"

  def test_not_found_returns_false(self, tmp_path):
    from tensorflowonspark_tpu import node
    assert not node._find_tensorboard(str(tmp_path))


class TestSpawnTensorboard:
  def test_spawn_args_and_url(self, tmp_path, monkeypatch):
    from tensorflowonspark_tpu import node
    fake = tmp_path / "tensorboard"
    fake.write_text("# fake")
    monkeypatch.setenv("TENSORBOARD_PORT", "23456")
    monkeypatch.setattr(node, "_find_tensorboard", lambda: str(fake))
    calls = {}

    class _Proc:
      pid = 4242

    monkeypatch.setattr(
        node.subprocess, "Popen",
        lambda args, **kw: calls.setdefault("args", args) and _Proc()
        or _Proc())
    info = node._spawn_tensorboard(str(tmp_path / "logs"))
    assert info["pid"] == 4242
    assert info["url"].startswith("http://") and info["url"].endswith(":23456")
    args = calls["args"]
    assert args[0] == sys.executable and args[1] == str(fake)
    assert "--logdir" in args and str(tmp_path / "logs") in args
    assert "--port" in args and "23456" in args

  def test_returns_none_when_not_found(self, monkeypatch):
    from tensorflowonspark_tpu import node
    monkeypatch.setattr(node, "_find_tensorboard", lambda: False)
    assert node._spawn_tensorboard("/tmp/logs") is None


class TestInferenceCLISubprocess:
  def test_python_dash_m_invocation(self, tmp_path):
    """The documented `python -m tensorflowonspark_tpu.inference_cli`
    entry point, as a real subprocess."""
    from tensorflowonspark_tpu import pipeline
    from tensorflowonspark_tpu.data import dfutil
    from tensorflowonspark_tpu.data.schema import parse_schema

    def predict_fn(params, batch):
      return {"y": np.asarray(batch["x"], "float32") * params["m"]}

    export_dir = str(tmp_path / "model")
    pipeline.export_bundle({"m": np.float32(10.0)}, predict_fn, export_dir)
    dfutil.save_as_tfrecords([[(1.5,), (2.5,)]],
                             parse_schema("struct<v:float>"),
                             str(tmp_path / "data"))

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    out_path = str(tmp_path / "preds.jsonl")
    proc = subprocess.run(
        [sys.executable, "-m", "tensorflowonspark_tpu.inference_cli",
         "--export_dir", export_dir,
         "--input", str(tmp_path / "data"),
         "--schema_hint", "struct<v:float>",
         "--input_mapping", json.dumps({"v": "x"}),
         "--output_mapping", json.dumps({"y": "pred"}),
         "--output", out_path],
        env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        capture_output=True, text=True, timeout=180)
    assert proc.returncode == 0, proc.stderr[-2000:]
    preds = [json.loads(l)["pred"] for l in open(out_path)]
    assert preds == [15.0, 25.0]

  def test_mapping_free_cli_uses_bundle_signature(self, tmp_path):
    """Without --output_mapping the CLI derives output columns from the
    signature recorded at export (transformSchema parity,
    reference TFModel.scala:294-311)."""
    from tensorflowonspark_tpu import pipeline
    from tensorflowonspark_tpu.data import dfutil
    from tensorflowonspark_tpu.data.schema import parse_schema

    def predict_fn(params, batch):
      x = np.asarray(batch["v"], "float32")
      return {"doubled": x * params["k"], "negated": -x}

    export_dir = str(tmp_path / "model")
    pipeline.export_bundle(
        {"k": np.float32(2.0)}, predict_fn, export_dir,
        example_batch={"v": np.zeros((1,), "float32")})
    dfutil.save_as_tfrecords([[(3.0,), (4.0,)]],
                             parse_schema("struct<v:float>"),
                             str(tmp_path / "data"))

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    out_path = str(tmp_path / "preds.jsonl")
    proc = subprocess.run(
        [sys.executable, "-m", "tensorflowonspark_tpu.inference_cli",
         "--export_dir", export_dir,
         "--input", str(tmp_path / "data"),
         "--schema_hint", "struct<v:float>",
         "--output", out_path],
        env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        capture_output=True, text=True, timeout=180)
    assert proc.returncode == 0, proc.stderr[-2000:]
    rows = [json.loads(l) for l in open(out_path)]
    assert rows == [{"doubled": 6.0, "negated": -3.0},
                    {"doubled": 8.0, "negated": -4.0}]


class TestCompatRoundtrip:
  def test_export_import_model(self, tmp_path):
    import jax.numpy as jnp
    from tensorflowonspark_tpu.utils import compat

    state = {"w": jnp.arange(6.0).reshape(2, 3), "b": jnp.asarray(1.5)}
    target = compat.export_model(state, str(tmp_path / "exp"),
                                 is_chief=True)
    assert target == str(tmp_path / "exp")
    restored = compat.import_model(target)
    np.testing.assert_allclose(np.asarray(restored["w"]),
                               np.arange(6.0).reshape(2, 3))

  def test_non_chief_writes_elsewhere(self, tmp_path):
    import jax.numpy as jnp
    from tensorflowonspark_tpu.utils import compat

    target = compat.export_model({"w": jnp.zeros(2)},
                                 str(tmp_path / "exp2"), is_chief=False)
    try:
      assert target != str(tmp_path / "exp2")
      assert not os.path.exists(str(tmp_path / "exp2"))
    finally:
      import shutil
      shutil.rmtree(target, ignore_errors=True)


class TestRendezvousVerbs:
  def test_qinfo_and_list(self):
    from tensorflowonspark_tpu.control.rendezvous import Client, Server

    s = Server(3)
    addr = s.start()
    try:
      c = Client(addr)
      c.register({"executor_id": 0, "host": "h0"})
      c.register({"executor_id": 2, "host": "h2"})
      count = c._request({"type": "QINFO"})
      assert count["registered"] == 2 and count["required"] == 3
      listed = c.get_reservations()
      assert [m["executor_id"] for m in listed] == [0, 2]
      unknown = c._request({"type": "NOPE"})
      assert unknown["type"] == "ERROR"
      c.close()
    finally:
      s.stop()


class TestSmallSurfaces:
  def test_yield_batch_scalar_rows(self):
    from tensorflowonspark_tpu.pipeline import yield_batch
    batches = list(yield_batch([1, 2, 3, 4, 5], batch_size=2))
    assert batches == [[[1, 2]], [[3, 4]], [[5]]]

  def test_yield_batch_multi_tensor(self):
    from tensorflowonspark_tpu.pipeline import yield_batch
    rows = [(1, "a"), (2, "b"), (3, "c")]
    batches = list(yield_batch(rows, batch_size=2, num_tensors=2))
    assert batches == [[[1, 2], ["a", "b"]], [[3], ["c"]]]

  def test_namespace_rejects_garbage(self):
    from tensorflowonspark_tpu.pipeline import Namespace
    with pytest.raises(TypeError):
      Namespace(42)

  def test_batched_custom_collate(self):
    from tensorflowonspark_tpu.data import readers
    got = list(readers.batched([1, 2, 3, 4], 2,
                               collate=lambda rows: sum(rows)))
    assert got == [3, 7]

  def test_datafeed_arrays_without_mapping(self):
    from tensorflowonspark_tpu.control import feedhub
    from tensorflowonspark_tpu.datafeed import DataFeed
    hub = feedhub.start(b"k", ["input", "output", "error"], mode="local")
    try:
      hub.get_queue("input").put_many([1.0, 2.0, None])
      feed = DataFeed(hub)
      arr = feed.next_batch_arrays(5, dtype="float32")
      np.testing.assert_allclose(arr, [1.0, 2.0])
    finally:
      hub.shutdown()

  def test_engine_factory(self):
    from tensorflowonspark_tpu.engine import get_engine
    e = get_engine("local", num_executors=1)
    assert e.num_executors == 1
    e.stop()
    with pytest.raises(ValueError):
      get_engine("nope")


class TestOpsScripts:
  def test_shell_scripts_parse(self):
    """Every ops recipe in scripts/ must at least pass bash -n (they
    cannot run here — no gcloud/Spark — but they must not rot)."""
    import glob
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    scripts = glob.glob(os.path.join(repo, "scripts", "*.sh"))
    assert len(scripts) >= 5, scripts
    for s in scripts:
      res = subprocess.run(["bash", "-n", s], capture_output=True,
                           text=True)
      assert res.returncode == 0, "%s: %s" % (s, res.stderr)


class TestFeedBench:
  @pytest.mark.slow
  def test_smoke_end_to_end(self):
    """The feed-plane benchmark (tools/feed_bench.py) runs its full
    pipeline — feeder subprocess -> hub/ring -> DataFeed -> jitted step —
    and reports a finite overhead for at least the queue transport.

    Marked slow (tier-1 budget audit): duplicate of
    tests/test_tools.py::TestFeedBenchSmoke::test_smoke_runs_end_to_end
    (same `feed_bench.py --smoke` subprocess), which stays tier-1;
    this copy runs via `make test`."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    res = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "feed_bench.py"),
         "--smoke"],
        capture_output=True, text=True, timeout=240, cwd=repo)
    assert res.returncode == 0, res.stderr[-2000:]
    line = json.loads(res.stdout.strip().splitlines()[-1])
    assert line["compute_steps_per_sec"] > 0
    q = line["per_transport"]["queue"]
    assert "fed_steps_per_sec" in q, line
