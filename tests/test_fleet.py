"""ServingFleet tests: the driver-side replica router (serving/fleet.py).

The load-bearing claim is the one the engine suite pins per engine,
lifted across replicas: whatever the router does — load-aware dispatch,
overload retries, replica ejection + failover replay, rolling swaps —
every request's output must equal its own single-request
``greedy_generate_kv`` decode, and ``stream()`` consumers must see each
position exactly once across the replica hop. Replica death is driven
deterministically via ``TOS_CHAOS_FLEET`` (``make fleet-chaos``).
"""

import pickle
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tensorflowonspark_tpu.models import transformer as tfm
from tensorflowonspark_tpu.serving import (
    DeadlineExceeded, RequestCancelled, ServingEngine, ServingFleet,
    ServingOverloaded)
from tensorflowonspark_tpu.serving import fleet as fleet_mod
from tensorflowonspark_tpu.utils import chaos

EOS = 7
PAD = 0


def _tiny(max_seq_len=48, **kw):
  return tfm.TransformerConfig(vocab_size=64, num_layers=2, num_heads=2,
                               d_model=32, d_ff=64,
                               max_seq_len=max_seq_len, remat=False,
                               dtype=jnp.float32, **kw)


@pytest.fixture(scope="module")
def tiny_state():
  cfg = _tiny()
  return cfg, tfm.create_state(jax.random.PRNGKey(0), cfg, seq_len=16)


def _reference(params, cfg, prompt, budget, eos_id=EOS):
  """Single-request decode truncated at its stop — the parity oracle."""
  out = np.asarray(tfm.greedy_generate_kv(
      params, cfg, jnp.asarray(prompt)[None], budget, eos_id=eos_id,
      pad_id=PAD))[0]
  gen = out[len(prompt):]
  stops = np.where(gen == eos_id)[0]
  stop = (int(stops[0]) + 1) if len(stops) else budget
  return np.concatenate([prompt, gen[:stop]])


def _factory(tiny_state, **kw):
  cfg, state = tiny_state
  kw.setdefault("num_slots", 2)
  kw.setdefault("horizon", 2)
  return lambda: ServingEngine(state.params, cfg, eos_id=EOS, pad_id=PAD,
                               **kw)


def _workload(seed, n=8, plens=(3, 5, 7), budgets=(4, 8)):
  rng = np.random.RandomState(seed)
  return [(rng.randint(1, 64, (int(rng.choice(plens)),)).astype(np.int32),
           int(rng.choice(budgets))) for _ in range(n)]


class TestFleetRouting:
  def test_mixed_workload_parity_across_replicas(self, tiny_state):
    """Requests spread over replicas and every output is bit-identical
    to its single-request decode — replicas are interchangeable. Rides
    the same run (PR 14): submit is per-request, so the batch read goes
    through generate(detailed=True) and pins the fleet timing ledger
    (trace id, TTFT, zero failovers, one attempt) on every request."""
    cfg, state = tiny_state
    with ServingFleet(_factory(tiny_state), num_replicas=2) as fl:
      work = _workload(3, n=10)
      outs = fl.generate([p for p, _ in work],
                         max_new_tokens=max(b for _, b in work),
                         timeout=120, detailed=True)
      stats = dict(fl.stats)
      # both replicas took traffic (10 requests over 2×2 slots must
      # overflow one replica's backlog score)
      dispatches = [r.dispatches for r in fl._replicas.values()]
    budget = max(b for _, b in work)
    for (p, _), o in zip(work, outs):
      np.testing.assert_array_equal(
          o["tokens"], _reference(state.params, cfg, p, budget))
      t = o["timing"]
      assert t["trace_id"] == o["trace_id"]
      assert t["failovers"] == 0 and len(t["attempts"]) == 1
      assert t["first_token"] is not None
      assert t["ttft"] is not None and t["e2e"] >= t["ttft"]
    assert stats["completed"] == 10 and stats["shed"] == 0
    assert all(d > 0 for d in dispatches)

  def test_dispatch_prefers_less_loaded_replica(self, tiny_state):
    """Load-aware routing: with one replica's queue pre-loaded, a new
    request goes to the idle one (backlog-clear-time score)."""
    with ServingFleet(_factory(tiny_state), num_replicas=2) as fl:
      reps = fl._dispatch_order()
      busy = reps[0]
      # park backlog on one replica's queue directly (below the router)
      for p, b in _workload(5, n=6, budgets=(16,)):
        busy.engine.submit(p, max_new_tokens=b)
      idle = [r for r in fl._replicas.values() if r is not busy][0]
      order = fl._dispatch_order()
      assert order[0] is idle
      frid = fl.submit(np.asarray([1, 2, 3], np.int32), max_new_tokens=4)
      assert fl.request(frid).cur_replica == idle.rid
      fl.result(frid, timeout=120)

  def test_generate_and_stream_roundtrip(self, tiny_state):
    cfg, state = tiny_state
    with ServingFleet(_factory(tiny_state), num_replicas=2) as fl:
      p = np.asarray([2, 9, 4], np.int32)
      frid = fl.submit(p, max_new_tokens=6)
      handle = fl.request(frid)
      toks = list(fl.stream(frid, timeout=120))
      ref = _reference(state.params, cfg, p, 6)
      assert toks == [int(t) for t in ref[len(p):]]
      # the consumer records the verdict itself — it must not race the
      # monitor sweep: done set, output recorded, completion counted
      assert handle.done.is_set() and handle.error is None
      np.testing.assert_array_equal(handle.output, ref)
      assert fl.stats["completed"] == 1
      outs = fl.generate([p, p[:2]], max_new_tokens=5, timeout=120)
      np.testing.assert_array_equal(
          outs[0], _reference(state.params, cfg, p, 5))

  def test_env_knobs_register_and_apply(self, tiny_state, monkeypatch):
    monkeypatch.setenv(fleet_mod.ENV_FLEET_REPLICAS, "3")
    monkeypatch.setenv(fleet_mod.ENV_FLEET_MAX_FAILOVERS, "7")
    monkeypatch.setenv(fleet_mod.ENV_FLEET_PROBE_FAILS, "5")
    monkeypatch.setenv(fleet_mod.ENV_FLEET_ADMIT_TIMEOUT, "11.5")
    monkeypatch.setenv(fleet_mod.ENV_FLEET_POLL, "0.02")
    fl = ServingFleet(_factory(tiny_state))
    assert fl.num_replicas == 3
    assert fl.max_failovers == 7
    assert fl.probe_fails == 5
    assert fl.admit_timeout == 11.5
    assert fl._poll == 0.02
    # explicit arguments beat the env knobs (the num_slots rule)
    fl2 = ServingFleet(_factory(tiny_state), num_replicas=1,
                       max_failovers=2)
    assert fl2.num_replicas == 1 and fl2.max_failovers == 2


class TestFleetAdmission:
  def test_retry_then_admit_when_backlog_clears(self, tiny_state):
    """All replicas overloaded → submit retries with backoff (honoring
    retry_after) inside the admission window and lands once capacity
    frees — the client sees one slow submit, not a rejection."""
    cfg, state = tiny_state
    fac = _factory(tiny_state, num_slots=1, max_queue=1)
    with ServingFleet(fac, num_replicas=2, admit_timeout=60.0) as fl:
      work = _workload(11, n=8, budgets=(6,))
      frids = [fl.submit(p, max_new_tokens=b) for p, b in work]
      outs = [fl.result(fr, timeout=120) for fr in frids]
      stats = dict(fl.stats)
    assert stats["retries"] >= 1          # at least one submit waited
    assert stats["shed"] == 0
    for (p, b), out in zip(work, outs):
      np.testing.assert_array_equal(
          out, _reference(state.params, cfg, p, b))

  def test_admit_deadline_bounds_retries(self, tiny_state):
    """When the backlog can't clear inside the fleet admission window,
    submit re-raises a structured fleet-level ServingOverloaded with a
    retry_after hint instead of blocking forever."""
    fac = _factory(tiny_state, num_slots=1, max_queue=1)
    fl = ServingFleet(fac, num_replicas=2, admit_timeout=0.3)
    # engines never started: queues accept one request each, then
    # every replica rejects and nothing ever drains
    for rep in fl._replicas.values():
      rep.engine.submit(np.asarray([1, 2], np.int32), max_new_tokens=4)
    t0 = time.monotonic()
    with pytest.raises(ServingOverloaded) as ei:
      fl.submit(np.asarray([5, 6], np.int32), max_new_tokens=4)
    assert time.monotonic() - t0 < 5.0
    assert ei.value.retry_after is not None
    assert fl.stats["rejected"] == 1

  def test_ttl_bounds_retries_below_admit_timeout(self, tiny_state):
    """A request's own TTL is the retry bound when tighter than the
    fleet window — retries never outlive the request, and TTL expiry
    mid-retry surfaces as the structured DeadlineExceeded verdict (the
    request died of old age, not of backpressure)."""
    fac = _factory(tiny_state, num_slots=1, max_queue=1)
    fl = ServingFleet(fac, num_replicas=1, admit_timeout=60.0)
    fl._replicas[0].engine.submit(np.asarray([1, 2], np.int32),
                                  max_new_tokens=4)
    t0 = time.monotonic()
    with pytest.raises((DeadlineExceeded, ServingOverloaded)):
      fl.submit(np.asarray([3], np.int32), max_new_tokens=4, ttl=0.3)
    assert time.monotonic() - t0 < 5.0

  def test_drain_closes_admission_and_finishes_work(self, tiny_state):
    cfg, state = tiny_state
    fl = ServingFleet(_factory(tiny_state), num_replicas=2).start()
    work = _workload(13, n=6)
    frids = [fl.submit(p, max_new_tokens=b) for p, b in work]
    handles = [fl.request(fr) for fr in frids]
    assert fl.drain(timeout=120)
    for (p, b), h in zip(work, handles):
      assert h.error is None
      np.testing.assert_array_equal(
          h.output, _reference(state.params, cfg, p, b))
    with pytest.raises(ServingOverloaded) as ei:
      fl.submit(np.asarray([1], np.int32), max_new_tokens=2)
    assert ei.value.draining
    # a usable hint, never None (the engine draining-rejection rule)
    assert ei.value.retry_after is not None and ei.value.retry_after > 0

  def test_cancel_inflight_and_pending(self, tiny_state):
    with ServingFleet(_factory(tiny_state), num_replicas=1) as fl:
      frid = fl.submit(np.asarray([4, 2, 5], np.int32),
                       max_new_tokens=32)
      assert fl.cancel(frid, timeout=60)
      with pytest.raises(RequestCancelled):
        fl.result(frid, timeout=10)

  def test_dead_on_arrival_deadline(self, tiny_state):
    with ServingFleet(_factory(tiny_state), num_replicas=1) as fl:
      with pytest.raises(DeadlineExceeded):
        fl.submit(np.asarray([1, 2], np.int32), max_new_tokens=4,
                  deadline=time.monotonic() - 1.0)


class TestFleetHealth:
  def test_probe_failures_eject_and_fail_over(self, tiny_state):
    """A replica that stops answering its health probe (the HEALTH-wire
    analogue) is ejected after ``probe_fails`` consecutive misses and
    its accepted work replays on a live replica, bit-identical."""
    cfg, state = tiny_state
    sick = {"rid": None}

    def probe(rep):
      return rep.rid != sick["rid"]

    with ServingFleet(_factory(tiny_state), num_replicas=2,
                      probe_fails=2, poll_interval=0.01,
                      health_probe=probe) as fl:
      work = _workload(17, n=6, budgets=(32,))
      frids = [fl.submit(p, max_new_tokens=b) for p, b in work]
      victim = fl.request(frids[0]).cur_replica
      sick["rid"] = victim
      outs = [fl.result(fr, timeout=120) for fr in frids]
      stats = dict(fl.stats)
      states = fl.replica_states()
      events = [e["event"] for e in fl.events]
    assert states[victim] == fleet_mod.EJECTED
    assert stats["ejections"] == 1 and stats["shed"] == 0
    assert stats["failovers"] >= 1
    assert "eject" in events and "failover" in events
    for (p, b), out in zip(work, outs):
      np.testing.assert_array_equal(
          out, _reference(state.params, cfg, p, b))

  def test_all_replicas_dead_fails_fast(self, tiny_state):
    fl = ServingFleet(_factory(tiny_state), num_replicas=1,
                      poll_interval=0.02).start()
    frid = fl.submit(np.asarray([3, 1], np.int32), max_new_tokens=32)
    fl._kill_replica(fl._replicas[0], RuntimeError("boom"))
    with pytest.raises(RuntimeError):
      fl.result(frid, timeout=30)
    assert not fl.alive
    # ejection released the dead engine's device state: kill/_die leave
    # the slab allocated, so _eject must stop() even a dead engine or a
    # degraded fleet pins one slab's HBM per ejection
    assert fl._replicas[0].engine._slabs is None
    with pytest.raises(RuntimeError):
      fl.submit(np.asarray([1], np.int32), max_new_tokens=2)
    fl.stop()

  def test_failover_budget_sheds_after_max(self, tiny_state):
    """A request that loses more than max_failovers replicas is failed
    (the fleet-level poison analogue), visibly — shed counts, waiter
    gets the root cause chain."""
    fl = ServingFleet(_factory(tiny_state), num_replicas=1,
                      max_failovers=0, poll_interval=0.02).start()
    frid = fl.submit(np.asarray([2, 2, 2], np.int32), max_new_tokens=32)
    fl._kill_replica(fl._replicas[0], RuntimeError("boom"))
    with pytest.raises(RuntimeError):
      fl.result(frid, timeout=30)
    assert fl.stats["shed"] == 1
    fl.stop()


class TestRollingSwap:
  def test_swap_mid_flight_sheds_nothing(self, tiny_state):
    """The zero-shed contract fleet-wide: every replica drained and
    replaced while requests are in flight; every accepted request
    completes bit-identical; new engines serve follow-up traffic."""
    cfg, state = tiny_state
    with ServingFleet(_factory(tiny_state), num_replicas=2) as fl:
      work = _workload(23, n=8, budgets=(8, 16))
      frids = [fl.submit(p, max_new_tokens=b) for p, b in work]
      rep = fl.rolling_swap(timeout=120.0)
      outs = [fl.result(fr, timeout=120) for fr in frids]
      assert rep["swapped"] == 2
      assert all(r["drained"] for r in rep["replicas"])
      assert fl.stats["swaps"] == 2 and fl.stats["shed"] == 0
      gens = [r.generation for r in fl._replicas.values()]
      assert gens == [1, 1]
      for (p, b), out in zip(work, outs):
        np.testing.assert_array_equal(
            out, _reference(state.params, cfg, p, b))
      # the swapped-in engines take traffic
      p = np.asarray([9, 9, 1], np.int32)
      np.testing.assert_array_equal(
          fl.result(fl.submit(p, max_new_tokens=4), timeout=120),
          _reference(state.params, cfg, p, 4))

  def test_swap_factory_reparams_the_fleet(self, tiny_state):
    """rolling_swap(engine_factory=...) swaps every replica to engines
    built by the NEW factory — the param-swap path — and keeps it for
    future rebuilds."""
    cfg, state = tiny_state
    built = []

    def new_factory():
      eng = ServingEngine(state.params, cfg, num_slots=2, eos_id=EOS,
                          pad_id=PAD, horizon=2)
      built.append(eng)
      return eng

    with ServingFleet(_factory(tiny_state), num_replicas=2) as fl:
      fl.rolling_swap(timeout=60.0, engine_factory=new_factory)
      assert len(built) == 2
      assert [r.engine for r in fl._replicas.values()] == built
      assert fl._factory is new_factory


class TestFleetChaos:
  """TOS_CHAOS_FLEET-driven proofs (make fleet-chaos): replica death is
  injected deterministically at dispatch granularity, never simulated by
  hand. Chaos counters are per-process — every test resets them."""

  pytestmark = pytest.mark.chaos

  @pytest.fixture(autouse=True)
  def _fresh_chaos(self, monkeypatch):
    chaos.reset()
    yield
    monkeypatch.delenv(chaos.ENV_FLEET, raising=False)
    chaos.reset()

  def test_replica_kill_mid_decode_fails_over_bit_identical(
      self, tiny_state, monkeypatch):
    """THE acceptance pin: N=3 replicas, one killed mid-flight at a
    deterministic dispatch while another rolling-swaps — zero accepted
    requests shed, every completion bit-identical to its reference
    decode, and the ejection/failover visible as structured events."""
    cfg, state = tiny_state
    monkeypatch.setenv(chaos.ENV_FLEET, "dispatch@1#2:kill")
    with ServingFleet(_factory(tiny_state), num_replicas=3,
                      poll_interval=0.02) as fl:
      work = _workload(31, n=9, budgets=(8, 16))
      frids = [fl.submit(p, max_new_tokens=b) for p, b in work]
      swap = fl.rolling_swap(timeout=120.0)   # mid-flight, post-kill
      outs = [fl.result(fr, timeout=120) for fr in frids]
      stats = dict(fl.stats)
      states = fl.replica_states()
      events = list(fl.events)
    assert states[1] == fleet_mod.EJECTED
    assert stats["ejections"] == 1
    assert stats["failovers"] >= 1 and stats["replays"] >= 1
    assert stats["shed"] == 0
    assert stats["replay_mismatches"] == 0
    # the dead replica is skipped, the live ones swap
    assert swap["swapped"] == 2
    kinds = [e["event"] for e in events]
    assert "eject" in kinds and "failover" in kinds \
        and "swap_done" in kinds
    eject = next(e for e in events if e["event"] == "eject")
    assert eject["replica"] == 1 and "InjectedFault" in eject["cause"]
    for (p, b), out in zip(work, outs):
      np.testing.assert_array_equal(
          out, _reference(state.params, cfg, p, b))

  def test_stream_positions_exactly_once_across_replica_hop(
      self, tiny_state, monkeypatch):
    """A stream() consumer sees each position exactly once even when the
    request hops replicas mid-stream: the fleet suppresses (and
    verifies) the already-delivered prefix of the replayed decode.
    Rides the same run (PR 14, one kill cycle is expensive): the hop is
    ONE trace — every span both replicas emitted (dispatch, queue,
    prefill, decode, stream relay) carries the fleet-minted trace id."""
    from tensorflowonspark_tpu.obs import spans as spans_mod
    rec = spans_mod.activate()
    cfg, state = tiny_state
    # replica 0's 2nd dispatch CONSULT: the streamed request below is
    # its 1st (an empty fleet dispatches in rid order); the consult that
    # trips the kill is forced mid-stream, with tokens already delivered
    monkeypatch.setenv(chaos.ENV_FLEET, "dispatch@0#2:kill")
    fac = _factory(tiny_state, num_slots=1)
    try:
      with ServingFleet(fac, num_replicas=2, poll_interval=0.02) as fl:
        p = np.asarray([5, 3, 8, 2], np.int32)
        frid = fl.submit(p, max_new_tokens=24)
        trace = fl._requests[frid].trace_id
        got, kicked = [], False
        for tok in fl.stream(frid, timeout=120):
          got.append(tok)
          if not kicked and len(got) == 2:
            kicked = True
            # occupy replica 1 (the idle one scores first), then force a
            # round that reaches replica 0 again — both busy, so the tie
            # breaks to rid 0, whose 2nd consult kills it mid-stream
            fl.submit(np.asarray([1, 1], np.int32), max_new_tokens=4)
            fl.submit(np.asarray([2, 2], np.int32), max_new_tokens=4)
        stats = dict(fl.stats)
        states = fl.replica_states()
    finally:
      spans_mod.deactivate()
    ref = _reference(state.params, cfg, p, 24)
    assert got == [int(t) for t in ref[len(p):]]
    assert states[0] == fleet_mod.EJECTED
    assert stats["failovers"] >= 1
    assert stats["replay_mismatches"] == 0
    recs = [r for r in rec.drain() if r.get("trace") == trace]
    names = {r["name"] for r in recs}
    dispatches = [r for r in recs if r["name"] == "fleet.dispatch"]
    assert len(dispatches) == 2                      # the hop
    assert {d["attrs"]["replica"] for d in dispatches} == {0, 1}
    assert {"serve.queue", "serve.prefill", "serve.decode.slot",
            "fleet.stream"} <= names
    assert sum(1 for r in recs if r["name"] == "serve.prefill") == 2
    stream_span = next(r for r in recs if r["name"] == "fleet.stream")
    assert stream_span["attrs"]["failovers"] >= 1
    assert stream_span["attrs"]["tokens"] == len(got)

  def test_stall_spec_delays_dispatch_only(self, tiny_state,
                                           monkeypatch):
    cfg, state = tiny_state
    monkeypatch.setenv(chaos.ENV_FLEET, "dispatch#1:stall:0.2")
    with ServingFleet(_factory(tiny_state), num_replicas=1) as fl:
      t0 = time.monotonic()
      frid = fl.submit(np.asarray([6, 4], np.int32), max_new_tokens=4)
      assert time.monotonic() - t0 >= 0.2
      np.testing.assert_array_equal(
          fl.result(frid, timeout=120),
          _reference(state.params, cfg, np.asarray([6, 4], np.int32), 4))

  def test_malformed_fleet_spec_raises(self, monkeypatch):
    monkeypatch.setenv(chaos.ENV_FLEET, "dispatch@1#2:raise")
    with pytest.raises(ValueError, match="fleet spec"):
      chaos.check_config()


class TestFleetExceptionPickle:
  """The four structured serving exceptions must round-trip pickle with
  their fields intact (manager proxies / any process boundary a fleet
  crosses) — the feedhub.QueueFull bug class, pinned per exception."""

  def test_serving_overloaded_roundtrip(self):
    e = ServingOverloaded("queue full", queue_depth=7, queued_tokens=123,
                          retry_after=1.5, draining=True)
    e2 = pickle.loads(pickle.dumps(e))
    assert type(e2) is ServingOverloaded
    assert str(e2) == "queue full"
    assert e2.queue_depth == 7 and e2.queued_tokens == 123
    assert e2.retry_after == 1.5 and e2.draining is True

  def test_deadline_exceeded_roundtrip(self):
    e = pickle.loads(pickle.dumps(DeadlineExceeded("too late")))
    assert type(e) is DeadlineExceeded and str(e) == "too late"

  def test_request_cancelled_roundtrip(self):
    e = pickle.loads(pickle.dumps(RequestCancelled("gone")))
    assert type(e) is RequestCancelled and str(e) == "gone"

  def test_poisoned_request_roundtrip(self):
    from tensorflowonspark_tpu.serving import PoisonedRequest
    e = pickle.loads(pickle.dumps(PoisonedRequest("bad req")))
    assert type(e) is PoisonedRequest and str(e) == "bad req"



class TestAvailabilityAccounting:
  """The availability SLO's client-boundary counters (PR 14): every
  submit OUTCOME pairs submitted with its verdict — a dead fleet counts
  submitted+rejected (a total outage must burn), a malformed prompt
  counts NEITHER (caller bugs stay out of both sides of the ratio)."""

  def test_dead_fleet_counts_submitted_and_rejected(self, tiny_state):
    fl = ServingFleet(_factory(tiny_state), num_replicas=1).start()
    fl.stop()
    # stopped fleet = the total-outage shape: no live replica will ever
    # take this request — client-visible unavailability
    with pytest.raises(RuntimeError):
      fl.submit(np.asarray([1, 2], np.int32), max_new_tokens=4)
    assert fl.stats["submitted"] == 1
    assert fl.stats["rejected"] == 1

  def test_malformed_prompt_counts_neither(self, tiny_state):
    with ServingFleet(_factory(tiny_state), num_replicas=1) as fl:
      with pytest.raises(ValueError, match="at least one token"):
        fl.submit(np.asarray([], np.int32), max_new_tokens=4)
      assert fl.stats["submitted"] == 0
      assert fl.stats["rejected"] == 0

  def test_served_request_counts_submitted_only(self, tiny_state):
    cfg, state = tiny_state
    with ServingFleet(_factory(tiny_state), num_replicas=1) as fl:
      p = np.asarray([4, 9], np.int32)
      frid = fl.submit(p, max_new_tokens=4)
      np.testing.assert_array_equal(
          fl.result(frid, timeout=120),
          _reference(state.params, cfg, p, 4))
      assert fl.stats["submitted"] == 1
      assert fl.stats["rejected"] == 0
