"""LocalEngine tests: Spark-parity scheduling semantics with real processes.

Covers the engine contract the cluster layer depends on: one task per
executor for run_on_executors, busy executors excluded from shared
scheduling, error propagation with tracebacks, barrier gang semantics
(parity: reference tests/test_TFParallel.py:16-51).
"""

import os
import time

import pytest

from tensorflowonspark_tpu.engine import LocalEngine


def _slot_and_pid(it):
  consumed = list(it)
  return (consumed, os.environ["TOS_EXECUTOR_SLOT"], os.getpid())


def _square_sum(it):
  return [sum(x * x for x in it)]


def _boom(it):
  list(it)
  raise ValueError("deliberate failure for testing")


def _sleep_then_slot(it):
  list(it)
  time.sleep(1.0)
  return os.environ["TOS_EXECUTOR_SLOT"]


def _barrier_fn(it, ctx):
  task_id = list(it)[0]
  infos = ctx.get_task_infos()
  ctx.barrier()
  return (task_id, len(infos))


class TestLocalEngine:
  @pytest.fixture(scope="class")
  def engine(self):
    e = LocalEngine(num_executors=2)
    yield e
    e.stop()

  def test_run_on_executors_distinct_processes(self, engine):
    results = engine.run_on_executors(_slot_and_pid).wait(timeout=30)
    slots = sorted(r[1] for r in results)
    pids = {r[2] for r in results}
    assert slots == ["0", "1"]
    assert len(pids) == 2            # real separate processes
    assert os.getpid() not in pids
    assert [r[0] for r in sorted(results)] == [[0], [1]]

  def test_map_partitions_collects(self, engine):
    parts = [[1, 2], [3], [4, 5, 6]]
    got = engine.map_partitions(parts, _square_sum, timeout=30)
    assert sorted(got) == [5, 9, 77]

  def test_error_propagates_with_traceback(self, engine):
    job = engine.foreach_partition([[1], [2]], _boom)
    with pytest.raises(RuntimeError, match="deliberate failure"):
      job.wait(timeout=30)
    assert "ValueError" in job.first_error()

  def test_busy_executor_excluded_from_shared_tasks(self, engine):
    # pin a slow task onto each executor, then queue shared work; shared
    # tasks must wait for a free executor, not interleave
    slow = engine.run_on_executors(_sleep_then_slot, num_tasks=1)
    t0 = time.time()
    got = engine.map_partitions([[1]], _square_sum, timeout=30)
    assert got == [1]
    slow.wait(timeout=30)
    assert time.time() - t0 < 5

  def test_executor_workdirs_isolated(self, engine):
    def write_marker(it):
      i = list(it)[0]
      with open("marker.txt", "w") as f:
        f.write(str(i))
      return os.getcwd()

    dirs = engine.run_on_executors(write_marker).wait(timeout=30)
    assert len(set(dirs)) == 2
    for d in dirs:
      assert os.path.exists(os.path.join(d, "marker.txt"))

  def test_barrier_run(self, engine):
    got = engine.barrier_run(_barrier_fn, num_tasks=2, timeout=60)
    assert sorted(got) == [(0, 2), (1, 2)]

  def test_barrier_oversubscription_raises(self, engine):
    with pytest.raises(ValueError, match="barrier gang"):
      engine.barrier_run(_barrier_fn, num_tasks=5)

  def test_run_on_executors_too_many_tasks_raises(self, engine):
    with pytest.raises(ValueError, match="executors"):
      engine.run_on_executors(_slot_and_pid, num_tasks=3)

  def test_generator_results_materialized(self, engine):
    def gen_fn(it):
      for x in it:
        yield x + 100

    got = engine.map_partitions([[1, 2]], gen_fn, timeout=30)
    assert got == [101, 102]
