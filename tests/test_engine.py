"""Engine contract tests: LocalEngine (real processes) + SparkEngine (stub).

The shared contract class runs against both engines — scheduling results,
per-task error attribution, barrier gang semantics (parity: reference
tests/test_TFParallel.py:16-51). LocalEngine-specific tests cover the
process-isolation behaviors a thread-backed stub cannot exhibit.

SparkEngine runs against tests/pyspark_stub.py (pyspark is not installed in
CI); the stub keeps Spark's slicing/partition-order/barrier semantics.
"""

import os
import sys
import time

import pytest

from tensorflowonspark_tpu.engine import LocalEngine

sys.path.insert(0, os.path.dirname(__file__))


def _slot_and_pid(it):
  consumed = list(it)
  return (consumed, os.environ.get("TOS_EXECUTOR_SLOT", "-"), os.getpid())


def _square_sum(it):
  return [sum(x * x for x in it)]


def _boom(it):
  list(it)
  raise ValueError("deliberate failure for testing")


def _boom_on_two(it):
  rows = list(it)
  if 2 in rows:
    raise ValueError("deliberate failure on partition with 2")
  return rows


def _sleep_then_slot(it):
  list(it)
  time.sleep(1.0)
  return os.environ["TOS_EXECUTOR_SLOT"]


def _barrier_fn(it, ctx):
  task_id = list(it)[0]
  infos = ctx.get_task_infos()
  ctx.barrier()
  return (task_id, len(infos))


@pytest.fixture(scope="module")
def local_engine():
  e = LocalEngine(num_executors=2)
  yield e
  e.stop()


@pytest.fixture(scope="module")
def spark_engine():
  import pyspark_stub
  sys.modules["pyspark"] = pyspark_stub
  from tensorflowonspark_tpu.engine.spark import SparkEngine
  e = SparkEngine(sc=pyspark_stub.SparkContext(num_executors=2))
  yield e
  sys.modules.pop("pyspark", None)


@pytest.fixture(params=["local", "spark"])
def engine(request):
  return request.getfixturevalue(request.param + "_engine")


class TestEngineContract:
  """Runs against BOTH engines."""

  def test_run_on_executors_routes_payloads(self, engine):
    results = engine.run_on_executors(_slot_and_pid, num_tasks=2).wait(
        timeout=30)
    assert sorted(r[0] for r in results) == [[0], [1]]

  def test_run_on_executors_custom_payloads(self, engine):
    results = engine.run_on_executors(
        _slot_and_pid, num_tasks=2, task_payloads=["a", "b"]).wait(timeout=30)
    assert sorted(r[0] for r in results) == [["a"], ["b"]]

  def test_map_partitions_collects(self, engine):
    parts = [[1, 2], [3], [4, 5, 6]]
    got = engine.map_partitions(parts, _square_sum, timeout=30)
    assert sorted(got) == [5, 9, 77]

  def test_map_partitions_preserves_boundaries(self, engine):
    # one result per partition proves boundaries were not re-sliced
    parts = [[1, 2], [3], [4, 5, 6]]
    got = engine.map_partitions(parts, lambda it: [len(list(it))], timeout=30)
    assert sorted(got) == [1, 2, 3]

  def test_generator_results_materialized(self, engine):
    def gen_fn(it):
      for x in it:
        yield x + 100

    got = engine.map_partitions([[1, 2]], gen_fn, timeout=30)
    assert got == [101, 102]

  def test_error_propagates_with_traceback(self, engine):
    job = engine.foreach_partition([[1], [2]], _boom)
    with pytest.raises(RuntimeError, match="deliberate failure"):
      job.wait(timeout=30)
    assert "ValueError" in job.first_error()

  def test_error_attributed_to_failing_task_only(self, engine):
    job = engine.foreach_partition([[1], [2]], _boom_on_two)
    with pytest.raises(RuntimeError, match="partition with 2"):
      job.wait(timeout=30)
    errors = [e for e in job.errors if e is not None]
    assert len(errors) == 1, "only the failing task should carry an error"
    assert "partition with 2" in errors[0]

  def test_barrier_run(self, engine):
    got = engine.barrier_run(_barrier_fn, num_tasks=2, timeout=60)
    assert sorted(got) == [(0, 2), (1, 2)]

  def test_barrier_oversubscription_raises(self, engine):
    with pytest.raises(ValueError, match="barrier gang"):
      engine.barrier_run(_barrier_fn, num_tasks=5)

  def test_default_fs(self, engine):
    assert engine.default_fs() == "file://"


class TestLazyMapPartitions:
  def test_local_lazy_streams_bounded(self, local_engine):
    """The driver must never hold the full result set: with a window of 2
    executors, at most window+1 partitions may have been pulled from the
    source by the time the first row is consumed."""
    pulled = []

    def parts():
      for p in range(20):
        pulled.append(p)
        yield [p * 600 + i for i in range(600)]   # 12,000 rows total

    lazy = local_engine.map_partitions_lazy(parts(),
                                            lambda it: [x * 2 for x in it])
    first = next(lazy)
    assert first == 0
    assert len(pulled) <= local_engine.num_executors + 2, \
        "lazy path pre-pulled the whole dataset"
    rest = list(lazy)
    assert len(rest) == 12000 - 1
    assert rest[-1] == (20 * 600 - 1) * 2

  def test_local_lazy_propagates_errors(self, local_engine):
    lazy = local_engine.map_partitions_lazy([[1], [2]], _boom_on_two)
    with pytest.raises(RuntimeError, match="partition with 2"):
      list(lazy)

  def test_spark_lazy_returns_uncollected_rdd(self, spark_engine):
    lazy = spark_engine.map_partitions_lazy([[1, 2], [3]], _square_sum)
    assert not isinstance(lazy, list)
    assert hasattr(lazy, "mapPartitions"), "expected an RDD-like handle"
    assert sorted(lazy.collect()) == [5, 9]

  def test_spark_lazy_local_iterator(self, spark_engine):
    # the CLI's streaming path: consume the RDD via toLocalIterator
    lazy = spark_engine.map_partitions_lazy([[1, 2], [3]], _square_sum)
    assert sorted(lazy.toLocalIterator()) == [5, 9]


class TestLocalEngine:
  """Process-isolation behaviors only real subprocess executors exhibit."""

  def test_run_on_executors_distinct_processes(self, local_engine):
    results = local_engine.run_on_executors(_slot_and_pid).wait(timeout=30)
    slots = sorted(r[1] for r in results)
    pids = {r[2] for r in results}
    assert slots == ["0", "1"]
    assert len(pids) == 2            # real separate processes
    assert os.getpid() not in pids

  def test_busy_executor_excluded_from_shared_tasks(self, local_engine):
    # pin a slow task onto each executor, then queue shared work; shared
    # tasks must wait for a free executor, not interleave
    slow = local_engine.run_on_executors(_sleep_then_slot, num_tasks=1)
    t0 = time.time()
    got = local_engine.map_partitions([[1]], _square_sum, timeout=30)
    assert got == [1]
    slow.wait(timeout=30)
    assert time.time() - t0 < 5

  def test_executor_workdirs_isolated(self, local_engine):
    def write_marker(it):
      i = list(it)[0]
      with open("marker.txt", "w") as f:
        f.write(str(i))
      return os.getcwd()

    dirs = local_engine.run_on_executors(write_marker).wait(timeout=30)
    assert len(set(dirs)) == 2
    for d in dirs:
      assert os.path.exists(os.path.join(d, "marker.txt"))

  def test_run_on_executors_too_many_tasks_raises(self, local_engine):
    with pytest.raises(ValueError, match="executors"):
      local_engine.run_on_executors(_slot_and_pid, num_tasks=3)

  def test_finished_jobs_evicted(self, local_engine):
    """The engine must not pin every job's results forever — the lazy map
    path's bounded-memory contract depends on eviction."""
    local_engine.map_partitions([[1, 2], [3]], _square_sum, timeout=30)
    deadline = time.time() + 5
    while local_engine._jobs and time.time() < deadline:
      time.sleep(0.05)
    assert not local_engine._jobs


class TestSparkEngineSpecific:
  def test_num_executors_from_conf(self):
    import pyspark_stub
    from tensorflowonspark_tpu.engine.spark import SparkEngine
    sc = pyspark_stub.SparkContext(
        num_executors=8, conf_values={"spark.executor.instances": "3"})
    assert SparkEngine(sc=sc).num_executors == 3

  def test_accepts_existing_rdd(self, spark_engine):
    rdd = spark_engine.sc.parallelize([1, 2, 3, 4], 2)
    got = spark_engine.map_partitions(rdd, _square_sum, timeout=30)
    assert sorted(got) == [5, 25]

  def test_barrier_timeout_enforced(self, spark_engine):
    def _slow_barrier_fn(it, ctx):
      list(it)
      time.sleep(5.0)
      return None

    with pytest.raises(TimeoutError):
      spark_engine.barrier_run(_slow_barrier_fn, num_tasks=2, timeout=0.5)
