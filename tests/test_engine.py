"""Engine contract tests: LocalEngine (real processes) + SparkEngine (stub).

The shared contract class runs against both engines — scheduling results,
per-task error attribution, barrier gang semantics (parity: reference
tests/test_TFParallel.py:16-51). LocalEngine-specific tests cover the
process-isolation behaviors a thread-backed stub cannot exhibit.

SparkEngine runs against tests/pyspark_stub.py (pyspark is not installed in
CI); the stub keeps Spark's slicing/partition-order/barrier semantics.
"""

import os
import sys
import time

import pytest

from tensorflowonspark_tpu.engine import LocalEngine

sys.path.insert(0, os.path.dirname(__file__))


def _slot_and_pid(it):
  consumed = list(it)
  return (consumed, os.environ.get("TOS_EXECUTOR_SLOT", "-"), os.getpid())


def _square_sum(it):
  return [sum(x * x for x in it)]


def _boom(it):
  list(it)
  raise ValueError("deliberate failure for testing")


def _boom_on_two(it):
  rows = list(it)
  if 2 in rows:
    raise ValueError("deliberate failure on partition with 2")
  return rows


def _sleep_then_slot(it):
  list(it)
  time.sleep(1.0)
  return os.environ["TOS_EXECUTOR_SLOT"]


def _barrier_fn(it, ctx):
  task_id = list(it)[0]
  infos = ctx.get_task_infos()
  ctx.barrier()
  return (task_id, len(infos))


@pytest.fixture(scope="module")
def local_engine():
  e = LocalEngine(num_executors=2)
  yield e
  e.stop()


@pytest.fixture(scope="module")
def spark_engine():
  import pyspark_stub
  sys.modules["pyspark"] = pyspark_stub
  from tensorflowonspark_tpu.engine.spark import SparkEngine
  e = SparkEngine(sc=pyspark_stub.SparkContext(num_executors=2))
  yield e
  sys.modules.pop("pyspark", None)


@pytest.fixture(params=["local", "spark"])
def engine(request):
  return request.getfixturevalue(request.param + "_engine")


class TestEngineContract:
  """Runs against BOTH engines."""

  def test_run_on_executors_routes_payloads(self, engine):
    results = engine.run_on_executors(_slot_and_pid, num_tasks=2).wait(
        timeout=30)
    assert sorted(r[0] for r in results) == [[0], [1]]

  def test_run_on_executors_custom_payloads(self, engine):
    results = engine.run_on_executors(
        _slot_and_pid, num_tasks=2, task_payloads=["a", "b"]).wait(timeout=30)
    assert sorted(r[0] for r in results) == [["a"], ["b"]]

  def test_map_partitions_collects(self, engine):
    parts = [[1, 2], [3], [4, 5, 6]]
    got = engine.map_partitions(parts, _square_sum, timeout=30)
    assert sorted(got) == [5, 9, 77]

  def test_map_partitions_preserves_boundaries(self, engine):
    # one result per partition proves boundaries were not re-sliced
    parts = [[1, 2], [3], [4, 5, 6]]
    got = engine.map_partitions(parts, lambda it: [len(list(it))], timeout=30)
    assert sorted(got) == [1, 2, 3]

  def test_generator_results_materialized(self, engine):
    def gen_fn(it):
      for x in it:
        yield x + 100

    got = engine.map_partitions([[1, 2]], gen_fn, timeout=30)
    assert got == [101, 102]

  def test_error_propagates_with_traceback(self, engine):
    job = engine.foreach_partition([[1], [2]], _boom)
    with pytest.raises(RuntimeError, match="deliberate failure"):
      job.wait(timeout=30)
    assert "ValueError" in job.first_error()

  def test_error_attributed_to_failing_task_only(self, engine):
    job = engine.foreach_partition([[1], [2]], _boom_on_two)
    with pytest.raises(RuntimeError, match="partition with 2"):
      job.wait(timeout=30)
    errors = [e for e in job.errors if e is not None]
    assert len(errors) == 1, "only the failing task should carry an error"
    assert "partition with 2" in errors[0]

  def test_barrier_run(self, engine):
    got = engine.barrier_run(_barrier_fn, num_tasks=2, timeout=60)
    assert sorted(got) == [(0, 2), (1, 2)]

  def test_barrier_oversubscription_raises(self, engine):
    with pytest.raises(ValueError, match="barrier gang"):
      engine.barrier_run(_barrier_fn, num_tasks=5)

  def test_default_fs(self, engine):
    assert engine.default_fs() == "file://"


class TestLazyMapPartitions:
  def test_local_lazy_streams_bounded(self, local_engine):
    """The driver must never hold the full result set: with a window of 2
    executors, at most window+1 partitions may have been pulled from the
    source by the time the first row is consumed."""
    pulled = []

    def parts():
      for p in range(20):
        pulled.append(p)
        yield [p * 600 + i for i in range(600)]   # 12,000 rows total

    lazy = local_engine.map_partitions_lazy(parts(),
                                            lambda it: [x * 2 for x in it])
    first = next(lazy)
    assert first == 0
    assert len(pulled) <= local_engine.num_executors + 2, \
        "lazy path pre-pulled the whole dataset"
    rest = list(lazy)
    assert len(rest) == 12000 - 1
    assert rest[-1] == (20 * 600 - 1) * 2

  def test_local_lazy_propagates_errors(self, local_engine):
    lazy = local_engine.map_partitions_lazy([[1], [2]], _boom_on_two)
    with pytest.raises(RuntimeError, match="partition with 2"):
      list(lazy)

  def test_spark_lazy_returns_uncollected_rdd(self, spark_engine):
    lazy = spark_engine.map_partitions_lazy([[1, 2], [3]], _square_sum)
    assert not isinstance(lazy, list)
    assert hasattr(lazy, "mapPartitions"), "expected an RDD-like handle"
    assert sorted(lazy.collect()) == [5, 9]

  def test_spark_lazy_local_iterator(self, spark_engine):
    # the CLI's streaming path: consume the RDD via toLocalIterator
    lazy = spark_engine.map_partitions_lazy([[1, 2], [3]], _square_sum)
    assert sorted(lazy.toLocalIterator()) == [5, 9]


class TestLocalEngine:
  """Process-isolation behaviors only real subprocess executors exhibit."""

  def test_run_on_executors_distinct_processes(self, local_engine):
    results = local_engine.run_on_executors(_slot_and_pid).wait(timeout=30)
    slots = sorted(r[1] for r in results)
    pids = {r[2] for r in results}
    assert slots == ["0", "1"]
    assert len(pids) == 2            # real separate processes
    assert os.getpid() not in pids

  def test_busy_executor_excluded_from_shared_tasks(self, local_engine):
    # pin a slow task onto each executor, then queue shared work; shared
    # tasks must wait for a free executor, not interleave
    slow = local_engine.run_on_executors(_sleep_then_slot, num_tasks=1)
    t0 = time.time()
    got = local_engine.map_partitions([[1]], _square_sum, timeout=30)
    assert got == [1]
    slow.wait(timeout=30)
    assert time.time() - t0 < 5

  def test_executor_workdirs_isolated(self, local_engine):
    def write_marker(it):
      i = list(it)[0]
      with open("marker.txt", "w") as f:
        f.write(str(i))
      return os.getcwd()

    dirs = local_engine.run_on_executors(write_marker).wait(timeout=30)
    assert len(set(dirs)) == 2
    for d in dirs:
      assert os.path.exists(os.path.join(d, "marker.txt"))

  def test_run_on_executors_too_many_tasks_raises(self, local_engine):
    with pytest.raises(ValueError, match="executors"):
      local_engine.run_on_executors(_slot_and_pid, num_tasks=3)

  def test_dead_executor_fails_task_and_respawns(self):
    """SIGKILLing an executor mid-task marks the task with the
    ExecutorLost prefix and respawns the slot; relaunch_task then re-runs
    the task successfully on the fresh process."""
    import signal
    from tensorflowonspark_tpu.engine.base import is_executor_lost

    e = LocalEngine(num_executors=2)
    try:
      victim_pid = e._procs[0].pid
      job = e.run_on_executors(_sleep_then_slot, num_tasks=1)
      time.sleep(0.3)                     # task is mid-sleep on slot 0
      os.kill(victim_pid, signal.SIGKILL)
      with pytest.raises(RuntimeError, match="ExecutorLost"):
        job.wait(timeout=30)
      assert is_executor_lost(job.errors[0])

      # the slot was respawned: relaunching the task succeeds
      e.relaunch_task(job, 0)
      results = job.wait(timeout=30)
      assert results[0] == "0"
      assert job.first_error() is None
      assert e._procs[0].pid != victim_pid
    finally:
      e.stop()

  @pytest.mark.slow
  def test_idle_dead_executor_respawned(self):
    """An executor killed while idle is respawned and keeps serving.

    Marked slow (tier-1 budget audit): ~40 s of monitor-poll waiting on
    a loaded box, and the respawn contract is pinned in tier-1 by the
    stronger test_dead_executor_fails_task_and_respawns (kill MID-task);
    the idle variant runs via `make test`."""
    import signal
    e = LocalEngine(num_executors=2)
    try:
      results = e.run_on_executors(_slot_and_pid).wait(timeout=30)
      old_pids = {r[2] for r in results}
      victim_pid = e._procs[1].pid
      os.kill(victim_pid, signal.SIGKILL)
      deadline = time.time() + 10
      while e._procs[1].pid == victim_pid and time.time() < deadline:
        time.sleep(0.05)            # until the monitor swapped the slot
      results = e.run_on_executors(_slot_and_pid).wait(timeout=30)
      assert sorted(r[1] for r in results) == ["0", "1"]
      assert len({r[2] for r in results} - old_pids) == 1
    finally:
      e.stop()

  def test_relaunch_task_replaces_payload(self, local_engine):
    job = local_engine.run_on_executors(_slot_and_pid, num_tasks=2,
                                        task_payloads=["a", "b"])
    job.wait(timeout=30)
    local_engine.relaunch_task(job, 1, payload={"replacement": True})
    assert not job.done()               # bookkeeping was reset
    results = job.wait(timeout=30)
    assert results[1][0] == [{"replacement": True}]

  def test_finished_jobs_evicted(self, local_engine):
    """The engine must not pin every job's results forever — the lazy map
    path's bounded-memory contract depends on eviction."""
    local_engine.map_partitions([[1, 2], [3]], _square_sum, timeout=30)
    deadline = time.time() + 5
    while local_engine._jobs and time.time() < deadline:
      time.sleep(0.05)
    assert not local_engine._jobs


class TestSparkEngineSpecific:
  def test_num_executors_from_conf(self):
    import pyspark_stub
    from tensorflowonspark_tpu.engine.spark import SparkEngine
    sc = pyspark_stub.SparkContext(
        num_executors=8, conf_values={"spark.executor.instances": "3"})
    assert SparkEngine(sc=sc).num_executors == 3

  def test_accepts_existing_rdd(self, spark_engine):
    rdd = spark_engine.sc.parallelize([1, 2, 3, 4], 2)
    got = spark_engine.map_partitions(rdd, _square_sum, timeout=30)
    assert sorted(got) == [5, 25]

  def test_raw_row_stream_warns_driver_materialization(self, spark_engine,
                                                       caplog):
    """A one-shot stream of RAW-ROW partitions handed to _as_rdd drains
    onto the driver (O(dataset) memory) — that hazard must be a runtime
    warning, not just a code comment (round-4 advice; mirrors the
    save_as_tfrecords warning)."""
    import logging
    with caplog.at_level(logging.WARNING,
                         logger="tensorflowonspark_tpu.engine.spark"):
      got = spark_engine.map_partitions(
          iter([[1, 2], [3, 4]]), _square_sum, timeout=30)
    assert sorted(got) == [5, 25]
    assert any("materialized on the DRIVER" in r.message
               for r in caplog.records)
    # lazy-handle streams ([callable] partitions) and re-iterable lists
    # stay silent — rows are produced executor-side / driver already owns
    caplog.clear()
    with caplog.at_level(logging.WARNING,
                         logger="tensorflowonspark_tpu.engine.spark"):
      spark_engine.map_partitions(
          iter([[lambda: [1, 2]], [lambda: [3, 4]]]),
          lambda it: [sum(1 for _ in it)], timeout=30)
      spark_engine.map_partitions([[1, 2], [3, 4]], _square_sum,
                                  timeout=30)
    assert not caplog.records

  def test_relaunch_task_resubmits_single_task(self, spark_engine):
    """SparkEngine.relaunch_task re-runs one run_on_executors task as a
    fresh single-task job and routes the result into the original slot."""
    job = spark_engine.run_on_executors(_slot_and_pid, num_tasks=2)
    job.wait(timeout=30)
    spark_engine.relaunch_task(job, 0, payload="again")
    results = job.wait(timeout=30)
    assert results[0][0] == ["again"]

  def test_relaunch_unsupported_for_data_jobs(self, spark_engine):
    job = spark_engine.foreach_partition([[1]], _square_sum)
    job.wait(timeout=30)
    with pytest.raises(NotImplementedError):
      spark_engine.relaunch_task(job, 0)

  def test_barrier_timeout_enforced(self, spark_engine):
    def _slow_barrier_fn(it, ctx):
      list(it)
      time.sleep(5.0)
      return None

    with pytest.raises(TimeoutError):
      spark_engine.barrier_run(_slow_barrier_fn, num_tasks=2, timeout=0.5)


class TestSparkTaskScheduling:
  """Spark scheduling semantics in the stub (VERDICT r2 item 6: the stub
  must model task-retry/straggler behavior, since pyspark cannot be
  installed here — see tests/SPARK_VALIDATION.md)."""

  def _sc(self, **conf):
    import pyspark_stub
    return pyspark_stub, pyspark_stub.SparkContext(
        num_executors=2, conf_values=conf)

  def test_flaky_task_succeeds_on_retry(self):
    stub, sc = self._sc()
    fails = {"n": 0}

    def flaky(it):
      rows = list(it)
      ctx = stub.TaskContext.get()
      if ctx.partitionId() == 1 and ctx.attemptNumber() < 2:
        fails["n"] += 1
        raise ValueError("transient")
      return iter([(ctx.partitionId(), ctx.attemptNumber(), sum(rows))])

    out = sc.parallelize([1, 2, 3, 4], 2).mapPartitions(flaky).collect()
    assert fails["n"] == 2
    assert (0, 0, 3) in out          # partition 0 succeeded first try
    assert (1, 2, 7) in out          # partition 1 needed two retries

  def test_permanent_failure_raises_after_max_failures(self):
    stub, sc = self._sc(**{"spark.task.maxFailures": "2"})
    attempts = []

    def doomed(it):
      list(it)
      attempts.append(stub.TaskContext.get().attemptNumber())
      raise ValueError("permanent")

    with pytest.raises(RuntimeError, match="failed 2 times"):
      sc.parallelize([1, 2], 1).mapPartitions(doomed).collect()
    assert attempts == [0, 1]

  def test_barrier_stage_retries_whole_gang(self):
    stub, sc = self._sc()
    runs = {0: 0, 1: 0}

    def gang_fn(it):
      pid = stub.BarrierTaskContext.get().partitionId()
      runs[pid] += 1
      stub.BarrierTaskContext.get().barrier()
      if pid == 0 and runs[0] == 1:
        raise ValueError("first stage attempt dies")
      return iter([pid])

    out = sc.parallelize([0, 1], 2).barrier().mapPartitions(gang_fn) \
        .collect()
    assert sorted(out) == [0, 1]
    # BOTH tasks ran twice: the healthy member was re-run with the failed
    # one (whole-stage resubmission, not per-task retry)
    assert runs == {0: 2, 1: 2}

  def test_speculation_duplicates_side_effects(self):
    stub, sc = self._sc(**{"spark.speculation": "true"})
    effects = []
    lock = __import__("threading").Lock()

    def task(it):
      rows = list(it)
      with lock:
        effects.append(stub.TaskContext.get().partitionId())
      return iter([sum(rows)])

    out = sc.parallelize([1, 2], 2).mapPartitions(task).collect()
    assert sorted(out) == [1, 2]     # results deduplicated...
    assert sorted(effects) == [0, 0, 1, 1]   # ...but side effects are NOT

  def test_engine_duplicate_node_start_is_rejected(self):
    """The framework defense speculation exists to test: two concurrent
    registrations for the same executor — the rendezvous accepts one and
    rejects the live duplicate (parity: TFSparkNode.py:259-265)."""
    from tensorflowonspark_tpu.control import rendezvous

    server = rendezvous.Server(1)
    addr = server.start()
    try:
      c1 = rendezvous.Client(addr)
      c1.register({"executor_id": 0, "host": "h", "port": 1,
                   "authkey": b"a", "pid": 111})
      c2 = rendezvous.Client(addr)
      c2.register({"executor_id": 0, "host": "h", "port": 2,
                   "authkey": b"b", "pid": 222})
      # the live duplicate is RECORDED for the driver's sanity check
      # (cluster.py aborts bring-up on it), not silently merged
      assert len(server.reservations.duplicates) == 1
      assert server.reservations.duplicates[0]["pid"] == 222
      assert len(server.reservations.get()) == 1
    finally:
      server.stop()


class TestSparkStreamingFeed:
  """The DStream/Structured-Streaming feeding hooks (parity: reference
  TFCluster.train accepting a DStream via foreachRDD, TFCluster.py:83-85;
  stop via reservation request_stop, examples/utils/stop_streaming.py).

  Tested at the adapter level: a TPUCluster wired to the stub SparkEngine
  with a recording train fn — node bring-up is covered by test_cluster."""

  def _fake_cluster(self, spark_engine, monkeypatch, fed):
    import threading
    from tensorflowonspark_tpu import cluster as tos_cluster

    lock = threading.Lock()

    def _recording_train_fn(cluster_info, cluster_meta, feed_timeout=600,
                            qname="input"):
      def _feed(it):
        rows = list(it)
        with lock:
          fed.append(rows)
      return _feed

    monkeypatch.setattr(tos_cluster.node_mod, "make_train_fn",
                        _recording_train_fn)

    class _FakeServer:
      done = threading.Event()
      stop_requested = threading.Event()
      def stopping(self):
        return self.stop_requested.is_set() or self.done.is_set()
      def stop(self):
        pass

    return tos_cluster.TPUCluster(
        engine=spark_engine, cluster_info=[], cluster_meta={"queues": []},
        server=_FakeServer(), input_mode=tos_cluster.InputMode.ENGINE,
        node_job=None, tf_status={})

  def test_train_dstream_feeds_each_microbatch(self, spark_engine,
                                               monkeypatch):
    import pyspark_stub
    fed = []
    c = self._fake_cluster(spark_engine, monkeypatch, fed)
    sc = spark_engine.sc
    ssc = pyspark_stub.StreamingContext(sc, batchDuration=0.01)
    batches = [sc.parallelize([b * 10 + i for i in range(4)], 2)
               for b in range(3)]
    handle = c.train_dstream(ssc.queueStream(batches), feed_timeout=30)
    ssc.start()
    ssc.awaitTermination(10)
    ssc.stop(stopSparkContext=False)
    assert handle.rounds == 3
    rows = sorted(r for part in fed for r in part)
    assert rows == sorted(b * 10 + i for b in range(3) for i in range(4))

  def test_train_dstream_stop_skips_later_batches(self, spark_engine,
                                                  monkeypatch):
    import pyspark_stub
    fed = []
    c = self._fake_cluster(spark_engine, monkeypatch, fed)
    sc = spark_engine.sc
    ssc = pyspark_stub.StreamingContext(sc, batchDuration=0.01)
    handle = c.train_dstream(
        ssc.queueStream([sc.parallelize([1, 2], 2) for _ in range(5)]))
    c.request_stop()  # stop BEFORE any batch: all skipped, none consumed
    ssc.start()
    ssc.awaitTermination(10)
    ssc.stop(stopSparkContext=False)
    assert handle.rounds == 0 and handle.stopped
    assert fed == []

  def test_train_accepts_dstream_directly(self, spark_engine, monkeypatch):
    """train(dstream) routes to the foreachRDD hook, like the reference."""
    import pyspark_stub
    fed = []
    c = self._fake_cluster(spark_engine, monkeypatch, fed)
    sc = spark_engine.sc
    ssc = pyspark_stub.StreamingContext(sc, batchDuration=0.01)
    c.train(ssc.queueStream([sc.parallelize([7, 8], 1)]))
    ssc.start()
    ssc.awaitTermination(10)
    ssc.stop(stopSparkContext=False)
    assert sorted(r for part in fed for r in part) == [7, 8]

  def test_train_rdd_epochs_via_union(self, spark_engine, monkeypatch):
    """An engine-native RDD replicates via union for epochs — the driver
    never iterates the data (reference sc.union([rdd]*N), TFCluster.py:90-94)."""
    fed = []
    c = self._fake_cluster(spark_engine, monkeypatch, fed)
    rdd = spark_engine.sc.parallelize([1, 2, 3, 4], 2)
    c.train(rdd, num_epochs=3, feed_timeout=30)
    assert len(fed) == 6          # 2 partitions x 3 epochs
    assert sorted(r for part in fed for r in part) == sorted([1, 2, 3, 4] * 3)

  def test_foreach_batch_callback(self, spark_engine, monkeypatch):
    """Structured Streaming path: cluster.foreach_batch() feeds DataFrames."""
    fed = []
    c = self._fake_cluster(spark_engine, monkeypatch, fed)

    class _FakeDF:
      def __init__(self, rdd):
        self.rdd = rdd

    cb = c.foreach_batch(feed_timeout=30)
    cb(_FakeDF(spark_engine.sc.parallelize([5, 6], 1)), 0)
    cb(_FakeDF(spark_engine.sc.parallelize([9], 1)), 1)
    assert sorted(r for part in fed for r in part) == [5, 6, 9]
    c.request_stop()
    cb(_FakeDF(spark_engine.sc.parallelize([99], 1)), 2)
    assert sorted(r for part in fed for r in part) == [5, 6, 9]


class TestSpeculationWinner:
  def test_speculation_survives_one_chain_failing(self):
    """Spark marks a task successful when ANY attempt survives: if the
    original attempt chain exhausts maxFailures while the speculative copy
    succeeds, collect() must succeed with the copy's result."""
    import threading

    import pyspark_stub
    sc = pyspark_stub.SparkContext(
        num_executors=2,
        conf_values={"spark.speculation": "true",
                     "spark.task.maxFailures": "2"})
    # chain identity: every attempt chain begins with attemptNumber 0 and
    # runs its attempts on one thread; the FIRST chain to start is doomed
    local = threading.local()
    state = {"chains": 0}
    lock = threading.Lock()

    def task(it):
      rows = list(it)
      if pyspark_stub.TaskContext.get().attemptNumber() == 0:
        with lock:
          local.chain = state["chains"]
          state["chains"] += 1
      if local.chain == 0:
        raise ValueError("this attempt chain always dies")
      return iter([sum(rows)])

    out = sc.parallelize([1, 2], 1).mapPartitions(task).collect()
    assert out == [3]
