"""Observability-plane tests: metrics/spans/collector/export + the
rendezvous ``OBS`` verb (delta shipping, bounded-buffer drop accounting,
clock-offset estimation under injected chaos delay)."""

import json
import os
import sys
import threading
import time
from unittest import mock

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tensorflowonspark_tpu.control import rendezvous
from tensorflowonspark_tpu.obs import collector, export, metrics, spans
from tensorflowonspark_tpu.utils import chaos


@pytest.fixture()
def clean_active():
  """Tests that install a process registry/tracer must not leak it."""
  yield
  metrics.deactivate()
  spans.deactivate()


class TestMetrics:
  def test_counter_gauge_histogram_snapshot(self):
    r = metrics.MetricsRegistry()
    c = r.counter("c")
    c.inc()
    c.inc(3)
    r.gauge("g").set(2.5)
    h = r.histogram("h", bounds=(1.0, 10.0))
    h.observe(0.5)
    h.observe(5)
    h.observe(100)
    snap = r.snapshot()
    assert snap["c"] == {"type": "counter", "value": 4}
    assert snap["g"] == {"type": "gauge", "value": 2.5}
    assert snap["h"]["counts"] == [1, 1, 1]    # <=1, <=10, overflow
    assert snap["h"]["count"] == 3 and snap["h"]["sum"] == 105.5
    # snapshots are plain builtins (msgpack/json-safe)
    json.dumps(snap)

  def test_same_name_different_type_rejected(self):
    r = metrics.MetricsRegistry()
    r.counter("x")
    with pytest.raises(TypeError):
      r.gauge("x")

  def test_delta_apply_roundtrip(self):
    """Deltas re-applied driver-side must reconstruct the totals — the
    OBS verb's whole shipping contract."""
    r = metrics.MetricsRegistry()
    c = r.counter("c")
    h = r.histogram("h", bounds=(1.0,))
    g = r.gauge("g")
    total = {}
    prev = r.snapshot()
    for i in range(3):
      c.inc(i + 1)
      h.observe(i)
      g.set(i)
      cur = r.snapshot()
      metrics.apply_delta(total, metrics.snapshot_delta(cur, prev))
      prev = cur
    final = r.snapshot()
    assert total["c"]["value"] == final["c"]["value"] == 6
    assert total["h"]["counts"] == final["h"]["counts"]
    assert total["h"]["count"] == 3
    assert total["g"]["value"] == 2      # gauge: last write, not a sum

  def test_delta_omits_unchanged(self):
    r = metrics.MetricsRegistry()
    r.counter("quiet")
    s1 = r.snapshot()
    assert metrics.snapshot_delta(r.snapshot(), s1) == {}
    r.counter("quiet").inc()
    d = metrics.snapshot_delta(r.snapshot(), s1)
    assert list(d) == ["quiet"] and d["quiet"]["value"] == 1

  def test_stats_snapshot_subtract_live_dict(self):
    """The one snapshot-subtract helper the benches route through: the
    live dict keeps mutating (daemon threads) and delta() reflects only
    the growth since the snapshot."""
    live = {"fetch_s": 1.0, "chunks": 3}
    snap = metrics.snapshot_stats(live)
    stop = threading.Event()

    def mutate():
      while not stop.is_set():
        live["fetch_s"] += 0.5
        live["chunks"] += 1

    t = threading.Thread(target=mutate, daemon=True)
    t.start()
    try:
      deadline = time.monotonic() + 5
      while live["chunks"] < 100 and time.monotonic() < deadline:
        time.sleep(0.01)
      d = snap.delta()
      assert d["chunks"] >= 97 and d["fetch_s"] >= 48.0
      assert snap.delta()["chunks"] >= d["chunks"]   # monotonic
    finally:
      stop.set()
      t.join(timeout=5)

  def test_active_registry_gated_by_env(self, clean_active):
    with mock.patch.dict("os.environ", {metrics.ENV_OBS: ""}):
      metrics.deactivate()
      assert metrics.active() is None
      assert spans.active() is None
    with mock.patch.dict("os.environ", {metrics.ENV_OBS: "1"}):
      reg = metrics.active()
      assert isinstance(reg, metrics.MetricsRegistry)
      assert metrics.active() is reg           # one per process
      assert isinstance(spans.active(), spans.SpanRecorder)
    # TOS_OBS=0 is off, not on
    with mock.patch.dict("os.environ", {metrics.ENV_OBS: "0"}):
      metrics.deactivate()
      assert metrics.active() is None


class TestSpans:
  def test_span_and_event_records(self):
    rec = spans.SpanRecorder(capacity=10)
    with rec.span("feed.batch", rows=32):
      time.sleep(0.01)
    rec.event("marker", kind="eof")
    got = rec.drain(None)
    assert len(got) == 2
    s, e = got
    assert s["name"] == "feed.batch" and s["ph"] == "X"
    assert s["dur"] >= 0.01 and s["attrs"] == {"rows": 32}
    assert e["ph"] == "i" and e["attrs"] == {"kind": "eof"}
    json.dumps(got)                       # wire-safe

  def test_bounded_buffer_drop_accounting(self):
    rec = spans.SpanRecorder(capacity=3)
    for i in range(7):
      rec.event("e%d" % i)
    assert len(rec) == 3
    assert rec.dropped == 4 and rec.recorded == 3
    assert rec.drop_counts() == {"spans_dropped": 4, "spans_recorded": 3}
    # drain frees capacity again
    assert len(rec.drain(None)) == 3
    rec.event("later")
    assert len(rec) == 1

  def test_clock_offset_keeps_min_rtt_sample(self):
    clk = spans.ClockOffset()
    assert clk.offset == 0.0 and clk.samples == 0
    clk.update(0.0, 5.0, 1.0)            # rtt 1.0, offset 4.5
    assert clk.offset == pytest.approx(4.5) and clk.rtt == 1.0
    clk.update(10.0, 14.7, 10.2)         # rtt 0.2: better, adopted
    assert clk.offset == pytest.approx(4.6) and clk.rtt == pytest.approx(0.2)
    clk.update(20.0, 99.0, 23.0)         # rtt 3.0: worse, ignored
    assert clk.offset == pytest.approx(4.6)
    assert clk.samples == 3

  def test_clock_offset_window_reelects_best_recent(self):
    """Once the elected sample ages out of the window, the MIN-RTT
    sample of the recent window is re-elected — never whatever lone
    (possibly delayed) sample happened to arrive at the boundary."""
    clk = spans.ClockOffset(window=2)
    clk.update(0.0, 5.0, 0.1)            # rtt 0.1: elected
    clk.update(1.0, 9.0, 1.4)            # rtt 0.4
    clk.update(3.0, 11.1, 3.3)           # rtt 0.3; window expired here
    # re-election picks the best of the last 2 samples (rtt 0.3), not
    # the stale rtt-0.1 winner and not blindly the newest
    assert clk.rtt == pytest.approx(0.3)
    assert clk.offset == pytest.approx(11.1 - 3.15)
    # a later delayed sample at a re-election boundary still loses to a
    # better sample inside the window
    clk.update(10.0, 14.2, 10.2)         # rtt 0.2: elected immediately
    clk.update(20.0, 99.0, 23.0)         # rtt 3.0
    clk.update(30.0, 99.5, 33.0)         # rtt 3.0; window expired
    assert clk.rtt == pytest.approx(3.0)
    assert clk.offset in (pytest.approx(99.0 - 21.5),
                          pytest.approx(99.5 - 31.5))


class _SinkServer:
  """A real rendezvous server with an attached ObsSink."""

  def __init__(self, sink=None):
    self.server = rendezvous.Server(1)
    self.server.obs_sink = sink
    self.addr = self.server.start()

  def close(self):
    self.server.stop()


class TestObsVerbAndCollector:
  def test_delta_shipping_end_to_end(self):
    """Shipper → OBS verb → sink: metric deltas accumulate server-side,
    spans arrive with the shipper's clock offset attached."""
    sink = collector.ObsSink()
    srv = _SinkServer(sink)
    reg = metrics.MetricsRegistry()
    rec = spans.SpanRecorder(capacity=100)
    shipper = collector.ObsShipper(srv.addr, 7, registry=reg, recorder=rec,
                                   interval=60, label="exec")
    try:
      reg.counter("work").inc(5)
      rec.event("phase1")
      assert shipper.ship(timeout=10)
      reg.counter("work").inc(2)
      assert shipper.ship(timeout=10)
      assert sink.metrics(7)["work"]["value"] == 7     # 5 + 2, not 5 + 7
      got = sink.obs_recv(16, timeout=5)
      assert [s["name"] for s in got] == ["phase1"]
      assert got[0]["executor_id"] == 7
      assert "offset" in got[0]
      # the OBS reply is a TIME exchange too
      assert shipper.clock.samples >= 1
      summary = sink.summary()
      assert summary["executors"][7]["ships"] == 2
      assert summary["rejected"] == 0
    finally:
      shipper.stop(timeout=2)
      srv.close()

  def test_idle_shipper_keeps_wire_quiet(self):
    sink = collector.ObsSink()
    srv = _SinkServer(sink)
    reg = metrics.MetricsRegistry()
    shipper = collector.ObsShipper(srv.addr, 1, registry=reg,
                                   recorder=spans.SpanRecorder(capacity=4),
                                   interval=60)
    try:
      reg.counter("x").inc()
      assert shipper.ship(timeout=10)
      before = sink.summary()["ingested"]
      assert shipper.ship(timeout=10)    # nothing new: acked locally
      assert sink.summary()["ingested"] == before
    finally:
      shipper.stop(timeout=2)
      srv.close()

  def test_rejected_ship_is_not_an_ack(self):
    """accepted=False (no sink / sink error) must NOT advance the
    metrics baseline: the delta re-ships once a sink is there."""
    srv = _SinkServer(sink=None)
    reg = metrics.MetricsRegistry()
    shipper = collector.ObsShipper(srv.addr, 3, registry=reg,
                                   recorder=spans.SpanRecorder(capacity=4),
                                   interval=60)
    try:
      reg.counter("work").inc(5)
      assert shipper.ship(timeout=10) is False
      assert shipper.ship_failures == 1 and shipper.ships_acked == 0
      sink = collector.ObsSink()
      srv.server.obs_sink = sink
      assert shipper.ship(timeout=10) is True
      assert sink.metrics(3)["work"]["value"] == 5   # nothing was lost
    finally:
      shipper.stop(timeout=2)
      srv.close()

  def test_obs_verb_without_sink_is_acked_and_dropped(self):
    srv = _SinkServer(sink=None)
    try:
      c = rendezvous.Client(srv.addr, timeout=5)
      resp = c._request({"type": "OBS", "executor_id": 0, "metrics": {},
                         "spans": []})
      assert resp["type"] == "OK" and resp["accepted"] is False
      assert "server_time" in resp
      c.close()
    finally:
      srv.close()

  def test_sink_bounded_span_buffer_drop_accounting(self):
    sink = collector.ObsSink(max_spans=3)
    msg = {"type": "OBS", "executor_id": 0, "metrics": {},
           "spans": [{"name": "s%d" % i, "ph": "i", "t0": float(i)}
                     for i in range(5)]}
    assert sink.ingest(msg)
    assert sink.spans_dropped == 2
    assert len(sink.obs_recv(10, timeout=1)) == 3
    assert sink.obs_recv(10, block=False) == []
    # malformed payloads are counted, never raised
    assert not sink.ingest({"type": "OBS"})
    assert sink.rejected == 1

  def test_ship_failure_counts_instead_of_raising(self):
    import socket
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()                            # nothing listens here
    rec = spans.SpanRecorder(capacity=10)
    rec.event("doomed")
    shipper = collector.ObsShipper(("127.0.0.1", port), 0,
                                   registry=metrics.MetricsRegistry(),
                                   recorder=rec, interval=60)
    assert shipper.ship(timeout=0.7) is False
    assert shipper.ship_failures >= 1
    assert shipper.spans_lost == 1       # drained spans counted, not kept
    shipper.stop(timeout=1)

  def test_clock_offset_estimation_under_chaos_rv_delay(self):
    """TOS_CHAOS_RV_DELAY on BEAT inflates individual round-trips; the
    min-RTT estimator must ride the clean beats: same-host monotonic
    clocks are shared, so the estimate must stay near zero even though
    the first beats saw a 0.2s injected delay (offset error up to 0.1s
    if they were trusted)."""
    chaos.reset()
    srv = _SinkServer()
    try:
      with mock.patch.dict("os.environ",
                           {chaos.ENV_RV_DELAY: "BEAT:0.2:2"}):
        sender = rendezvous.HeartbeatSender(srv.addr, 0, interval=0.05)
        sender.start()
        try:
          deadline = time.monotonic() + 10
          while sender.clock.samples < 5 and time.monotonic() < deadline:
            time.sleep(0.05)
        finally:
          sender.stop()
      assert sender.clock.samples >= 5
      # the adopted sample is a clean (undelayed) round-trip…
      assert sender.clock.rtt < 0.15
      # …so the offset error is bounded by rtt/2, far under the 0.1s a
      # delayed-sample estimate would carry
      assert abs(sender.clock.offset) <= sender.clock.rtt / 2 + 0.02
    finally:
      chaos.reset()
      srv.close()

  def test_beat_reply_carries_server_time(self):
    srv = _SinkServer()
    try:
      c = rendezvous.Client(srv.addr, timeout=5)
      resp = c._request({"type": "BEAT", "executor_id": 0})
      assert resp["type"] == "OK" and "server_time" in resp
      c.close()
    finally:
      srv.close()


class TestExport:
  def _clock(self, offset):
    clk = spans.ClockOffset()
    clk.update(0.0, offset, 0.0)         # rtt 0: exact offset
    return clk

  def test_process_log_merge_and_chrome_trace(self, tmp_path):
    d = str(tmp_path)
    log = export.ProcessLog(d, label="exec", executor_id=3,
                            clock=self._clock(2.0))
    log.append_spans([{"name": "feed.batch", "ph": "X", "t0": 1.0,
                       "dur": 0.5, "tid": "MainThread",
                       "attrs": {"rows": 8}}])
    log.close(metrics_snapshot={"c": {"type": "counter", "value": 4}})
    paths = export.find_logs(d)
    assert len(paths) == 1 and "obs-exec3-" in paths[0]
    procs = export.merge_jsonl(paths)
    assert len(procs) == 1
    p = procs[0]
    assert p["meta"]["label"] == "exec" and p["meta"]["executor_id"] == 3
    assert p["clock"]["offset"] == pytest.approx(2.0)
    assert p["metrics"]["c"]["value"] == 4
    assert export.anchored_window(p) == (pytest.approx(3.0),
                                         pytest.approx(3.5))
    trace = export.chrome_trace(procs)
    names = {e["name"] for e in trace["traceEvents"]}
    assert {"process_name", "thread_name", "feed.batch"} <= names
    (span_ev,) = [e for e in trace["traceEvents"]
                  if e["name"] == "feed.batch"]
    assert span_ev["ph"] == "X"
    assert span_ev["ts"] == pytest.approx(3.0e6)      # anchored, µs
    assert span_ev["dur"] == pytest.approx(0.5e6)
    assert span_ev["args"] == {"rows": 8}
    json.dumps(trace)

  def test_merge_skips_malformed_lines(self, tmp_path):
    path = tmp_path / "obs-exec0-1.jsonl"
    path.write_text('{"kind": "meta", "label": "exec", "executor_id": 0, '
                    '"pid": 1, "t_wall": 0, "t_mono": 0}\n'
                    'not json\n'
                    '{"kind": "span", "name": "s", "ph": "i", "t0": 1.0}\n')
    (p,) = export.merge_jsonl([str(path)])
    assert p["skipped"] == 1 and len(p["spans"]) == 1

  def test_no_dir_is_a_noop(self, monkeypatch):
    monkeypatch.delenv(export.ENV_OBS_DIR, raising=False)
    log = export.ProcessLog(label="exec", executor_id=0)
    log.append_spans([{"name": "s", "ph": "i", "t0": 0.0}])
    log.close()
    assert log.path is None

  def test_prometheus_histogram_exposition(self):
    snap = {"feed.batch_ms": {"type": "histogram", "bounds": [1.0, 5.0],
                              "counts": [2, 1, 1], "sum": 10.0, "count": 4}}
    text = export.prometheus_text(snap, labels={"proc": "exec0"})
    lines = text.splitlines()
    assert lines[0] == "# TYPE tos_feed_batch_ms histogram"
    assert 'tos_feed_batch_ms_bucket{proc="exec0",le="1"} 2' in lines
    assert 'tos_feed_batch_ms_bucket{proc="exec0",le="5"} 3' in lines
    assert 'tos_feed_batch_ms_bucket{proc="exec0",le="+Inf"} 4' in lines
    assert 'tos_feed_batch_ms_count{proc="exec0"} 4' in lines


class TestStepTimerRegistrySeam:
  def test_step_timer_feeds_active_registry(self, clean_active):
    from tensorflowonspark_tpu.obs import profiler
    reg = metrics.activate()
    rec = spans.activate()
    t = profiler.StepTimer(warmup=1)
    for _ in range(3):
      with t.step(items=10):
        time.sleep(0.001)
    snap = reg.snapshot()
    assert snap["train.steps"]["value"] == 2        # warmup excluded
    assert snap["train.items"]["value"] == 20
    assert snap["train.step_ms"]["count"] == 2
    got = [s for s in rec.drain(None) if s["name"] == "train.step"]
    assert len(got) == 2 and got[0]["attrs"]["items"] == 10

  def test_step_timer_inert_without_registry(self, clean_active):
    from tensorflowonspark_tpu.obs import profiler
    metrics.deactivate()
    spans.deactivate()
    t = profiler.StepTimer(warmup=0)
    with t.step(items=1):
      pass
    assert t.summary()["steps"] == 1

  def test_deprecated_import_path_still_works(self):
    import importlib
    import warnings
    with warnings.catch_warnings():
      warnings.simplefilter("ignore", DeprecationWarning)
      import tensorflowonspark_tpu.utils.profiler as shim
      importlib.reload(shim)
    from tensorflowonspark_tpu.obs import profiler as new
    assert shim.StepTimer is new.StepTimer
    assert shim.mfu is new.mfu
    assert shim.annotate is new.annotate


class TestShipperSamplersAndTopSummary:
  def test_samplers_run_per_ship_and_clock_gauges_land(self):
    """Pre-ship samplers (the device-memory seat) run once per round and
    their gauges — plus the clock-quality gauges — ride the normal delta
    wire into the sink's top summary."""
    sink = collector.ObsSink()
    srv = _SinkServer(sink)
    reg = metrics.MetricsRegistry()
    shipper = collector.ObsShipper(srv.addr, 3, registry=reg,
                                   recorder=spans.SpanRecorder(capacity=4),
                                   interval=60, label="exec")
    calls = []
    shipper.add_sampler(lambda: calls.append(1))

    def broken():
      raise RuntimeError("boom")

    shipper.add_sampler(broken)
    from tensorflowonspark_tpu.obs import device as obs_device
    shipper.add_sampler(obs_device.make_memory_sampler(
        reg, stats_fn=lambda: {"0": {"bytes_in_use": 42,
                                     "peak_bytes_in_use": 64}}))
    try:
      assert shipper.ship(timeout=10)        # ship 1: a TIME exchange too
      # clock-quality gauges PIGGYBACK on real deltas (alone they must
      # not wake the wire); give ship 2 one real counter delta to ride
      reg.counter("work").inc()
      assert shipper.ship(timeout=10)
      assert len(calls) == 2
      assert shipper.sampler_failures == 2   # broken counted, not raised
      top = sink.top_summary()
      entry = top["3"]
      assert entry["label"] == "exec"
      assert entry["metrics"]["device.bytes_in_use"] == 42
      assert entry["metrics"]["device.peak_bytes"] == 64
      assert entry["metrics"]["clock.samples"] >= 1
      assert "clock.rtt_ms" in entry["metrics"]
    finally:
      shipper.stop(timeout=2)
      srv.close()

  def test_health_reply_carries_obs_summary_and_alert_ring(self):
    """The HEALTH verb's PR-8 extension: with a sink and an alert source
    attached, replies carry the per-executor obs summary and the live
    alert ring — the wire tools/obs_top.py monitors through."""
    from tensorflowonspark_tpu.obs import anomaly
    sink = collector.ObsSink()
    sink.ingest({"executor_id": 4, "label": "exec", "pid": 1, "seq": 1,
                 "metrics": {"train.steps": {"type": "counter",
                                             "value": 9.0}},
                 "spans": [], "drops": {}, "clock": {}})
    srv = _SinkServer(sink)
    det = anomaly.AnomalyDetector(sink, registry=metrics.MetricsRegistry(),
                                  recorder=None, interval=1.0, window=4.0)
    det._fire("straggler", 4, 4.0, 100.0, {"rate": 0.0}, "synthetic")
    srv.server.alert_source = det
    try:
      c = rendezvous.Client(srv.addr, timeout=10)
      reply = c._request({"type": "HEALTH"})
      c.close()
      assert reply["type"] == "HEALTH"
      assert reply["obs"]["4"]["metrics"]["train.steps"] == 9.0
      assert [a["alert"] for a in reply["alerts"]] == ["straggler"]
      # json/msgpack-safe end to end (obs_top --once --json prints it)
      json.dumps(reply)
    finally:
      srv.close()

  def test_health_reply_without_obs_stays_liveness_only(self):
    srv = _SinkServer(None)
    try:
      c = rendezvous.Client(srv.addr, timeout=10)
      reply = c._request({"type": "HEALTH"})
      c.close()
      assert "obs" not in reply and "alerts" not in reply
    finally:
      srv.close()


class TestChromeTraceFlows:
  """Cross-process flow arrows (PR 14): spans sharing a request trace id
  chain into chrome flow events (ph s/t/f, one shared id)."""

  def _procs(self):
    # two processes, one request: dispatch on the driver, prefill+decode
    # on the executor — the cross-process waterfall shape
    driver = {"meta": {"label": "driver", "executor_id": 0, "pid": 100},
              "clock": {"offset": 0.0},
              "spans": [{"name": "fleet.dispatch", "ph": "X", "t0": 1.0,
                         "dur": 0.1, "tid": "main", "trace": "aaaa"},
                        {"name": "unrelated", "ph": "X", "t0": 1.0,
                         "dur": 0.1, "tid": "main"}]}
    ex = {"meta": {"label": "exec", "executor_id": 1, "pid": 200},
          "clock": {"offset": 0.0},
          "spans": [{"name": "serve.prefill", "ph": "X", "t0": 1.2,
                     "dur": 0.3, "tid": "loop", "trace": "aaaa"},
                    {"name": "serve.decode.slot", "ph": "X", "t0": 1.6,
                     "dur": 0.2, "tid": "loop", "trace": "aaaa"},
                    {"name": "serve.replay", "ph": "i", "t0": 1.7,
                     "tid": "loop", "trace": "aaaa"}]}
    return [driver, ex]

  def test_flow_chain_is_well_formed(self):
    trace = export.chrome_trace(self._procs())
    flows = [e for e in trace["traceEvents"] if e.get("cat") == "trace"]
    # 3 X-spans on the trace -> s, t, f (instants join via args only)
    assert [f["ph"] for f in sorted(flows, key=lambda e: e["ts"])] \
        == ["s", "t", "f"]
    assert len({f["id"] for f in flows}) == 1
    # flow ids must stay float64-exact: trace viewers parse JSON numbers
    # into doubles, and an id past 2**53 can collide after rounding
    assert all(0 < f["id"] < (1 << 53) for f in flows)
    assert export._flow_id("f" * 16) < (1 << 53)
    assert flows[-1].get("bp") == "e" or \
        next(f for f in flows if f["ph"] == "f")["bp"] == "e"
    # every flow point binds INSIDE its enclosing slice, and the chain
    # crosses the process boundary
    xs = {(e["pid"], e["tid"], e["ts"]): e for e in trace["traceEvents"]
          if e["ph"] == "X" and (e.get("args") or {}).get("trace")}
    assert {f["pid"] for f in flows} == {100, 200}
    for f in flows:
      host = [e for (pid, tid, ts), e in xs.items()
              if pid == f["pid"] and tid == f["tid"]
              and ts <= f["ts"] <= ts + e["dur"]]
      assert host, f
    # the trace id itself is clickable on every span AND the instant
    tagged = [e for e in trace["traceEvents"]
              if (e.get("args") or {}).get("trace") == "aaaa"]
    assert len(tagged) == 4
    json.dumps(trace)

  def test_single_span_traces_emit_no_flow(self):
    procs = [{"meta": {"label": "exec", "executor_id": 0, "pid": 1},
              "clock": {"offset": 0.0},
              "spans": [{"name": "serve.prefill", "ph": "X", "t0": 0.0,
                         "dur": 0.1, "tid": "t", "trace": "bbbb"}]}]
    trace = export.chrome_trace(procs)
    assert [e for e in trace["traceEvents"]
            if e.get("cat") == "trace"] == []

  def test_prometheus_sketch_exposition(self):
    from tensorflowonspark_tpu.obs import quantiles
    sk = quantiles.QuantileSketch()
    sk.extend(float(v) for v in range(1, 101))
    snap = {"serve.ttft_ms": {"type": "sketch", "count": 100,
                              "data": sk.to_dict()}}
    text = export.prometheus_text(snap, labels={"proc": "exec0"})
    lines = text.splitlines()
    assert lines[0] == "# TYPE tos_serve_ttft_ms summary"
    assert 'tos_serve_ttft_ms{proc="exec0",quantile="0.5"} 50' in lines
    assert 'tos_serve_ttft_ms{proc="exec0",quantile="0.99"} 99' in lines
    assert 'tos_serve_ttft_ms_count{proc="exec0"} 100' in lines
