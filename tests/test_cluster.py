"""L2'+L3' integration tests: full cluster lifecycle on the LocalEngine.

Port of the reference's distributed-integration tier
(reference tests/test_TFCluster.py, run on a 2-worker Spark standalone
cluster): independent single-node computations (:16-27), ENGINE-mode
inference round-trip sum(x^2) (:29-48), exception during feeding (:50-68),
late exception after feeding with grace_secs (:70-91), port
release/unrelease semantics (:93-121); plus ps/evaluator lifecycle.
"""

import os

import pytest

from tensorflowonspark_tpu import cluster as tos_cluster
from tensorflowonspark_tpu.cluster import InputMode
from tensorflowonspark_tpu.engine import LocalEngine


@pytest.fixture()
def engine():
  e = LocalEngine(num_executors=2)
  yield e
  e.stop()


# fake tensorboard entry point: records start/kill so tests can observe
# the node runtime's spawn and kill-on-shutdown behavior
_FAKE_TB = """\
import argparse, os, signal, sys, time
p = argparse.ArgumentParser()
p.add_argument("--logdir"); p.add_argument("--port"); p.add_argument("--host")
a, _ = p.parse_known_args()


def _bye(sig, frame):
  with open(os.path.join(a.logdir, "tb_killed.txt"), "w") as f:
    f.write("killed")
  sys.exit(0)


signal.signal(signal.SIGTERM, _bye)
with open(os.path.join(a.logdir, "tb_started.txt"), "w") as f:
  f.write("%d %s" % (os.getpid(), a.port))
while True:
  time.sleep(0.2)
"""


def test_tensorboard_spawned_on_chief_and_killed_on_shutdown(
    tmp_path, monkeypatch):
  """tensorboard=True spawns the discovered binary on the chief with the
  requested port, tensorboard_url() plumbs through cluster_info, and
  shutdown kills the server (parity: TFSparkNode.py:292-329, 619-625;
  TFCluster.tensorboard_url, TFCluster.py:207-212)."""
  import time
  from tensorflowonspark_tpu.utils.hostinfo import get_free_port

  fake_bin = tmp_path / "bin"
  fake_bin.mkdir()
  (fake_bin / "tensorboard").write_text(_FAKE_TB)
  log_dir = tmp_path / "logs"
  log_dir.mkdir()
  port = get_free_port()
  monkeypatch.setenv("PATH",
                     str(fake_bin) + os.pathsep + os.environ.get("PATH", ""))
  monkeypatch.setenv("TENSORBOARD_PORT", str(port))

  engine = LocalEngine(num_executors=2)
  try:
    c = tos_cluster.run(engine, lambda args, ctx: None,
                        input_mode=InputMode.FILES, tensorboard=True,
                        log_dir=str(log_dir), reservation_timeout=30)
    url = c.tensorboard_url()
    assert url is not None and url.endswith(":%d" % port), url

    started = log_dir / "tb_started.txt"
    deadline = time.time() + 20
    while not started.exists() and time.time() < deadline:
      time.sleep(0.2)
    assert started.exists(), "fake tensorboard never started"
    tb_pid, tb_port = started.read_text().split()
    assert tb_port == str(port)
    os.kill(int(tb_pid), 0)        # alive while the cluster runs

    c.shutdown(timeout=120)
    killed = log_dir / "tb_killed.txt"
    deadline = time.time() + 20
    while not killed.exists() and time.time() < deadline:
      time.sleep(0.2)
    assert killed.exists(), "shutdown did not SIGTERM the tensorboard"
  finally:
    engine.stop()


def test_independent_jax_nodes(engine):
  """Each node runs a small real JAX computation (parity :16-27)."""

  def main_fn(args, ctx):
    import jax.numpy as jnp
    result = float(jnp.square(jnp.arange(4)).sum())  # 0+1+4+9
    with open("result.txt", "w") as f:
      f.write("%d:%s:%f" % (ctx.executor_id, ctx.job_name, result))

  c = tos_cluster.run(engine, main_fn, tf_args=None,
                      input_mode=InputMode.FILES, reservation_timeout=30)
  c.shutdown(timeout=120)

  for slot in range(2):
    path = os.path.join(engine.executor_workdir(slot), "result.txt")
    assert os.path.exists(path)
    eid, job, val = open(path).read().split(":")
    assert job == "worker"
    assert float(val) == 14.0


def test_train_unroll_knob_reaches_every_node(engine):
  """cluster.run(train_unroll=K) exports TOS_TRAIN_UNROLL into each node
  process, so make_train_loop/slab_batches default to the cluster's K
  without per-fn plumbing (and an explicit argument still wins)."""

  def main_fn(args, ctx):
    import os as _os
    from tensorflowonspark_tpu.parallel.sharding import (ENV_TRAIN_UNROLL,
                                                         resolve_unroll)
    with open("unroll.txt", "w") as f:
      f.write("%s|%d|%d" % (_os.environ.get(ENV_TRAIN_UNROLL),
                            resolve_unroll(), resolve_unroll(2)))

  c = tos_cluster.run(engine, main_fn, input_mode=InputMode.FILES,
                      reservation_timeout=30, train_unroll=4)
  assert c.cluster_meta["train_unroll"] == 4
  c.shutdown(timeout=120)
  for slot in range(2):
    path = os.path.join(engine.executor_workdir(slot), "unroll.txt")
    assert open(path).read() == "4|4|2"


def test_train_unroll_validation(engine):
  with pytest.raises(ValueError):
    tos_cluster.run(engine, lambda a, c: None, train_unroll=0)


def test_apply_node_env_retracts_only_its_own_export(monkeypatch):
  """A persistent executor must not leak run A's train_unroll into run B
  (which never opted in) — but a USER-set env pin is not ours to pop."""
  from tensorflowonspark_tpu import node
  from tensorflowonspark_tpu.parallel.sharding import ENV_TRAIN_UNROLL
  monkeypatch.delenv(ENV_TRAIN_UNROLL, raising=False)
  node._applied_node_env.clear()
  node._apply_node_env({"train_unroll": 8})       # run A exports
  assert os.environ[ENV_TRAIN_UNROLL] == "8"
  node._apply_node_env({"train_unroll": None})    # run B sets nothing
  assert ENV_TRAIN_UNROLL not in os.environ       # A's export retracted
  monkeypatch.setenv(ENV_TRAIN_UNROLL, "3")       # user's own pin
  node._apply_node_env({"train_unroll": None})
  assert os.environ[ENV_TRAIN_UNROLL] == "3"      # passes through


def test_cluster_spec_and_roles(engine):
  def main_fn(args, ctx):
    with open("spec.txt", "w") as f:
      f.write("%s|%d|%d|%d" % (ctx.job_name, ctx.task_index,
                               ctx.num_processes, ctx.process_id))

  c = tos_cluster.run(engine, main_fn, master_node="chief",
                      input_mode=InputMode.FILES, reservation_timeout=30)
  assert len(c.cluster_info) == 2
  spec_jobs = sorted(n["job_name"] for n in c.cluster_info)
  assert spec_jobs == ["chief", "worker"]
  c.shutdown(timeout=120)

  specs = sorted(open(os.path.join(engine.executor_workdir(s), "spec.txt"))
                 .read() for s in range(2))
  assert specs == ["chief|0|2|0", "worker|0|2|1"]


def test_inference_roundtrip_sum_squares(engine):
  """ENGINE-mode inference over 200 rows in 10 partitions (parity :29-48)."""

  def main_fn(args, ctx):
    feed = ctx.get_data_feed(train_mode=False)
    while not feed.should_stop():
      batch = feed.next_batch(32)
      if batch:
        feed.batch_results([x * x for x in batch])

  c = tos_cluster.run(engine, main_fn, input_mode=InputMode.ENGINE,
                      reservation_timeout=30)
  data = list(range(200))
  partitions = [data[i::10] for i in range(10)]
  results = c.inference(partitions, feed_timeout=60)
  c.shutdown(timeout=120)
  assert len(results) == 200
  assert sum(results) == sum(x * x for x in data)


def test_inference_lazy_streams_without_driver_collect(engine):
  """collect=False streams ≥10k inference rows through the driver without
  ever materializing the full result list (parity: reference
  TFCluster.inference returning a lazy RDD, TFCluster.py:96-115)."""

  def main_fn(args, ctx):
    feed = ctx.get_data_feed(train_mode=False)
    while not feed.should_stop():
      batch = feed.next_batch(256)
      if batch:
        feed.batch_results([x + 1 for x in batch])

  c = tos_cluster.run(engine, main_fn, input_mode=InputMode.ENGINE,
                      reservation_timeout=30)
  n_rows, n_parts = 12000, 24
  pulled = []

  def parts():
    for p in range(n_parts):
      pulled.append(p)
      yield list(range(p * 500, (p + 1) * 500))

  lazy = c.inference(parts(), feed_timeout=60, collect=False)
  assert not isinstance(lazy, list)
  total, count = 0, 0
  first_row_pull_count = None
  for row in lazy:
    if first_row_pull_count is None:
      first_row_pull_count = len(pulled)
    total += row
    count += 1
  c.shutdown(timeout=120)
  assert count == n_rows
  assert total == sum(range(n_rows)) + n_rows
  assert first_row_pull_count <= engine.num_executors + 2, \
      "lazy inference pre-pulled the whole dataset onto the driver"


def test_default_transport_is_shm_on_local_engine(engine):
  """feed_transport="auto" (the default) resolves to the shared-memory
  ring on engines whose executors share this host; 32k rows flow through
  it end-to-end."""
  from tensorflowonspark_tpu.control import shmring
  if not shmring.available():
    pytest.skip("native shmring unavailable")

  def main_fn(args, ctx):
    feed = ctx.get_data_feed(train_mode=True)
    total = 0
    while not feed.should_stop():
      for x in feed.next_batch(512):
        total += x
    with open("total32k.txt", "w") as f:
      f.write(str(total))

  c = tos_cluster.run(engine, main_fn, input_mode=InputMode.ENGINE,
                      reservation_timeout=30)
  assert c.cluster_meta["feed_transport"] == "shm"
  n = 32_000
  data = list(range(n))
  c.train([data[i::16] for i in range(16)], num_epochs=1, feed_timeout=120)
  c.shutdown(timeout=120)
  totals = []
  for slot in range(2):
    path = os.path.join(engine.executor_workdir(slot), "total32k.txt")
    if os.path.exists(path):
      totals.append(int(open(path).read()))
  assert sum(totals) == sum(range(n))


def test_remote_feeder_falls_back_to_hub_queue(engine):
  """Multi-host story: a feeder that cannot reach a node's shm ring feeds
  through the hub queue, and the node's DualInput consumer drains BOTH
  channels. Simulated by injecting rows straight into the hub queue (what
  input_channel's fallback does on a remote host) while the normal feed
  uses the ring."""
  from tensorflowonspark_tpu.control import feedhub, shmring
  if not shmring.available():
    pytest.skip("native shmring unavailable")

  def main_fn(args, ctx):
    feed = ctx.get_data_feed(train_mode=True)
    total = 0
    while not feed.should_stop():
      for x in feed.next_batch(64):
        total += x
    with open("total_dual.txt", "w") as f:
      f.write(str(total))

  c = tos_cluster.run(engine, main_fn, input_mode=InputMode.ENGINE,
                      reservation_timeout=30)
  assert c.cluster_meta["feed_transport"] == "shm"
  # "remote" rows: put into every node's hub queue directly, bypassing
  # the ring — exactly the remote-feeder fallback path
  remote_rows = list(range(1000, 1200))
  for n in c.cluster_info:
    hub = feedhub.connect(tuple(n["hub_addr"]),
                          c.cluster_meta["authkey"])
    hub.get_queue("input").put_many(remote_rows, block=True, timeout=30)
  # normal (ring) feed + end-of-feed markers via shutdown
  local_rows = list(range(200))
  c.train([local_rows[i::4] for i in range(4)], num_epochs=1,
          feed_timeout=60)
  c.shutdown(timeout=120)

  totals = []
  for slot in range(2):
    path = os.path.join(engine.executor_workdir(slot), "total_dual.txt")
    if os.path.exists(path):
      totals.append(int(open(path).read()))
  assert sum(totals) == sum(local_rows) + 2 * sum(remote_rows)


@pytest.mark.parametrize("transport", ["queue", "shm"])
def test_train_feed_and_shutdown(engine, transport):
  """ENGINE-mode training feed: every row reaches some worker exactly once
  — on both the queue and shared-memory transports."""
  if transport == "shm":
    from tensorflowonspark_tpu.control import shmring
    if not shmring.available():
      pytest.skip("native shmring unavailable")

  def main_fn(args, ctx):
    feed = ctx.get_data_feed(train_mode=True)
    total = 0
    while not feed.should_stop():
      for x in feed.next_batch(16):
        total += x
    with open("total.txt", "w") as f:
      f.write(str(total))

  c = tos_cluster.run(engine, main_fn, input_mode=InputMode.ENGINE,
                      reservation_timeout=30, feed_transport=transport)
  partitions = [[1] * 10, [2] * 10, [3] * 10, [4] * 10]
  c.train(partitions, num_epochs=2, feed_timeout=60)
  c.shutdown(timeout=120)

  grand = 0
  for slot in range(2):
    path = os.path.join(engine.executor_workdir(slot), "total.txt")
    grand += int(open(path).read())
  assert grand == 2 * (10 + 20 + 30 + 40)


def test_exception_during_feeding(engine):
  """A worker failing mid-feed must fail the train job (parity :50-68)."""

  def main_fn(args, ctx):
    feed = ctx.get_data_feed(train_mode=True)
    feed.next_batch(1)
    raise RuntimeError("intentional worker failure")

  c = tos_cluster.run(engine, main_fn, input_mode=InputMode.ENGINE,
                      reservation_timeout=30)
  with pytest.raises((RuntimeError, TimeoutError),
                     match="worker error|feed timeout"):
    c.train([[1] * 50 for _ in range(4)], feed_timeout=15)
  with pytest.raises(RuntimeError):
    c.shutdown(timeout=120)


def test_late_exception_after_feeding(engine):
  """An error after feeding completes must surface at shutdown with
  grace_secs (parity :70-91)."""

  def main_fn(args, ctx):
    feed = ctx.get_data_feed(train_mode=True)
    while not feed.should_stop():
      feed.next_batch(16)
    raise RuntimeError("intentional late failure")

  c = tos_cluster.run(engine, main_fn, input_mode=InputMode.ENGINE,
                      reservation_timeout=30)
  c.train([[1] * 5, [2] * 5], feed_timeout=60)
  with pytest.raises(RuntimeError, match="late failure|worker error"):
    c.shutdown(grace_secs=1, timeout=120)


def test_shutdown_task_targets_payload_executor(tmp_path, monkeypatch):
  """The engine's shared task queue can place BOTH shutdown tasks on one
  executor (whichever frees up first). The end-of-feed marker must reach
  the hub of the executor named in the partition payload — not the hub of
  the slot the task happens to occupy — or the untargeted node never sees
  its marker and hangs in the feed loop until engine teardown (exposed
  when TCP_NODELAY made node stop fast enough for placements to collide)."""
  from tensorflowonspark_tpu import node as node_mod
  from tensorflowonspark_tpu.control import feedhub
  from tensorflowonspark_tpu.utils import hostinfo

  authkey = b"k"
  hubs = [feedhub.start(authkey, ["input", "error"], mode="local")
          for _ in range(2)]
  try:
    for h in hubs:
      h.set("state", "stopped")   # nodes already exited; no wait loop
    cluster_info = [
        {"executor_id": i, "job_name": "worker", "task_index": i,
         "hub_addr": list(h.addr)} for i, h in enumerate(hubs)]
    wd = tmp_path / "exec0"       # this task occupies executor 0's slot...
    wd.mkdir()
    hostinfo.write_executor_id(0, str(wd))
    monkeypatch.chdir(wd)

    fn = node_mod.make_shutdown_fn(cluster_info, {"authkey": authkey})
    # ...but its payload targets executor 1: the marker must reach hub 1
    assert fn(iter([1])) == [1]
    assert hubs[1].get_queue("input").get_many(1, block=False) == [None]
    assert hubs[0].get_queue("input").qsize() == 0
    # a correctly-placed task (payload matches the slot) marks its own hub
    assert fn(iter([0])) == [0]
    assert hubs[0].get_queue("input").get_many(1, block=False) == [None]
  finally:
    for h in hubs:
      h.shutdown()


def test_port_reservation_semantics(engine):
  """release_port=False keeps the node port reserved until user code releases
  it (parity :93-121)."""

  def main_fn(args, ctx):
    import socket
    assert ctx.tmp_socket is not None
    port = ctx.tmp_socket.getsockname()[1]
    probe = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    bind_failed = False
    try:
      probe.bind(("", port))
    except OSError:
      bind_failed = True
    finally:
      probe.close()
    ctx.release_port()
    probe2 = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    probe2.bind(("", port))  # must succeed now
    probe2.close()
    with open("ports.txt", "w") as f:
      f.write(str(bind_failed))

  c = tos_cluster.run(engine, main_fn, input_mode=InputMode.FILES,
                      release_port=False, reservation_timeout=30)
  c.shutdown(timeout=120)
  for slot in range(2):
    content = open(os.path.join(engine.executor_workdir(slot),
                                "ports.txt")).read()
    assert content == "True"


def test_ps_evaluator_lifecycle():
  """ps + evaluator sidecars park on the control queue and stop on driver
  signal (parity: TFSparkNode.py:441-458, TFCluster.py:186-194)."""
  engine = LocalEngine(num_executors=3)
  try:
    def main_fn(args, ctx):
      with open("role.txt", "w") as f:
        f.write("%s:%d" % (ctx.job_name, ctx.task_index))

    c = tos_cluster.run(engine, main_fn, num_ps=1, eval_node=True,
                        input_mode=InputMode.FILES, reservation_timeout=30)
    jobs = sorted(n["job_name"] for n in c.cluster_info)
    assert jobs == ["evaluator", "ps", "worker"]
    c.shutdown(timeout=120)
    roles = set()
    for slot in range(3):
      roles.add(open(os.path.join(engine.executor_workdir(slot),
                                  "role.txt")).read().split(":")[0])
    assert roles == {"ps", "evaluator", "worker"}
  finally:
    engine.stop()


def test_engine_reuse_two_clusters(engine):
  """A second cluster on the same engine must reclaim the previous run's
  stale hubs (different authkey) instead of failing bring-up."""

  def main_fn(args, ctx):
    with open("gen.txt", "a") as f:
      f.write("x")

  for generation in range(2):
    c = tos_cluster.run(engine, main_fn, input_mode=InputMode.FILES,
                        reservation_timeout=30)
    c.shutdown(timeout=120)
  for slot in range(2):
    content = open(os.path.join(engine.executor_workdir(slot),
                                "gen.txt")).read()
    assert content == "xx"


def test_early_bringup_failure_surfaces_fast():
  """A node failing before registration must abort run() with its traceback
  well before the reservation timeout."""
  import time

  def main_fn(args, ctx):
    pass

  # sabotage node bring-up inside the executors: the pinned node port is
  # unparseable, so every node task raises before registering
  bad = LocalEngine(num_executors=2,
                    env={"TOS_TPU_NODE_PORT": "notaport"})
  try:
    t0 = time.time()
    with pytest.raises(RuntimeError,
                       match="(?s)cluster startup aborted.*notaport"):
      tos_cluster.run(bad, main_fn, input_mode=InputMode.FILES,
                      reservation_timeout=300)
    assert time.time() - t0 < 60
  finally:
    bad.stop()


def test_shm_feed_transport_roundtrip(engine):
  """ENGINE mode over the native shared-memory ring: train + inference
  round-trips must behave identically to the queue transport."""
  from tensorflowonspark_tpu.control import shmring
  if not shmring.available():
    pytest.skip("native shmring unavailable")

  def main_fn(args, ctx):
    feed = ctx.get_data_feed(train_mode=False)
    while not feed.should_stop():
      batch = feed.next_batch(32)
      if batch:
        feed.batch_results([x * 3 for x in batch])

  c = tos_cluster.run(engine, main_fn, input_mode=InputMode.ENGINE,
                      reservation_timeout=30, feed_transport="shm")
  assert all(n is not None for n in c.cluster_info)
  data = list(range(150))
  results = c.inference([data[i::6] for i in range(6)], feed_timeout=60)
  c.shutdown(timeout=120)
  assert sorted(results) == sorted(x * 3 for x in data)


def test_train_stream_with_stop_signal(engine):
  """Streaming feed rounds end on the graceful stop signal (parity:
  DStream feeding + stop_streaming, reference TFCluster.py:83-85,150-152)."""

  def main_fn(args, ctx):
    feed = ctx.get_data_feed(train_mode=True)
    total = 0
    while not feed.should_stop():
      for x in feed.next_batch(16):
        total += x
    with open("stream_total.txt", "w") as f:
      f.write(str(total))

  c = tos_cluster.run(engine, main_fn, input_mode=InputMode.ENGINE,
                      reservation_timeout=30)

  def stream():
    for round_no in range(100):       # "unbounded" source
      if round_no == 3:
        # a remote client sends the stop signal (stop_streaming parity)
        from tensorflowonspark_tpu.control.rendezvous import Client
        Client(tuple(c.server_addr)).request_stop()
      yield [[1] * 10, [1] * 10]

  rounds = c.train_stream(stream(), feed_timeout=60)
  assert rounds <= 4
  c.shutdown(timeout=120)
  grand = sum(int(open(os.path.join(engine.executor_workdir(s),
                                    "stream_total.txt")).read())
              for s in range(2))
  assert grand == rounds * 20


def test_driver_ps_nodes():
  """ps nodes hosted on the driver machine (parity: TFCluster.py:298-316):
  cluster_size = engine executors + num_ps."""
  engine = LocalEngine(num_executors=2)
  try:
    def main_fn(args, ctx):
      with open("role.txt", "w") as f:
        f.write("%s:%d" % (ctx.job_name, ctx.task_index))

    c = tos_cluster.run(engine, main_fn, num_executors=3, num_ps=1,
                        driver_ps_nodes=True,
                        input_mode=InputMode.FILES, reservation_timeout=30)
    jobs = sorted(n["job_name"] for n in c.cluster_info)
    assert jobs == ["ps", "worker", "worker"]
    assert len(c.driver_ps_procs) == 1
    c.shutdown(timeout=120)
    assert not c.driver_ps_procs[0].is_alive()
    # both engine executors ran workers (ps lived on the driver)
    for slot in range(2):
      role = open(os.path.join(engine.executor_workdir(slot),
                               "role.txt")).read()
      assert role.startswith("worker")
  finally:
    engine.stop()


def test_driver_ps_requires_files_mode(engine):
  with pytest.raises(ValueError, match="driver_ps_nodes"):
    tos_cluster.run(engine, lambda a, c: None, num_ps=1,
                    driver_ps_nodes=True, input_mode=InputMode.ENGINE)


def test_validation_errors(engine):
  with pytest.raises(AssertionError, match="at least one worker"):
    tos_cluster.run(engine, lambda a, c: None, num_ps=2,
                    input_mode=InputMode.FILES)
  with pytest.raises(ValueError, match="executors"):
    tos_cluster.run(engine, lambda a, c: None, num_executors=5)


def test_inference_over_lazy_tfrecord_partitions(engine, tmp_path):
  """load_tfrecords(lazy=True) handles feed straight into the cluster:
  the feeder resolves each callable ON the executor
  (node._materialize_partition), so TFRecord decode never happens on the
  driver — the reference's executor-side loadTFRecords parse
  (dfutil.py:44-81) composed with InputMode.SPARK feeding."""
  import os as _os
  from tensorflowonspark_tpu.data import dfutil
  from tensorflowonspark_tpu.data.schema import parse_schema

  sch = parse_schema("struct<v:long>")
  src = [[(f * 10 + i,) for i in range(5)] for f in range(4)]
  dfutil.save_as_tfrecords(src, sch, str(tmp_path / "d"))
  marker = str(tmp_path / "decoded_pid")

  parts, _ = dfutil.load_tfrecords(str(tmp_path / "d"), schema=sch,
                                   lazy=True)

  def spying(i, p):
    # wrap each handle so the test can observe WHERE it ran
    def _run():
      with open("%s.%d" % (marker, i), "w") as fh:
        fh.write(str(_os.getpid()))
      return (row[0] for row in p())
    return _run

  parts = [spying(i, p) for i, p in enumerate(parts)]

  def main_fn(args, ctx):
    feed = ctx.get_data_feed(train_mode=False)
    while not feed.should_stop():
      batch = feed.next_batch(16)
      if batch:
        feed.batch_results([x * 2 for x in batch])

  c = tos_cluster.run(engine, main_fn, input_mode=InputMode.ENGINE,
                      reservation_timeout=30)
  results = c.inference(parts, feed_timeout=60)
  c.shutdown(timeout=120)
  assert sorted(results) == sorted(r[0] * 2 for p in src for r in p)
  import glob as _glob
  pids = {open(m).read() for m in _glob.glob(marker + ".*")}
  assert pids and str(_os.getpid()) not in pids, \
      "lazy partitions were materialized on the driver"


def test_quarantine_drain_keeps_markers_for_inference_feeds():
  """The supervisor's dead-hub drain preserves EndPartition markers when
  the active feed is an inference feed (cluster_meta carries feed_kind),
  so a refeed keeps per-partition result alignment — and keeps dropping
  them for train feeds."""
  from tensorflowonspark_tpu.cluster import ClusterSupervisor
  from tensorflowonspark_tpu.control import feedhub
  from tensorflowonspark_tpu.control.marker import EndPartition
  from tensorflowonspark_tpu.node import put_rows_chunk

  def _drain(feed_kind):
    hub = feedhub.start(b"k", ["input", "output", "error"], mode="remote")
    try:
      q = hub.get_queue("input")
      put_rows_chunk(q, [1, 2], timeout=5)
      q.put(EndPartition())
      put_rows_chunk(q, [3], timeout=5)
      meta = {"authkey": b"k", "input_mode": InputMode.ENGINE,
              "queues": ["input", "output", "error"],
              "feed_kind": feed_kind}
      sup = ClusterSupervisor(engine=None, server=None, node_job=None,
                              cluster_meta=meta, cluster_info=[],
                              engine_ids=[], tf_status={"error": None})
      return sup._quarantine_dead_hub(
          {"executor_id": 0, "hub_addr": list(hub.addr)})
    finally:
      hub.shutdown()

  pending = _drain("inference")
  assert [r for r in pending["input"] if not isinstance(r, EndPartition)] \
      == [1, 2, 3]
  assert isinstance(pending["input"][2], EndPartition)  # position preserved
  pending = _drain("train")
  assert pending["input"] == [1, 2, 3]
