"""Data-interop tests: TFRecord codec (native + fallback), tf.Example wire
codec (cross-checked against TensorFlow's own protos), schema parser,
dfutil round-trip (parity: reference tests/test_dfutil.py:30-73 and the
Scala DFUtilTest/SimpleTypeParserTest semantics)."""


import os
import numpy as np
import pytest

from tensorflowonspark_tpu.data import dfutil, example_codec, schema, tfrecord


class TestTFRecordCodec:
  def test_native_builds(self):
    assert tfrecord.native_available(), \
        "native codec should build in this image (g++ present)"

  def test_roundtrip(self, tmp_path):
    path = str(tmp_path / "x.tfrecord")
    records = [b"hello", b"", b"\x00\xff" * 100, b"z" * 10000]
    with tfrecord.TFRecordWriter(path) as w:
      for r in records:
        w.write(r)
    assert list(tfrecord.TFRecordReader(path)) == records

  def test_corruption_detected(self, tmp_path):
    path = str(tmp_path / "x.tfrecord")
    with tfrecord.TFRecordWriter(path) as w:
      w.write(b"payload-payload")
    raw = bytearray(open(path, "rb").read())
    raw[14] ^= 0xFF  # flip a data byte
    open(path, "wb").write(bytes(raw))
    with pytest.raises(IOError):
      list(tfrecord.TFRecordReader(path))

  def test_python_fallback_matches_native(self, tmp_path, monkeypatch):
    native_path = str(tmp_path / "n.tfrecord")
    with tfrecord.TFRecordWriter(native_path) as w:
      w.write(b"cross-check")
    # force the pure-Python path and read the natively-written file
    monkeypatch.setattr(tfrecord, "_lib", None)
    assert list(tfrecord.TFRecordReader(native_path)) == [b"cross-check"]
    py_path = str(tmp_path / "p.tfrecord")
    with tfrecord.TFRecordWriter(py_path) as w:
      w.write(b"cross-check")
    assert open(native_path, "rb").read() == open(py_path, "rb").read()

  def test_tensorflow_reads_our_files(self, tmp_path):
    tf = pytest.importorskip("tensorflow")
    path = str(tmp_path / "x.tfrecord")
    with tfrecord.TFRecordWriter(path) as w:
      for i in range(5):
        w.write(b"record-%d" % i)
    got = [r.numpy() for r in tf.data.TFRecordDataset([path])]
    assert got == [b"record-%d" % i for i in range(5)]


class TestExampleCodec:
  def test_roundtrip(self):
    feats = {"ints": [1, -2, 3], "floats": [1.5, -2.25],
             "strs": [b"a", b"bb"], "empty": []}
    out = example_codec.decode_example(example_codec.encode_example(feats))
    assert out["ints"] == [1, -2, 3]
    np.testing.assert_allclose(out["floats"], [1.5, -2.25])
    assert out["strs"] == [b"a", b"bb"]
    assert out["empty"] == []

  def test_cross_check_with_tensorflow_protos(self):
    tf = pytest.importorskip("tensorflow")
    feats = {"i": [7, 1 << 40], "f": [3.5], "b": [b"bytes", b"more"]}
    ours = example_codec.encode_example(feats)
    parsed = tf.train.Example.FromString(ours)
    assert list(parsed.features.feature["i"].int64_list.value) == [7, 1 << 40]
    assert parsed.features.feature["b"].bytes_list.value[0] == b"bytes"
    # decode TF's own serialization with our codec
    theirs = parsed.SerializeToString()
    back = example_codec.decode_example(theirs)
    assert back["i"] == [7, 1 << 40]
    np.testing.assert_allclose(back["f"], [3.5])


class TestSchemaParser:
  def test_basic(self):
    s = schema.parse_schema("struct<label:int,features:array<float>>")
    assert s.names() == ["label", "features"]
    assert s.field("features").is_array
    assert s.field("label").dtype == "int"

  def test_all_types(self):
    s = schema.parse_schema(
        "struct<a:binary,b:boolean,c:double,d:float,e:int,f:bigint,"
        "g:long,h:string,i:array<string>>")
    assert len(s.fields) == 9
    assert s.field("f").dtype == "long"  # bigint normalizes

  def test_whitespace_tolerated(self):
    s = schema.parse_schema("struct< x : array< int > , y : string >")
    assert s.field("x").is_array

  def test_errors(self):
    for bad in ["int", "struct<>", "struct<x:unknown>", "struct<:int>",
                "struct<x:array<array<int>>>"]:
      with pytest.raises(ValueError):
        schema.parse_schema(bad)


class TestDfutil:
  SCHEMA = schema.parse_schema(
      "struct<idx:long,scalar:double,vec:array<float>,name:string,"
      "blob:binary,flag:boolean>")

  def _rows(self, n=20):
    return [(i, i * 1.5, [float(i), float(i + 1)], "row%d" % i,
             bytes([i % 256, 255]), i % 2 == 0) for i in range(n)]

  def test_roundtrip_all_dtypes(self, tmp_path):
    rows = self._rows()
    parts = [rows[:10], rows[10:]]
    out = str(tmp_path / "ds")
    files = dfutil.save_as_tfrecords(parts, self.SCHEMA, out)
    assert len(files) == 2
    loaded, sch = dfutil.load_tfrecords(out, schema=self.SCHEMA)
    flat = [r for p in loaded for r in p]
    assert len(flat) == 20
    got = sorted(flat)[3]
    assert got[0] == 3 and got[1] == 4.5
    np.testing.assert_allclose(got[2], [3.0, 4.0])
    assert got[3] == "row3" and got[4] == bytes([3, 255]) and got[5] is False
    assert dfutil.is_loaded_path(out)

  def test_schema_inference_with_binary_hint(self, tmp_path):
    rows = self._rows(4)
    out = str(tmp_path / "ds")
    dfutil.save_as_tfrecords([rows], self.SCHEMA, out)
    _, inferred = dfutil.load_tfrecords(out, binary_features={"blob"})
    assert inferred.field("blob").dtype == "binary"
    assert inferred.field("name").dtype == "string"
    assert inferred.field("vec").is_array
    assert inferred.field("idx").dtype == "long"

  def test_distributed_save(self, tmp_path):
    from tensorflowonspark_tpu.engine import LocalEngine
    engine = LocalEngine(num_executors=2)
    try:
      rows = self._rows(12)
      out = str(tmp_path / "ds")
      files = dfutil.save_as_tfrecords([rows[:6], rows[6:]], self.SCHEMA,
                                       out, engine=engine)
      assert len(files) == 2
      loaded, _ = dfutil.load_tfrecords(out, schema=self.SCHEMA)
      assert sum(len(p) for p in loaded) == 12
    finally:
      engine.stop()

  def test_repartition_on_load(self, tmp_path):
    rows = self._rows(9)
    out = str(tmp_path / "ds")
    dfutil.save_as_tfrecords([rows], self.SCHEMA, out)
    loaded, _ = dfutil.load_tfrecords(out, schema=self.SCHEMA,
                                      num_partitions=3)
    assert len(loaded) == 3
    assert sum(len(p) for p in loaded) == 9


class TestRemoteFS:
  """Remote-scheme IO through fsspec (VERDICT r2 missing item 1): the same
  reader/writer/sharding surface must work on cluster storage, exercised
  here on fsspec's memory:// filesystem (gs:// uses the identical code path
  via gcsfs; parity: reference dfutil.py:39,63 through Hadoop's FS)."""

  @pytest.fixture(autouse=True)
  def _clean_memfs(self):
    import fsspec
    fs = fsspec.filesystem("memory")
    for p in list(fs.store):
      fs.store.pop(p, None)
    yield

  def test_tfrecord_roundtrip_remote(self):
    records = [b"alpha", b"", b"\x01\x02" * 500]
    with tfrecord.TFRecordWriter("memory://bucket/data/x.tfrecord") as w:
      for r in records:
        w.write(r)
    got = list(tfrecord.TFRecordReader("memory://bucket/data/x.tfrecord"))
    assert got == records

  def test_shard_files_remote_pattern(self):
    from tensorflowonspark_tpu.data import readers
    for i in range(5):
      with tfrecord.TFRecordWriter("memory://bucket/ds/part-%02d" % i) as w:
        w.write(b"r%d" % i)
    shards0 = readers.shard_files("memory://bucket/ds/part-*", 2, 0)
    shards1 = readers.shard_files("memory://bucket/ds/part-*", 2, 1)
    assert len(shards0) + len(shards1) == 5
    assert not set(shards0) & set(shards1)
    assert all(p.startswith("memory://") for p in shards0 + shards1)
    # the sharded paths read back through the same surface
    rows = [rec for p in sorted(shards0 + shards1)
            for rec in tfrecord.TFRecordReader(p)]
    assert rows == [b"r0", b"r1", b"r2", b"r3", b"r4"]

  def test_file_scheme_uses_local_io(self, tmp_path):
    path = "file://" + str(tmp_path / "y.tfrecord")
    with tfrecord.TFRecordWriter(path) as w:
      w.write(b"local")
    assert list(tfrecord.TFRecordReader(path)) == [b"local"]

  def test_dfutil_roundtrip_remote(self):
    sch = schema.parse_schema("struct<idx:long,name:string>")
    rows = [(i, "n%d" % i) for i in range(8)]
    dfutil.save_as_tfrecords([rows[:4], rows[4:]], sch,
                             "memory://bucket/out")
    loaded, _ = dfutil.load_tfrecords("memory://bucket/out", schema=sch)
    assert sorted(r for p in loaded for r in p) == rows

  def test_read_tfrecord_examples_remote(self):
    from tensorflowonspark_tpu.data import readers
    sch = schema.parse_schema("struct<idx:long>")
    dfutil.save_as_tfrecords([[(7,)], [(9,)]], sch, "memory://bucket/ex")
    got = sorted(readers.read_tfrecord_examples(
        readers.shard_files("memory://bucket/ex/part-*", 1, 0), schema=sch))
    assert got == [(7,), (9,)]


def _lazy_rows(index, n, touched_path):
  """Executor-side row factory: records WHERE it ran, then yields rows."""
  def _gen():
    with open(touched_path + ".%d" % index, "w") as f:
      f.write(str(os.getpid()))
    for j in range(n):
      yield (index * n + j,)
  return _gen


class TestLazySave:
  """save_as_tfrecords must ship partition HANDLES, not materialized rows
  (VERDICT r2 missing item 2; parity: reference dfutil.py:29-41 writes from
  executors through Spark's output format)."""

  def test_callable_partitions_materialize_on_executor(self, tmp_path):
    from tensorflowonspark_tpu.engine import LocalEngine
    sch = schema.parse_schema("struct<v:long>")
    touched = str(tmp_path / "touched")
    engine = LocalEngine(num_executors=2)
    try:
      parts = [_lazy_rows(i, 50, touched) for i in range(4)]
      files = dfutil.save_as_tfrecords(parts, sch, str(tmp_path / "out"),
                                       engine=engine)
      assert len(files) == 4
      # every factory ran in a process that is NOT the driver
      for i in range(4):
        pid = int(open(touched + ".%d" % i).read())
        assert pid != os.getpid(), "partition %d materialized on driver" % i
      loaded, _ = dfutil.load_tfrecords(str(tmp_path / "out"), schema=sch)
      assert sorted(r[0] for p in loaded for r in p) == list(range(200))
    finally:
      engine.stop()

  def test_callable_partitions_without_engine(self, tmp_path):
    sch = schema.parse_schema("struct<v:long>")
    parts = [lambda k=k: iter([(k,), (10 + k,)]) for k in range(3)]
    files = dfutil.save_as_tfrecords(parts, sch, str(tmp_path / "out"))
    assert len(files) == 3
    loaded, _ = dfutil.load_tfrecords(str(tmp_path / "out"), schema=sch)
    assert sorted(r[0] for p in loaded for r in p) == [0, 1, 2, 10, 11, 12]

  def test_generator_partitions_with_engine(self, tmp_path, caplog):
    """One-shot iterators are valid partitions too: cloudpickle cannot
    ship a generator, so they alone are materialized before shipping —
    loudly, since that is exactly the O(driver-memory) behavior the
    handle path exists to avoid (round-3 verdict item 8)."""
    import logging
    from tensorflowonspark_tpu.engine import LocalEngine
    sch = schema.parse_schema("struct<v:long>")
    engine = LocalEngine(num_executors=2)
    try:
      parts = [iter([(0,), (1,)]), (r for r in [(2,), (3,)])]
      with caplog.at_level(logging.WARNING,
                           logger="tensorflowonspark_tpu.data.dfutil"):
        files = dfutil.save_as_tfrecords(parts, sch, str(tmp_path / "out"),
                                         engine=engine)
      assert len(files) == 2
      warns = [r for r in caplog.records
               if "materializing it on the DRIVER" in r.getMessage()]
      assert len(warns) == 2, "one warning per materialized partition"
      loaded, _ = dfutil.load_tfrecords(str(tmp_path / "out"), schema=sch)
      assert sorted(r[0] for p in loaded for r in p) == [0, 1, 2, 3]
    finally:
      engine.stop()


class TestLazyLoad:
  """load_tfrecords(lazy=True): the driver decodes at most ONE record;
  partitions are callable handles resolved executor-side (the reference's
  loadTFRecords decoded records in Spark tasks, dfutil.py:44-81)."""

  def _write(self, tmp_path, n_files=3, rows_per=4):
    sch = schema.parse_schema("struct<v:long>")
    parts = [[(f * 100 + i,) for i in range(rows_per)]
             for f in range(n_files)]
    dfutil.save_as_tfrecords(parts, sch, str(tmp_path / "d"))
    expect = sorted(r[0] for p in parts for r in p)
    return sch, str(tmp_path / "d"), expect

  def test_driver_reads_at_most_one_record(self, tmp_path, monkeypatch):
    sch, path, expect = self._write(tmp_path)
    reads = {"n": 0}
    real_reader = dfutil.tfrecord.TFRecordReader

    class CountingReader(real_reader):
      def __next__(self):
        reads["n"] += 1
        return super().__next__()

    monkeypatch.setattr(dfutil.tfrecord, "TFRecordReader", CountingReader)
    parts, inferred = dfutil.load_tfrecords(path, lazy=True)
    assert reads["n"] == 1          # schema inference only
    assert all(callable(p) for p in parts) and len(parts) == 3
    rows = sorted(r[0] for p in parts for r in p())
    assert rows == expect
    assert dfutil.is_loaded_path(path)

  def test_lazy_with_explicit_schema_reads_nothing(self, tmp_path,
                                                   monkeypatch):
    sch, path, expect = self._write(tmp_path)
    reads = {"n": 0}
    real_reader = dfutil.tfrecord.TFRecordReader

    class CountingReader(real_reader):
      def __next__(self):
        reads["n"] += 1
        return super().__next__()

    monkeypatch.setattr(dfutil.tfrecord, "TFRecordReader", CountingReader)
    parts, _ = dfutil.load_tfrecords(path, schema=sch, lazy=True)
    assert reads["n"] == 0
    assert sorted(r[0] for p in parts for r in p()) == expect

  def test_lazy_num_partitions_groups_files(self, tmp_path):
    sch, path, expect = self._write(tmp_path, n_files=4)
    parts, _ = dfutil.load_tfrecords(path, lazy=True, num_partitions=2)
    assert len(parts) == 2
    assert sorted(r[0] for p in parts for r in p()) == expect

  def test_lazy_resave_through_engine(self, tmp_path):
    """Lazy handles flow straight into save_as_tfrecords(engine=...):
    rows decode AND re-encode on executors, never the driver."""
    from tensorflowonspark_tpu.engine import LocalEngine
    sch, path, expect = self._write(tmp_path)
    parts, inferred = dfutil.load_tfrecords(path, lazy=True)
    engine = LocalEngine(num_executors=2)
    try:
      out = dfutil.save_as_tfrecords(parts, inferred,
                                     str(tmp_path / "copy"), engine=engine)
      assert len(out) == 3
    finally:
      engine.stop()
    loaded, _ = dfutil.load_tfrecords(str(tmp_path / "copy"), schema=sch)
    assert sorted(r[0] for p in loaded for r in p) == expect

  def test_lazy_schema_skips_empty_leading_file(self, tmp_path):
    sch = schema.parse_schema("struct<v:long>")
    dfutil.save_as_tfrecords([[], [(7,)]], sch, str(tmp_path / "d"))
    parts, inferred = dfutil.load_tfrecords(str(tmp_path / "d"), lazy=True)
    assert [r[0] for p in parts for r in p()] == [7]

  def test_lazy_num_partitions_clamped(self, tmp_path):
    sch, path, expect = self._write(tmp_path, n_files=3)
    for bad in (-1, 0, 99):
      parts, _ = dfutil.load_tfrecords(path, lazy=True,
                                       num_partitions=bad or None)
      assert sorted(r[0] for p in parts for r in p()) == expect

  def test_wrap_lazy_preserves_reiterable_sequences(self):
    """Epoch replication re-iterates its input; a custom Sequence must not
    be drained into a one-shot generator (only true iterators stream)."""
    import collections.abc
    from tensorflowonspark_tpu.cluster import TPUCluster

    class Parts(collections.abc.Sequence):
      def __init__(self, data):
        self._d = data
      def __getitem__(self, i):
        return self._d[i]
      def __len__(self):
        return len(self._d)

    wrapped = TPUCluster._wrap_lazy(Parts([[1, 2], [3]]))
    assert isinstance(wrapped, list)
    assert TPUCluster._replicate(wrapped, 2) == [[1, 2], [3], [1, 2], [3]]
    gen = TPUCluster._wrap_lazy(iter([[1], [2]]))
    assert not isinstance(gen, list)
    assert list(gen) == [[1], [2]]

  def test_train_iterator_rdd_lazy_uses_rowfree_action(self):
    """SparkEngine's map_partitions_lazy hands back an uncollected RDD
    (not an iterator): train()'s streaming branch must trigger it with a
    row-free action (count), never try to iterate it on the driver."""
    from tensorflowonspark_tpu.cluster import InputMode, TPUCluster

    class _RDD:
      counted = 0

      def count(self):
        _RDD.counted += 1
        return 3

    class _Eng:
      def __init__(self):
        self.lazy_calls = []

      def map_partitions_lazy(self, parts, fn, timeout=None):
        self.lazy_calls.append((parts, fn))
        return _RDD()

    c = TPUCluster.__new__(TPUCluster)
    c.engine = _Eng()
    c.input_mode = InputMode.ENGINE
    c.cluster_info = []
    c.cluster_meta = {"authkey": b"k"}
    c.train(iter([[(1,)], [(2,)]]))
    assert _RDD.counted == 1
    assert len(c.engine.lazy_calls) == 1
