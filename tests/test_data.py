"""Data-interop tests: TFRecord codec (native + fallback), tf.Example wire
codec (cross-checked against TensorFlow's own protos), schema parser,
dfutil round-trip (parity: reference tests/test_dfutil.py:30-73 and the
Scala DFUtilTest/SimpleTypeParserTest semantics)."""


import numpy as np
import pytest

from tensorflowonspark_tpu.data import dfutil, example_codec, schema, tfrecord


class TestTFRecordCodec:
  def test_native_builds(self):
    assert tfrecord.native_available(), \
        "native codec should build in this image (g++ present)"

  def test_roundtrip(self, tmp_path):
    path = str(tmp_path / "x.tfrecord")
    records = [b"hello", b"", b"\x00\xff" * 100, b"z" * 10000]
    with tfrecord.TFRecordWriter(path) as w:
      for r in records:
        w.write(r)
    assert list(tfrecord.TFRecordReader(path)) == records

  def test_corruption_detected(self, tmp_path):
    path = str(tmp_path / "x.tfrecord")
    with tfrecord.TFRecordWriter(path) as w:
      w.write(b"payload-payload")
    raw = bytearray(open(path, "rb").read())
    raw[14] ^= 0xFF  # flip a data byte
    open(path, "wb").write(bytes(raw))
    with pytest.raises(IOError):
      list(tfrecord.TFRecordReader(path))

  def test_python_fallback_matches_native(self, tmp_path, monkeypatch):
    native_path = str(tmp_path / "n.tfrecord")
    with tfrecord.TFRecordWriter(native_path) as w:
      w.write(b"cross-check")
    # force the pure-Python path and read the natively-written file
    monkeypatch.setattr(tfrecord, "_lib", None)
    assert list(tfrecord.TFRecordReader(native_path)) == [b"cross-check"]
    py_path = str(tmp_path / "p.tfrecord")
    with tfrecord.TFRecordWriter(py_path) as w:
      w.write(b"cross-check")
    assert open(native_path, "rb").read() == open(py_path, "rb").read()

  def test_tensorflow_reads_our_files(self, tmp_path):
    tf = pytest.importorskip("tensorflow")
    path = str(tmp_path / "x.tfrecord")
    with tfrecord.TFRecordWriter(path) as w:
      for i in range(5):
        w.write(b"record-%d" % i)
    got = [r.numpy() for r in tf.data.TFRecordDataset([path])]
    assert got == [b"record-%d" % i for i in range(5)]


class TestExampleCodec:
  def test_roundtrip(self):
    feats = {"ints": [1, -2, 3], "floats": [1.5, -2.25],
             "strs": [b"a", b"bb"], "empty": []}
    out = example_codec.decode_example(example_codec.encode_example(feats))
    assert out["ints"] == [1, -2, 3]
    np.testing.assert_allclose(out["floats"], [1.5, -2.25])
    assert out["strs"] == [b"a", b"bb"]
    assert out["empty"] == []

  def test_cross_check_with_tensorflow_protos(self):
    tf = pytest.importorskip("tensorflow")
    feats = {"i": [7, 1 << 40], "f": [3.5], "b": [b"bytes", b"more"]}
    ours = example_codec.encode_example(feats)
    parsed = tf.train.Example.FromString(ours)
    assert list(parsed.features.feature["i"].int64_list.value) == [7, 1 << 40]
    assert parsed.features.feature["b"].bytes_list.value[0] == b"bytes"
    # decode TF's own serialization with our codec
    theirs = parsed.SerializeToString()
    back = example_codec.decode_example(theirs)
    assert back["i"] == [7, 1 << 40]
    np.testing.assert_allclose(back["f"], [3.5])


class TestSchemaParser:
  def test_basic(self):
    s = schema.parse_schema("struct<label:int,features:array<float>>")
    assert s.names() == ["label", "features"]
    assert s.field("features").is_array
    assert s.field("label").dtype == "int"

  def test_all_types(self):
    s = schema.parse_schema(
        "struct<a:binary,b:boolean,c:double,d:float,e:int,f:bigint,"
        "g:long,h:string,i:array<string>>")
    assert len(s.fields) == 9
    assert s.field("f").dtype == "long"  # bigint normalizes

  def test_whitespace_tolerated(self):
    s = schema.parse_schema("struct< x : array< int > , y : string >")
    assert s.field("x").is_array

  def test_errors(self):
    for bad in ["int", "struct<>", "struct<x:unknown>", "struct<:int>",
                "struct<x:array<array<int>>>"]:
      with pytest.raises(ValueError):
        schema.parse_schema(bad)


class TestDfutil:
  SCHEMA = schema.parse_schema(
      "struct<idx:long,scalar:double,vec:array<float>,name:string,"
      "blob:binary,flag:boolean>")

  def _rows(self, n=20):
    return [(i, i * 1.5, [float(i), float(i + 1)], "row%d" % i,
             bytes([i % 256, 255]), i % 2 == 0) for i in range(n)]

  def test_roundtrip_all_dtypes(self, tmp_path):
    rows = self._rows()
    parts = [rows[:10], rows[10:]]
    out = str(tmp_path / "ds")
    files = dfutil.save_as_tfrecords(parts, self.SCHEMA, out)
    assert len(files) == 2
    loaded, sch = dfutil.load_tfrecords(out, schema=self.SCHEMA)
    flat = [r for p in loaded for r in p]
    assert len(flat) == 20
    got = sorted(flat)[3]
    assert got[0] == 3 and got[1] == 4.5
    np.testing.assert_allclose(got[2], [3.0, 4.0])
    assert got[3] == "row3" and got[4] == bytes([3, 255]) and got[5] is False
    assert dfutil.is_loaded_path(out)

  def test_schema_inference_with_binary_hint(self, tmp_path):
    rows = self._rows(4)
    out = str(tmp_path / "ds")
    dfutil.save_as_tfrecords([rows], self.SCHEMA, out)
    _, inferred = dfutil.load_tfrecords(out, binary_features={"blob"})
    assert inferred.field("blob").dtype == "binary"
    assert inferred.field("name").dtype == "string"
    assert inferred.field("vec").is_array
    assert inferred.field("idx").dtype == "long"

  def test_distributed_save(self, tmp_path):
    from tensorflowonspark_tpu.engine import LocalEngine
    engine = LocalEngine(num_executors=2)
    try:
      rows = self._rows(12)
      out = str(tmp_path / "ds")
      files = dfutil.save_as_tfrecords([rows[:6], rows[6:]], self.SCHEMA,
                                       out, engine=engine)
      assert len(files) == 2
      loaded, _ = dfutil.load_tfrecords(out, schema=self.SCHEMA)
      assert sum(len(p) for p in loaded) == 12
    finally:
      engine.stop()

  def test_repartition_on_load(self, tmp_path):
    rows = self._rows(9)
    out = str(tmp_path / "ds")
    dfutil.save_as_tfrecords([rows], self.SCHEMA, out)
    loaded, _ = dfutil.load_tfrecords(out, schema=self.SCHEMA,
                                      num_partitions=3)
    assert len(loaded) == 3
    assert sum(len(p) for p in loaded) == 9
