"""SPMD layer tests on the virtual 8-device CPU mesh: mesh construction,
collectives, ring attention exactness, and the sharded train-step factory.

This is the test tier SURVEY.md §4 prescribes for multi-device behavior
(xla_force_host_platform_device_count — the analog of the reference's
2-worker standalone cluster).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from tensorflowonspark_tpu.parallel import collectives as C
from tensorflowonspark_tpu.parallel import mesh as M
from tensorflowonspark_tpu.parallel import ring_attention as RA
from tensorflowonspark_tpu.parallel import sharding as SH


@pytest.fixture(scope="module")
def devices():
  d = jax.devices()
  if len(d) < 8:
    pytest.skip("needs 8 virtual devices")
  return d


class TestMesh:
  def test_wildcard_absorbs(self, devices):
    mesh = M.build_mesh(M.MeshSpec(data=-1, tensor=2), devices=devices)
    assert mesh.shape[M.AXIS_DATA] == 4
    assert mesh.shape[M.AXIS_TENSOR] == 2

  def test_explicit_exact(self, devices):
    mesh = M.build_mesh(M.MeshSpec(data=2, sequence=2, tensor=2),
                        devices=devices)
    assert dict(mesh.shape)[M.AXIS_SEQUENCE] == 2

  def test_mismatch_raises(self, devices):
    with pytest.raises(ValueError, match="devices"):
      M.build_mesh(M.MeshSpec(data=3, tensor=2), devices=devices)

  def test_two_wildcards_raise(self, devices):
    with pytest.raises(ValueError, match="-1"):
      M.build_mesh(M.MeshSpec(data=-1, tensor=-1), devices=devices)

  def test_axis_size(self, devices):
    mesh = M.build_mesh(M.MeshSpec(data=2, fsdp=2, tensor=2),
                        devices=devices)
    assert M.axis_size(mesh, M.AXIS_DATA, M.AXIS_FSDP) == 4
    assert M.data_axes(mesh) == (M.AXIS_DATA, M.AXIS_FSDP)


class _FakeTPU:
  """Mock device carrying the attributes mesh_utils inspects."""
  platform = "tpu"
  device_kind = "TPU v5e"

  def __init__(self, i, coords, slice_index=0, process_index=0):
    self.id = i
    self.coords = coords
    self.core_on_chip = 0
    self.process_index = process_index
    self.slice_index = slice_index

  def __repr__(self):
    return "FakeTPU(%d, %r, slice=%d)" % (self.id, self.coords,
                                          self.slice_index)


class TestTopologyMesh:
  """build_mesh must honor physical topology on TPU (VERDICT r2 item 3):
  the tensor axis lands on ICI neighbors even when jax.devices() enumerates
  chips out of physical order."""

  def _scrambled_grid(self):
    coords = [(x, y, 0) for y in range(2) for x in range(4)]
    order = [0, 3, 1, 2, 7, 4, 6, 5]
    return [_FakeTPU(i, coords[order[i]]) for i in range(8)]

  def test_tensor_axis_lands_on_neighbors(self):
    mesh = M.build_mesh(M.MeshSpec(data=-1, tensor=4),
                        devices=self._scrambled_grid())
    arr = np.asarray(mesh.devices).reshape(2, 4)
    for row in arr:
      xs = sorted(d.coords[0] for d in row)
      ys = {d.coords[1] for d in row}
      assert xs == [0, 1, 2, 3], "tensor axis straddles the grid: %r" % row
      assert len(ys) == 1, "tensor axis crosses rows: %r" % row

  def test_hybrid_mesh_puts_data_on_dcn(self):
    """Two slices: the data axis absorbs the slice count; every
    non-data axis stays inside one slice (ICI), per SURVEY §2.4."""
    devs = []
    for s in range(2):
      for i in range(4):
        devs.append(_FakeTPU(s * 4 + i, (i % 2, i // 2, 0), slice_index=s,
                             process_index=s))
    mesh = M.build_mesh(M.MeshSpec(data=2, tensor=4), devices=devs)
    arr = np.asarray(mesh.devices).reshape(2, 4)
    for data_idx in range(2):
      slices = {d.slice_index for d in arr[data_idx]}
      assert len(slices) == 1, \
          "tensor axis crosses the DCN boundary: %r" % arr[data_idx]

  def test_cpu_devices_fall_back_to_enumeration(self, devices):
    mesh = M.build_mesh(M.MeshSpec(data=-1), devices=devices)
    assert list(np.asarray(mesh.devices).ravel()) == list(devices)

  def test_unabsorbable_slice_count_falls_back(self, caplog):
    """3 slices over axes of degree 2/4: no axis absorbs 3 — warn and
    keep enumeration order rather than fail bring-up."""
    devs = []
    for s in range(3):
      for i in range(2):
        devs.append(_FakeTPU(s * 2 + i, (i, 0, 0), slice_index=s))
    import logging
    with caplog.at_level(logging.WARNING,
                         logger="tensorflowonspark_tpu.parallel.mesh"):
      mesh = M.build_mesh(M.MeshSpec(data=2, tensor=3), devices=devs)
    assert "falling back to enumeration order" in caplog.text
    assert list(np.asarray(mesh.devices).ravel()) == devs


class TestCollectives:
  def test_psum_and_ring_permute(self, devices):
    mesh = M.build_mesh(M.MeshSpec(data=8), devices=devices)

    def body(x):
      total = C.all_reduce(jnp.sum(x), M.AXIS_DATA)
      rotated = C.ring_permute(x, M.AXIS_DATA, shift=1)
      return total * jnp.ones_like(x), rotated

    x = jnp.arange(16.0)
    fn = C.shard_map_fn(body, mesh, in_specs=P(M.AXIS_DATA),
                        out_specs=(P(M.AXIS_DATA), P(M.AXIS_DATA)))
    total, rotated = jax.jit(fn)(x)
    assert float(total[0]) == float(x.sum())
    # shard i moves to slot i+1: slot 0 now holds the last shard
    np.testing.assert_allclose(np.asarray(rotated[:2]), [14.0, 15.0])

  def test_hierarchical_all_reduce_matches_psum(self, devices):
    """reduce_scatter(ICI) → psum(DCN) → all_gather(ICI) must equal the
    flat psum over both axes (and the mean variant the pmean)."""
    mesh = M.build_mesh(M.MeshSpec(data=2, fsdp=4), devices=devices)
    # 8 dim-0 shards of 4 rows each: the ICI reduce_scatter needs the local
    # shard's scatter dim divisible by the fsdp axis size (4)
    x = jnp.arange(256.0).reshape(32, 8)

    def flat(v):
      return lax.psum(v, (M.AXIS_FSDP, M.AXIS_DATA))

    def tiered(v):
      return C.hierarchical_all_reduce(v, ici_axis=M.AXIS_FSDP,
                                       dcn_axis=M.AXIS_DATA)

    spec = P((M.AXIS_DATA, M.AXIS_FSDP))
    got_flat = jax.jit(C.shard_map_fn(flat, mesh, spec, spec))(x)
    got_tier = jax.jit(C.shard_map_fn(tiered, mesh, spec, spec))(x)
    np.testing.assert_allclose(np.asarray(got_tier), np.asarray(got_flat),
                               rtol=1e-6)
    mean = jax.jit(C.shard_map_fn(
        lambda v: C.hierarchical_all_reduce(v, M.AXIS_FSDP, M.AXIS_DATA,
                                            mean=True), mesh, spec, spec))(x)
    np.testing.assert_allclose(np.asarray(mean), np.asarray(got_flat) / 8,
                               rtol=1e-6)

  def test_sync_gradients_averages_pytree(self, devices):
    mesh = M.build_mesh(M.MeshSpec(data=8), devices=devices)
    grads = {"w": jnp.arange(8.0), "b": jnp.ones((8, 2))}

    def body(g):
      return C.sync_gradients(g, M.AXIS_DATA)

    spec = {"w": P(M.AXIS_DATA), "b": P(M.AXIS_DATA)}
    out = jax.jit(C.shard_map_fn(body, mesh, (spec,), spec))(grads)
    # every shard of w becomes the mean of the 8 single-element shards
    np.testing.assert_allclose(np.asarray(out["w"]),
                               np.full(8, np.arange(8.0).mean()))
    np.testing.assert_allclose(np.asarray(out["b"]), np.ones((8, 2)))

  def test_broadcast_from(self, devices):
    mesh = M.build_mesh(M.MeshSpec(data=8), devices=devices)
    x = jnp.arange(8.0)
    out = jax.jit(C.shard_map_fn(
        lambda v: C.broadcast_from(v, M.AXIS_DATA, src_index=3),
        mesh, P(M.AXIS_DATA), P(M.AXIS_DATA)))(x)
    np.testing.assert_allclose(np.asarray(out), np.full(8, 3.0))

  def test_global_norm_cross_shard(self, devices):
    mesh = M.build_mesh(M.MeshSpec(data=8), devices=devices)
    tree = {"a": jnp.arange(8.0), "b": -jnp.arange(16.0).reshape(8, 2)}
    expected = float(jnp.sqrt(sum(jnp.sum(v * v)
                                  for v in tree.values())))
    spec = {"a": P(M.AXIS_DATA), "b": P(M.AXIS_DATA)}
    out = jax.jit(C.shard_map_fn(
        lambda t: C.global_norm(t, M.AXIS_DATA) * jnp.ones(1),
        mesh, (spec,), P()))(tree)
    np.testing.assert_allclose(float(out[0]), expected, rtol=1e-6)

  def test_clip_by_global_norm(self, devices):
    mesh = M.build_mesh(M.MeshSpec(data=8), devices=devices)
    tree = {"g": jnp.full(8, 3.0)}   # global norm = sqrt(8*9) ~ 8.49
    spec = {"g": P(M.AXIS_DATA)}

    def body(t):
      clipped, norm = C.clip_by_global_norm(t, 1.0, M.AXIS_DATA)
      return clipped, norm * jnp.ones(1)

    clipped, norm = jax.jit(C.shard_map_fn(
        body, mesh, (spec,), (spec, P())))(tree)
    np.testing.assert_allclose(float(norm[0]), float(np.sqrt(72)), rtol=1e-6)
    # clipped global norm is exactly max_norm
    np.testing.assert_allclose(
        float(np.sqrt((np.asarray(clipped["g"]) ** 2).sum())), 1.0,
        rtol=1e-5)


class TestRingAttention:
  @pytest.mark.parametrize("causal", [True, False])
  def test_matches_full_attention(self, devices, causal):
    mesh = M.build_mesh(M.MeshSpec(data=2, sequence=4), devices=devices)
    rng = np.random.RandomState(0)
    B, S, H, D = 2, 32, 4, 16
    q, k, v = (jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
               for _ in range(3))
    ref = RA.full_attention(q, k, v, causal=causal)
    out = jax.jit(
        lambda q, k, v: RA.ring_attention(q, k, v, mesh, causal=causal)
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-4, rtol=1e-4)

  @pytest.mark.parametrize("causal", [True, False])
  def test_ring_flash_matches_full_attention(self, devices, causal):
    """Ring attention with Pallas flash blocks: forward exactness."""
    mesh = M.build_mesh(M.MeshSpec(data=2, sequence=4), devices=devices)
    rng = np.random.RandomState(1)
    B, S, H, D = 2, 32, 2, 16
    q, k, v = (jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
               for _ in range(3))
    ref = RA.full_attention(q, k, v, causal=causal)
    out = jax.jit(lambda q, k, v: RA.ring_attention(
        q, k, v, mesh, causal=causal, use_flash=True, blk_q=8, blk_k=8,
        interpret=True))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-4, rtol=1e-4)

  def _expand(self, kv, h):
    return np.repeat(np.asarray(kv), h // kv.shape[2], axis=2)

  @pytest.mark.parametrize("use_flash", [False, True])
  def test_gqa_grouped_kv_matches_expanded(self, devices, use_flash):
    """GQA ring: grouped K/V (hk < h) gives exactly the attention of the
    expanded equivalent — the ring expands per step, locally."""
    mesh = M.build_mesh(M.MeshSpec(sequence=4), devices=devices[:4])
    rng = np.random.RandomState(5)
    B, S, H, HK, D = 2, 32, 4, 2, 16
    q = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
    k = jnp.asarray(rng.randn(B, S, HK, D), jnp.float32)
    v = jnp.asarray(rng.randn(B, S, HK, D), jnp.float32)
    ref = RA.full_attention(q, jnp.asarray(self._expand(k, H)),
                            jnp.asarray(self._expand(v, H)), causal=True)
    kwargs = dict(use_flash=True, blk_q=8, blk_k=8, interpret=True) \
        if use_flash else {}
    out = jax.jit(lambda q, k, v: RA.ring_attention(
        q, k, v, mesh, causal=True, **kwargs))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-4, rtol=1e-4)

  def test_gqa_grads_match_expanded_dense(self, devices):
    mesh = M.build_mesh(M.MeshSpec(sequence=4), devices=devices[:4])
    rng = np.random.RandomState(6)
    B, S, H, HK, D = 1, 32, 4, 2, 8
    q = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
    k = jnp.asarray(rng.randn(B, S, HK, D), jnp.float32)
    v = jnp.asarray(rng.randn(B, S, HK, D), jnp.float32)
    w = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)

    def loss_ring(q, k, v):
      return jnp.sum(RA.ring_attention(q, k, v, mesh, causal=True) * w)

    def loss_dense(q, k, v):
      ke = jnp.repeat(k, H // HK, axis=2)
      ve = jnp.repeat(v, H // HK, axis=2)
      return jnp.sum(RA.full_attention(q, ke, ve, causal=True) * w)

    gr = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gr, gd):
      np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                 atol=1e-4, rtol=1e-4)

  def test_gqa_indivisible_tensor_axis_expands_up_front(self, devices):
    """When a tensor axis shards heads and cannot divide the grouped KV
    count (hk=2 on tensor=4), the ring expands KV up front rather than
    break the head spec — correctness preserved at pre-GQA traffic."""
    mesh = M.build_mesh(M.MeshSpec(sequence=2, tensor=4),
                        devices=devices[:8])
    rng = np.random.RandomState(8)
    B, S, H, HK, D = 1, 16, 4, 2, 8
    q = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
    k = jnp.asarray(rng.randn(B, S, HK, D), jnp.float32)
    v = jnp.asarray(rng.randn(B, S, HK, D), jnp.float32)
    ref = RA.full_attention(q, jnp.asarray(self._expand(k, H)),
                            jnp.asarray(self._expand(v, H)), causal=True)
    out = jax.jit(lambda q, k, v: RA.ring_attention(
        q, k, v, mesh, causal=True))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-4, rtol=1e-4)

  def test_gqa_ring_permutes_grouped_blocks(self, devices):
    """Structural ICI-traffic check: every ppermute in the ring program
    carries HK (grouped) heads, never the expanded H."""
    mesh = M.build_mesh(M.MeshSpec(sequence=4), devices=devices[:4])
    B, S, H, HK, D = 1, 32, 4, 2, 8
    q = jnp.zeros((B, S, H, D), jnp.float32)
    k = jnp.zeros((B, S, HK, D), jnp.float32)
    v = jnp.zeros((B, S, HK, D), jnp.float32)
    jaxpr = jax.make_jaxpr(lambda q, k, v: RA.ring_attention(
        q, k, v, mesh, causal=True))(q, k, v)

    shapes = []

    def walk(jx):
      for eqn in jx.eqns:
        if eqn.primitive.name == "ppermute":
          shapes.append(tuple(eqn.invars[0].aval.shape))
        for val in eqn.params.values():
          for sub in jax.tree.leaves(val, is_leaf=lambda x: hasattr(x, "eqns")):
            if hasattr(sub, "eqns"):
              walk(sub)
            elif hasattr(sub, "jaxpr"):
              walk(sub.jaxpr)

    walk(jaxpr.jaxpr)
    assert shapes, "no ppermute found in the ring program"
    for shp in shapes:
      assert shp[2] == HK, "ring permuted expanded heads: %r" % (shp,)

  def test_gqa_ring_flash_grads_match_expanded(self, devices):
    """GQA ring on the FLASH path: grouped KV flows unexpanded into the
    kernels (grouped-aware BlockSpec + cross-head dK/dV accumulation) and
    grads still equal AD through the expanded dense reference — the
    round-3 ROADMAP deferral, closed."""
    mesh = M.build_mesh(M.MeshSpec(sequence=4), devices=devices[:4])
    rng = np.random.RandomState(9)
    B, S, H, HK, D = 1, 32, 4, 2, 8
    q = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
    k = jnp.asarray(rng.randn(B, S, HK, D), jnp.float32)
    v = jnp.asarray(rng.randn(B, S, HK, D), jnp.float32)
    w = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)

    def loss_ring(q, k, v):
      return jnp.sum(RA.ring_attention(q, k, v, mesh, causal=True,
                                       use_flash=True, blk_q=8, blk_k=8,
                                       interpret=True) * w)

    def loss_dense(q, k, v):
      ke = jnp.repeat(k, H // HK, axis=2)
      ve = jnp.repeat(v, H // HK, axis=2)
      return jnp.sum(RA.full_attention(q, ke, ve, causal=True) * w)

    gr = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gr, gd):
      np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                 atol=1e-4, rtol=1e-4)

  def test_ring_flash_gradients_match_dense(self, devices):
    """Training through ring-flash: grads equal dense full attention."""
    mesh = M.build_mesh(M.MeshSpec(sequence=4), devices=devices[:4])
    rng = np.random.RandomState(2)
    B, S, H, D = 1, 32, 2, 8
    q, k, v = (jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
               for _ in range(3))
    w = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)

    def loss_ring(q, k, v):
      return jnp.sum(w * RA.ring_attention(
          q, k, v, mesh, causal=True, use_flash=True, blk_q=8, blk_k=8,
          interpret=True))

    def loss_dense(q, k, v):
      return jnp.sum(w * RA.full_attention(q, k, v, causal=True))

    gr = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gr, gd):
      np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                 atol=1e-4, rtol=1e-4)


class TestPipelineParallel:
  def test_matches_sequential(self, devices):
    from tensorflowonspark_tpu.parallel import pipeline_parallel as PP

    mesh = M.build_mesh(M.MeshSpec(data=2, pipeline=4), devices=devices)
    rng = np.random.RandomState(0)
    n_stages, d = 4, 16
    # stage i: x -> tanh(x @ W_i)
    W = jnp.asarray(rng.randn(n_stages, d, d) * 0.3, jnp.float32)
    x = jnp.asarray(rng.randn(8, d), jnp.float32)

    def stage_fn(w, a):
      return jnp.tanh(a @ w)

    ref = x
    for i in range(n_stages):
      ref = stage_fn(W[i], ref)

    out = jax.jit(lambda W, x: PP.pipeline_apply(
        stage_fn, W, x, mesh, num_microbatches=4))(W, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)

  def test_differentiable(self, devices):
    from tensorflowonspark_tpu.parallel import pipeline_parallel as PP

    mesh = M.build_mesh(M.MeshSpec(pipeline=4), devices=devices[:4])
    rng = np.random.RandomState(1)
    W = jnp.asarray(rng.randn(4, 8, 8) * 0.3, jnp.float32)
    x = jnp.asarray(rng.randn(4, 8), jnp.float32)

    def stage_fn(w, a):
      return jnp.tanh(a @ w)

    def loss_pipe(W):
      return jnp.sum(PP.pipeline_apply(stage_fn, W, x, mesh, 2) ** 2)

    def loss_seq(W):
      a = x
      for i in range(4):
        a = stage_fn(W[i], a)
      return jnp.sum(a ** 2)

    g_pipe = jax.jit(jax.grad(loss_pipe))(W)
    g_seq = jax.grad(loss_seq)(W)
    np.testing.assert_allclose(np.asarray(g_pipe), np.asarray(g_seq),
                               atol=1e-4, rtol=1e-4)


class TestPipeline1F1B:
  """The 1F1B schedule: loss+grads in one interleaved loop with an
  O(n_stages) activation ring — must agree with plain sequential AD."""

  def _setup(self):
    from tensorflowonspark_tpu.parallel import pipeline_parallel as PP
    rng = np.random.RandomState(7)
    n_stages, d, b = 4, 16, 8
    W = jnp.asarray(rng.randn(n_stages, d, d) * 0.3, jnp.float32)
    x = jnp.asarray(rng.randn(b, d), jnp.float32)
    t = jnp.asarray(rng.randn(b, d), jnp.float32)

    def stage_fn(w, a):
      return jnp.tanh(a @ w)

    def loss_fn(y, tgt):
      return jnp.mean((y - tgt) ** 2)

    def seq_loss(W):
      a = x
      for i in range(n_stages):
        a = stage_fn(W[i], a)
      return loss_fn(a, t)

    return PP, stage_fn, loss_fn, W, x, t, seq_loss

  @pytest.mark.parametrize("n_micro", [2, 4, 8])
  def test_matches_sequential_grads(self, devices, n_micro):
    PP, stage_fn, loss_fn, W, x, t, seq_loss = self._setup()
    mesh = M.build_mesh(M.MeshSpec(pipeline=4), devices=devices[:4])
    loss, grads = jax.jit(lambda W, x, t: PP.pipeline_train_step(
        stage_fn, loss_fn, W, x, t, mesh, num_microbatches=n_micro))(W, x, t)
    np.testing.assert_allclose(float(loss), float(seq_loss(W)),
                               atol=1e-5, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(grads),
                               np.asarray(jax.grad(seq_loss)(W)),
                               atol=1e-4, rtol=1e-4)

  def test_cond_is_real_branch(self, devices):
    """The stage-0 embed and last-stage head+loss are guarded by lax.cond
    on the pipeline axis index. Under vmap such conds lower to select
    (both branches run everywhere — a silent perf regression); under
    shard_map the predicate is a per-device scalar and must survive as a
    real HLO ``conditional`` (round-3 advice)."""
    PP, stage_fn, loss_fn, W, x, t, _ = self._setup()
    mesh = M.build_mesh(M.MeshSpec(pipeline=4), devices=devices[:4])
    f = jax.jit(lambda W, x, t: PP.pipeline_train_step(
        stage_fn, loss_fn, W, x, t, mesh, num_microbatches=4))
    hlo = f.lower(W, x, t).compile().as_text()
    assert "conditional(" in hlo, \
        "embed/head lax.cond was lowered to select: edge-stage work " \
        "now runs on every stage"

  def test_bf16_params_and_loss(self, devices):
    """bf16 end-to-end: the loss-vjp cotangent matches the loss dtype and
    grads accumulate in f32 before casting back to the param dtype."""
    PP, stage_fn, loss_fn, W, x, t, seq_loss = self._setup()
    Wb = W.astype(jnp.bfloat16)
    xb, tb = x.astype(jnp.bfloat16), t.astype(jnp.bfloat16)
    mesh = M.build_mesh(M.MeshSpec(pipeline=4), devices=devices[:4])
    loss, grads = jax.jit(lambda W, x, t: PP.pipeline_train_step(
        stage_fn, loss_fn, W, x, t, mesh, num_microbatches=4))(Wb, xb, tb)
    assert jax.tree.leaves(grads)[0].dtype == jnp.bfloat16
    np.testing.assert_allclose(float(loss), float(seq_loss(W)), atol=0.05)
    np.testing.assert_allclose(np.asarray(grads, np.float32),
                               np.asarray(jax.grad(seq_loss)(W)),
                               atol=0.05)

  def test_inputs_scattered_along_pipeline(self, devices):
    """With n_micro % S == 0 the token/target microbatches are scattered
    over the pipeline axis and ride ppermute conveyors (round-4 verdict
    item 6) — visible as extra collective-permutes in the compiled HLO
    versus the replicated fallback (n_micro < S). A silent regression to
    always-replicate would pass the parity tests; this pins the path."""
    PP, stage_fn, loss_fn, W, x, t, _ = self._setup()
    mesh = M.build_mesh(M.MeshSpec(pipeline=4), devices=devices[:4])

    def cp_count(n_micro):
      hlo = jax.jit(lambda W, x, t: PP.pipeline_train_step(
          stage_fn, loss_fn, W, x, t, mesh,
          num_microbatches=n_micro)).lower(W, x, t).compile().as_text()
      return hlo.count("collective-permute(")

    replicated = cp_count(2)    # 2 < S=4 -> fallback: act + cotangent CPs
    scattered = cp_count(4)     # divisible -> + token & target conveyors
    assert replicated >= 2
    assert scattered > replicated, (replicated, scattered)

  def test_microbatch_data_divisibility_asserts(self, devices):
    PP, stage_fn, loss_fn, W, x, t, _ = self._setup()
    mesh = M.build_mesh(M.MeshSpec(data=2, pipeline=4), devices=devices)
    with pytest.raises(AssertionError, match="data-axis extent"):
      PP.pipeline_train_step(stage_fn, loss_fn, W, x, t, mesh,
                             num_microbatches=8)  # micro_b=1, data=2

  def test_with_data_parallel_axis(self, devices):
    """DP x PP: per-shard losses/grads pmean over the data axis so the
    result equals the global-batch computation."""
    PP, stage_fn, loss_fn, W, x, t, seq_loss = self._setup()
    mesh = M.build_mesh(M.MeshSpec(data=2, pipeline=4), devices=devices)
    loss, grads = jax.jit(lambda W, x, t: PP.pipeline_train_step(
        stage_fn, loss_fn, W, x, t, mesh, num_microbatches=4))(W, x, t)
    np.testing.assert_allclose(float(loss), float(seq_loss(W)),
                               atol=1e-5, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(grads),
                               np.asarray(jax.grad(seq_loss)(W)),
                               atol=1e-4, rtol=1e-4)


class TestExpertParallel:
  def test_matches_reference(self, devices):
    from tensorflowonspark_tpu.parallel import expert_parallel as EP

    mesh = M.build_mesh(M.MeshSpec(data=2, expert=4), devices=devices)
    params = EP.init_moe_params(jax.random.PRNGKey(0), num_experts=8,
                                d_model=16, d_ff=32)
    x = jnp.asarray(np.random.RandomState(0).randn(24, 16), jnp.float32)
    ref = EP.moe_ffn_reference(params, x)
    sharded = EP.shard_moe_params(params, mesh)
    out = jax.jit(lambda p, x: EP.moe_ffn(p, x, mesh))(sharded, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)

  def test_expert_weights_actually_sharded(self, devices):
    from tensorflowonspark_tpu.parallel import expert_parallel as EP
    mesh = M.build_mesh(M.MeshSpec(expert=8), devices=devices)
    params = EP.shard_moe_params(
        EP.init_moe_params(jax.random.PRNGKey(0), 8, 16, 32), mesh)
    assert len(params["w_up"].sharding.device_set) == 8

  def test_a2a_matches_reference_with_ample_capacity(self, devices):
    from tensorflowonspark_tpu.parallel import expert_parallel as EP

    mesh = M.build_mesh(M.MeshSpec(data=2, expert=4), devices=devices)
    params = EP.init_moe_params(jax.random.PRNGKey(0), num_experts=8,
                                d_model=16, d_ff=32)
    x = jnp.asarray(np.random.RandomState(0).randn(64, 16), jnp.float32)
    ref = EP.moe_ffn_reference(params, x)
    sharded = EP.shard_moe_params(params, mesh)
    # capacity_factor high enough that no token is dropped
    out = jax.jit(lambda p, x: EP.moe_ffn_a2a(p, x, mesh,
                                              capacity_factor=8.0))(
        sharded, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)

  def test_a2a_capacity_drops_gracefully(self, devices):
    from tensorflowonspark_tpu.parallel import expert_parallel as EP
    mesh = M.build_mesh(M.MeshSpec(expert=4), devices=devices[:4])
    params = EP.init_moe_params(jax.random.PRNGKey(0), 4, 8, 16)
    x = jnp.asarray(np.random.RandomState(1).randn(32, 8), jnp.float32)
    sharded = EP.shard_moe_params(params, mesh)
    # tiny capacity: result must be finite (dropped tokens -> zeros)
    out = jax.jit(lambda p, x: EP.moe_ffn_a2a(p, x, mesh,
                                              capacity_factor=0.5))(
        sharded, x)
    assert np.isfinite(np.asarray(out)).all()

  def test_a2a_top2_matches_reference_with_ample_capacity(self, devices):
    from tensorflowonspark_tpu.parallel import expert_parallel as EP

    mesh = M.build_mesh(M.MeshSpec(data=2, expert=4), devices=devices)
    params = EP.init_moe_params(jax.random.PRNGKey(4), num_experts=8,
                                d_model=16, d_ff=32)
    x = jnp.asarray(np.random.RandomState(4).randn(64, 16), jnp.float32)
    ref = EP.moe_ffn_reference(params, x, top_k=2)
    sharded = EP.shard_moe_params(params, mesh)
    out = jax.jit(lambda p, x: EP.moe_ffn_a2a(p, x, mesh,
                                              capacity_factor=8.0,
                                              top_k=2))(sharded, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)

  def test_a2a_top2_capacity_drops_only_overflow(self, devices):
    """With a tight capacity, surviving assignments keep their renormalized
    weights — outputs stay finite and within the ample-capacity envelope."""
    from tensorflowonspark_tpu.parallel import expert_parallel as EP
    mesh = M.build_mesh(M.MeshSpec(expert=4), devices=devices[:4])
    params = EP.init_moe_params(jax.random.PRNGKey(5), 4, 8, 16)
    x = jnp.asarray(np.random.RandomState(5).randn(32, 8), jnp.float32)
    sharded = EP.shard_moe_params(params, mesh)
    tight = jax.jit(lambda p, x: EP.moe_ffn_a2a(
        p, x, mesh, capacity_factor=0.5, top_k=2))(sharded, x)
    ample = jax.jit(lambda p, x: EP.moe_ffn_a2a(
        p, x, mesh, capacity_factor=8.0, top_k=2))(sharded, x)
    assert np.isfinite(np.asarray(tight)).all()
    per_token = jnp.abs(tight - ample).max(axis=-1)
    assert float(per_token.max()) > 1e-6      # something was dropped
    # early queue positions fit under even a tight capacity, so some
    # tokens' outputs must survive exactly
    assert int((per_token < 1e-6).sum()) >= 1

  def test_top2_routing_matches_reference(self, devices):
    from tensorflowonspark_tpu.parallel import expert_parallel as EP
    mesh = M.build_mesh(M.MeshSpec(data=2, expert=4), devices=devices)
    params = EP.init_moe_params(jax.random.PRNGKey(2), 8, 16, 32)
    x = jnp.asarray(np.random.RandomState(2).randn(24, 16), jnp.float32)
    ref = EP.moe_ffn_reference(params, x, top_k=2)
    out = jax.jit(lambda p, x: EP.moe_ffn(p, x, mesh, top_k=2))(
        EP.shard_moe_params(params, mesh), x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)
    # top-2 combine weights sum to 1 per token -> output differs from top-1
    top1 = EP.moe_ffn_reference(params, x, top_k=1)
    assert float(jnp.max(jnp.abs(top1 - ref))) > 1e-4

  def test_load_balancing_loss(self):
    from tensorflowonspark_tpu.parallel import expert_parallel as EP
    params = EP.init_moe_params(jax.random.PRNGKey(0), 4, 8, 16)
    x = jnp.asarray(np.random.RandomState(3).randn(256, 8), jnp.float32)
    aux = float(EP.load_balancing_loss(params, x))
    assert aux >= 1.0 - 1e-3          # 1.0 is the uniform-routing floor
    assert np.isfinite(aux)
    # differentiable w.r.t. the gate
    g = jax.grad(lambda p: EP.load_balancing_loss(p, x))(params)
    assert float(jnp.abs(g["w_gate"]).sum()) > 0

  def test_differentiable(self, devices):
    from tensorflowonspark_tpu.parallel import expert_parallel as EP
    mesh = M.build_mesh(M.MeshSpec(expert=4), devices=devices[:4])
    params = EP.init_moe_params(jax.random.PRNGKey(1), 4, 8, 16)
    x = jnp.asarray(np.random.RandomState(1).randn(6, 8), jnp.float32)

    g_ref = jax.grad(lambda p: jnp.sum(
        EP.moe_ffn_reference(p, x) ** 2))(params)
    sharded = EP.shard_moe_params(params, mesh)
    g_shard = jax.jit(jax.grad(lambda p: jnp.sum(
        EP.moe_ffn(p, x, mesh) ** 2)))(sharded)
    np.testing.assert_allclose(np.asarray(g_shard["w_up"]),
                               np.asarray(g_ref["w_up"]),
                               atol=1e-4, rtol=1e-4)


class TestShardedTrainStep:
  def test_gqa_kv_heads_replicate_when_indivisible(self, devices):
    """GQA K/V projections whose head count the tensor axis can't divide
    fall back to replication instead of failing state init (kv_heads=2
    on tensor=4); the model still initializes sharded and takes a step."""
    from tensorflowonspark_tpu.models import transformer as tfm

    mesh = M.build_mesh(M.MeshSpec(data=2, tensor=4), devices=devices)
    cfg = tfm.TransformerConfig(vocab_size=32, num_layers=2, num_heads=8,
                                num_kv_heads=2, d_model=32, d_ff=64,
                                max_seq_len=16, remat=False,
                                dtype=jnp.float32)
    state, sharding = tfm.create_sharded_state(jax.random.PRNGKey(0), cfg,
                                               mesh, seq_len=16)

    def loss_fn(params, tokens):
      return tfm.causal_lm_loss(
          state.apply_fn({"params": params}, tokens), tokens)

    step = SH.make_train_step(loss_fn, mesh, sharding)
    rng = np.random.RandomState(0)
    tokens = SH.shard_batch(
        jnp.asarray(rng.randint(0, 32, (4, 16)), jnp.int32), mesh)
    state, loss = step(state, tokens)
    assert np.isfinite(float(loss))

  def test_transformer_trains_sharded(self, devices):
    """Full dp+sp+tp train loop: loss must decrease on a tiny corpus."""
    from tensorflowonspark_tpu.models import transformer as tfm

    mesh = M.build_mesh(M.MeshSpec(data=2, sequence=2, tensor=2),
                        devices=devices)
    seq = 32
    cfg = tfm.TransformerConfig(vocab_size=64, num_layers=2, num_heads=4,
                                d_model=64, d_ff=128, max_seq_len=seq,
                                remat=False, use_ring_attention=True)
    state, sharding = tfm.create_sharded_state(jax.random.PRNGKey(0), cfg,
                                               mesh, learning_rate=1e-2,
                                               seq_len=seq)

    def loss_fn(params, tokens):
      return tfm.causal_lm_loss(
          state.apply_fn({"params": params}, tokens), tokens)

    step = SH.make_train_step(loss_fn, mesh, sharding,
                              batch_extra_axes=(M.AXIS_SEQUENCE,))
    # a learnable pattern: token ids follow a fixed cycle
    base = np.tile(np.arange(seq) % 16, (4, 1)).astype("int32")
    tokens = SH.shard_batch(jnp.asarray(base), mesh,
                            extra_axes=(M.AXIS_SEQUENCE,))
    losses = []
    for _ in range(8):
      state, loss = step(state, tokens)
      losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.7, losses

    # params actually sharded: at least one leaf spans multiple devices
    leaves = jax.tree.leaves(state.params)
    assert any(len(l.sharding.device_set) > 1 for l in leaves)

  def test_state_shardings_distinguish_same_shape_params(self, devices):
    """Adam moments must mirror THEIR parameter's layout: two params with
    identical shapes but different shardings each keep their own (a
    shape-keyed lookup would assign both the first layout and silently
    reshard between a param and its moments every step)."""
    import optax
    from flax.training import train_state
    from jax.sharding import NamedSharding

    mesh = M.build_mesh(M.MeshSpec(data=2, tensor=2), devices=devices[:4])
    params = {"a": jnp.zeros((8, 8)), "b": jnp.zeros((8, 8))}
    abs_state = jax.eval_shape(lambda: train_state.TrainState.create(
        apply_fn=lambda v, x: x, params=params, tx=optax.adam(1e-3)))
    sh_a = NamedSharding(mesh, P(M.AXIS_TENSOR, None))
    sh_b = NamedSharding(mesh, P(None, M.AXIS_TENSOR))
    full = SH.state_shardings(abs_state, {"a": sh_a, "b": sh_b}, mesh)
    mu = full.opt_state[0].mu
    nu = full.opt_state[0].nu
    assert mu["a"] == sh_a and nu["a"] == sh_a
    assert mu["b"] == sh_b and nu["b"] == sh_b

  def test_fused_layer_norm_matches_flax_in_model(self, devices):
    """The fused Pallas LayerNorm (per-shard via shard_map) trains the
    sharded transformer on the same trajectory as flax LayerNorm."""
    from tensorflowonspark_tpu.models import transformer as tfm

    mesh = M.build_mesh(M.MeshSpec(data=2, sequence=2, tensor=2),
                        devices=devices)
    seq = 32
    losses = {}
    for impl in ("flax", "fused"):
      cfg = tfm.TransformerConfig(vocab_size=64, num_layers=2, num_heads=4,
                                  d_model=64, d_ff=128, max_seq_len=seq,
                                  remat=False, dtype=jnp.float32,
                                  use_ring_attention=True,
                                  layer_norm_impl=impl)
      state, sharding = tfm.create_sharded_state(jax.random.PRNGKey(0), cfg,
                                                 mesh, learning_rate=1e-2,
                                                 seq_len=seq)
      if impl == "fused":   # the fused module actually in the tree
        assert "scale" in state.params["layer_0"]["ln1"]

      def loss_fn(params, tokens, apply_fn=state.apply_fn):
        return tfm.causal_lm_loss(apply_fn({"params": params}, tokens),
                                  tokens)

      step = SH.make_train_step(loss_fn, mesh, sharding,
                                batch_extra_axes=(M.AXIS_SEQUENCE,))
      base = np.tile(np.arange(seq) % 16, (4, 1)).astype("int32")
      tokens = SH.shard_batch(jnp.asarray(base), mesh,
                              extra_axes=(M.AXIS_SEQUENCE,))
      traj = []
      for _ in range(4):
        state, loss = step(state, tokens)
        traj.append(float(loss))
      losses[impl] = traj
    np.testing.assert_allclose(losses["fused"], losses["flax"],
                               atol=1e-5, rtol=1e-5)

  def test_ln_matmul_fused_matches_unfused_sharded(self, devices):
    """ln_matmul_impl="fused" on a dp×sp×tp mesh (per-shard kernel via
    ops.ln_matmul_sharded) trains on the same trajectory as the unfused
    model — round-3 verdict item 4: before this, the fusion applied only
    in mesh-free contexts, so multi-chip training got nothing from it.
    fuse_qkv=True covers BOTH fused call sites (ln1→QKV, ln2→up)."""
    from tensorflowonspark_tpu.models import transformer as tfm

    import dataclasses
    mesh = M.build_mesh(M.MeshSpec(data=2, sequence=2, tensor=2),
                        devices=devices)
    seq = 32
    cfg = tfm.TransformerConfig(vocab_size=64, num_layers=2, num_heads=4,
                                d_model=64, d_ff=128, max_seq_len=seq,
                                remat=False, dtype=jnp.float32,
                                use_ring_attention=True, fuse_qkv=True,
                                layer_norm_impl="flax")
    cfg_f = dataclasses.replace(cfg, ln_matmul_impl="fused",
                                act_matmul_impl="fused")
    state, sharding = tfm.create_sharded_state(jax.random.PRNGKey(0), cfg,
                                               mesh, learning_rate=1e-2,
                                               seq_len=seq)
    state_f, sharding_f = tfm.create_sharded_state(jax.random.PRNGKey(0),
                                                   cfg_f, mesh,
                                                   learning_rate=1e-2,
                                                   seq_len=seq)
    # same param paths either way (checkpoints interchangeable)
    assert (jax.tree.structure(state.params)
            == jax.tree.structure(state_f.params))

    base = np.tile(np.arange(seq) % 16, (4, 1)).astype("int32")
    tokens = SH.shard_batch(jnp.asarray(base), mesh,
                            extra_axes=(M.AXIS_SEQUENCE,))

    # shared params: DenseGeneral and the raw 3-D kernel module draw
    # different values from the same RNG path, so per-impl inits diverge
    # numerically; the property under test is identical loss AND grads
    # at identical params
    def loss(c, p):
      return tfm.causal_lm_loss(
          tfm.Transformer(c, mesh).apply({"params": p}, tokens), tokens)

    l0, g0 = jax.jit(jax.value_and_grad(
        lambda p: loss(cfg, p)))(state.params)
    l1, g1 = jax.jit(jax.value_and_grad(
        lambda p: loss(cfg_f, p)))(state.params)
    np.testing.assert_allclose(float(l0), float(l1), atol=1e-5, rtol=1e-5)
    f0, _ = jax.flatten_util.ravel_pytree(g0)
    f1, _ = jax.flatten_util.ravel_pytree(g1)
    np.testing.assert_allclose(np.asarray(f0), np.asarray(f1),
                               atol=2e-4, rtol=2e-4)

    # and the fused config trains through the sharded step machinery
    def loss_fn(params, toks, apply_fn=state_f.apply_fn):
      return tfm.causal_lm_loss(apply_fn({"params": params}, toks), toks)

    step = SH.make_train_step(loss_fn, mesh, sharding_f,
                              batch_extra_axes=(M.AXIS_SEQUENCE,))
    losses = []
    for _ in range(4):
      state_f, l = step(state_f, tokens)
      losses.append(float(l))
    assert losses[-1] < losses[0], losses

  def test_ring_flash_in_model_matches_dense(self, devices):
    """Sequence-parallel training with the flash kernels forced inside the
    ring (attention_impl="flash") follows the dense trajectory — the
    production long-context path, exercised via interpret mode on CPU."""
    from tensorflowonspark_tpu.models import transformer as tfm

    mesh = M.build_mesh(M.MeshSpec(data=2, sequence=2), devices=devices[:4])
    seq = 32
    losses = {}
    for impl in ("dense", "flash"):
      cfg = tfm.TransformerConfig(vocab_size=64, num_layers=2, num_heads=4,
                                  d_model=64, d_ff=128, max_seq_len=seq,
                                  remat=False, dtype=jnp.float32,
                                  use_ring_attention=True,
                                  attention_impl=impl)
      state, sharding = tfm.create_sharded_state(jax.random.PRNGKey(0), cfg,
                                                 mesh, learning_rate=1e-2,
                                                 seq_len=seq)

      def loss_fn(params, tokens, apply_fn=state.apply_fn):
        return tfm.causal_lm_loss(apply_fn({"params": params}, tokens),
                                  tokens)

      step = SH.make_train_step(loss_fn, mesh, sharding,
                                batch_extra_axes=(M.AXIS_SEQUENCE,))
      base = np.tile(np.arange(seq) % 16, (4, 1)).astype("int32")
      tokens = SH.shard_batch(jnp.asarray(base), mesh,
                              extra_axes=(M.AXIS_SEQUENCE,))
      traj = []
      for _ in range(4):
        state, loss = step(state, tokens)
        traj.append(float(loss))
      losses[impl] = traj
    np.testing.assert_allclose(losses["flash"], losses["dense"],
                               atol=2e-4, rtol=2e-4)

  def test_moe_transformer_sharded_over_expert_axis(self, devices):
    """The MoE flagship trains with experts sharded over the expert axis
    inside one jitted SPMD step."""
    from tensorflowonspark_tpu.models import transformer as tfm

    mesh = M.build_mesh(M.MeshSpec(data=2, expert=4), devices=devices)
    cfg = tfm.TransformerConfig(vocab_size=64, num_layers=2, num_heads=4,
                                d_model=64, d_ff=128, remat=False,
                                dtype=jnp.float32, moe_experts=4,
                                moe_top_k=2, moe_every=2)
    state, sharding = tfm.create_sharded_state(jax.random.PRNGKey(0), cfg,
                                               mesh, learning_rate=1e-2,
                                               seq_len=16)
    w_up = state.params["layer_1"]["moe"]["w_up"]
    assert len(w_up.sharding.device_set) >= 4   # experts actually sharded

    def loss_fn(params, tokens):
      return tfm.causal_lm_loss(
          state.apply_fn({"params": params}, tokens), tokens)

    step = SH.make_train_step(loss_fn, mesh, sharding)
    base = np.tile(np.arange(16) % 8, (8, 1)).astype("int32")
    tokens = SH.shard_batch(jnp.asarray(base), mesh)
    losses = []
    for _ in range(8):
      state, loss = step(state, tokens)
      losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.8, losses

  def test_moe_transformer_a2a_dispatch_path(self, devices):
    """moe_capacity_factor > 0 routes MoE layers through the GShard
    all-to-all dispatch inside the jitted SPMD step; training still
    converges on the cyclic-token corpus."""
    from tensorflowonspark_tpu.models import transformer as tfm

    mesh = M.build_mesh(M.MeshSpec(data=2, expert=4), devices=devices)
    cfg = tfm.TransformerConfig(vocab_size=64, num_layers=2, num_heads=4,
                                d_model=64, d_ff=128, remat=False,
                                dtype=jnp.float32, moe_experts=4,
                                moe_top_k=2, moe_every=2,
                                moe_capacity_factor=4.0)
    state, sharding = tfm.create_sharded_state(jax.random.PRNGKey(0), cfg,
                                               mesh, learning_rate=1e-2,
                                               seq_len=16)

    def loss_fn(params, tokens):
      return tfm.causal_lm_loss(
          state.apply_fn({"params": params}, tokens), tokens)

    step = SH.make_train_step(loss_fn, mesh, sharding)
    base = np.tile(np.arange(16) % 8, (8, 1)).astype("int32")
    tokens = SH.shard_batch(jnp.asarray(base), mesh)
    losses = []
    for _ in range(8):
      state, loss = step(state, tokens)
      losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.8, losses

  def test_param_shardings_follow_rules(self, devices):
    from tensorflowonspark_tpu.models import transformer as tfm

    mesh = M.build_mesh(M.MeshSpec(data=2, tensor=4), devices=devices)
    cfg = tfm.TransformerConfig(vocab_size=64, num_layers=1, num_heads=4,
                                d_model=64, d_ff=128, remat=False)
    state, _ = tfm.create_sharded_state(jax.random.PRNGKey(0), cfg, mesh,
                                        seq_len=16)
    up = state.params["layer_0"]["mlp"]["up"]["kernel"]
    # mlp dim sharded over 4-way tensor axis
    assert up.sharding.spec[-1] == M.AXIS_TENSOR


class TestRingGQATransformer:
  def test_ring_gqa_logits_match_dense(self, devices):
    """The model's ring path feeds GROUPED K/V into the ring (ICI traffic
    cut by num_heads/kv_heads); logits must equal the mesh-free dense
    path on identical params."""
    from tensorflowonspark_tpu.models import transformer as tfm

    mesh = M.build_mesh(M.MeshSpec(sequence=4), devices=devices[:4])
    cfg = tfm.TransformerConfig(vocab_size=64, num_layers=2, num_heads=4,
                                num_kv_heads=2, d_model=64, d_ff=128,
                                max_seq_len=32, remat=False,
                                dtype=jnp.float32, use_ring_attention=True)
    state = tfm.create_state(jax.random.PRNGKey(0), cfg, seq_len=32)
    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, 64, (2, 32)), jnp.int32)

    ring_logits = jax.jit(lambda p, t: tfm.Transformer(cfg, mesh).apply(
        {"params": p}, t))(state.params, tokens)
    import dataclasses
    cfg_d = dataclasses.replace(cfg, use_ring_attention=False)
    dense_logits = tfm.Transformer(cfg_d, None).apply(
        {"params": state.params}, tokens)
    np.testing.assert_allclose(np.asarray(ring_logits),
                               np.asarray(dense_logits),
                               atol=1e-4, rtol=1e-4)


class TestRingWindow:
  """Sliding-window attention through the ring (sequence parallelism):
  both ring paths (dense blocks and Pallas flash blocks) must match the
  dense windowed reference, including windows that straddle shard
  boundaries and windows smaller than one shard."""

  @pytest.mark.parametrize("use_flash", [False, True])
  @pytest.mark.parametrize("window", [3, 8, 20])
  def test_ring_window_matches_dense(self, devices, use_flash, window):
    mesh = M.build_mesh(M.MeshSpec(data=2, sequence=4), devices=devices)
    rng = np.random.RandomState(2)
    B, S, H, D = 2, 32, 2, 16
    q, k, v = (jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
               for _ in range(3))
    ref = RA.full_attention(q, k, v, causal=True, window=window)
    out = jax.jit(lambda q, k, v: RA.ring_attention(
        q, k, v, mesh, causal=True, use_flash=use_flash, blk_q=8, blk_k=8,
        interpret=True, window=window))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-4, rtol=1e-4)

  def test_ring_window_grads_match_dense(self, devices):
    mesh = M.build_mesh(M.MeshSpec(sequence=4), devices=devices[:4])
    rng = np.random.RandomState(3)
    B, S, H, D = 1, 32, 2, 8
    q, k, v = (jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
               for _ in range(3))
    w = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)

    def ring_loss(q, k, v):
      return jnp.sum(w * RA.ring_attention(q, k, v, mesh, causal=True,
                                           use_flash=True, blk_q=8,
                                           blk_k=8, interpret=True,
                                           window=12))

    def dense_loss(q, k, v):
      return jnp.sum(w * RA.full_attention(q, k, v, causal=True,
                                           window=12))

    got = jax.jit(jax.grad(ring_loss, argnums=(0, 1, 2)))(q, k, v)
    ref = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(got, ref):
      np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                 atol=2e-4, rtol=2e-4)
