"""Optimizer recipe builder: schedules, clipping, TrainState wiring."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tensorflowonspark_tpu import optim


class TestSchedules:
  def test_constant(self):
    s = optim.make_schedule(3e-4)
    assert float(s(0)) == pytest.approx(3e-4)
    assert float(s(10_000)) == pytest.approx(3e-4)

  def test_warmup_cosine(self):
    s = optim.make_schedule(1e-3, "cosine", warmup_steps=100,
                            decay_steps=1000)
    assert float(s(0)) == pytest.approx(0.0)
    assert float(s(50)) == pytest.approx(5e-4, rel=1e-3)
    assert float(s(100)) == pytest.approx(1e-3, rel=1e-3)
    # cosine decays to ~0 at the end
    assert float(s(1100)) < 1e-5
    # monotone decay after warmup
    assert float(s(300)) > float(s(700))

  def test_linear_to_end_value(self):
    s = optim.make_schedule(1e-3, "linear", decay_steps=100,
                            end_value=1e-4)
    assert float(s(100)) == pytest.approx(1e-4, rel=1e-3)

  def test_invalid(self):
    with pytest.raises(ValueError, match="schedule"):
      optim.make_schedule(1e-3, "exponential")
    with pytest.raises(ValueError, match="decay_steps"):
      optim.make_schedule(1e-3, "cosine")


class TestOptimizer:
  def test_clipping_normalizes_gradient_scale(self):
    """With clip_norm, the step is invariant to gradient magnitude once
    past the clip threshold: Adam sees the same clipped gradient for g
    and 1000*g, so the updates match exactly."""
    tx = optim.make_optimizer(learning_rate=1.0, weight_decay=0.0,
                              clip_norm=1.0)
    params = {"w": jnp.zeros((4,))}
    g = {"w": jnp.asarray([3.0, -1.0, 2.0, 0.5])}
    huge = {"w": g["w"] * 1000.0}
    u1, _ = tx.update(g, tx.init(params), params)
    u2, _ = tx.update(huge, tx.init(params), params)
    np.testing.assert_allclose(np.asarray(u1["w"]), np.asarray(u2["w"]),
                               rtol=1e-6)
    # And the update is bounded by lr per element (Adam normalization):
    assert float(jnp.max(jnp.abs(u1["w"]))) <= 1.0 + 1e-5

  def test_clip_transform_bounds_gradient(self):
    """The clipping stage itself bounds the global norm at clip_norm."""
    import optax
    clip = optax.clip_by_global_norm(1.0)
    huge = {"w": jnp.full((4,), 1e6)}
    clipped, _ = clip.update(huge, clip.init(huge), None)
    assert float(jnp.linalg.norm(clipped["w"])) == pytest.approx(1.0,
                                                                 rel=1e-5)

  def test_weight_decay_mask_skips_vectors(self):
    """Default decay mask decays kernels (ndim>=2) but not biases/norm
    scales (ndim<2): with zero gradient, only the kernel moves."""
    tx = optim.make_optimizer(learning_rate=1.0, weight_decay=0.1)
    params = {"kernel": jnp.ones((2, 2)), "bias": jnp.ones((2,))}
    zeros = jax.tree_util.tree_map(jnp.zeros_like, params)
    updates, _ = tx.update(zeros, tx.init(params), params)
    assert float(jnp.max(jnp.abs(updates["bias"]))) == 0.0
    assert float(jnp.max(jnp.abs(updates["kernel"]))) > 0.0

  def test_train_state_wiring(self):
    """create_state(tx=...) trains the transformer with the recipe
    (warmup+cosine+clip) end to end."""
    from tensorflowonspark_tpu.models import transformer as tfm
    cfg = tfm.TransformerConfig(vocab_size=16, num_layers=2, num_heads=2,
                                d_model=32, d_ff=64, max_seq_len=24,
                                remat=False)
    tx = optim.make_optimizer(learning_rate=3e-3, schedule="cosine",
                              warmup_steps=10, decay_steps=200,
                              clip_norm=1.0)
    state = tfm.create_state(jax.random.PRNGKey(0), cfg, seq_len=24,
                             tx=tx)
    cycle = np.tile(np.arange(8), 10)
    tokens = jnp.asarray(np.stack([cycle[i:i + 24] for i in range(4)]),
                         jnp.int32)

    @jax.jit
    def step(state, tokens):
      def loss_fn(p):
        return tfm.causal_lm_loss(
            state.apply_fn({"params": p}, tokens), tokens)
      loss, grads = jax.value_and_grad(loss_fn)(state.params)
      return state.apply_gradients(grads=grads), loss

    losses = []
    for _ in range(60):
      state, loss = step(state, tokens)
      losses.append(float(loss))
    assert losses[-1] < losses[0]


class TestOptimizerFamilies:
  """Alternative cores behind make_optimizer(optimizer=...): lion (half
  Adam's optimizer memory), adafactor (factored second moments — the TPU
  memory-saver), sgd (the ResNet recipe). Each must minimize a simple
  objective; adafactor must actually factor its statistics."""

  def _minimize(self, tx, steps=200):
    import optax
    params = {"w": jnp.asarray([3.0, -2.0, 1.5, 4.0])}
    opt_state = tx.init(params)
    for _ in range(steps):
      grads = jax.grad(lambda p: jnp.sum(p["w"] ** 2))(params)
      updates, opt_state = tx.update(grads, opt_state, params)
      params = optax.apply_updates(params, updates)
    return float(jnp.sum(params["w"] ** 2)), opt_state

  @pytest.mark.parametrize("name,lr", [("adamw", 0.1), ("lion", 0.03),
                                       ("adafactor", 0.1), ("sgd", 0.1)])
  def test_each_family_minimizes(self, name, lr):
    tx = optim.make_optimizer(learning_rate=lr, weight_decay=0.0,
                              optimizer=name)
    final, _ = self._minimize(tx)
    assert final < 0.5, "%s did not minimize: %.3f" % (name, final)

  def test_adafactor_factors_matrix_stats(self):
    """For an [m, n] kernel adafactor keeps O(m+n) statistics, not an
    [m, n] second moment — the property that makes it the embedding-
    table optimizer on memory-bound chips."""
    import optax
    tx = optim.make_optimizer(learning_rate=0.1, weight_decay=0.0,
                              optimizer="adafactor")
    # both dims >= adafactor's min_dim_size_to_factor (128 default)
    params = {"k": jnp.zeros((256, 128))}
    state = tx.init(params)
    leaves = [l for l in jax.tree.leaves(state) if hasattr(l, "shape")]
    assert any(l.shape in ((256,), (128,)) for l in leaves), \
        "no factored row/col statistics found"
    assert not any(l.shape == (256, 128) for l in leaves), \
        "full-rank second moment present — not factored"

  def test_invalid_optimizer_raises(self):
    with pytest.raises(ValueError, match="optimizer"):
      optim.make_optimizer(optimizer="adam2")

  def test_grad_accumulation_matches_mean_gradient(self):
    """grad_accum_steps=k: k update calls move params once, exactly as a
    single update on the MEAN of the k gradients (sgd makes the algebra
    exact), and the schedule advances once per effective step."""
    import optax
    k = 4
    tx = optim.make_optimizer(learning_rate=0.1, weight_decay=0.0,
                              optimizer="sgd", momentum=0.0,
                              grad_accum_steps=k)
    ref = optim.make_optimizer(learning_rate=0.1, weight_decay=0.0,
                               optimizer="sgd", momentum=0.0)
    params = {"w": jnp.asarray([1.0, 2.0])}
    grads = [{"w": jnp.asarray([float(i + 1), -float(i)])}
             for i in range(k)]
    mean = jax.tree.map(lambda *g: sum(g) / k, *grads)

    state = tx.init(params)
    p = params
    mids = []
    for g in grads:
      up, state = tx.update(g, state, p)
      p = optax.apply_updates(p, up)
      mids.append(np.asarray(p["w"]).copy())
    # no movement until the k-th microbatch
    for m in mids[:-1]:
      np.testing.assert_array_equal(m, np.asarray(params["w"]))
    up_ref, _ = ref.update(mean, ref.init(params), params)
    expect = optax.apply_updates(params, up_ref)
    np.testing.assert_allclose(mids[-1], np.asarray(expect["w"]),
                               rtol=1e-6)

  @pytest.mark.parametrize("name", ["adafactor", "sgd"])
  def test_decay_is_lr_scaled_and_masked(self, name):
    """adafactor/sgd get AdamW-semantics decoupled decay (lr·wd·p), NOT
    optax.adafactor's raw per-step rate (which would shrink params 1%
    per step at the shared default regardless of schedule/warmup), and
    optax.sgd's none-at-all. With zero gradients: 2-D params decay by
    exactly lr·wd·p per step, 1-D params (mask) don't, and during a
    zero-lr warmup step nothing decays."""
    import optax
    lr, wd = 0.5, 0.01
    tx = optim.make_optimizer(learning_rate=lr, weight_decay=wd,
                              optimizer=name)
    params = {"k": jnp.full((4, 4), 2.0), "b": jnp.full((4,), 2.0)}
    zeros = jax.tree.map(jnp.zeros_like, params)
    state = tx.init(params)
    updates, state = tx.update(zeros, state, params)
    np.testing.assert_allclose(np.asarray(updates["k"]),
                               np.full((4, 4), -lr * wd * 2.0), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(updates["b"]), np.zeros(4),
                               atol=1e-12)

    # zero-lr warmup: no decay either
    tx2 = optim.make_optimizer(learning_rate=lr, weight_decay=wd,
                               optimizer=name, schedule="cosine",
                               warmup_steps=10, decay_steps=100)
    st2 = tx2.init(params)
    up2, _ = tx2.update(zeros, st2, params)
    np.testing.assert_allclose(np.asarray(up2["k"]), np.zeros((4, 4)),
                               atol=1e-9)
