"""Optimizer recipe builder: schedules, clipping, TrainState wiring."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tensorflowonspark_tpu import optim


class TestSchedules:
  def test_constant(self):
    s = optim.make_schedule(3e-4)
    assert float(s(0)) == pytest.approx(3e-4)
    assert float(s(10_000)) == pytest.approx(3e-4)

  def test_warmup_cosine(self):
    s = optim.make_schedule(1e-3, "cosine", warmup_steps=100,
                            decay_steps=1000)
    assert float(s(0)) == pytest.approx(0.0)
    assert float(s(50)) == pytest.approx(5e-4, rel=1e-3)
    assert float(s(100)) == pytest.approx(1e-3, rel=1e-3)
    # cosine decays to ~0 at the end
    assert float(s(1100)) < 1e-5
    # monotone decay after warmup
    assert float(s(300)) > float(s(700))

  def test_linear_to_end_value(self):
    s = optim.make_schedule(1e-3, "linear", decay_steps=100,
                            end_value=1e-4)
    assert float(s(100)) == pytest.approx(1e-4, rel=1e-3)

  def test_invalid(self):
    with pytest.raises(ValueError, match="schedule"):
      optim.make_schedule(1e-3, "exponential")
    with pytest.raises(ValueError, match="decay_steps"):
      optim.make_schedule(1e-3, "cosine")


class TestOptimizer:
  def test_clipping_normalizes_gradient_scale(self):
    """With clip_norm, the step is invariant to gradient magnitude once
    past the clip threshold: Adam sees the same clipped gradient for g
    and 1000*g, so the updates match exactly."""
    tx = optim.make_optimizer(learning_rate=1.0, weight_decay=0.0,
                              clip_norm=1.0)
    params = {"w": jnp.zeros((4,))}
    g = {"w": jnp.asarray([3.0, -1.0, 2.0, 0.5])}
    huge = {"w": g["w"] * 1000.0}
    u1, _ = tx.update(g, tx.init(params), params)
    u2, _ = tx.update(huge, tx.init(params), params)
    np.testing.assert_allclose(np.asarray(u1["w"]), np.asarray(u2["w"]),
                               rtol=1e-6)
    # And the update is bounded by lr per element (Adam normalization):
    assert float(jnp.max(jnp.abs(u1["w"]))) <= 1.0 + 1e-5

  def test_clip_transform_bounds_gradient(self):
    """The clipping stage itself bounds the global norm at clip_norm."""
    import optax
    clip = optax.clip_by_global_norm(1.0)
    huge = {"w": jnp.full((4,), 1e6)}
    clipped, _ = clip.update(huge, clip.init(huge), None)
    assert float(jnp.linalg.norm(clipped["w"])) == pytest.approx(1.0,
                                                                 rel=1e-5)

  def test_weight_decay_mask_skips_vectors(self):
    """Default decay mask decays kernels (ndim>=2) but not biases/norm
    scales (ndim<2): with zero gradient, only the kernel moves."""
    tx = optim.make_optimizer(learning_rate=1.0, weight_decay=0.1)
    params = {"kernel": jnp.ones((2, 2)), "bias": jnp.ones((2,))}
    zeros = jax.tree_util.tree_map(jnp.zeros_like, params)
    updates, _ = tx.update(zeros, tx.init(params), params)
    assert float(jnp.max(jnp.abs(updates["bias"]))) == 0.0
    assert float(jnp.max(jnp.abs(updates["kernel"]))) > 0.0

  def test_train_state_wiring(self):
    """create_state(tx=...) trains the transformer with the recipe
    (warmup+cosine+clip) end to end."""
    from tensorflowonspark_tpu.models import transformer as tfm
    cfg = tfm.TransformerConfig(vocab_size=16, num_layers=2, num_heads=2,
                                d_model=32, d_ff=64, max_seq_len=24,
                                remat=False)
    tx = optim.make_optimizer(learning_rate=3e-3, schedule="cosine",
                              warmup_steps=10, decay_steps=200,
                              clip_norm=1.0)
    state = tfm.create_state(jax.random.PRNGKey(0), cfg, seq_len=24,
                             tx=tx)
    cycle = np.tile(np.arange(8), 10)
    tokens = jnp.asarray(np.stack([cycle[i:i + 24] for i in range(4)]),
                         jnp.int32)

    @jax.jit
    def step(state, tokens):
      def loss_fn(p):
        return tfm.causal_lm_loss(
            state.apply_fn({"params": p}, tokens), tokens)
      loss, grads = jax.value_and_grad(loss_fn)(state.params)
      return state.apply_gradients(grads=grads), loss

    losses = []
    for _ in range(60):
      state, loss = step(state, tokens)
      losses.append(float(loss))
    assert losses[-1] < losses[0]
