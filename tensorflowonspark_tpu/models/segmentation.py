"""Image segmentation: a MobileNet-style encoder U-Net.

Capability parity with the reference's segmentation example
(/root/reference/examples/segmentation/segmentation_spark.py — a
MobileNetV2-encoder U-Net trained multi-worker on the Oxford pets dataset),
built TPU-first in flax: depthwise-separable encoder blocks, transpose-conv
decoder with skip connections, bfloat16 compute, per-pixel cross-entropy.
"""

from typing import Any, Sequence

import jax
import jax.numpy as jnp
import flax.linen as nn
import optax
from flax.training import train_state


class SeparableDown(nn.Module):
  """Depthwise-separable strided conv block (MobileNet-flavored encoder)."""
  filters: int
  dtype: Any = jnp.bfloat16

  @nn.compact
  def __call__(self, x):
    in_ch = x.shape[-1]
    x = nn.Conv(in_ch, (3, 3), strides=(2, 2), feature_group_count=in_ch,
                use_bias=False, dtype=self.dtype, name="depthwise")(x)
    x = nn.Conv(self.filters, (1, 1), use_bias=False, dtype=self.dtype,
                name="pointwise")(x)
    x = nn.GroupNorm(num_groups=min(8, self.filters), dtype=jnp.float32)(x)
    return nn.relu(x)


class UpBlock(nn.Module):
  filters: int
  dtype: Any = jnp.bfloat16

  @nn.compact
  def __call__(self, x, skip):
    x = nn.ConvTranspose(self.filters, (3, 3), strides=(2, 2),
                         use_bias=False, dtype=self.dtype)(x)
    x = jnp.concatenate([x, skip.astype(x.dtype)], axis=-1)
    x = nn.Conv(self.filters, (3, 3), use_bias=False, dtype=self.dtype)(x)
    x = nn.GroupNorm(num_groups=min(8, self.filters), dtype=jnp.float32)(x)
    return nn.relu(x)


class UNet(nn.Module):
  """U-Net over NHWC images; per-pixel ``num_classes`` logits."""
  num_classes: int = 3           # parity: pets masks have 3 classes
  encoder_filters: Sequence[int] = (32, 64, 128, 256)
  dtype: Any = jnp.bfloat16

  @nn.compact
  def __call__(self, x, train: bool = False):
    x = x.astype(self.dtype)
    x = nn.Conv(self.encoder_filters[0], (3, 3), use_bias=False,
                dtype=self.dtype, name="stem")(x)
    skips = []
    for i, f in enumerate(self.encoder_filters):
      skips.append(x)
      x = SeparableDown(f, self.dtype, name="down%d" % i)(x)
    for i, (f, skip) in enumerate(zip(reversed(self.encoder_filters),
                                      reversed(skips))):
      x = UpBlock(f, self.dtype, name="up%d" % i)(x, skip)
    x = nn.Conv(self.num_classes, (1, 1), dtype=jnp.float32, name="head")(x)
    return x


def create_state(rng, model: UNet = None, image_shape=(128, 128, 3),
                 learning_rate: float = 1e-3):
  model = model or UNet()
  params = model.init(rng, jnp.zeros((1,) + tuple(image_shape),
                                     jnp.float32))["params"]
  tx = optax.adam(learning_rate)
  return train_state.TrainState.create(apply_fn=model.apply, params=params,
                                       tx=tx)


@jax.jit
def train_step(state, images, masks):
  """masks: int32 [B,H,W] class ids."""

  def _loss(params):
    logits = state.apply_fn({"params": params}, images, train=True)
    return optax.softmax_cross_entropy_with_integer_labels(
        logits, masks).mean()

  loss, grads = jax.value_and_grad(_loss)(state.params)
  return state.apply_gradients(grads=grads), loss


def synthetic_dataset(num: int, size: int = 128, seed: int = 0):
  """Synthetic segmentation data: images whose masks are recoverable
  (circles of per-class intensity), for offline training/benchmarks."""
  import numpy as np
  rng = np.random.RandomState(seed)
  images = rng.rand(num, size, size, 3).astype("float32") * 0.1
  masks = np.zeros((num, size, size), "int32")
  yy, xx = np.mgrid[:size, :size]
  for i in range(num):
    cx, cy, r = rng.randint(size // 4, 3 * size // 4, 2).tolist() + \
        [rng.randint(size // 8, size // 4)]
    cls = rng.randint(1, 3)
    inside = (yy - cy) ** 2 + (xx - cx) ** 2 < r ** 2
    masks[i][inside] = cls
    images[i][inside] += 0.4 * cls
  return images, masks
