"""Model families mirroring the reference's example workloads, TPU-first.

The reference shipped its models as examples (MNIST MLP/CNN keras, ResNet
CIFAR, MobileNetV2 U-Net segmentation — /root/reference/examples/); here they
are first-class library models in flax, designed for bfloat16 MXU execution
and pjit/GSPMD sharding, plus a Transformer family (the long-context flagship
capability the reference lacked).
"""
