"""MNIST model family: MLP + CNN in flax, with jitted train/eval steps.

Capability parity with the reference's MNIST examples
(/root/reference/examples/mnist/keras/mnist_tf.py:23-39 — a
512-unit MLP with dropout; mnist_spark.py uses the same). TPU-first design:

- compute in bfloat16 (MXU-native), parameters in float32;
- one fused jitted ``train_step`` (forward + backward + optimizer) — no
  per-batch Python;
- batch-axis sharding hooks for data parallelism (the caller passes an
  optional ``jax.sharding.NamedSharding`` for inputs; collectives are
  inserted by XLA, not hand-written).
"""

from typing import Any, Tuple

import jax
import jax.numpy as jnp
import flax.linen as nn
import optax
from flax.training import train_state

IMAGE_SHAPE = (28, 28, 1)
NUM_CLASSES = 10


class MLP(nn.Module):
  """512-unit ReLU MLP (parity with the reference example topology)."""
  hidden: int = 512
  num_classes: int = NUM_CLASSES
  dtype: Any = jnp.bfloat16

  @nn.compact
  def __call__(self, x, train: bool = False):
    x = x.reshape((x.shape[0], -1)).astype(self.dtype)
    x = nn.Dense(self.hidden, dtype=self.dtype)(x)
    x = nn.relu(x)
    x = nn.Dense(self.num_classes, dtype=self.dtype)(x)
    return x.astype(jnp.float32)


class CNN(nn.Module):
  """Small convnet; conv feature maps sized for MXU-friendly channel dims."""
  num_classes: int = NUM_CLASSES
  dtype: Any = jnp.bfloat16

  @nn.compact
  def __call__(self, x, train: bool = False):
    x = x.astype(self.dtype)
    x = nn.Conv(32, (3, 3), dtype=self.dtype)(x)
    x = nn.relu(x)
    x = nn.avg_pool(x, (2, 2), strides=(2, 2))
    x = nn.Conv(64, (3, 3), dtype=self.dtype)(x)
    x = nn.relu(x)
    x = nn.avg_pool(x, (2, 2), strides=(2, 2))
    x = x.reshape((x.shape[0], -1))
    x = nn.Dense(256, dtype=self.dtype)(x)
    x = nn.relu(x)
    x = nn.Dense(self.num_classes, dtype=self.dtype)(x)
    return x.astype(jnp.float32)


def create_state(rng: jax.Array, model: nn.Module = None,
                 learning_rate: float = 1e-3,
                 batch_shape: Tuple[int, ...] = (1,) + IMAGE_SHAPE
                 ) -> train_state.TrainState:
  model = model or MLP()
  params = model.init(rng, jnp.zeros(batch_shape, jnp.float32))["params"]
  tx = optax.adam(learning_rate)
  return train_state.TrainState.create(apply_fn=model.apply, params=params,
                                       tx=tx)


def loss_fn(logits: jax.Array, labels: jax.Array) -> jax.Array:
  return optax.softmax_cross_entropy_with_integer_labels(logits,
                                                         labels).mean()


@jax.jit
def train_step(state: train_state.TrainState, images: jax.Array,
               labels: jax.Array):
  """One fused optimization step; returns (new_state, loss)."""

  def _loss(params):
    logits = state.apply_fn({"params": params}, images, train=True)
    return loss_fn(logits, labels)

  loss, grads = jax.value_and_grad(_loss)(state.params)
  return state.apply_gradients(grads=grads), loss


@jax.jit
def eval_step(state: train_state.TrainState, images: jax.Array,
              labels: jax.Array):
  logits = state.apply_fn({"params": state.params}, images)
  accuracy = (jnp.argmax(logits, -1) == labels).mean()
  return loss_fn(logits, labels), accuracy


def synthetic_dataset(num: int, seed: int = 0,
                      noise: float = 0.05) -> Tuple[Any, Any]:
  """Deterministic synthetic MNIST-like data (the environment has no
  dataset egress). Labels are recoverable from the images (each class has a
  distinct template + noise), so models demonstrably learn."""
  import numpy as np
  rng = np.random.RandomState(seed)
  templates = rng.rand(NUM_CLASSES, *IMAGE_SHAPE).astype("float32")
  labels = rng.randint(0, NUM_CLASSES, size=num)
  images = templates[labels] + noise * rng.randn(num, *IMAGE_SHAPE) \
      .astype("float32")
  return images.astype("float32"), labels.astype("int32")
