"""Decoder-only Transformer: the long-context flagship model family.

The reference had no attention model at all (SURVEY.md §5); this family is
the showcase for the framework's TPU-native parallelism: tensor parallelism
(megatron-style column/row sharding via flax logical axes), FSDP parameter
sharding, and sequence parallelism through ring attention
(parallel/ring_attention.py). bfloat16 compute / float32 params+softmax,
rotary position embeddings, remat-friendly block structure.

Logical axis names map to mesh axes through
``parallel.sharding.LOGICAL_RULES``:
  vocab/heads/mlp -> tensor axis, embed -> fsdp axis,
  batch -> data+fsdp, sequence -> sequence axis.
"""

import dataclasses
import functools
from dataclasses import dataclass
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax import lax
import flax.linen as nn

from tensorflowonspark_tpu import ops
from tensorflowonspark_tpu.obs import device as obs_device
from tensorflowonspark_tpu.parallel import mesh as mesh_lib
from tensorflowonspark_tpu.parallel import ring_attention as ra


@dataclass(frozen=True)
class TransformerConfig:
  vocab_size: int = 32000
  num_layers: int = 12
  num_heads: int = 12
  d_model: int = 768
  d_ff: int = 3072
  max_seq_len: int = 2048
  dtype: Any = jnp.bfloat16
  remat: bool = True
  # What remat SAVES at block boundaries (active only when remat=True):
  # "none" recomputes everything in the backward (max memory savings,
  # ~21% step-time cost measured at the bench shape); "dots" saves MXU
  # (matmul) outputs and recomputes only cheap elementwise/VPU work — a
  # fraction of the recompute cost for most of the memory win, usually
  # the better batch-size lever on TPU (HBM-bound regime)
  remat_policy: str = "none"
  use_ring_attention: bool = False   # set True when seq is mesh-sharded
  # "auto": Pallas flash attention on TPU, dense elsewhere; "flash" forces
  # the kernel everywhere (interpret mode off-TPU — how CPU CI exercises
  # the production attention path); "dense" opts out
  attention_impl: str = "auto"
  # Grouped-query attention: 0 means = num_heads (vanilla MHA); 1 is MQA.
  # K/V are projected to this many heads and the per-layer KV cache stores
  # only them — a num_heads/num_kv_heads reduction in serving cache memory
  num_kv_heads: int = 0
  # Sliding-window attention (Mistral convention: each position attends
  # to its last `attention_window` positions, itself included; 0 = full
  # causal). The flash kernels bound their block loops to the window, so
  # attention FLOPs become O(seq·window); composed with ring attention,
  # ring steps whose KV shard slid out of the window collapse to zero
  # kernel-loop iterations. Training, prefill and KV-cache decode all
  # honor it.
  attention_window: int = 0
  # Project Q, K and V with ONE matmul (heads axis = num_heads + 2·kv_heads,
  # sliced after): one bigger MXU op instead of three smaller ones. Changes
  # the parameter tree ("qkv" instead of "q"/"k"/"v")
  fuse_qkv: bool = False
  # "auto": fused Pallas LayerNorm (ops.layer_norm) on TPU, flax elsewhere;
  # "fused" forces the kernel everywhere (interpret mode off-TPU — how CPU
  # CI exercises the production code path); "flax" opts out
  layer_norm_impl: str = "auto"
  # "fused": the ln2 -> MLP up-projection pair runs as ONE Pallas kernel
  # (ops.ln_matmul) — the normalized activation never round-trips HBM
  # (interpret mode off-TPU). Applies everywhere except decode: mesh-free
  # contexts run the plain kernel, sharded models map it per-shard
  # through shard_map (ops.ln_matmul_sharded). Param tree is IDENTICAL
  # either way (ln2/scale, mlp/up/kernel), so checkpoints are
  # interchangeable across settings. "off" opts out.
  ln_matmul_impl: str = "off"
  # "fused": the MLP's gelu -> down-projection pair runs as ONE Pallas
  # kernel (ops.gelu_matmul) — the [rows, d_ff] activated tensor (the
  # widest in the block) never round-trips HBM (interpret off-TPU).
  # Sharded models contract the tensor-sharded d_ff per shard and psum,
  # the same collective the unfused down-proj needs. Param tree is
  # IDENTICAL either way (mlp/down/kernel). "off" opts out.
  act_matmul_impl: str = "off"
  # Mixture-of-experts: when moe_experts > 0, every `moe_every`-th layer
  # (moe_every >= 1) replaces its dense MLP with an expert-routed FFN
  # (parallel.expert_parallel; experts shard over the `expert` mesh axis)
  moe_experts: int = 0
  moe_top_k: int = 1
  moe_every: int = 2
  # > 0 enables GShard-style all-to-all dispatch with this capacity factor
  # when the expert mesh axis is sharded (communication-optimal; overflow
  # tokens above ceil(T_local·k/E)·factor are dropped); 0 keeps the exact
  # dense-masked dispatch
  moe_capacity_factor: float = 0.0
  # "model": the KV cache stores cfg.dtype; "int8": per-token/head
  # symmetric int8 with f32 scales — decode is HBM-bound on re-reading
  # the cache every step, so halving its bytes (vs bf16) is a direct
  # decode-throughput lever at ~0.4% per-entry quantization error. The
  # flash prefill is unaffected (it attends the raw projections); the
  # dense paths apply the scales to k-indexed tensors (scores/probs), so
  # no dequantized cache-sized copy exists in the program — asserted on
  # compiled TPU HLO (tests/test_mosaic_gate.py).
  kv_cache_dtype: str = "model"
  # Paged KV decode cache (the serving plane's HBM-capacity lever,
  # serving/slots.py): kv_page_size > 0 replaces each layer's contiguous
  # [batch, max_seq_len, ...] decode cache with a shared page POOL
  # ([kv_num_pages, kv_page_size, kv_heads, head_dim]) plus a per-slot
  # page table ([batch, kv_pages_per_slot] int32) and a VECTOR cursor.
  # A slot then holds only the pages its token mass needs, so slot count
  # scales with actual tokens instead of num_slots × max_seq_len worst
  # case. Page 0 is the TRASH page: never allocated, the sink for
  # frozen-lane writes and unused table entries. Training/prefill paths
  # are untouched (paging applies to decode=True with vector cursors).
  kv_page_size: int = 0
  kv_num_pages: int = 0
  kv_pages_per_slot: int = 0
  # "gather": table lookup with the embed dim explicitly replicated first,
  # so SPMD slices the gather result instead of involuntarily rematerializing
  # the [B, S, D] activation (the round-2 dryrun warning); "one_hot": contract
  # a one-hot over the vocab-sharded table — no table all-gather at all, at
  # 2·B·S·V·D extra FLOPs, the right trade for huge vocabs on large meshes
  embed_lookup: str = "gather"

  def __post_init__(self):
    if self.moe_experts > 0 and self.moe_every < 1:
      raise ValueError("moe_every must be >= 1 when moe_experts > 0")
    if self.attention_impl not in ("auto", "flash", "dense"):
      raise ValueError("attention_impl must be 'auto', 'flash' or 'dense', "
                       "got %r" % (self.attention_impl,))
    if self.layer_norm_impl not in ("auto", "fused", "flax"):
      raise ValueError("layer_norm_impl must be 'auto', 'fused' or 'flax', "
                       "got %r" % (self.layer_norm_impl,))
    if self.num_kv_heads < 0:
      raise ValueError("num_kv_heads must be >= 0, got %d"
                       % (self.num_kv_heads,))
    if self.attention_window < 0:
      raise ValueError("attention_window must be >= 0 (0 = full causal), "
                       "got %d" % (self.attention_window,))
    if self.num_kv_heads and self.num_heads % self.num_kv_heads != 0:
      raise ValueError("num_kv_heads (%d) must divide num_heads (%d)"
                       % (self.num_kv_heads, self.num_heads))
    if self.embed_lookup not in ("gather", "one_hot"):
      raise ValueError("embed_lookup must be 'gather' or 'one_hot', got %r"
                       % (self.embed_lookup,))
    if self.ln_matmul_impl not in ("off", "fused"):
      raise ValueError("ln_matmul_impl must be 'off' or 'fused', got %r"
                       % (self.ln_matmul_impl,))
    if self.act_matmul_impl not in ("off", "fused"):
      raise ValueError("act_matmul_impl must be 'off' or 'fused', got %r"
                       % (self.act_matmul_impl,))
    if self.remat_policy not in ("none", "dots"):
      raise ValueError("remat_policy must be 'none' or 'dots', got %r"
                       % (self.remat_policy,))
    if self.kv_cache_dtype not in ("model", "int8"):
      raise ValueError("kv_cache_dtype must be 'model' or 'int8', got %r"
                       % (self.kv_cache_dtype,))
    if self.kv_page_size < 0 or self.kv_num_pages < 0 \
        or self.kv_pages_per_slot < 0:
      raise ValueError("kv_page_size/kv_num_pages/kv_pages_per_slot must "
                       "be >= 0")
    if self.kv_page_size > 0:
      if self.kv_num_pages < 2:
        raise ValueError(
            "paged KV needs kv_num_pages >= 2 (page 0 is the reserved "
            "trash page), got %d" % (self.kv_num_pages,))
      if self.kv_pages_per_slot < 1:
        raise ValueError("paged KV needs kv_pages_per_slot >= 1, got %d"
                         % (self.kv_pages_per_slot,))
      if self.kv_cache_dtype == "int8":
        raise ValueError("paged KV does not compose with the int8 cache "
                         "yet — use kv_cache_dtype='model'")

  @property
  def head_dim(self) -> int:
    assert self.d_model % self.num_heads == 0
    return self.d_model // self.num_heads

  @property
  def kv_heads(self) -> int:
    return self.num_kv_heads or self.num_heads


def _rotary(x, positions):
  """Rotary position embedding over the last (head_dim) axis."""
  d = x.shape[-1]
  half = d // 2
  freqs = jnp.exp(-jnp.arange(0, half, dtype=jnp.float32)
                  * (jnp.log(10000.0) / half))
  angles = positions[..., None].astype(jnp.float32) * freqs  # [B,S,half]
  cos = jnp.cos(angles)[:, :, None, :]
  sin = jnp.sin(angles)[:, :, None, :]
  x1, x2 = x[..., :half], x[..., half:]
  return jnp.concatenate(
      [x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1).astype(x.dtype)


def _flash_eligible(cfg: TransformerConfig, seq_len: int) -> bool:
  """Whether the Pallas flash kernel should handle this attention.

  "auto" uses the kernel on TPU only; "flash" FORCES it everywhere —
  interpret mode off-TPU, which is how CPU CI trains through the
  production attention path (same convention as ``layer_norm_impl``);
  "dense" always opts out. Either way the sequence must divide into
  kernel blocks.
  """
  if cfg.attention_impl == "dense":
    return False
  divisible = seq_len % min(128, max(1, seq_len)) == 0
  if cfg.attention_impl == "flash":
    if not divisible:
      # forcing must be honest: never silently degrade to dense
      raise ValueError(
          "attention_impl='flash' but the (local) sequence length %d does "
          "not divide into kernel blocks — pad the sequence or use 'auto'"
          % seq_len)
    return True
  # "auto" = the kernel wherever kernels are in play: the TPU backend
  # (even with interpret forced on for numerics debugging), or under
  # TOS_PALLAS_INTERPRET=0 (the deviceless Mosaic gate compiling FOR a
  # TPU topology from a CPU client — it must compile what the chip runs)
  return ops.pallas_kernels_enabled() and divisible


def _fused_ln_eligible(cfg: TransformerConfig) -> bool:
  """Whether blocks should use the fused Pallas LayerNorm ("auto" follows
  the same kernels-in-play policy as attention, see _flash_eligible)."""
  if cfg.layer_norm_impl == "flax":
    return False
  if cfg.layer_norm_impl == "fused":
    return True
  return ops.pallas_kernels_enabled()


class FusedLayerNorm(nn.Module):
  """LayerNorm via the fused Pallas kernel (ops.layer_norm).

  Same parameter ("scale"), stats dtype (f32) and eps as the flax
  ``nn.LayerNorm(use_bias=False)`` it replaces, so checkpoints are
  interchangeable across ``layer_norm_impl`` settings. With a mesh the
  kernel maps per-shard through shard_map (ops.layer_norm_sharded) — an
  unpartitioned pallas_call over GSPMD-sharded activations would force
  gathers (ROADMAP: ops coverage).
  """
  mesh: Optional[Any] = None
  eps: float = 1e-6
  interpret: bool = False

  @nn.compact
  def __call__(self, x):
    from tensorflowonspark_tpu import ops
    w = self.param("scale", nn.initializers.ones, (x.shape[-1],),
                   jnp.float32)
    # x goes in at its native dtype — the kernel computes f32 statistics
    # internally, so upcasting here would only double the HBM read traffic
    # (the downstream matmuls cast to cfg.dtype regardless)
    if self.mesh is not None:
      return ops.layer_norm_sharded(x, w, self.mesh, eps=self.eps,
                                    interpret=self.interpret)
    return ops.layer_norm(x, w, eps=self.eps, interpret=self.interpret)


def _make_layer_norm(cfg: TransformerConfig, mesh, name: str):
  if _fused_ln_eligible(cfg):
    return FusedLayerNorm(mesh=mesh, name=name,
                          interpret=ops.pallas_interpret())
  return nn.LayerNorm(dtype=jnp.float32, use_bias=False, name=name)


def _ln_matmul_call(x, ln_scale, w2, mesh=None):
  """The fused LN+matmul kernel with the shared off-TPU interpret policy
  (one definition for the attention and MLP call sites). With a mesh the
  kernel maps per-shard through shard_map (ops.ln_matmul_sharded), so the
  multi-chip training path gets the fusion too."""
  from tensorflowonspark_tpu.ops import ln_matmul as _ln_mm
  from tensorflowonspark_tpu.ops import ln_matmul_sharded as _ln_mm_sh
  interp = ops.pallas_interpret()
  if mesh is not None:
    return _ln_mm_sh(x, ln_scale, w2, mesh, interpret=interp)
  return _ln_mm(x, ln_scale, w2, interpret=interp)


# grouped-KV head broadcast: ONE definition, shared with the ring
# (parallel.ring_attention.expand_heads) so the grouping convention
# (blocked: KV head j serves query heads [j*g, (j+1)*g)) cannot drift
_expand_kv = ra.expand_heads


class _QKVKernel(nn.Module):
  """Declares the fused-QKV kernel at the same param path
  (``attn/qkv/kernel``) nn.DenseGeneral would, for the fused-LN path."""
  d_model: int
  n_heads_total: int
  head_dim: int
  heads_logical: Optional[str]

  @nn.compact
  def __call__(self):
    return self.param(
        "kernel",
        nn.with_logical_partitioning(
            nn.initializers.lecun_normal(),
            ("embed", self.heads_logical, "kv")),
        (self.d_model, self.n_heads_total, self.head_dim), jnp.float32)


def _heads_logical(n_heads: int, mesh) -> Optional[str]:
  """The logical axis for a heads dimension: "heads" (→ the
  tensor-parallel mesh axis) when the head count divides the tensor axis,
  else None (replicated). ONE rule shared by the projection kernels and
  the KV-cache constraint — a head count the axis can't divide (grouped
  KV heads, or the fused h+2·hk projection) must fall back to replication
  on BOTH sides or params and cache shard inconsistently (GSPMD then
  gathers the cache every decode step)."""
  t = 1 if mesh is None else mesh.shape.get(mesh_lib.AXIS_TENSOR, 1)
  return "heads" if n_heads % max(1, t) == 0 else None


class Attention(nn.Module):
  cfg: TransformerConfig
  mesh: Optional[Any] = None

  @nn.compact
  def __call__(self, x, positions, decode: bool = False, ln_scale=None):
    """With ``ln_scale`` (requires ``fuse_qkv``), ``x`` is the RAW
    residual stream and ln1 + the QKV projection run as one Pallas kernel
    (ops.ln_matmul); otherwise ``x`` arrives normalized."""
    cfg = self.cfg
    dense = lambda feats, logical, name: nn.DenseGeneral(  # noqa: E731
        feats, axis=-1, dtype=cfg.dtype, use_bias=False, name=name,
        kernel_init=nn.with_logical_partitioning(
            nn.initializers.lecun_normal(), logical))
    heads_axis = lambda n: _heads_logical(n, self.mesh)  # noqa: E731

    if cfg.fuse_qkv:
      # one MXU matmul for all three projections, sliced on the heads axis
      h, hk = cfg.num_heads, cfg.kv_heads
      if ln_scale is not None:
        kernel = _QKVKernel(cfg.d_model, h + 2 * hk, cfg.head_dim,
                            heads_axis(h + 2 * hk), name="qkv")()
        flat = _ln_matmul_call(
            x, ln_scale, kernel.reshape(cfg.d_model, -1).astype(cfg.dtype),
            mesh=self.mesh)
        qkv = flat.reshape(x.shape[:-1] + (h + 2 * hk, cfg.head_dim))
      else:
        qkv = dense((h + 2 * hk, cfg.head_dim),
                    ("embed", heads_axis(h + 2 * hk), "kv"), "qkv")(x)
      q = qkv[..., :h, :]
      k = qkv[..., h:h + hk, :]
      v = qkv[..., h + hk:, :]
    else:
      if ln_scale is not None:
        raise ValueError("ln-fused attention requires fuse_qkv")
      q = dense((cfg.num_heads, cfg.head_dim),
                ("embed", heads_axis(cfg.num_heads), "kv"), "q")(x)
      # GQA: K/V carry only kv_heads heads (= num_heads unless configured)
      k = dense((cfg.kv_heads, cfg.head_dim),
                ("embed", heads_axis(cfg.kv_heads), "kv"), "k")(x)
      v = dense((cfg.kv_heads, cfg.head_dim),
                ("embed", heads_axis(cfg.kv_heads), "kv"), "v")(x)

    if decode:
      return self._decode_attend(q, k, v)

    q = _rotary(q, positions)
    k = _rotary(k, positions)

    interp = ops.pallas_interpret()           # forced-flash CI runs
    if cfg.use_ring_attention and self.mesh is not None:
      # the ring takes GROUPED K/V as-is: unexpanded blocks rotate on the
      # ICI (num_heads/kv_heads less traffic); the flash kernels consume
      # them unexpanded and the dense block math fuses the expand
      seq_shards = self.mesh.shape.get(mesh_lib.AXIS_SEQUENCE, 1)
      local_seq = q.shape[1] // max(1, seq_shards)
      out = ra.ring_attention(q, k, v, self.mesh, causal=True,
                              use_flash=_flash_eligible(cfg, local_seq),
                              interpret=interp,
                              window=cfg.attention_window or None)
    else:
      if _flash_eligible(cfg, q.shape[1]):
        # the flash kernels consume grouped KV natively (grouped-aware
        # BlockSpec; cross-head dK/dV accumulation in the backward grid)
        from tensorflowonspark_tpu.ops import flash_attention
        out = flash_attention(q, k, v, causal=True, interpret=interp,
                              window=cfg.attention_window or None)
      else:
        # the dense reference attends at full head count: broadcast each
        # KV head to its query group (XLA fuses the repeat)
        out = ra.full_attention(q, _expand_kv(k, cfg.num_heads),
                                _expand_kv(v, cfg.num_heads), causal=True,
                                window=cfg.attention_window or None)

    return self._out_proj(out)

  def _out_proj(self, out):
    cfg = self.cfg
    return nn.DenseGeneral(
        cfg.d_model, axis=(-2, -1), dtype=cfg.dtype, use_bias=False,
        name="out",
        kernel_init=nn.with_logical_partitioning(
            nn.initializers.lecun_normal(), ("heads", "kv", "embed")))(out)

  def _decode_attend(self, q, k, v):
    """Incremental attention against a KV cache (serving path).

    Writes the new keys/values at the cache cursor, attends the query
    block against everything cached so far, and advances the cursor.
    Cache shape is [batch, max_seq_len, kv_heads, head_dim] per layer —
    under GQA the cache holds only the grouped KV heads (the serving
    memory win), and the attention einsums carry an explicit group axis
    instead of materializing an expanded cache. A fresh-cache prefill of
    a block-divisible segment runs through the GQA flash kernel instead
    of the seg × max_seq dense einsum (see the cond below).

    The cursor (``cache/index``) is either a SCALAR — all rows in
    lockstep, the classic batched-decode path — or a VECTOR of per-row
    cursors (``serving/``'s slot slabs): each row writes at its own
    offset (a vmapped update-slice, i.e. one scatter) and masks against
    its own length, so one jitted step can advance in-flight requests
    that are at different positions in their sequences.
    """
    cfg = self.cfg
    if cfg.kv_page_size > 0:
      return self._decode_attend_paged(q, k, v)
    b, seg, h, d = q.shape
    hk = cfg.kv_heads
    quant = cfg.kv_cache_dtype == "int8"
    cache_dt = jnp.int8 if quant else cfg.dtype
    cached_k = self.variable(
        "cache", "cached_k", jnp.zeros, (b, cfg.max_seq_len, hk, d),
        cache_dt)
    cached_v = self.variable(
        "cache", "cached_v", jnp.zeros, (b, cfg.max_seq_len, hk, d),
        cache_dt)
    if quant:
      k_scale = self.variable("cache", "k_scale", jnp.zeros,
                              (b, cfg.max_seq_len, hk), jnp.float32)
      v_scale = self.variable("cache", "v_scale", jnp.zeros,
                              (b, cfg.max_seq_len, hk), jnp.float32)
    cursor = self.variable("cache", "index",
                           lambda: jnp.zeros((), jnp.int32))
    idx = cursor.value
    vec = idx.ndim == 1          # per-slot cursors (serving slab decode)

    if vec:
      positions = idx[:, None] + jnp.arange(seg)[None, :]
    else:
      positions = idx + jnp.broadcast_to(jnp.arange(seg), (b, seg))
    q = _rotary(q, positions)
    k = _rotary(k, positions)

    def _cache_write(buf, val, trail):
      """Write ``val`` at the cursor: one dynamic_update_slice for the
      shared scalar cursor, a vmapped per-row update (one scatter) for
      per-slot cursors. ``trail``: trailing dims after the seq axis.

      Multi-token per-row writes go through an explicit OOB-dropping
      scatter instead: a speculative verify window may transiently
      overshoot ``max_seq_len`` on a lane whose remaining budget is
      smaller than the draft depth, and dynamic_update_slice would
      CLAMP the start — silently overwriting live attended KV below the
      cursor (breaking bit-parity) instead of dropping the overflow
      (which is never attended: accepted tokens stay within budget)."""
      if not vec:
        return jax.lax.dynamic_update_slice(
            buf, val, (0, idx) + (0,) * trail)
      if seg == 1:
        # single-token decode can never overshoot (cursor < max_seq_len
        # by the submit-time budget check): keep the cheap update-slice
        return jax.vmap(
            lambda row, v, i: jax.lax.dynamic_update_slice(
                row, v, (i,) + (0,) * trail))(buf, val, idx)
      rows = jnp.broadcast_to(jnp.arange(b)[:, None], (b, seg)).reshape(-1)
      pos = positions.reshape(-1)          # OOB entries drop, not clamp
      return buf.at[rows, pos].set(val.reshape((b * seg,) + val.shape[2:]))
    # tensor-parallel serving: keep the cache sharded on its (grouped)
    # heads dim so each chip holds 1/t of the KV bytes and attends its own
    # head slice — without the constraint GSPMD may gather the cache.
    # Same divisibility rule as the projection kernels (_heads_logical).
    kv_spec = ("batch", None, _heads_logical(hk, self.mesh), "kv")

    def _quantize(x):
      # per-token/head symmetric int8 over the head dim
      xf = x.astype(jnp.float32)
      amax = jnp.max(jnp.abs(xf), axis=-1)               # [b, seg, hk]
      s = jnp.maximum(amax, 1e-8) / 127.0
      v8 = jnp.clip(jnp.round(xf / s[..., None]), -127, 127)
      return v8.astype(jnp.int8), s

    if quant:
      k8, ks = _quantize(k)
      v8, vs = _quantize(v)
      k_store, v_store = k8, v8
      k_scale.value = _constrain(_cache_write(k_scale.value, ks, 1),
                                 kv_spec[:3], self.mesh)
      v_scale.value = _constrain(_cache_write(v_scale.value, vs, 1),
                                 kv_spec[:3], self.mesh)
    else:
      k_store, v_store = k.astype(cfg.dtype), v.astype(cfg.dtype)
    cached_k.value = _constrain(_cache_write(cached_k.value, k_store, 2),
                                kv_spec, self.mesh)
    cached_v.value = _constrain(_cache_write(cached_v.value, v_store, 2),
                                kv_spec, self.mesh)
    cursor.value = idx + seg

    scale = 1.0 / (d ** 0.5)

    def _dense_attend(_):
      # q regrouped [b, seg, kv_head, group, d]: query head i = KV head
      # i//g; attends the whole cache with the causal+unwritten mask.
      # int8 cache: the scales apply to K-INDEXED tensors — scores
      # (sum_d q·k8·s[k] = (sum_d q·k8)·s[k]) and probs (folding v's
      # scale) — so no dequantized cache-sized f32 tensor exists in the
      # program AT ALL; the dots consume the int8 values via a bare
      # convert (the per-step HBM traffic is the int8 bytes by
      # construction, not by hoping a broadcast-multiply fuses)
      kf = cached_k.value.astype(jnp.float32)
      vf = cached_v.value.astype(jnp.float32)
      qg = q.reshape(b, seg, hk, h // hk, d).astype(jnp.float32)
      scores = jnp.einsum("bqhgd,bkhd->bhgqk", qg, kf) * scale
      if quant:
        # [b, max, hk] -> [b, hk, 1, 1, max] over the scores' k dim
        ks5 = k_scale.value.transpose(0, 2, 1)[:, :, None, None, :]
        scores = scores * ks5
      if vec:
        # per-row cursors: each slot masks against ITS length
        q_pos = idx[:, None, None] + jnp.arange(seg)[None, :, None]
        k_pos = jnp.arange(cfg.max_seq_len)[None, None, :]
        keep = k_pos <= q_pos                         # [b, seg, max]
        if cfg.attention_window:
          keep = jnp.logical_and(keep,
                                 k_pos > q_pos - cfg.attention_window)
        mask = keep[:, None, None]                    # [b,1,1,seg,max]
      else:
        q_pos = idx + jnp.arange(seg)[:, None]        # [seg, 1]
        k_pos = jnp.arange(cfg.max_seq_len)[None, :]  # [1, max]
        keep = k_pos <= q_pos                         # causal + unwritten
        if cfg.attention_window:
          # sliding window: cache entries older than the window are
          # masked (they stay in the cache buffer; the mask is what
          # bounds decode)
          keep = jnp.logical_and(keep,
                                 k_pos > q_pos - cfg.attention_window)
        mask = keep[None, None, None]
      scores = jnp.where(mask, scores, -1e30)
      probs = jax.nn.softmax(scores, axis=-1)
      if quant:
        vs5 = v_scale.value.transpose(0, 2, 1)[:, :, None, None, :]
        probs = probs * vs5
      o = jnp.einsum("bhgqk,bkhd->bqhgd", probs, vf)
      return o.reshape(b, seg, h, d).astype(q.dtype)

    # PREFILL fast path: a fresh-cache multi-token segment attends only
    # within itself (causal), so the flash kernel runs it O(seg²)-tiled
    # over the grouped K/V directly — the dense path does seg × max_seq
    # work against a mostly-empty cache and materializes f32 scores. The
    # cursor check is traced, so chunked prefill (idx > 0, where queries
    # must also see earlier cache entries) falls through to the dense
    # branch of the SAME cond and stays correct.
    # Under a >1-device mesh the kernel needs a shard_map wrap — GSPMD
    # refuses to auto-partition Mosaic kernels — with query and KV heads
    # sharded CONSISTENTLY (both over tensor, or both replicated):
    # mismatched head layouts would break the kernel's local i//g
    # query→KV-head mapping, so such configs prefill through the dense
    # einsums instead.
    single = self.mesh is None or self.mesh.size == 1
    heads_consistent = single or (
        _heads_logical(h, self.mesh) == _heads_logical(hk, self.mesh))
    use_flash_prefill = False
    if not vec and heads_consistent and seg > 1 \
        and cfg.attention_impl != "dense":
      ecfg = cfg
      if cfg.attention_impl == "flash" and seg % min(128, seg) != 0:
        # serving accepts arbitrary prompt lengths the caller doesn't
        # block-align; degrade forced-flash to "auto" for this internal
        # shape rather than raise (the _generate_fn precedent)
        ecfg = dataclasses.replace(cfg, attention_impl="auto")
      use_flash_prefill = _flash_eligible(ecfg, seg)
    if use_flash_prefill:
      from tensorflowonspark_tpu.ops import flash_attention

      def _flash_prefill(_):
        interp = ops.pallas_interpret()
        win = cfg.attention_window or None
        if single:
          return flash_attention(q, k, v, causal=True, interpret=interp,
                                 window=win).astype(q.dtype)
        from tensorflowonspark_tpu.utils.compat import jax_shard_map as shard_map
        from jax.sharding import PartitionSpec as P
        batch_axes = mesh_lib.data_axes(self.mesh) or None
        t_ax = mesh_lib.AXIS_TENSOR \
            if _heads_logical(hk, self.mesh) == "heads" else None
        spec = P(batch_axes, None, t_ax, None)
        fn = shard_map(
            lambda qq, kk, vv: flash_attention(qq, kk, vv, causal=True,
                                               interpret=interp,
                                               window=win),
            mesh=self.mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_vma=False)
        return fn(q, k, v).astype(q.dtype)

      out = lax.cond(idx == 0, _flash_prefill, _dense_attend, None)
    else:
      out = _dense_attend(None)
    return self._out_proj(out)

  def _decode_attend_paged(self, q, k, v):
    """Incremental attention against a PAGED KV cache (serving slabs).

    Per layer the cache is a page POOL — ``pages_k``/``pages_v``
    ``[kv_num_pages, kv_page_size, kv_heads, head_dim]`` — addressed
    through a per-slot ``page_table [batch, kv_pages_per_slot] int32``
    and the VECTOR cursor ``index [batch]``: slot ``b``'s token at
    position ``p`` lives in page ``page_table[b, p // page_size]`` at
    offset ``p % page_size``. Writes are one scatter over the flattened
    (page, offset) indices; reads gather each slot's page list back into
    a ``[batch, pages_per_slot·page_size, ...]`` view and run the same
    masked dense attention as the vector-cursor contiguous branch.

    Page 0 is the TRASH page: unused table entries point at it, so a
    frozen lane (cursor 0, table all-zero) scatters its garbage there
    and positions past ``pages_per_slot`` pages clip onto it — nothing
    a live slot attends is ever touched, because the mask admits only
    ``k_pos <= q_pos`` and every position a live slot can reach lies in
    its own (or its shared read-only prefix) pages.
    """
    cfg = self.cfg
    b, seg, h, d = q.shape
    hk = cfg.kv_heads
    ps, pp = cfg.kv_page_size, cfg.kv_pages_per_slot
    span = pp * ps                       # a slot's maximum visible tokens
    pages_k = self.variable(
        "cache", "pages_k", jnp.zeros, (cfg.kv_num_pages, ps, hk, d),
        cfg.dtype)
    pages_v = self.variable(
        "cache", "pages_v", jnp.zeros, (cfg.kv_num_pages, ps, hk, d),
        cfg.dtype)
    table = self.variable("cache", "page_table", jnp.zeros, (b, pp),
                          jnp.int32)
    # paged decode is slot-shaped by construction: the cursor is born a
    # vector (the contiguous branch's scalar/vector duality doesn't apply)
    cursor = self.variable("cache", "index", jnp.zeros, (b,), jnp.int32)
    idx = cursor.value

    positions = idx[:, None] + jnp.arange(seg)[None, :]        # [b, seg]
    q = _rotary(q, positions)
    k = _rotary(k, positions)

    # write: token position -> (page, offset) through the table. A
    # position inside the span but past the slot's allocation resolves
    # through an unused table entry to the trash page; a position PAST
    # the span (a speculative verify window overshooting a full slot)
    # is forced to trash explicitly — the clip would otherwise alias it
    # into the slot's LAST page over live attended tokens
    page_slot = jnp.clip(positions // ps, 0, pp - 1)           # [b, seg]
    page_ids = jnp.take_along_axis(table.value, page_slot, axis=1)
    page_ids = jnp.where(positions < pp * ps, page_ids, 0)
    offs = positions % ps
    flat_pages = page_ids.reshape(-1)
    flat_offs = offs.reshape(-1)
    # tensor-parallel serving: keep the page pools sharded on the
    # (grouped) heads dim — the same constraint (and rationale) as the
    # contiguous branch: without it GSPMD may gather the pool, the
    # largest HBM object in serving, every step
    pool_spec = (None, None, _heads_logical(hk, self.mesh), "kv")
    pages_k.value = _constrain(
        pages_k.value.at[flat_pages, flat_offs].set(
            k.astype(cfg.dtype).reshape(b * seg, hk, d)),
        pool_spec, self.mesh)
    pages_v.value = _constrain(
        pages_v.value.at[flat_pages, flat_offs].set(
            v.astype(cfg.dtype).reshape(b * seg, hk, d)),
        pool_spec, self.mesh)
    cursor.value = idx + seg

    # read: gather each slot's pages into its contiguous token view
    kf = pages_k.value[table.value].reshape(b, span, hk, d) \
        .astype(jnp.float32)
    vf = pages_v.value[table.value].reshape(b, span, hk, d) \
        .astype(jnp.float32)
    scale = 1.0 / (d ** 0.5)
    qg = q.reshape(b, seg, hk, h // hk, d).astype(jnp.float32)
    scores = jnp.einsum("bqhgd,bkhd->bhgqk", qg, kf) * scale
    q_pos = idx[:, None, None] + jnp.arange(seg)[None, :, None]
    k_pos = jnp.arange(span)[None, None, :]
    keep = k_pos <= q_pos                                  # [b, seg, span]
    if cfg.attention_window:
      keep = jnp.logical_and(keep, k_pos > q_pos - cfg.attention_window)
    scores = jnp.where(keep[:, None, None], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    o = jnp.einsum("bhgqk,bkhd->bqhgd", probs, vf)
    return self._out_proj(o.reshape(b, seg, h, d).astype(q.dtype))


class _UpKernel(nn.Module):
  """Declares the MLP up-projection kernel at the same param path
  (``mlp/up/kernel``) nn.Dense would, for the fused-LN path that feeds it
  to ops.ln_matmul instead of a Dense call."""
  d_model: int
  d_ff: int

  @nn.compact
  def __call__(self):
    return self.param(
        "kernel",
        nn.with_logical_partitioning(nn.initializers.lecun_normal(),
                                     ("embed", "mlp")),
        (self.d_model, self.d_ff), jnp.float32)


class _DownKernel(nn.Module):
  """Declares the MLP down-projection kernel at the same param path
  (``mlp/down/kernel``) nn.Dense would, for the fused gelu+matmul path
  that feeds it to ops.gelu_matmul instead of a Dense call."""
  d_ff: int
  d_model: int

  @nn.compact
  def __call__(self):
    return self.param(
        "kernel",
        nn.with_logical_partitioning(nn.initializers.lecun_normal(),
                                     ("mlp", "embed")),
        (self.d_ff, self.d_model), jnp.float32)


def _gelu_matmul_call(x, w, mesh=None):
  """The fused GELU+matmul kernel with the shared off-TPU interpret
  policy; per-shard through shard_map under a mesh (with the tensor-axis
  psum the unfused down-proj needs anyway)."""
  from tensorflowonspark_tpu.ops import gelu_matmul, gelu_matmul_sharded
  interp = ops.pallas_interpret()
  if mesh is not None:
    return gelu_matmul_sharded(x, w, mesh, interpret=interp)
  return gelu_matmul(x, w, interpret=interp)


class MLPBlock(nn.Module):
  cfg: TransformerConfig
  mesh: Optional[Any] = None
  act_fused: bool = False

  @nn.compact
  def __call__(self, x, ln_scale=None):
    """With ``ln_scale`` (the preceding LayerNorm's weight), the norm and
    the up-projection run as one Pallas kernel over the RAW ``x``; without
    it, ``x`` is expected already normalized (the regular path). With
    ``act_fused``, gelu + the down-projection run as one Pallas kernel
    over the pre-activation (ops.gelu_matmul) — combined with the LN
    fusion the whole MLP is two kernels with nothing unfused between."""
    cfg = self.cfg
    if ln_scale is not None:
      kernel = _UpKernel(cfg.d_model, cfg.d_ff, name="up")()
      h = _ln_matmul_call(x, ln_scale, kernel.astype(cfg.dtype),
                          mesh=self.mesh)
    else:
      h = nn.Dense(cfg.d_ff, dtype=cfg.dtype, use_bias=False, name="up",
                   kernel_init=nn.with_logical_partitioning(
                       nn.initializers.lecun_normal(), ("embed", "mlp")))(x)
    if self.act_fused:
      down = _DownKernel(cfg.d_ff, cfg.d_model, name="down")()
      return _gelu_matmul_call(h, down.astype(cfg.dtype), mesh=self.mesh)
    h = nn.gelu(h)
    return nn.Dense(cfg.d_model, dtype=cfg.dtype, use_bias=False,
                    name="down",
                    kernel_init=nn.with_logical_partitioning(
                        nn.initializers.lecun_normal(), ("mlp", "embed")))(h)


class MoEBlock(nn.Module):
  """Expert-routed FFN (see parallel.expert_parallel): dense masked
  dispatch over the ``expert`` mesh axis, top-k routing, with the
  load-balancing auxiliary loss sown under ``intermediates/moe_aux``.

  Constraint: tokens must not be sequence-sharded (MoE layers flatten
  [B, S, D] to tokens, which composes with data/expert sharding only).
  """
  cfg: TransformerConfig
  mesh: Optional[Any] = None

  @nn.compact
  def __call__(self, x):
    from tensorflowonspark_tpu.parallel import expert_parallel as ep

    cfg = self.cfg
    d = x.shape[-1]
    params = {
        "w_gate": self.param(
            "w_gate", nn.initializers.lecun_normal(),
            (d, cfg.moe_experts), jnp.float32),
        "w_up": self.param(
            "w_up", nn.with_logical_partitioning(
                nn.initializers.lecun_normal(), ("expert", "embed", "mlp")),
            (cfg.moe_experts, d, cfg.d_ff), jnp.float32),
        "w_down": self.param(
            "w_down", nn.with_logical_partitioning(
                nn.initializers.lecun_normal(), ("expert", "mlp", "embed")),
            (cfg.moe_experts, cfg.d_ff, d), jnp.float32),
    }
    flat = x.reshape(-1, d)
    # one router forward feeds both the dispatch and the aux loss
    dispatch, combine, probs = ep.route(params, flat, cfg.moe_top_k)
    expert_sharded = self.mesh is not None and \
        self.mesh.shape.get(mesh_lib.AXIS_EXPERT, 1) > 1
    if expert_sharded and cfg.moe_capacity_factor > 0:
      # communication-optimal path: tokens exchanged over the expert axis
      # with two all-to-alls, each device runs only its experts (the
      # router re-runs per-shard inside the body — a tiny matmul)
      y = ep.moe_ffn_a2a(params, flat, self.mesh,
                         capacity_factor=cfg.moe_capacity_factor,
                         top_k=cfg.moe_top_k)
    elif expert_sharded:
      y = ep.moe_ffn(params, flat, self.mesh, top_k=cfg.moe_top_k,
                     routing=(dispatch, combine))
    else:
      y = ep.moe_ffn_reference(params, flat, top_k=cfg.moe_top_k,
                               routing=(dispatch, combine))
    self.sow("intermediates", "moe_aux",
             ep.aux_loss_from(probs, dispatch, cfg.moe_top_k))
    return y.reshape(x.shape).astype(x.dtype)


def _constrain(x, spec, mesh):
  """Activation sharding constraint with explicit rules + mesh.

  ``nn.with_logical_constraint`` without a rules context (or mesh) is a
  SILENT NO-OP — flax returns ``x`` unchanged. Discovered in round 3: every
  activation constraint in this model was inert, which is why the round-2
  multichip dryrun showed SPMD involuntarily rematerializing the embedding
  activations. Passing ``rules=LOGICAL_RULES, mesh=mesh`` makes the
  constraint real; ``mesh=None`` (single device) stays a no-op by design.
  """
  if mesh is None:
    return x
  from tensorflowonspark_tpu.parallel import sharding as sh
  return nn.with_logical_constraint(x, spec, rules=sh.LOGICAL_RULES,
                                    mesh=mesh)


class _LNScale(nn.Module):
  """Declares a LayerNorm scale at the same param path ("<name>/scale")
  the norm modules would, for the fused ln+matmul path that consumes the
  raw activations plus this weight in one kernel."""
  features: int

  @nn.compact
  def __call__(self):
    return self.param("scale", nn.initializers.ones, (self.features,),
                      jnp.float32)


class Block(nn.Module):
  cfg: TransformerConfig
  mesh: Optional[Any] = None
  use_moe: bool = False

  @nn.compact
  def __call__(self, x, positions, decode: bool = False):
    cfg = self.cfg
    fuse_ln = cfg.ln_matmul_impl == "fused" and not decode
    if fuse_ln and cfg.fuse_qkv:
      # ln1 + the fused QKV projection as ONE kernel over the raw
      # residual stream (param paths unchanged: ln1/scale, attn/qkv)
      scale1 = _LNScale(cfg.d_model, name="ln1")()
      x = x + Attention(cfg, self.mesh, name="attn")(x, positions,
                                                     ln_scale=scale1)
    else:
      y = _make_layer_norm(cfg, self.mesh, "ln1")(x)
      x = x + Attention(cfg, self.mesh, name="attn")(y, positions,
                                                     decode=decode)
    act_fused = cfg.act_matmul_impl == "fused" and not decode
    if fuse_ln and not self.use_moe:
      # ln2 + up-projection as ONE kernel over the raw residual stream;
      # same param paths as the unfused branch (ln2/scale, mlp/up/kernel)
      scale = _LNScale(cfg.d_model, name="ln2")()
      x = x + MLPBlock(cfg, self.mesh, act_fused,
                       name="mlp")(x, ln_scale=scale)
    else:
      y = _make_layer_norm(cfg, self.mesh, "ln2")(x)
      if self.use_moe:
        x = x + MoEBlock(cfg, self.mesh, name="moe")(y)
      else:
        x = x + MLPBlock(cfg, self.mesh, act_fused, name="mlp")(y)
    if decode:
      return x
    return _constrain(x, ("batch", "sequence", "embed"), self.mesh)


def _remat_block(cfg: TransformerConfig):
  """``nn.remat(Block)`` under the configured save policy.

  "none": only block boundaries survive to the backward (everything
  inside recomputes — max memory savings). "dots": MXU (matmul) outputs
  are saved and only elementwise/VPU work recomputes
  (``jax.checkpoint_policies.dots_with_no_batch_dims_saveable``) — on an
  HBM-bound chip this buys most of the batch-size headroom at a fraction
  of the ~21% full-recompute cost, making bigger-batch configs the MFU
  lever they should be.
  """
  if cfg.remat_policy == "dots":
    return nn.remat(
        Block,
        policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable)
  return nn.remat(Block)


class TiedEmbed(nn.Module):
  """Tied input/output embedding with SPMD-friendly lookup layouts.

  Drop-in for the ``nn.Embed`` it replaces — same param path
  (``params["embed"]["embedding"]``), same ``attend`` contract — but the
  lookup controls its shardings: under a mesh where the table is
  (vocab->tensor, embed->fsdp) and activations are (batch, sequence)-sharded,
  a naive gather leaves SPMD resharding a [B, S, D] tensor it can only
  "involuntarily fully rematerialize" (the round-2 MULTICHIP warning).

  * ``gather``: constrain the lookup table to ("vocab", None) first — one
    explicit all-gather of the small [V, D] table over the embed axis — so
    the gather result is born replicated on D and SPMD's repartition to
    (batch, sequence, embed) is a local slice.
  * ``one_hot``: contract one_hot(tokens) against the still-sharded table;
    the vocab contraction becomes a psum over the tensor axis and the result
    arrives already (batch, sequence)-sharded with D on fsdp. No table
    all-gather at all; costs 2·B·S·V·D FLOPs.
  """
  cfg: TransformerConfig
  mesh: Optional[Any] = None

  def setup(self):
    self.embedding = self.param(
        "embedding",
        nn.with_logical_partitioning(nn.initializers.normal(0.02),
                                     ("vocab", "embed")),
        (self.cfg.vocab_size, self.cfg.d_model), jnp.float32)

  def __call__(self, tokens):
    cfg = self.cfg
    table = jnp.asarray(self.embedding, cfg.dtype)
    if cfg.embed_lookup == "one_hot":
      one_hot = jax.nn.one_hot(tokens, cfg.vocab_size, dtype=cfg.dtype)
      one_hot = _constrain(one_hot, ("batch", "sequence", "vocab"),
                           self.mesh)
      return jnp.einsum("bsv,vd->bsd", one_hot, table)
    table = _constrain(table, ("vocab", None), self.mesh)
    return jnp.take(table, tokens, axis=0)

  def attend(self, x):
    table = jnp.asarray(self.embedding, self.cfg.dtype)
    return jnp.einsum("...d,vd->...v", x, table)


class Transformer(nn.Module):
  """Causal LM. Input: int32 token ids [batch, seq]; output: logits."""
  cfg: TransformerConfig
  mesh: Optional[Any] = None

  @nn.compact
  def __call__(self, tokens, decode: bool = False,
               return_hidden: bool = False,
               exit_layer: Optional[int] = None):
    """``exit_layer`` (static) runs only the first N blocks before the
    final norm + tied projection — the SHALLOW-EXIT draft of
    self-speculative decoding (serving/slots.py): the draft is a prefix
    of the target's own layers, so it shares params and KV slabs and
    needs no second model. Untouched layers' cache entries pass through
    an ``apply`` unchanged (flax keeps unvisited collection entries), so
    a shallow decode step advances only the visited layers' cursors."""
    cfg = self.cfg
    if exit_layer is not None and not 1 <= exit_layer <= cfg.num_layers:
      raise ValueError("exit_layer must be in [1, num_layers=%d], got %r"
                       % (cfg.num_layers, exit_layer))
    positions = jnp.broadcast_to(jnp.arange(tokens.shape[1]), tokens.shape)
    emb = TiedEmbed(cfg, self.mesh, name="embed")
    x = emb(tokens)
    if not decode:
      x = _constrain(x, ("batch", "sequence", "embed"), self.mesh)

    block = Block
    if cfg.remat and not decode:
      block = _remat_block(cfg)
    for i in range(cfg.num_layers if exit_layer is None else exit_layer):
      use_moe = (cfg.moe_experts > 0
                 and i % cfg.moe_every == cfg.moe_every - 1)
      layer = block(cfg, self.mesh, use_moe, name="layer_%d" % i)
      x = layer(x, positions, True) if decode else layer(x, positions)

    x = _make_layer_norm(cfg, self.mesh, "ln_f")(x)
    if return_hidden:
      # pre-projection hidden states for the fused blocked loss
      # (:func:`causal_lm_loss_blocked`) — callers project against the
      # tied table chunk-by-chunk instead of materializing [B, S, V]
      return x.astype(cfg.dtype)
    # tied output projection (attend to the embedding table)
    logits = emb.attend(x.astype(cfg.dtype))
    return logits.astype(jnp.float32)


@functools.lru_cache(maxsize=8)
def _generate_fn(cfg: TransformerConfig, plen: int, num_steps: int):
  """Cached jitted decode loop; params/buf are runtime args so repeated
  generate calls reuse one compilation and params are never baked in as
  compile-time constants."""
  total = plen + num_steps
  if cfg.attention_impl == "flash" and total % min(128, max(1, total)) != 0:
    # the generation buffer's length (plen + num_steps) is an internal
    # shape callers don't control block-alignment of — a forced-flash
    # model must still generate, so degrade to "auto" here (flash when
    # the buffer divides, dense otherwise) rather than raise
    cfg = dataclasses.replace(cfg, attention_impl="auto")
  model = Transformer(cfg)

  def decode(params, buf):
    # recompile sentinel seam (obs/device.py): one trace = one jit-cache
    # entry; steady-state generation must never bump this post-warmup
    obs_device.note_trace("transformer.generate")

    def step(i, buf):
      logits = model.apply({"params": params}, buf)     # [b, total, V]
      pos = plen + i - 1
      last = lax.dynamic_index_in_dim(logits, pos, axis=1, keepdims=False)
      nxt = jnp.argmax(last, axis=-1).astype(jnp.int32)  # [b]
      return lax.dynamic_update_slice(buf, nxt[:, None], (0, plen + i))

    return lax.fori_loop(0, num_steps, step, buf)

  return jax.jit(decode)


def greedy_generate(params, cfg: TransformerConfig, prompt, num_steps: int,
                    mesh=None):
  """Greedy autoregressive decoding (jit-compiled fixed-length loop).

  prompt: int32 [batch, prompt_len]. Returns [batch, prompt_len+num_steps].
  Recomputes the full forward per step — simple and cache-free; use
  :func:`greedy_generate_kv` for the O(1)-per-token serving path. The
  compiled loop is cached per (config, prompt_len, num_steps).
  """
  del mesh  # generation runs wherever params live; sharding via params
  b, plen = prompt.shape
  buf = jnp.zeros((b, plen + num_steps), jnp.int32)
  buf = lax.dynamic_update_slice(buf, prompt.astype(jnp.int32), (0, 0))
  return _generate_fn(cfg, plen, num_steps)(params, buf)


def _select_token(logits, rng, temperature: float, top_k: int):
  """Greedy (temperature == 0) or top-k temperature sampling."""
  if temperature == 0.0:
    return jnp.argmax(logits, axis=-1).astype(jnp.int32)
  scaled = logits.astype(jnp.float32) / temperature
  if top_k > 0 and top_k < logits.shape[-1]:
    kth = lax.top_k(scaled, top_k)[0][..., -1:]   # dedicated TPU top-k op
    scaled = jnp.where(scaled < kth, -1e30, scaled)
  return jax.random.categorical(rng, scaled, axis=-1).astype(jnp.int32)


# 32 entries, not 8: serving traffic (and the parity suites) legitimately
# touch dozens of (batch, prompt_len, num_steps) shapes — an 8-entry
# cache thrashes and recompiles shapes it just evicted. Entries hold
# compiled executables (code, not params), so the residency cost is MBs
@functools.lru_cache(maxsize=32)
def _kv_generate_fn(cfg: TransformerConfig, batch: int, plen: int,
                    num_steps: int, temperature: float, top_k: int,
                    mesh=None, eos_id=None, pad_id: int = 0):
  """Cached jitted KV-cache decode: prefill once, then one token per step
  against the per-layer key/value cache — O(1) attention work per new
  token instead of a full-sequence recompute.

  With ``eos_id``, the scan carries a per-sequence done-mask: a row that
  sampled ``eos_id`` keeps its EOS token and emits ``pad_id`` for every
  later step (its unavoidable padding work inside this fixed-shape loop —
  the ``serving/`` slot engine is the path that RECLAIMS those steps by
  freeing the slot). The loop itself stays fixed-length so the compiled
  program's shape never depends on data.

  With ``mesh``, decode is tensor-parallel (the reference's dedicated
  inference layer scaled past one chip, TFModel.scala:245-292): params go
  in under their logical shardings (heads over the tensor axis), the KV
  cache stays heads-sharded on-chip (``_decode_attend``'s constraint), the
  batch dim rides the data axes, and the output gathers replicated. The
  jit carries explicit in/out shardings so host-resident bundle params are
  placed correctly on first call."""
  model = Transformer(cfg, mesh=mesh)

  def decode(params, prompt, rng):
    obs_device.note_trace("transformer.kv_generate")
    variables = {"params": params, "cache": _zero_cache(model, batch)}
    logits, mutated = model.apply(variables, prompt, decode=True,
                                  mutable=["cache"])
    rng, sub = jax.random.split(rng)
    nxt = _select_token(logits[:, -1], sub, temperature, top_k)
    done = (nxt == eos_id) if eos_id is not None \
        else jnp.zeros((batch,), jnp.bool_)

    def step(carry, _):
      cache, tok, rng, done = carry
      logits, mutated = model.apply({"params": params, "cache": cache},
                                    tok[:, None], decode=True,
                                    mutable=["cache"])
      rng, sub = jax.random.split(rng)
      new = _select_token(logits[:, -1], sub, temperature, top_k)
      if eos_id is not None:
        new = jnp.where(done, jnp.int32(pad_id), new)
        done = jnp.logical_or(done, new == eos_id)
      return (mutated["cache"], new, rng, done), new

    # prefill produced g_1; each scan iteration computes one further token
    _, toks = lax.scan(step, (mutated["cache"], nxt, rng, done), None,
                       length=num_steps - 1)
    generated = jnp.concatenate([nxt[:, None], toks.T], axis=1) \
        if num_steps > 1 else nxt[:, None]
    return jnp.concatenate([prompt, generated], axis=1)

  if mesh is None:
    return jax.jit(decode)
  from tensorflowonspark_tpu.parallel import sharding as sh
  abs_boxed = jax.eval_shape(
      lambda: model.init(jax.random.PRNGKey(0),
                         jnp.zeros((batch, 1), jnp.int32),
                         decode=True))["params"]
  param_sharding = sh.param_sharding_from_boxed(abs_boxed, mesh)
  jitted = jax.jit(decode,
                   in_shardings=(param_sharding, sh.batch_sharding(mesh),
                                 sh.replicated(mesh)),
                   out_shardings=sh.replicated(mesh))

  def call(params, prompt, rng):
    # checkpoint-restored params arrive COMMITTED to one device and jit
    # refuses to reshard committed args — device_put places them onto the
    # mesh (a no-op for already-placed arrays, so steady-state serving
    # pays nothing)
    return jitted(jax.device_put(params, param_sharding), prompt, rng)

  call.jitted = jitted   # AOT surface (mosaic_gate lowers this directly)
  return call


def greedy_generate_kv(params, cfg: TransformerConfig, prompt,
                       num_steps: int, temperature: float = 0.0,
                       top_k: int = 0, rng=None, mesh=None,
                       eos_id=None, pad_id: int = 0):
  """Decoding with a per-layer KV cache (the serving path).

  Greedy by default; ``temperature > 0`` samples (optionally top-k
  filtered) using ``rng``. Semantically identical to
  :func:`greedy_generate` when greedy, but each new token attends against
  cached keys/values rather than recomputing the full prefix — requires
  prompt_len + num_steps <= cfg.max_seq_len. With ``mesh``, decode runs
  tensor-parallel: heads (and the heads-sharded KV cache) split over the
  tensor axis, batch over the data axes (see ``_kv_generate_fn``).

  ``eos_id`` enables per-sequence stopping: a row that emits ``eos_id``
  keeps the EOS token and every later position is ``pad_id`` (the output
  shape stays [b, plen + num_steps]); tokens before the stop are
  identical to the eos-free decode. The loop still runs ``num_steps``
  device steps — reclaiming finished rows' steps is what
  ``serving.ServingEngine`` (continuous batching) is for.
  """
  b, plen = prompt.shape
  if plen + num_steps > cfg.max_seq_len:
    raise ValueError(
        "generation of %d tokens from a %d-token prompt exceeds the "
        "cfg.max_seq_len=%d cache" % (num_steps, plen, cfg.max_seq_len))
  if temperature < 0:
    raise ValueError("temperature must be >= 0, got %r" % temperature)
  if eos_id is not None and int(eos_id) == int(pad_id):
    raise ValueError("eos_id and pad_id must differ (both %d): a padded "
                     "position would read as a fresh stop" % int(pad_id))
  if rng is None:
    if temperature != 0:
      # a silent fixed key would make every "sampled" call identical
      raise ValueError("temperature > 0 requires an explicit rng key")
    rng = jax.random.PRNGKey(0)
  pad = 0
  if mesh is not None:
    # the batch dim shards over the data axes; a ragged final serving
    # batch (pipeline.yield_batch's `if count > 0` tail) is padded up to
    # the axis extent and sliced back after — decode rows are independent,
    # so padding never changes real rows' greedy tokens (with
    # temperature > 0 the padded shape shifts the vectorized draw, which
    # sampling semantics permit)
    from tensorflowonspark_tpu.parallel import mesh as mesh_lib
    pad = (-b) % mesh_lib.axis_size(mesh, mesh_lib.AXIS_DATA,
                                    mesh_lib.AXIS_FSDP)
  if pad:
    prompt = jnp.concatenate(
        [prompt.astype(jnp.int32),
         jnp.zeros((pad, plen), jnp.int32)], axis=0)
  out = _kv_generate_fn(cfg, b + pad, plen, num_steps, float(temperature),
                        int(top_k), mesh,
                        None if eos_id is None else int(eos_id),
                        int(pad_id))(params, prompt.astype(jnp.int32), rng)
  return out[:b] if pad else out


def _zero_cache(model, batch: int):
  """A fresh all-zeros decode cache for ``model`` (init runs the decode
  path on a dummy token; zeroing resets its cursor advance)."""
  return jax.tree.map(
      jnp.zeros_like,
      model.init(jax.random.PRNGKey(0), jnp.zeros((batch, 1), jnp.int32),
                 decode=True)["cache"])


def _set_cache_cursor(cache, value):
  """Rewind every layer's decode cursor (the ``index`` cache leaves).

  Speculative rollback needs nothing else: entries past the cursor are
  never attended (the causal+unwritten mask) and the next write
  overwrites them, so rejected drafts cost a cursor assignment, not a
  cache restore."""
  from jax.tree_util import tree_map_with_path

  def f(path, leaf):
    if path and getattr(path[-1], "key", None) == "index":
      return jnp.asarray(value, leaf.dtype)
    return leaf

  return tree_map_with_path(f, cache)


@functools.lru_cache(maxsize=4)
def _spec_generate_fn(draft_cfg: TransformerConfig, cfg: TransformerConfig,
                      batch: int, plen: int, num_steps: int, k: int,
                      mesh=None):
  """Cached jitted greedy speculative decode (see
  :func:`speculative_generate_kv`). ``mesh`` (single-device) only binds
  the jit to a device for AOT lowering — the deviceless gate's surface."""
  draft = Transformer(draft_cfg)
  target = Transformer(cfg)

  def decode(draft_params, params, prompt):
    cache_d = _zero_cache(draft, batch)
    cache_t = _zero_cache(target, batch)
    # prefill both; the TARGET's argmax after the prompt is token 1
    logits_t, mut_t = target.apply({"params": params, "cache": cache_t},
                                   prompt, decode=True, mutable=["cache"])
    _, mut_d = draft.apply({"params": draft_params, "cache": cache_d},
                           prompt, decode=True, mutable=["cache"])
    cache_t, cache_d = mut_t["cache"], mut_d["cache"]
    g1 = jnp.argmax(logits_t[:, -1], -1).astype(jnp.int32)

    total = plen + num_steps + k + 1   # slack: a round may overshoot
    buf = jnp.zeros((batch, total), jnp.int32)
    buf = lax.dynamic_update_slice(buf, prompt.astype(jnp.int32), (0, 0))
    buf = lax.dynamic_update_slice(buf, g1[:, None], (0, plen))

    def cond(carry):
      return carry[1] < num_steps

    def body(carry):
      buf, n_gen, last, cache_t, cache_d = carry
      # both cursors sit at plen + n_gen - 1 (tokens CONSUMED so far)

      def dscan(c, _):
        cache, tok = c
        lg, mu = draft.apply({"params": draft_params, "cache": cache},
                             tok[:, None], decode=True, mutable=["cache"])
        nxt = jnp.argmax(lg[:, -1], -1).astype(jnp.int32)
        return (mu["cache"], nxt), nxt

      (cache_d, _), P = lax.scan(dscan, (cache_d, last), None, length=k)
      P = P.T                                          # [b, k] proposals

      # ONE target pass scores all k proposals: inputs [last, p1..p_{k-1}],
      # logits[:, j] is the target's prediction AFTER input j
      V = jnp.concatenate([last[:, None], P[:, :k - 1]], axis=1)
      lg_t, mut_t = target.apply({"params": params, "cache": cache_t}, V,
                                 decode=True, mutable=["cache"])
      cache_t = mut_t["cache"]
      T = jnp.argmax(lg_t, -1).astype(jnp.int32)       # [b, k]

      # longest agreeing prefix; min over rows keeps the batch in
      # lockstep (rows that accepted more get exactly those tokens back
      # as the bonus — still the target's greedy output)
      ok = (P == T).astype(jnp.int32)
      m = jnp.min(jnp.sum(jnp.cumprod(ok, axis=1), axis=1))
      bonus = lax.dynamic_index_in_dim(T, jnp.minimum(m, k - 1), 1,
                                       keepdims=True)  # [b, 1]
      emit = jnp.concatenate([P, jnp.zeros((batch, 1), jnp.int32)], axis=1)
      emit = lax.dynamic_update_slice(emit, bonus, (0, jnp.minimum(m, k)))
      buf = lax.dynamic_update_slice(buf, emit, (0, plen + n_gen))

      adv = jnp.where(m < k, m + 1, k)       # accepted + bonus
      new_last = jnp.where(m < k, bonus[:, 0], P[:, k - 1])
      new_cursor = plen + n_gen + adv - 1
      return (buf, n_gen + adv, new_last,
              _set_cache_cursor(cache_t, new_cursor),
              _set_cache_cursor(cache_d, new_cursor))

    buf, _, _, _, _ = lax.while_loop(
        cond, body, (buf, jnp.asarray(1, jnp.int32), g1, cache_t, cache_d))
    return buf[:, :plen + num_steps]

  if mesh is None:
    return jax.jit(decode)
  from tensorflowonspark_tpu.parallel import sharding as sh
  r = sh.replicated(mesh)
  return jax.jit(decode, in_shardings=(r, r, r), out_shardings=r)


def speculative_generate_kv(draft_params, draft_cfg: TransformerConfig,
                            params, cfg: TransformerConfig, prompt,
                            num_steps: int, draft_k: int = 4):
  """Greedy speculative decoding: a cheap DRAFT model proposes
  ``draft_k`` tokens per round and the target verifies them in ONE
  batched decode pass — the target runs ~num_steps/(accepted+1) forward
  passes instead of num_steps, and the output is EXACTLY the target's
  own greedy decode (greedy acceptance is lossless; pinned by test).

  Rollback is free by design: rejected draft entries sit past the
  rewound cache cursor, masked from attention and overwritten by the
  next round (:func:`_set_cache_cursor`). Batched rows accept the
  row-wise MINIMUM prefix each round (lockstep cursors); rows that
  agreed further simply receive those same tokens via the bonus path.

  Both configs must share a vocabulary; requires
  ``prompt_len + num_steps + draft_k <= max_seq_len`` on both models
  (a round's draft writes may transiently overshoot the kept output).
  """
  if draft_cfg.vocab_size != cfg.vocab_size:
    raise ValueError("draft and target must share a vocabulary (%d vs %d)"
                     % (draft_cfg.vocab_size, cfg.vocab_size))
  if draft_k < 1:
    raise ValueError("draft_k must be >= 1, got %d" % draft_k)
  b, plen = prompt.shape
  need = plen + num_steps + draft_k
  for name, c in (("draft", draft_cfg), ("target", cfg)):
    if need > c.max_seq_len:
      raise ValueError(
          "speculative decode needs %d cache slots (prompt %d + steps %d "
          "+ draft_k %d) but the %s max_seq_len is %d"
          % (need, plen, num_steps, draft_k, name, c.max_seq_len))
  return _spec_generate_fn(draft_cfg, cfg, b, plen, num_steps,
                           int(draft_k))(draft_params, params,
                                         prompt.astype(jnp.int32))


# per-process meshes for MeshSpec-carrying serving bundles (see
# make_serving_predict_fn._mesh — deliberately NOT closure state)
_SERVING_MESH_CACHE = {}

# per-process continuous-batching engines for variable-length serving
# batches (same NOT-closure-state rationale: a live ServingEngine holds a
# thread + device arrays and must never ride a pickled bundle)
_SERVING_ENGINE_CACHE = {}

# how long a cached-engine rebuild waits for the old engine to finish its
# accepted requests before stopping it (ServingEngine.drain — a param
# swap must shed zero accepted work; docs/ROBUSTNESS.md)
_SERVING_ENGINE_DRAIN_TIMEOUT = 60.0


def _prompt_rows(prompts):
  """Normalize a predict-fn prompt column to (rows, ragged?).

  ``rows`` is a list of 1-D int32 arrays; ``ragged`` is True when rows
  disagree on length — list/tuple columns of per-row sequences and
  object-dtype arrays (``pipeline``'s ragged-column fallback) both land
  here. Rectangular ndarrays return (None, False): the batched
  fixed-shape path handles them without row materialization.
  """
  import numpy as np
  if isinstance(prompts, np.ndarray) and prompts.dtype != object:
    return None, False
  seq = list(prompts)
  rows = [np.atleast_1d(np.asarray(r, np.int32).ravel()) for r in seq]
  lengths = {len(r) for r in rows}
  return rows, len(lengths) > 1


def make_serving_predict_fn(cfg: TransformerConfig, num_steps: int,
                            temperature: float = 0.0, top_k: int = 0,
                            seed: int = 0, mesh=None, mesh_spec=None,
                            eos_id=None, pad_id: int = 0,
                            num_slots=None):
  """Build a ``predict_fn(params, batch)`` for ``pipeline.export_bundle``.

  The batched KV-cache serving loop as a pipeline bundle: TFModel.transform
  batches rows with ``yield_batch``, and each batch decodes through
  :func:`greedy_generate_kv` (prefill once, then O(1) attention per new
  token). ``batch`` maps an input tensor name to a stacked int32 prompt
  array [B, prompt_len] (prompts in a partition must share a length);
  returns ``{"tokens": [B, prompt_len + num_steps]}``.

  The jitted decode is cached per (config, batch, prompt_len, num_steps),
  so steady-state serving reuses one compilation per shape. With
  ``temperature > 0`` the sampling key is folded with the batch content
  and a per-process call counter, so different batches (and repeated
  serves of the same batch) draw different streams — never the fixed-key
  repetition ``greedy_generate_kv``'s explicit-rng guard exists to
  prevent. ``mesh`` makes each serve tensor-parallel over its axes (the
  multi-chip inference layer, reference TFModel.scala:245-292). A live
  Mesh holds PJRT device objects and cannot ride a pickled bundle — for
  serving through ``pipeline.export_bundle`` / ``TFModel.transform`` pass
  ``mesh_spec`` (a picklable ``parallel.mesh.MeshSpec``) instead: each
  executor process builds the mesh from ITS visible devices on first
  serve (the per-executor-session pattern of the reference's JVM layer).

  VARIABLE-LENGTH batches (a list/object column whose rows disagree on
  prompt length — ``TFModel.transform``'s ragged-column fallback) route
  through the continuous-batching ``serving.ServingEngine`` instead of
  the fixed-shape loop: one persistent per-process engine per config
  (``num_slots`` slots, default ``TOS_SERVE_SLOTS``), EOS early-exit via
  ``eos_id``, outputs right-padded with ``pad_id`` to a rectangle. The
  engine is greedy-only, so ragged batches with ``temperature > 0``
  raise. ``eos_id`` also applies on the rectangular path (per-sequence
  stop inside the fixed loop).
  """
  if mesh is not None and mesh_spec is not None:
    raise ValueError("pass mesh OR mesh_spec, not both")
  state = {"calls": 0}

  def _mesh():
    if mesh is not None:
      return mesh
    if mesh_spec is None:
      return None
    # cache OUTSIDE the closure, reached via an IMPORT at call time: a
    # live Mesh stashed in `state` — or in a module global this dynamic
    # closure referenced directly, which cloudpickle serializes BY VALUE —
    # would ride along when export_bundle pickles predict_fn and crash on
    # the PJRT device objects the moment the fn was smoke-served first
    import tensorflowonspark_tpu.models.transformer as _self
    key = tuple(sorted(mesh_spec.degrees().items()))
    m = _self._SERVING_MESH_CACHE.get(key)
    if m is None:
      from tensorflowonspark_tpu.parallel import mesh as mesh_lib
      m = _self._SERVING_MESH_CACHE[key] = mesh_lib.build_mesh(mesh_spec)
    return m

  def _engine(params):
    # cache OUTSIDE the closure, reached via an IMPORT at call time (the
    # _SERVING_MESH_CACHE pickling rationale). One engine per serving
    # config AND param CONTENT; rebuilt if the caller serves a different
    # param tree.
    import tensorflowonspark_tpu.models.transformer as _self
    from tensorflowonspark_tpu.serving import ServingEngine
    from tensorflowonspark_tpu.utils.checkpoint import params_fingerprint
    # the key carries a CONTENT fingerprint of the params, not an object
    # identity: a republished model of the same shape (the registry
    # continuous-deployment loop re-serving a bundle after a new version
    # lands) previously hit the config-only key and served STALE weights
    # from the cached engine whenever the new tree aliased the old one.
    # Fingerprinting is one pass over the leaves — amortized across the
    # whole ragged partition a cache hit serves. The identity fast path
    # stays: pipeline.load_bundle memoizes (params, predict_fn) per
    # export_dir, so steady-state serves hand back the SAME pytree
    # object and skip the hash entirely.
    cfg_key = (cfg, num_steps, eos_id, pad_id, num_slots, repr(mesh_spec),
               None if mesh is None else id(mesh))
    for k, (p, eng) in list(_self._SERVING_ENGINE_CACHE.items()):
      if k[:len(cfg_key)] == cfg_key and p is params and eng.alive:
        return eng
    key = cfg_key + (params_fingerprint(params),)
    cached = _self._SERVING_ENGINE_CACHE.get(key)
    # a dead engine (loop thread died on an error) must be rebuilt, not
    # returned — otherwise one bad batch poisons ragged serving forever
    if cached is not None and cached[1].alive:
      return cached[1]
    # retire every engine under this serving config (the stale version
    # AND any dead same-version entry): drain finishes every request the
    # old engine already accepted (bounded), THEN stops it — in-flight
    # work from concurrent transform partitions is never shed. A dead
    # engine drains instantly (its loop cannot make progress).
    for k in [k for k in _self._SERVING_ENGINE_CACHE
              if k == key or k[:len(cfg_key)] == cfg_key]:
      _self._SERVING_ENGINE_CACHE.pop(k)[1].drain(
          timeout=_self._SERVING_ENGINE_DRAIN_TIMEOUT)
    # admission bounds OFF for this internal path: the transform feed is
    # already bounded (yield_batch caps rows per predict call) and has
    # no retry story — the client-facing TOS_SERVE_MAX_QUEUE* defaults
    # would turn a big ragged partition into a hard failure that the
    # pre-robustness engine served fine. Direct ServingEngine users
    # keep the bounds.
    eng = ServingEngine(params, cfg, num_slots=num_slots, eos_id=eos_id,
                        pad_id=pad_id, max_new_tokens=num_steps,
                        max_queue=0, max_queued_tokens=0,
                        mesh=_mesh()).start()
    _self._SERVING_ENGINE_CACHE[key] = (params, eng)
    return eng

  def predict_fn(params, batch):
    import zlib
    import numpy as np
    raw = next(iter(batch.values()))
    rows, ragged = _prompt_rows(raw)
    if ragged:
      # mixed-length generation: the continuous-batching engine decodes
      # each row to ITS own length/stop instead of a padded lockstep loop
      if temperature > 0:
        raise ValueError(
            "variable-length serving batches decode through the "
            "continuous-batching engine, which is greedy-only — "
            "temperature > 0 needs equal-length prompts")
      eng = _engine(params)
      outs = eng.generate(rows, max_new_tokens=num_steps)
      width = max(len(o) for o in outs)
      padded = np.full((len(outs), width), pad_id, np.int32)
      for i, o in enumerate(outs):
        padded[i, :len(o)] = o
      return {"tokens": padded}
    # an object/list column whose rows happen to share one length is NOT
    # ragged — but np.asarray on the object array would still raise, so
    # stack the already-normalized rows
    prompts = np.stack(rows) if rows is not None else \
        np.asarray(raw, np.int32)
    if prompts.ndim == 1:          # one column of scalar token ids
      prompts = prompts[:, None]
    rng = None
    if temperature > 0:
      state["calls"] += 1
      rng = jax.random.fold_in(
          jax.random.fold_in(jax.random.PRNGKey(seed),
                             zlib.crc32(prompts.tobytes())),
          state["calls"])
    out = greedy_generate_kv(params, cfg, jnp.asarray(prompts), num_steps,
                             temperature=temperature, top_k=top_k, rng=rng,
                             mesh=_mesh(), eos_id=eos_id, pad_id=pad_id)
    return {"tokens": np.asarray(out)}

  return predict_fn


def causal_lm_loss(logits, tokens, z_loss: float = 0.0):
  """Next-token cross-entropy (shifted); ignores the final position.

  ``z_loss`` > 0 adds the auxiliary ``z_loss · mean(logsumexp²)`` term
  (PaLM/T5X recipe, typically 1e-4): it pulls the partition function
  toward 1, stabilizing bf16 logit growth over long runs — cheap
  insurance on TPU where the softmax runs in bf16-accumulated f32.
  """
  import optax
  targets = tokens[:, 1:]
  logits = logits[:, :-1]
  ce = optax.softmax_cross_entropy_with_integer_labels(
      logits, targets).mean()
  if z_loss:
    lse = jax.nn.logsumexp(logits.astype(jnp.float32), axis=-1)
    ce = ce + z_loss * jnp.mean(lse ** 2)
  return ce


def tied_embedding_table(params):
  """The tied input/output embedding [vocab, d_model] from a Transformer
  param tree (unboxing flax ``Partitioned`` leaves if present)."""
  table = params["embed"]["embedding"]
  if hasattr(table, "unbox"):
    table = table.unbox()
  return table


def causal_lm_loss_blocked(hidden, table, tokens, chunk: int = 256,
                           z_loss: float = 0.0):
  """Next-token cross-entropy fused with the tied output projection.

  The [batch, seq, vocab] logits are never materialized: sequence chunks
  of ``chunk`` positions are projected against ``table``, reduced to
  (logsumexp, label logit), and discarded; ``jax.checkpoint`` around the
  chunk body makes the backward recompute each chunk's logits in turn, so
  peak activation memory is [batch, chunk, vocab] instead of
  [batch, seq, vocab] (a vocab-sized factor — ~2 GB down to ~500 MB at
  the bench config, which is what bounded the trainable batch size).

  ``hidden``: final-layer-norm output from
  ``model.apply(..., return_hidden=True)`` [B, S, D]; ``table``: tied
  embedding [V, D] (:func:`tied_embedding_table`). Matches
  :func:`causal_lm_loss` on the same inputs (including ``z_loss``) to
  float tolerance — the per-chunk logsumexp the reduction already
  computes feeds the z-term for free.
  """
  targets = tokens[:, 1:]
  x = hidden[:, :-1]
  b, s, d = x.shape
  n = -(-s // chunk)
  pad = n * chunk - s
  if pad:
    x = jnp.pad(x, ((0, 0), (0, pad), (0, 0)))
    targets = jnp.pad(targets, ((0, 0), (0, pad)))
  mask = (jnp.arange(n * chunk) < s).astype(jnp.float32)
  xs = x.reshape(b, n, -1, d).transpose(1, 0, 2, 3)     # [n, B, C, D]
  ts = targets.reshape(b, n, -1).transpose(1, 0, 2)     # [n, B, C]
  ms = mask.reshape(n, -1)                              # [n, C]
  tbl = table.astype(x.dtype)

  @jax.checkpoint
  def body(carry, inp):
    tot, z_tot = carry
    xc, tc, mc = inp
    logits = jnp.einsum("bcd,vd->bcv", xc, tbl,
                        preferred_element_type=jnp.float32)
    lse = jax.nn.logsumexp(logits, axis=-1)             # [B, C]
    ll = jnp.take_along_axis(logits, tc[..., None], axis=-1)[..., 0]
    return (tot + jnp.sum((lse - ll) * mc[None, :]),
            z_tot + jnp.sum(lse ** 2 * mc[None, :])), None

  (total, z_total), _ = jax.lax.scan(
      body, (jnp.float32(0.0), jnp.float32(0.0)), (xs, ts, ms))
  loss = total / (b * s)
  if z_loss:
    loss = loss + z_loss * z_total / (b * s)
  return loss


def _init_fns(rng, cfg: TransformerConfig, mesh, learning_rate, seq_len,
              init_batch: int = 1, tx=None):
  """(params_init_fn, make_state_fn) pair for parallel.sharding init.

  ``tx``: any optax GradientTransformation (see :mod:`optim` for the
  schedule/clipping recipe builder); defaults to plain AdamW at
  ``learning_rate``."""
  import optax
  from flax.training import train_state

  model = Transformer(cfg, mesh)
  tokens = jnp.zeros((init_batch, seq_len), jnp.int32)

  def params_init():
    return model.init(rng, tokens)["params"]  # Partitioned-boxed

  def make_state(params):
    opt = tx if tx is not None else         optax.adamw(learning_rate, weight_decay=0.01)
    return train_state.TrainState.create(apply_fn=model.apply,
                                         params=params, tx=opt)

  return params_init, make_state


def create_state(rng, cfg: TransformerConfig,
                 learning_rate: float = 3e-4, seq_len: int = 128,
                 tx=None):
  """Single-device TrainState (params unboxed, unsharded)."""
  from flax.core import meta
  params_init, make_state = _init_fns(rng, cfg, None, learning_rate,
                                      seq_len, tx=tx)
  return make_state(meta.unbox(params_init()))


def create_sharded_state(rng, cfg: TransformerConfig, mesh,
                         learning_rate: float = 3e-4, seq_len: int = 128,
                         tx=None):
  """TrainState initialized directly onto the mesh (TP/FSDP layouts applied
  at init — large models never materialize replicated).

  Returns (state, state_sharding).
  """
  from tensorflowonspark_tpu.parallel import sharding as sh
  from tensorflowonspark_tpu.parallel import mesh as mesh_lib
  # the init trace must itself be shardable: batch covers the data axes
  init_batch = mesh_lib.axis_size(mesh, mesh_lib.AXIS_DATA,
                                  mesh_lib.AXIS_FSDP)
  params_init, make_state = _init_fns(rng, cfg, mesh, learning_rate, seq_len,
                                      init_batch=init_batch, tx=tx)
  return sh.init_sharded_state(params_init, make_state, mesh)


# ---------------------------------------------------------------------------
# Pipeline-parallel training (1F1B over the block stack)
# ---------------------------------------------------------------------------

def pipeline_partition_params(params, n_stages: int):
  """Split a Transformer param tree for the 1F1B pipeline.

  Returns ``(outer_params, stage_params)``: the embedding table and final
  norm stay outer (first/last stage work); the homogeneous ``layer_i``
  blocks stack into ``[n_stages, layers_per_stage, ...]`` leaves, stage
  ``s`` owning the contiguous chunk ``[s*k, (s+1)*k)``.
  """
  num_layers = sum(1 for k in params if k.startswith("layer_"))
  assert num_layers % n_stages == 0, \
      "%d layers do not split into %d stages" % (num_layers, n_stages)
  k = num_layers // n_stages
  layers = [params["layer_%d" % i] for i in range(num_layers)]
  stage = jax.tree.map(
      lambda *ls: jnp.stack(ls).reshape((n_stages, k) + ls[0].shape), *layers)
  # everything that is not a pipelined block is outer (first/last stage
  # work) — keyed negatively so model variants with extra top-level params
  # (untied head, learned positions) are carried instead of silently lost
  outer = {key: v for key, v in params.items()
           if not key.startswith("layer_")}
  return outer, stage


def pipeline_unpartition_grads(g_outer, g_stage, num_layers: int):
  """Rebuild the full param-tree layout from pipeline grads."""
  flat = jax.tree.map(
      lambda g: g.reshape((num_layers,) + g.shape[2:]), g_stage)
  tree = dict(g_outer)
  for i in range(num_layers):
    tree["layer_%d" % i] = jax.tree.map(lambda g, _i=i: g[_i], flat)
  return tree


def make_pipeline_train_step(cfg: TransformerConfig, mesh,
                             num_microbatches: int):
  """A ``(params, tokens) -> (loss, grads)`` step training the Transformer
  with the 1F1B schedule over the mesh's ``pipeline`` axis.

  Stage sharding is explicit in the pipeline's shard_map, so blocks run
  with ``mesh=None`` (no inner sharding constraints); the embed runs on
  the first stage and the final-norm + tied projection + loss on the last,
  via ``parallel.pipeline_parallel.pipeline_lm_train_step`` — the tied
  table's embed- and head-side grad contributions are summed across those
  stages. Homogeneous layers only (``moe_experts == 0``: MoE layers have a
  different param tree and cannot stack into uniform stages).
  """
  from tensorflowonspark_tpu.parallel import mesh as mesh_lib
  from tensorflowonspark_tpu.parallel import pipeline_parallel as PP

  assert cfg.moe_experts == 0, "pipeline stages must be homogeneous"
  n_stages = mesh.shape[mesh_lib.AXIS_PIPELINE]
  # honor cfg.remat like the dense path does: the per-microbatch stage vjp
  # otherwise stores every intra-block intermediate for all
  # layers-per-stage blocks — the regime where remat matters most
  block = (_remat_block(cfg) if cfg.remat else Block)(cfg, None)
  embed_mod = TiedEmbed(cfg, None)
  ln_f = _make_layer_norm(cfg, None, "ln_f")

  def embed_fn(outer, tokens):
    x = embed_mod.apply({"params": outer["embed"]}, tokens)
    return x.astype(cfg.dtype)

  def stage_fn(stage_p, x):
    positions = jnp.broadcast_to(jnp.arange(x.shape[1]), x.shape[:2])

    def body(carry, layer_p):
      return block.apply({"params": layer_p}, carry, positions), None

    x, _ = lax.scan(body, x, stage_p)
    return x

  def head_loss_fn(outer, x, targets):
    x = ln_f.apply({"params": outer["ln_f"]}, x)
    # the one tied-projection definition (TiedEmbed.attend), not a copy
    logits = embed_mod.apply({"params": outer["embed"]}, x.astype(cfg.dtype),
                             method="attend")
    return causal_lm_loss(logits.astype(jnp.float32), targets)

  def partitioned_step(outer, stage, tokens):
    """(outer_params, stage_params, tokens) -> (loss, g_outer, g_stage) —
    for training loops that keep params (and optimizer state) in the
    pipeline layout across steps, avoiding the per-step restack."""
    return PP.pipeline_lm_train_step(
        embed_fn, stage_fn, head_loss_fn, outer, stage, tokens, tokens,
        mesh, num_microbatches)

  def step(params, tokens):
    # convenience layout: restacks the layer tree each step — fine for
    # validation/small models; large-scale loops should hold the
    # partitioned layout and call ``step.partitioned`` directly
    outer, stage = pipeline_partition_params(params, n_stages)
    loss, g_outer, g_stage = partitioned_step(outer, stage, tokens)
    return loss, pipeline_unpartition_grads(g_outer, g_stage,
                                            cfg.num_layers)

  step.partitioned = partitioned_step
  return step
