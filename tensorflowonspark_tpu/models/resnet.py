"""ResNet family: ResNet-50 (ImageNet) and CIFAR ResNet-56.

Capability parity with the reference's ResNet-CIFAR example
(/root/reference/examples/resnet/resnet_cifar_dist.py, which wraps the
upstream tensorflow/models Keras ResNet-56), built TPU-first:

- bfloat16 conv/matmul compute, float32 params and batch-norm statistics;
- channels-last NHWC layout (TPU-native);
- fused jitted train step; batch-norm running stats carried in a flax
  ``batch_stats`` collection and updated inside the step (cross-device sync
  via ``axis_name`` is unnecessary under GSPMD data sharding — XLA inserts
  the reductions for the batch dimension automatically).
"""

from functools import partial
from typing import Any, Callable, Sequence, Tuple

import jax
import jax.numpy as jnp
import flax.linen as nn
import optax
from flax.training import train_state


class TrainStateBN(train_state.TrainState):
  batch_stats: Any = None


class BottleneckBlock(nn.Module):
  filters: int
  strides: Tuple[int, int] = (1, 1)
  dtype: Any = jnp.bfloat16

  @nn.compact
  def __call__(self, x, train: bool = False):
    conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
    norm = partial(nn.BatchNorm, use_running_average=not train,
                   momentum=0.9, epsilon=1e-5, dtype=jnp.float32)
    residual = x
    y = conv(self.filters, (1, 1), name="conv1")(x)
    y = norm(name="bn1")(y)
    y = nn.relu(y)
    y = conv(self.filters, (3, 3), self.strides, name="conv2")(y)
    y = norm(name="bn2")(y)
    y = nn.relu(y)
    y = conv(self.filters * 4, (1, 1), name="conv3")(y)
    y = norm(name="bn3", scale_init=nn.initializers.zeros)(y)
    if residual.shape != y.shape:
      residual = conv(self.filters * 4, (1, 1), self.strides,
                      name="proj")(residual)
      residual = norm(name="bn_proj")(residual)
    return nn.relu(residual + y.astype(residual.dtype))


class BasicBlock(nn.Module):
  filters: int
  strides: Tuple[int, int] = (1, 1)
  dtype: Any = jnp.bfloat16

  @nn.compact
  def __call__(self, x, train: bool = False):
    conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
    norm = partial(nn.BatchNorm, use_running_average=not train,
                   momentum=0.9, epsilon=1e-5, dtype=jnp.float32)
    residual = x
    y = conv(self.filters, (3, 3), self.strides, name="conv1")(x)
    y = norm(name="bn1")(y)
    y = nn.relu(y)
    y = conv(self.filters, (3, 3), name="conv2")(y)
    y = norm(name="bn2", scale_init=nn.initializers.zeros)(y)
    if residual.shape != y.shape:
      residual = conv(self.filters, (1, 1), self.strides,
                      name="proj")(residual)
      residual = norm(name="bn_proj")(residual)
    return nn.relu(residual + y.astype(residual.dtype))


class ResNet(nn.Module):
  """Generic ResNet over NHWC inputs."""
  stage_sizes: Sequence[int]
  block_cls: Callable = BottleneckBlock
  num_classes: int = 1000
  num_filters: int = 64
  stem: str = "imagenet"       # "imagenet" (7x7/2 + pool) or "cifar" (3x3)
  dtype: Any = jnp.bfloat16

  @nn.compact
  def __call__(self, x, train: bool = False):
    conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
    norm = partial(nn.BatchNorm, use_running_average=not train,
                   momentum=0.9, epsilon=1e-5, dtype=jnp.float32)
    x = x.astype(self.dtype)
    if self.stem == "imagenet":
      x = conv(self.num_filters, (7, 7), (2, 2), padding=[(3, 3), (3, 3)],
               name="stem_conv")(x)
      x = norm(name="stem_bn")(x)
      x = nn.relu(x)
      x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
    else:
      x = conv(self.num_filters, (3, 3), name="stem_conv")(x)
      x = norm(name="stem_bn")(x)
      x = nn.relu(x)

    for i, n_blocks in enumerate(self.stage_sizes):
      for j in range(n_blocks):
        strides = (2, 2) if i > 0 and j == 0 else (1, 1)
        x = self.block_cls(self.num_filters * 2 ** i, strides,
                           dtype=self.dtype,
                           name="stage%d_block%d" % (i, j))(x, train=train)

    x = jnp.mean(x, axis=(1, 2))
    x = nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)
    return x


def ResNet50(num_classes: int = 1000, dtype=jnp.bfloat16) -> ResNet:
  return ResNet(stage_sizes=(3, 4, 6, 3), block_cls=BottleneckBlock,
                num_classes=num_classes, dtype=dtype)


def ResNet56CIFAR(num_classes: int = 10, dtype=jnp.bfloat16) -> ResNet:
  """The reference example's model scale (ResNet-56 for CIFAR-10)."""
  return ResNet(stage_sizes=(9, 9, 9), block_cls=BasicBlock,
                num_classes=num_classes, num_filters=16, stem="cifar",
                dtype=dtype)


def create_state(rng, model: ResNet, image_shape=(224, 224, 3),
                 learning_rate: float = 0.1, momentum: float = 0.9):
  variables = model.init(rng, jnp.zeros((1,) + tuple(image_shape),
                                        jnp.float32), train=False)
  tx = optax.sgd(learning_rate, momentum=momentum, nesterov=True)
  return TrainStateBN.create(
      apply_fn=model.apply, params=variables["params"], tx=tx,
      batch_stats=variables.get("batch_stats", {}))


@jax.jit
def train_step(state: TrainStateBN, images, labels):
  """Fused forward+backward+SGD step with batch-stats update."""

  def _loss(params):
    logits, mutated = state.apply_fn(
        {"params": params, "batch_stats": state.batch_stats},
        images, train=True, mutable=["batch_stats"])
    loss = optax.softmax_cross_entropy_with_integer_labels(
        logits, labels).mean()
    return loss, mutated["batch_stats"]

  (loss, new_stats), grads = jax.value_and_grad(_loss, has_aux=True)(
      state.params)
  state = state.apply_gradients(grads=grads)
  return state.replace(batch_stats=new_stats), loss


@jax.jit
def eval_step(state: TrainStateBN, images, labels):
  logits = state.apply_fn(
      {"params": state.params, "batch_stats": state.batch_stats},
      images, train=False)
  loss = optax.softmax_cross_entropy_with_integer_labels(
      logits, labels).mean()
  acc = (jnp.argmax(logits, -1) == labels).mean()
  return loss, acc
