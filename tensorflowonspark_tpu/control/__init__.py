"""L1' control plane: cluster rendezvous + per-host feed hub.

Replaces the reference's ``reservation.py`` (pickle-over-TCP discovery),
``TFManager.py`` (multiprocessing.BaseManager IPC hub) and ``marker.py``
(/root/reference/tensorflowonspark/). The wire format here is length-prefixed
msgpack — structurally identical framing, but without pickle's arbitrary code
execution on receive.
"""

from tensorflowonspark_tpu.control.marker import Marker, EndPartition  # noqa: F401
from tensorflowonspark_tpu.control.rendezvous import (  # noqa: F401
    Server, Client, Reservations,
)
from tensorflowonspark_tpu.control import feedhub  # noqa: F401
