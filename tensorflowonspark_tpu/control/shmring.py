"""Python wrapper for the native shared-memory ring buffer.

The high-throughput alternative to the manager-proxy feed queues
(control/feedhub.py): serialized batches move through POSIX shared memory
(native/shmring.cpp) with no per-row IPC round-trips — the TPU-first
redesign of the reference's feed-plane bottleneck (SURVEY.md §3.2,
row-at-a-time pickled puts at TFSparkNode.py:500-502).

Topology: single producer (the feeder task) / single consumer (the node's
data loader) per ring, which is exactly what the engine guarantees.
Batches are serialized with the columnar chunk codec
(control/chunkcodec.py): homogeneous row chunks ship as raw column
buffers in a msgpack envelope, everything else falls back to cloudpickle
inside the codec.
"""

import ctypes
import logging
import os
import subprocess
from typing import Optional

from tensorflowonspark_tpu.control.chunkcodec import MAX_PAYLOAD as \
    _CODEC_MAX_PAYLOAD


logger = logging.getLogger(__name__)

_SO_PATH = os.path.join(os.path.dirname(__file__), os.pardir, "data",
                        "_shmring_native.so")
_SRC_PATH = os.path.join(os.path.dirname(__file__), os.pardir, os.pardir,
                         "native", "shmring.cpp")
_lib = None
_lib_tried = False


def _compile(so: str) -> bool:
  try:
    # -lrt: shm_open/shm_unlink live in librt on older glibc; linking it
    # explicitly is harmless where they moved into libc
    subprocess.run(["g++", "-O3", "-fPIC", "-shared", "-std=c++17",
                    "-o", so, os.path.abspath(_SRC_PATH), "-lrt"],
                   check=True, capture_output=True, timeout=120)
    return True
  except (OSError, subprocess.SubprocessError) as e:
    logger.warning("shmring native build failed: %s", e)
    return False


def _bind(so: str):
  lib = ctypes.CDLL(so)
  lib.tos_ring_create.restype = ctypes.c_void_p
  lib.tos_ring_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
  lib.tos_ring_open.restype = ctypes.c_void_p
  lib.tos_ring_open.argtypes = [ctypes.c_char_p]
  lib.tos_ring_write.restype = ctypes.c_int
  lib.tos_ring_write.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                 ctypes.c_uint32, ctypes.c_int]
  lib.tos_ring_read.restype = ctypes.c_int64
  lib.tos_ring_read.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                ctypes.c_uint32, ctypes.c_int]
  lib.tos_ring_close_write.argtypes = [ctypes.c_void_p]
  lib.tos_ring_pending.restype = ctypes.c_uint64
  lib.tos_ring_pending.argtypes = [ctypes.c_void_p]
  lib.tos_ring_free.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                ctypes.c_int]
  return lib


def _load():
  global _lib, _lib_tried
  if _lib_tried:
    return _lib
  _lib_tried = True
  so = os.path.abspath(_SO_PATH)
  built = False
  if not os.path.exists(so) and os.path.exists(_SRC_PATH):
    if not _compile(so):
      return None
    built = True
  if not os.path.exists(so):
    return None
  try:
    _lib = _bind(so)
  except (OSError, AttributeError) as e:
    # a PREBUILT .so from a different image can fail to dlopen or miss
    # symbols here (e.g. undefined shm_open when linked without -lrt).
    # available() must gate cleanly — every node bring-up consults it, and
    # leaking a loader error would abort whole-cluster startup over an
    # optional fast path. Rebuild from source once, else fall back.
    logger.warning("shmring native library failed to load (%s)%s", e,
                   "; rebuilding from source" if os.path.exists(_SRC_PATH)
                   else "; falling back to queue transport")
    _lib = None
    if not built and os.path.exists(_SRC_PATH) and _compile(so):
      try:
        _lib = _bind(so)
      except (OSError, AttributeError) as e2:
        logger.warning("rebuilt shmring library still fails to load (%s); "
                       "falling back to queue transport", e2)
        _lib = None
  return _lib


def available() -> bool:
  return _load() is not None


# rings held alive per process (same lifetime pattern as feedhub.hold);
# freed explicitly at shutdown or by the atexit sweep — POSIX shm persists
# past process death, so leaked segments would eat /dev/shm (RAM) until
# reboot
_held = {}
_atexit_registered = False


def hold(key, ring: "ShmRing") -> None:
  global _atexit_registered
  _held[key] = ring
  if not _atexit_registered:
    import atexit
    atexit.register(release_all)
    _atexit_registered = True


def held(key) -> Optional["ShmRing"]:
  return _held.get(key)


def release(key) -> None:
  """Free (and unlink) a held ring."""
  ring = _held.pop(key, None)
  if ring is not None:
    ring.free()


def release_all() -> None:
  for key in list(_held):
    release(key)


def unlink_stale(name: str) -> None:
  """Best-effort unlink of a ring segment whose owner died without freeing
  it (POSIX shm persists past process death). Used by relaunched nodes to
  reap their dead predecessor's segment before creating a fresh,
  generation-suffixed ring."""
  try:
    os.unlink(os.path.join("/dev/shm", name.lstrip("/")))
  except OSError:
    pass


class RingClosed(Exception):
  pass


class RingTimeout(Exception):
  pass


_open_cache = {}


def open_cached(name: str) -> "ShmRing":
  """Open a ring once per process (mmap reuse across feeder tasks)."""
  if name not in _open_cache:
    _open_cache[name] = ShmRing.open(name)
  return _open_cache[name]


class RingQueueAdapter(object):
  """FeedQueue-compatible facade over a ShmRing.

  Exposes the subset of the feed-queue API the feeder tasks and DataFeed
  use (``put``/``put_many``/``get_many``/``task_done``/``join``), so the
  queue and shared-memory transports share one code path. Items travel as
  chunk batches; ``task_done`` is a no-op (the ring's tail pointer IS the
  consumption acknowledgment) and ``join`` waits for the ring to drain.
  """

  def __init__(self, ring: "ShmRing"):
    self._ring = ring
    self._end_sent = False   # synthesized end-of-feed delivered (either API)
    import collections
    self._buffer = collections.deque()

  # keep any single ring payload comfortably below the ring capacity so a
  # write can always be placed after a drain (a record larger than roughly
  # half the ring can wedge against the wrap-around padding); ONE bound
  # shared with put_rows_chunk so both producer paths split identically
  MAX_PAYLOAD = _CODEC_MAX_PAYLOAD

  # producer side ------------------------------------------------------------

  def put_many(self, items, block: bool = True, timeout=None) -> None:
    items = list(items)
    t = None if (block and timeout is None) else (timeout if block else 0.0)
    from tensorflowonspark_tpu.control import chunkcodec
    payload = chunkcodec.encode(items)
    if len(payload) > self.MAX_PAYLOAD and len(items) > 1:
      # split oversized chunks so large rows stream through (parity with
      # FeedQueue.put_many spilling through bounded queues)
      half = len(items) // 2
      self.put_many(items[:half], block=block, timeout=timeout)
      self.put_many(items[half:], block=block, timeout=timeout)
      return
    self._ring.put_payload(payload, timeout=t)

  def put(self, item, block: bool = True, timeout=None) -> None:
    self.put_many([item], block=block, timeout=timeout)

  def put_chunk(self, n: int, payload: bytes, block: bool = True,
                timeout=None) -> None:
    """Enqueue one ALREADY-ENCODED chunk (``n`` is informational here —
    the ring's byte accounting is its own backpressure). Same signature
    as ``FeedQueue.put_chunk`` so producers treat both transports alike;
    callers split oversized chunks at the row level (``node.put_rows_chunk``)
    before reaching either."""
    t = None if (block and timeout is None) else (timeout if block else 0.0)
    self._ring.put_payload(payload, timeout=t)

  def join(self, timeout=None) -> bool:
    import time as _time
    deadline = None if timeout is None else _time.monotonic() + timeout
    while self._ring.pending_bytes() > 0:
      if deadline is not None and _time.monotonic() > deadline:
        return False
      _time.sleep(0.005)
    return True

  # consumer side ------------------------------------------------------------

  def get_many(self, max_items: int, block: bool = True, timeout=None):
    if not self._buffer:
      if self._end_sent:
        return []
      try:
        got = self._ring.get_batch(
            timeout=(timeout if timeout is not None else
                     (None if block else 0.0)))
        self._buffer.extend(got)
      except RingTimeout:
        return []
      except RingClosed:
        # producer closed the ring without an in-band end-of-feed marker
        # (e.g. it died): synthesize one, exactly once, so
        # DataFeed.next_batch reaches done_feeding instead of polling an
        # empty closed ring forever — and later calls return [] so
        # DataFeed.terminate's consecutive-empty drain loop still ends
        self._end_sent = True
        return [None]
    out = []
    while self._buffer and len(out) < max_items:
      out.append(self._buffer.popleft())
    return out

  def get_chunk(self, max_rows: int = 1024, block: bool = True,
                timeout=None):
    """Dequeue ONE chunk without materializing rows; ``None`` on timeout.

    Returns the consumer-facing union ``("data", ColumnChunk | row_list)``
    or ``("marker", m)``: one ring payload maps to one chunk, decoded via
    ``chunkcodec.decode_columns`` with the scratch buffer passed straight
    into msgpack (no whole-payload copy; the column views are backed by
    msgpack-owned bytes, so producer slot reuse after ``task_done`` cannot
    touch a handed-off chunk). Single-marker chunks (a ``put(None)`` /
    ``put(EndPartition())`` from the producer) surface as chunk-boundary
    ``("marker", m)`` envelopes; a ring closed without an in-band marker
    synthesizes ``("marker", None)`` exactly once.
    """
    from tensorflowonspark_tpu.control import chunkcodec
    if self._buffer:
      # rows left over from interleaved legacy get_many use
      out = []
      while self._buffer and len(out) < max_rows:
        out.append(self._buffer.popleft())
      return ("rows", out)
    if self._end_sent:
      return None
    try:
      payload = self._ring.get_payload(
          timeout=(timeout if timeout is not None else
                   (None if block else 0.0)))
    except RingTimeout:
      return None
    except RingClosed:
      self._end_sent = True
      return ("marker", None)
    return chunkcodec.classify_decoded(chunkcodec.decode_columns(payload))

  def task_done(self, n: int = 1) -> None:
    pass

  def qsize(self) -> int:
    return len(self._buffer) + (1 if self._ring.pending_bytes() else 0)

  def empty(self) -> bool:
    return self.qsize() == 0


class ShmRing(object):
  """One endpoint of a shared-memory batch ring."""

  def __init__(self, name: str, handle, lib, owner: bool):
    self.name = name
    self._h = handle
    self._lib = lib
    self._owner = owner
    self._buf = ctypes.create_string_buffer(1 << 20)

  # -- constructors ----------------------------------------------------------

  @classmethod
  def create(cls, name: str, capacity: int = 64 * 1024 * 1024) -> "ShmRing":
    lib = _load()
    if lib is None:
      raise RuntimeError("native shmring unavailable (no toolchain?)")
    h = lib.tos_ring_create(name.encode(), capacity)
    if not h:
      raise OSError("failed to create shm ring %r" % name)
    return cls(name, h, lib, owner=True)

  @classmethod
  def open(cls, name: str) -> "ShmRing":
    lib = _load()
    if lib is None:
      raise RuntimeError("native shmring unavailable (no toolchain?)")
    h = lib.tos_ring_open(name.encode())
    if not h:
      raise OSError("failed to open shm ring %r" % name)
    return cls(name, h, lib, owner=False)

  # -- batch API -------------------------------------------------------------

  def put_batch(self, batch, timeout: Optional[float] = None) -> None:
    """Serialize and enqueue one batch (a list of rows / arrays pytree).

    Homogeneous row lists go through the columnar chunk codec (raw column
    buffers, no pickle); everything else falls back to cloudpickle inside
    the codec."""
    from tensorflowonspark_tpu.control import chunkcodec
    self.put_payload(chunkcodec.encode(batch), timeout=timeout)

  def put_payload(self, payload: bytes,
                  timeout: Optional[float] = None) -> None:
    """Enqueue an already-serialized batch."""
    rc = self._lib.tos_ring_write(
        self._h, payload, len(payload),
        -1 if timeout is None else int(timeout * 1000))
    if rc == 0:
      return
    if rc == 1:
      raise RingTimeout("ring %r write timed out" % self.name)
    if rc == 2:
      raise RingClosed("ring %r is closed" % self.name)
    raise ValueError("batch of %d bytes exceeds ring capacity"
                     % len(payload))

  def get_batch(self, timeout: Optional[float] = None):
    """Dequeue one batch; raises RingClosed when drained after close."""
    from tensorflowonspark_tpu.control import chunkcodec
    return chunkcodec.decode(self.get_payload(timeout=timeout))

  def get_payload(self, timeout: Optional[float] = None):
    """Dequeue one raw serialized record as a memoryview over the reader
    scratch buffer — ZERO-COPY hand-off to the codec. The view is only
    valid until the next read: decode before reading again (msgpack
    copies bin/str data into owned bytes during the parse, so decoded
    chunks survive scratch reuse)."""
    t = -1 if timeout is None else int(timeout * 1000)
    while True:
      n = self._lib.tos_ring_read(self._h, self._buf, len(self._buf), t)
      if n >= 0:
        return memoryview(self._buf)[:n]
      if n == -1:
        raise RingTimeout("ring %r read timed out" % self.name)
      if n == -2:
        raise RingClosed("ring %r closed and drained" % self.name)
      # -3: record larger than our scratch — grow and retry
      self._buf = ctypes.create_string_buffer(len(self._buf) * 2)

  def close_write(self) -> None:
    """Producer signals end-of-stream (consumer drains then RingClosed)."""
    self._lib.tos_ring_close_write(self._h)

  def pending_bytes(self) -> int:
    return self._lib.tos_ring_pending(self._h)

  def free(self) -> None:
    if self._h:
      self._lib.tos_ring_free(self._h, self.name.encode(),
                              1 if self._owner else 0)
      self._h = None

  def __enter__(self):
    return self

  def __exit__(self, *exc):
    self.free()
