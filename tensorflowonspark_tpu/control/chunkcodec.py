"""Columnar chunk codec for the feed data plane.

The feed plane moves chunks (lists of rows) between processes. Pickling a
list of numpy rows costs a per-row object walk on both sides; most feed
traffic is homogeneous (every row an ndarray, or a fixed-arity tuple of
ndarrays/scalars — exactly what ``dfutil``/``DataFeed`` produce). Such
chunks are encoded COLUMNAR: each column is stacked into one contiguous
buffer and shipped as raw bytes inside a msgpack envelope — no pickle on
the hot path, one memcpy per column. Anything heterogeneous falls back to
cloudpickle transparently.

Format: msgpack map ``{"f": format, ...}``; format 0 = cloudpickle
payload under ``"p"``; format 1 = columnar with ``"t"`` (rows are tuples)
and ``"c"`` (list of columns, each ``{"d": dtype, "s": shape, "b": bytes,
"y": python-scalar flag}``). A column may additionally carry ``"e"`` — a
per-column WIRE ENCODING id (absent = raw bytes, the original format,
byte-identical). Per column per chunk a cheap sampled heuristic picks one
of the registry's encodings when it is allowed and pays:

- ``dict`` — low-cardinality integer columns (labels, category ids):
  unique values plus a uint8 index stream;
- ``delta`` — monotone non-decreasing integer columns (row ids,
  timestamps): first value plus per-element deltas in the narrowest
  unsigned dtype that holds them;
- ``bitpack`` — bool columns at one bit per element;
- ``zlib`` — stdlib byte-level fallback for any column whose sampled
  compression ratio clears the threshold.

Every encoding round-trips EXACTLY (bit-identical values), so consumers
cannot observe which encoding a chunk rode in on. The candidate set comes
from ``TOS_FEED_WIRE_ENCODINGS`` (comma-separated registry names;
``raw``/empty disables every encoder).

Two decode modes:

- :func:`decode` — row materialization (pickle parity: writable rows),
  the legacy hot path;
- :func:`decode_columns` — returns a :class:`ColumnChunk` whose column
  arrays are READ-ONLY: ``raw`` columns are ZERO-COPY views over the
  msgpack bin payload (msgpack owns the bytes, so the views outlive any
  transport scratch buffer the payload was parsed from); encoded columns
  materialize exactly once at decode (they are smaller by construction).
  Consumers assemble batches by slicing and concatenating these columns;
  the concatenation at batch hand-off is the single copy on that path
  (and ``datafeed._assemble_columns`` elides even that when a batch falls
  inside one chunk).
"""

import os
import zlib
from typing import List, Optional

import cloudpickle
import msgpack
import numpy as np

_F_PICKLE = 0
_F_COLUMNAR = 1

_SCALARS = (bool, int, float)

#: chunk payloads above this ENCODED size are split at the row level
#: before transport (a ring record larger than ~half the ring capacity
#: can wedge against the wrap-around padding; hub-queue envelopes just
#: get cheaper to pickle). Compression widens the effective row budget —
#: the split always measures the encoded payload, never the raw rows.
MAX_PAYLOAD = 4 * 1024 * 1024

#: candidate per-column wire encodings: comma-separated ``_ENCODERS``
#: names; ``raw`` or empty disables every encoder (env registry: TOS008)
ENV_FEED_WIRE_ENCODINGS = "TOS_FEED_WIRE_ENCODINGS"

DEFAULT_WIRE_ENCODINGS = "dict,delta,bitpack,zlib"

# wire ids (a column's "e" key; an absent key means _E_RAW)
_E_RAW = 0
_E_DICT = 1
_E_DELTA = 2
_E_BITPACK = 3
_E_ZLIB = 4

#: columns below this raw size always ship raw: the heuristic probe and
#: the decode-side materialization both out-price the byte savings
MIN_ENCODE_BYTES = 512
_SAMPLE_ELEMS = 64         # cardinality probe sample size
_DICT_PROBE_MAX = 16       # sampled distinct values above this: no dict
_DICT_MAX = 256            # full distinct bound (uint8 index stream)
_ZLIB_PROBE_BYTES = 4096   # leading slice test-compressed by the probe
                           # (level-1 ratio estimates stabilize well under
                           # 4 KiB; a declined probe is the hot path on
                           # incompressible columns, so it must stay cheap)
_ZLIB_PROBE_RATIO = 0.7    # probe must compress below this to continue
_ZLIB_LEVEL = 1            # speed over ratio: the feeder is a hot path


class OversizedRowError(ValueError):
  """A SINGLE row's encoded payload exceeds ``MAX_PAYLOAD``: it cannot be
  split further at the row level, so no transport can carry it. Raised as
  a structured error by ``node.put_rows_chunk`` instead of recursing."""


def _enc_bitpack(arr: np.ndarray, raw: bytes) -> Optional[dict]:
  if arr.dtype.kind != "b":
    return None
  return {"e": _E_BITPACK, "b": np.packbits(arr.reshape(-1)).tobytes()}


def _dec_bitpack(col: dict, count: int) -> np.ndarray:
  bits = np.unpackbits(np.frombuffer(col["b"], np.uint8), count=count)
  return bits.view(np.bool_)


def _enc_dict(arr: np.ndarray, raw: bytes) -> Optional[dict]:
  if arr.dtype.kind not in "iu":
    return None
  flat = arr.reshape(-1)
  # strided cardinality probe before the O(n log n) full unique
  step = max(1, flat.size // _SAMPLE_ELEMS)
  if np.unique(flat[::step]).size > _DICT_PROBE_MAX:
    return None
  uniq, inv = np.unique(flat, return_inverse=True)
  if uniq.size > _DICT_MAX:
    return None
  idx = inv.astype(np.uint8).reshape(-1)
  if uniq.nbytes + idx.nbytes >= len(raw):
    return None
  return {"e": _E_DICT, "b": idx.tobytes(), "u": uniq.tobytes()}


def _dec_dict(col: dict, count: int) -> np.ndarray:
  uniq = np.frombuffer(col["u"], dtype=np.dtype(col["d"]))
  idx = np.frombuffer(col["b"], dtype=np.uint8)
  return uniq[idx]


def _enc_delta(arr: np.ndarray, raw: bytes) -> Optional[dict]:
  # scalar-per-row integer columns only; values must fit python->msgpack
  # int64 and the span must fit uint32 so the int64 delta math is exact
  if arr.dtype.kind not in "iu" or arr.ndim != 1 or arr.size < 2 \
      or arr.dtype.itemsize < 2:
    return None
  lo, hi = int(arr[0]), int(arr[-1])
  if lo < -(1 << 63) or hi > (1 << 63) - 1 or hi - lo > 0xFFFFFFFF:
    return None
  if not bool(np.all(arr[1:] >= arr[:-1])):
    return None
  deltas = arr[1:].astype(np.int64) - arr[:-1].astype(np.int64)
  dmax = int(deltas.max())
  wire = np.uint8 if dmax <= 0xFF else \
      np.uint16 if dmax <= 0xFFFF else np.uint32
  if np.dtype(wire).itemsize >= arr.dtype.itemsize:
    return None
  return {"e": _E_DELTA, "b": deltas.astype(wire).tobytes(),
          "w": np.dtype(wire).str, "m": lo}


def _dec_delta(col: dict, count: int) -> np.ndarray:
  out = np.empty(count, dtype=np.int64)
  out[0] = col["m"]
  if count > 1:
    deltas = np.frombuffer(col["b"], dtype=np.dtype(col["w"]))
    np.cumsum(deltas, dtype=np.int64, out=out[1:])
    out[1:] += col["m"]
  # exact: every value sits inside the original dtype's range
  return out.astype(np.dtype(col["d"]))


def _enc_zlib(arr: np.ndarray, raw: bytes) -> Optional[dict]:
  probe = raw[:_ZLIB_PROBE_BYTES]
  if len(zlib.compress(probe, _ZLIB_LEVEL)) > _ZLIB_PROBE_RATIO * len(probe):
    return None  # sampled ratio says incompressible: don't pay the full pass
  comp = zlib.compress(raw, _ZLIB_LEVEL)
  if len(comp) >= len(raw):
    return None
  return {"e": _E_ZLIB, "b": comp}


def _dec_zlib(col: dict, count: int) -> np.ndarray:
  return np.frombuffer(zlib.decompress(col["b"]), dtype=np.dtype(col["d"]))


#: the wire-encoding registry. Contract (analyzer rule TOS014): every
#: ``_ENCODERS`` key MUST have a matching ``_DECODERS`` arm — an encoder
#: alone emits payloads no consumer can open. Decoder-only arms are fine
#: (kept for wire compatibility after an encoder retires).
_ENCODERS = {
    "dict": _enc_dict,
    "delta": _enc_delta,
    "bitpack": _enc_bitpack,
    "zlib": _enc_zlib,
}

_DECODERS = {
    "dict": _dec_dict,
    "delta": _dec_delta,
    "bitpack": _dec_bitpack,
    "zlib": _dec_zlib,
}

#: heuristic try-order: cheap structural encodings first, byte-level
#: zlib last (it is the most expensive probe and the slowest decode)
_PRECEDENCE = ("bitpack", "delta", "dict", "zlib")

_WIRE_IDS = {"dict": _E_DICT, "delta": _E_DELTA, "bitpack": _E_BITPACK,
             "zlib": _E_ZLIB}
_ID_NAMES = {v: k for k, v in _WIRE_IDS.items()}

_allowed_cache: dict = {}

#: probe hysteresis: a column that declined EVERY enabled encoder backs
#: off exponentially (skip 1, 2, 4, ... _PROBE_BACKOFF_MAX chunks between
#: probes) — a declining column keeps declining, so steady-state probe
#: cost on incompressible data amortizes to ~zero, while a distribution
#: shift is still caught within _PROBE_BACKOFF_MAX chunks. Keyed by
#: (column position, dtype) in the sender process; any successful pick
#: resets the column's backoff. Single-writer state (one feeder thread
#: encodes a given stream); a racing reader at worst probes early.
_PROBE_BACKOFF_MAX = 32
_probe_backoff: dict = {}


def _allowed_encodings() -> tuple:
  """Enabled encoder names in precedence order (memoized per env value)."""
  spec = os.environ.get(ENV_FEED_WIRE_ENCODINGS, DEFAULT_WIRE_ENCODINGS)
  got = _allowed_cache.get(spec)
  if got is None:
    names = {s.strip() for s in spec.split(",")}
    got = tuple(n for n in _PRECEDENCE if n in names)
    _allowed_cache[spec] = got
  return got


def _encode_array(arr: np.ndarray, shape: list, scalar: int,
                  stats=None, col_key=None) -> dict:
  """One stacked ``(n, *shape)`` column array -> wire descriptor.

  Runs the sampled heuristic over the enabled encodings; a raw column's
  descriptor is byte-identical to the pre-registry format (no ``"e"``).
  ``col_key`` (the column's position in its chunk) opts the column into
  probe backoff; direct callers without a stable identity leave it None
  and probe every time."""
  raw = arr.tobytes()
  allowed = _allowed_encodings()
  if len(raw) >= MIN_ENCODE_BYTES and allowed:
    key = (col_key, arr.dtype.str) if col_key is not None else None
    state = _probe_backoff.get(key) if key is not None else None
    if state is not None and state[1] > 0:
      state[1] -= 1            # backing off: ship raw without probing
    else:
      for name in allowed:
        ext = _ENCODERS[name](arr, raw)
        if ext is not None:
          if key is not None:
            _probe_backoff.pop(key, None)
          if stats is not None:
            stats[name] = stats.get(name, 0) + 1
          desc = {"d": arr.dtype.str, "s": shape, "y": scalar}
          desc.update(ext)
          return desc
      if key is not None:
        skip = min(_PROBE_BACKOFF_MAX, state[0] * 2 if state else 1)
        _probe_backoff[key] = [skip, skip]
  if stats is not None:
    stats["raw"] = stats.get("raw", 0) + 1
  return {"d": arr.dtype.str, "s": shape, "b": raw, "y": scalar}


def _encode_column(values, stats=None, col_key=None) -> Optional[dict]:
  """One column (len(chunk) values) -> descriptor, or None if ineligible."""
  first = values[0]
  if isinstance(first, np.ndarray):
    dtype, shape = first.dtype, first.shape
    if dtype == object or not all(
        isinstance(v, np.ndarray) and v.dtype == dtype and v.shape == shape
        for v in values):
      return None
    return _encode_array(np.stack(values), list(shape), 0, stats, col_key)
  if isinstance(first, _SCALARS):
    kind = type(first)
    # EXACT python types only: decode materializes .item() python scalars,
    # so np.float64 (a float subclass that passes the isinstance above)
    # would silently come back retyped — pickle round-trips it instead
    if kind not in _SCALARS or not all(type(v) is kind for v in values):
      return None
    try:
      arr = np.asarray(values)
    except OverflowError:   # int outside every numpy integer range
      return None
    # the array dtype must round-trip the value kind EXACTLY: ints beyond
    # int64 coerce to float64 (silent rounding + retyping), so ineligible
    if arr.dtype.kind != {bool: "b", int: "i", float: "f"}[kind]:
      return None
    return _encode_array(arr, [], 1, stats, col_key)
  return None


def _view_column(col: dict, n: int) -> np.ndarray:
  """Column descriptor -> (n, *shape) ndarray view over the bin payload.

  Zero-copy: the array is read-only and backed by the bytes object
  msgpack produced for the bin — no bytearray copy, no per-row list."""
  arr = np.frombuffer(col["b"], dtype=np.dtype(col["d"]))
  return arr.reshape((n,) + tuple(col["s"]))


def _decode_column(col: dict, n: int) -> np.ndarray:
  """Column descriptor -> (n, *shape) ndarray, READ-ONLY either way:
  ``raw`` stays the zero-copy :func:`_view_column` path; encoded columns
  materialize exactly once here."""
  wire_id = col.get("e", _E_RAW)
  if wire_id == _E_RAW:
    return _view_column(col, n)
  name = _ID_NAMES.get(wire_id)
  if name is None:
    raise ValueError("unknown wire-encoding id %r (sender newer than this "
                     "decoder?)" % (wire_id,))
  shape = (n,) + tuple(col["s"])
  count = 1
  for dim in shape:
    count *= int(dim)
  arr = _DECODERS[name](col, count).reshape(shape)
  arr.flags.writeable = False
  return arr


class ColumnChunk(object):
  """A decoded columnar chunk: per-column ndarray views sharing one payload.

  ``cols[j]`` has shape ``(n, *row_shape)`` and is READ-ONLY (raw columns
  alias the msgpack bin bytes; encoded columns are frozen decode output).
  ``scalar[j]`` marks columns whose row values were python scalars;
  ``tuples`` says whether rows were tuples.
  :meth:`rows` materializes the exact row list :func:`decode` returns
  (writable, pickle parity) — the fallback for row-granular consumers.
  """

  __slots__ = ("cols", "scalar", "tuples", "n")

  def __init__(self, cols: List[np.ndarray], scalar: List[int],
               tuples: bool, n: int):
    self.cols = cols
    self.scalar = scalar
    self.tuples = tuples
    self.n = n

  def rows(self, start: int = 0) -> List:
    """Materialize rows ``start..n`` (writable copies, decode() parity)."""
    per_col = []
    for arr, y in zip(self.cols, self.scalar):
      part = arr[start:]
      if y:
        per_col.append([v.item() for v in part])
      else:
        # one copy per column; rows come out as non-overlapping WRITABLE
        # views of it (consumers mutate rows in place, e.g. `row /= 255.0`)
        per_col.append(list(part.copy()))
    if not self.tuples:
      return per_col[0]
    return [tuple(col[i] for col in per_col) for i in range(self.n - start)]


def _encode_chunk(chunk: "ColumnChunk", stats=None) -> bytes:
  """Encode an in-process :class:`ColumnChunk` (already-stacked columns —
  e.g. a feeder-side pushdown segment's output) without re-materializing
  rows. Falls back to pickle under the same eligibility rules as the
  row-list path (object columns, pure-scalar chunks)."""
  if chunk.n and not any(c.dtype == object for c in chunk.cols) and \
      any(not y for y in chunk.scalar):
    tally: dict = {}
    cols = [_encode_array(arr, list(arr.shape[1:]), int(bool(y)), tally, j)
            for j, (arr, y) in enumerate(zip(chunk.cols, chunk.scalar))]
    if stats is not None:
      for k, v in tally.items():
        stats[k] = stats.get(k, 0) + v
    return msgpack.packb({"f": _F_COLUMNAR, "n": chunk.n,
                          "t": 1 if chunk.tuples else 0, "c": cols},
                         use_bin_type=True)
  return msgpack.packb({"f": _F_PICKLE,
                        "p": cloudpickle.dumps(chunk.rows())},
                       use_bin_type=True)


def encode(chunk, stats=None) -> bytes:
  """Serialize a chunk (any object; lists of homogeneous rows go columnar).

  ``chunk`` may also be a :class:`ColumnChunk`, whose stacked columns
  encode directly. ``stats``: optional dict tallying per-column encoding
  counts (``{"raw": 2, "dict": 1, ...}``) for chunks that ship columnar."""
  if isinstance(chunk, ColumnChunk):
    return _encode_chunk(chunk, stats)
  if isinstance(chunk, list) and chunk:
    cols = None
    tally: dict = {}
    first = chunk[0]
    if isinstance(first, tuple) and first and all(
        isinstance(r, tuple) and len(r) == len(first) for r in chunk):
      cols = [_encode_column([r[j] for r in chunk], tally, j)
              for j in range(len(first))]
      tuples = 1
    elif not isinstance(first, tuple):
      cols = [_encode_column(chunk, tally, 0)]
      tuples = 0
    # columnar only pays when real array data avoids the pickle walk;
    # pure-scalar chunks are faster (and smaller) through pickle
    if cols is not None and all(c is not None for c in cols) and \
        any(not c["y"] for c in cols):
      if stats is not None:
        for k, v in tally.items():
          stats[k] = stats.get(k, 0) + v
      return msgpack.packb({"f": _F_COLUMNAR, "n": len(chunk),
                            "t": tuples, "c": cols}, use_bin_type=True)
  return msgpack.packb({"f": _F_PICKLE, "p": cloudpickle.dumps(chunk)},
                       use_bin_type=True)


def decode_columns(payload):
  """Decode WITHOUT materializing rows: columnar chunks come back as a
  :class:`ColumnChunk` of read-only column arrays (zero-copy views for
  ``raw`` columns); pickle-format payloads return the original object
  (typically a row list). ``payload`` may be any buffer (bytes or a
  memoryview over a transport scratch — msgpack copies bin data into
  owned bytes during the parse, so the returned views never alias the
  caller's buffer)."""
  msg = msgpack.unpackb(payload, raw=False)
  if msg["f"] == _F_PICKLE:
    return cloudpickle.loads(msg["p"])
  n = msg["n"]
  return ColumnChunk([_decode_column(c, n) for c in msg["c"]],
                     [c["y"] for c in msg["c"]], bool(msg["t"]), n)


def decode(payload):
  out = decode_columns(payload)
  if isinstance(out, ColumnChunk):
    return out.rows()
  return out


def classify_decoded(chunk):
  """Normalize a :func:`decode_columns` result to the consumer wire union.

  THE single definition of the chunk-boundary contract every transport's
  ``get_chunk`` and the feed's fetch path share: ``("data", ColumnChunk |
  row_list)`` for payload chunks, ``("marker", m)`` for a single-marker
  chunk (an end-of-feed ``None`` or a ``Marker`` shipped alone at a chunk
  boundary); bare pickled scalars wrap into a one-row list."""
  from tensorflowonspark_tpu.control.marker import Marker
  if isinstance(chunk, ColumnChunk):
    return ("data", chunk)
  if isinstance(chunk, list):
    if len(chunk) == 1 and (chunk[0] is None or isinstance(chunk[0], Marker)):
      return ("marker", chunk[0])
    return ("data", chunk)
  return ("data", [chunk])
