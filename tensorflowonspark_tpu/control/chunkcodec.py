"""Columnar chunk codec for the feed data plane.

The feed plane moves chunks (lists of rows) between processes. Pickling a
list of numpy rows costs a per-row object walk on both sides; most feed
traffic is homogeneous (every row an ndarray, or a fixed-arity tuple of
ndarrays/scalars — exactly what ``dfutil``/``DataFeed`` produce). Such
chunks are encoded COLUMNAR: each column is stacked into one contiguous
buffer and shipped as raw bytes inside a msgpack envelope — no pickle on
the hot path, one memcpy per column. Anything heterogeneous falls back to
cloudpickle transparently.

Format: msgpack map ``{"f": format, ...}``; format 0 = cloudpickle
payload under ``"p"``; format 1 = columnar with ``"t"`` (rows are tuples)
and ``"c"`` (list of columns, each ``{"d": dtype, "s": shape, "b": bytes,
"y": python-scalar flag}``).

Two decode modes:

- :func:`decode` — row materialization (pickle parity: writable rows),
  the legacy hot path;
- :func:`decode_columns` — returns a :class:`ColumnChunk` whose column
  arrays are ZERO-COPY views over the msgpack bin payload (msgpack owns
  the bytes, so the views outlive any transport scratch buffer the
  payload was parsed from). Consumers assemble batches by slicing and
  concatenating these columns; the concatenation at batch hand-off is
  the single copy on that path.
"""

from typing import List, Optional

import cloudpickle
import msgpack
import numpy as np

_F_PICKLE = 0
_F_COLUMNAR = 1

_SCALARS = (bool, int, float)

#: chunk payloads above this are split at the row level before transport
#: (a ring record larger than ~half the ring capacity can wedge against
#: the wrap-around padding; hub-queue envelopes just get cheaper to pickle)
MAX_PAYLOAD = 4 * 1024 * 1024


def _encode_column(values) -> Optional[dict]:
  """One column (len(chunk) values) -> descriptor, or None if ineligible."""
  first = values[0]
  if isinstance(first, np.ndarray):
    dtype, shape = first.dtype, first.shape
    if dtype == object or not all(
        isinstance(v, np.ndarray) and v.dtype == dtype and v.shape == shape
        for v in values):
      return None
    return {"d": dtype.str, "s": list(shape), "b": np.stack(values).tobytes(),
            "y": 0}
  if isinstance(first, _SCALARS):
    kind = type(first)
    # EXACT python types only: decode materializes .item() python scalars,
    # so np.float64 (a float subclass that passes the isinstance above)
    # would silently come back retyped — pickle round-trips it instead
    if kind not in _SCALARS or not all(type(v) is kind for v in values):
      return None
    try:
      arr = np.asarray(values)
    except OverflowError:   # int outside every numpy integer range
      return None
    # the array dtype must round-trip the value kind EXACTLY: ints beyond
    # int64 coerce to float64 (silent rounding + retyping), so ineligible
    if arr.dtype.kind != {bool: "b", int: "i", float: "f"}[kind]:
      return None
    return {"d": arr.dtype.str, "s": [], "b": arr.tobytes(), "y": 1}
  return None


def _view_column(col: dict, n: int) -> np.ndarray:
  """Column descriptor -> (n, *shape) ndarray view over the bin payload.

  Zero-copy: the array is read-only and backed by the bytes object
  msgpack produced for the bin — no bytearray copy, no per-row list."""
  arr = np.frombuffer(col["b"], dtype=np.dtype(col["d"]))
  return arr.reshape((n,) + tuple(col["s"]))


class ColumnChunk(object):
  """A decoded columnar chunk: per-column ndarray views sharing one payload.

  ``cols[j]`` has shape ``(n, *row_shape)`` and is READ-ONLY (it aliases
  the msgpack bin bytes). ``scalar[j]`` marks columns whose row values
  were python scalars; ``tuples`` says whether rows were tuples.
  :meth:`rows` materializes the exact row list :func:`decode` returns
  (writable, pickle parity) — the fallback for row-granular consumers.
  """

  __slots__ = ("cols", "scalar", "tuples", "n")

  def __init__(self, cols: List[np.ndarray], scalar: List[int],
               tuples: bool, n: int):
    self.cols = cols
    self.scalar = scalar
    self.tuples = tuples
    self.n = n

  def rows(self, start: int = 0) -> List:
    """Materialize rows ``start..n`` (writable copies, decode() parity)."""
    per_col = []
    for arr, y in zip(self.cols, self.scalar):
      part = arr[start:]
      if y:
        per_col.append([v.item() for v in part])
      else:
        # one copy per column; rows come out as non-overlapping WRITABLE
        # views of it (consumers mutate rows in place, e.g. `row /= 255.0`)
        per_col.append(list(part.copy()))
    if not self.tuples:
      return per_col[0]
    return [tuple(col[i] for col in per_col) for i in range(self.n - start)]


def encode(chunk) -> bytes:
  """Serialize a chunk (any object; lists of homogeneous rows go columnar)."""
  if isinstance(chunk, list) and chunk:
    cols = None
    first = chunk[0]
    if isinstance(first, tuple) and first and all(
        isinstance(r, tuple) and len(r) == len(first) for r in chunk):
      cols = [_encode_column([r[j] for r in chunk])
              for j in range(len(first))]
      tuples = 1
    elif not isinstance(first, tuple):
      cols = [_encode_column(chunk)]
      tuples = 0
    # columnar only pays when real array data avoids the pickle walk;
    # pure-scalar chunks are faster (and smaller) through pickle
    if cols is not None and all(c is not None for c in cols) and \
        any(not c["y"] for c in cols):
      return msgpack.packb({"f": _F_COLUMNAR, "n": len(chunk),
                            "t": tuples, "c": cols}, use_bin_type=True)
  return msgpack.packb({"f": _F_PICKLE, "p": cloudpickle.dumps(chunk)},
                       use_bin_type=True)


def decode_columns(payload):
  """Decode WITHOUT materializing rows: columnar chunks come back as a
  :class:`ColumnChunk` of zero-copy column views; pickle-format payloads
  return the original object (typically a row list). ``payload`` may be
  any buffer (bytes or a memoryview over a transport scratch — msgpack
  copies bin data into owned bytes during the parse, so the returned
  views never alias the caller's buffer)."""
  msg = msgpack.unpackb(payload, raw=False)
  if msg["f"] == _F_PICKLE:
    return cloudpickle.loads(msg["p"])
  n = msg["n"]
  return ColumnChunk([_view_column(c, n) for c in msg["c"]],
                     [c["y"] for c in msg["c"]], bool(msg["t"]), n)


def decode(payload):
  out = decode_columns(payload)
  if isinstance(out, ColumnChunk):
    return out.rows()
  return out


def classify_decoded(chunk):
  """Normalize a :func:`decode_columns` result to the consumer wire union.

  THE single definition of the chunk-boundary contract every transport's
  ``get_chunk`` and the feed's fetch path share: ``("data", ColumnChunk |
  row_list)`` for payload chunks, ``("marker", m)`` for a single-marker
  chunk (an end-of-feed ``None`` or a ``Marker`` shipped alone at a chunk
  boundary); bare pickled scalars wrap into a one-row list."""
  from tensorflowonspark_tpu.control.marker import Marker
  if isinstance(chunk, ColumnChunk):
    return ("data", chunk)
  if isinstance(chunk, list):
    if len(chunk) == 1 and (chunk[0] is None or isinstance(chunk[0], Marker)):
      return ("marker", chunk[0])
    return ("data", chunk)
  return ("data", [chunk])
