"""Columnar chunk codec for the feed data plane.

The feed plane moves chunks (lists of rows) between processes. Pickling a
list of numpy rows costs a per-row object walk on both sides; most feed
traffic is homogeneous (every row an ndarray, or a fixed-arity tuple of
ndarrays/scalars — exactly what ``dfutil``/``DataFeed`` produce). Such
chunks are encoded COLUMNAR: each column is stacked into one contiguous
buffer and shipped as raw bytes inside a msgpack envelope — no pickle on
the hot path, one memcpy per column. Anything heterogeneous falls back to
cloudpickle transparently.

Format: msgpack map ``{"f": format, ...}``; format 0 = cloudpickle
payload under ``"p"``; format 1 = columnar with ``"t"`` (rows are tuples)
and ``"c"`` (list of columns, each ``{"d": dtype, "s": shape, "b": bytes,
"y": python-scalar flag}``).
"""

from typing import List, Optional

import cloudpickle
import msgpack
import numpy as np

_F_PICKLE = 0
_F_COLUMNAR = 1

_SCALARS = (bool, int, float)


def _encode_column(values) -> Optional[dict]:
  """One column (len(chunk) values) -> descriptor, or None if ineligible."""
  first = values[0]
  if isinstance(first, np.ndarray):
    dtype, shape = first.dtype, first.shape
    if dtype == object or not all(
        isinstance(v, np.ndarray) and v.dtype == dtype and v.shape == shape
        for v in values):
      return None
    return {"d": dtype.str, "s": list(shape), "b": np.stack(values).tobytes(),
            "y": 0}
  if isinstance(first, _SCALARS):
    kind = type(first)
    if not all(type(v) is kind for v in values):
      return None
    arr = np.asarray(values)
    if arr.dtype == object:
      return None
    return {"d": arr.dtype.str, "s": [], "b": arr.tobytes(), "y": 1}
  return None


def _decode_column(col: dict, n: int) -> List:
  # bytearray: one copy per column, but the rows come out WRITABLE (pickle
  # parity — consumers mutate rows in place, e.g. `row /= 255.0`)
  arr = np.frombuffer(bytearray(col["b"]), dtype=np.dtype(col["d"]))
  shape = tuple(col["s"])
  arr = arr.reshape((n,) + shape)
  if col["y"]:
    return [v.item() for v in arr]
  return list(arr)


def encode(chunk) -> bytes:
  """Serialize a chunk (any object; lists of homogeneous rows go columnar)."""
  if isinstance(chunk, list) and chunk:
    cols = None
    first = chunk[0]
    if isinstance(first, tuple) and first and all(
        isinstance(r, tuple) and len(r) == len(first) for r in chunk):
      cols = [_encode_column([r[j] for r in chunk])
              for j in range(len(first))]
      tuples = 1
    elif not isinstance(first, tuple):
      cols = [_encode_column(chunk)]
      tuples = 0
    # columnar only pays when real array data avoids the pickle walk;
    # pure-scalar chunks are faster (and smaller) through pickle
    if cols is not None and all(c is not None for c in cols) and \
        any(not c["y"] for c in cols):
      return msgpack.packb({"f": _F_COLUMNAR, "n": len(chunk),
                            "t": tuples, "c": cols}, use_bin_type=True)
  return msgpack.packb({"f": _F_PICKLE, "p": cloudpickle.dumps(chunk)},
                       use_bin_type=True)


def decode(payload: bytes):
  msg = msgpack.unpackb(payload, raw=False)
  if msg["f"] == _F_PICKLE:
    return cloudpickle.loads(msg["p"])
  n = msg["n"]
  columns = [_decode_column(c, n) for c in msg["c"]]
  if not msg["t"]:
    return columns[0]
  return [tuple(col[i] for col in columns) for i in range(n)]
