"""Per-executor feed hub: queues + key-value state shared across processes.

Capability parity with the reference's ``TFManager.py``
(/root/reference/tensorflowonspark/TFManager.py): a
``multiprocessing.managers.BaseManager`` exposing named joinable queues and a
key-value store, started in ``'local'`` mode for workers (loopback only) or
``'remote'`` mode for ps/evaluator nodes so the driver can reach them across
the network (TFManager.py:40-65). The state machine lives under key
``'state'``: ``'running' → 'terminating' → 'stopped'``.

TPU-first redesign: the reference moved one pickled row per proxy round-trip
(TFSparkNode.py:500-502 / TFNode.py:276-300) — two IPC hops per row, which
would starve a TPU. The hub therefore exposes **batch transfer**
(``put_many`` / ``get_many``) so the feeder pushes whole chunks and the
training process pops up to a full batch per round-trip, while preserving the
exact queue semantics the DataFeed API depends on: blocking ``put``,
``task_done``/``join`` backpressure, ``None`` end-of-feed and ``EndPartition``
markers as in-band items.
"""

import collections
import logging
import os
import socket
import threading
import time
from multiprocessing import connection as _mpconn
from multiprocessing.managers import BaseManager
from typing import Dict, List, Optional, Sequence, Tuple

from tensorflowonspark_tpu.control.marker import Marker

logger = logging.getLogger(__name__)


def _sock_nodelay(conn) -> None:
  """Disable Nagle on a live manager connection.

  CPython's ``Connection._send_bytes`` writes the length header and the
  body as TWO separate ``send()`` calls for payloads over 16 KiB; with
  Nagle on, that interacts with the peer's delayed ACK into an ~40 ms
  stall per message EACH WAY for mid-size (16–64 KiB) payloads —
  exactly where wire-encoded chunk envelopes land (measured: 88 ms per
  put+get round trip vs 0.4 ms just above 64 KiB). Socket options stick
  to the underlying socket, so setting them through a dup'd fd covers
  the Connection's own handle. Non-TCP transports raise and are left
  untouched.
  """
  try:
    s = socket.fromfd(conn.fileno(), socket.AF_INET, socket.SOCK_STREAM)
    try:
      s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    finally:
      s.close()
  except (OSError, ValueError):  # tosa: ignore[TOS004] - non-TCP transport
    pass


_nodelay_installed = False


def _install_nodelay() -> None:
  """Patch ``multiprocessing.connection`` so every manager socket this
  process dials or accepts runs with TCP_NODELAY (idempotent; called on
  the server via ``_init_server`` and on clients via start/connect —
  proxies dial lazily per thread, so per-call hooks cannot cover them)."""
  global _nodelay_installed
  if _nodelay_installed:
    return
  _nodelay_installed = True
  orig_client = _mpconn.SocketClient

  def _client_nodelay(address):
    c = orig_client(address)
    _sock_nodelay(c)
    return c

  _mpconn.SocketClient = _client_nodelay
  orig_accept = _mpconn.SocketListener.accept

  def _accept_nodelay(self):
    c = orig_accept(self)
    _sock_nodelay(c)
    return c

  _mpconn.SocketListener.accept = _accept_nodelay


class ChunkEnvelope(object):
  """A codec-encoded feed chunk traveling the hub queue as ONE item.

  ``n`` rows ride inside ``payload`` (control/chunkcodec.py bytes); the
  queue's bound and unfinished-task counter both weigh the envelope as
  ``n`` rows, so backpressure and ``join`` semantics are identical to
  the same rows enqueued individually — but the manager round-trip moves
  one bytes object instead of pickling every row."""

  __slots__ = ("n", "payload")

  def __init__(self, n: int, payload: bytes):
    self.n = n
    self.payload = payload

  def __reduce__(self):
    return (ChunkEnvelope, (self.n, self.payload))


def _item_weight(item) -> int:
  return item.n if isinstance(item, ChunkEnvelope) else 1


class FeedQueue(object):
  """A joinable, bounded, batch-aware queue (thread-safe).

  Semantics match ``multiprocessing.JoinableQueue``: every item put increments
  an unfinished-task counter which ``task_done`` decrements; ``join`` blocks
  until it reaches zero. Adds ``put_many``/``get_many`` so a whole chunk moves
  per manager round-trip, and ``put_chunk``/``get_chunk`` so a chunk moves as
  ONE :class:`ChunkEnvelope` item (weighted as its row count) with markers
  delivered as chunk-boundary envelopes.
  """

  def __init__(self, maxsize: int = 0):
    self._maxsize = maxsize
    self._items = collections.deque()
    self._size = 0          # weighted length: envelopes count their rows
    self._cond = threading.Condition()
    self._unfinished = 0

  def _has_room(self, n: int) -> bool:
    return self._maxsize <= 0 or self._size + n <= self._maxsize

  def put(self, item, block: bool = True, timeout: Optional[float] = None):
    self.put_many([item], block=block, timeout=timeout)

  def put_many(self, items: Sequence, block: bool = True,
               timeout: Optional[float] = None) -> None:
    """Enqueue items, spilling chunks larger than ``maxsize`` in pieces.

    A blocking put of a chunk bigger than the queue bound must not deadlock:
    admit whatever fits (at least one item at a time) and keep going as the
    consumer drains. Non-blocking puts are all-or-nothing: if the whole
    chunk cannot be admitted immediately, nothing is enqueued. A timed-out
    blocking put raises :class:`QueueFull` with ``.admitted`` set to the
    number of items already enqueued, so callers can avoid re-feeding them.
    """
    items = list(items)
    deadline = None if timeout is None else time.monotonic() + timeout
    pos = 0
    with self._cond:
      if not block and not self._has_room(len(items)):
        raise QueueFull(0)
      while pos < len(items):
        room = (len(items) - pos if self._maxsize <= 0
                else self._maxsize - self._size)
        if room <= 0:
          if not block:
            raise QueueFull(pos)
          remaining = None if deadline is None else deadline - time.monotonic()
          if remaining is not None and remaining <= 0:
            raise QueueFull(pos)
          self._cond.wait(remaining if remaining is not None else 1.0)
          continue
        chunk = items[pos:pos + room]
        self._items.extend(chunk)
        weight = sum(_item_weight(it) for it in chunk)
        self._size += weight
        self._unfinished += weight
        pos += len(chunk)
        self._cond.notify_all()

  def get(self, block: bool = True, timeout: Optional[float] = None):
    got = self.get_many(1, block=block, timeout=timeout)
    if not got:
      raise QueueEmpty()
    return got[0]

  def get_many(self, max_items: int, block: bool = True,
               timeout: Optional[float] = None) -> List:
    """Pop up to ``max_items``; blocks for at least one item when ``block``.

    Stops early at a control marker boundary is NOT done here — marker
    interpretation belongs to the DataFeed layer; this is a plain queue.
    """
    deadline = None if timeout is None else time.monotonic() + timeout
    with self._cond:
      while not self._items:
        if not block:
          return []
        remaining = None if deadline is None else deadline - time.monotonic()
        if remaining is not None and remaining <= 0:
          return []
        self._cond.wait(remaining if remaining is not None else 1.0)
      out = []
      while self._items and len(out) < max_items:
        item = self._items.popleft()
        self._size -= _item_weight(item)
        out.append(item)
      self._cond.notify_all()
      return out

  # -- chunk-granular delivery ----------------------------------------------

  def put_chunk(self, n: int, payload: bytes, block: bool = True,
                timeout: Optional[float] = None) -> None:
    """Enqueue one codec-encoded chunk of ``n`` rows as a single envelope.

    The envelope is atomic (it cannot spill in pieces): admission waits
    for ``n`` rows of room, or for the queue to be empty — so a chunk
    larger than the whole bound still streams through alone instead of
    deadlocking. Weighted exactly like ``n`` individual rows for both the
    bound and the ``join`` counter.
    """
    deadline = None if timeout is None else time.monotonic() + timeout
    with self._cond:
      while not (self._has_room(n) or self._size == 0):
        if not block:
          raise QueueFull(0)
        remaining = None if deadline is None else deadline - time.monotonic()
        if remaining is not None and remaining <= 0:
          raise QueueFull(0)
        self._cond.wait(remaining if remaining is not None else 1.0)
      self._items.append(ChunkEnvelope(n, payload))
      self._size += n
      self._unfinished += n
      self._cond.notify_all()

  def get_chunk(self, max_rows: int = 1024, block: bool = True,
                timeout: Optional[float] = None):
    """Pop ONE chunk-boundary unit; ``None`` on timeout.

    Returns a wire tuple (the caller acks with ``task_done(weight)``):

    - ``("enc", n, payload)`` — one codec-encoded envelope (weight n);
    - ``("marker", m)`` — an end-of-feed ``None`` or a ``Marker``
      instance, always delivered alone at a chunk boundary (weight 1);
    - ``("rows", [..])`` — contiguous legacy raw rows, gathered up to
      ``max_rows`` and stopping BEFORE any envelope/marker (weight =
      row count).
    """
    deadline = None if timeout is None else time.monotonic() + timeout
    with self._cond:
      while not self._items:
        if not block:
          return None
        remaining = None if deadline is None else deadline - time.monotonic()
        if remaining is not None and remaining <= 0:
          return None
        self._cond.wait(remaining if remaining is not None else 1.0)
      head = self._items[0]
      if isinstance(head, ChunkEnvelope):
        self._items.popleft()
        self._size -= head.n
        self._cond.notify_all()
        return ("enc", head.n, head.payload)
      if head is None or isinstance(head, Marker):
        self._items.popleft()
        self._size -= 1
        self._cond.notify_all()
        return ("marker", head)
      out = []
      while self._items and len(out) < max_rows:
        item = self._items[0]
        if isinstance(item, ChunkEnvelope) or item is None \
            or isinstance(item, Marker):
          break
        out.append(self._items.popleft())
        self._size -= 1
      self._cond.notify_all()
      return ("rows", out)

  def task_done(self, n: int = 1) -> None:
    with self._cond:
      if n > self._unfinished:
        raise ValueError("task_done(%d) called with only %d unfinished" %
                         (n, self._unfinished))
      self._unfinished -= n
      self._cond.notify_all()

  def join(self, timeout: Optional[float] = None) -> bool:
    """Block until all items have been processed; True if drained."""
    deadline = None if timeout is None else time.monotonic() + timeout
    with self._cond:
      while self._unfinished > 0:
        remaining = None if deadline is None else deadline - time.monotonic()
        if remaining is not None and remaining <= 0:
          return False
        self._cond.wait(remaining if remaining is not None else 1.0)
      return True

  def qsize(self) -> int:
    """Pending ROWS (envelopes weigh their row count), not deque entries."""
    with self._cond:
      return self._size

  def empty(self) -> bool:
    return self.qsize() == 0


class QueueFull(Exception):
  """Raised when a put cannot complete; ``admitted`` counts items that were
  already enqueued before the failure (0 for non-blocking puts)."""

  def __init__(self, admitted: int = 0):
    super().__init__("queue full (admitted=%d)" % admitted)
    self.admitted = admitted

  def __reduce__(self):
    # BaseManager proxies pickle server-side exceptions back to the caller;
    # the default Exception reduction replays __init__ with the formatted
    # message string, which "%d" rejects — clients then saw a bare
    # TypeError instead of QueueFull (and lost the admitted count)
    return (QueueFull, (self.admitted,))


class QueueEmpty(Exception):
  pass


# --- manager plumbing -------------------------------------------------------
# Module-level registries that live inside the manager *server* process.
_queues: Dict[str, FeedQueue] = {}
_kv: Dict[str, object] = {}
_kv_lock = threading.Lock()


def _init_server(queue_names, qmax):
  global _queues, _kv
  # runs in the manager SERVER process before its Listener is created, so
  # every accepted connection gets TCP_NODELAY (see _install_nodelay)
  _install_nodelay()
  _queues = {name: FeedQueue(maxsize=qmax) for name in queue_names}
  # the error queue must never block its writer
  if "error" in _queues:
    _queues["error"] = FeedQueue(maxsize=0)
  _kv = {"state": "running"}


def _get_queue(name: str) -> FeedQueue:
  q = _queues.get(name)
  if q is None:
    raise KeyError("no such feed queue: %r (have %r)" % (name, list(_queues)))
  return q


def _kv_get(key: str):
  with _kv_lock:
    return _kv.get(key)


def _kv_set(key: str, value) -> None:
  with _kv_lock:
    _kv[key] = value


def _force_exit():
  """Schedule hard exit of the manager SERVER process (returns first).

  BaseManager has no remote shutdown for non-owners, but fault recovery
  needs one: when a node is SIGKILLed its hub manager survives as an
  orphan (daemonic children die with a clean parent exit, not a SIGKILL),
  still answering with state 'running'. The supervisor/reclaim path calls
  this after draining the dead node's queues so orphaned managers don't
  accumulate across relaunches.
  """
  t = threading.Timer(0.2, os._exit, args=(0,))
  t.daemon = True
  t.start()
  return True


_QUEUE_METHODS = ["put", "put_many", "put_chunk", "get", "get_many",
                  "get_chunk", "task_done", "join", "qsize", "empty"]


class FeedHubManager(BaseManager):
  pass


FeedHubManager.register("get_queue", callable=_get_queue,
                        exposed=_QUEUE_METHODS)
FeedHubManager.register("get", callable=_kv_get)
FeedHubManager.register("set", callable=_kv_set)
FeedHubManager.register("force_exit", callable=_force_exit)


class FeedHub(object):
  """Client/owner handle for a feed hub (parity: TFManager start/connect)."""

  def __init__(self, manager: BaseManager, addr: Tuple[str, int],
               authkey: bytes, owned: bool):
    self._manager = manager
    self.addr = addr
    self.authkey = authkey
    self._owned = owned

  def get_queue(self, name: str):
    return self._manager.get_queue(name)

  def get(self, key: str):
    # BaseManager proxies wrap results; use _getvalue to unbox plain values
    v = self._manager.get(key)
    try:
      return v._getvalue()
    except AttributeError:
      return v

  def set(self, key: str, value) -> None:
    self._manager.set(key, value)

  def force_exit(self) -> None:
    """Hard-stop the hub SERVER process (see ``_force_exit``); usable by
    any connected client, unlike ``shutdown`` which only the owner may
    call. Best-effort: an already-dead server raises, callers catch."""
    self._manager.force_exit()

  def shutdown(self) -> None:
    if self._owned:
      try:
        self._manager.shutdown()
      except Exception:  # noqa: BLE001 - already-dead manager is fine
        pass


# Hubs held alive per process. The owner of a hub must keep referencing the
# manager object or BaseManager's finalizer tears the server down; task
# closures are deserialized with detached globals (cloudpickle), so the
# holder must be THIS module, which closures reference by import. (Parity
# role: the TFSparkNode holder class, reference TFSparkNode.py:111-125.)
_held: Dict[object, "FeedHub"] = {}


def hold(key, hub: "FeedHub") -> None:
  """Keep ``hub`` alive in this process until released."""
  _held[key] = hub


def held(key) -> Optional["FeedHub"]:
  return _held.get(key)


def release(key) -> None:
  hub = _held.pop(key, None)
  if hub is not None:
    hub.shutdown()


def start(authkey: bytes, queue_names: Sequence[str],
          mode: str = "local", qmax: int = 1024,
          host: Optional[str] = None) -> FeedHub:
  """Start a feed hub server process.

  Args:
    authkey: shared secret for manager authentication.
    queue_names: names of queues to create (e.g. ['input','output','error']).
    mode: ``'local'`` binds loopback (workers); ``'remote'`` binds all
      interfaces so the driver can connect (ps/evaluator nodes) —
      parity: TFManager.py:40-65.
    qmax: per-queue bound, the backpressure window (in items/chunks).
    host: advertised host for remote mode (defaults to this host's IP).
  """
  bind_host = "127.0.0.1" if mode == "local" else ""
  _install_nodelay()
  # spawn, not fork: the caller (an engine executor) typically has live
  # queue-feeder threads, and forking a process that holds their locks can
  # deadlock the manager child before it ever listens
  import multiprocessing as mp
  mgr = FeedHubManager(address=(bind_host, 0), authkey=authkey,
                       ctx=mp.get_context("spawn"))
  mgr.start(initializer=_init_server, initargs=(list(queue_names), qmax))
  actual = mgr.address
  if mode == "remote":
    from tensorflowonspark_tpu.utils.hostinfo import get_ip_address
    advertise = host if host else get_ip_address()
    actual = (advertise, actual[1])
  logger.info("feed hub started (%s) at %s", mode, actual)
  return FeedHub(mgr, actual, authkey, owned=True)


def connect(addr: Tuple[str, int], authkey: bytes) -> FeedHub:
  """Connect to an existing feed hub (parity: TFManager.py:68-83)."""
  _install_nodelay()
  mgr = FeedHubManager(address=(addr[0], int(addr[1])), authkey=authkey)
  mgr.connect()
  return FeedHub(mgr, (addr[0], int(addr[1])), authkey, owned=False)
