"""Cluster rendezvous: the discovery/control plane.

Capability parity with the reference's ``reservation.py``
(/root/reference/tensorflowonspark/reservation.py): a driver-side ``Server``
collects one registration per executor, executors ``register`` and then
``await_reservations`` until the whole cluster is present, and a ``STOP`` verb
doubles as the graceful-stop signal for streaming jobs. Differences, by design:

- Wire format is length-prefixed **msgpack**, not pickle (framing parity with
  reservation.py:68-97, minus arbitrary-code-execution on receive).
- Registration is **idempotent by executor_id**: a retried task re-registers
  and replaces its previous entry (reference behavior at TFSparkNode.py:331-340),
  while true duplicates (two different addresses claiming one executor_id) are
  surfaced for the cluster layer's duplicate check (TFCluster.py:357-372).
- The server is also the process rendezvous used to synthesize
  ``jax.distributed.initialize(coordinator_address, num_processes, process_id)``
  — the TPU-native analog of synthesizing ``TF_CONFIG``.

Message verbs (parity with reservation.py:130-146): ``REG``, ``QINFO`` (count
registered), ``QUERY`` (done?), ``LIST`` (full reservation list), ``STOP``;
plus the liveness verbs ``BEAT`` (per-executor heartbeat; a ``bye`` beat
marks clean departure) and ``HEALTH`` (snapshot of the liveness table) —
the reference had no liveness detection at all: a hung executor stalled the
job until the 3-day shutdown watchdog fired (TFCluster.py:136-144).

Observability rides the same wire: ``OBS`` ships bounded metric/span
deltas from executors into the driver's ``Server.obs_sink``
(``obs.collector.ObsSink``; without a sink the verb is acked and
dropped), and ``BEAT``/``OBS`` replies carry ``server_time`` (the
driver's monotonic clock) so clients can estimate their clock offset
NTP-style and per-executor traces land on one timeline
(``obs.spans.ClockOffset``).

Env overrides (parity with reservation.py:25-26,190-206):
``TOS_TPU_SERVER_HOST`` pins the server bind/advertise host;
``TOS_TPU_SERVER_PORT`` pins the port, accepting either ``"9000"`` or a range
``"9000-9100"`` from which the first bindable port is taken.
"""

import logging
import os
import random
import select
import socket
import struct
import threading
import time
from typing import Dict, List, Optional, Tuple

import msgpack

from tensorflowonspark_tpu.obs import spans as obs_spans
from tensorflowonspark_tpu.utils import chaos

logger = logging.getLogger(__name__)

ENV_SERVER_HOST = "TOS_TPU_SERVER_HOST"
ENV_SERVER_PORT = "TOS_TPU_SERVER_PORT"

_HEADER = struct.Struct(">I")

# rendezvous messages are small metadata dicts; anything larger is a protocol
# violation (or garbage bytes hitting the port) — refuse before buffering it
MAX_MESSAGE_BYTES = 4 * 1024 * 1024


class MessageSocket(object):
  """Length-prefixed msgpack messages over a TCP socket.

  Framing parity with the reference's MessageSocket (reservation.py:68-97):
  4-byte big-endian length + payload.
  """

  def receive(self, sock: socket.socket) -> dict:
    header = self._recv_exact(sock, _HEADER.size)
    (length,) = _HEADER.unpack(header)
    if length > MAX_MESSAGE_BYTES:
      raise ConnectionError(
          "oversized rendezvous message (%d bytes); dropping connection" % length)
    payload = self._recv_exact(sock, length)
    return msgpack.unpackb(payload, raw=False)

  def send(self, sock: socket.socket, msg: dict) -> None:
    payload = msgpack.packb(msg, use_bin_type=True)
    if len(payload) > MAX_MESSAGE_BYTES:
      # refuse before the wire: the receiver would drop the connection
      # anyway, and the sender deserves a diagnosable error instead of a
      # reconnect loop against a peer that keeps hanging up
      raise ValueError(
          "refusing to send oversized rendezvous message (%d bytes > %d)"
          % (len(payload), MAX_MESSAGE_BYTES))
    sock.sendall(_HEADER.pack(len(payload)) + payload)

  @staticmethod
  def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
      chunk = sock.recv(n - len(buf))
      if not chunk:
        raise ConnectionError("socket closed while reading message")
      buf += chunk
    return buf


class Reservations(object):
  """Thread-safe store of node reservations, keyed by executor_id.

  Parity: reservation.py:31-65, plus idempotent-replace semantics and
  duplicate tracking for the driver-side sanity check.
  """

  def __init__(self, required: int):
    self.required = required
    self._lock = threading.RLock()
    self._table: Dict[int, dict] = {}
    self.duplicates: List[dict] = []

  def add(self, meta: dict) -> None:
    executor_id = meta["executor_id"]
    with self._lock:
      prev = self._table.get(executor_id)
      if prev is not None:
        same_host = prev.get("host") == meta.get("host")
        same_proc = same_host and prev.get("pid") == meta.get("pid")
        # Legitimate replacements: the same process re-sending (lost reply),
        # or a retried task that RECLAIMED its predecessor's stale hub (it
        # proved the old owner is gone — node.py's live-hub check). A fresh
        # registration colliding with a live entry — same host or not — is a
        # concurrent duplicate (two tasks claiming one executor slot, the
        # reference's TFCluster.py:357-372 failure mode) and must surface.
        if not same_proc and not (same_host and meta.get("reclaimed")):
          self.duplicates.append(meta)
          logger.warning(
              "duplicate reservation for executor %d: %s pid=%s vs %s pid=%s",
              executor_id, prev.get("host"), prev.get("pid"),
              meta.get("host"), meta.get("pid"))
      self._table[executor_id] = meta

  def done(self) -> bool:
    with self._lock:
      return len(self._table) >= self.required

  def get(self) -> List[dict]:
    with self._lock:
      return [self._table[k] for k in sorted(self._table)]

  def remaining(self) -> int:
    with self._lock:
      return max(0, self.required - len(self._table))


class Liveness(object):
  """Per-executor heartbeat table (server side).

  States, derived from the age of the last beat at query time:

  - ``unknown``    — never beat (node hasn't registered/started yet)
  - ``live``       — beat within ``suspect_after`` intervals
  - ``suspect``    — missed (at least) one beat deadline
  - ``dead``       — silent for ``miss_limit`` intervals: the node's
    process is presumed gone (SIGKILL, OOM, preemption) and the
    supervisor may reclaim and relaunch it
  - ``departed``   — sent a clean goodbye (``bye`` beat); never flagged
  - ``restarting`` — a supervisor took ownership pending re-registration

  A registration counts as the first beat, so a node that dies between
  registering and its first heartbeat is still detected — but under the
  longer ``startup_grace`` deadline, not the 2-interval one: between
  registering and starting its own heartbeats a node legitimately blocks
  in cluster assembly (waiting for the full roster), and that gap must
  not read as death. Once a node's OWN first beat arrives, the strict
  missed-beat deadline applies. With ``interval=None`` the table is
  inert: no state ever becomes ``dead``.
  """

  def __init__(self, interval: Optional[float] = None,
               miss_limit: float = 2.0, suspect_after: float = 1.25,
               startup_grace: float = 30.0):
    self.interval = float(interval) if interval else None
    self.miss_limit = float(miss_limit)
    self.suspect_after = float(suspect_after)
    self.startup_grace = float(startup_grace)
    self._lock = threading.Lock()
    self._last: Dict[int, float] = {}
    self._progress: Dict[int, object] = {}
    self._departed: set = set()
    self._restarting: set = set()
    self._confirmed: set = set()   # sent a real beat (not just REG)

  def beat(self, executor_id: int, departing: bool = False,
           progress=None, registration: bool = False) -> None:
    with self._lock:
      self._last[executor_id] = time.monotonic()
      if registration:
        # a (re-)registration starts a new incarnation: it must confirm
        # with its own first beat before the strict deadline applies, so a
        # relaunched node gets the startup grace again
        self._confirmed.discard(executor_id)
      else:
        self._confirmed.add(executor_id)
      if progress is not None:
        self._progress[executor_id] = progress
      if departing:
        self._departed.add(executor_id)
      else:
        self._departed.discard(executor_id)
        self._restarting.discard(executor_id)

  def mark_restarting(self, executor_id: int) -> None:
    """Supervisor takes ownership: suppress dead-detection until the
    relaunched node re-registers (which beats, clearing the flag)."""
    with self._lock:
      self._restarting.add(executor_id)

  def rearm(self, executor_id: int) -> None:
    """Re-arm the startup grace for a fresh incarnation (relaunch/resize).

    ``mark_restarting`` suppresses dead-detection only until the next
    non-registration beat clears the flag — and a STALE beat from the old
    incarnation (a stalled-not-dead process flushing its send queue) does
    exactly that, re-confirming the executor so the strict 2-interval
    deadline applies while the NEW incarnation is still booting. The
    supervisor calls this at relaunch/readmit time: the beat clock resets
    and confirmation is dropped, so the next sweep sees at worst
    ``startup_grace`` headroom instead of instantly re-declaring death
    (which would burn a second restart attempt on one failure).
    """
    with self._lock:
      self._last[executor_id] = time.monotonic()
      self._confirmed.discard(executor_id)
      self._departed.discard(executor_id)

  def state(self, executor_id: int) -> str:
    with self._lock:
      return self._state_locked(executor_id, time.monotonic())

  def _state_locked(self, executor_id: int, now: float) -> str:
    if executor_id in self._departed:
      return "departed"
    if executor_id in self._restarting:
      return "restarting"
    last = self._last.get(executor_id)
    if last is None:
      return "unknown"
    if self.interval is None:
      return "live"
    age = now - last
    if executor_id not in self._confirmed:
      # registered but not yet heartbeating: bring-up blocks in cluster
      # assembly, so only the (long) startup grace applies
      grace = max(self.startup_grace, self.interval * self.miss_limit)
      return "live" if age <= grace else "dead"
    if age <= self.interval * self.suspect_after:
      return "live"
    if age <= self.interval * self.miss_limit:
      return "suspect"
    return "dead"

  def dead(self) -> List[int]:
    """Executor ids currently past the missed-beat deadline."""
    with self._lock:
      now = time.monotonic()
      return sorted(e for e in self._last
                    if self._state_locked(e, now) == "dead")

  def snapshot(self) -> Dict[int, dict]:
    """{executor_id: {"state", "age", "progress"}} for HEALTH queries."""
    with self._lock:
      now = time.monotonic()
      return {e: {"state": self._state_locked(e, now),
                  "age": now - self._last[e],
                  "progress": self._progress.get(e)}
              for e in self._last}


class HeartbeatSender(object):
  """Background thread beating ``BEAT`` every ``interval`` seconds.

  Runs inside the process executing the user main fn, so a SIGKILL, OOM
  kill or preemption stops the beats — exactly the signal the server's
  :class:`Liveness` table (and the driver's ClusterSupervisor) uses to
  declare the node dead. ``start()`` sends the first beat synchronously,
  so even a process killed immediately afterwards was seen alive once.
  On clean ``stop()`` a final ``bye`` beat marks the node departed so
  completed nodes are never flagged dead. Delivery failures are retried
  forever (throttled after ``max_failures`` consecutive misses) — a
  transient control-plane glitch must not silence a healthy node.

  ``set_progress`` attaches an application-level progress value (e.g. the
  training step) to subsequent beats — surfaced via ``HEALTH`` for
  observability and future stall detection.

  Each beat doubles as a TIME exchange: the reply's ``server_time``
  (driver monotonic) plus the beat's local send/receive timestamps feed
  ``clock`` — an :class:`obs.spans.ClockOffset` estimating this
  process's offset to the driver's clock. The obs shipper shares this
  estimator, so span timestamps anchor without extra round-trips.
  """

  def __init__(self, server_addr: Tuple[str, int], executor_id: int,
               interval: float = 5.0, max_failures: int = 5,
               clock: Optional[obs_spans.ClockOffset] = None):
    self.server_addr = (server_addr[0], int(server_addr[1]))
    self.executor_id = executor_id
    self.interval = float(interval)
    self.max_failures = max_failures
    self.clock = clock if clock is not None else obs_spans.ClockOffset()
    self._progress = None
    self._stop = threading.Event()
    self._thread: Optional[threading.Thread] = None
    self._client: Optional["Client"] = None
    self._failures = 0
    # serializes _beat (and the client teardown in stop) against the
    # loop thread: stop() joins with a TIMEOUT, so the bye beat can
    # overlap a wedged in-flight beat and race _client/_failures
    self._beat_lock = threading.Lock()

  def set_progress(self, value) -> None:
    # numpy/jax scalars are not msgpack-serializable; coerce to builtins
    # here so a beat can never die on the progress payload
    if hasattr(value, "item"):
      try:
        value = value.item()
      except Exception:  # noqa: BLE001 - non-scalar array etc.
        value = str(value)
    elif not isinstance(value, (int, float, str, bool, type(None))):
      value = str(value)
    self._progress = value

  def _beat(self, bye: bool = False) -> bool:
    with self._beat_lock:
      return self._beat_locked(bye)

  def _beat_locked(self, bye: bool) -> bool:
    try:
      if self._client is None:
        # short per-request deadline: a beat that cannot be delivered
        # within ~2 intervals is useless anyway (capped so the bye beat at
        # node exit never stalls shutdown against a stopped server)
        self._client = Client(self.server_addr,
                              timeout=max(0.5, min(2.0, 2 * self.interval)))
      msg = {"type": "BEAT", "executor_id": self.executor_id}
      if bye:
        msg["bye"] = True
      if self._progress is not None:
        msg["progress"] = self._progress
      t0 = time.monotonic()
      resp = self._client._request(msg)
      t1 = time.monotonic()
      if "server_time" in resp:
        # NTP-style offset sample piggybacked on the beat round-trip;
        # a chaos/load-delayed beat just yields a high-RTT sample the
        # min-RTT estimator ignores
        self.clock.update(t0, resp["server_time"], t1)
      self._failures = 0
      return True
    except Exception as e:  # noqa: BLE001 - the heartbeat thread must
      # survive ANYTHING (a dead thread reads as node death to the
      # supervisor); serialization surprises count as delivery failures
      self._failures += 1
      if self._failures == 1:
        logger.warning("heartbeat delivery failing for executor %d: %s",
                       self.executor_id, e)
      if self._client is not None:
        self._client.close()
        self._client = None
      return False

  def _run(self) -> None:
    while True:
      # after max_failures consecutive failures, throttle — but NEVER
      # stop, and never beyond 2×interval: the liveness deadline is 2
      # intervals, so a healthy node must get back on the wire within one
      # deadline of the server healing, or the supervisor would relaunch
      # a live node over a transient network blip
      delay = self.interval
      if self._failures >= self.max_failures:
        delay = 2 * self.interval
        if self._failures == self.max_failures:
          logger.warning("heartbeat delivery for executor %d failing "
                         "persistently (%d consecutive); throttling beats",
                         self.executor_id, self._failures)
      if self._stop.wait(delay):
        return
      self._beat()

  def start(self) -> "HeartbeatSender":
    self._beat()                        # guarantee at least one beat
    self._thread = threading.Thread(target=self._run, daemon=True,
                                    name="heartbeat-%d" % self.executor_id)
    self._thread.start()
    return self

  def stop(self) -> None:
    self._stop.set()
    if self._thread is not None:
      self._thread.join(timeout=max(1.0, 2 * self.interval))
    self._beat(bye=True)                # best-effort clean departure
    with self._beat_lock:
      if self._client is not None:
        self._client.close()
        self._client = None


def _parse_port_spec(spec: str) -> List[int]:
  """``"9000"`` → [9000]; ``"9000-9003"`` → [9000..9003]."""
  if "-" in spec:
    lo, hi = spec.split("-", 1)
    return list(range(int(lo), int(hi) + 1))
  return [int(spec)]


class Server(MessageSocket):
  """Driver-side rendezvous server (parity: reservation.py:100-231)."""

  def __init__(self, count: int, heartbeat_interval: Optional[float] = None,
               miss_limit: float = 2.0, startup_grace: float = 30.0):
    assert count > 0
    self.reservations = Reservations(count)
    self.liveness = Liveness(heartbeat_interval, miss_limit=miss_limit,
                             startup_grace=startup_grace)
    self.done = threading.Event()
    # streaming-stop flag (the STOP verb): "stop feeding after the current
    # round" — DISTINCT from ``done`` (serving ended). The server must keep
    # serving after a stop request: nodes still in bring-up poll
    # await_reservations, and heartbeats/goodbyes keep the liveness table
    # truthful until shutdown actually stops the server. Closing the
    # listener on STOP (the old behavior) made any bring-up that raced the
    # stop signal retry against ECONNREFUSED for its whole reservation
    # timeout and fail the node (the train_stream shutdown flake).
    self.stop_requested = threading.Event()
    #: driver-attached ``obs.collector.ObsSink`` consuming OBS deltas;
    #: None (the default) acks-and-drops so the obs plane is never a
    #: prerequisite for the control plane
    self.obs_sink = None
    #: driver-attached ``obs.anomaly.AnomalyDetector`` (or anything with
    #: ``recent_alerts(max_items)``): HEALTH replies then carry the live
    #: alert ring so out-of-process monitors (tools/obs_top.py) see what
    #: the driver's detector loop sees. None = no ``alerts`` field.
    self.alert_source = None
    #: driver-attached ``parallel.groups.SyncPlane`` serving the
    #: SYNC/SYNCQ/GROUP verbs (elastic multi-group training). None (the
    #: default) answers those verbs with an ERROR reply — the control
    #: plane never requires the training plane to exist.
    self.sync_plane = None
    #: driver-attached ``serving.remote.ServingHostPlane`` serving the
    #: SHREG/SHSYNC/SHBYE verbs (cross-host serving: executor-resident
    #: ServingEngines syncing with driver-side RemoteReplica proxies).
    #: None (the default) answers those verbs with an ERROR reply — the
    #: control plane never requires the serving plane to exist.
    self.serving_plane = None
    #: HEALTH obs/alert enrichment failures (counted, never raised)
    self.health_obs_failures = 0
    self._listener: Optional[socket.socket] = None
    self.addr: Optional[Tuple[str, int]] = None
    # round -> set of arrived task ids; sets make re-sent arrivals (client
    # retries after a lost reply) idempotent
    self._barrier_arrivals: Dict[int, set] = {}
    self._barrier_lock = threading.Lock()

  def start(self) -> Tuple[str, int]:
    """Bind (honoring env pinning) and serve on a background thread."""
    host_env = os.environ.get(ENV_SERVER_HOST)
    port_env = os.environ.get(ENV_SERVER_PORT)
    bind_host = host_env if host_env else ""
    ports = _parse_port_spec(port_env) if port_env else [0]

    sock = None
    last_err = None
    for port in ports:
      candidate = None
      try:
        candidate = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        candidate.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        candidate.bind((bind_host, port))
        sock = candidate
        break
      except OSError as e:
        if candidate is not None:
          candidate.close()
        last_err = e
    if sock is None:
      raise OSError("unable to bind rendezvous server on ports {}: {}".format(
          ports, last_err))
    sock.listen(64)

    from tensorflowonspark_tpu.utils.hostinfo import get_ip_address
    advertise_host = host_env if host_env else get_ip_address()
    self.addr = (advertise_host, sock.getsockname()[1])
    self._listener = sock

    t = threading.Thread(target=self._serve, name="rendezvous-server",
                         daemon=True)
    t.start()
    logger.info("rendezvous server listening at %s", self.addr)
    return self.addr

  @staticmethod
  def _drain_frames(buf: bytearray) -> List[dict]:
    """Pop every complete length-prefixed message from ``buf`` (mutates it).

    Partial frames stay buffered — a client that stalls mid-message costs
    nothing; its bytes wait here while other connections are served.
    """
    msgs = []
    while len(buf) >= _HEADER.size:
      (length,) = _HEADER.unpack(bytes(buf[:_HEADER.size]))
      if length > MAX_MESSAGE_BYTES:
        raise ConnectionError(
            "oversized rendezvous message (%d bytes); dropping connection"
            % length)
      if len(buf) < _HEADER.size + length:
        break
      payload = bytes(buf[_HEADER.size:_HEADER.size + length])
      del buf[:_HEADER.size + length]
      msgs.append(msgpack.unpackb(payload, raw=False))
    return msgs

  def _serve(self) -> None:
    # per-connection receive buffers: reads are one recv() per select hit
    # (never a blocking read-to-completion), so one slow/stalled peer cannot
    # serialize the control plane for everyone else
    conns: Dict[socket.socket, bytearray] = {}
    while not self.done.is_set():
      try:
        readable, _, _ = select.select([self._listener] + list(conns),
                                       [], [], 0.25)
      except OSError:
        break
      for s in readable:
        if s is self._listener:
          try:
            client, _ = self._listener.accept()
            # bounds sendall toward a peer that never drains replies
            client.settimeout(30.0)
            conns[client] = bytearray()
          except OSError:
            pass
          continue
        try:
          chunk = s.recv(65536)
          if not chunk:
            raise ConnectionError("peer closed")
          buf = conns[s]
          buf += chunk
          for msg in self._drain_frames(buf):
            self._handle(s, msg)
        except Exception as e:  # noqa: BLE001 - a bad client (garbage
          # bytes, truncated msgpack, malformed REG) must never kill the
          # serve loop; drop only that connection
          if not isinstance(e, (ConnectionError, OSError)):
            logger.warning("dropping rendezvous connection after bad "
                           "message: %s", e)
          del conns[s]
          s.close()
    for s in conns:
      try:
        s.close()
      except OSError:
        pass
    # close the listener the moment serving ends: late clients (heartbeat
    # senders, stop requests) get instant ECONNREFUSED instead of a
    # connection parked forever in a never-accepted backlog
    if self._listener is not None:
      try:
        self._listener.close()
      except OSError:
        pass

  def _handle(self, sock: socket.socket, msg: dict) -> None:
    mtype = msg.get("type")
    if mtype == "REG":
      self.reservations.add(msg["data"])
      # registration counts as the first beat (under the startup grace):
      # a node that dies before its first heartbeat is still detected
      if "executor_id" in msg["data"]:
        self.liveness.beat(msg["data"]["executor_id"], registration=True)
      self.send(sock, {"type": "OK"})
    elif mtype == "BEAT":
      self.liveness.beat(msg["executor_id"], departing=msg.get("bye", False),
                         progress=msg.get("progress"))
      # server_time turns every beat into a TIME exchange (clock-offset
      # estimation for the obs plane — see HeartbeatSender.clock)
      self.send(sock, {"type": "OK", "server_time": time.monotonic()})
    elif mtype == "OBS":
      sink = self.obs_sink
      accepted = False
      if sink is not None:
        # ingest is bounded and swallows its own malformed-payload cases;
        # a sink bug must not kill the serve loop, so the failure is
        # reported to the SENDER (accepted=False) instead of raised here
        try:
          accepted = bool(sink.ingest(msg))
        except Exception as e:  # noqa: BLE001 - reported via accepted flag
          accepted = False
          logger.warning("obs sink rejected a delta: %s", e)
      self.send(sock, {"type": "OK", "accepted": accepted,
                       "server_time": time.monotonic()})
    elif mtype == "HEALTH":
      snap = {str(k): v for k, v in self.liveness.snapshot().items()}
      reply = {"type": "HEALTH", "data": snap,
               "server_time": time.monotonic()}
      # the obs extension of the liveness snapshot: per-executor metric
      # state + the detector's alert ring. Both bounded, both best-effort
      # — a telemetry bug must never break a HEALTH poll.
      sink = self.obs_sink
      if sink is not None:
        try:
          reply["obs"] = sink.top_summary()
        except Exception as e:  # noqa: BLE001 - reply stays liveness-only;
          # counted so a chronically failing summary is diagnosable
          self.health_obs_failures += 1
          logger.warning("obs summary for HEALTH failed: %s", e)
      alerts = self.alert_source
      if alerts is not None:
        try:
          reply["alerts"] = alerts.recent_alerts()
        except Exception as e:  # noqa: BLE001 - reply stays alert-free
          self.health_obs_failures += 1
          logger.warning("alert ring for HEALTH failed: %s", e)
        # the SLO plane's live status (obs.slo via the detector): the
        # burn-rate verdicts out-of-process monitors and the canary
        # phase read — best-effort like the rest of the enrichment
        slo_fn = getattr(alerts, "slo_status", None)
        if slo_fn is not None:
          try:
            slo = slo_fn()
            if slo is not None:
              reply["slo"] = slo
          except Exception as e:  # noqa: BLE001 - reply stays slo-free
            self.health_obs_failures += 1
            logger.warning("slo status for HEALTH failed: %s", e)
        # the deploy plane's live state (serving.deploy gauges via the
        # detector's samples): which version serves, which canaries —
        # same best-effort contract
        deploy_fn = getattr(alerts, "deploy_status", None)
        if deploy_fn is not None:
          try:
            dep = deploy_fn()
            if dep is not None:
              reply["deploy"] = dep
          except Exception as e:  # noqa: BLE001 - reply stays deploy-free
            self.health_obs_failures += 1
            logger.warning("deploy status for HEALTH failed: %s", e)
      plane = self.sync_plane
      if plane is not None:
        # elastic-training topology (groups active/lost, sync latency) —
        # best-effort like every other HEALTH enrichment
        try:
          reply["groups"] = plane.status()
        except Exception as e:  # noqa: BLE001 - reply stays groups-free
          self.health_obs_failures += 1
          logger.warning("sync-plane status for HEALTH failed: %s", e)
      shplane = self.serving_plane
      if shplane is not None:
        # cross-host serving topology (per-host liveness + load): what
        # wire_health_probe keys replica ejection on and obs_top renders
        # as host[...] rows — same best-effort contract
        try:
          reply["hosts"] = shplane.status()
        except Exception as e:  # noqa: BLE001 - reply stays hosts-free
          self.health_obs_failures += 1
          logger.warning("serving-plane status for HEALTH failed: %s", e)
      self.send(sock, reply)
    elif mtype == "QINFO":
      self.send(sock, {"type": "COUNT",
                       "registered": self.reservations.required -
                       self.reservations.remaining(),
                       "required": self.reservations.required})
    elif mtype == "QUERY":
      self.send(sock, {"type": "DONE", "done": self.reservations.done()})
    elif mtype == "LIST":
      self.send(sock, {"type": "RESERVATIONS",
                       "data": self.reservations.get()})
    elif mtype == "BARRIER":
      # reusable barrier rounds for gang-scheduled tasks: each task announces
      # arrival at round r (idempotently, keyed by task id), then polls
      # BQUERY until everyone arrived
      rnd = int(msg["round"])
      with self._barrier_lock:
        self._barrier_arrivals.setdefault(rnd, set()).add(msg["task_id"])
        # prune long-completed rounds so streaming jobs syncing per-batch
        # don't grow the dict unboundedly
        if len(self._barrier_arrivals) > 16:
          for old in sorted(self._barrier_arrivals)[:-8]:
            if old < rnd - 2:
              del self._barrier_arrivals[old]
      self.send(sock, {"type": "OK"})
    elif mtype == "BQUERY":
      rnd = int(msg["round"])
      with self._barrier_lock:
        arrived = len(self._barrier_arrivals.get(rnd, ()))
      self.send(sock, {"type": "BDONE",
                       "done": arrived >= int(msg["required"])})
    elif mtype in ("SYNC", "SYNCQ", "GROUP"):
      # elastic multi-group training: cross-group weight exchange rides the
      # rendezvous plane (ISSUE 16). The verbs delegate to the attached
      # SyncPlane (like obs_sink for OBS) so this module stays free of any
      # jax/training dependency; a plane bug degrades to an ERROR reply the
      # group client surfaces, never a dead serve loop.
      plane = self.sync_plane
      if plane is None:
        self.send(sock, {"type": "ERROR",
                         "error": "no sync plane attached for %s" % mtype})
      else:
        try:
          self.send(sock, plane.handle(msg))
        except Exception as e:  # noqa: BLE001 - reported to the caller
          logger.warning("sync plane failed on %s: %s", mtype, e)
          self.send(sock, {"type": "ERROR", "error": str(e)})
    elif mtype in ("SHREG", "SHSYNC", "SHBYE"):
      # cross-host serving: executor-resident ServingHosts register,
      # sync (events out / commands in) and depart over the rendezvous
      # plane (ISSUE 20). Delegated to the attached ServingHostPlane
      # (the sync_plane pattern) so this module stays free of any
      # serving dependency; a plane bug degrades to an ERROR reply the
      # host surfaces, never a dead serve loop.
      shplane = self.serving_plane
      if shplane is None:
        self.send(sock, {"type": "ERROR",
                         "error": "no serving plane attached for %s" % mtype})
      else:
        try:
          self.send(sock, shplane.handle(msg))
        except Exception as e:  # noqa: BLE001 - reported to the caller
          logger.warning("serving plane failed on %s: %s", mtype, e)
          self.send(sock, {"type": "ERROR", "error": str(e)})
    elif mtype == "STOP":
      logger.info("rendezvous server received STOP")
      self.stop_requested.set()
      self.send(sock, {"type": "OK"})
    else:
      self.send(sock, {"type": "ERROR", "error": "unknown verb: %r" % mtype})

  def await_reservations(self, timeout: int = 600, status: Optional[dict] = None):
    """Block until all nodes registered; raise on timeout or reported error.

    ``status`` is the shared dict the launcher thread writes errors into
    (parity: tf_status error-abort, reservation.py:113-128 +
    TFCluster.py:328-330).
    """
    deadline = time.time() + timeout
    while not self.reservations.done():
      if status and status.get("error"):
        raise RuntimeError("cluster startup aborted: {}".format(status["error"]))
      if time.time() > deadline:
        raise TimeoutError(
            "timed out waiting for {} node(s) to register after {}s".format(
                self.reservations.remaining(), timeout))
      time.sleep(0.1)
    return self.reservations.get()

  def stop(self) -> None:
    self.stop_requested.set()
    self.done.set()
    if self._listener is not None:
      try:
        self._listener.close()
      except OSError:
        pass

  def stopping(self) -> bool:
    """Stop requested (STOP verb) or serving already ended — the flag the
    streaming feed loops check between rounds."""
    return self.stop_requested.is_set() or self.done.is_set()


class Client(MessageSocket):
  """Executor-side rendezvous client (parity: reservation.py:234-301).

  The request/reconnect loop is BOUNDED: exponential backoff with full
  jitter, capped per-sleep at ``backoff_cap`` and in total by ``timeout``
  (a hard deadline per request). A server that stays unreachable yields a
  clear :class:`ConnectionError` naming its address instead of an infinite
  retry loop wedging the node.
  """

  def __init__(self, server_addr: Tuple[str, int], timeout: float = 30.0,
               backoff_base: float = 0.05, backoff_cap: float = 2.0):
    self.server_addr = (server_addr[0], int(server_addr[1]))
    self.timeout = float(timeout)
    self.backoff_base = backoff_base
    self.backoff_cap = backoff_cap
    try:
      self._sock: Optional[socket.socket] = self._connect()
    except OSError:
      # retried (with backoff, against the deadline) at the first request
      self._sock = None

  def _connect(self) -> socket.socket:
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    try:
      # a per-operation socket deadline: a server that stopped serving (or
      # a half-open connection) must surface as a retryable timeout, never
      # as an unbounded recv() — request/reply exchanges here are all small
      # and fast, so a generous cap costs nothing
      s.settimeout(max(1.0, min(self.timeout, 10.0)))
      s.connect(self.server_addr)
    except BaseException:
      # the reconnect loop retries for the whole deadline budget; each
      # failed attempt must release its socket or the retries pile up fds
      # in a long-lived executor process (TOS006)
      s.close()
      raise
    return s

  def _request(self, msg: dict) -> dict:
    if chaos.enabled():
      drop, delay = chaos.message_fault(msg.get("type"))
      if delay:
        time.sleep(delay)
      if drop:
        # lost on the (simulated) wire: the server never sees it; callers
        # polling for state simply observe nothing changed
        return {"type": "DROPPED", "dropped": True, "done": False}
    deadline = time.monotonic() + self.timeout
    attempt = 0
    last = None
    while True:
      try:
        if self._sock is None:
          self._sock = self._connect()
        self.send(self._sock, msg)
        return self.receive(self._sock)
      except (ConnectionError, OSError) as e:
        last = e
        if attempt == 0:
          logger.warning("rendezvous request to %s failed (%s); retrying "
                         "with backoff", self.server_addr, e)
        if self._sock is not None:
          try:
            self._sock.close()
          except OSError:
            pass
          self._sock = None
        now = time.monotonic()
        if now >= deadline:
          raise ConnectionError(
              "unable to reach rendezvous server at %s:%d after %d "
              "attempt(s) over %.1fs: %s"
              % (self.server_addr[0], self.server_addr[1], attempt + 1,
                 self.timeout, last))
        # exponential backoff with full jitter, capped per-sleep and
        # clipped to the remaining deadline budget
        delay = min(self.backoff_cap, self.backoff_base * (2 ** attempt))
        delay *= 0.5 + random.random()
        time.sleep(max(0.0, min(delay, deadline - now)))
        attempt += 1

  def register(self, reservation: dict) -> None:
    self._request({"type": "REG", "data": reservation})

  def get_reservations(self) -> List[dict]:
    return self._request({"type": "LIST"})["data"]

  def await_reservations(self, timeout: int = 600) -> List[dict]:
    """Poll until the cluster is fully registered (1s poll cadence,
    parity: reservation.py:290-296)."""
    deadline = time.time() + timeout
    while True:
      if self._request({"type": "QUERY"})["done"]:
        return self.get_reservations()
      if time.time() > deadline:
        raise TimeoutError("timed out awaiting full cluster registration")
      time.sleep(1)

  def barrier_wait(self, round_num: int, required: int,
                   timeout: float = 600, task_id=None) -> None:
    """Announce arrival at a barrier round and wait for the full gang.

    ``task_id`` identifies this participant so retried announcements (after
    a lost reply) stay idempotent on the server.
    """
    if task_id is None:
      import os
      task_id = "%s:%d" % (socket.gethostname(), os.getpid())
    self._request({"type": "BARRIER", "round": round_num,
                   "task_id": task_id})
    deadline = time.time() + timeout
    while True:
      resp = self._request({"type": "BQUERY", "round": round_num,
                            "required": required})
      if resp["done"]:
        return
      if time.time() > deadline:
        raise TimeoutError("barrier round %d timed out" % round_num)
      time.sleep(0.05)

  def request_stop(self) -> None:
    try:
      self._request({"type": "STOP"})
    except ConnectionError:
      logger.warning("rendezvous server already gone on STOP")

  def close(self) -> None:
    if self._sock is None:
      return
    try:
      self._sock.close()
    except OSError:
      pass
    self._sock = None
