"""Cluster rendezvous: the discovery/control plane.

Capability parity with the reference's ``reservation.py``
(/root/reference/tensorflowonspark/reservation.py): a driver-side ``Server``
collects one registration per executor, executors ``register`` and then
``await_reservations`` until the whole cluster is present, and a ``STOP`` verb
doubles as the graceful-stop signal for streaming jobs. Differences, by design:

- Wire format is length-prefixed **msgpack**, not pickle (framing parity with
  reservation.py:68-97, minus arbitrary-code-execution on receive).
- Registration is **idempotent by executor_id**: a retried task re-registers
  and replaces its previous entry (reference behavior at TFSparkNode.py:331-340),
  while true duplicates (two different addresses claiming one executor_id) are
  surfaced for the cluster layer's duplicate check (TFCluster.py:357-372).
- The server is also the process rendezvous used to synthesize
  ``jax.distributed.initialize(coordinator_address, num_processes, process_id)``
  — the TPU-native analog of synthesizing ``TF_CONFIG``.

Message verbs (parity with reservation.py:130-146): ``REG``, ``QINFO`` (count
registered), ``QUERY`` (done?), ``LIST`` (full reservation list), ``STOP``.

Env overrides (parity with reservation.py:25-26,190-206):
``TOS_TPU_SERVER_HOST`` pins the server bind/advertise host;
``TOS_TPU_SERVER_PORT`` pins the port, accepting either ``"9000"`` or a range
``"9000-9100"`` from which the first bindable port is taken.
"""

import logging
import os
import select
import socket
import struct
import threading
import time
from typing import Dict, List, Optional, Tuple

import msgpack

logger = logging.getLogger(__name__)

ENV_SERVER_HOST = "TOS_TPU_SERVER_HOST"
ENV_SERVER_PORT = "TOS_TPU_SERVER_PORT"

_HEADER = struct.Struct(">I")

# rendezvous messages are small metadata dicts; anything larger is a protocol
# violation (or garbage bytes hitting the port) — refuse before buffering it
MAX_MESSAGE_BYTES = 4 * 1024 * 1024


class MessageSocket(object):
  """Length-prefixed msgpack messages over a TCP socket.

  Framing parity with the reference's MessageSocket (reservation.py:68-97):
  4-byte big-endian length + payload.
  """

  def receive(self, sock: socket.socket) -> dict:
    header = self._recv_exact(sock, _HEADER.size)
    (length,) = _HEADER.unpack(header)
    if length > MAX_MESSAGE_BYTES:
      raise ConnectionError(
          "oversized rendezvous message (%d bytes); dropping connection" % length)
    payload = self._recv_exact(sock, length)
    return msgpack.unpackb(payload, raw=False)

  def send(self, sock: socket.socket, msg: dict) -> None:
    payload = msgpack.packb(msg, use_bin_type=True)
    sock.sendall(_HEADER.pack(len(payload)) + payload)

  @staticmethod
  def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
      chunk = sock.recv(n - len(buf))
      if not chunk:
        raise ConnectionError("socket closed while reading message")
      buf += chunk
    return buf


class Reservations(object):
  """Thread-safe store of node reservations, keyed by executor_id.

  Parity: reservation.py:31-65, plus idempotent-replace semantics and
  duplicate tracking for the driver-side sanity check.
  """

  def __init__(self, required: int):
    self.required = required
    self._lock = threading.RLock()
    self._table: Dict[int, dict] = {}
    self.duplicates: List[dict] = []

  def add(self, meta: dict) -> None:
    executor_id = meta["executor_id"]
    with self._lock:
      prev = self._table.get(executor_id)
      if prev is not None:
        same_host = prev.get("host") == meta.get("host")
        same_proc = same_host and prev.get("pid") == meta.get("pid")
        # Legitimate replacements: the same process re-sending (lost reply),
        # or a retried task that RECLAIMED its predecessor's stale hub (it
        # proved the old owner is gone — node.py's live-hub check). A fresh
        # registration colliding with a live entry — same host or not — is a
        # concurrent duplicate (two tasks claiming one executor slot, the
        # reference's TFCluster.py:357-372 failure mode) and must surface.
        if not same_proc and not (same_host and meta.get("reclaimed")):
          self.duplicates.append(meta)
          logger.warning(
              "duplicate reservation for executor %d: %s pid=%s vs %s pid=%s",
              executor_id, prev.get("host"), prev.get("pid"),
              meta.get("host"), meta.get("pid"))
      self._table[executor_id] = meta

  def done(self) -> bool:
    with self._lock:
      return len(self._table) >= self.required

  def get(self) -> List[dict]:
    with self._lock:
      return [self._table[k] for k in sorted(self._table)]

  def remaining(self) -> int:
    with self._lock:
      return max(0, self.required - len(self._table))


def _parse_port_spec(spec: str) -> List[int]:
  """``"9000"`` → [9000]; ``"9000-9003"`` → [9000..9003]."""
  if "-" in spec:
    lo, hi = spec.split("-", 1)
    return list(range(int(lo), int(hi) + 1))
  return [int(spec)]


class Server(MessageSocket):
  """Driver-side rendezvous server (parity: reservation.py:100-231)."""

  def __init__(self, count: int):
    assert count > 0
    self.reservations = Reservations(count)
    self.done = threading.Event()
    self._listener: Optional[socket.socket] = None
    self.addr: Optional[Tuple[str, int]] = None
    # round -> set of arrived task ids; sets make re-sent arrivals (client
    # retries after a lost reply) idempotent
    self._barrier_arrivals: Dict[int, set] = {}
    self._barrier_lock = threading.Lock()

  def start(self) -> Tuple[str, int]:
    """Bind (honoring env pinning) and serve on a background thread."""
    host_env = os.environ.get(ENV_SERVER_HOST)
    port_env = os.environ.get(ENV_SERVER_PORT)
    bind_host = host_env if host_env else ""
    ports = _parse_port_spec(port_env) if port_env else [0]

    sock = None
    last_err = None
    for port in ports:
      candidate = None
      try:
        candidate = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        candidate.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        candidate.bind((bind_host, port))
        sock = candidate
        break
      except OSError as e:
        if candidate is not None:
          candidate.close()
        last_err = e
    if sock is None:
      raise OSError("unable to bind rendezvous server on ports {}: {}".format(
          ports, last_err))
    sock.listen(64)

    from tensorflowonspark_tpu.utils.hostinfo import get_ip_address
    advertise_host = host_env if host_env else get_ip_address()
    self.addr = (advertise_host, sock.getsockname()[1])
    self._listener = sock

    t = threading.Thread(target=self._serve, name="rendezvous-server",
                         daemon=True)
    t.start()
    logger.info("rendezvous server listening at %s", self.addr)
    return self.addr

  @staticmethod
  def _drain_frames(buf: bytearray) -> List[dict]:
    """Pop every complete length-prefixed message from ``buf`` (mutates it).

    Partial frames stay buffered — a client that stalls mid-message costs
    nothing; its bytes wait here while other connections are served.
    """
    msgs = []
    while len(buf) >= _HEADER.size:
      (length,) = _HEADER.unpack(bytes(buf[:_HEADER.size]))
      if length > MAX_MESSAGE_BYTES:
        raise ConnectionError(
            "oversized rendezvous message (%d bytes); dropping connection"
            % length)
      if len(buf) < _HEADER.size + length:
        break
      payload = bytes(buf[_HEADER.size:_HEADER.size + length])
      del buf[:_HEADER.size + length]
      msgs.append(msgpack.unpackb(payload, raw=False))
    return msgs

  def _serve(self) -> None:
    # per-connection receive buffers: reads are one recv() per select hit
    # (never a blocking read-to-completion), so one slow/stalled peer cannot
    # serialize the control plane for everyone else
    conns: Dict[socket.socket, bytearray] = {}
    while not self.done.is_set():
      try:
        readable, _, _ = select.select([self._listener] + list(conns),
                                       [], [], 0.25)
      except OSError:
        break
      for s in readable:
        if s is self._listener:
          try:
            client, _ = self._listener.accept()
            # bounds sendall toward a peer that never drains replies
            client.settimeout(30.0)
            conns[client] = bytearray()
          except OSError:
            pass
          continue
        try:
          chunk = s.recv(65536)
          if not chunk:
            raise ConnectionError("peer closed")
          buf = conns[s]
          buf += chunk
          for msg in self._drain_frames(buf):
            self._handle(s, msg)
        except Exception as e:  # noqa: BLE001 - a bad client (garbage
          # bytes, truncated msgpack, malformed REG) must never kill the
          # serve loop; drop only that connection
          if not isinstance(e, (ConnectionError, OSError)):
            logger.warning("dropping rendezvous connection after bad "
                           "message: %s", e)
          del conns[s]
          s.close()
    for s in conns:
      try:
        s.close()
      except OSError:
        pass

  def _handle(self, sock: socket.socket, msg: dict) -> None:
    mtype = msg.get("type")
    if mtype == "REG":
      self.reservations.add(msg["data"])
      self.send(sock, {"type": "OK"})
    elif mtype == "QINFO":
      self.send(sock, {"type": "COUNT",
                       "registered": self.reservations.required -
                       self.reservations.remaining(),
                       "required": self.reservations.required})
    elif mtype == "QUERY":
      self.send(sock, {"type": "DONE", "done": self.reservations.done()})
    elif mtype == "LIST":
      self.send(sock, {"type": "RESERVATIONS",
                       "data": self.reservations.get()})
    elif mtype == "BARRIER":
      # reusable barrier rounds for gang-scheduled tasks: each task announces
      # arrival at round r (idempotently, keyed by task id), then polls
      # BQUERY until everyone arrived
      rnd = int(msg["round"])
      with self._barrier_lock:
        self._barrier_arrivals.setdefault(rnd, set()).add(msg["task_id"])
        # prune long-completed rounds so streaming jobs syncing per-batch
        # don't grow the dict unboundedly
        if len(self._barrier_arrivals) > 16:
          for old in sorted(self._barrier_arrivals)[:-8]:
            if old < rnd - 2:
              del self._barrier_arrivals[old]
      self.send(sock, {"type": "OK"})
    elif mtype == "BQUERY":
      rnd = int(msg["round"])
      with self._barrier_lock:
        arrived = len(self._barrier_arrivals.get(rnd, ()))
      self.send(sock, {"type": "BDONE",
                       "done": arrived >= int(msg["required"])})
    elif mtype == "STOP":
      logger.info("rendezvous server received STOP")
      self.done.set()
      self.send(sock, {"type": "OK"})
    else:
      self.send(sock, {"type": "ERROR", "error": "unknown verb: %r" % mtype})

  def await_reservations(self, timeout: int = 600, status: Optional[dict] = None):
    """Block until all nodes registered; raise on timeout or reported error.

    ``status`` is the shared dict the launcher thread writes errors into
    (parity: tf_status error-abort, reservation.py:113-128 +
    TFCluster.py:328-330).
    """
    deadline = time.time() + timeout
    while not self.reservations.done():
      if status and status.get("error"):
        raise RuntimeError("cluster startup aborted: {}".format(status["error"]))
      if time.time() > deadline:
        raise TimeoutError(
            "timed out waiting for {} node(s) to register after {}s".format(
                self.reservations.remaining(), timeout))
      time.sleep(0.1)
    return self.reservations.get()

  def stop(self) -> None:
    self.done.set()
    if self._listener is not None:
      try:
        self._listener.close()
      except OSError:
        pass


class Client(MessageSocket):
  """Executor-side rendezvous client (parity: reservation.py:234-301)."""

  RETRIES = 3

  def __init__(self, server_addr: Tuple[str, int]):
    self.server_addr = (server_addr[0], int(server_addr[1]))
    self._sock = self._connect()

  def _connect(self) -> socket.socket:
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    s.connect(self.server_addr)
    return s

  def _request(self, msg: dict) -> dict:
    last = None
    for attempt in range(self.RETRIES):
      try:
        self.send(self._sock, msg)
        return self.receive(self._sock)
      except (ConnectionError, OSError) as e:
        last = e
        logger.warning("rendezvous send failed (attempt %d): %s", attempt + 1, e)
        try:
          self._sock.close()
        except OSError:
          pass
        time.sleep(0.5 * (attempt + 1))
        try:
          self._sock = self._connect()
        except OSError as e2:
          last = e2
    raise ConnectionError("unable to reach rendezvous server at {}: {}".format(
        self.server_addr, last))

  def register(self, reservation: dict) -> None:
    self._request({"type": "REG", "data": reservation})

  def get_reservations(self) -> List[dict]:
    return self._request({"type": "LIST"})["data"]

  def await_reservations(self, timeout: int = 600) -> List[dict]:
    """Poll until the cluster is fully registered (1s poll cadence,
    parity: reservation.py:290-296)."""
    deadline = time.time() + timeout
    while True:
      if self._request({"type": "QUERY"})["done"]:
        return self.get_reservations()
      if time.time() > deadline:
        raise TimeoutError("timed out awaiting full cluster registration")
      time.sleep(1)

  def barrier_wait(self, round_num: int, required: int,
                   timeout: float = 600, task_id=None) -> None:
    """Announce arrival at a barrier round and wait for the full gang.

    ``task_id`` identifies this participant so retried announcements (after
    a lost reply) stay idempotent on the server.
    """
    if task_id is None:
      import os
      task_id = "%s:%d" % (socket.gethostname(), os.getpid())
    self._request({"type": "BARRIER", "round": round_num,
                   "task_id": task_id})
    deadline = time.time() + timeout
    while True:
      resp = self._request({"type": "BQUERY", "round": round_num,
                            "required": required})
      if resp["done"]:
        return
      if time.time() > deadline:
        raise TimeoutError("barrier round %d timed out" % round_num)
      time.sleep(0.05)

  def request_stop(self) -> None:
    try:
      self._request({"type": "STOP"})
    except ConnectionError:
      logger.warning("rendezvous server already gone on STOP")

  def close(self) -> None:
    try:
      self._sock.close()
    except OSError:
      pass
