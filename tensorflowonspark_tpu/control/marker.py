"""Feed-queue sentinels (parity: /root/reference/tensorflowonspark/marker.py).

``None`` on a feed queue means end-of-feed by convention; ``EndPartition``
separates partitions so inference can emit exactly one result batch per input
partition.
"""


class Marker(object):
  """Base class for feed-queue control markers."""


class EndPartition(Marker):
  """Marks the end of one data partition within the feed stream."""

  def __eq__(self, other):
    return isinstance(other, EndPartition)

  def __hash__(self):
    return hash(EndPartition)
