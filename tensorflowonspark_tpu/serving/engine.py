"""ServingEngine: slot-based in-flight (continuous) batching.

The throughput lever the fixed-batch serving loop leaves on the table:
``greedy_generate_kv`` decodes every request in a batch for the full
``num_steps`` and a new batch cannot start until the slowest sequence
finishes — on mixed-length traffic most slot-steps are wasted padding.
This engine keeps ONE persistent jitted step function advancing a
fixed-capacity slot slab (``serving.slots.SlotDecoder``); the moment a
slot's request hits EOS or its token budget the slot is freed, the next
queued request is prefilled directly into that cache region, and the
step keeps running — the device stays saturated at request granularity
(the same overlap-and-saturate principle as the PR 4 feed plane, and
the batching story of arXiv:2011.03641).

Greedy decode only, and per-request outputs are BIT-IDENTICAL to the
single-request ``greedy_generate_kv`` decode of the same prompt: rows
are independent in every einsum, per-slot cursors mask each lane to its
own length, and prefill chunking changes which einsum computes a value
but not the value (pinned by tests/test_serving.py).

The engine is SELF-HEALING (docs/ROBUSTNESS.md):

* admission control — the queue is bounded by request count AND
  queued-token mass; ``submit`` raises a structured
  :class:`~tensorflowonspark_tpu.serving.scheduler.ServingOverloaded`
  with a retry-after hint derived from the live tokens/s rate instead
  of growing without bound;
* deadlines & cancellation — a per-request ``deadline``/``ttl`` is
  checked at admission (an expired queued request fails with
  ``DeadlineExceeded`` without ever taking a slot) and at every horizon
  boundary; ``cancel(rid)`` frees an in-flight slot exactly like EOS;
* crash-replay recovery — an exception in the loop thread no longer
  kills the engine: the slab is rebuilt and every in-flight request is
  transparently replayed from its prompt (greedy ⇒ bit-identical;
  stream consumers see no duplicates because the already-emitted prefix
  is suppressed), with capped consecutive restarts + backoff and poison
  detection (a request blamed across N consecutive crashes is failed,
  not replayed);
* graceful drain — ``drain(timeout)`` stops admission, finishes every
  accepted request, then stops, so rolling restarts shed zero work.

Usage::

    eng = ServingEngine(params, cfg, num_slots=8, eos_id=2).start()
    rid = eng.submit(prompt_ids, max_new_tokens=128, ttl=30.0)
    tokens = eng.result(rid, timeout=60)        # prompt + generated
    # or: for tok in eng.stream(rid): ...
    eng.drain(timeout=30)                       # or eng.stop()

The engine is FAST (this PR's decode-speed stack, each stage gated on
``serve_bench`` parity and composable with the self-healing surface):

* paged KV slab — ``page_size > 0`` swaps the per-slot ``max_seq_len``
  HBM reservation for a page pool + per-slot page tables
  (``serving.slots``): a request holds only the pages its
  prompt+budget token mass needs, so ``num_slots`` can exceed what
  contiguous reservation would fit; a request that cannot get pages
  waits in the queue (completions free pages) instead of failing;
* shared-prefix cache — ``prefix_pages > 0`` (requires paging) keeps a
  driver-side radix trie over prompt prefixes at page granularity
  (``serving.scheduler.PrefixCache``): requests sharing a prefix
  prefill it ONCE and fork read-only page references (the divergence
  page stays private — copy-on-write at page granularity), turning the
  system-prompt-heavy workload's O(requests × prefix) prefill into
  O(1) per distinct prefix; eviction is ref-counted LRU;
* self-speculative decode — ``spec_depth > 0`` drafts with a
  ``spec_layers``-deep shallow-exit prefix of the SAME model and
  verifies with one full-model step per round (``SlotDecoder
  .step_spec``): greedy verification keeps exactly the tokens
  ``greedy_generate_kv`` would emit, so bit-parity (and crash replay,
  which leans on it) survives the speedup.

All waits are timeout-bounded (TOS001) and the loop thread is a daemon
(TOS007). Config knobs ride registered ``TOS_*`` env vars (TOS008):
``TOS_SERVE_SLOTS``, ``TOS_SERVE_BUCKETS``, ``TOS_SERVE_POLL``,
``TOS_SERVE_HORIZON``, ``TOS_SERVE_MAX_QUEUE``,
``TOS_SERVE_MAX_QUEUED_TOKENS``, ``TOS_SERVE_TTL``,
``TOS_SERVE_MAX_RESTARTS``, ``TOS_SERVE_RESTART_BACKOFF``,
``TOS_SERVE_POISON_CRASHES``, ``TOS_SERVE_PAGE_SIZE``,
``TOS_SERVE_NUM_PAGES``, ``TOS_SERVE_PREFIX_PAGES``,
``TOS_SERVE_SPEC_DEPTH``, ``TOS_SERVE_SPEC_LAYERS``.
"""

import contextlib
import logging
import os
import queue as std_queue
import threading
import time
from typing import List, Optional, Sequence

import numpy as np

from tensorflowonspark_tpu.obs import metrics as obs_metrics
from tensorflowonspark_tpu.obs import spans as obs_spans
from tensorflowonspark_tpu.serving import scheduler as sched
from tensorflowonspark_tpu.serving import slots as slots_lib

logger = logging.getLogger(__name__)

#: default slot capacity when the caller passes ``num_slots=None``
ENV_SERVE_SLOTS = "TOS_SERVE_SLOTS"
#: idle-loop poll interval (seconds) — the bound on every engine wait
ENV_SERVE_POLL = "TOS_SERVE_POLL"
#: decode horizon: how many tokens one fused step dispatch advances.
#: 1 = per-token dispatch (lowest admission latency); larger values
#: amortize dispatch + host-sync overhead over the horizon at the cost
#: of at most horizon-1 frozen slot-steps per finished request and
#: admission every horizon tokens (see SlotDecoder.step_many)
ENV_SERVE_HORIZON = "TOS_SERVE_HORIZON"
#: admission bound on queued request count (0 disables)
ENV_SERVE_MAX_QUEUE = "TOS_SERVE_MAX_QUEUE"
#: admission bound on queued token mass: sum of prompt+budget over the
#: backlog (0 disables; an oversized request still admits when the
#: queue is empty)
ENV_SERVE_MAX_QUEUED_TOKENS = "TOS_SERVE_MAX_QUEUED_TOKENS"
#: default per-request TTL in seconds applied when submit passes neither
#: ``deadline`` nor ``ttl`` (0 = no default deadline)
ENV_SERVE_TTL = "TOS_SERVE_TTL"
#: consecutive loop crashes (no successful decode between) tolerated
#: before the engine dies terminally
ENV_SERVE_MAX_RESTARTS = "TOS_SERVE_MAX_RESTARTS"
#: base restart backoff in seconds (doubles per consecutive crash,
#: capped at 2s; interruptible by stop())
ENV_SERVE_RESTART_BACKOFF = "TOS_SERVE_RESTART_BACKOFF"
#: a request blamed for this many consecutive crashes is failed
#: (PoisonedRequest), not replayed — the crash-loop breaker
ENV_SERVE_POISON_CRASHES = "TOS_SERVE_POISON_CRASHES"
#: paged KV slab: tokens per page (0 = contiguous per-slot reservation)
ENV_SERVE_PAGE_SIZE = "TOS_SERVE_PAGE_SIZE"
#: paged KV slab: pool size in pages, incl. the reserved trash page 0
#: (0 = auto: num_slots × ceil(max_seq_len/page_size) + 1, the
#: contiguous worst case — set lower to spend less HBM than
#: num_slots × max_seq_len)
ENV_SERVE_NUM_PAGES = "TOS_SERVE_NUM_PAGES"
#: shared-prefix cache budget in pages (0 = off; requires paging) —
#: ref-counted LRU eviction keeps the cache at/under this
ENV_SERVE_PREFIX_PAGES = "TOS_SERVE_PREFIX_PAGES"
#: self-speculative decode: draft-window depth per round (0 = off)
ENV_SERVE_SPEC_DEPTH = "TOS_SERVE_SPEC_DEPTH"
#: self-speculative decode: shallow-exit draft depth in layers
#: (0 = auto: num_layers // 2)
ENV_SERVE_SPEC_LAYERS = "TOS_SERVE_SPEC_LAYERS"
#: request-trace detail spans (``serve.decode.slot`` per lane per
#: dispatch + ``serve.prefill.chunk`` per bucket chunk): ``0`` keeps
#: request tracing on (queue/prefill/stream spans, trace ids, ledger)
#: but drops the high-volume detail records — the knob to reach for if
#: the span buffer's drop counter moves on a large deployment
ENV_OBS_TRACE_DETAIL = "TOS_OBS_TRACE_DETAIL"

_DEFAULT_SLOTS = 4
_DEFAULT_POLL = 0.05
_DEFAULT_HORIZON = 4
_DEFAULT_MAX_QUEUE = 1024
_DEFAULT_MAX_QUEUED_TOKENS = 1 << 20
_DEFAULT_MAX_RESTARTS = 5
_DEFAULT_RESTART_BACKOFF = 0.05
_DEFAULT_POISON_CRASHES = 2
#: restart backoff never exceeds this many seconds
_BACKOFF_CAP = 2.0
#: retry-after hint while the tokens/s EMA is still cold (no decode has
#: completed yet): a bounded default, never "retry immediately" — a cold
#: engine's first decode pass is at least a prefill + dispatch away
_COLD_RETRY_AFTER = 0.25
#: restart_log keeps this many most-recent recovery records
_RESTART_LOG_CAP = 64


def _env_int(name: str, default: int) -> int:
  return int(os.environ.get(name, str(default)))


def _env_float(name: str, default: float) -> float:
  return float(os.environ.get(name, str(default)))


class ServingEngine(object):
  """Continuous-batching serving runtime over one model + param set."""

  def __init__(self, params, cfg, num_slots: Optional[int] = None,
               eos_id: Optional[int] = None, pad_id: int = 0,
               max_new_tokens: int = 64, buckets=None, mesh=None,
               poll_interval: Optional[float] = None,
               horizon: Optional[int] = None,
               max_queue: Optional[int] = None,
               max_queued_tokens: Optional[int] = None,
               default_ttl: Optional[float] = None,
               max_restarts: Optional[int] = None,
               restart_backoff: Optional[float] = None,
               poison_crashes: Optional[int] = None,
               page_size: Optional[int] = None,
               num_pages: Optional[int] = None,
               prefix_pages: Optional[int] = None,
               spec_depth: Optional[int] = None,
               spec_layers: Optional[int] = None):
    if eos_id is not None and int(eos_id) == int(pad_id):
      raise ValueError("eos_id and pad_id must differ (both %d)"
                       % int(pad_id))
    if num_slots is None:
      num_slots = _env_int(ENV_SERVE_SLOTS, _DEFAULT_SLOTS)
    if horizon is None:
      horizon = _env_int(ENV_SERVE_HORIZON, _DEFAULT_HORIZON)
    if horizon < 1:
      raise ValueError("horizon must be >= 1, got %d" % horizon)
    self.params = params
    self.cfg = cfg
    self.eos_id = None if eos_id is None else int(eos_id)
    self.pad_id = int(pad_id)
    self.horizon = horizon
    self.default_max_new_tokens = int(max_new_tokens)
    # explicit argument beats the env knob (the num_slots/horizon rule)
    self.buckets = tuple(buckets) if buckets is not None \
        else sched.buckets_from_env(slots_lib.DEFAULT_BUCKETS)
    self.max_queue = int(max_queue if max_queue is not None
                         else _env_int(ENV_SERVE_MAX_QUEUE,
                                       _DEFAULT_MAX_QUEUE))
    self.max_queued_tokens = int(
        max_queued_tokens if max_queued_tokens is not None
        else _env_int(ENV_SERVE_MAX_QUEUED_TOKENS,
                      _DEFAULT_MAX_QUEUED_TOKENS))
    ttl = default_ttl if default_ttl is not None \
        else _env_float(ENV_SERVE_TTL, 0.0)
    self.default_ttl = float(ttl) if ttl and ttl > 0 else None
    self.max_restarts = int(max_restarts if max_restarts is not None
                            else _env_int(ENV_SERVE_MAX_RESTARTS,
                                          _DEFAULT_MAX_RESTARTS))
    self.restart_backoff = float(
        restart_backoff if restart_backoff is not None
        else _env_float(ENV_SERVE_RESTART_BACKOFF,
                        _DEFAULT_RESTART_BACKOFF))
    self.poison_crashes = max(1, int(
        poison_crashes if poison_crashes is not None
        else _env_int(ENV_SERVE_POISON_CRASHES, _DEFAULT_POISON_CRASHES)))
    # explicit arguments beat the env knobs (the num_slots rule)
    self.page_size = int(page_size if page_size is not None
                         else _env_int(ENV_SERVE_PAGE_SIZE, 0))
    self.num_pages = int(num_pages if num_pages is not None
                         else _env_int(ENV_SERVE_NUM_PAGES, 0))
    self.prefix_pages = int(prefix_pages if prefix_pages is not None
                            else _env_int(ENV_SERVE_PREFIX_PAGES, 0))
    self.spec_depth = int(spec_depth if spec_depth is not None
                          else _env_int(ENV_SERVE_SPEC_DEPTH, 0))
    spec_layers = int(spec_layers if spec_layers is not None
                      else _env_int(ENV_SERVE_SPEC_LAYERS, 0))
    if self.prefix_pages > 0 and self.page_size <= 0:
      raise ValueError(
          "the shared-prefix cache shares POOL PAGES — "
          "TOS_SERVE_PREFIX_PAGES > 0 requires TOS_SERVE_PAGE_SIZE > 0")
    self.decoder = slots_lib.SlotDecoder(
        cfg, num_slots, pad_id=pad_id, eos_id=self.eos_id, mesh=mesh,
        page_size=self.page_size, num_pages=self.num_pages,
        spec_depth=self.spec_depth, spec_layers=spec_layers)
    # spec rounds per dispatch: each round emits 1..spec_depth tokens,
    # so this keeps the best-case tokens-per-dispatch near the horizon
    self._spec_rounds = max(1, -(-horizon // max(1, self.spec_depth)))
    self._poll = float(poll_interval if poll_interval is not None
                       else os.environ.get(ENV_SERVE_POLL, _DEFAULT_POLL))
    self._queue = sched.RequestQueue()
    self._lock = threading.Lock()
    self._stats_lock = threading.Lock()
    self._requests = {}                    # rid -> Request (in flight or done)
    self._slots: List[Optional[sched.Request]] = [None] * num_slots
    self._slabs = None                     # built lazily on start()
    # paged-KV host state — (re)built with the slab (_ensure_slabs): the
    # allocator/trie describe DEVICE pages, so a rebuilt slab resets them
    self._pool: Optional[sched.PagePool] = None
    self._prefix: Optional[sched.PrefixCache] = None
    self._req_pages = {}                   # rid -> [page ids] (one ref each)
    self._last = np.full((num_slots,), self.pad_id, np.int32)
    self._stop_evt = threading.Event()
    self._thread: Optional[threading.Thread] = None
    self._loop_error: Optional[BaseException] = None
    self._draining = False
    self._admitting: Optional[sched.Request] = None
    self._crash_streak = 0
    self._tok_rate = 0.0                   # EMA tokens/s over decode passes
    #: bounded record of crash recoveries: {t, duration_s, replayed,
    #: poisoned, streak, error} — serve_bench --chaos reads recovery
    #: latency off this
    self.restart_log: List[dict] = []
    # counters ONLY (monotonic): StatsSnapshot.delta subtracts these, so
    # a last-write gauge here would read as a bogus per-pass delta —
    # gauges (kv_pages_in_use/free) live on the obs registry and the
    # kv_pages_in_use/kv_pages_free properties instead
    self.stats = {"steps": 0, "live_slot_steps": 0, "emitted_tokens": 0,
                  "prefills": 0, "submitted": 0, "completed": 0,
                  "rejected": 0,
                  "expired": 0, "cancelled": 0, "replays": 0,
                  "engine_restarts": 0, "poisoned": 0,
                  "replay_mismatches": 0, "prefix_hits": 0,
                  "prefix_evictions": 0, "spec_accepted": 0,
                  "spec_rejected": 0}
    # obs seam (docs/OBSERVABILITY.md): cached handles; disabled = one
    # None check per decode dispatch
    self._rec = obs_spans.active()
    self._trace_detail = os.environ.get(ENV_OBS_TRACE_DETAIL,
                                        "1") not in ("0",)
    reg = obs_metrics.active()
    self._obs_m = None if reg is None else {
        "tokens": reg.counter("serve.tokens"),
        "submitted": reg.counter("serve.submitted"),
        "completed": reg.counter("serve.completed"),
        "prefills": reg.counter("serve.prefills"),
        "steps": reg.counter("serve.steps"),
        "rejected": reg.counter("serve.rejected"),
        "expired": reg.counter("serve.expired"),
        "cancelled": reg.counter("serve.cancelled"),
        "replays": reg.counter("serve.replays"),
        "engine_restarts": reg.counter("serve.engine_restarts"),
        "poisoned": reg.counter("serve.poisoned"),
        "prefix_hits": reg.counter("serve.prefix_hits"),
        "prefix_evictions": reg.counter("serve.prefix_evictions"),
        "spec_accepted": reg.counter("serve.spec_accepted"),
        "spec_rejected": reg.counter("serve.spec_rejected"),
        "occupancy": reg.gauge("serve.occupancy"),
        "queue_depth": reg.gauge("serve.queue_depth"),
        "slots_active": reg.gauge("serve.slots_active"),
        "kv_pages_in_use": reg.gauge("serve.kv_pages_in_use"),
        "kv_pages_free": reg.gauge("serve.kv_pages_free"),
        "decode_ms": reg.histogram("serve.decode_ms"),
    }
    # the SLO plane's latency objects (obs.quantiles): mergeable
    # streaming sketches — per-executor sketches ship whole over the OBS
    # verb and the driver MERGES them, so a cluster p99 is a real p99,
    # not an average of per-process ones (docs/OBSERVABILITY.md)
    self._obs_q = None if reg is None else {
        "ttft_ms": reg.quantiles("serve.ttft_ms"),
        "tpot_ms": reg.quantiles("serve.tpot_ms"),
        "e2e_ms": reg.quantiles("serve.e2e_ms"),
        "queue_wait_ms": reg.quantiles("serve.queue_wait_ms"),
    }

  def _count(self, key: str, n: int = 1) -> None:
    """Bump a stats key and its obs counter twin (when the plane is on).

    Locked: rejected/expired/cancelled are bumped from client threads
    (submit, cancel-on-a-dead-engine) AND the loop thread — a bare
    ``+=`` interleaving would drop increments."""
    with self._stats_lock:
      self.stats[key] += n
    if self._obs_m is not None and key in self._obs_m:
      self._obs_m[key].inc(n)

  def stats_snapshot(self) -> obs_metrics.StatsSnapshot:
    """Subtraction baseline over the LIVE ``stats`` dict — the safe way
    to read per-pass deltas while the loop thread keeps mutating it
    (obs.metrics.StatsSnapshot; serve_bench uses this)."""
    return obs_metrics.snapshot_stats(self.stats)

  # -- lifecycle ------------------------------------------------------------

  @property
  def num_slots(self) -> int:
    return self.decoder.num_slots

  @property
  def kv_pages_in_use(self) -> int:
    """Allocated pool pages (0 when paging is off / engine not started)."""
    pool = self._pool
    return 0 if pool is None else pool.in_use

  @property
  def kv_pages_free(self) -> int:
    pool = self._pool
    return 0 if pool is None else pool.free_pages

  def _ensure_slabs(self) -> None:
    """(Re)build the device slab AND the host page state describing it —
    a fresh slab means every old page id is meaningless, so the
    allocator, prefix trie and per-request page lists reset with it
    (crash recovery rebuilds everything; replayed requests re-allocate
    at re-admission)."""
    if self._slabs is not None:
      return
    self._slabs = self.decoder.init_slabs()
    if self.decoder.paged:
      self._pool = sched.PagePool(self.decoder.num_pages)
      self._prefix = sched.PrefixCache(self.page_size, self.prefix_pages) \
          if self.prefix_pages > 0 else None
      self._req_pages = {}

  def start(self) -> "ServingEngine":
    if self._thread is not None and self._thread.is_alive():
      return self
    self._stop_evt.clear()
    self._loop_error = None
    self._draining = False
    self._crash_streak = 0
    self._queue.reopen()
    self._ensure_slabs()
    self._thread = threading.Thread(target=self._loop, daemon=True,
                                    name="tos-serving-engine")
    self._thread.start()
    return self

  def stop(self, timeout: float = 30.0) -> None:
    """Stop the loop thread; unfinished requests (queued AND in flight)
    are failed. Idempotent, and safe before :meth:`start`."""
    self._stop_evt.set()
    t = self._thread
    if t is not None:
      t.join(timeout=timeout)
      if t.is_alive():
        logger.warning("serving loop did not stop within %.1fs", timeout)
    err = RuntimeError("serving engine stopped")
    # close-and-drain is atomic under the queue's own lock: a submit
    # racing this stop either lands before (and is failed here) or
    # fails fast on the closed queue — never orphaned (the old
    # submit-vs-loop-death race, docs/ROBUSTNESS.md)
    for req in self._queue.close(err):
      req.finish(err)
    with self._lock:
      live = [r for r in self._slots if r is not None]
      self._slots = [None] * self.num_slots
      adm, self._admitting = self._admitting, None
    if adm is not None:
      live.append(adm)
    for req in live:
      req.finish(err)                      # finish() is idempotent
    self._slabs = None                     # next start() gets a fresh slab
    # page ids described the dropped slab: the allocator/trie die with it
    self._pool = None
    self._prefix = None
    self._req_pages = {}

  def drain(self, timeout: float) -> bool:
    """Graceful shutdown: stop admission, finish every accepted request
    (queued and in flight), then stop. Returns True when all accepted
    work completed inside ``timeout`` (requests left at the deadline are
    failed by the final :meth:`stop`). Rolling restarts and the
    cached-engine rebuild in ``make_serving_predict_fn`` use this so
    zero accepted requests are shed. ``timeout`` is required — the
    wait parks on in-flight progress, so the deadline must be the
    caller's choice (TOS001, like ``wait_alert``)."""
    deadline = time.monotonic() + max(0.0, float(timeout))
    self._draining = True                  # submit() rejects from here on
    while time.monotonic() < deadline:
      if self._loop_error is not None:
        break
      t = self._thread
      if t is None or not t.is_alive():
        break
      if self._idle():
        break
      time.sleep(min(0.05, self._poll))
    completed = self._idle() and self._loop_error is None
    self.stop(timeout=max(1.0, deadline - time.monotonic()))
    return completed

  def _idle(self) -> bool:
    # order matters (drain's zero-shed contract): the queue is checked
    # FIRST. A pop marks the request as mid-admission while the queue
    # lock is held (pop_nowait's on_pop hook), so once we observe the
    # queue empty, any popped request is already visible in
    # _admitting or a slot — there is no in-neither window to misread
    # as idle.
    if len(self._queue) > 0:
      return False
    with self._lock:
      return not (any(r is not None for r in self._slots)
                  or self._admitting is not None)

  def __enter__(self):
    return self.start()

  def __exit__(self, *exc):
    self.stop()

  # -- client API -----------------------------------------------------------

  def submit(self, prompt, max_new_tokens: Optional[int] = None,
             deadline: Optional[float] = None,
             ttl: Optional[float] = None,
             trace_id: Optional[str] = None) -> int:
    """Queue one prompt; returns the request id.

    ``deadline`` is an absolute ``time.monotonic()`` bound; ``ttl`` is
    seconds from now (pass one or the other). An admitted request whose
    deadline passes fails with ``DeadlineExceeded`` — while queued,
    without ever taking a slot; in flight, at the next horizon boundary.
    Raises ``ServingOverloaded`` (structured: queue depth, queued token
    mass, retry-after hint) instead of queueing without bound.
    ``trace_id`` joins an existing request-scoped trace (the fleet
    passes the FleetRequest's, so a cross-replica failover hop stays ONE
    trace); None mints a fresh one on the Request.
    """
    budget = int(max_new_tokens if max_new_tokens is not None
                 else self.default_max_new_tokens)
    if budget < 1:
      raise ValueError("max_new_tokens must be >= 1, got %d" % budget)
    now = time.monotonic()
    if deadline is not None and ttl is not None:
      raise ValueError("pass deadline OR ttl, not both")
    if ttl is None and deadline is None and self.default_ttl is not None:
      ttl = self.default_ttl
    if ttl is not None:
      deadline = now + float(ttl)
    req = sched.Request(prompt, budget, deadline=deadline,
                        trace_id=trace_id)
    if len(req.prompt) < 1:
      # reject here, not in the loop thread: a chunk_plan(0) crash there
      # would take every other in-flight request down with it
      raise ValueError("prompt must contain at least one token")
    if len(req.prompt) + budget > self.cfg.max_seq_len:
      raise ValueError(
          "prompt of %d tokens + budget %d exceeds the max_seq_len=%d "
          "slot cache" % (len(req.prompt), budget, self.cfg.max_seq_len))
    if self.decoder.paged:
      needed = -(-(len(req.prompt) + budget) // self.page_size)
      if needed > self.decoder.num_pages - 1:
        # reject here, not in the loop: a request no amount of
        # completions can ever page in would pin admission forever
        raise ValueError(
            "prompt of %d tokens + budget %d needs %d KV pages but the "
            "pool holds %d allocatable (TOS_SERVE_NUM_PAGES=%d minus "
            "the trash page)" % (len(req.prompt), budget, needed,
                                 self.decoder.num_pages - 1,
                                 self.decoder.num_pages))
    # past validation: this IS traffic — the availability SLO's
    # denominator (obs.slo: bad = rejected + poisoned over submitted).
    # Malformed requests (the ValueErrors above) are caller bugs, not
    # unavailability, and stay out of both sides of the ratio.
    self._count("submitted")
    if req.expired(now):
      self._count("expired")
      raise sched.DeadlineExceeded(
          "request dead on arrival: its deadline already passed at "
          "submit")
    if self._draining:
      self._count("rejected")   # drain-time turn-aways must be visible
      raise sched.ServingOverloaded(
          "serving engine is draining — admission is closed",
          queue_depth=len(self._queue),
          queued_tokens=self._queue.token_mass,
          retry_after=self._retry_after(self._queue.token_mass),
          draining=True)
    if self._loop_error is not None:
      raise RuntimeError("serving loop died") from self._loop_error
    with self._lock:
      self._requests[req.rid] = req
    try:
      self._queue.push_bounded(req, self.max_queue, self.max_queued_tokens)
    except sched.ServingOverloaded as e:
      with self._lock:
        self._requests.pop(req.rid, None)
      self._count("rejected")
      e.retry_after = self._retry_after(e.queued_tokens)
      raise
    except sched.QueueClosed:
      # the loop died (or the engine stopped) between our liveness check
      # and the push — the close happened under the queue's lock, so we
      # fail HERE instead of orphaning the request until its timeout
      with self._lock:
        self._requests.pop(req.rid, None)
      if self._loop_error is not None:
        raise RuntimeError("serving loop died") from self._loop_error
      raise RuntimeError("serving engine stopped")
    return req.rid

  def _retry_after(self, queued_tokens: int) -> float:
    """Backpressure hint: how long until the live decode rate clears the
    current backlog. Before the first decode completes the tokens/s EMA
    is 0 and the backlog estimate is undefined — a cold engine answers
    the bounded ``_COLD_RETRY_AFTER`` default instead of a
    retry-immediately hint that would have clients hammering an engine
    still compiling its first dispatch."""
    rate = self._tok_rate
    if rate <= 0:
      return round(max(self._poll, _COLD_RETRY_AFTER), 3)
    return round(min(60.0, max(self._poll, queued_tokens / rate)), 3)

  def cancel(self, rid: int, timeout: float) -> bool:
    """Cancel a request: queued → failed without taking a slot; in
    flight → its slot frees at the next horizon boundary, exactly like
    EOS. Blocks (bounded) until the request actually finished; returns
    True when it did. Already-finished requests return True unchanged.
    ``timeout`` is required — the wait parks on the slot release, so
    the deadline must be the caller's choice (TOS001).
    """
    req = self._req(rid)
    if req.done.is_set():
      return True
    req.cancelled.set()
    t = self._thread
    if t is None or not t.is_alive():
      # no loop to reap it: fail queued entries synchronously so the
      # caller is not parked on a dead engine
      self._reap_queue(time.monotonic())
    req.done.wait(timeout=timeout)
    return req.done.is_set()

  def _req(self, rid: int) -> sched.Request:
    with self._lock:
      try:
        return self._requests[rid]
      except KeyError:
        raise KeyError("unknown request id %r" % (rid,))

  def request(self, rid: int) -> sched.Request:
    """The live Request handle (timing/latency fields ride on it).

    Hold the handle before calling :meth:`result`/:meth:`poll` — those
    pop the registry entry once the output is delivered."""
    return self._req(rid)

  def poll(self, rid: int) -> Optional[np.ndarray]:
    """The finished output (prompt + generated), or None if in flight."""
    req = self._req(rid)
    if not req.done.is_set():
      return None
    return self._result_of(req, pop=True)

  def result(self, rid: int, timeout: float = 600.0) -> np.ndarray:
    """Block (bounded) for one request's output. Fails FAST — with the
    loop's root cause — when the engine is dead or was never started,
    instead of sitting out the full timeout."""
    req = self._req(rid)
    self._wait_done(req, timeout, "request %d" % rid)
    return self._result_of(req, pop=True)

  def _wait_done(self, req: sched.Request, timeout: float,
                 what: str) -> None:
    deadline = time.monotonic() + timeout
    chunk = max(0.05, self._poll)
    while True:
      remaining = deadline - time.monotonic()
      if req.done.wait(timeout=max(0.0, min(chunk, remaining))):
        return
      self._raise_if_dead(req, what)
      if deadline - time.monotonic() <= 0:
        raise TimeoutError("%s not finished within %.1fs"
                           % (what, timeout))

  def _raise_if_dead(self, req: Optional[sched.Request],
                     what: str) -> None:
    """Fail-fast check for waiters: a dead (or never-started) engine
    cannot finish anything — raise the root cause now, not at the
    caller's timeout."""
    if req is not None and req.done.is_set():
      return
    if self._loop_error is not None:
      raise RuntimeError("serving loop died; %s cannot finish"
                         % what) from self._loop_error
    t = self._thread
    if t is None:
      raise RuntimeError(
          "serving engine was never started — call start() before "
          "waiting on %s" % what)
    if not t.is_alive():
      raise RuntimeError("serving engine is stopped; %s cannot finish"
                         % what)

  def _result_of(self, req: sched.Request, pop: bool) -> np.ndarray:
    if pop:
      with self._lock:
        self._requests.pop(req.rid, None)
    err = req.error
    if isinstance(err, (sched.DeadlineExceeded, sched.RequestCancelled,
                        sched.PoisonedRequest)):
      raise err                  # structured verdicts surface as-is
    if err is not None:
      raise RuntimeError("request %d failed" % req.rid) from err
    return req.output()

  def stream(self, rid: int, timeout: float = 600.0):
    """Yield generated tokens as they are produced (EOS inclusive).

    Crash replays are invisible here: the engine suppresses the
    already-emitted prefix, so a consumer sees each position exactly
    once. Fails fast on a dead/never-started engine."""
    req = self._req(rid)
    deadline = time.monotonic() + timeout
    t0 = time.monotonic()
    emitted = 0
    while True:
      remaining = deadline - time.monotonic()
      if remaining <= 0:
        raise TimeoutError("stream for request %d stalled" % rid)
      try:
        tok = req.stream_q.get(timeout=min(remaining, self._poll * 10))
      except std_queue.Empty:
        self._raise_if_dead(req, "request %d" % rid)
        continue
      if tok is None:
        break
      emitted += 1
      yield tok
    if self._rec is not None:
      # the delivery phase of the waterfall: stream attach → sentinel
      self._rec.record_span("serve.stream", t0, time.monotonic() - t0,
                            trace=req.trace_id, rid=rid, tokens=emitted)
    with self._lock:
      self._requests.pop(rid, None)
    err = req.error
    if isinstance(err, (sched.DeadlineExceeded, sched.RequestCancelled,
                        sched.PoisonedRequest)):
      raise err
    if err is not None:
      raise RuntimeError("request %d failed after %d token(s)"
                         % (rid, emitted)) from err

  def generate(self, prompts: Sequence,
               max_new_tokens: Optional[int] = None,
               timeout: float = 600.0,
               detailed: bool = False) -> List:
    """Submit a batch of prompts and wait for all outputs (in order).

    If a mid-list submit is rejected (overload/validation), the
    already-submitted prefix is cancelled before re-raising — no
    orphaned work keeps burning slots for a caller that went away.

    ``detailed=True`` returns ``{"tokens": ndarray, "trace_id": str,
    "timing": dict}`` per prompt instead of the bare array — the
    per-request timing ledger (``Request.timing``: submitted/admitted/
    prefill_done/first_token/finished stamps + ttft/e2e/queue_wait/tpot)
    and the trace id for ``obs_report --request``."""
    rids = []
    try:
      for p in prompts:
        rids.append(self.submit(p, max_new_tokens=max_new_tokens))
    except BaseException:
      for rid in rids:
        with contextlib.suppress(Exception):
          self.cancel(rid, timeout=1.0)
      raise
    deadline = time.monotonic() + timeout
    outs = []
    for rid in rids:
      req = self._req(rid)      # hold the handle: result() pops the map
      out = self.result(rid, timeout=max(0.001,
                                         deadline - time.monotonic()))
      if detailed:
        outs.append({"tokens": out, "trace_id": req.trace_id,
                     "timing": req.timing()})
      else:
        outs.append(out)
    return outs

  @property
  def alive(self) -> bool:
    """False once the engine is terminally dead (loop exhausted its
    restart budget) or stopped — callers holding a cached engine must
    rebuild instead of reusing it. A transient crash mid-replay keeps
    ``alive`` True: the engine is healing, not dead. True before
    ``start()`` (a constructed engine is startable)."""
    if self._loop_error is not None:
      return False
    t = self._thread
    return t is None or t.is_alive()

  @property
  def occupancy(self) -> float:
    """Live-slot fraction over all decode steps so far (goodput proxy)."""
    steps = self.stats["steps"]
    if not steps:
      return 0.0
    return self.stats["live_slot_steps"] / float(steps * self.num_slots)

  # -- load telemetry (the fleet router's dispatch inputs) -------------------
  # The same numbers the HEALTH wire carries as serve.* gauges, exposed
  # as cheap properties so a driver-side router (serving.fleet) can
  # score replicas without the obs plane being on.

  @property
  def queue_depth(self) -> int:
    """Queued-or-admitting request count: a request the loop popped but
    has not finished prefilling into a slot is still BACKLOG — without
    counting it, a replica mid-prefill reads (queue 0, occupancy 0) and
    a load-aware router double-books exactly the replica that is busiest
    admitting (the drain _idle rule, applied to the scoring read)."""
    adm = self._admitting
    return len(self._queue) + (1 if adm is not None else 0)

  @property
  def queued_tokens(self) -> int:
    """Queued-or-admitting token mass: sum of prompt+budget over the
    backlog (same mid-admission rule as :attr:`queue_depth`)."""
    adm = self._admitting
    extra = (len(adm.prompt) + adm.max_new_tokens) if adm is not None \
        else 0
    return self._queue.token_mass + extra

  @property
  def tokens_per_sec(self) -> float:
    """Live tokens/s EMA over decode passes (0.0 before the first)."""
    return self._tok_rate

  @property
  def slots_in_use(self) -> int:
    with self._lock:
      return sum(1 for r in self._slots if r is not None)

  @property
  def occupancy_now(self) -> float:
    """Instantaneous occupied-slot fraction (vs the historical
    :attr:`occupancy` goodput proxy)."""
    return self.slots_in_use / float(self.num_slots)

  def kill(self, cause: Optional[BaseException] = None,
           timeout: float = 5.0) -> None:
    """Terminal-death injection seam: die AS IF the loop exhausted its
    restart budget — the loop thread exits, :attr:`alive` flips False,
    and every waiter (queued, in flight, future) fails fast with
    ``cause``. The fleet's chaos path (``TOS_CHAOS_FLEET`` kill actions,
    ``serving.fleet``) and the failover tests drive this; production
    code should use :meth:`stop`/:meth:`drain`."""
    err = cause if cause is not None else RuntimeError(
        "serving engine killed")
    self._stop_evt.set()                   # the loop exits its next pass
    self._die(err)
    t = self._thread
    if t is not None:
      t.join(timeout=timeout)

  # -- engine loop ----------------------------------------------------------

  def _loop(self) -> None:
    while not self._stop_evt.is_set():
      try:
        self._ensure_slabs()               # rebuilt after a crash
        self._reap()
        self._admit()
        if not any(r is not None for r in self._slots):
          # idle: bounded block until work arrives (TOS001)
          self._queue.wait_nonempty(timeout=self._poll)
          continue
        self._decode_once()
        self._crash_streak = 0             # a full decode pass = healthy
      except BaseException as e:  # noqa: BLE001 - crash-replay recovery;
        # terminal failures are forwarded to every waiter by _die
        if not self._recover(e):
          return

  # -- crash-replay recovery -------------------------------------------------

  def _recover(self, error: BaseException) -> bool:
    """Heal from a loop crash: rebuild device state and transparently
    replay every in-flight request from its prompt (greedy ⇒ the
    regenerated stream is bit-identical; the already-emitted prefix is
    suppressed). Returns False when the engine must die instead
    (stopping, or the consecutive-restart budget is spent)."""
    if self._stop_evt.is_set():
      return False                         # stop() owns cleanup from here
    t_crash = time.monotonic()
    self._crash_streak += 1
    streak = self._crash_streak
    if streak > self.max_restarts:
      logger.exception("serving loop died terminally (%d consecutive "
                       "crashes > max_restarts=%d)",
                       streak, self.max_restarts)
      self._die(error)
      return False
    logger.warning("serving loop crashed (consecutive crash %d/%d), "
                   "recovering: %r", streak, self.max_restarts, error)
    self._count("engine_restarts")
    # collect the victims: in-flight slots in slot order, then the
    # request that was mid-admission (the _admit prefill path) — it is
    # in neither the queue nor a slot and must not be lost
    with self._lock:
      victims = [r for r in self._slots if r is not None]
      self._slots = [None] * self.num_slots
      adm, self._admitting = self._admitting, None
    if adm is not None:
      victims.append(adm)
    self._last[:] = self.pad_id
    self._slabs = None                     # fresh slab next iteration
    # the crash took the slab's pages with it: allocator, prefix trie
    # and per-request page lists rebuild with the slab (_ensure_slabs);
    # replayed requests re-allocate at re-admission
    self._pool = None
    self._prefix = None
    self._req_pages = {}
    # blame: a crash during admission implicates exactly the request
    # being prefilled; a crash mid-decode cannot be attributed and
    # implicates every in-flight lane
    for req in victims:
      if adm is None or req is adm:
        req.crash_count += 1
    now = time.monotonic()
    replay: List[sched.Request] = []
    poisoned = 0
    for req in victims:
      if req.done.is_set():
        continue
      if req.cancelled.is_set():
        self._count("cancelled")
        req.finish(sched.RequestCancelled(
            "request %d cancelled" % req.rid))
        continue
      if req.expired(now):
        self._count("expired")
        req.finish(sched.DeadlineExceeded(
            "request %d deadline passed during crash recovery" % req.rid))
        continue
      if req.crash_count >= self.poison_crashes:
        poisoned += 1
        self._count("poisoned")
        err = sched.PoisonedRequest(
            "request %d was in flight across %d consecutive engine "
            "crashes — failed, not replayed" % (req.rid, req.crash_count))
        err.__cause__ = error
        req.finish(err)
        continue
      replay.append(req)
    try:
      # ahead of the backlog, original order preserved: appendleft in
      # reverse puts victims back in the order they were running
      for req in reversed(replay):
        req.begin_replay()
        self._queue.push_front(req)
    except sched.QueueClosed:
      err = RuntimeError("serving engine stopped")
      for req in replay:
        req.finish(err)
      return False
    if replay:
      self._count("replays", len(replay))
      if self._rec is not None:
        for req in replay:
          # the crash-replay suppression window on the request's own
          # trace: the next len(tokens) emits re-derive delivered
          # positions (docs/ROBUSTNESS.md); the waterfall shows it as
          # an instant on the trace, streak-stamped
          self._rec.event("serve.replay", trace=req.trace_id,
                          rid=req.rid, suppressed=len(req.tokens),
                          streak=streak)
    if poisoned:
      # removing the suspected cause IS progress: don't let a healed
      # poison sequence burn the restart budget of a real crash loop
      self._crash_streak = 0
    backoff = min(_BACKOFF_CAP,
                  self.restart_backoff * (2 ** (streak - 1)))
    if backoff > 0:
      self._stop_evt.wait(backoff)         # interruptible by stop()
    rec = {"t": t_crash, "duration_s": time.monotonic() - t_crash,
           "replayed": len(replay), "poisoned": poisoned,
           "streak": streak, "error": repr(error)[:200]}
    self.restart_log.append(rec)
    del self.restart_log[:-_RESTART_LOG_CAP]
    if self._rec is not None:
      self._rec.event("serve.restart", replayed=len(replay),
                      poisoned=poisoned, streak=streak)
    return True

  def _die(self, error: BaseException) -> None:
    """Terminal loop death: mark the root cause, then fail every waiter
    — queued, in flight, and mid-admission — so nobody burns a timeout.
    The queue close is atomic with its drain (scheduler.RequestQueue),
    so a racing submit can never orphan a request behind it."""
    self._loop_error = error
    for req in self._queue.close(error):
      req.finish(error)
    with self._lock:
      live = [r for r in self._slots if r is not None]
      self._slots = [None] * self.num_slots
      adm, self._admitting = self._admitting, None
    if adm is not None:
      live.append(adm)
    for req in live:
      req.finish(error)

  # -- reaping (deadlines & cancellation) ------------------------------------

  def _reap(self) -> None:
    """Fail expired/cancelled requests: queued ones without ever taking
    a slot, in-flight ones by freeing their slot at this horizon
    boundary — exactly the bookkeeping an EOS exit does."""
    now = time.monotonic()
    self._reap_queue(now)
    freed = []
    for slot in range(self.num_slots):
      req = self._slots[slot]
      if req is None:
        continue
      if not (req.cancelled.is_set() or req.expired(now)):
        continue
      self._fail_reaped(req, now)
      with self._lock:
        self._slots[slot] = None
      self._last[slot] = self.pad_id
      if self.decoder.paged:
        self._release_pages(req.rid)
        freed.append(slot)
    self._reset_freed(freed)

  def _reset_freed(self, freed: List[int]) -> None:
    """Point freed slots' page tables at the trash page BEFORE the next
    decode dispatch: a freed lane keeps computing (frozen), and its
    stale table would otherwise scribble into pages the allocator may
    already have handed to a new request."""
    if not freed:
      return
    mask = np.zeros((self.num_slots,), bool)
    mask[freed] = True
    self._slabs = self.decoder.reset_slots(self._slabs, mask)

  def _reap_queue(self, now: float) -> None:
    for req in self._queue.reap(
        lambda r: r.cancelled.is_set() or r.expired(now)):
      self._fail_reaped(req, now)

  def _fail_reaped(self, req: sched.Request, now: float) -> None:
    if req.cancelled.is_set():
      self._count("cancelled")
      req.finish(sched.RequestCancelled(
          "request %d cancelled" % req.rid))
    else:
      self._count("expired")
      req.finish(sched.DeadlineExceeded(
          "request %d missed its deadline by %.3fs"
          % (req.rid, now - (req.deadline or now))))

  # -- admission -------------------------------------------------------------

  def _alloc_pages(self, req: sched.Request):
    """Page in one request: ``(all pages in token order, shared prefix
    pages, shared token count)``, or None when the pool cannot host it
    right now (the caller requeues; completions free pages).

    Prefix-cache hits fork read-only references to the prefix's FULL
    pages (pinned before any eviction can free them); the divergence
    page and the tail/budget pages are fresh private allocations. A full
    pool shrinks the prefix cache LRU-first before giving up.
    """
    plen = len(req.prompt)
    shared_pages, shared_tokens = [], 0
    if self._prefix is not None:
      hit = self._prefix.lookup(req.prompt)
      # always leave >= 1 tail token: the last prompt token must run
      # through the model to yield g1, and the divergence page is never
      # shared (the copy-on-write boundary)
      usable = min(len(hit), (plen - 1) // self.page_size)
      shared_pages = hit[:usable]
      shared_tokens = usable * self.page_size
      for p in shared_pages:         # pin BEFORE eviction can free them
        self._pool.ref(p)
    need = -(-(plen + req.max_new_tokens) // self.page_size) \
        - len(shared_pages)
    fresh = self._pool.alloc(need)
    while fresh is None and self._prefix is not None \
        and self._prefix.pages_held > 0:
      # evict the whole deficit in one batched trie walk; STOP once a
      # round frees nothing (every evicted page still ref'd by live
      # readers) — grinding the trie to empty would destroy all prefix
      # sharing without ever satisfying this allocation
      if self._evict_prefix(need - self._pool.free_pages) == 0:
        break
      fresh = self._pool.alloc(need)
    if fresh is None:
      for p in shared_pages:
        self._pool.unref(p)
      return None
    return shared_pages + fresh, shared_pages, shared_tokens

  def _evict_prefix(self, n: int) -> int:
    """Evict up to ``n`` LRU prefix pages; returns how many actually
    came FREE (a page still ref'd by live readers leaves the cache but
    stays allocated until its last ref drops)."""
    freed = 0
    for p in self._prefix.evict(max(1, n)):
      self._count("prefix_evictions")
      freed += bool(self._pool.unref(p))
    return freed

  def _release_pages(self, rid: int) -> None:
    """Drop the request's page refs EXACTLY once (pop-then-unref: a
    second call for the same rid is a no-op, so reap/complete/drain
    paths cannot double-free; pages shared with the prefix cache or
    other readers stay allocated until their last ref drops)."""
    pages = self._req_pages.pop(rid, None)
    if pages:
      for p in pages:
        self._pool.unref(p)

  def _admit(self) -> None:
    """Prefill queued requests into free slots (EOS-freed or virgin)."""
    for slot in range(self.num_slots):
      if self._slots[slot] is not None:
        continue
      req = None
      while req is None:
        # on_pop marks the request mid-admission ATOMICALLY with the
        # pop (under the queue lock): crash-safe for _recover, and
        # drain's idle check can never observe the in-neither gap
        req = self._queue.pop_nowait(on_pop=self._mark_admitting)
        if req is None:
          return
        now = time.monotonic()
        if req.cancelled.is_set() or req.expired(now):
          # the admission-time deadline check: fail WITHOUT a slot
          self._fail_reaped(req, now)
          self._admitting = None
          req = None
      pages, shared_tokens, table = None, 0, None
      if self.decoder.paged:
        alloc = self._alloc_pages(req)
        if alloc is None:
          # pool exhausted: requeue AHEAD of the backlog (it was already
          # admitted; bounds don't re-apply) and stop admitting — the
          # next completion frees pages and admission resumes
          self._queue.push_front(req)
          self._admitting = None
          return
        pages, _, shared_tokens = alloc
        table = pages + [0] * (self.decoder.pages_per_slot - len(pages))
      if req.started_at is None:
        req.started_at = time.monotonic()
        if self._rec is not None:
          # the queue-wait phase of the waterfall: submit → admitted.
          # Recorded once, at FIRST admission (a crash-replay
          # re-admission is not a second client-visible queue wait)
          self._rec.record_span("serve.queue", req.submitted_at,
                                req.started_at - req.submitted_at,
                                trace=req.trace_id, rid=req.rid)
      cm = self._rec.span("serve.prefill", trace=req.trace_id,
                          rid=req.rid,
                          prompt_len=len(req.prompt), slot=slot,
                          shared_tokens=shared_tokens) \
          if self._rec is not None else contextlib.nullcontext()
      with cm:
        resume = None
        if shared_tokens:
          # prefix hit: rebuild the warm row cache from the shared pages
          # and prefill only the tail — the O(prefix) work is skipped
          self._count("prefix_hits")
          row = self.decoder.gather_pages(self._slabs, table,
                                          shared_tokens)
          resume = (row, shared_tokens)
        row_cache, first = self.decoder.prefill(
            self.params, req.prompt, self.buckets, resume=resume,
            trace=req.trace_id if self._rec is not None
            and self._trace_detail else None)
      if req.prefill_done_at is None:   # replays keep the original stamp
        req.prefill_done_at = time.monotonic()
      self.stats["prefills"] += 1
      if self._obs_m is not None:
        self._obs_m["prefills"].inc()
      if not req.emit(first):
        self.stats["replay_mismatches"] += 1
      self.stats["emitted_tokens"] += 1
      if self._finished(req, first):
        self._complete(req)
        if pages is not None:    # never inserted: nothing else holds them
          for p in pages:
            self._pool.unref(p)
        self._admitting = None
        continue                 # slot stays free for the next request
      if self.decoder.paged:
        self._slabs = self.decoder.insert_pages(self._slabs, row_cache,
                                                slot, table,
                                                start=shared_tokens)
        if self._prefix is not None:
          # the prompt's full pages become shareable: the cache takes
          # its own ref on each newly cached page, outliving this
          # request; then the LRU budget is enforced
          for p in self._prefix.register(req.prompt, pages):
            self._pool.ref(p)
          over = self._prefix.over_budget
          if over:
            self._evict_prefix(over)
        self._req_pages[req.rid] = pages
      else:
        self._slabs = self.decoder.insert(self._slabs, row_cache, slot)
      with self._lock:
        self._slots[slot] = req
      self._admitting = None
      self._last[slot] = first

  def _mark_admitting(self, req: sched.Request) -> None:
    self._admitting = req

  def _finished(self, req: sched.Request, token: int) -> bool:
    if self.eos_id is not None and int(token) == self.eos_id:
      return True
    return req.generated >= req.max_new_tokens

  def _complete(self, req: sched.Request) -> None:
    self.stats["completed"] += 1
    if self._obs_m is not None:
      self._obs_m["completed"].inc()
    req.finish(None)
    if self._obs_q is not None:
      # the request's timing ledger feeds the mergeable latency
      # sketches — the SLO plane's per-engine TTFT/TPOT/e2e/queue-wait
      # objects (completed requests only: a rejected request has no
      # latency, it has an availability verdict)
      q = self._obs_q
      if req.ttft is not None:
        q["ttft_ms"].observe(req.ttft * 1e3)
      if req.tpot is not None:
        q["tpot_ms"].observe(req.tpot * 1e3)
      if req.latency is not None:
        q["e2e_ms"].observe(req.latency * 1e3)
      if req.queue_wait is not None:
        q["queue_wait_ms"].observe(req.queue_wait * 1e3)

  def _decode_once(self) -> None:
    """One fused ``horizon``-step dispatch + host-side harvest.

    The device scan carries each lane's EOS/budget done-mask; the host
    replays the identical stop rule over the returned ``[horizon,
    num_slots]`` token matrix, so the two views cannot diverge. A lane
    that stops mid-horizon idles (frozen) for the remaining scan steps —
    the bounded price of amortizing dispatch over the horizon."""
    t0 = time.monotonic()
    tokens_before = self.stats["emitted_tokens"]
    active = np.asarray([r is not None for r in self._slots], bool)
    remaining = np.asarray(
        [0 if r is None else r.max_new_tokens - r.generated
         for r in self._slots], np.int32)
    if self.spec_depth > 0:
      steps, lanes = self._decode_spec(active, remaining)
    else:
      steps, lanes = self._decode_plain(active, remaining)
    dt = time.monotonic() - t0
    emitted = self.stats["emitted_tokens"] - tokens_before
    if dt > 0 and emitted:
      # live tokens/s EMA — the denominator of the retry-after hint
      rate = emitted / dt
      self._tok_rate = rate if self._tok_rate <= 0 \
          else 0.5 * self._tok_rate + 0.5 * rate
    if self._rec is not None or self._obs_m is not None:
      live = sum(1 for r in self._slots if r is not None)
      if self._rec is not None:
        self._rec.record_span("serve.decode", t0, dt,
                              horizon=self.horizon,
                              active=int(active.sum()))
        # slot-attributed decode horizons: one child span per lane that
        # decoded in this dispatch, carrying the request's trace and its
        # per-lane emitted count (from the harvest of step_many's
        # [horizon, slots] token matrix) — the decode phase of the
        # per-request waterfall (obs_report --request). TRACE_DETAIL
        # gated: the one span family that scales with slots × dispatches
        if self._trace_detail:
          for slot, trace, emitted_lane in lanes:
            self._rec.record_span("serve.decode.slot", t0, dt,
                                  trace=trace, slot=slot,
                                  tokens=emitted_lane)
      m = self._obs_m
      if m is not None:
        m["steps"].inc(steps)
        m["tokens"].inc(emitted)
        m["decode_ms"].observe(dt * 1e3)
        m["occupancy"].set(self.occupancy)
        m["queue_depth"].set(len(self._queue))
        m["slots_active"].set(live)
        if self._pool is not None:
          m["kv_pages_in_use"].set(self._pool.in_use)
          m["kv_pages_free"].set(self._pool.free_pages)

  def _harvest(self, req, tok: int, slot: int, freed: List[int]) -> bool:
    """Record one emitted token; on the request's stop, free its slot
    (and pages) exactly like EOS. Returns True when the slot freed."""
    if not req.emit(tok):
      self.stats["replay_mismatches"] += 1
    self.stats["emitted_tokens"] += 1
    self.stats["live_slot_steps"] += 1
    if not self._finished(req, tok):
      return False
    self._complete(req)
    with self._lock:
      self._slots[slot] = None
    self._last[slot] = self.pad_id
    if self.decoder.paged:
      self._release_pages(req.rid)
      freed.append(slot)
    return True

  def _decode_plain(self, active, remaining):
    """The non-speculative fused horizon (SlotDecoder.step_many).
    Returns ``(steps, lanes)`` — ``lanes`` is the slot-attributed
    ``(slot, trace_id, emitted)`` list for the per-request decode spans,
    built only while the recorder is live (zero work otherwise)."""
    self._slabs, toks, _, _ = self.decoder.step_many(
        self.params, self._slabs, self._last, active, remaining,
        self.horizon)
    toks = np.asarray(toks)                       # [horizon, num_slots]
    self.stats["steps"] += self.horizon
    want_lanes = self._rec is not None and self._trace_detail
    lanes: List[tuple] = []
    freed: List[int] = []
    for slot in range(self.num_slots):
      req = self._slots[slot]
      if req is None:
        continue
      emitted = 0
      for j in range(self.horizon):
        emitted += 1
        if self._harvest(req, int(toks[j, slot]), slot, freed):
          break
      else:
        self._last[slot] = int(toks[self.horizon - 1, slot])
      if want_lanes:
        lanes.append((slot, req.trace_id, emitted))
    self._reset_freed(freed)
    return self.horizon, lanes

  def _decode_spec(self, active, remaining):
    """The self-speculative fused dispatch (SlotDecoder.step_spec).

    ``counts[r, lane]`` bounds each lane's valid tokens per round (the
    device's accept/EOS/budget verdict); the host still replays the
    stop rule per token (the step_many contract), so the two views
    cannot diverge. Accepted/rejected draft verdicts feed the
    ``spec_accepted``/``spec_rejected`` counters. Returns ``(steps,
    lanes)`` like :meth:`_decode_plain`.
    """
    k, rounds = self.spec_depth, self._spec_rounds
    self._slabs, toks, counts, acc, rej, _, _ = self.decoder.step_spec(
        self.params, self._slabs, self._last, active, remaining, rounds)
    toks = np.asarray(toks)            # [rounds, spec_depth, num_slots]
    counts = np.asarray(counts)        # [rounds, num_slots]
    # a round's slot-step opportunity is its verify window (k wide) —
    # occupancy then reads as useful-token fraction incl. rejections
    self.stats["steps"] += rounds * k
    self._count("spec_accepted", int(np.asarray(acc).sum()))
    self._count("spec_rejected", int(np.asarray(rej).sum()))
    want_lanes = self._rec is not None and self._trace_detail
    lanes: List[tuple] = []
    freed: List[int] = []
    for slot in range(self.num_slots):
      req = self._slots[slot]
      if req is None:
        continue
      done = False
      emitted = 0
      last_tok = None
      for r in range(rounds):
        for j in range(int(counts[r, slot])):
          last_tok = int(toks[r, j, slot])
          emitted += 1
          if self._harvest(req, last_tok, slot, freed):
            done = True
            break
        if done:
          break
      if not done and last_tok is not None:
        self._last[slot] = last_tok
      if want_lanes:
        lanes.append((slot, req.trace_id, emitted))
    self._reset_freed(freed)
    return rounds * k, lanes
