"""ServingEngine: slot-based in-flight (continuous) batching.

The throughput lever the fixed-batch serving loop leaves on the table:
``greedy_generate_kv`` decodes every request in a batch for the full
``num_steps`` and a new batch cannot start until the slowest sequence
finishes — on mixed-length traffic most slot-steps are wasted padding.
This engine keeps ONE persistent jitted step function advancing a
fixed-capacity slot slab (``serving.slots.SlotDecoder``); the moment a
slot's request hits EOS or its token budget the slot is freed, the next
queued request is prefilled directly into that cache region, and the
step keeps running — the device stays saturated at request granularity
(the same overlap-and-saturate principle as the PR 4 feed plane, and
the batching story of arXiv:2011.03641).

Greedy decode only, and per-request outputs are BIT-IDENTICAL to the
single-request ``greedy_generate_kv`` decode of the same prompt: rows
are independent in every einsum, per-slot cursors mask each lane to its
own length, and prefill chunking changes which einsum computes a value
but not the value (pinned by tests/test_serving.py).

Usage::

    eng = ServingEngine(params, cfg, num_slots=8, eos_id=2).start()
    rid = eng.submit(prompt_ids, max_new_tokens=128)
    tokens = eng.result(rid, timeout=60)        # prompt + generated
    # or: for tok in eng.stream(rid): ...
    eng.stop()

All waits are timeout-bounded (TOS001) and the loop thread is a daemon
(TOS007). Config knobs ride registered ``TOS_*`` env vars (TOS008):
``TOS_SERVE_SLOTS``, ``TOS_SERVE_BUCKETS``, ``TOS_SERVE_POLL``.
"""

import contextlib
import logging
import os
import queue as std_queue
import threading
import time
from typing import List, Optional, Sequence

import numpy as np

from tensorflowonspark_tpu.obs import metrics as obs_metrics
from tensorflowonspark_tpu.obs import spans as obs_spans
from tensorflowonspark_tpu.serving import scheduler as sched
from tensorflowonspark_tpu.serving import slots as slots_lib

logger = logging.getLogger(__name__)

#: default slot capacity when the caller passes ``num_slots=None``
ENV_SERVE_SLOTS = "TOS_SERVE_SLOTS"
#: idle-loop poll interval (seconds) — the bound on every engine wait
ENV_SERVE_POLL = "TOS_SERVE_POLL"
#: decode horizon: how many tokens one fused step dispatch advances.
#: 1 = per-token dispatch (lowest admission latency); larger values
#: amortize dispatch + host-sync overhead over the horizon at the cost
#: of at most horizon-1 frozen slot-steps per finished request and
#: admission every horizon tokens (see SlotDecoder.step_many)
ENV_SERVE_HORIZON = "TOS_SERVE_HORIZON"

_DEFAULT_SLOTS = 4
_DEFAULT_POLL = 0.05
_DEFAULT_HORIZON = 4


class ServingEngine(object):
  """Continuous-batching serving runtime over one model + param set."""

  def __init__(self, params, cfg, num_slots: Optional[int] = None,
               eos_id: Optional[int] = None, pad_id: int = 0,
               max_new_tokens: int = 64, buckets=None, mesh=None,
               poll_interval: Optional[float] = None,
               horizon: Optional[int] = None):
    if eos_id is not None and int(eos_id) == int(pad_id):
      raise ValueError("eos_id and pad_id must differ (both %d)"
                       % int(pad_id))
    if num_slots is None:
      num_slots = int(os.environ.get(ENV_SERVE_SLOTS, str(_DEFAULT_SLOTS)))
    if horizon is None:
      horizon = int(os.environ.get(ENV_SERVE_HORIZON,
                                   str(_DEFAULT_HORIZON)))
    if horizon < 1:
      raise ValueError("horizon must be >= 1, got %d" % horizon)
    self.params = params
    self.cfg = cfg
    self.eos_id = None if eos_id is None else int(eos_id)
    self.pad_id = int(pad_id)
    self.horizon = horizon
    self.default_max_new_tokens = int(max_new_tokens)
    # explicit argument beats the env knob (the num_slots/horizon rule)
    self.buckets = tuple(buckets) if buckets is not None \
        else sched.buckets_from_env(slots_lib.DEFAULT_BUCKETS)
    self.decoder = slots_lib.SlotDecoder(cfg, num_slots, pad_id=pad_id,
                                         eos_id=self.eos_id, mesh=mesh)
    self._poll = float(poll_interval if poll_interval is not None
                       else os.environ.get(ENV_SERVE_POLL, _DEFAULT_POLL))
    self._queue = sched.RequestQueue()
    self._lock = threading.Lock()
    self._requests = {}                    # rid -> Request (in flight or done)
    self._slots: List[Optional[sched.Request]] = [None] * num_slots
    self._slabs = None                     # built lazily on start()
    self._last = np.full((num_slots,), self.pad_id, np.int32)
    self._stop_evt = threading.Event()
    self._thread: Optional[threading.Thread] = None
    self._loop_error: Optional[BaseException] = None
    self.stats = {"steps": 0, "live_slot_steps": 0, "emitted_tokens": 0,
                  "prefills": 0, "completed": 0}
    # obs seam (docs/OBSERVABILITY.md): cached handles; disabled = one
    # None check per decode dispatch
    self._rec = obs_spans.active()
    reg = obs_metrics.active()
    self._obs_m = None if reg is None else {
        "tokens": reg.counter("serve.tokens"),
        "completed": reg.counter("serve.completed"),
        "prefills": reg.counter("serve.prefills"),
        "steps": reg.counter("serve.steps"),
        "occupancy": reg.gauge("serve.occupancy"),
        "queue_depth": reg.gauge("serve.queue_depth"),
        "slots_active": reg.gauge("serve.slots_active"),
        "decode_ms": reg.histogram("serve.decode_ms"),
    }

  def stats_snapshot(self) -> obs_metrics.StatsSnapshot:
    """Subtraction baseline over the LIVE ``stats`` dict — the safe way
    to read per-pass deltas while the loop thread keeps mutating it
    (obs.metrics.StatsSnapshot; serve_bench uses this)."""
    return obs_metrics.snapshot_stats(self.stats)

  # -- lifecycle ------------------------------------------------------------

  @property
  def num_slots(self) -> int:
    return self.decoder.num_slots

  def start(self) -> "ServingEngine":
    if self._thread is not None and self._thread.is_alive():
      return self
    self._stop_evt.clear()
    self._loop_error = None
    if self._slabs is None:
      self._slabs = self.decoder.init_slabs()
    self._thread = threading.Thread(target=self._loop, daemon=True,
                                    name="tos-serving-engine")
    self._thread.start()
    return self

  def stop(self, timeout: float = 30.0) -> None:
    """Stop the loop thread; queued-but-unstarted requests are failed."""
    self._stop_evt.set()
    t = self._thread
    if t is not None:
      t.join(timeout=timeout)
      if t.is_alive():
        logger.warning("serving loop did not stop within %.1fs", timeout)
    err = RuntimeError("serving engine stopped")
    for req in self._queue.drain():
      req.finish(err)
    with self._lock:
      live = [r for r in self._slots if r is not None]
      self._slots = [None] * self.num_slots
    for req in live:
      if not req.done.is_set():
        req.finish(err)
    self._slabs = None                     # next start() gets a fresh slab

  def __enter__(self):
    return self.start()

  def __exit__(self, *exc):
    self.stop()

  # -- client API -----------------------------------------------------------

  def submit(self, prompt, max_new_tokens: Optional[int] = None) -> int:
    """Queue one prompt; returns the request id."""
    budget = int(max_new_tokens if max_new_tokens is not None
                 else self.default_max_new_tokens)
    if budget < 1:
      raise ValueError("max_new_tokens must be >= 1, got %d" % budget)
    req = sched.Request(prompt, budget)
    if len(req.prompt) < 1:
      # reject here, not in the loop thread: a chunk_plan(0) crash there
      # would take every other in-flight request down with it
      raise ValueError("prompt must contain at least one token")
    if len(req.prompt) + budget > self.cfg.max_seq_len:
      raise ValueError(
          "prompt of %d tokens + budget %d exceeds the max_seq_len=%d "
          "slot cache" % (len(req.prompt), budget, self.cfg.max_seq_len))
    if self._loop_error is not None:
      raise RuntimeError("serving loop died") from self._loop_error
    with self._lock:
      self._requests[req.rid] = req
    self._queue.push(req)
    return req.rid

  def _req(self, rid: int) -> sched.Request:
    with self._lock:
      try:
        return self._requests[rid]
      except KeyError:
        raise KeyError("unknown request id %r" % (rid,))

  def request(self, rid: int) -> sched.Request:
    """The live Request handle (timing/latency fields ride on it).

    Hold the handle before calling :meth:`result`/:meth:`poll` — those
    pop the registry entry once the output is delivered."""
    return self._req(rid)

  def poll(self, rid: int) -> Optional[np.ndarray]:
    """The finished output (prompt + generated), or None if in flight."""
    req = self._req(rid)
    if not req.done.is_set():
      return None
    return self._result_of(req, pop=True)

  def result(self, rid: int, timeout: float = 600.0) -> np.ndarray:
    """Block (bounded) for one request's output."""
    req = self._req(rid)
    if not req.done.wait(timeout=timeout):
      raise TimeoutError("request %d not finished within %.1fs"
                         % (rid, timeout))
    return self._result_of(req, pop=True)

  def _result_of(self, req: sched.Request, pop: bool) -> np.ndarray:
    if pop:
      with self._lock:
        self._requests.pop(req.rid, None)
    if req.error is not None:
      raise RuntimeError("request %d failed" % req.rid) from req.error
    return req.output()

  def stream(self, rid: int, timeout: float = 600.0):
    """Yield generated tokens as they are produced (EOS inclusive)."""
    req = self._req(rid)
    deadline = time.monotonic() + timeout
    emitted = 0
    while True:
      remaining = deadline - time.monotonic()
      if remaining <= 0:
        raise TimeoutError("stream for request %d stalled" % rid)
      try:
        tok = req.stream_q.get(timeout=min(remaining, self._poll * 10))
      except std_queue.Empty:
        continue
      if tok is None:
        break
      emitted += 1
      yield tok
    with self._lock:
      self._requests.pop(rid, None)
    if req.error is not None:
      raise RuntimeError("request %d failed after %d token(s)"
                         % (rid, emitted)) from req.error

  def generate(self, prompts: Sequence,
               max_new_tokens: Optional[int] = None,
               timeout: float = 600.0) -> List[np.ndarray]:
    """Submit a batch of prompts and wait for all outputs (in order)."""
    rids = [self.submit(p, max_new_tokens=max_new_tokens) for p in prompts]
    deadline = time.monotonic() + timeout
    outs = []
    for rid in rids:
      outs.append(self.result(rid, timeout=max(0.001,
                                               deadline - time.monotonic())))
    return outs

  @property
  def alive(self) -> bool:
    """False once the loop thread has died on an error — callers holding
    a cached engine must rebuild instead of reusing a dead one."""
    return self._loop_error is None

  @property
  def occupancy(self) -> float:
    """Live-slot fraction over all decode steps so far (goodput proxy)."""
    steps = self.stats["steps"]
    if not steps:
      return 0.0
    return self.stats["live_slot_steps"] / float(steps * self.num_slots)

  # -- engine loop ----------------------------------------------------------

  def _loop(self) -> None:
    try:
      while not self._stop_evt.is_set():
        self._admit()
        if not any(r is not None for r in self._slots):
          # idle: bounded block until work arrives (TOS001)
          self._queue.wait_nonempty(timeout=self._poll)
          continue
        self._decode_once()
    except BaseException as e:  # noqa: BLE001 - forwarded to every waiter
      self._loop_error = e
      logger.exception("serving loop died")
      for req in self._queue.drain():
        req.finish(e)
      with self._lock:
        live = [r for r in self._slots if r is not None]
        self._slots = [None] * self.num_slots
      for req in live:
        req.finish(e)

  def _admit(self) -> None:
    """Prefill queued requests into free slots (EOS-freed or virgin)."""
    for slot in range(self.num_slots):
      if self._slots[slot] is not None:
        continue
      req = self._queue.pop_nowait()
      if req is None:
        return
      req.started_at = time.monotonic()
      cm = self._rec.span("serve.prefill", rid=req.rid,
                          prompt_len=len(req.prompt), slot=slot) \
          if self._rec is not None else contextlib.nullcontext()
      with cm:
        row_cache, first = self.decoder.prefill(self.params, req.prompt,
                                                self.buckets)
      self.stats["prefills"] += 1
      if self._obs_m is not None:
        self._obs_m["prefills"].inc()
      req.emit(first)
      self.stats["emitted_tokens"] += 1
      if self._finished(req, first):
        self._complete(req)
        continue                 # slot stays free for the next request
      self._slabs = self.decoder.insert(self._slabs, row_cache, slot)
      with self._lock:
        self._slots[slot] = req
      self._last[slot] = first

  def _finished(self, req: sched.Request, token: int) -> bool:
    if self.eos_id is not None and int(token) == self.eos_id:
      return True
    return len(req.tokens) >= req.max_new_tokens

  def _complete(self, req: sched.Request) -> None:
    self.stats["completed"] += 1
    if self._obs_m is not None:
      self._obs_m["completed"].inc()
    req.finish(None)

  def _decode_once(self) -> None:
    """One fused ``horizon``-step dispatch + host-side harvest.

    The device scan carries each lane's EOS/budget done-mask; the host
    replays the identical stop rule over the returned ``[horizon,
    num_slots]`` token matrix, so the two views cannot diverge. A lane
    that stops mid-horizon idles (frozen) for the remaining scan steps —
    the bounded price of amortizing dispatch over the horizon."""
    obs_on = self._rec is not None or self._obs_m is not None
    t0 = time.monotonic() if obs_on else 0.0
    tokens_before = self.stats["emitted_tokens"]
    active = np.asarray([r is not None for r in self._slots], bool)
    remaining = np.asarray(
        [0 if r is None else r.max_new_tokens - len(r.tokens)
         for r in self._slots], np.int32)
    self._slabs, toks, _, _ = self.decoder.step_many(
        self.params, self._slabs, self._last, active, remaining,
        self.horizon)
    toks = np.asarray(toks)                       # [horizon, num_slots]
    self.stats["steps"] += self.horizon
    for slot in range(self.num_slots):
      req = self._slots[slot]
      if req is None:
        continue
      for j in range(self.horizon):
        tok = int(toks[j, slot])
        req.emit(tok)
        self.stats["emitted_tokens"] += 1
        self.stats["live_slot_steps"] += 1
        if self._finished(req, tok):
          self._complete(req)
          with self._lock:
            self._slots[slot] = None
          self._last[slot] = self.pad_id
          break
      else:
        self._last[slot] = int(toks[self.horizon - 1, slot])
    if obs_on:
      dt = time.monotonic() - t0
      live = sum(1 for r in self._slots if r is not None)
      if self._rec is not None:
        self._rec.record_span("serve.decode", t0, dt,
                              horizon=self.horizon,
                              active=int(active.sum()))
      m = self._obs_m
      if m is not None:
        m["steps"].inc(self.horizon)
        m["tokens"].inc(self.stats["emitted_tokens"] - tokens_before)
        m["decode_ms"].observe(dt * 1e3)
        m["occupancy"].set(self.occupancy)
        m["queue_depth"].set(len(self._queue))
        m["slots_active"].set(live)
