"""Executor-side half of the cross-host serving plane: the ServingHost.

Runs a REAL :class:`~.engine.ServingEngine` inside an executor process
and speaks to the driver exclusively over the rendezvous wire — one
``SHREG`` to announce itself, then an ``SHSYNC`` round every
``TOS_HOST_SYNC`` seconds that pushes request events (admission
verdicts, token deltas, completions) and load stats, and pulls queued
commands (submit/stage/cancel/build/drain/stop/kill) from the
driver-side :class:`~.remote.ServingHostPlane`. The driver never dials
the host: executors routinely live behind NAT/overlay networks where
only the rendezvous server is addressable (the reference's
executor→driver reservation flow), so the host polls — at 20 ms
cadence the extra token latency is well under a decode step.

Wire discipline mirrors the driver side: token pushes and command
pulls are budgeted to ``TOS_HOST_CHUNK`` payload tokens per frame, and
staged prompt parts are reassembled here — no frame approaches the
rendezvous server's 4 MB refusal threshold.

Exactly-once across retries: every token event carries its stream
position (``pos`` = index of its first token), so a resend after a
dropped/failed sync is idempotent — the driver-side mirror applies
only the suffix beyond what it already holds. That is what keeps
failover replay BIT-identical and stream positions exactly-once even
when the wire itself is flaky (docs/ROBUSTNESS.md §Cross-host
serving).

The engine is built host-side from the :class:`~.registry.ModelRegistry`
at ``registry_root`` — the host watches for the commanded version to
COMMIT in its own filesystem view and reconstructs the
:class:`TransformerConfig` from the manifest's ``extra["model_cfg"]``
(dtype travels as a string name) — so ``deploy.py`` canary/promote
drives version swaps on machines the driver doesn't share a live
params pytree with. ``cfg_wire`` is the publisher-side helper that
makes a config manifest-safe.

Chaos: each sync round consults ``chaos.host_fault("sync", host_id)``
(``TOS_CHAOS_HOST``): ``kill`` SIGKILLs this whole process — no
cleanup, the wire just goes silent, exactly like a preempted host;
``partition`` keeps the engine decoding but skips all wire I/O for the
spec'd duration; ``stall`` sleeps the loop inline. A second point,
``decode``, ticks only on rounds with requests in flight — so
``decode@K#N:kill`` lands mid-decode by construction, however long the
engine build/warm took (the ``TOS_CHAOS_SERVE`` ``decode#N`` precedent
at host granularity).
"""

import collections
import dataclasses
import logging
import os
import queue as std_queue
import signal
import threading
import time
from typing import Any, Callable, Dict, Optional

import numpy as np

from tensorflowonspark_tpu.control import rendezvous
from tensorflowonspark_tpu.serving import remote as remote_mod
from tensorflowonspark_tpu.utils import chaos

logger = logging.getLogger(__name__)

#: seconds between SHSYNC rounds (the host's wire cadence — also the
#: worst-case added latency per token hop and per command pickup)
ENV_HOST_SYNC = "TOS_HOST_SYNC"
#: bound on a host-side engine build: registry-commit wait + params
#: load + engine start must finish within this
ENV_HOST_BUILD = "TOS_HOST_BUILD_TIMEOUT"

_DEFAULT_SYNC = 0.02
_DEFAULT_BUILD = 120.0

_DTYPE_NAMES = ("float32", "bfloat16", "float16", "float64")


def cfg_wire(cfg) -> dict:
  """A ``TransformerConfig`` as a manifest-safe dict (``dtype`` becomes
  its string name) — what publishers put in
  ``registry.publish(..., extra={"model_cfg": cfg_wire(cfg)})`` so a
  ServingHost can rebuild the config in another process."""
  d = dataclasses.asdict(cfg)
  dt = d.get("dtype")
  if dt is not None and not isinstance(dt, str):
    d["dtype"] = np.dtype(dt).name
  return d


def build_engine_from_manifest(params, manifest: dict,
                               overrides: Optional[dict] = None):
  """Reconstruct a ServingEngine from a registry manifest: the config
  from ``extra["model_cfg"]``, engine options from
  ``extra["serve_opts"]`` with host-local ``overrides`` winning."""
  # jax-heavy imports stay inside the function: this module must be
  # importable (and the spawn entry reachable) before the host process
  # has decided its platform env
  import jax.numpy as jnp
  from tensorflowonspark_tpu.models import transformer as tfm
  from tensorflowonspark_tpu.serving import engine as engine_mod
  extra = (manifest or {}).get("extra") or {}
  cfg_d = dict(extra.get("model_cfg") or {})
  if not cfg_d:
    raise RuntimeError(
        "manifest lacks extra['model_cfg'] — publish with "
        "extra={'model_cfg': host.cfg_wire(cfg)} so serving hosts can "
        "rebuild the model config cross-process")
  name = cfg_d.pop("dtype", "float32")
  if name not in _DTYPE_NAMES:
    raise RuntimeError("unknown model dtype %r in manifest (known: %s)"
                       % (name, list(_DTYPE_NAMES)))
  cfg = tfm.TransformerConfig(dtype=getattr(jnp, name), **cfg_d)
  opts = dict(extra.get("serve_opts") or {})
  opts.update(overrides or {})
  return engine_mod.ServingEngine(params, cfg, **opts)


class ServingHost(object):
  """One executor-resident serving replica runtime.

  ``run()`` blocks in the sync loop until a ``kill``/``exit`` command
  (or ``stop_event``). All engine-blocking commands (build/drain/stop/
  kill) execute on a serial worker thread so the wire never goes
  silent behind a long drain — driver-side host-death detection keys
  purely on sync staleness.
  """

  def __init__(self, server_addr, host_id: int,
               registry_root: Optional[str] = None,
               make_engine: Optional[Callable] = None,
               build_opts: Optional[dict] = None,
               sync_interval: Optional[float] = None,
               build_timeout: Optional[float] = None,
               client_timeout: float = 10.0,
               chunk: Optional[int] = None):
    self.server_addr = (server_addr[0], int(server_addr[1]))
    self.host_id = int(host_id)
    self.registry_root = registry_root
    #: test/extension hook: ``make_engine(version) -> engine`` (or
    #: ``(engine, version)``) replaces the registry build path
    self.make_engine = make_engine
    self.build_opts = dict(build_opts or {})
    self.sync_interval = float(
        sync_interval if sync_interval is not None
        else os.environ.get(ENV_HOST_SYNC, str(_DEFAULT_SYNC)))
    self.build_timeout = float(
        build_timeout if build_timeout is not None
        else os.environ.get(ENV_HOST_BUILD, str(_DEFAULT_BUILD)))
    self.client_timeout = float(client_timeout)
    self.chunk = max(256, int(
        chunk if chunk is not None
        else os.environ.get(remote_mod.ENV_HOST_CHUNK,
                            str(remote_mod._DEFAULT_CHUNK))))
    self.engine = None
    self.generation = 0
    self.version: Optional[int] = None
    self._outbox: collections.deque = collections.deque()
    self._olock = threading.Lock()
    #: tid -> {"handle": engine request, "sent": tokens shipped}
    self._track: Dict[int, dict] = {}
    #: tid -> [staged prompt parts] awaiting the submit command
    self._staging: Dict[int, list] = {}
    self._work: std_queue.Queue = std_queue.Queue()
    self._stop_event = threading.Event()
    self.stats = {"syncs": 0, "sync_failures": 0, "commands": 0,
                  "events": 0, "builds": 0, "partitions": 0,
                  "requeues": 0}

  # -- event plumbing --------------------------------------------------------

  def _emit(self, ev: dict) -> None:
    with self._olock:
      self._outbox.append(ev)

  def _pop_events(self):
    """Pop outbox events up to the per-frame chunk budget, preserving
    order (a ``done`` never overtakes its tokens)."""
    out, budget = [], self.chunk
    with self._olock:
      while self._outbox:
        ev = self._outbox[0]
        cost = len(ev.get("toks") or ())
        if out and cost > budget:
          break
        out.append(self._outbox.popleft())
        budget -= cost
        if budget <= 0 or len(out) >= 64:
          break
    return out

  def _requeue(self, events) -> None:
    """Put unacked events back at the FRONT (position-stamped token
    events make the eventual resend idempotent driver-side)."""
    if not events:
      return
    with self._olock:
      self._outbox.extendleft(reversed(events))
    self.stats["requeues"] += 1

  # -- command execution -----------------------------------------------------

  def _apply(self, cmd: dict) -> None:
    op = cmd.get("op")
    self.stats["commands"] += 1
    if op == "submit":
      self._do_submit(cmd)
    elif op == "stage":
      self._staging.setdefault(int(cmd["tid"]), []).append(
          (int(cmd.get("seq", 0)), cmd.get("part") or []))
    elif op == "cancel":
      t = self._track.get(cmd.get("tid"))
      if t is not None:
        t["handle"].cancelled.set()
    elif op == "build":
      # _work is unbounded: put_nowait never blocks the sync loop
      self._work.put_nowait(lambda: self._do_build(cmd.get("bid"),
                                                   cmd.get("version")))
    elif op == "drain":
      self._work.put_nowait(
          lambda: self._do_drain(cmd.get("did"),
                                 float(cmd.get("timeout", 30.0))))
    elif op == "stop":
      self._work.put_nowait(
          lambda: self._do_stop(cmd.get("sid"),
                                float(cmd.get("timeout", 30.0))))
    elif op == "kill":
      self._work.put_nowait(lambda: self._do_kill(cmd.get("cause")))
    elif op == "exit":
      self._stop_event.set()
    else:
      logger.warning("serving host %d: unknown command %r",
                     self.host_id, op)

  def _do_submit(self, cmd: dict) -> None:
    tid = int(cmd["tid"])
    try:
      if self.engine is None:
        raise RuntimeError("serving host %d has no engine (not built)"
                           % self.host_id)
      if cmd.get("staged"):
        parts = self._staging.pop(tid, [])
        if len(parts) != int(cmd["staged"]):
          raise RuntimeError(
              "staged prompt for request %d incomplete: %d/%d parts"
              % (tid, len(parts), int(cmd["staged"])))
        prompt = [t for _, part in sorted(parts) for t in part]
      else:
        prompt = cmd.get("prompt") or []
      hrid = self.engine.submit(
          np.asarray(prompt, np.int32),
          max_new_tokens=int(cmd["max_new_tokens"]),
          ttl=cmd.get("ttl"), trace_id=cmd.get("trace_id"))
    except BaseException as e:  # noqa: BLE001 - every admission failure
      # (overload, validation, dead engine) becomes a structured 'rej'
      self._emit({"ev": "rej", "tid": tid,
                  "error": remote_mod.encode_error(e)})
      return
    self._track[tid] = {"handle": self.engine.request(hrid), "sent": 0}
    self._emit({"ev": "acc", "tid": tid})

  def _harvest(self) -> None:
    """Ship new tokens (position-stamped) and completions for every
    tracked request; runs every sync round on the loop thread."""
    for tid in list(self._track):
      t = self._track[tid]
      h = t["handle"]
      done = h.done.is_set()  # read BEFORE tokens: the engine appends
      # the final token before setting done, so done==True means
      # h.tokens is complete
      toks = h.tokens
      if len(toks) > t["sent"]:
        self._emit({"ev": "tok", "tid": tid, "pos": t["sent"],
                    "toks": [int(x) for x in toks[t["sent"]:]]})
        t["sent"] = len(toks)
      if done:
        err = h.error
        self._emit({"ev": "done", "tid": tid,
                    "error": None if err is None
                    else remote_mod.encode_error(err)})
        del self._track[tid]

  # -- blocking ops (serial worker thread) -----------------------------------

  def _worker_loop(self) -> None:
    while not self._stop_event.is_set():
      try:
        thunk = self._work.get(timeout=0.2)
      except std_queue.Empty:
        continue
      try:
        thunk()
      except Exception:  # noqa: BLE001 # tosa: ignore[TOS004] - every op
        # ships its own structured failure event (built/drained/stopped
        # with ok=False or error) which the DRIVER raises; the worker
        # thread must survive for the next command
        logger.warning("serving host %d worker op failed", self.host_id,
                       exc_info=True)

  def _do_build(self, bid, version) -> None:
    self.stats["builds"] += 1
    try:
      # stop the previous generation first (a build commanded by the
      # swap/deploy flow follows a drain, so this is idempotent; it also
      # frees the old engine's slab before the new one allocates)
      if self.engine is not None:
        try:
          self.engine.stop(timeout=5.0)
        except Exception:  # noqa: BLE001 # tosa: ignore[TOS004] - the old
          # generation may already be dead; the build result is what the
          # driver observes, shipped via the 'built' event either way
          pass
        self.engine = None
      eng, v = self._build_engine(version)
      eng.start()
      self.generation += 1
      self.engine, self.version = eng, v
      self._emit({"ev": "built", "bid": bid, "ok": True,
                  "generation": self.generation, "version": v,
                  "meta": {"default_max_new_tokens":
                           int(eng.default_max_new_tokens)}})
    except Exception as e:  # noqa: BLE001 - structured failure ack; the
      # driver-side start() raises it as a build failure
      logger.warning("serving host %d engine build failed: %s",
                     self.host_id, e)
      self._emit({"ev": "built", "bid": bid, "ok": False,
                  "error": "%s: %s" % (type(e).__name__, e)})

  def _build_engine(self, version):
    if self.make_engine is not None:
      result = self.make_engine(version)
      return result if isinstance(result, tuple) else (result, version)
    if self.registry_root is None:
      raise RuntimeError("serving host %d has neither registry_root nor "
                         "make_engine — nothing to build from"
                         % self.host_id)
    from tensorflowonspark_tpu.serving import registry as registry_mod
    reg = registry_mod.ModelRegistry(self.registry_root)
    deadline = time.monotonic() + self.build_timeout
    v = None if version is None else int(version)
    # wait for the commanded version (or any first version) to COMMIT in
    # THIS host's filesystem view — the cross-process registry watch
    while True:
      have = reg.versions()
      if v is None and have:
        v = max(have)
        break
      if v is not None and v in have:
        break
      if time.monotonic() >= deadline:
        raise RuntimeError(
            "version %s not committed in registry %r within %.1fs"
            % ("latest" if v is None else v, self.registry_root,
               self.build_timeout))
      time.sleep(0.05)
    params, manifest = reg.get(v)
    return build_engine_from_manifest(params, manifest, self.build_opts), v

  def _do_drain(self, did, timeout: float) -> None:
    ok = False
    if self.engine is not None:
      try:
        ok = bool(self.engine.drain(timeout))
      except Exception:  # noqa: BLE001 # tosa: ignore[TOS004] - a drain
        # crash ships as ok=False in the 'drained' event; the driver's
        # swap then treats the replica as failed (its observable contract)
        logger.warning("serving host %d drain failed", self.host_id,
                       exc_info=True)
    self._emit({"ev": "drained", "did": did, "ok": ok})

  def _do_stop(self, sid, timeout: float) -> None:
    if self.engine is not None:
      try:
        self.engine.stop(timeout=timeout)
      except Exception:  # noqa: BLE001 # tosa: ignore[TOS004] - stopping a
        # dead engine is fine; the 'stopped' ack below is the observable
        pass
    self._emit({"ev": "stopped", "sid": sid})

  def _do_kill(self, cause) -> None:
    if self.engine is not None:
      try:
        self.engine.kill(RuntimeError(str(cause or "killed over the wire")))
      except Exception:  # noqa: BLE001 # tosa: ignore[TOS004] - killing an
        # already-dead engine is fine; the driver marked its proxy dead
        # before sending this, so there is no observer to fail
        pass

  # -- wire stats ------------------------------------------------------------

  def _stats_payload(self) -> dict:
    eng = self.engine
    out: Dict[str, Any] = {"generation": self.generation,
                           "version": self.version, "pid": os.getpid()}
    if eng is None:
      out.update(engine_alive=False, loop_error=None, queue_depth=0,
                 queued_tokens=0, tokens_per_sec=0.0, occupancy_now=0.0)
      return out
    try:
      err = eng._loop_error
      out.update(engine_alive=bool(eng.alive),
                 loop_error=None if err is None else str(err),
                 queue_depth=int(eng.queue_depth),
                 queued_tokens=int(eng.queued_tokens),
                 tokens_per_sec=float(eng.tokens_per_sec),
                 occupancy_now=float(eng.occupancy_now))
    except Exception:  # noqa: BLE001 - an engine mid-stop can race its
      # own accounting; a conservative "dead" row beats a crashed host
      out.update(engine_alive=False, loop_error="stats unavailable",
                 queue_depth=0, queued_tokens=0, tokens_per_sec=0.0,
                 occupancy_now=0.0)
    return out

  # -- the loop --------------------------------------------------------------

  def run(self, stop_event: Optional[threading.Event] = None) -> None:
    """Register, then sync until told to exit (blocking)."""
    if stop_event is not None:
      self._stop_event = stop_event
    worker = threading.Thread(target=self._worker_loop, daemon=True,
                              name="tos-host-worker-%d" % self.host_id)
    worker.start()
    client = rendezvous.Client(self.server_addr,
                               timeout=self.client_timeout)
    try:
      self._run_wire(client)
    finally:
      self._stop_event.set()
      try:
        client._request({"type": "SHBYE", "host_id": self.host_id})
      except Exception:  # noqa: BLE001 # tosa: ignore[TOS004] - departing
        # is best-effort: a dead server can't be told goodbye, and the
        # plane's staleness timeout covers an unsent SHBYE anyway
        pass
      client.close()
      if self.engine is not None:
        try:
          self.engine.stop(timeout=5.0)
        except Exception:  # noqa: BLE001 # tosa: ignore[TOS004] - exit
          # path; the process ends either way and the driver detects the
          # departure via SHBYE/staleness, not via this stop
          pass

  def _register(self, client) -> None:
    reply = client._request({
        "type": "SHREG", "host_id": self.host_id,
        "meta": {"pid": os.getpid(),
                 "registry_root": self.registry_root}})
    if reply.get("type") != "OK":
      raise RuntimeError("serving host %d registration refused: %r"
                         % (self.host_id, reply))
    # adopt the plane's negotiated chunk budget so both directions of
    # the wire obey ONE framing limit
    if reply.get("chunk"):
      self.chunk = int(reply["chunk"])

  def _chaos_point(self, point: str, partition_until: float) -> float:
    fault = chaos.host_fault(point, self.host_id)
    if fault is not None:
      action, secs = fault
      if action == "kill":
        logger.warning("chaos: serving host %d SIGKILLing itself (%s)",
                       self.host_id, point)
        os.kill(os.getpid(), signal.SIGKILL)
      elif action == "partition":
        self.stats["partitions"] += 1
        partition_until = time.monotonic() + float(secs)
    return partition_until

  def _run_wire(self, client) -> None:
    self._register(client)
    partition_until = 0.0
    while not self._stop_event.is_set():
      partition_until = self._chaos_point("sync", partition_until)
      if self._track:
        # ticks only while requests are in flight: a kill spec'd here
        # is guaranteed to interrupt live decodes, whatever the build
        # and jit-warm phases cost in sync rounds
        partition_until = self._chaos_point("decode", partition_until)
      self._harvest()
      if time.monotonic() < partition_until:
        # partitioned: the engine keeps decoding, tokens buffer in the
        # outbox, the wire stays dark — the driver sees pure silence
        time.sleep(self.sync_interval)
        continue
      events = self._pop_events()
      try:
        reply = client._request({"type": "SHSYNC", "host_id": self.host_id,
                                 "events": events,
                                 "stats": self._stats_payload()})
      except Exception as e:  # noqa: BLE001 - transport failure: the server
        # definitely did not apply these events; resend next round
        self.stats["sync_failures"] += 1
        self._requeue(events)
        logger.warning("serving host %d sync failed: %s", self.host_id, e)
        time.sleep(min(0.5, 10 * self.sync_interval))
        continue
      if reply.get("type") != "OK":
        self.stats["sync_failures"] += 1
        # position-stamped events make resending safe even if the plane
        # half-applied before erroring
        self._requeue(events)
        if "unregistered" in str(reply.get("error", "")):
          try:
            self._register(client)
          except Exception:  # noqa: BLE001 # tosa: ignore[TOS004] - keep
            # syncing; every later round retries registration through
            # this same path until the plane answers OK
            pass
        time.sleep(min(0.5, 10 * self.sync_interval))
        continue
      self.stats["syncs"] += 1
      self.stats["events"] += len(events)
      for cmd in reply.get("cmds") or ():
        self._apply(cmd)
      time.sleep(self.sync_interval)


def run_host_thread(server_addr, host_id: int, **kw):
  """Run a ServingHost on a daemon thread in THIS process (the wire is
  still real — sockets, framing, chunking — only the process boundary
  is elided). The cheap tier-1 harness; kill-chaos needs real
  processes via :func:`start_host_process`.

  Returns ``(host, stop)`` where ``stop()`` exits the loop and joins.
  """
  host = ServingHost(server_addr, host_id, **kw)
  stop_event = threading.Event()
  th = threading.Thread(target=host.run, kwargs={"stop_event": stop_event},
                        daemon=True, name="tos-host-%d" % host_id)
  th.start()

  def stop(timeout: float = 10.0) -> None:
    stop_event.set()
    th.join(timeout=timeout)

  return host, stop


def _host_proc_main(server_addr, host_id, registry_root, build_opts,
                    env: Optional[dict]) -> None:
  """Spawn entry for a ServingHost executor process."""
  if env:
    os.environ.update({str(k): str(v) for k, v in env.items()})
  # never let a host process dial the sandbox's remote chip; the parent
  # decides the real platform via inherited env (JAX_PLATFORMS et al.)
  from tensorflowonspark_tpu.utils import platform_env
  platform_env.drop_remote_plugin()
  logging.basicConfig(level=logging.INFO)
  host = ServingHost(tuple(server_addr), int(host_id),
                     registry_root=registry_root, build_opts=build_opts)
  host.run()


def start_host_process(server_addr, host_id: int,
                       registry_root: Optional[str] = None,
                       build_opts: Optional[dict] = None,
                       env: Optional[dict] = None):
  """Spawn a ServingHost in a fresh process (the chaos-killable real
  thing). ``env`` entries are applied in the child before jax's
  backend initializes (chaos knobs, sync cadence, platform pins).
  Returns the started ``multiprocessing.Process``."""
  import multiprocessing as mp
  proc = mp.get_context("spawn").Process(
      target=_host_proc_main,
      args=(list(server_addr), int(host_id), registry_root,
            dict(build_opts or {}), dict(env or {})),
      daemon=True, name="tos-serving-host-%d" % host_id)
  proc.start()
  return proc


def make_serving_host_main(server_addr,
                           registry_root: Optional[str] = None,
                           build_opts: Optional[dict] = None):
  """A ``cluster.run`` main fn that turns each worker into a
  ServingHost (host id = executor id): the L6 "inference as a service
  on executors" deployment — the driver keeps the fleet/deploy
  controllers and drives these hosts over the wire::

      cluster = TPUCluster.run(engine, make_serving_host_main(
          cluster_addr, registry_root="/models"), args, num_executors=N)
  """
  def serving_host_main(args, ctx) -> None:
    del args
    host = ServingHost(tuple(server_addr), int(ctx.executor_id),
                       registry_root=registry_root, build_opts=build_opts)
    host.run()

  return serving_host_main
