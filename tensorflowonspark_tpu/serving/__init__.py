"""Continuous-batching serving runtime (slot-based in-flight decode).

Public surface:

* :class:`~tensorflowonspark_tpu.serving.engine.ServingEngine` — the
  runtime: submit/poll/stream/generate over a persistent slot slab,
  plus the self-healing surface — admission control
  (:class:`ServingOverloaded`), per-request deadlines
  (:class:`DeadlineExceeded`) and ``cancel(rid)``
  (:class:`RequestCancelled`), crash-replay recovery with poison
  detection (:class:`PoisonedRequest`), and graceful ``drain(timeout)``.
* :class:`~tensorflowonspark_tpu.serving.slots.SlotDecoder` /
  :func:`~tensorflowonspark_tpu.serving.slots.chunk_plan` — the jitted
  device ops and the bucketed-prefill policy.
* :class:`~tensorflowonspark_tpu.serving.scheduler.Request` /
  :class:`~tensorflowonspark_tpu.serving.scheduler.RequestQueue` — the
  host-side bookkeeping (bounded, closable admission queue).

See docs/PERFORMANCE.md §Serving for the static-vs-continuous batching
story, docs/ROBUSTNESS.md for the failure model and chaos knobs, and
``tools/serve_bench.py --compare`` / ``--chaos`` for the measurements.
"""

from tensorflowonspark_tpu.serving.engine import (            # noqa: F401
    ENV_SERVE_MAX_QUEUE, ENV_SERVE_MAX_QUEUED_TOKENS, ENV_SERVE_POLL,
    ENV_SERVE_SLOTS, ENV_SERVE_TTL, ServingEngine)
from tensorflowonspark_tpu.serving.scheduler import (         # noqa: F401
    ENV_SERVE_BUCKETS, DeadlineExceeded, PoisonedRequest, Request,
    RequestCancelled, RequestQueue, ServingOverloaded)
from tensorflowonspark_tpu.serving.slots import (             # noqa: F401
    DEFAULT_BUCKETS, SlotDecoder, chunk_plan)
