"""Continuous-batching serving runtime (slot-based in-flight decode).

Public surface:

* :class:`~tensorflowonspark_tpu.serving.engine.ServingEngine` — the
  runtime: submit/poll/stream/generate over a persistent slot slab.
* :class:`~tensorflowonspark_tpu.serving.slots.SlotDecoder` /
  :func:`~tensorflowonspark_tpu.serving.slots.chunk_plan` — the jitted
  device ops and the bucketed-prefill policy.
* :class:`~tensorflowonspark_tpu.serving.scheduler.Request` /
  :class:`~tensorflowonspark_tpu.serving.scheduler.RequestQueue` — the
  host-side bookkeeping.

See docs/PERFORMANCE.md §Serving for the static-vs-continuous batching
story and ``tools/serve_bench.py --compare`` for the measurement.
"""

from tensorflowonspark_tpu.serving.engine import (            # noqa: F401
    ENV_SERVE_POLL, ENV_SERVE_SLOTS, ServingEngine)
from tensorflowonspark_tpu.serving.scheduler import (         # noqa: F401
    ENV_SERVE_BUCKETS, Request, RequestQueue)
from tensorflowonspark_tpu.serving.slots import (             # noqa: F401
    DEFAULT_BUCKETS, SlotDecoder, chunk_plan)
