"""Continuous-batching serving runtime (slot-based in-flight decode).

Public surface:

* :class:`~tensorflowonspark_tpu.serving.engine.ServingEngine` — the
  runtime: submit/poll/stream/generate over a persistent slot slab,
  plus the self-healing surface — admission control
  (:class:`ServingOverloaded`), per-request deadlines
  (:class:`DeadlineExceeded`) and ``cancel(rid)``
  (:class:`RequestCancelled`), crash-replay recovery with poison
  detection (:class:`PoisonedRequest`), and graceful ``drain(timeout)``.
* :class:`~tensorflowonspark_tpu.serving.slots.SlotDecoder` /
  :func:`~tensorflowonspark_tpu.serving.slots.chunk_plan` — the jitted
  device ops and the bucketed-prefill policy.
* :class:`~tensorflowonspark_tpu.serving.scheduler.Request` /
  :class:`~tensorflowonspark_tpu.serving.scheduler.RequestQueue` — the
  host-side bookkeeping (bounded, closable admission queue).
* :class:`~tensorflowonspark_tpu.serving.scheduler.PagePool` /
  :class:`~tensorflowonspark_tpu.serving.scheduler.PrefixCache` — the
  paged-KV host state: the ref-counted page allocator and the
  shared-prefix radix trie (page-granular, LRU-evicted).
* :class:`~tensorflowonspark_tpu.serving.fleet.ServingFleet` — the
  driver-side replica router: load-aware dispatch over N engines,
  retry-with-backoff on overload, health ejection + cross-replica
  failover replay (stream positions exactly-once), and zero-shed
  :meth:`rolling_swap` (docs/ROBUSTNESS.md §Fleet).
* :class:`~tensorflowonspark_tpu.serving.registry.ModelRegistry` — the
  durable train→serve seam: atomic versioned publish (the checkpoint
  commit-marker protocol), ``watch()``, quarantine, ref-counted GC.
* :class:`~tensorflowonspark_tpu.serving.deploy.DeploymentController`
  — SLO-gated canary rollout: CANARY → VERIFY (greedy parity +
  obs/SLO deltas) → PROMOTE or ROLLBACK+quarantine, zero-shed end to
  end, with :meth:`resume` converging the fleet after a controller
  death (docs/ROBUSTNESS.md §Continuous deployment).
* :class:`~tensorflowonspark_tpu.serving.host.ServingHost` /
  :class:`~tensorflowonspark_tpu.serving.remote.RemoteReplica` — the
  cross-host serving plane: executor-resident engines syncing over the
  rendezvous wire (SHREG/SHSYNC/SHBYE) with driver-side replica
  proxies, so the SAME fleet routes/ejects/failover-replays/swaps
  across process boundaries (docs/ROBUSTNESS.md §Cross-host serving).

Decode-speed stack (docs/PERFORMANCE.md §"Paged KV, prefix cache &
speculative decode"): ``TOS_SERVE_PAGE_SIZE`` pages the KV slab,
``TOS_SERVE_PREFIX_PAGES`` turns on prefix sharing over it, and
``TOS_SERVE_SPEC_DEPTH`` enables self-speculative decoding — each stage
independently gated on ``serve_bench`` bit-parity.

See docs/PERFORMANCE.md §Serving for the static-vs-continuous batching
story, docs/ROBUSTNESS.md for the failure model and chaos knobs, and
``tools/serve_bench.py --compare`` / ``--chaos`` for the measurements.
"""

from tensorflowonspark_tpu.serving.engine import (            # noqa: F401
    ENV_SERVE_MAX_QUEUE, ENV_SERVE_MAX_QUEUED_TOKENS, ENV_SERVE_NUM_PAGES,
    ENV_SERVE_PAGE_SIZE, ENV_SERVE_POLL, ENV_SERVE_PREFIX_PAGES,
    ENV_SERVE_SLOTS, ENV_SERVE_SPEC_DEPTH, ENV_SERVE_SPEC_LAYERS,
    ENV_SERVE_TTL, ServingEngine)
from tensorflowonspark_tpu.serving.deploy import (            # noqa: F401
    ENV_DEPLOY_BAKE, ENV_DEPLOY_POLL, ENV_DEPLOY_SLICE,
    ENV_DEPLOY_SPOT_CHECKS, ENV_DEPLOY_SWAP_TIMEOUT,
    ENV_DEPLOY_TTFT_RATIO, ControllerKilled, DeploymentController)
from tensorflowonspark_tpu.serving.host import (              # noqa: F401
    ENV_HOST_BUILD, ENV_HOST_SYNC, ServingHost, build_engine_from_manifest,
    cfg_wire, make_serving_host_main, run_host_thread, start_host_process)
from tensorflowonspark_tpu.serving.remote import (            # noqa: F401
    ENV_HOST_ADMIT, ENV_HOST_CHUNK, ENV_HOST_START, ENV_HOST_TIMEOUT,
    RemoteReplica, RemoteRequest, ServingHostPlane, attach_serving_plane,
    remote_engine_factory, wire_health_probe)
from tensorflowonspark_tpu.serving.fleet import (             # noqa: F401
    ENV_FLEET_ADMIT_TIMEOUT, ENV_FLEET_MAX_FAILOVERS,
    ENV_FLEET_MAX_REPLICAS, ENV_FLEET_POLL, ENV_FLEET_PROBE_FAILS,
    ENV_FLEET_REPLICAS, FleetRequest, Replica, ServingFleet)
from tensorflowonspark_tpu.serving.registry import (          # noqa: F401
    ENV_REGISTRY_KEEP, ENV_REGISTRY_POLL, ModelRegistry)
from tensorflowonspark_tpu.serving.scheduler import (         # noqa: F401
    ENV_SERVE_BUCKETS, DeadlineExceeded, PagePool, PoisonedRequest,
    PrefixCache, Request, RequestCancelled, RequestQueue,
    ServingOverloaded)
from tensorflowonspark_tpu.serving.slots import (             # noqa: F401
    DEFAULT_BUCKETS, SlotDecoder, chunk_plan)
