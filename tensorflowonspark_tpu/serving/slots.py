"""Device side of continuous batching: slot slabs and their jitted ops.

The slab is ONE persistent KV-cache pytree with a fixed slot capacity.
Two layouts exist:

* CONTIGUOUS (default): per layer ``[num_slots, max_seq_len, kv_heads,
  head_dim]`` key/value buffers plus a VECTOR cursor ``index:
  [num_slots]`` (the per-slot-cursor branch of
  ``models.transformer.Attention._decode_attend``). Every slot reserves
  ``max_seq_len`` of HBM whether it needs it or not.
* PAGED (``page_size > 0``): per layer a page POOL ``[num_pages,
  page_size, kv_heads, head_dim]`` plus a per-slot ``page_table
  [num_slots, pages_per_slot] int32`` and the vector cursor — a slot
  holds only the pages its token mass needs, so slot count scales with
  actual tokens instead of ``num_slots × max_seq_len`` worst case
  (``_decode_attend_paged``). Page 0 is the reserved TRASH page; the
  host-side allocator is ``serving.scheduler.PagePool``. Paging also
  unlocks the shared-prefix cache (``serving.scheduler.PrefixCache``):
  requests sharing a prompt prefix fork read-only references to the
  prefix's full pages and prefill only their tail.

Jitted functions owning the slab:

* :meth:`SlotDecoder.prefill` — run one request's prompt through the
  model on a fresh single-row cache, in bucket-sized chunks so the jit
  cache holds at most ``len(buckets)`` prefill shapes. The first chunk
  is a fresh-cache prefill (flash-eligible on TPU); later chunks ride
  the warm-cache ``idx > 0`` dense branch of the same cond.
* :meth:`SlotDecoder.insert` — scatter that row cache into the slab at a
  freed slot (``lax.dynamic_update_slice`` on every leaf) and set the
  slot's cursor to the prompt length.
* :meth:`SlotDecoder.step` — advance ALL live slots one token in one
  fixed-shape call: each slot writes at its own cursor, attends its own
  length, and inactive slots are frozen (their cursor write is undone,
  their emitted token forced to ``pad_id``) so freed capacity costs
  nothing but the lane's arithmetic.
* :meth:`SlotDecoder.step_many` — ``horizon`` of those steps fused into
  one jitted scan that carries the per-slot done-mask (EOS hit / budget
  spent) ON DEVICE: dispatch + host-sync overhead is paid once per
  ``horizon`` tokens instead of per token, at the cost of at most
  ``horizon - 1`` frozen slot-steps per completion (the same
  done-mask mechanics as ``greedy_generate_kv(eos_id=...)``, so the
  emitted stream stays bit-identical).
* :meth:`SlotDecoder.step_spec` — SELF-SPECULATIVE decode
  (``spec_depth > 0``): each fused round drafts ``spec_depth`` tokens
  with a shallow-exit prefix of the model's own layers
  (``Transformer(..., exit_layer=spec_layers)`` — shared params, shared
  slab), rolls the draft layers' cursors back, verifies the whole
  window with ONE full-model multi-token step, and accepts the longest
  per-lane prefix the target agrees with plus the target's own
  correction token. Greedy verification accepts exactly the tokens
  ``greedy_generate_kv`` would emit, so the bit-identical-decode
  contract (crash replay, parity tests) survives the speedup; rejected
  draft entries sit past the rewound per-lane cursor, masked and
  overwritten (the ``_set_cache_cursor`` rollback trick, vectorized).

Everything here is functional — the ``serving.engine.ServingEngine``
thread owns the slab value and the host-side bookkeeping (which slots
are live, per-request budgets/EOS, the page allocator / prefix trie).
"""

import dataclasses
import time
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.tree_util import tree_map_with_path

from tensorflowonspark_tpu.models import transformer as tfm
from tensorflowonspark_tpu.obs import device as obs_device
from tensorflowonspark_tpu.obs import spans as obs_spans
from tensorflowonspark_tpu.utils import chaos

#: prompt-chunk sizes for bucketed prefill, largest-first. The compiled
#: prefill cache holds at most one entry per size, so arbitrary prompt
#: lengths never grow the jit cache unboundedly; 1 must be reachable so
#: every length decomposes.
DEFAULT_BUCKETS = (512, 128, 32, 16, 8, 4, 2, 1)


def chunk_plan(plen: int, buckets: Sequence[int] = DEFAULT_BUCKETS):
  """Decompose a prompt length into descending bucket-sized chunks.

  Greedy largest-first: ``chunk_plan(37, (128, 32, 8, 4, 2, 1))`` →
  ``[32, 4, 1]``. A trailing 1 is appended to the bucket set if missing
  so every positive length has a plan.
  """
  if plen < 1:
    raise ValueError("prompt length must be >= 1, got %d" % plen)
  sizes = sorted({int(b) for b in buckets if int(b) > 0}, reverse=True)
  if not sizes or sizes[-1] != 1:
    sizes.append(1)
  plan, rem = [], plen
  for b in sizes:
    while rem >= b:
      plan.append(b)
      rem -= b
  return plan


def _is_index(path) -> bool:
  return bool(path) and getattr(path[-1], "key", None) == "index"


def _cursor_leaf(slabs):
  """The slab's per-slot cursor vector (the first ``index`` leaf — every
  layer carries the same value in steady state)."""
  from jax.tree_util import tree_flatten_with_path
  for path, leaf in tree_flatten_with_path(slabs)[0]:
    if _is_index(path):
      return leaf
  raise ValueError("slab pytree has no 'index' leaf")


def _with_cursor(slabs, vec):
  """Every layer's cursor set to ``vec`` (vectorized rollback: rejected
  speculative entries sit past the cursor, masked and overwritten — the
  same free-rollback property as ``transformer._set_cache_cursor``)."""
  return tree_map_with_path(
      lambda p, leaf: vec.astype(leaf.dtype) if _is_index(p) else leaf,
      slabs)


class SlotDecoder(object):
  """Jitted slab operations for one (config, num_slots) serving shape.

  Greedy decode only: continuous batching's contract is that every
  request's tokens are bit-identical to its own single-request decode,
  which sampling's batch-shaped rng draw cannot promise.

  ``page_size > 0`` switches the slab to the PAGED layout (``num_pages``
  pool pages of ``page_size`` tokens each, ``pages_per_slot`` table
  entries per slot — defaults cover the contiguous worst case so paging
  alone never shrinks capacity; set ``num_pages`` lower to spend less
  HBM than ``num_slots × max_seq_len``). ``spec_depth > 0`` enables
  :meth:`step_spec` with a ``spec_layers``-deep shallow-exit draft.
  """

  def __init__(self, cfg, num_slots: int, pad_id: int = 0, eos_id=None,
               mesh=None, page_size: int = 0, num_pages: int = 0,
               pages_per_slot: int = 0, spec_depth: int = 0,
               spec_layers: int = 0):
    if num_slots < 1:
      raise ValueError("num_slots must be >= 1, got %d" % num_slots)
    self.cfg = cfg
    self.num_slots = num_slots
    self.pad_id = int(pad_id)
    self.eos_id = None if eos_id is None else int(eos_id)
    self.mesh = mesh
    self.page_size = int(page_size)
    self.paged = self.page_size > 0
    if self.paged:
      pps = int(pages_per_slot) or -(-cfg.max_seq_len // self.page_size)
      pool = int(num_pages) or num_slots * pps + 1
      self.pages_per_slot = pps
      self.num_pages = pool
      # the slab model carries the paged cache layout in its config (the
      # jit-cache key), while prefill keeps the contiguous row layout
      self.slab_cfg = dataclasses.replace(
          cfg, kv_page_size=self.page_size, kv_num_pages=pool,
          kv_pages_per_slot=pps)
    else:
      self.pages_per_slot = 0
      self.num_pages = 0
      self.slab_cfg = cfg
    self.spec_depth = int(spec_depth)
    if self.spec_depth < 0:
      raise ValueError("spec_depth must be >= 0, got %d" % self.spec_depth)
    self.spec_layers = int(spec_layers) or max(1, cfg.num_layers // 2)
    if self.spec_depth and not 1 <= self.spec_layers <= cfg.num_layers:
      raise ValueError(
          "spec_layers must be in [1, num_layers=%d], got %d"
          % (cfg.num_layers, self.spec_layers))
    self.model = tfm.Transformer(cfg, mesh=mesh)
    self.slab_model = tfm.Transformer(self.slab_cfg, mesh=mesh) \
        if self.paged else self.model
    # jit caches retrace per chunk shape (bounded by the bucket set) /
    # once for insert+step (fixed slab shapes)
    self._prefill_fn = jax.jit(self._prefill_impl)
    self._insert_fn = jax.jit(self._insert_impl)
    self._insert_pages_fn = jax.jit(self._insert_pages_impl)
    self._gather_pages_fn = jax.jit(self._gather_pages_impl)
    self._reset_slots_fn = jax.jit(self._reset_slots_impl)
    self._step_fn = jax.jit(self._step_impl)
    self._step_many_jits = {}    # horizon -> jitted fused-scan step
    self._step_spec_jits = {}    # rounds -> jitted fused spec-round scan
    self._zero_row = None        # memoized fresh [1, ...] cache (immutable)

  # -- slab construction ----------------------------------------------------

  def init_slabs(self):
    """A fresh all-zeros slab with VECTOR per-slot cursors (paged slabs
    are born vector-cursored with their page tables all-trash)."""
    cache = tfm._zero_cache(self.slab_model, self.num_slots)
    if self.paged:
      return cache                 # index is already [num_slots]

    def widen(path, leaf):
      if _is_index(path):
        return jnp.zeros((self.num_slots,), leaf.dtype)
      return leaf

    return tree_map_with_path(widen, cache)

  # -- prefill (single row, bucketed chunks) --------------------------------

  def _prefill_impl(self, params, cache, tokens):
    # recompile sentinel seam: fires once per (re)trace — the prefill jit
    # cache must stay bounded by the bucket set (obs/device.py)
    obs_device.note_trace("serve.prefill")
    logits, mutated = self.model.apply(
        {"params": params, "cache": cache}, tokens, decode=True,
        mutable=["cache"])
    nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
    return mutated["cache"], nxt

  def prefill(self, params, prompt, buckets: Sequence[int] = DEFAULT_BUCKETS,
              resume=None, trace=None) -> Tuple[object, int]:
    """Prefill one prompt into a fresh [1, ...] row cache.

    Returns ``(row_cache, first_token)``: the warm cache (cursor at
    ``len(prompt)``) and the first generated token g1. Chunks follow
    :func:`chunk_plan`, so only the LAST chunk's logits matter.

    ``resume=(row_cache, start)`` skips the first ``start`` prompt
    tokens: the given warm cache already holds their KV (the
    shared-prefix path — ``gather_pages`` rebuilds such a cache from
    cached pool pages), so only the tail rides the chunked prefill.
    ``start`` must leave at least one tail token (the last prompt token
    must run through the model to yield g1).

    ``trace`` (a request trace id) turns on per-chunk
    ``serve.prefill.chunk`` spans when the obs recorder is live — the
    bucketed-decomposition phase of the request waterfall. Chunk
    dispatches are async (only the final ``int(nxt[0])`` syncs), so a
    chunk span measures dispatch-to-dispatch time; the enclosing
    ``serve.prefill`` span carries the true synced total.
    """
    plen = len(prompt)
    if plen + 1 > self.cfg.max_seq_len:
      raise ValueError(
          "prompt of %d tokens leaves no decode room in the "
          "max_seq_len=%d cache" % (plen, self.cfg.max_seq_len))
    # deterministic fault site (TOS_CHAOS_SERVE, docs/ROBUSTNESS.md):
    # raise-or-stall here stands in for a device failure during prefill.
    # The index is the prompt length — the one identity a spec can pin
    # before request ids exist (per-length specs make poison requests)
    chaos.serve_fault("prefill", index=plen)
    if resume is not None:
      cache, off = resume
      off = int(off)
      if not 0 <= off < plen:
        raise ValueError(
            "prefill resume offset %d must be in [0, prompt_len=%d)"
            % (off, plen))
    else:
      if self._zero_row is None:
        # memoized: model.init is a full trace, far too slow to pay per
        # admitted request; jax arrays are immutable so one zero pytree
        # serves every prefill
        self._zero_row = tfm._zero_cache(self.model, 1)
      cache, off = self._zero_row, 0
    prompt = jnp.asarray(prompt, jnp.int32).reshape(1, plen)
    rec = obs_spans.active() if trace is not None else None
    nxt = None
    for seg in chunk_plan(plen - off, buckets):
      t0 = time.monotonic()
      cache, nxt = self._prefill_fn(
          params, cache, lax.dynamic_slice(prompt, (0, off), (1, seg)))
      if rec is not None:
        rec.record_span("serve.prefill.chunk", t0,
                        time.monotonic() - t0, trace=trace,
                        chunk=seg, offset=off)
      off += seg
    return cache, int(nxt[0])

  # -- slot insert ----------------------------------------------------------

  def _insert_impl(self, slabs, row, slot):
    obs_device.note_trace("serve.insert")

    def ins(s, r):
      if r.ndim == s.ndim:        # [1, ...] row leaf into [S, ...] slab
        return lax.dynamic_update_slice(
            s, r.astype(s.dtype), (slot,) + (0,) * (s.ndim - 1))
      # scalar cursor -> one element of the vector cursor
      return lax.dynamic_update_slice(
          s, r.astype(s.dtype).reshape(1), (slot,))

    return jax.tree.map(ins, slabs, row)

  def insert(self, slabs, row_cache, slot: int):
    """Write a prefilled row cache into slab position ``slot``."""
    return self._insert_fn(slabs, row_cache, jnp.asarray(slot, jnp.int32))

  # -- paged slab ops --------------------------------------------------------

  def _each_attn(self, slabs, row):
    """Yield matching (slab attn-cache dict, row attn-cache dict) pairs —
    the paged slab and the contiguous row cache have different leaf sets,
    so tree_map cannot pair them; this walks the shared dict spine."""
    if isinstance(slabs, dict) and "pages_k" in slabs:
      yield slabs, row
      return
    for key in slabs:
      for pair in self._each_attn(slabs[key],
                                  None if row is None else row[key]):
        yield pair

  def _map_attn(self, slabs, row, fn):
    """Rebuild ``slabs`` with ``fn(slab_attn, row_attn)`` applied at every
    attention-cache node (the dict holding ``pages_k``)."""
    if isinstance(slabs, dict) and "pages_k" in slabs:
      return fn(slabs, row)
    return {k: self._map_attn(slabs[k],
                              None if row is None else row[k], fn)
            for k in slabs}

  def _insert_pages_impl(self, slabs, row, slot, pages, start):
    """Scatter a prefilled row cache into pool pages.

    ``pages[i]`` receives prompt tokens ``[i·page_size, (i+1)·page_size)``;
    positions below ``start`` (already resident in shared prefix pages)
    and at/after the row's cursor are routed to the trash page. Sets the
    slot's page-table row and cursor as part of the same dispatch.
    """
    obs_device.note_trace("serve.insert_pages")
    ps, pp = self.page_size, self.pages_per_slot
    max_len = self.cfg.max_seq_len
    pos = jnp.arange(max_len)

    def ins(att_s, att_r):
      plen = att_r["index"].astype(jnp.int32)            # row cursor
      valid = jnp.logical_and(pos >= start, pos < plen)
      pg = jnp.where(valid, pages[jnp.clip(pos // ps, 0, pp - 1)], 0)
      off = pos % ps
      new = dict(att_s)
      new["pages_k"] = att_s["pages_k"].at[pg, off].set(
          att_r["cached_k"][0].astype(att_s["pages_k"].dtype))
      new["pages_v"] = att_s["pages_v"].at[pg, off].set(
          att_r["cached_v"][0].astype(att_s["pages_v"].dtype))
      new["page_table"] = att_s["page_table"].at[slot].set(pages)
      new["index"] = att_s["index"].at[slot].set(plen)
      return new

    return self._map_attn(slabs, row, ins)

  def insert_pages(self, slabs, row_cache, slot: int, pages, start: int = 0):
    """Paged insert: write ``row_cache`` into ``pages`` (a
    ``pages_per_slot``-long int32 list, unused tail entries 0/trash) for
    slab position ``slot``, skipping the first ``start`` tokens (they
    live in shared read-only prefix pages the table also names)."""
    return self._insert_pages_fn(slabs, row_cache,
                                 jnp.asarray(slot, jnp.int32),
                                 jnp.asarray(pages, jnp.int32),
                                 jnp.asarray(start, jnp.int32))

  def _gather_pages_impl(self, slabs, pages, n_tokens):
    """Rebuild a contiguous [1, ...] row cache holding ``n_tokens``
    prefix tokens gathered from pool ``pages`` — the warm cache a
    shared-prefix tail prefill resumes from. Positions at/after
    ``n_tokens`` are garbage but sit past the cursor (masked, then
    overwritten by the tail prefill's writes before they are attended).
    """
    obs_device.note_trace("serve.gather_pages")
    ps, pp = self.page_size, self.pages_per_slot
    max_len = self.cfg.max_seq_len
    take = min(pp * ps, max_len)

    def build(att_s, _):
      hk, d = att_s["pages_k"].shape[-2:]
      row = {}
      for name in ("cached_k", "cached_v"):
        src = att_s["pages_" + name[-1]]
        flat = src[pages].reshape(pp * ps, hk, d)
        buf = jnp.zeros((max_len, hk, d), src.dtype)
        row[name] = buf.at[:take].set(flat[:take])[None]
      row["index"] = n_tokens.astype(jnp.int32)
      return row

    return self._map_attn(slabs, None, build)

  def gather_pages(self, slabs, pages, n_tokens: int):
    return self._gather_pages_fn(slabs, jnp.asarray(pages, jnp.int32),
                                 jnp.asarray(n_tokens, jnp.int32))

  def _reset_slots_impl(self, slabs, freed):
    """Zero the page tables and cursors of freed slots: a freed slot's
    lane keeps computing (frozen), and its stale table would otherwise
    route garbage writes into pages the allocator has already handed to
    a NEW request — the reset points them at the trash page instead."""
    obs_device.note_trace("serve.reset_slots")

    def rst(att_s, _):
      new = dict(att_s)
      new["page_table"] = jnp.where(freed[:, None], 0,
                                    att_s["page_table"])
      new["index"] = jnp.where(freed, 0, att_s["index"])
      return new

    return self._map_attn(slabs, None, rst)

  def reset_slots(self, slabs, freed_mask):
    return self._reset_slots_fn(slabs, jnp.asarray(freed_mask, jnp.bool_))

  # -- decode step ----------------------------------------------------------

  def _one_step(self, params, slabs, tok, active):
    logits, mutated = self.slab_model.apply(
        {"params": params, "cache": slabs}, tok[:, None], decode=True,
        mutable=["cache"])
    new_cache = mutated["cache"]

    def freeze(path, new, old):
      # inactive slots must not advance: undo their cursor bump so the
      # garbage k/v their lane wrote stays masked and gets overwritten
      # by the next real token (or by the next prefill insert)
      if _is_index(path):
        return jnp.where(active, new, old)
      return new

    new_cache = tree_map_with_path(freeze, new_cache, slabs)
    nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
    nxt = jnp.where(active, nxt, jnp.int32(self.pad_id))
    return new_cache, nxt

  def _step_impl(self, params, slabs, tok, active):
    obs_device.note_trace("serve.step")
    return self._one_step(params, slabs, tok, active)

  def step(self, params, slabs, last_tokens, active):
    """One token for every live slot: ``(new_slabs, next_tokens)``.

    ``last_tokens: [num_slots] int32`` (pad for inactive lanes),
    ``active: [num_slots] bool``. Inactive lanes compute but are frozen.
    """
    return self._step_fn(params, slabs, jnp.asarray(last_tokens, jnp.int32),
                         jnp.asarray(active, jnp.bool_))

  def step_many(self, params, slabs, last_tokens, active, remaining,
                horizon: int):
    """``horizon`` fused decode steps with on-device EOS/budget stops.

    Returns ``(new_slabs, tokens, active, remaining)`` where ``tokens``
    is ``[horizon, num_slots]`` — a lane's stream is valid up to ITS
    stop (EOS inclusive / budget exhausted), pad after; the host replays
    the same stop rule to harvest. ``remaining: [num_slots] int32`` is
    each lane's unspent token budget. One compile per distinct horizon.
    """
    if horizon < 1:
      raise ValueError("horizon must be >= 1, got %d" % horizon)
    # deterministic fault site (TOS_CHAOS_SERVE): one count per fused
    # decode dispatch — "decode#N:raise" crashes the Nth horizon step
    chaos.serve_fault("decode")
    fn = self._step_many_jits.get(horizon)
    if fn is None:
      def impl(params, slabs, tok, active, remaining, _h=horizon):
        obs_device.note_trace("serve.step_many")

        def body(carry, _):
          slabs, tok, active, remaining = carry
          slabs, nxt = self._one_step(params, slabs, tok, active)
          remaining = jnp.where(active, remaining - 1, remaining)
          done_now = remaining <= 0
          if self.eos_id is not None:
            done_now = jnp.logical_or(done_now, nxt == self.eos_id)
          new_active = jnp.logical_and(active, jnp.logical_not(done_now))
          tok = jnp.where(new_active, nxt, jnp.int32(self.pad_id))
          return (slabs, tok, new_active, remaining), nxt

        (slabs, _, active, remaining), toks = lax.scan(
            body, (slabs, tok, active, remaining), None, length=_h)
        return slabs, toks, active, remaining

      fn = self._step_many_jits[horizon] = jax.jit(impl)
      # the serving step's HLO cost (flops / bytes accessed), captured
      # once per horizon at first use — rides the OBS wire as gauges.
      # The horizon must live in the LABEL: it is a closed-over scan
      # length, invisible to the arg-shape fingerprint, and two horizons
      # have genuinely different costs
      obs_device.capture_cost(
          "serve.step_many.h%d" % horizon, fn, params, slabs,
          jnp.asarray(last_tokens, jnp.int32),
          jnp.asarray(active, jnp.bool_),
          jnp.asarray(remaining, jnp.int32))
    return fn(params, slabs, jnp.asarray(last_tokens, jnp.int32),
              jnp.asarray(active, jnp.bool_),
              jnp.asarray(remaining, jnp.int32))

  # -- self-speculative decode ----------------------------------------------

  def step_spec(self, params, slabs, last_tokens, active, remaining,
                rounds: int):
    """``rounds`` fused SELF-SPECULATIVE rounds (requires
    ``spec_depth > 0``). Each round per lane: draft ``spec_depth``
    tokens with the ``spec_layers``-deep shallow exit, roll the draft
    layers' cursors back, verify the window with ONE full-model
    multi-token step, keep the longest target-agreeing prefix plus the
    target's correction token, and advance that lane's cursor by
    exactly the kept count — so every kept token is the target's own
    greedy emission (bit-identical to ``greedy_generate_kv``) and a
    round emits 1..spec_depth tokens per live lane.

    Returns ``(new_slabs, tokens, counts, accepted, rejected, active,
    remaining)``: ``tokens [rounds, spec_depth, num_slots]`` (a lane's
    round is valid for its first ``counts[r, lane]`` positions, pad
    after — counts are REQUIRED for harvest: rejection padding is
    indistinguishable from an emitted pad token), ``accepted``/
    ``rejected [rounds, num_slots]`` draft-token verdicts for the
    telemetry counters. One compile per distinct ``rounds``.
    """
    if not self.spec_depth:
      raise ValueError("step_spec requires spec_depth > 0")
    if rounds < 1:
      raise ValueError("rounds must be >= 1, got %d" % rounds)
    # the same deterministic fault site as step_many: one count per
    # fused decode dispatch, so TOS_CHAOS_SERVE schedules hit spec and
    # non-spec engines alike
    chaos.serve_fault("decode")
    fn = self._step_spec_jits.get(rounds)
    if fn is None:
      k = self.spec_depth

      def impl(params, slabs, tok, active, remaining, _r=rounds):
        obs_device.note_trace("serve.step_spec")

        def round_body(carry, _):
          slabs, tok, active, remaining = carry
          cur0 = _cursor_leaf(slabs).astype(jnp.int32)

          def dstep(c, _):
            cache, t = c
            logits, mut = self.slab_model.apply(
                {"params": params, "cache": cache}, t[:, None],
                decode=True, mutable=["cache"],
                exit_layer=self.spec_layers)
            nxt = jnp.argmax(logits[:, -1], -1).astype(jnp.int32)
            return (mut["cache"], nxt), nxt

          (cache_d, _), P = lax.scan(dstep, (slabs, tok), None, length=k)
          # rollback: only the shallow layers advanced; their draft
          # writes sit past the restored cursor, masked and overwritten
          cache_d = _with_cursor(cache_d, cur0)
          Pt = P.T                                         # [S, k]
          V = jnp.concatenate([tok[:, None], Pt[:, :k - 1]], axis=1)
          logits, mut = self.slab_model.apply(
              {"params": params, "cache": cache_d}, V, decode=True,
              mutable=["cache"])
          cache_v = mut["cache"]
          T = jnp.argmax(logits, -1).astype(jnp.int32)     # [S, k]
          ok = (Pt == T).astype(jnp.int32)
          m = jnp.sum(jnp.cumprod(ok, axis=1), axis=1)     # [S] in [0,k]
          bonus = jnp.take_along_axis(
              T, jnp.minimum(m, k - 1)[:, None], axis=1)[:, 0]
          cols = jnp.arange(k)[None, :]
          # kept stream: m agreed proposals, then (m < k) the target's
          # correction — never more than k tokens, all target-greedy
          emit = jnp.where(cols == m[:, None], bonus[:, None], Pt)
          adv = jnp.where(m < k, m + 1, k)
          limit = jnp.minimum(adv, remaining)
          if self.eos_id is not None:
            iseos = jnp.logical_and(emit == self.eos_id,
                                    cols < limit[:, None])
            has_eos = jnp.any(iseos, axis=1)
            stop = jnp.where(has_eos, jnp.argmax(iseos, axis=1) + 1,
                             limit)
          else:
            has_eos = jnp.zeros_like(active)
            stop = limit
          stop = jnp.where(active, stop, 0)
          toks = jnp.where(cols < stop[:, None], emit,
                           jnp.int32(self.pad_id))
          new_rem = jnp.where(active, remaining - stop, remaining)
          done = jnp.logical_or(new_rem <= 0, has_eos)
          new_active = jnp.logical_and(active, jnp.logical_not(done))
          newlast = jnp.take_along_axis(
              emit, jnp.clip(stop - 1, 0, k - 1)[:, None], axis=1)[:, 0]
          new_tok = jnp.where(new_active, newlast,
                              jnp.int32(self.pad_id))
          slabs2 = _with_cursor(cache_v, cur0 + stop)
          accepted = jnp.minimum(stop, m)
          rejected = jnp.where(active, k - m, 0)
          return (slabs2, new_tok, new_active, new_rem), \
              (toks.T, stop, accepted, rejected)

        (slabs, tok, active, remaining), ys = lax.scan(
            round_body, (slabs, tok, active, remaining), None, length=_r)
        toks, counts, acc, rej = ys
        return slabs, toks, counts, acc, rej, active, remaining

      fn = self._step_spec_jits[rounds] = jax.jit(impl)
      obs_device.capture_cost(
          "serve.step_spec.r%d" % rounds, fn, params, slabs,
          jnp.asarray(last_tokens, jnp.int32),
          jnp.asarray(active, jnp.bool_),
          jnp.asarray(remaining, jnp.int32))
    return fn(params, slabs, jnp.asarray(last_tokens, jnp.int32),
              jnp.asarray(active, jnp.bool_),
              jnp.asarray(remaining, jnp.int32))
