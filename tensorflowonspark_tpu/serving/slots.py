"""Device side of continuous batching: slot slabs and their jitted ops.

The slab is ONE persistent KV-cache pytree with a fixed slot capacity —
per layer ``[num_slots, max_seq_len, kv_heads, head_dim]`` key/value
buffers plus a VECTOR cursor ``index: [num_slots]`` (the per-slot-cursor
branch of ``models.transformer.Attention._decode_attend``). Three jitted
functions own it:

* :meth:`SlotDecoder.prefill` — run one request's prompt through the
  model on a fresh single-row cache, in bucket-sized chunks so the jit
  cache holds at most ``len(buckets)`` prefill shapes. The first chunk
  is a fresh-cache prefill (flash-eligible on TPU); later chunks ride
  the warm-cache ``idx > 0`` dense branch of the same cond.
* :meth:`SlotDecoder.insert` — scatter that row cache into the slab at a
  freed slot (``lax.dynamic_update_slice`` on every leaf) and set the
  slot's cursor to the prompt length.
* :meth:`SlotDecoder.step` — advance ALL live slots one token in one
  fixed-shape call: each slot writes at its own cursor, attends its own
  length, and inactive slots are frozen (their cursor write is undone,
  their emitted token forced to ``pad_id``) so freed capacity costs
  nothing but the lane's arithmetic.
* :meth:`SlotDecoder.step_many` — ``horizon`` of those steps fused into
  one jitted scan that carries the per-slot done-mask (EOS hit / budget
  spent) ON DEVICE: dispatch + host-sync overhead is paid once per
  ``horizon`` tokens instead of per token, at the cost of at most
  ``horizon - 1`` frozen slot-steps per completion (the same
  done-mask mechanics as ``greedy_generate_kv(eos_id=...)``, so the
  emitted stream stays bit-identical).

Everything here is functional — the ``serving.engine.ServingEngine``
thread owns the slab value and the host-side bookkeeping (which slots
are live, per-request budgets/EOS).
"""

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.tree_util import tree_map_with_path

from tensorflowonspark_tpu.models import transformer as tfm
from tensorflowonspark_tpu.obs import device as obs_device
from tensorflowonspark_tpu.utils import chaos

#: prompt-chunk sizes for bucketed prefill, largest-first. The compiled
#: prefill cache holds at most one entry per size, so arbitrary prompt
#: lengths never grow the jit cache unboundedly; 1 must be reachable so
#: every length decomposes.
DEFAULT_BUCKETS = (512, 128, 32, 16, 8, 4, 2, 1)


def chunk_plan(plen: int, buckets: Sequence[int] = DEFAULT_BUCKETS):
  """Decompose a prompt length into descending bucket-sized chunks.

  Greedy largest-first: ``chunk_plan(37, (128, 32, 8, 4, 2, 1))`` →
  ``[32, 4, 1]``. A trailing 1 is appended to the bucket set if missing
  so every positive length has a plan.
  """
  if plen < 1:
    raise ValueError("prompt length must be >= 1, got %d" % plen)
  sizes = sorted({int(b) for b in buckets if int(b) > 0}, reverse=True)
  if not sizes or sizes[-1] != 1:
    sizes.append(1)
  plan, rem = [], plen
  for b in sizes:
    while rem >= b:
      plan.append(b)
      rem -= b
  return plan


def _is_index(path) -> bool:
  return bool(path) and getattr(path[-1], "key", None) == "index"


class SlotDecoder(object):
  """Jitted slab operations for one (config, num_slots) serving shape.

  Greedy decode only: continuous batching's contract is that every
  request's tokens are bit-identical to its own single-request decode,
  which sampling's batch-shaped rng draw cannot promise.
  """

  def __init__(self, cfg, num_slots: int, pad_id: int = 0, eos_id=None,
               mesh=None):
    if num_slots < 1:
      raise ValueError("num_slots must be >= 1, got %d" % num_slots)
    self.cfg = cfg
    self.num_slots = num_slots
    self.pad_id = int(pad_id)
    self.eos_id = None if eos_id is None else int(eos_id)
    self.mesh = mesh
    self.model = tfm.Transformer(cfg, mesh=mesh)
    # jit caches retrace per chunk shape (bounded by the bucket set) /
    # once for insert+step (fixed slab shapes)
    self._prefill_fn = jax.jit(self._prefill_impl)
    self._insert_fn = jax.jit(self._insert_impl)
    self._step_fn = jax.jit(self._step_impl)
    self._step_many_jits = {}    # horizon -> jitted fused-scan step
    self._zero_row = None        # memoized fresh [1, ...] cache (immutable)

  # -- slab construction ----------------------------------------------------

  def init_slabs(self):
    """A fresh all-zeros slab with VECTOR per-slot cursors."""
    cache = tfm._zero_cache(self.model, self.num_slots)

    def widen(path, leaf):
      if _is_index(path):
        return jnp.zeros((self.num_slots,), leaf.dtype)
      return leaf

    return tree_map_with_path(widen, cache)

  # -- prefill (single row, bucketed chunks) --------------------------------

  def _prefill_impl(self, params, cache, tokens):
    # recompile sentinel seam: fires once per (re)trace — the prefill jit
    # cache must stay bounded by the bucket set (obs/device.py)
    obs_device.note_trace("serve.prefill")
    logits, mutated = self.model.apply(
        {"params": params, "cache": cache}, tokens, decode=True,
        mutable=["cache"])
    nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
    return mutated["cache"], nxt

  def prefill(self, params, prompt, buckets: Sequence[int] = DEFAULT_BUCKETS
              ) -> Tuple[object, int]:
    """Prefill one prompt into a fresh [1, ...] row cache.

    Returns ``(row_cache, first_token)``: the warm cache (cursor at
    ``len(prompt)``) and the first generated token g1. Chunks follow
    :func:`chunk_plan`, so only the LAST chunk's logits matter.
    """
    plen = len(prompt)
    if plen + 1 > self.cfg.max_seq_len:
      raise ValueError(
          "prompt of %d tokens leaves no decode room in the "
          "max_seq_len=%d cache" % (plen, self.cfg.max_seq_len))
    # deterministic fault site (TOS_CHAOS_SERVE, docs/ROBUSTNESS.md):
    # raise-or-stall here stands in for a device failure during prefill.
    # The index is the prompt length — the one identity a spec can pin
    # before request ids exist (per-length specs make poison requests)
    chaos.serve_fault("prefill", index=plen)
    if self._zero_row is None:
      # memoized: model.init is a full trace, far too slow to pay per
      # admitted request; jax arrays are immutable so one zero pytree
      # serves every prefill
      self._zero_row = tfm._zero_cache(self.model, 1)
    cache = self._zero_row
    prompt = jnp.asarray(prompt, jnp.int32).reshape(1, plen)
    off, nxt = 0, None
    for seg in chunk_plan(plen, buckets):
      cache, nxt = self._prefill_fn(
          params, cache, lax.dynamic_slice(prompt, (0, off), (1, seg)))
      off += seg
    return cache, int(nxt[0])

  # -- slot insert ----------------------------------------------------------

  def _insert_impl(self, slabs, row, slot):
    obs_device.note_trace("serve.insert")

    def ins(s, r):
      if r.ndim == s.ndim:        # [1, ...] row leaf into [S, ...] slab
        return lax.dynamic_update_slice(
            s, r.astype(s.dtype), (slot,) + (0,) * (s.ndim - 1))
      # scalar cursor -> one element of the vector cursor
      return lax.dynamic_update_slice(
          s, r.astype(s.dtype).reshape(1), (slot,))

    return jax.tree.map(ins, slabs, row)

  def insert(self, slabs, row_cache, slot: int):
    """Write a prefilled row cache into slab position ``slot``."""
    return self._insert_fn(slabs, row_cache, jnp.asarray(slot, jnp.int32))

  # -- decode step ----------------------------------------------------------

  def _one_step(self, params, slabs, tok, active):
    logits, mutated = self.model.apply(
        {"params": params, "cache": slabs}, tok[:, None], decode=True,
        mutable=["cache"])
    new_cache = mutated["cache"]

    def freeze(path, new, old):
      # inactive slots must not advance: undo their cursor bump so the
      # garbage k/v their lane wrote stays masked and gets overwritten
      # by the next real token (or by the next prefill insert)
      if _is_index(path):
        return jnp.where(active, new, old)
      return new

    new_cache = tree_map_with_path(freeze, new_cache, slabs)
    nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
    nxt = jnp.where(active, nxt, jnp.int32(self.pad_id))
    return new_cache, nxt

  def _step_impl(self, params, slabs, tok, active):
    obs_device.note_trace("serve.step")
    return self._one_step(params, slabs, tok, active)

  def step(self, params, slabs, last_tokens, active):
    """One token for every live slot: ``(new_slabs, next_tokens)``.

    ``last_tokens: [num_slots] int32`` (pad for inactive lanes),
    ``active: [num_slots] bool``. Inactive lanes compute but are frozen.
    """
    return self._step_fn(params, slabs, jnp.asarray(last_tokens, jnp.int32),
                         jnp.asarray(active, jnp.bool_))

  def step_many(self, params, slabs, last_tokens, active, remaining,
                horizon: int):
    """``horizon`` fused decode steps with on-device EOS/budget stops.

    Returns ``(new_slabs, tokens, active, remaining)`` where ``tokens``
    is ``[horizon, num_slots]`` — a lane's stream is valid up to ITS
    stop (EOS inclusive / budget exhausted), pad after; the host replays
    the same stop rule to harvest. ``remaining: [num_slots] int32`` is
    each lane's unspent token budget. One compile per distinct horizon.
    """
    if horizon < 1:
      raise ValueError("horizon must be >= 1, got %d" % horizon)
    # deterministic fault site (TOS_CHAOS_SERVE): one count per fused
    # decode dispatch — "decode#N:raise" crashes the Nth horizon step
    chaos.serve_fault("decode")
    fn = self._step_many_jits.get(horizon)
    if fn is None:
      def impl(params, slabs, tok, active, remaining, _h=horizon):
        obs_device.note_trace("serve.step_many")

        def body(carry, _):
          slabs, tok, active, remaining = carry
          slabs, nxt = self._one_step(params, slabs, tok, active)
          remaining = jnp.where(active, remaining - 1, remaining)
          done_now = remaining <= 0
          if self.eos_id is not None:
            done_now = jnp.logical_or(done_now, nxt == self.eos_id)
          new_active = jnp.logical_and(active, jnp.logical_not(done_now))
          tok = jnp.where(new_active, nxt, jnp.int32(self.pad_id))
          return (slabs, tok, new_active, remaining), nxt

        (slabs, _, active, remaining), toks = lax.scan(
            body, (slabs, tok, active, remaining), None, length=_h)
        return slabs, toks, active, remaining

      fn = self._step_many_jits[horizon] = jax.jit(impl)
      # the serving step's HLO cost (flops / bytes accessed), captured
      # once per horizon at first use — rides the OBS wire as gauges.
      # The horizon must live in the LABEL: it is a closed-over scan
      # length, invisible to the arg-shape fingerprint, and two horizons
      # have genuinely different costs
      obs_device.capture_cost(
          "serve.step_many.h%d" % horizon, fn, params, slabs,
          jnp.asarray(last_tokens, jnp.int32),
          jnp.asarray(active, jnp.bool_),
          jnp.asarray(remaining, jnp.int32))
    return fn(params, slabs, jnp.asarray(last_tokens, jnp.int32),
              jnp.asarray(active, jnp.bool_),
              jnp.asarray(remaining, jnp.int32))
