"""Driver-side half of the cross-host serving plane.

The reference's L6 tier is "inference as a service" on *executors*; PRs
6–18 built every serving subsystem — engine, fleet, registry, deploy —
inside the driver process. This module (with ``serving/host.py``, the
executor half) moves the replicas out: a :class:`ServingHostPlane`
attaches to the rendezvous :class:`~..control.rendezvous.Server` (the
``obs_sink``/``sync_plane`` attachment pattern) and serves three new
wire verbs — ``SHREG`` (a ServingHost announces itself), ``SHSYNC``
(the host's heartbeat-with-payload: it pushes request events and load
stats, and pulls queued commands) and ``SHBYE`` (clean departure) —
while a :class:`RemoteReplica` proxy satisfies the exact replica
surface :class:`~.fleet.ServingFleet` dispatches against
(``submit``/``request``/``result``/``stream``/``cancel``/``drain``/
``kill``/``stop``/``start``/``generate``, ``alive``, ``_loop_error``,
and the load-score properties ``queue_depth``/``queued_tokens``/
``tokens_per_sec``/``occupancy_now``), so the PR 12 fleet routes,
retries, health-ejects, failover-replays and zero-shed
``rolling_swap``s across process boundaries WITHOUT modification.

Wire discipline: the rendezvous server refuses frames over
``MAX_MESSAGE_BYTES`` (4 MB), so nothing here ever ships a fat
message. Prompts larger than ``TOS_HOST_CHUNK`` tokens are staged in
parts (``stage`` commands reassembled host-side), command pulls and
token pushes are budgeted per sync frame, and everything else on the
wire is small structured metadata.

Correctness across the process hop inherits PR 12's argument unchanged:
greedy decode is bit-identical, so replicas stay interchangeable
whether they share the driver's address space or not. A host that dies
(SIGKILL, OOM, preemption — or ``TOS_CHAOS_HOST``) simply stops
syncing; its :class:`RemoteReplica` flips ``alive`` False after
``TOS_HOST_TIMEOUT`` silent seconds, the fleet ejects it, and the
mirror's received-token prefix feeds the same failover replay +
exactly-once stream suppression that cross-replica failover already
proved (docs/ROBUSTNESS.md §Cross-host serving).

Structured exceptions cross the wire as field dicts and are
RECONSTRUCTED driver-side (``ServingOverloaded`` keeps its
``retry_after``/``queue_depth``/``draining``; ``DeadlineExceeded``/
``RequestCancelled``/``PoisonedRequest`` keep their types) — the
``QueueFull.__reduce__`` lesson, applied to msgpack. Deadlines are
absolute ``time.monotonic()`` values and monotonic clocks don't travel:
the proxy converts to remaining-TTL at send time and the host
re-anchors (``ServingEngine.submit(ttl=...)``).

Usage (driver)::

    server = rendezvous.Server(...); addr = server.start()
    plane = remote.attach_serving_plane(server)
    # ... ServingHost processes dial in (serving/host.py) ...
    plane.await_hosts(2, timeout=60)
    fleet = ServingFleet(remote.remote_engine_factory(plane),
                         num_replicas=2,
                         health_probe=remote.wire_health_probe(addr))
"""

import collections
import itertools
import logging
import os
import queue as std_queue
import threading
import time
from typing import Callable, Dict, List, Optional

import numpy as np

from tensorflowonspark_tpu.obs import metrics as obs_metrics
from tensorflowonspark_tpu.serving import scheduler as sched

logger = logging.getLogger(__name__)

#: seconds of SHSYNC silence after which a serving host is presumed dead
#: (its RemoteReplicas flip ``alive`` False and the fleet ejects them)
ENV_HOST_TIMEOUT = "TOS_HOST_TIMEOUT"
#: bound on the proxy's wait for a submit's accept/reject ack
ENV_HOST_ADMIT = "TOS_HOST_ADMIT_TIMEOUT"
#: bound on ``RemoteReplica.start()`` — the host-side engine build
#: (registry watch + params load + engine start) must ack within this
ENV_HOST_START = "TOS_HOST_START_TIMEOUT"
#: max payload tokens per wire frame (prompt parts, token pushes,
#: command pulls are all budgeted against it) — the chunked-framing
#: knob that keeps every frame far under ``MAX_MESSAGE_BYTES``
ENV_HOST_CHUNK = "TOS_HOST_CHUNK"

_DEFAULT_TIMEOUT = 2.0
_DEFAULT_ADMIT = 10.0
_DEFAULT_START = 120.0
_DEFAULT_CHUNK = 65536
#: fallback generation budget before the host's build ack reports the
#: real engine default (fleet.submit only consults it when the caller
#: passed no ``max_new_tokens``)
_FALLBACK_MAX_NEW_TOKENS = 64
#: done mirrors kept for late ``request()`` lookups before pruning
_MIRROR_KEEP = 1024

_tids = itertools.count(1)
_bids = itertools.count(1)


def _env_float(name: str, default: float) -> float:
  return float(os.environ.get(name, str(default)))


def _env_int(name: str, default: int) -> int:
  return int(os.environ.get(name, str(default)))


def encode_error(e: BaseException) -> dict:
  """Structured serving exception -> wire fields (msgpack-safe)."""
  if isinstance(e, sched.ServingOverloaded):
    return {"kind": "overloaded", "msg": str(e),
            "queue_depth": e.queue_depth, "queued_tokens": e.queued_tokens,
            "retry_after": e.retry_after, "draining": bool(e.draining)}
  if isinstance(e, sched.DeadlineExceeded):
    return {"kind": "deadline", "msg": str(e)}
  if isinstance(e, sched.RequestCancelled):
    return {"kind": "cancelled", "msg": str(e)}
  if isinstance(e, sched.PoisonedRequest):
    return {"kind": "poisoned", "msg": str(e)}
  if isinstance(e, ValueError):
    return {"kind": "value", "msg": str(e)}
  return {"kind": "runtime", "msg": repr(e)}


def decode_error(d: Optional[dict]) -> Optional[BaseException]:
  """Wire fields -> the structured exception, type preserved — the
  fleet's verdict handling (DeadlineExceeded/RequestCancelled/
  PoisonedRequest re-raised, everything else a failover cause) must
  behave identically for a remote replica."""
  if d is None:
    return None
  kind, msg = d.get("kind"), d.get("msg", "")
  if kind == "overloaded":
    return sched.ServingOverloaded(
        msg, queue_depth=d.get("queue_depth"),
        queued_tokens=d.get("queued_tokens"),
        retry_after=d.get("retry_after"),
        draining=bool(d.get("draining")))
  if kind == "deadline":
    return sched.DeadlineExceeded(msg)
  if kind == "cancelled":
    return sched.RequestCancelled(msg)
  if kind == "poisoned":
    return sched.PoisonedRequest(msg)
  if kind == "value":
    return ValueError(msg)
  return RuntimeError(msg)


class _CancelEvent(threading.Event):
  """An Event whose first ``set()`` also fires a callback — the fleet
  cancels by calling ``handle.cancelled.set()`` directly on the request
  handle (``ServingFleet.cancel`` / the ``_assign`` race path), and for
  a remote mirror that set must ALSO enqueue the cancel command."""

  def __init__(self, on_set: Callable[[], None]):
    super().__init__()
    self._on_set = on_set

  def set(self) -> None:  # noqa: A003 - Event API
    first = not self.is_set()
    super().set()
    if first:
      try:
        self._on_set()
      except Exception:  # noqa: BLE001 # tosa: ignore[TOS004] - a cancel
        # relay failure must not poison the caller (set() is called from
        # fleet routing paths); the host-side TTL still bounds the request
        logger.warning("remote cancel relay failed", exc_info=True)


class RemoteRequest(object):
  """Driver-side mirror of one host-side engine request — the handle
  shape ``ServingFleet`` consumes (``stream_q``/``tokens``/``done``/
  ``error``/``cancelled``/``first_token_at``), fed by SHSYNC events
  applied on the rendezvous serve thread. ``tokens`` is exactly the
  prefix the host streamed, so ``_begin_failover`` captures the same
  replay baseline it would from a local request."""

  __slots__ = ("tid", "prompt", "max_new_tokens", "trace_id", "stream_q",
               "tokens", "done", "error", "cancelled", "first_token_at",
               "admitted", "rejection", "submitted_at")

  def __init__(self, prompt: np.ndarray, max_new_tokens: int,
               trace_id, on_cancel: Callable[[], None]):
    self.tid = next(_tids)
    self.prompt = prompt
    self.max_new_tokens = int(max_new_tokens)
    self.trace_id = trace_id
    self.stream_q: std_queue.Queue = std_queue.Queue()
    self.tokens: List[int] = []
    self.done = threading.Event()
    self.error: Optional[BaseException] = None
    self.cancelled = _CancelEvent(on_cancel)
    self.first_token_at: Optional[float] = None
    #: admission verdict: set once the host acked (rejection None) or
    #: rejected (rejection holds the reconstructed exception)
    self.admitted = threading.Event()
    self.rejection: Optional[BaseException] = None
    self.submitted_at = time.monotonic()

  def _apply_tokens(self, pos: int, toks) -> None:
    """Apply a position-stamped token delta exactly once: ``pos`` is
    the stream index of ``toks[0]``, so a resend after a flaky sync
    (the host requeues unacked events) appends only the unseen suffix
    — stream positions stay exactly-once BY CONSTRUCTION, not by
    hoping the wire never retries."""
    skip = len(self.tokens) - int(pos)
    if skip < 0:
      # a gap would mean the host skipped an event — the wire is an
      # ordered per-host FIFO, so this is a protocol bug, not weather
      raise RuntimeError(
          "token gap for request %d: have %d, delta starts at %d"
          % (self.tid, len(self.tokens), int(pos)))
    fresh = toks[skip:] if skip else toks
    if self.first_token_at is None and fresh:
      self.first_token_at = time.monotonic()
    for t in fresh:
      t = int(t)
      self.tokens.append(t)
      self.stream_q.put_nowait(t)         # unbounded: never blocks

  def _finish(self, error: Optional[BaseException]) -> None:
    if self.done.is_set():
      return
    self.error = error
    self.stream_q.put_nowait(None)        # unbounded: never blocks
    self.done.set()


class _HostRecord(object):
  """One registered ServingHost as the plane sees it."""

  __slots__ = ("host_id", "meta", "last_sync", "stats", "cmds", "mirrors",
               "builds", "drains", "stops", "departed", "reserved")

  def __init__(self, host_id: int, meta: dict):
    self.host_id = int(host_id)
    self.meta = dict(meta or {})
    self.last_sync = time.monotonic()
    #: latest host-shipped load/liveness stats (SHSYNC payload)
    self.stats: dict = {}
    self.cmds: collections.deque = collections.deque()
    #: tid -> RemoteRequest mirror awaiting events
    self.mirrors: Dict[int, RemoteRequest] = {}
    #: bid -> {"done": Event, "reply": dict} build acks
    self.builds: Dict[int, dict] = {}
    self.drains: Dict[int, dict] = {}
    self.stops: Dict[int, threading.Event] = {}
    self.departed = False
    #: the RemoteReplica currently bound to this host (the swap
    #: allocator's bookkeeping), None when free
    self.reserved: Optional["RemoteReplica"] = None


class ServingHostPlane(object):
  """The driver-side state behind the SHREG/SHSYNC/SHBYE verbs.

  Passive by construction: everything happens inside :meth:`handle`
  calls on the rendezvous serve thread (which must never block) or
  inside proxy calls on fleet/client threads — there is no thread here.
  Host death is therefore an *absence*: :meth:`host_alive` compares the
  last SHSYNC age against ``TOS_HOST_TIMEOUT`` at read time.
  """

  def __init__(self, timeout: Optional[float] = None,
               chunk: Optional[int] = None):
    self.timeout = float(timeout if timeout is not None
                         else _env_float(ENV_HOST_TIMEOUT, _DEFAULT_TIMEOUT))
    self.chunk = max(256, int(chunk if chunk is not None
                              else _env_int(ENV_HOST_CHUNK, _DEFAULT_CHUNK)))
    self._lock = threading.Lock()
    self._hosts: Dict[int, _HostRecord] = {}
    self.stats = {"registrations": 0, "syncs": 0, "events": 0,
                  "commands": 0, "bad_messages": 0}
    reg = obs_metrics.active()
    self._g_total = None if reg is None else reg.gauge("serve.hosts_total")
    self._g_alive = None if reg is None else reg.gauge("serve.hosts_alive")

  # -- wire side (rendezvous serve thread) -----------------------------------

  def handle(self, msg: dict) -> dict:
    """Dispatch one serving-plane wire message; always returns a reply
    dict (the Server arm sends it verbatim)."""
    mtype = msg.get("type")
    try:
      if mtype == "SHREG":
        return self._handle_reg(msg)
      if mtype == "SHSYNC":
        return self._handle_sync(msg)
      if mtype == "SHBYE":
        return self._handle_bye(msg)
    except Exception as e:  # noqa: BLE001 - a malformed host payload must
      # degrade to an ERROR reply, never a dead rendezvous serve loop
      self.stats["bad_messages"] += 1
      logger.warning("serving plane failed on %s: %s", mtype, e)
      return {"type": "ERROR", "error": str(e)}
    return {"type": "ERROR", "error": "unknown serving verb %r" % mtype}

  def _handle_reg(self, msg: dict) -> dict:
    hid = int(msg["host_id"])
    with self._lock:
      rec = self._hosts.get(hid)
      if rec is None:
        self._hosts[hid] = _HostRecord(hid, msg.get("meta"))
      else:
        # a re-registration (lost reply, or a relaunched host process
        # reclaiming its slot): refresh liveness, keep queued commands
        rec.meta = dict(msg.get("meta") or {})
        rec.last_sync = time.monotonic()
        rec.departed = False
      self.stats["registrations"] += 1
    self._refresh_gauges()
    return {"type": "OK", "timeout": self.timeout, "chunk": self.chunk}

  def _handle_sync(self, msg: dict) -> dict:
    hid = int(msg["host_id"])
    with self._lock:
      rec = self._hosts.get(hid)
      if rec is None:
        # syncing without registering (plane restarted under the host):
        # tell it to re-register rather than invent a half-known host
        return {"type": "ERROR", "error": "unregistered host %d" % hid}
      rec.last_sync = time.monotonic()
      rec.departed = False
      if isinstance(msg.get("stats"), dict):
        rec.stats = msg["stats"]
      events = msg.get("events") or ()
      cmds = self._pop_cmds_locked(rec)
      self.stats["syncs"] += 1
      self.stats["events"] += len(events)
      self.stats["commands"] += len(cmds)
    # events are applied outside the hosts lock: they touch per-mirror
    # state only, and a waiter woken by an ack may immediately call back
    # into the plane (reserve/release) which takes the lock
    for ev in events:
      self._apply_event(rec, ev)
    self._refresh_gauges()
    return {"type": "OK", "cmds": cmds, "server_time": time.monotonic()}

  def _handle_bye(self, msg: dict) -> dict:
    hid = int(msg["host_id"])
    with self._lock:
      rec = self._hosts.get(hid)
      if rec is not None:
        rec.departed = True
    self._refresh_gauges()
    return {"type": "OK"}

  def _pop_cmds_locked(self, rec: _HostRecord) -> List[dict]:
    """Pop queued commands up to the per-frame chunk budget (prompt and
    stage payload tokens count against it; at least one command always
    ships so an oversized-looking queue can never wedge)."""
    out: List[dict] = []
    budget = self.chunk
    while rec.cmds:
      cmd = rec.cmds[0]
      cost = len(cmd.get("prompt") or ()) + len(cmd.get("part") or ())
      if out and cost > budget:
        break
      out.append(rec.cmds.popleft())
      budget -= cost
      if budget <= 0 or len(out) >= 64:
        break
    return out

  def _apply_event(self, rec: _HostRecord, ev: dict) -> None:
    kind = ev.get("ev")
    if kind == "tok":
      m = rec.mirrors.get(ev.get("tid"))
      if m is not None:
        m._apply_tokens(int(ev.get("pos", 0)), ev.get("toks") or ())
    elif kind == "done":
      m = rec.mirrors.get(ev.get("tid"))
      if m is not None:
        m._finish(decode_error(ev.get("error")))
    elif kind == "acc":
      m = rec.mirrors.get(ev.get("tid"))
      if m is not None:
        m.admitted.set()
    elif kind == "rej":
      m = rec.mirrors.get(ev.get("tid"))
      if m is not None:
        m.rejection = decode_error(ev.get("error")) or RuntimeError(
            "host %d rejected request" % rec.host_id)
        m.admitted.set()
        m._finish(m.rejection)
    elif kind == "built":
      slot = rec.builds.get(ev.get("bid"))
      if slot is not None:
        slot["reply"] = ev
        slot["done"].set()
    elif kind == "drained":
      slot = rec.drains.get(ev.get("did"))
      if slot is not None:
        slot["reply"] = ev
        slot["done"].set()
    elif kind == "stopped":
      done = rec.stops.get(ev.get("sid"))
      if done is not None:
        done.set()
    else:
      self.stats["bad_messages"] += 1
      logger.warning("serving plane: unknown host event %r from host %d",
                     kind, rec.host_id)

  # -- driver side (fleet / proxy threads) -----------------------------------

  def _rec(self, host_id: int) -> _HostRecord:
    with self._lock:
      try:
        return self._hosts[int(host_id)]
      except KeyError:
        raise KeyError("unknown serving host %r" % (host_id,))

  def enqueue(self, host_id: int, cmd: dict) -> None:
    self._rec(host_id).cmds.append(cmd)

  def host_alive(self, host_id: int) -> bool:
    with self._lock:
      rec = self._hosts.get(int(host_id))
      if rec is None or rec.departed:
        return False
      return (time.monotonic() - rec.last_sync) <= self.timeout

  def host_ids(self) -> List[int]:
    with self._lock:
      return sorted(self._hosts)

  def await_hosts(self, count: int, timeout: float) -> List[int]:
    """Block (bounded) until ``count`` hosts have registered and are
    syncing; returns their ids. The cross-host analogue of
    ``Server.await_reservations``."""
    deadline = time.monotonic() + float(timeout)
    while True:
      live = [h for h in self.host_ids() if self.host_alive(h)]
      if len(live) >= count:
        return live[:count]
      if time.monotonic() >= deadline:
        raise TimeoutError(
            "only %d/%d serving host(s) registered within %.1fs"
            % (len(live), count, timeout))
      time.sleep(0.05)

  def status(self) -> Dict[str, dict]:
    """{host_id: liveness + load row} — the HEALTH enrichment payload
    (``reply["hosts"]``) that ``obs_top`` renders and
    :func:`wire_health_probe` keys ejection on. String keys, matching
    the liveness snapshot convention."""
    now = time.monotonic()
    out: Dict[str, dict] = {}
    with self._lock:
      for hid, rec in self._hosts.items():
        age = now - rec.last_sync
        alive = (not rec.departed) and age <= self.timeout
        st = rec.stats
        out[str(hid)] = {
            "alive": bool(alive),
            "state": ("departed" if rec.departed
                      else ("live" if alive else "lost")),
            "age": round(age, 3),
            "engine_alive": bool(st.get("engine_alive", False)),
            "generation": int(st.get("generation", 0)),
            "version": st.get("version"),
            "queue_depth": int(st.get("queue_depth", 0)),
            "queued_tokens": int(st.get("queued_tokens", 0)),
            "tokens_per_sec": float(st.get("tokens_per_sec", 0.0)),
            "occupancy_now": float(st.get("occupancy_now", 0.0)),
            "requests": len(rec.mirrors),
        }
    return out

  def _refresh_gauges(self) -> None:
    if self._g_total is None:
      return
    now = time.monotonic()
    with self._lock:
      total = sum(1 for r in self._hosts.values() if not r.departed)
      alive = sum(1 for r in self._hosts.values()
                  if not r.departed and now - r.last_sync <= self.timeout)
    self._g_total.set(total)
    self._g_alive.set(alive)

  # -- host allocation (the swap/factory seam) -------------------------------

  def reserve(self, replica: "RemoteReplica",
              host_id: Optional[int] = None) -> int:
    """Bind a proxy to a free host (or the named one). Allocation
    prefers free+alive hosts in id order, so a freshly-constructed
    fleet maps replica k onto host k, and a ``swap_replica`` — whose
    drain released exactly one host — rebuilds on the host it drained.
    """
    with self._lock:
      if host_id is not None:
        rec = self._hosts.get(int(host_id))
        if rec is None:
          raise KeyError("unknown serving host %r" % (host_id,))
        if rec.reserved is not None and rec.reserved is not replica:
          raise RuntimeError("serving host %d already bound" % rec.host_id)
        rec.reserved = replica
        return rec.host_id
      now = time.monotonic()
      free = [r for r in sorted(self._hosts.values(),
                                key=lambda r: r.host_id)
              if r.reserved is None]
      live = [r for r in free
              if not r.departed and now - r.last_sync <= self.timeout]
      pick = (live or free or [None])[0]
      if pick is None:
        raise RuntimeError(
            "no free serving host for a new replica (%d registered, all "
            "bound)" % len(self._hosts))
      pick.reserved = replica
      return pick.host_id

  def release(self, replica: "RemoteReplica", host_id: int) -> None:
    with self._lock:
      rec = self._hosts.get(int(host_id))
      if rec is not None and rec.reserved is replica:
        rec.reserved = None

  def _prune_mirrors_locked(self, rec: _HostRecord) -> None:
    done = [tid for tid, m in rec.mirrors.items() if m.done.is_set()]
    if len(done) > _MIRROR_KEEP:
      for tid in sorted(done)[:-_MIRROR_KEEP]:
        rec.mirrors.pop(tid, None)


class RemoteReplica(object):
  """The engine-shaped proxy for one executor-resident ServingEngine.

  Satisfies the replica surface ``ServingFleet`` (and the deploy
  controller's VERIFY spot-checks) dispatch against; every method is
  timeout-bounded and host death fails waiters fast instead of hanging
  them (TOS001). One proxy binds one host *generation*: after a drain +
  rebuild (the swap path) the old proxy reads dead and a fresh proxy —
  from :func:`remote_engine_factory` — owns the host's new engine.
  """

  def __init__(self, plane: ServingHostPlane,
               host_id: Optional[int] = None, version: Optional[int] = None,
               admit_timeout: Optional[float] = None,
               start_timeout: Optional[float] = None):
    self._plane = plane
    self.version = version
    self.host_id = plane.reserve(self, host_id)
    self.admit_timeout = float(
        admit_timeout if admit_timeout is not None
        else _env_float(ENV_HOST_ADMIT, _DEFAULT_ADMIT))
    self.start_timeout = float(
        start_timeout if start_timeout is not None
        else _env_float(ENV_HOST_START, _DEFAULT_START))
    self._started = False
    self._dead = False
    self._gen: Optional[int] = None
    self.default_max_new_tokens = _FALLBACK_MAX_NEW_TOKENS
    self._lock = threading.Lock()

  # -- lifecycle -------------------------------------------------------------

  def start(self) -> "RemoteReplica":
    """Command the host to build (from its own registry view, at
    ``version`` — or the latest) and start a fresh engine; blocks until
    the build acks. Raises RuntimeError on failure/host death."""
    with self._lock:
      if self._started and not self._dead:
        return self
      if self._dead:
        raise RuntimeError("remote replica on host %d is dead"
                           % self.host_id)
      rec = self._plane._rec(self.host_id)
      bid = next(_bids)
      slot = {"done": threading.Event(), "reply": None}
      rec.builds[bid] = slot
      self._plane.enqueue(self.host_id, {
          "op": "build", "bid": bid,
          "version": None if self.version is None else int(self.version)})
      deadline = time.monotonic() + self.start_timeout
      while not slot["done"].is_set():
        if time.monotonic() >= deadline:
          rec.builds.pop(bid, None)
          raise RuntimeError(
              "serving host %d did not ack engine build within %.1fs"
              % (self.host_id, self.start_timeout))
        if not slot["done"].wait(timeout=0.05) \
            and not self._plane.host_alive(self.host_id):
          rec.builds.pop(bid, None)
          raise RuntimeError("serving host %d died during engine build"
                             % self.host_id)
      rec.builds.pop(bid, None)
      reply = slot["reply"] or {}
      if not reply.get("ok"):
        raise RuntimeError("engine build failed on host %d: %s"
                           % (self.host_id, reply.get("error")))
      self._gen = int(reply.get("generation", 0))
      self.version = reply.get("version", self.version)
      meta = reply.get("meta") or {}
      if meta.get("default_max_new_tokens"):
        self.default_max_new_tokens = int(meta["default_max_new_tokens"])
      self._started = True
      return self

  @property
  def alive(self) -> bool:
    """True before start (a constructed replica is startable — the
    engine contract); after it: the host is syncing, its engine loop is
    up, and the host still runs THIS proxy's generation."""
    if self._dead:
      return False
    if not self._started:
      return True
    if not self._plane.host_alive(self.host_id):
      return False
    st = self._plane._rec(self.host_id).stats
    return bool(st.get("engine_alive")) \
        and int(st.get("generation", -1)) == self._gen

  @property
  def _loop_error(self) -> Optional[BaseException]:
    if not self._started:
      return None
    if not self._plane.host_alive(self.host_id):
      return RuntimeError("serving host %d lost (no sync within %.1fs)"
                          % (self.host_id, self._plane.timeout))
    err = self._plane._rec(self.host_id).stats.get("loop_error")
    return None if not err else RuntimeError(str(err))

  def _mark_dead(self) -> None:
    self._dead = True
    self._plane.release(self, self.host_id)

  def kill(self, cause: Optional[BaseException] = None,
           timeout: float = 5.0) -> None:
    """Terminal-death relay: the host engine dies as if its loop
    exhausted restarts; this proxy reads dead immediately."""
    try:
      self._plane.enqueue(self.host_id, {
          "op": "kill", "cause": repr(cause) if cause else "killed"})
    except KeyError:
      pass
    self._mark_dead()

  def stop(self, timeout: float = 30.0) -> None:
    """Stop the host-side engine (idempotent, bounded, safe on a dead
    host — the ejection path calls this best-effort)."""
    if self._dead:
      return
    try:
      rec = self._plane._rec(self.host_id)
    except KeyError:
      self._dead = True
      return
    sid = next(_bids)
    done = threading.Event()
    rec.stops[sid] = done
    self._plane.enqueue(self.host_id, {"op": "stop", "sid": sid,
                                       "timeout": float(timeout)})
    deadline = time.monotonic() + max(0.1, float(timeout))
    while not done.is_set() and time.monotonic() < deadline:
      if not self._plane.host_alive(self.host_id):
        break
      done.wait(timeout=0.05)
    rec.stops.pop(sid, None)
    self._mark_dead()

  def drain(self, timeout: float) -> bool:
    """Zero-shed drain of the host engine (the swap move): close its
    admission, finish accepted work, stop. True when everything
    completed in time. The host reservation is released on return so
    the NEXT factory build lands on this freshly-drained host."""
    rec = self._plane._rec(self.host_id)
    did = next(_bids)
    slot = {"done": threading.Event(), "reply": None}
    rec.drains[did] = slot
    self._plane.enqueue(self.host_id, {"op": "drain", "did": did,
                                       "timeout": float(timeout)})
    # margin: the drain itself is bounded by ``timeout`` host-side; the
    # ack just needs one more sync hop (plus scheduling slack)
    deadline = time.monotonic() + float(timeout) + \
        max(1.0, 3 * self._plane.timeout)
    ok = False
    while time.monotonic() < deadline:
      if slot["done"].wait(timeout=0.05):
        ok = bool((slot["reply"] or {}).get("ok"))
        break
      if not self._plane.host_alive(self.host_id):
        break
    rec.drains.pop(did, None)
    self._mark_dead()
    return ok

  # -- client API ------------------------------------------------------------

  def submit(self, prompt, max_new_tokens: Optional[int] = None,
             deadline: Optional[float] = None,
             ttl: Optional[float] = None,
             trace_id: Optional[str] = None) -> int:
    """Queue one prompt on the remote engine; returns the request id.

    Blocks (bounded by ``TOS_HOST_ADMIT_TIMEOUT``) for the host's
    admission verdict so overload/validation surface EXACTLY like a
    local engine: ``ServingOverloaded`` with its structured hint,
    ``DeadlineExceeded`` for dead-on-arrival, RuntimeError when the
    host/engine is gone. The driver's absolute deadline travels as
    remaining-TTL (monotonic clocks don't cross processes)."""
    if self._dead or not self._started:
      raise RuntimeError("remote replica on host %d is not serving"
                         % self.host_id)
    arr = np.asarray(prompt, np.int32).ravel()
    if len(arr) < 1:
      raise ValueError("prompt must contain at least one token")
    if deadline is not None and ttl is not None:
      raise ValueError("pass deadline OR ttl, not both")
    if deadline is not None:
      ttl = deadline - time.monotonic()
    if ttl is not None and ttl <= 0:
      raise sched.DeadlineExceeded(
          "request dead on arrival: its deadline already passed at submit")
    rec = self._plane._rec(self.host_id)
    plist = [int(t) for t in arr]
    mirror = RemoteRequest(arr, max_new_tokens
                           if max_new_tokens is not None
                           else self.default_max_new_tokens, trace_id,
                           on_cancel=lambda: self._relay_cancel())
    rec.mirrors[mirror.tid] = mirror
    with self._plane._lock:
      self._plane._prune_mirrors_locked(rec)
    # chunked framing: a prompt over the per-frame budget is staged in
    # parts and reassembled host-side — no frame ever nears the 4 MB
    # rendezvous cap
    chunk = self._plane.chunk
    staged = 0
    if len(plist) > chunk:
      for seq, off in enumerate(range(0, len(plist), chunk)):
        self._plane.enqueue(self.host_id, {
            "op": "stage", "tid": mirror.tid, "seq": seq,
            "part": plist[off:off + chunk]})
        staged += 1
    cmd = {"op": "submit", "tid": mirror.tid,
           "max_new_tokens": int(mirror.max_new_tokens),
           "ttl": None if ttl is None else float(ttl),
           "trace_id": trace_id, "staged": staged}
    if not staged:
      cmd["prompt"] = plist
    self._plane.enqueue(self.host_id, cmd)
    mirror.cancelled._on_set = lambda: self._send_cancel(mirror.tid)
    admit_deadline = time.monotonic() + self.admit_timeout
    while not mirror.admitted.is_set():
      if time.monotonic() >= admit_deadline:
        rec.mirrors.pop(mirror.tid, None)
        raise RuntimeError(
            "serving host %d did not ack submit within %.1fs"
            % (self.host_id, self.admit_timeout))
      if not mirror.admitted.wait(timeout=0.05) and not self.alive:
        rec.mirrors.pop(mirror.tid, None)
        raise RuntimeError("remote replica on host %d died during submit"
                           % self.host_id)
    if mirror.rejection is not None:
      rec.mirrors.pop(mirror.tid, None)
      raise mirror.rejection
    return mirror.tid

  def _relay_cancel(self) -> None:
    # placeholder until the mirror's tid exists; submit() rebinds to
    # _send_cancel(tid) right after constructing the mirror
    pass

  def _send_cancel(self, tid: int) -> None:
    try:
      self._plane.enqueue(self.host_id, {"op": "cancel", "tid": tid})
    except KeyError:
      pass

  def request(self, rid: int) -> RemoteRequest:
    rec = self._plane._rec(self.host_id)
    try:
      return rec.mirrors[rid]
    except KeyError:
      raise KeyError("unknown remote request id %r" % (rid,))

  def result(self, rid: int, timeout: float = 600.0) -> np.ndarray:
    """Block (bounded) for one request's output (prompt + generated),
    failing fast when the host dies — the engine waiter contract."""
    m = self.request(rid)
    deadline = time.monotonic() + float(timeout)
    while not m.done.is_set():
      if time.monotonic() >= deadline:
        raise TimeoutError("remote request %d not finished within %.1fs"
                           % (rid, timeout))
      if not m.done.wait(timeout=0.05) and not self.alive:
        raise RuntimeError(
            "remote replica on host %d died; request %d cannot finish"
            % (self.host_id, rid))
    self._plane._rec(self.host_id).mirrors.pop(rid, None)
    err = m.error
    if isinstance(err, (sched.DeadlineExceeded, sched.RequestCancelled,
                        sched.PoisonedRequest)):
      raise err
    if err is not None:
      raise RuntimeError("remote request %d failed" % rid) from err
    return np.concatenate([m.prompt, np.asarray(m.tokens, np.int32)])

  def stream(self, rid: int, timeout: float = 600.0):
    """Yield generated tokens as they arrive over the wire (EOS
    inclusive), exactly the engine's stream contract."""
    m = self.request(rid)
    deadline = time.monotonic() + float(timeout)
    while True:
      if time.monotonic() >= deadline:
        raise TimeoutError("stream for remote request %d stalled" % rid)
      try:
        tok = m.stream_q.get(timeout=0.05)
      except std_queue.Empty:
        if not self.alive and not m.done.is_set():
          raise RuntimeError(
              "remote replica on host %d died mid-stream" % self.host_id)
        continue
      if tok is None:
        break
      yield int(tok)
    err = m.error
    if isinstance(err, (sched.DeadlineExceeded, sched.RequestCancelled,
                        sched.PoisonedRequest)):
      raise err
    if err is not None:
      raise RuntimeError("remote request %d failed mid-stream"
                         % rid) from err

  def cancel(self, rid: int, timeout: float) -> bool:
    m = self.request(rid)
    if m.done.is_set():
      return True
    m.cancelled.set()
    m.done.wait(timeout=timeout)
    return m.done.is_set()

  def generate(self, prompts, max_new_tokens: Optional[int] = None,
               timeout: float = 600.0) -> List[np.ndarray]:
    """Submit a batch and wait for outputs in order — the deploy
    controller's VERIFY spot-check surface."""
    rids = [self.submit(p, max_new_tokens=max_new_tokens) for p in prompts]
    deadline = time.monotonic() + float(timeout)
    return [self.result(r, timeout=max(0.001, deadline - time.monotonic()))
            for r in rids]

  # -- load telemetry (the fleet router's dispatch inputs) -------------------

  def _stat(self, name: str, default):
    try:
      return self._plane._rec(self.host_id).stats.get(name, default)
    except KeyError:
      return default

  @property
  def queue_depth(self) -> int:
    return int(self._stat("queue_depth", 0))

  @property
  def queued_tokens(self) -> int:
    return int(self._stat("queued_tokens", 0))

  @property
  def tokens_per_sec(self) -> float:
    return float(self._stat("tokens_per_sec", 0.0))

  @property
  def occupancy_now(self) -> float:
    return float(self._stat("occupancy_now", 0.0))


def attach_serving_plane(server,
                         timeout: Optional[float] = None,
                         chunk: Optional[int] = None) -> ServingHostPlane:
  """Create a :class:`ServingHostPlane` and attach it to a rendezvous
  ``Server`` (the ``sync_plane`` attachment pattern): the SHREG/SHSYNC/
  SHBYE arms delegate here, and HEALTH replies gain a ``hosts`` row."""
  plane = ServingHostPlane(timeout=timeout, chunk=chunk)
  server.serving_plane = plane
  return plane


def remote_engine_factory(plane: ServingHostPlane,
                          version: Optional[int] = None,
                          host_id: Optional[int] = None,
                          **proxy_kw) -> Callable[[], RemoteReplica]:
  """An engine factory for ``ServingFleet``/``swap_replica``: each call
  binds a fresh :class:`RemoteReplica` to a free host (allocation order
  makes fleet construction map replica k to host k, and a swap rebuild
  land on the host its drain just freed). ``version`` pins the registry
  version the host builds — the deploy controller's cross-process
  re-param seam."""
  def factory() -> RemoteReplica:
    return RemoteReplica(plane, host_id=host_id, version=version,
                         **proxy_kw)
  return factory


def wire_health_probe(server_addr, timeout: float = 5.0,
                      client_factory: Optional[Callable] = None):
  """A ``ServingFleet.health_probe`` that rides the real HEALTH verb:
  each probe polls the rendezvous server and keys the verdict on the
  serving plane's ``hosts`` row for the replica's host — the
  out-of-process answer to PR 12's in-process stand-in. Replicas whose
  engine has no ``host_id`` (local, in-process) fall back to the
  engine's own ``alive`` flag, so mixed fleets keep both paths."""
  from tensorflowonspark_tpu.control import rendezvous as rv
  state = {"client": None}
  lock = threading.Lock()

  def probe(rep) -> bool:
    hid = getattr(rep.engine, "host_id", None)
    if hid is None:
      return bool(rep.engine.alive)
    with lock:
      if state["client"] is None:
        state["client"] = (client_factory() if client_factory is not None
                           else rv.Client(server_addr, timeout=timeout))
      resp = state["client"]._request({"type": "HEALTH"})
    row = (resp.get("hosts") or {}).get(str(hid))
    if row is None:
      return False
    return bool(row.get("alive")) and bool(row.get("engine_alive"))

  return probe
