"""ServingFleet: a driver-side router over N ServingEngine replicas.

One :class:`~tensorflowonspark_tpu.serving.engine.ServingEngine` is a
single process: a terminal loop death, an unresponsive host, or a param
swap is a fleet-wide outage. This module is the reference's L6
"inference as a service" tier rebuilt natively (PAPER.md §1) with
TF-Replicator's replica abstraction applied to serving: replicas are
INTERCHANGEABLE because the greedy bit-identical-decode contract makes
any replica's answer *the* answer — which is what turns cross-replica
failover from best-effort into provably correct, exactly the way it made
single-engine crash-replay correct (docs/ROBUSTNESS.md).

The fleet keeps serving through:

* **load imbalance** — dispatch is load-aware off the telemetry each
  engine already exports (queued token mass, queue depth, live tokens/s
  EMA, instantaneous occupancy — the same numbers the HEALTH wire
  carries): a request goes to the replica with the shortest estimated
  backlog-clear time, and each replica's own admission bounds
  (``TOS_SERVE_MAX_QUEUE``/``MAX_QUEUED_TOKENS``) still apply;
* **overload** — when every live replica rejects with
  :class:`ServingOverloaded`, ``submit`` retries with backoff honoring
  the smallest structured ``retry_after`` hint, bounded by a fleet-level
  admission deadline (the request's own TTL when it has one, else
  ``TOS_FLEET_ADMIT_TIMEOUT``) so retries never outlive the request;
* **replica death** — a replica that dies terminally (the engine's
  capped-restart exhaustion) or stops answering its health probe is
  EJECTED, and every request it had accepted but not finished is
  transparently resubmitted to a live replica from its prompt
  (failover replay). Greedy decode regenerates the identical stream;
  ``stream()`` consumers see each position exactly once across the
  replica hop because the fleet suppresses (and VERIFIES, counting
  ``replay_mismatches``) the already-delivered prefix — the
  cross-replica analogue of ``Request.begin_replay``;
* **rolling param swaps** — :meth:`rolling_swap` drains one replica at
  a time through the zero-shed ``drain()`` contract while dispatch
  shifts to the others, then swaps in a fresh engine from the factory:
  fleet-wide re-param with zero accepted requests shed.

Every ejection/failover/swap is a structured event (:attr:`events`, the
obs ``fleet.*`` counters, recorder instants) and the anomaly detector
raises ``fleet_degraded`` while the fleet runs below its configured
replica count (docs/OBSERVABILITY.md). Replica-granularity chaos rides
``TOS_CHAOS_FLEET`` (``dispatch[@replica][#nth]:kill`` /
``...:stall:seconds``, utils/chaos.py) so the whole story is proven
deterministically, never assumed.

Usage::

    fleet = ServingFleet(lambda: ServingEngine(params, cfg, eos_id=2),
                         num_replicas=3).start()
    frid = fleet.submit(prompt_ids, max_new_tokens=128, ttl=30.0)
    tokens = fleet.result(frid, timeout=60)
    fleet.rolling_swap(timeout=30.0,          # zero-shed re-param
                       engine_factory=lambda: ServingEngine(
                           new_params, cfg, eos_id=2))
    fleet.drain(timeout=30)                   # or fleet.stop()

All waits are timeout-bounded (TOS001); the monitor thread is a daemon
(TOS007); knobs ride registered ``TOS_FLEET_*`` env vars (TOS008).
"""

import collections
import contextlib
import itertools
import logging
import os
import queue as std_queue
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from tensorflowonspark_tpu.obs import metrics as obs_metrics
from tensorflowonspark_tpu.obs import spans as obs_spans
from tensorflowonspark_tpu.serving import engine as engine_mod
from tensorflowonspark_tpu.serving import scheduler as sched
from tensorflowonspark_tpu.utils import chaos

logger = logging.getLogger(__name__)

#: replica count when the ctor passes ``num_replicas=None``
ENV_FLEET_REPLICAS = "TOS_FLEET_REPLICAS"
#: fleet monitor cadence in seconds — the bound on every fleet wait
ENV_FLEET_POLL = "TOS_FLEET_POLL"
#: cross-replica failovers tolerated per request before it is failed
#: (the fleet-level poison analogue: a request that kills every replica
#: it lands on must not chew through the whole fleet)
ENV_FLEET_MAX_FAILOVERS = "TOS_FLEET_MAX_FAILOVERS"
#: consecutive health-probe failures before a replica is ejected
ENV_FLEET_PROBE_FAILS = "TOS_FLEET_PROBE_FAILS"
#: submit retry bound in seconds for requests with NO deadline of their
#: own — with one, the request's deadline bounds the retries instead
ENV_FLEET_ADMIT_TIMEOUT = "TOS_FLEET_ADMIT_TIMEOUT"
#: replica-count ceiling for the ``on_saturated`` scale-up hook. UNSET
#: (and no ``max_replicas`` arg) means the hook is OFF — saturation
#: stays signal-only (the ``fleet_saturated`` detector), exactly as
#: before. Set it and a saturated submit may add replicas (from the
#: fleet's current factory — the deploy controller keeps that pointed at
#: the promoted version) up to this bound.
ENV_FLEET_MAX_REPLICAS = "TOS_FLEET_MAX_REPLICAS"

_DEFAULT_REPLICAS = 2
_DEFAULT_POLL = 0.05
_DEFAULT_MAX_FAILOVERS = 3
_DEFAULT_PROBE_FAILS = 3
_DEFAULT_ADMIT_TIMEOUT = 30.0
#: retry sleep when a rejection carries no usable retry_after hint
_DEFAULT_RETRY_SLEEP = 0.1
#: bounded structured-event log (ejections, failovers, swaps)
_EVENT_CAP = 256

ACTIVE = "active"
DRAINING = "draining"
EJECTED = "ejected"

_fleet_request_ids = itertools.count(1)


def _env_int(name: str, default: int) -> int:
  return int(os.environ.get(name, str(default)))


def _env_float(name: str, default: float) -> float:
  return float(os.environ.get(name, str(default)))


class Replica(object):
  """One engine slot in the fleet: the engine plus routing state."""

  __slots__ = ("rid", "engine", "state", "reason", "probe_fails",
               "dispatches", "generation")

  def __init__(self, rid: int, engine):
    self.rid = rid
    self.engine = engine
    self.state = ACTIVE
    self.reason: Optional[str] = None      # why ejected
    self.probe_fails = 0                   # consecutive failed probes
    self.dispatches = 0                    # requests routed here
    self.generation = 0                    # bumped per rolling swap


class FleetRequest(object):
  """One logical request as the FLEET sees it: the prompt/budget/deadline
  plus the chain of replica attempts it rode. Clients hold this handle;
  the engine-side :class:`~.scheduler.Request` objects underneath it are
  disposable (a failover abandons one and creates the next).

  ``prev_tokens`` records the longest generated prefix any dead attempt
  produced — the successful attempt's output is verified against it
  (greedy ⇒ bit-identical; disagreement counts ``replay_mismatches``
  instead of being trusted blindly), and ``stream()`` uses its own
  delivered history the same way to keep each position exactly-once
  across the replica hop.

  The fleet mints the request's ``trace_id`` at submit and hands it to
  EVERY engine attempt (``ServingEngine.submit(trace_id=...)``), so a
  cross-replica failover hop stays ONE trace — the spans both replicas
  emitted share it, which is what lets ``obs_report --request`` render
  the hop. ``first_token_at`` is the timing-ledger TTFT stamp: the
  EARLIEST first token any attempt delivered to the client — a failover
  replay regenerates positions the client already holds, so it never
  moves this stamp (the engine-side crash-replay rule, applied across
  replicas)."""

  __slots__ = ("frid", "prompt", "max_new_tokens", "deadline", "done",
               "error", "output", "cancelled", "submitted_at",
               "finished_at", "first_token_at", "trace_id",
               "attempts", "cur_replica", "cur_rid",
               "cur_req", "attempt_seq", "prev_tokens", "failovers",
               "next_try", "model_version")

  def __init__(self, prompt, max_new_tokens: int, deadline=None):
    self.frid = next(_fleet_request_ids)
    self.prompt = np.asarray(prompt, np.int32).ravel()
    self.max_new_tokens = int(max_new_tokens)
    self.deadline = None if deadline is None else float(deadline)
    self.done = threading.Event()
    self.error: Optional[BaseException] = None
    self.output: Optional[np.ndarray] = None
    self.cancelled = threading.Event()
    self.submitted_at = time.monotonic()
    self.finished_at: Optional[float] = None
    self.first_token_at: Optional[float] = None
    self.trace_id = obs_spans.new_trace_id()
    self.attempts: List[tuple] = []        # (replica_id, engine_rid)
    self.cur_replica: Optional[int] = None
    self.cur_rid: Optional[int] = None
    self.cur_req = None                    # engine-side Request handle
    self.attempt_seq = 0
    self.prev_tokens: List[int] = []
    self.failovers = 0
    self.next_try = 0.0                    # earliest failover re-place
    #: registry version of the replica that SERVED this request (the
    #: deploy plane's stamp; None when no version map is set) — rides
    #: the timing ledger and the fleet.dispatch span so every trace
    #: names the model that answered it
    self.model_version = None

  def expired(self, now: Optional[float] = None) -> bool:
    if self.deadline is None:
      return False
    return (time.monotonic() if now is None else now) >= self.deadline

  def note_first_token(self, at: Optional[float]) -> None:
    """Fold one attempt's first-token stamp into the ledger (earliest
    wins; a replayed attempt's later stamp never resets TTFT)."""
    if at is not None and (self.first_token_at is None
                           or at < self.first_token_at):
      self.first_token_at = at

  @property
  def ttft(self) -> Optional[float]:
    if self.first_token_at is None:
      return None
    return self.first_token_at - self.submitted_at

  def timing(self) -> dict:
    """The fleet-level timing ledger (the engine ``Request.timing``
    shape, plus ``failovers``/``attempts``)."""
    return {"trace_id": self.trace_id, "frid": self.frid,
            "submitted": self.submitted_at,
            "first_token": self.first_token_at,
            "finished": self.finished_at,
            "ttft": self.ttft, "e2e": self.latency,
            "failovers": self.failovers,
            "attempts": list(self.attempts),
            "model_version": self.model_version,
            "replica": self.cur_replica}

  def finish(self, error: Optional[BaseException],
             output: Optional[np.ndarray] = None) -> bool:
    """Idempotent single verdict (the engine Request.finish rule).
    Returns True only for the call that SET the verdict — completion
    accounting keys on it, since the monitor sweep and a stream()
    consumer can both observe the same clean finish."""
    if self.done.is_set():
      return False
    self.error = error
    self.output = output
    self.finished_at = time.monotonic()
    self.done.set()
    return True

  @property
  def latency(self) -> Optional[float]:
    if self.finished_at is None:
      return None
    return self.finished_at - self.submitted_at


class ServingFleet(object):
  """Route requests across N ServingEngine replicas; keep serving
  through replica failure, overload and rolling param swaps."""

  def __init__(self, engine_factory: Callable[[], object],
               num_replicas: Optional[int] = None,
               poll_interval: Optional[float] = None,
               max_failovers: Optional[int] = None,
               probe_fails: Optional[int] = None,
               admit_timeout: Optional[float] = None,
               health_probe: Optional[Callable[[Replica], bool]] = None,
               max_replicas: Optional[int] = None):
    # explicit arguments beat the env knobs (the num_slots rule)
    n = int(num_replicas if num_replicas is not None
            else _env_int(ENV_FLEET_REPLICAS, _DEFAULT_REPLICAS))
    if n < 1:
      raise ValueError("num_replicas must be >= 1, got %d" % n)
    #: scale-up ceiling for :meth:`on_saturated`; None (knob unset, no
    #: arg) keeps the hook OFF — saturation stays signal-only
    cap = int(max_replicas if max_replicas is not None
              else _env_int(ENV_FLEET_MAX_REPLICAS, 0))
    self.max_replicas = cap if cap > 0 else None
    if self.max_replicas is not None and self.max_replicas < n:
      raise ValueError("max_replicas %d < num_replicas %d"
                       % (self.max_replicas, n))
    self._factory = engine_factory
    self._poll = float(poll_interval if poll_interval is not None
                       else _env_float(ENV_FLEET_POLL, _DEFAULT_POLL))
    self.max_failovers = int(
        max_failovers if max_failovers is not None
        else _env_int(ENV_FLEET_MAX_FAILOVERS, _DEFAULT_MAX_FAILOVERS))
    self.probe_fails = max(1, int(
        probe_fails if probe_fails is not None
        else _env_int(ENV_FLEET_PROBE_FAILS, _DEFAULT_PROBE_FAILS)))
    self.admit_timeout = float(
        admit_timeout if admit_timeout is not None
        else _env_float(ENV_FLEET_ADMIT_TIMEOUT, _DEFAULT_ADMIT_TIMEOUT))
    #: optional liveness probe ``(Replica) -> bool`` consulted every
    #: monitor pass — the in-process stand-in for "answers HEALTH": an
    #: out-of-process deployment points this at the replica's HEALTH
    #: round-trip. ``probe_fails`` CONSECUTIVE False/raising probes
    #: eject the replica; the engine's own ``alive`` flag is always
    #: checked first and needs no probe.
    self.health_probe = health_probe
    self._replicas: Dict[int, Replica] = {
        rid: Replica(rid, engine_factory()) for rid in range(n)}
    self.num_replicas = n
    self._lock = threading.Lock()
    self._stats_lock = threading.Lock()
    self._requests: Dict[int, FleetRequest] = {}
    self._pending: collections.deque = collections.deque()
    self._draining = False
    self._stop_evt = threading.Event()
    self._thread: Optional[threading.Thread] = None
    #: bounded structured event log: {"event": eject|failover|swap, ...}
    self.events: collections.deque = collections.deque(maxlen=_EVENT_CAP)
    # counters ONLY (the engine stats rule: StatsSnapshot subtracts).
    # "submitted" counts CLIENT requests at the fleet boundary — the
    # availability SLO's denominator (obs.slo): engine-level
    # serve.submitted counts dispatch ATTEMPTS, which fleet
    # retries/failovers inflate, and a total-outage submit never
    # reaches an engine at all
    self.stats = {"submitted": 0, "dispatched": 0, "completed": 0,
                  "rejected": 0,
                  "retries": 0, "failovers": 0, "replays": 0,
                  "replay_mismatches": 0, "ejections": 0, "swaps": 0,
                  "shed": 0, "monitor_failures": 0, "scale_ups": 0,
                  "canary_dispatches": 0}
    #: canary routing state: {"rid", "every", "counter"} while a deploy
    #: canary holds one replica (serving.deploy), else None
    self._canary: Optional[dict] = None
    #: replica id -> registry model version (the deploy plane's map;
    #: stamps requests/spans, read back by version-consistency checks)
    self._versions: Dict[int, object] = {}
    self._rec = obs_spans.active()
    reg = obs_metrics.active()
    self._obs_m = None if reg is None else {
        k: reg.counter("fleet." + k) for k in self.stats}
    self._obs_g = None if reg is None else {
        "replicas_total": reg.gauge("fleet.replicas_total"),
        "replicas_active": reg.gauge("fleet.replicas_active"),
        "replicas_draining": reg.gauge("fleet.replicas_draining"),
        "queue_depth": reg.gauge("fleet.queue_depth"),
        "occupancy": reg.gauge("fleet.occupancy"),
    }

  # -- bookkeeping -----------------------------------------------------------

  def _count(self, key: str, n: int = 1) -> None:
    with self._stats_lock:
      self.stats[key] += n
    if self._obs_m is not None:
      self._obs_m[key].inc(n)

  def stats_snapshot(self) -> obs_metrics.StatsSnapshot:
    """Subtraction baseline over the live stats dict (serve_bench)."""
    return obs_metrics.snapshot_stats(self.stats)

  def _event(self, kind: str, **fields) -> None:
    rec = dict(fields, event=kind, t=time.monotonic())
    self.events.append(rec)
    logger.warning("fleet %s: %s", kind, fields)
    if self._rec is not None:
      self._rec.event("fleet." + kind, **{
          k: v for k, v in fields.items()
          if isinstance(v, (int, float, str, bool))})

  # -- lifecycle -------------------------------------------------------------

  def start(self) -> "ServingFleet":
    if self._thread is not None and self._thread.is_alive():
      return self
    self._stop_evt.clear()
    self._draining = False
    for rep in self._replicas.values():
      if rep.state != EJECTED:
        rep.engine.start()
    self._thread = threading.Thread(target=self._monitor, daemon=True,
                                    name="tos-serving-fleet")
    self._thread.start()
    return self

  def stop(self, timeout: float = 30.0) -> None:
    """Stop the monitor and every replica; unfinished requests fail.
    Idempotent, safe before :meth:`start`."""
    self._stop_evt.set()
    t = self._thread
    if t is not None:
      t.join(timeout=timeout)
    err = RuntimeError("serving fleet stopped")
    for rep in self._replicas.values():
      if rep.state != EJECTED:
        rep.engine.stop(timeout=max(1.0, timeout / max(1, len(
            self._replicas))))
    with self._lock:
      reqs = list(self._requests.values())
      self._pending.clear()
    for freq in reqs:
      freq.finish(err)

  def drain(self, timeout: float) -> bool:
    """Graceful fleet shutdown: close admission, finish every accepted
    request (on whichever replica holds it, failing over if one dies
    mid-drain), then stop. True when all accepted work completed inside
    ``timeout``. ``timeout`` required (TOS001, the engine drain rule)."""
    deadline = time.monotonic() + max(0.0, float(timeout))
    self._draining = True
    while time.monotonic() < deadline:
      if self._idle():
        break
      if self._thread is None or not self._thread.is_alive():
        break
      time.sleep(min(0.05, self._poll))
    completed = self._idle()
    self.stop(timeout=max(1.0, deadline - time.monotonic()))
    return completed

  def _idle(self) -> bool:
    with self._lock:
      if self._pending:
        return False
      return all(freq.done.is_set() for freq in self._requests.values())

  def __enter__(self):
    return self.start()

  def __exit__(self, *exc):
    self.stop()

  @property
  def alive(self) -> bool:
    """False once the fleet is stopped or has no live replica left."""
    t = self._thread
    if t is not None and not t.is_alive() and self._stop_evt.is_set():
      return False
    return any(rep.state != EJECTED and rep.engine.alive
               for rep in self._replicas.values())

  def replica_states(self) -> Dict[int, str]:
    return {rid: rep.state for rid, rep in self._replicas.items()}

  @property
  def active_replicas(self) -> int:
    return sum(1 for rep in self._replicas.values()
               if rep.state == ACTIVE and rep.engine.alive)

  # -- dispatch --------------------------------------------------------------

  def _score(self, rep: Replica):
    """Load score: estimated seconds to clear the replica's queued
    token backlog at its live decode rate (a cold replica competes on
    raw backlog — comparable enough: an idle cold replica scores 0),
    tie-broken by queue depth, instantaneous occupancy, replica id."""
    eng = rep.engine
    backlog = eng.queued_tokens
    rate = eng.tokens_per_sec
    wait = backlog / rate if rate > 0 else float(backlog)
    return (wait, eng.queue_depth, eng.occupancy_now, rep.rid)

  def _dispatch_order(self) -> List[Replica]:
    with self._lock:
      live = [rep for rep in self._replicas.values()
              if rep.state == ACTIVE and rep.engine.alive]
    return sorted(live, key=self._score)

  def _canary_order(self) -> List[Replica]:
    """Dispatch order under an active canary: every ``every``-th
    placement round tries the canary replica FIRST (the configured
    traffic slice); all other rounds try it LAST — baseline traffic
    stays off the candidate, but a fully-overloaded baseline can still
    fall back to the canary rather than shed (zero-shed beats slice
    purity)."""
    order = self._dispatch_order()
    with self._lock:
      can = self._canary
      if can is None:
        return order
      rid = can["rid"]
      can["counter"] += 1
      take = can["every"] > 0 and can["counter"] % can["every"] == 0
    canary = [r for r in order if r.rid == rid]
    others = [r for r in order if r.rid != rid]
    if not canary:
      return order
    return canary + others if take else others + canary

  def _try_place(self, freq: FleetRequest) -> Optional[float]:
    """One dispatch round over every live replica, best-scored first
    (canary-slice-aware while a canary is set). Returns None when
    placed; the smallest ``retry_after`` hint when every replica
    rejected (inf when none was even reachable)."""
    hint = None
    for rep in self._canary_order():
      if chaos.fleet_fault("dispatch", rep.rid) == "kill":
        # replica-granularity chaos: this replica dies AT this dispatch
        # (mid-decode for everything it already accepted) — eject now so
        # the request lands on a live peer and failover replays begin
        self._kill_replica(rep, chaos.InjectedFault(
            "chaos: fleet replica %d killed at dispatch" % rep.rid))
        continue
      rep.dispatches += 1
      t0 = time.monotonic()
      try:
        # the fleet's trace_id rides every attempt: a failover hop's
        # spans on the NEXT replica join the same trace
        erid = rep.engine.submit(freq.prompt,
                                 max_new_tokens=freq.max_new_tokens,
                                 deadline=freq.deadline,
                                 trace_id=freq.trace_id)
      except sched.ServingOverloaded as e:
        ra = e.retry_after
        if ra is not None and (hint is None or ra < hint):
          hint = ra
        continue
      except sched.DeadlineExceeded:
        raise
      except RuntimeError:
        # the replica died between the order snapshot and the submit —
        # the monitor's next pass ejects it; try the next one
        continue
      if self._rec is not None:
        # the routing phase of the waterfall: which replica took it,
        # whether this was a fresh dispatch or a failover re-place, and
        # (deploy plane) which model version answers it
        attrs = {"trace": freq.trace_id, "replica": rep.rid,
                 "attempt": freq.attempt_seq + 1}
        ver = self._versions.get(rep.rid)
        if ver is not None:
          attrs["model_version"] = ver
        self._rec.record_span("fleet.dispatch", t0,
                              time.monotonic() - t0, **attrs)
      self._assign(freq, rep, erid)
      return None
    return hint if hint is not None else float("inf")

  def _assign(self, freq: FleetRequest, rep: Replica, erid: int) -> None:
    handle = rep.engine.request(erid)
    with self._lock:
      freq.attempts.append((rep.rid, erid))
      freq.cur_replica = rep.rid
      freq.cur_rid = erid
      freq.cur_req = handle
      freq.attempt_seq += 1
      freq.model_version = self._versions.get(rep.rid)
      can = self._canary
      if freq.cancelled.is_set():
        handle.cancelled.set()             # cancel raced the placement
    self._count("dispatched")
    if can is not None and can["rid"] == rep.rid:
      self._count("canary_dispatches")

  def submit(self, prompt, max_new_tokens: Optional[int] = None,
             deadline: Optional[float] = None,
             ttl: Optional[float] = None) -> int:
    """Queue one prompt on the least-loaded live replica; returns the
    fleet request id.

    When every replica rejects (:class:`ServingOverloaded`), retries
    with backoff honoring the smallest structured ``retry_after``,
    bounded by the request's own deadline (or ``TOS_FLEET_ADMIT_TIMEOUT``
    without one) — then re-raises a fleet-level ``ServingOverloaded``
    carrying the hint. Validation errors (empty/oversized prompt) and
    dead-on-arrival deadlines surface immediately, as on the engine.
    """
    if deadline is not None and ttl is not None:
      raise ValueError("pass deadline OR ttl, not both")
    now = time.monotonic()
    if ttl is not None:
      deadline = now + float(ttl)
    if len(np.asarray(prompt, np.int32).ravel()) < 1:
      # the engine's empty-prompt rule, checked at the fleet boundary:
      # a malformed request is a caller bug, not traffic, and must stay
      # out of BOTH sides of the availability ratio
      raise ValueError("prompt must contain at least one token")
    # "submitted" is the availability SLO's denominator — client traffic
    # counted at the fleet boundary, at every OUTCOME point below (never
    # on a validation error, and paired with "rejected" on every
    # client-visible admission failure, including a dead fleet: a total
    # outage must move the ratio it exists to burn)
    if max_new_tokens is None:
      # replicas share one config; any live engine's default applies
      rep = next((r for r in self._replicas.values()
                  if r.state != EJECTED), None)
      if rep is None:
        self._count("submitted")
        self._count("rejected")
        raise RuntimeError("serving fleet has no replicas left")
      max_new_tokens = rep.engine.default_max_new_tokens
    freq = FleetRequest(prompt, max_new_tokens, deadline=deadline)
    if freq.expired(now):
      # traffic, but not unavailability: the engine's expired rule
      self._count("submitted")
      raise sched.DeadlineExceeded(
          "request dead on arrival: its deadline already passed at "
          "submit")
    if self._draining:
      self._count("submitted")
      self._count("rejected")
      # a usable hint, never None (the engine's draining-rejection
      # rule): this fleet is going away, so the bounded cold-start
      # default is the honest "come back shortly, elsewhere" signal
      raise sched.ServingOverloaded(
          "serving fleet is draining — admission is closed",
          retry_after=engine_mod._COLD_RETRY_AFTER, draining=True)
    if not self.alive:
      self._count("submitted")
      self._count("rejected")
      raise RuntimeError("serving fleet is stopped or has no live "
                         "replicas")
    admit_deadline = min(
        freq.deadline if freq.deadline is not None else float("inf"),
        now + self.admit_timeout)
    with self._lock:
      self._requests[freq.frid] = freq
    first = True
    while True:
      try:
        hint = self._try_place(freq)
      except BaseException as e:
        with self._lock:
          self._requests.pop(freq.frid, None)
        # engine-side validation (ValueError: e.g. a prompt the paged
        # pool can never host) is a caller bug — everything else that
        # escapes the placement loop was real traffic
        if not isinstance(e, ValueError):
          self._count("submitted")
          if not isinstance(e, sched.DeadlineExceeded):
            self._count("rejected")
        raise
      if hint is None:
        self._count("submitted")
        return freq.frid
      if not first:
        self._count("retries")
      first = False
      if self.on_saturated():
        continue             # a fresh replica may take it — retry now
      sleep = hint if hint not in (None, float("inf")) \
          else _DEFAULT_RETRY_SLEEP
      remaining = admit_deadline - time.monotonic()
      if remaining <= 0 or not self.alive:
        with self._lock:
          self._requests.pop(freq.frid, None)
        self._count("submitted")
        self._count("rejected")
        if not self.alive:
          raise RuntimeError("serving fleet has no live replicas")
        raise sched.ServingOverloaded(
            "every replica rejected for the whole fleet admission "
            "window (%d live)" % self.active_replicas,
            retry_after=sleep if sleep != float("inf") else None)
      # bounded, stop-interruptible backoff honoring retry_after
      if self._rec is not None:
        self._rec.event("fleet.backoff", trace=freq.trace_id,
                        retry_after=float(sleep)
                        if sleep != float("inf") else None)
      self._stop_evt.wait(min(max(sleep, self._poll), remaining))

  # -- client read side ------------------------------------------------------

  def _freq(self, frid: int) -> FleetRequest:
    with self._lock:
      try:
        return self._requests[frid]
      except KeyError:
        raise KeyError("unknown fleet request id %r" % (frid,))

  def request(self, frid: int) -> FleetRequest:
    """The live FleetRequest handle (latency/attempt fields ride it).
    Hold it before :meth:`result` — that pops the registry entry."""
    return self._freq(frid)

  def _raise_if_dead(self, what: str) -> None:
    if not self.alive:
      raise RuntimeError("serving fleet is stopped or has no live "
                         "replicas; %s cannot finish" % what)

  def result(self, frid: int, timeout: float = 600.0) -> np.ndarray:
    """Block (bounded) for one request's output (prompt + generated).
    Fails fast when the fleet is dead, like the engine's waiters."""
    freq = self._freq(frid)
    deadline = time.monotonic() + timeout
    chunk = max(0.05, self._poll)
    while not freq.done.is_set():
      remaining = deadline - time.monotonic()
      if remaining <= 0:
        raise TimeoutError("fleet request %d not finished within %.1fs"
                           % (frid, timeout))
      if not freq.done.wait(timeout=min(chunk, remaining)):
        self._raise_if_dead("fleet request %d" % frid)
    with self._lock:
      self._requests.pop(frid, None)
    err = freq.error
    if isinstance(err, (sched.DeadlineExceeded, sched.RequestCancelled,
                        sched.PoisonedRequest)):
      raise err
    if err is not None:
      raise RuntimeError("fleet request %d failed" % frid) from err
    return freq.output

  def stream(self, frid: int, timeout: float = 600.0):
    """Yield generated tokens as they are produced (EOS inclusive),
    exactly once per position — across engine crash replays (the engine
    suppresses those) AND across fleet failovers to another replica:
    a new attempt regenerates from the prompt, and this relay suppresses
    (verifying) the prefix it already delivered."""
    freq = self._freq(frid)
    deadline = time.monotonic() + timeout
    t_attach = time.monotonic()
    chunk = max(0.05, self._poll)
    delivered: List[int] = []
    er = None
    er_done = False
    pos = 0
    while True:
      if time.monotonic() >= deadline:
        raise TimeoutError("stream for fleet request %d stalled" % frid)
      with self._lock:
        cur = freq.cur_req
      if cur is not er:
        er, pos, er_done = cur, 0, False   # failover: new attempt stream
      if er is None or er_done:
        if freq.done.is_set():
          break                            # terminal verdict below
        self._raise_if_dead("fleet request %d" % frid)
        time.sleep(chunk)
        continue
      try:
        tok = er.stream_q.get(timeout=chunk)
      except std_queue.Empty:
        self._raise_if_dead("fleet request %d" % frid)
        continue
      if tok is None:
        if er.error is None:
          break                            # attempt completed cleanly
        if isinstance(er.error, (sched.DeadlineExceeded,
                                 sched.RequestCancelled,
                                 sched.PoisonedRequest)):
          break                            # structured verdict below
        er_done = True                     # crashed: await the failover
        continue
      if pos < len(delivered):
        # replayed position from the new replica: suppress, but VERIFY
        # — greedy bit-identity says it matches what we delivered
        if int(tok) != delivered[pos]:
          self._count("replay_mismatches")
        pos += 1
        continue
      if not delivered:
        freq.note_first_token(time.monotonic())
      delivered.append(int(tok))
      pos += 1
      yield int(tok)
    # record the verdict ourselves instead of racing the monitor's next
    # sweep: a consumer that breaks on the sentinel and popped the
    # registry before that sweep would otherwise leave the request
    # without a terminal verdict (done never set, completed uncounted,
    # a concurrent cancel() parked until its timeout)
    if not freq.done.is_set() and er is not None and er.done.is_set():
      if er.error is None:
        self._finish_ok(freq, er)
      else:
        freq.finish(er.error)
    if self._rec is not None:
      # the delivery phase, fleet-side: the relay that survived the
      # replica hop (tokens = client-visible positions, exactly once)
      self._rec.record_span("fleet.stream", t_attach,
                            time.monotonic() - t_attach,
                            trace=freq.trace_id, frid=frid,
                            tokens=len(delivered),
                            failovers=freq.failovers)
    with self._lock:
      self._requests.pop(frid, None)
    err = freq.error if freq.done.is_set() else \
        (er.error if er is not None else None)
    if isinstance(err, (sched.DeadlineExceeded, sched.RequestCancelled,
                        sched.PoisonedRequest)):
      raise err
    if err is not None:
      raise RuntimeError("fleet request %d failed after %d token(s)"
                         % (frid, len(delivered))) from err

  def generate(self, prompts: Sequence,
               max_new_tokens: Optional[int] = None,
               timeout: float = 600.0, detailed: bool = False) -> List:
    """Submit a batch and wait for all outputs in order; a mid-list
    rejection cancels the already-submitted prefix (the engine rule).
    ``detailed=True`` returns ``{"tokens", "trace_id", "timing"}`` per
    prompt (the fleet timing ledger incl. ``failovers``), mirroring
    ``ServingEngine.generate``."""
    frids = []
    try:
      for p in prompts:
        frids.append(self.submit(p, max_new_tokens=max_new_tokens))
    except BaseException:
      for frid in frids:
        with contextlib.suppress(Exception):
          self.cancel(frid, timeout=1.0)
      raise
    deadline = time.monotonic() + timeout
    outs = []
    for frid in frids:
      freq = self._freq(frid)   # hold the handle: result() pops the map
      out = self.result(frid,
                        timeout=max(0.001, deadline - time.monotonic()))
      if detailed:
        outs.append({"tokens": out, "trace_id": freq.trace_id,
                     "timing": freq.timing()})
      else:
        outs.append(out)
    return outs

  def cancel(self, frid: int, timeout: float) -> bool:
    """Cancel a fleet request wherever it currently lives (queued on a
    replica, in flight, or between replicas awaiting failover). Blocks
    (bounded) until it finished; ``timeout`` required (TOS001)."""
    freq = self._freq(frid)
    if freq.done.is_set():
      return True
    freq.cancelled.set()
    with self._lock:
      er = freq.cur_req
    if er is not None:
      er.cancelled.set()                   # the replica reaps it
    freq.done.wait(timeout=timeout)
    return freq.done.is_set()

  # -- rolling swap & the deploy-plane surface -------------------------------

  def swap_replica(self, rid: int, timeout: float,
                   engine_factory: Optional[Callable] = None,
                   version=None) -> dict:
    """Zero-shed swap of ONE replica: mark it DRAINING (dispatch shifts
    to the others), drain it through the engine's zero-shed ``drain()``
    contract, then swap in a fresh engine from ``engine_factory``
    (default: the fleet's own factory). The canary move in the deploy
    state machine — and the unit :meth:`rolling_swap` iterates.
    ``version`` (when given) updates the replica's entry in the served-
    version map. A drain that times out still sheds nothing: leftovers
    fail over to live replicas and replay. ``timeout`` required
    (TOS001, the drain rule)."""
    rep = self._replicas[rid]
    if rep.state == EJECTED:
      return {"replica": rid, "skipped": "ejected"}
    factory = engine_factory if engine_factory is not None \
        else self._factory
    with self._lock:
      rep.state = DRAINING                 # dispatch skips it from here
    self._event("swap_start", replica=rid)
    drained = rep.engine.drain(timeout=timeout)
    new_eng = factory()
    new_eng.start()
    with self._lock:
      rep.engine = new_eng
      rep.state = ACTIVE
      rep.probe_fails = 0
      rep.generation += 1
      if version is not None:
        self._versions[rid] = version
    self._count("swaps")
    self._event("swap_done", replica=rid, drained=bool(drained),
                generation=rep.generation,
                **({} if version is None else {"model_version": version}))
    return {"replica": rid, "drained": bool(drained),
            "generation": rep.generation}

  def rolling_swap(self, timeout: float,
                   engine_factory: Optional[Callable] = None,
                   version=None) -> dict:
    """Fleet-wide zero-shed param swap: one replica at a time through
    :meth:`swap_replica` — pass an ``engine_factory`` closing over new
    params to re-param the whole fleet with zero accepted requests shed.
    ``timeout`` bounds EACH replica's drain and is required (TOS001)."""
    if engine_factory is not None:
      self._factory = engine_factory       # future ejection rebuilds too
    report = [self.swap_replica(rid, timeout,
                                engine_factory=engine_factory,
                                version=version)
              for rid in sorted(self._replicas)]
    return {"swapped": sum(1 for r in report if "drained" in r),
            "replicas": report}

  def set_canary(self, rid: int, every: int) -> None:
    """Route every ``every``-th placement round to replica ``rid`` first
    (the canary traffic slice, deterministic by construction); all other
    rounds keep baseline traffic off it. ``every=4`` ≈ a 25% slice."""
    if rid not in self._replicas:
      raise KeyError("unknown replica id %r" % (rid,))
    if every < 1:
      raise ValueError("canary slice divisor must be >= 1, got %d" % every)
    with self._lock:
      self._canary = {"rid": int(rid), "every": int(every), "counter": 0}

  def clear_canary(self) -> None:
    with self._lock:
      self._canary = None

  @property
  def canary_rid(self) -> Optional[int]:
    with self._lock:
      return None if self._canary is None else self._canary["rid"]

  def set_replica_version(self, rid: int, version) -> None:
    """Record which registry version replica ``rid`` serves — stamped
    onto every request it answers (timing ledger + dispatch span)."""
    with self._lock:
      self._versions[int(rid)] = version

  def served_versions(self) -> Dict[int, object]:
    """{replica id: model version} over non-ejected replicas (None for
    replicas never stamped) — the deploy controller's consistency read."""
    with self._lock:
      return {rid: self._versions.get(rid)
              for rid, rep in self._replicas.items()
              if rep.state != EJECTED}

  def add_replica(self, engine_factory: Optional[Callable] = None,
                  version=None) -> int:
    """Grow the fleet by one replica (from ``engine_factory`` or the
    fleet's current factory); returns the new replica id. Started
    immediately when the fleet runs. Unbounded on purpose — the CAPPED
    entry point is :meth:`on_saturated`."""
    factory = engine_factory if engine_factory is not None \
        else self._factory
    eng = factory()
    t = self._thread
    if t is not None and t.is_alive():
      eng.start()
    with self._lock:
      rid = (max(self._replicas) + 1) if self._replicas else 0
      self._replicas[rid] = Replica(rid, eng)
      self.num_replicas += 1
      if version is not None:
        self._versions[rid] = version
    self._count("scale_ups")
    self._event("scale_up", replica=rid, total=self.num_replicas)
    return rid

  def on_saturated(self, engine_factory: Optional[Callable] = None) -> bool:
    """Capped scale-up hook: when the fleet is saturated (every live
    replica rejecting — the condition the ``fleet_saturated`` detector
    alerts on), add ONE replica, bounded by ``max_replicas`` /
    ``TOS_FLEET_MAX_REPLICAS``. OFF unless that bound is configured
    (saturation stays signal-only, the pre-existing behavior). Called
    automatically from the submit retry path; also callable by an
    external actuator reacting to the detector's alert. Returns True
    when a replica was added."""
    if self.max_replicas is None:
      return False
    with self._lock:
      live = sum(1 for rep in self._replicas.values()
                 if rep.state != EJECTED)
    if live >= self.max_replicas:
      return False
    self.add_replica(engine_factory)
    return True

  # -- ejection & failover ---------------------------------------------------

  def _kill_replica(self, rep: Replica, cause: BaseException) -> None:
    """Chaos/test seam: terminal replica death + immediate ejection."""
    rep.engine.kill(cause)
    self._eject(rep, "chaos-kill", cause)

  def _eject(self, rep: Replica, reason: str,
             cause: Optional[BaseException]) -> None:
    """Remove a replica from dispatch and fail over everything it had
    accepted but not finished. Idempotent (check-and-set under the
    fleet lock): the monitor and a chaos kill can race here safely."""
    with self._lock:
      if rep.state == EJECTED:
        return
      rep.state = EJECTED
      rep.reason = reason
      victims = [freq for freq in self._requests.values()
                 if freq.cur_replica == rep.rid
                 and not freq.done.is_set()]
    self._count("ejections")
    self._event("eject", replica=rep.rid, reason=reason,
                victims=len(victims), cause=repr(cause)[:200])
    err = cause if cause is not None else RuntimeError(
        "replica %d ejected (%s)" % (rep.rid, reason))
    for freq in victims:
      self._begin_failover(freq, err)
    self._place_pending(time.monotonic())
    # best-effort isolation AND resource release: stop() is idempotent
    # and safe on a dead engine, and it is what drops the engine's KV
    # slabs/page pool (kill/_die leave them allocated) — skipping it
    # for an already-dead replica would pin a full slab's HBM for the
    # fleet's remaining lifetime while it serves degraded
    with contextlib.suppress(Exception):
      rep.engine.stop(timeout=1.0)

  def _begin_failover(self, freq: FleetRequest, cause: BaseException,
                      expect=None) -> None:
    """Detach a request from its dead replica and queue it for
    resubmission — capturing the emitted prefix first so the stream
    relay and the final-output verification can hold the exactly-once /
    bit-identical line across the hop.

    Exactly-once per attempt: the ejection path (which can run on a
    CLIENT thread via a chaos kill) and the monitor's completion sweep
    can both reach here for the same request — an already-detached
    request (``cur_req`` None) or one the sweep saw under a STALE
    handle (``expect`` no longer current) is left alone, so a request
    is never queued for failover twice off one death."""
    with self._lock:
      er = freq.cur_req
      if er is None or (expect is not None and er is not expect):
        return
      if len(er.tokens) > len(freq.prev_tokens):
        freq.prev_tokens = list(er.tokens)
      freq.note_first_token(er.first_token_at)
      freq.cur_req = None
      freq.cur_replica = None
      freq.cur_rid = None
      freq.failovers += 1
      over = freq.failovers > self.max_failovers
    if over:
      self._count("shed")
      err = RuntimeError(
          "fleet request %d failed over %d times (max %d) — not "
          "resubmitted" % (freq.frid, freq.failovers - 1,
                           self.max_failovers))
      err.__cause__ = cause
      freq.finish(err)
      return
    self._count("failovers")
    self._event("failover", frid=freq.frid, attempt=freq.failovers,
                emitted=len(freq.prev_tokens), trace=freq.trace_id)
    with self._lock:
      self._pending.append(freq)

  def _place_pending(self, now: float) -> None:
    """Resubmit failed-over requests to live replicas. Rejections keep
    the request pending with a ``retry_after``-honoring next-try time
    (the monitor cadence is the backoff floor), so failover replay
    respects the same admission bounds as fresh traffic without ever
    busy-spinning."""
    with self._lock:
      pending, self._pending = list(self._pending), collections.deque()
    keep = []
    for freq in pending:
      if freq.done.is_set():
        continue
      if freq.cancelled.is_set():
        freq.finish(sched.RequestCancelled(
            "fleet request %d cancelled" % freq.frid))
        continue
      if freq.expired(now):
        freq.finish(sched.DeadlineExceeded(
            "fleet request %d deadline passed awaiting failover"
            % freq.frid))
        continue
      if now < freq.next_try:
        keep.append(freq)
        continue
      if self.active_replicas == 0:
        if all(rep.state == EJECTED for rep in self._replicas.values()):
          self._count("shed")
          freq.finish(RuntimeError(
              "fleet request %d lost its replica and no live replica "
              "remains" % freq.frid))
          continue
        keep.append(freq)                  # draining swap: wait it out
        continue
      hint = self._try_place(freq)
      if hint is None:
        self._count("replays")
        continue
      self._count("retries")
      freq.next_try = now + (hint if hint != float("inf")
                             else _DEFAULT_RETRY_SLEEP)
      keep.append(freq)
    if keep:
      with self._lock:
        self._pending.extend(keep)

  # -- the monitor loop ------------------------------------------------------

  def _monitor(self) -> None:
    while not self._stop_evt.wait(self._poll):
      try:
        now = time.monotonic()
        self._check_replicas(now)
        self._place_pending(now)
        self._check_completions()
        self._update_gauges()
      except Exception:  # noqa: BLE001 - the monitor must outlive any
        # single pass's bug (the ClusterSupervisor._loop rule); the
        # engines keep serving without it, and the failure is VISIBLE:
        # counted + logged with the trace
        self._count("monitor_failures")
        logger.exception("fleet monitor pass failed")

  def _check_replicas(self, now: float) -> None:
    for rep in list(self._replicas.values()):
      if rep.state == EJECTED:
        continue
      eng = rep.engine
      if not eng.alive:
        if rep.state == DRAINING:
          continue   # a swap owns this engine's lifecycle right now
        self._eject(rep, "died", eng._loop_error
                    or RuntimeError("replica %d engine stopped"
                                    % rep.rid))
        continue
      if self.health_probe is None:
        continue
      try:
        ok = bool(self.health_probe(rep))
      except Exception:  # noqa: BLE001 - a raising probe IS a failed
        ok = False                         # probe, not a monitor crash
      if ok:
        rep.probe_fails = 0
        continue
      rep.probe_fails += 1
      if rep.probe_fails >= self.probe_fails:
        self._eject(rep, "unresponsive", RuntimeError(
            "replica %d failed %d consecutive health probes"
            % (rep.rid, rep.probe_fails)))

  def _check_completions(self) -> None:
    with self._lock:
      snapshot = [(freq, freq.cur_req) for freq in
                  self._requests.values()
                  if not freq.done.is_set() and freq.cur_req is not None]
    for freq, er in snapshot:
      if not er.done.is_set():
        continue
      err = er.error
      if err is None:
        self._finish_ok(freq, er)
      elif isinstance(err, (sched.DeadlineExceeded,
                            sched.RequestCancelled,
                            sched.PoisonedRequest)):
        freq.finish(err)
      else:
        # the replica died/stopped under it: replay it elsewhere (the
        # expect guard makes this a no-op if the ejection path already
        # detached it, or if it was re-placed since the snapshot)
        self._begin_failover(freq, err, expect=er)

  def _finish_ok(self, freq: FleetRequest, er) -> None:
    toks = list(er.tokens)
    freq.note_first_token(er.first_token_at)
    if not freq.finish(None, output=np.concatenate(
        [freq.prompt, np.asarray(toks, np.int32)])):
      return    # someone else (monitor vs stream consumer) got here first
    prev = freq.prev_tokens
    if prev and toks[:len(prev)] != prev[:len(toks)]:
      # the replayed output must re-derive what the dead attempt
      # emitted (greedy bit-identity) — count divergence, never hide it
      self._count("replay_mismatches")
    self._count("completed")

  def _update_gauges(self) -> None:
    if self._obs_g is None:
      return
    active = [rep for rep in self._replicas.values()
              if rep.state == ACTIVE and rep.engine.alive]
    draining = sum(1 for rep in self._replicas.values()
                   if rep.state == DRAINING)
    self._obs_g["replicas_total"].set(self.num_replicas)
    self._obs_g["replicas_active"].set(len(active))
    # a DRAINING replica is a healthy swap in progress, not lost
    # capacity: the fleet_degraded detector keys on active + draining
    # so a routine rolling swap never reads as an ejection
    self._obs_g["replicas_draining"].set(draining)
    self._obs_g["queue_depth"].set(
        sum(rep.engine.queue_depth for rep in active))
    if active:
      self._obs_g["occupancy"].set(
          sum(rep.engine.occupancy_now for rep in active) / len(active))
