"""Host side of continuous batching: requests, the admission queue, and
the prompt-bucket policy.

Pure bookkeeping — no device work happens here. The
:class:`ServingEngine` thread pops :class:`Request` objects off the
:class:`RequestQueue` whenever a slot frees and prefills them in
(``serving.slots``); callers hold the request handle and wait on its
event / stream queue. Every blocking wait is timeout-bounded (TOS001).

The robustness vocabulary also lives here (docs/ROBUSTNESS.md):

* :class:`ServingOverloaded` — structured admission rejection (queue
  depth / queued-token mass over the bound, or the engine is draining),
  carrying a ``retry_after`` hint derived from the live decode rate;
* :class:`DeadlineExceeded` — a request's TTL ran out (at submit, while
  queued, or mid-flight at a horizon boundary);
* :class:`RequestCancelled` — the client called ``cancel(rid)``;
* :class:`PoisonedRequest` — the request was in flight across
  ``poison_crashes`` consecutive engine crashes and is failed instead of
  replayed (no crash loops on one bad request).
"""

import collections
import itertools
import os
import queue as std_queue
import threading
import time
from typing import List, Optional

import numpy as np

#: comma list overriding the default prefill bucket sizes
#: (``serving.slots.DEFAULT_BUCKETS``)
ENV_SERVE_BUCKETS = "TOS_SERVE_BUCKETS"

_request_ids = itertools.count(1)


class ServingOverloaded(RuntimeError):
  """Admission rejected: the queue bound would be exceeded (or the
  engine is draining). ``retry_after`` (seconds, may be None) is derived
  from the engine's live tokens/s rate over the queued token mass —
  the client-visible backpressure signal."""

  def __init__(self, message: str, queue_depth: int = 0,
               queued_tokens: int = 0, retry_after=None,
               draining: bool = False):
    super().__init__(message)
    self.queue_depth = int(queue_depth)
    self.queued_tokens = int(queued_tokens)
    self.retry_after = retry_after
    self.draining = bool(draining)


class DeadlineExceeded(TimeoutError):
  """The request's deadline/TTL expired before it finished."""


class RequestCancelled(RuntimeError):
  """The client cancelled the request (``ServingEngine.cancel``)."""


class PoisonedRequest(RuntimeError):
  """Failed instead of replayed: the request was in flight across N
  consecutive engine crashes (the crash-loop breaker)."""


class QueueClosed(RuntimeError):
  """Internal: push on a closed queue (engine stopped or loop dead).
  Carries the closing cause so submit can fail fast with the root."""


class Request(object):
  """One in-flight generation request.

  ``tokens`` accumulates generated ids (EOS inclusive, never pad);
  ``done`` fires when the request finishes or fails; ``stream_q``
  receives each token as it is emitted, then a ``None`` sentinel.
  ``deadline`` is an absolute ``time.monotonic()`` bound (None = no
  deadline); ``cancelled`` is the client-side cancellation flag the
  engine loop reaps; ``crash_count`` counts engine crashes this request
  was blamed for (poison detection, docs/ROBUSTNESS.md).
  """

  __slots__ = ("rid", "prompt", "max_new_tokens", "tokens", "done",
               "stream_q", "error", "submitted_at", "started_at",
               "finished_at", "deadline", "cancelled", "crash_count",
               "_suppress")

  def __init__(self, prompt, max_new_tokens: int, deadline=None):
    self.rid = next(_request_ids)
    self.prompt = np.asarray(prompt, np.int32).ravel()
    self.max_new_tokens = int(max_new_tokens)
    self.tokens: List[int] = []
    self.done = threading.Event()
    self.stream_q: std_queue.Queue = std_queue.Queue()
    self.error: Optional[BaseException] = None
    self.submitted_at = time.monotonic()
    self.started_at: Optional[float] = None
    self.finished_at: Optional[float] = None
    self.deadline = None if deadline is None else float(deadline)
    self.cancelled = threading.Event()
    self.crash_count = 0
    # crash-replay suppression: how many upcoming emits regenerate
    # already-delivered positions (greedy ⇒ bit-identical) and must not
    # reach tokens/stream a second time
    self._suppress = 0

  @property
  def token_cost(self) -> int:
    """Worst-case token mass this request puts on the engine (prompt to
    prefill + budget to decode) — the unit of the queued-token bound."""
    return len(self.prompt) + self.max_new_tokens

  @property
  def generated(self) -> int:
    """Tokens generated in the CURRENT engine incarnation. Equal to
    ``len(tokens)`` except mid-replay, where already-recorded tokens are
    still being regenerated — budget math must use THIS, or a replayed
    request would stop short of re-reaching its pre-crash position."""
    return len(self.tokens) - self._suppress

  def expired(self, now: Optional[float] = None) -> bool:
    if self.deadline is None:
      return False
    return (time.monotonic() if now is None else now) >= self.deadline

  def begin_replay(self) -> None:
    """Arm suppression for a crash replay: the next ``len(tokens)``
    emits re-derive positions the client already holds."""
    self._suppress = len(self.tokens)

  def emit(self, token: int) -> bool:
    """Record one generated token. Returns replay parity: False when a
    suppressed (replayed) emit disagrees with the recorded token — the
    greedy bit-identity contract says that never happens; the engine
    counts violations instead of trusting it blindly."""
    token = int(token)
    if self._suppress:
      idx = len(self.tokens) - self._suppress
      self._suppress -= 1
      return self.tokens[idx] == token
    self.tokens.append(token)
    self.stream_q.put_nowait(token)        # unbounded: never blocks
    return True

  def finish(self, error: Optional[BaseException] = None) -> None:
    """Idempotent: a request failed by the crash path and again by
    ``stop()`` keeps its FIRST verdict (and one stream sentinel)."""
    if self.done.is_set():
      return
    self.error = error
    self.finished_at = time.monotonic()
    self.stream_q.put_nowait(None)         # unbounded: never blocks
    self.done.set()

  @property
  def latency(self) -> Optional[float]:
    if self.finished_at is None:
      return None
    return self.finished_at - self.submitted_at

  def output(self) -> np.ndarray:
    """prompt + generated tokens (EOS inclusive, no padding)."""
    return np.concatenate(
        [self.prompt, np.asarray(self.tokens, np.int32)])


def buckets_from_env(default):
  """The prefill bucket set: ``TOS_SERVE_BUCKETS`` (comma ints) or
  ``default``."""
  raw = os.environ.get(ENV_SERVE_BUCKETS, "").strip()
  if not raw:
    return tuple(default)
  try:
    sizes = tuple(int(p) for p in raw.split(",") if p.strip())
  except ValueError:
    raise ValueError("%s must be a comma list of ints, got %r"
                     % (ENV_SERVE_BUCKETS, raw))
  if not sizes or min(sizes) < 1:
    raise ValueError("%s must name positive chunk sizes, got %r"
                     % (ENV_SERVE_BUCKETS, raw))
  return sizes


class RequestQueue(object):
  """Thread-safe FIFO of pending requests with bounded waits, bounded
  admission, and a closed state.

  * ``push_bounded`` enforces the request-count AND queued-token-mass
    bounds (``ServingOverloaded``); an oversized request is still
    admitted when the queue is empty — it CAN be served (slots don't
    care), the bound is about backlog (the feedhub oversized-envelope
    rule).
  * ``close(error)`` atomically (under the one lock ``push`` uses)
    marks the queue dead and returns the drained backlog — the fix for
    the submit-vs-loop-death race: a push can land before or after the
    close, never between the dying loop's drain and its error mark.
  * ``push_front`` re-queues crash-replay requests ahead of the backlog
    (they were already admitted; bounds don't re-apply).
  """

  def __init__(self):
    self._items = collections.deque()
    self._cond = threading.Condition()
    self._tokens = 0                       # queued token mass
    self._closed: Optional[BaseException] = None

  def _check_open_locked(self):
    if self._closed is not None:
      raise QueueClosed("request queue is closed") from self._closed

  def push(self, request: Request) -> None:
    with self._cond:
      self._check_open_locked()
      self._items.append(request)
      self._tokens += request.token_cost
      self._cond.notify_all()

  def push_front(self, request: Request) -> None:
    """Replay re-queue: ahead of the backlog, exempt from bounds."""
    with self._cond:
      self._check_open_locked()
      self._items.appendleft(request)
      self._tokens += request.token_cost
      self._cond.notify_all()

  def push_bounded(self, request: Request, max_requests: int = 0,
                   max_tokens: int = 0) -> None:
    """Admit under the bounds (0 disables a bound) or raise
    :class:`ServingOverloaded` / :class:`QueueClosed`."""
    with self._cond:
      self._check_open_locked()
      depth, tokens = len(self._items), self._tokens
      if max_requests and depth >= max_requests:
        raise ServingOverloaded(
            "serving queue full: %d queued request(s) at the "
            "TOS_SERVE_MAX_QUEUE=%d bound" % (depth, max_requests),
            queue_depth=depth, queued_tokens=tokens)
      if max_tokens and self._items and \
          tokens + request.token_cost > max_tokens:
        raise ServingOverloaded(
            "serving queue full: %d queued tokens + %d for this request "
            "exceeds the TOS_SERVE_MAX_QUEUED_TOKENS=%d bound"
            % (tokens, request.token_cost, max_tokens),
            queue_depth=depth, queued_tokens=tokens)
      self._items.append(request)
      self._tokens += request.token_cost
      self._cond.notify_all()

  def pop_nowait(self, on_pop=None) -> Optional[Request]:
    """Pop the head; ``on_pop(req)`` runs UNDER the queue lock — the
    engine uses it to mark the request as mid-admission atomically with
    the pop, so a drain checking queue-then-admitting can never observe
    the gap between the two (the zero-shed contract)."""
    with self._cond:
      if self._items:
        req = self._items.popleft()
        self._tokens -= req.token_cost
        if on_pop is not None:
          on_pop(req)
        return req
      return None

  def reap(self, pred) -> List[Request]:
    """Remove (and return) every queued request matching ``pred`` —
    expired/cancelled requests fail without ever taking a slot."""
    with self._cond:
      kept, removed = collections.deque(), []
      for req in self._items:
        if pred(req):
          removed.append(req)
          self._tokens -= req.token_cost
        else:
          kept.append(req)
      self._items = kept
      return removed

  def wait_nonempty(self, timeout: float) -> bool:
    """Block (bounded) until at least one request is queued."""
    with self._cond:
      if self._items:
        return True
      self._cond.wait(timeout=timeout)
      return bool(self._items)

  def close(self, error: BaseException) -> List[Request]:
    """Mark closed and return the drained backlog, atomically. A queue
    closed with an earlier error stays closed with THAT error."""
    with self._cond:
      if self._closed is None:
        self._closed = error
      items = list(self._items)
      self._items.clear()
      self._tokens = 0
      self._cond.notify_all()
      return items

  def reopen(self) -> None:
    with self._cond:
      self._closed = None

  @property
  def closed(self) -> bool:
    with self._cond:
      return self._closed is not None

  @property
  def token_mass(self) -> int:
    with self._cond:
      return self._tokens

  def __len__(self) -> int:
    with self._cond:
      return len(self._items)
