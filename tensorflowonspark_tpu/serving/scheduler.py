"""Host side of continuous batching: requests, the admission queue, and
the prompt-bucket policy.

Pure bookkeeping — no device work happens here. The
:class:`ServingEngine` thread pops :class:`Request` objects off the
:class:`RequestQueue` whenever a slot frees and prefills them in
(``serving.slots``); callers hold the request handle and wait on its
event / stream queue. Every blocking wait is timeout-bounded (TOS001).
"""

import collections
import itertools
import os
import queue as std_queue
import threading
import time
from typing import List, Optional

import numpy as np

#: comma list overriding the default prefill bucket sizes
#: (``serving.slots.DEFAULT_BUCKETS``)
ENV_SERVE_BUCKETS = "TOS_SERVE_BUCKETS"

_request_ids = itertools.count(1)


def buckets_from_env(default):
  """The prefill bucket set: ``TOS_SERVE_BUCKETS`` (comma ints) or
  ``default``."""
  raw = os.environ.get(ENV_SERVE_BUCKETS, "").strip()
  if not raw:
    return tuple(default)
  try:
    sizes = tuple(int(p) for p in raw.split(",") if p.strip())
  except ValueError:
    raise ValueError("%s must be a comma list of ints, got %r"
                     % (ENV_SERVE_BUCKETS, raw))
  if not sizes or min(sizes) < 1:
    raise ValueError("%s must name positive chunk sizes, got %r"
                     % (ENV_SERVE_BUCKETS, raw))
  return sizes


class Request(object):
  """One in-flight generation request.

  ``tokens`` accumulates generated ids (EOS inclusive, never pad);
  ``done`` fires when the request finishes or fails; ``stream_q``
  receives each token as it is emitted, then a ``None`` sentinel.
  """

  __slots__ = ("rid", "prompt", "max_new_tokens", "tokens", "done",
               "stream_q", "error", "submitted_at", "started_at",
               "finished_at")

  def __init__(self, prompt, max_new_tokens: int):
    self.rid = next(_request_ids)
    self.prompt = np.asarray(prompt, np.int32).ravel()
    self.max_new_tokens = int(max_new_tokens)
    self.tokens: List[int] = []
    self.done = threading.Event()
    self.stream_q: std_queue.Queue = std_queue.Queue()
    self.error: Optional[BaseException] = None
    self.submitted_at = time.monotonic()
    self.started_at: Optional[float] = None
    self.finished_at: Optional[float] = None

  def emit(self, token: int) -> None:
    self.tokens.append(int(token))
    self.stream_q.put_nowait(int(token))   # unbounded: never blocks

  def finish(self, error: Optional[BaseException] = None) -> None:
    self.error = error
    self.finished_at = time.monotonic()
    self.stream_q.put_nowait(None)         # unbounded: never blocks
    self.done.set()

  @property
  def latency(self) -> Optional[float]:
    if self.finished_at is None:
      return None
    return self.finished_at - self.submitted_at

  def output(self) -> np.ndarray:
    """prompt + generated tokens (EOS inclusive, no padding)."""
    return np.concatenate(
        [self.prompt, np.asarray(self.tokens, np.int32)])


class RequestQueue(object):
  """Thread-safe FIFO of pending requests with bounded waits."""

  def __init__(self):
    self._items = collections.deque()
    self._cond = threading.Condition()

  def push(self, request: Request) -> None:
    with self._cond:
      self._items.append(request)
      self._cond.notify_all()

  def pop_nowait(self) -> Optional[Request]:
    with self._cond:
      if self._items:
        return self._items.popleft()
      return None

  def wait_nonempty(self, timeout: float) -> bool:
    """Block (bounded) until at least one request is queued."""
    with self._cond:
      if self._items:
        return True
      self._cond.wait(timeout=timeout)
      return bool(self._items)

  def drain(self) -> List[Request]:
    with self._cond:
      items = list(self._items)
      self._items.clear()
      return items

  def __len__(self) -> int:
    with self._cond:
      return len(self._items)
