"""Host side of continuous batching: requests, the admission queue, the
prompt-bucket policy, and the paged-KV bookkeeping (page allocator +
shared-prefix radix cache).

Pure bookkeeping — no device work happens here. The
:class:`ServingEngine` thread pops :class:`Request` objects off the
:class:`RequestQueue` whenever a slot frees and prefills them in
(``serving.slots``); callers hold the request handle and wait on its
event / stream queue. Every blocking wait is timeout-bounded (TOS001).

:class:`PagePool` is the ref-counted host allocator over the device page
pool (``serving.slots`` paged slabs): page 0 is the reserved trash page,
requests hold one ref per private page, and shared prefix pages carry
one ref per reader plus one for the :class:`PrefixCache` entry — a page
returns to the free list exactly when its last ref drops, which is what
makes drain/param-swap release pages exactly once. :class:`PrefixCache`
is the driver-side radix trie keyed on prompt-token prefixes at PAGE
granularity: requests sharing a prefix prefill it once and fork
read-only references to its full pages; the divergence (partial) page is
never shared — each request writes its own copy — which realizes
copy-on-write at page granularity without any device-side copy.
Eviction is ref-counted LRU bounded by ``TOS_SERVE_PREFIX_PAGES``. Both
are engine-loop-thread-only (no locks): all allocation, sharing and
release happens on the one thread that owns the slab.

The robustness vocabulary also lives here (docs/ROBUSTNESS.md):

* :class:`ServingOverloaded` — structured admission rejection (queue
  depth / queued-token mass over the bound, or the engine is draining),
  carrying a ``retry_after`` hint derived from the live decode rate;
* :class:`DeadlineExceeded` — a request's TTL ran out (at submit, while
  queued, or mid-flight at a horizon boundary);
* :class:`RequestCancelled` — the client called ``cancel(rid)``;
* :class:`PoisonedRequest` — the request was in flight across
  ``poison_crashes`` consecutive engine crashes and is failed instead of
  replayed (no crash loops on one bad request).
"""

import collections
import heapq
import itertools
import os
import queue as std_queue
import threading
import time
from typing import List, Optional

import numpy as np

from tensorflowonspark_tpu.obs import spans as spans_mod

#: comma list overriding the default prefill bucket sizes
#: (``serving.slots.DEFAULT_BUCKETS``)
ENV_SERVE_BUCKETS = "TOS_SERVE_BUCKETS"

_request_ids = itertools.count(1)


class ServingOverloaded(RuntimeError):
  """Admission rejected: the queue bound would be exceeded (or the
  engine is draining). ``retry_after`` (seconds, may be None) is derived
  from the engine's live tokens/s rate over the queued token mass —
  the client-visible backpressure signal."""

  def __init__(self, message: str, queue_depth: int = 0,
               queued_tokens: int = 0, retry_after=None,
               draining: bool = False):
    super().__init__(message)
    self.queue_depth = int(queue_depth)
    self.queued_tokens = int(queued_tokens)
    self.retry_after = retry_after
    self.draining = bool(draining)

  def __reduce__(self):
    # BaseManager proxies (and any other pickle boundary a fleet replica
    # crosses) replay __init__ with the default Exception reduction's
    # single formatted-message arg — here that would DROP the structured
    # fields (queue_depth, retry_after, draining) the retry logic keys
    # on. Same manager-proxy bug class as feedhub.QueueFull.
    return (type(self), (self.args[0] if self.args else "",
                         self.queue_depth, self.queued_tokens,
                         self.retry_after, self.draining))


class DeadlineExceeded(TimeoutError):
  """The request's deadline/TTL expired before it finished."""

  def __reduce__(self):
    # explicit args-based reduction: keeps the round-trip honest even if
    # a structured field is ever added (the QueueFull lesson — a custom
    # __init__ without this surfaces as TypeError across the boundary)
    return (type(self), tuple(self.args))


class RequestCancelled(RuntimeError):
  """The client cancelled the request (``ServingEngine.cancel``)."""

  def __reduce__(self):
    return (type(self), tuple(self.args))


class PoisonedRequest(RuntimeError):
  """Failed instead of replayed: the request was in flight across N
  consecutive engine crashes (the crash-loop breaker)."""

  def __reduce__(self):
    return (type(self), tuple(self.args))


class QueueClosed(RuntimeError):
  """Internal: push on a closed queue (engine stopped or loop dead).
  Carries the closing cause so submit can fail fast with the root."""


class Request(object):
  """One in-flight generation request.

  ``tokens`` accumulates generated ids (EOS inclusive, never pad);
  ``done`` fires when the request finishes or fails; ``stream_q``
  receives each token as it is emitted, then a ``None`` sentinel.
  ``deadline`` is an absolute ``time.monotonic()`` bound (None = no
  deadline); ``cancelled`` is the client-side cancellation flag the
  engine loop reaps; ``crash_count`` counts engine crashes this request
  was blamed for (poison detection, docs/ROBUSTNESS.md).

  Every request carries a TIMING LEDGER (public read-only fields, all
  ``time.monotonic``): ``submitted_at`` (submit), ``started_at``
  (admitted to a slot), ``prefill_done_at``, ``first_token_at`` and
  ``finished_at``, plus the derived :attr:`ttft` / :attr:`latency` /
  :attr:`queue_wait` and the :meth:`timing` dict. A crash replay
  regenerates already-delivered positions but NEVER resets
  ``first_token_at`` — the client saw its first token once, and that is
  the moment TTFT measures (pinned by tests). ``trace_id`` is the
  request-scoped trace (``obs.spans.new_trace_id``) stamped on every
  span the request touches; pass one in to join an existing trace (the
  fleet does, so a failover hop stays ONE trace).
  """

  __slots__ = ("rid", "prompt", "max_new_tokens", "tokens", "done",
               "stream_q", "error", "submitted_at", "started_at",
               "prefill_done_at", "first_token_at",
               "finished_at", "deadline", "cancelled", "crash_count",
               "replays", "trace_id", "_suppress")

  def __init__(self, prompt, max_new_tokens: int, deadline=None,
               trace_id: Optional[str] = None):
    self.rid = next(_request_ids)
    self.prompt = np.asarray(prompt, np.int32).ravel()
    self.max_new_tokens = int(max_new_tokens)
    self.tokens: List[int] = []
    self.done = threading.Event()
    self.stream_q: std_queue.Queue = std_queue.Queue()
    self.error: Optional[BaseException] = None
    self.submitted_at = time.monotonic()
    self.started_at: Optional[float] = None
    self.prefill_done_at: Optional[float] = None
    self.first_token_at: Optional[float] = None
    self.finished_at: Optional[float] = None
    self.deadline = None if deadline is None else float(deadline)
    self.cancelled = threading.Event()
    self.crash_count = 0
    #: crash replays this request rode (each one regenerates the
    #: already-emitted prefix; docs/ROBUSTNESS.md)
    self.replays = 0
    self.trace_id = trace_id if trace_id is not None \
        else spans_mod.new_trace_id()
    # crash-replay suppression: how many upcoming emits regenerate
    # already-delivered positions (greedy ⇒ bit-identical) and must not
    # reach tokens/stream a second time
    self._suppress = 0

  @property
  def token_cost(self) -> int:
    """Worst-case token mass this request puts on the engine (prompt to
    prefill + budget to decode) — the unit of the queued-token bound."""
    return len(self.prompt) + self.max_new_tokens

  @property
  def generated(self) -> int:
    """Tokens generated in the CURRENT engine incarnation. Equal to
    ``len(tokens)`` except mid-replay, where already-recorded tokens are
    still being regenerated — budget math must use THIS, or a replayed
    request would stop short of re-reaching its pre-crash position."""
    return len(self.tokens) - self._suppress

  def expired(self, now: Optional[float] = None) -> bool:
    if self.deadline is None:
      return False
    return (time.monotonic() if now is None else now) >= self.deadline

  def begin_replay(self) -> None:
    """Arm suppression for a crash replay: the next ``len(tokens)``
    emits re-derive positions the client already holds. The timing
    ledger is NOT reset: ``first_token_at`` keeps the moment the client
    first saw a token (a replay re-derives it, the client never waits
    for it again)."""
    self._suppress = len(self.tokens)
    self.replays += 1

  def emit(self, token: int) -> bool:
    """Record one generated token. Returns replay parity: False when a
    suppressed (replayed) emit disagrees with the recorded token — the
    greedy bit-identity contract says that never happens; the engine
    counts violations instead of trusting it blindly."""
    token = int(token)
    if self.first_token_at is None:
      self.first_token_at = time.monotonic()
    if self._suppress:
      idx = len(self.tokens) - self._suppress
      self._suppress -= 1
      return self.tokens[idx] == token
    self.tokens.append(token)
    self.stream_q.put_nowait(token)        # unbounded: never blocks
    return True

  def finish(self, error: Optional[BaseException] = None) -> None:
    """Idempotent: a request failed by the crash path and again by
    ``stop()`` keeps its FIRST verdict (and one stream sentinel)."""
    if self.done.is_set():
      return
    self.error = error
    self.finished_at = time.monotonic()
    self.stream_q.put_nowait(None)         # unbounded: never blocks
    self.done.set()

  @property
  def latency(self) -> Optional[float]:
    if self.finished_at is None:
      return None
    return self.finished_at - self.submitted_at

  @property
  def ttft(self) -> Optional[float]:
    """Time to first token (seconds since submit; None before it)."""
    if self.first_token_at is None:
      return None
    return self.first_token_at - self.submitted_at

  @property
  def queue_wait(self) -> Optional[float]:
    """Submit → admitted-to-a-slot wait (None while still queued)."""
    if self.started_at is None:
      return None
    return self.started_at - self.submitted_at

  @property
  def tpot(self) -> Optional[float]:
    """Per-output-token time: decode seconds per generated token past
    the first (None until finished with >= 2 tokens)."""
    if self.finished_at is None or self.first_token_at is None:
      return None
    n = len(self.tokens) - 1
    if n < 1:
      return None
    return (self.finished_at - self.first_token_at) / n

  def timing(self) -> dict:
    """The per-request timing ledger as one plain dict — the fields the
    canary verdict and ``generate(detailed=True)`` read. Raw stamps are
    ``time.monotonic``; derived durations are seconds."""
    return {"trace_id": self.trace_id, "rid": self.rid,
            "submitted": self.submitted_at, "admitted": self.started_at,
            "prefill_done": self.prefill_done_at,
            "first_token": self.first_token_at,
            "finished": self.finished_at,
            "ttft": self.ttft, "e2e": self.latency,
            "queue_wait": self.queue_wait, "tpot": self.tpot,
            "generated": len(self.tokens), "replays": self.replays}

  def output(self) -> np.ndarray:
    """prompt + generated tokens (EOS inclusive, no padding)."""
    return np.concatenate(
        [self.prompt, np.asarray(self.tokens, np.int32)])


def buckets_from_env(default):
  """The prefill bucket set: ``TOS_SERVE_BUCKETS`` (comma ints) or
  ``default``."""
  raw = os.environ.get(ENV_SERVE_BUCKETS, "").strip()
  if not raw:
    return tuple(default)
  try:
    sizes = tuple(int(p) for p in raw.split(",") if p.strip())
  except ValueError:
    raise ValueError("%s must be a comma list of ints, got %r"
                     % (ENV_SERVE_BUCKETS, raw))
  if not sizes or min(sizes) < 1:
    raise ValueError("%s must name positive chunk sizes, got %r"
                     % (ENV_SERVE_BUCKETS, raw))
  return sizes


class PagePool(object):
  """Ref-counted free-list allocator over a paged KV slab's page pool.

  Page 0 is the reserved TRASH page (frozen-lane writes and unused
  page-table entries land there) and is never allocated. ``alloc`` is
  all-or-nothing: a request either gets every page its prompt+budget
  token mass needs or waits in the queue for completions to free pages.
  Sharing (the prefix cache, every additional reader of a prefix page)
  rides ``ref``/``unref``; a page rejoins the free list exactly when its
  last ref drops. Engine-loop-thread-only: no locking.
  """

  def __init__(self, num_pages: int):
    if num_pages < 2:
      raise ValueError("PagePool needs num_pages >= 2 (page 0 is the "
                       "reserved trash page), got %d" % num_pages)
    self.num_pages = int(num_pages)
    self._free = collections.deque(range(1, self.num_pages))
    self._refs = [0] * self.num_pages

  @property
  def capacity(self) -> int:
    """Allocatable pages (the pool minus the trash page)."""
    return self.num_pages - 1

  @property
  def free_pages(self) -> int:
    return len(self._free)

  @property
  def in_use(self) -> int:
    return self.capacity - len(self._free)

  def alloc(self, n: int) -> Optional[List[int]]:
    """``n`` fresh pages (each at refcount 1), or None if the pool
    cannot satisfy the whole request right now (all-or-nothing: partial
    grants would deadlock two half-admitted requests against each
    other)."""
    if n < 0:
      raise ValueError("alloc count must be >= 0, got %d" % n)
    if n > len(self._free):
      return None
    pages = [self._free.popleft() for _ in range(n)]
    for p in pages:
      self._refs[p] = 1
    return pages

  def ref(self, page: int) -> None:
    """One more holder of an allocated page (prefix sharing)."""
    if self._refs[page] <= 0:
      raise ValueError("ref on free page %d" % page)
    self._refs[page] += 1

  def unref(self, page: int) -> bool:
    """Drop one ref; returns True when this freed the page. Raises on a
    double free — page accounting bugs must be loud, not leaks."""
    r = self._refs[page]
    if page <= 0 or r <= 0:
      raise ValueError("unref of free/trash page %d (double free?)"
                       % page)
    self._refs[page] = r - 1
    if r == 1:
      self._free.append(page)
      return True
    return False


class PrefixCache(object):
  """Driver-side radix trie over prompt-token prefixes, page-granular.

  Each trie node caches ONE full page of a prompt: the tuple of
  ``page_size`` tokens it covers maps to the pool page holding their KV.
  Lookup walks a prompt's full-page chunks and returns the longest
  cached run; a hit means those tokens are never re-prefilled — the
  engine gathers the pages into a warm row cache and prefills only the
  tail. Only FULL pages are cached/shared: the divergence page (the
  prompt's partial last page, where requests write their own tails) is
  always private, which is copy-on-write at page granularity with the
  copy replaced by a ≤ page_size-token recompute.

  The cache holds one pool ref per cached page (taken by the engine via
  ``PagePool.ref`` on ``register``), so cached prefixes survive their
  originating request. Eviction is LRU over leaf nodes, bounded by
  ``max_pages`` (``TOS_SERVE_PREFIX_PAGES``); evicted pages are returned
  for the engine to unref. Engine-loop-thread-only: no locking.
  """

  def __init__(self, page_size: int, max_pages: int):
    if page_size < 1:
      raise ValueError("page_size must be >= 1, got %d" % page_size)
    self.page_size = int(page_size)
    self.max_pages = int(max_pages)
    self._root: dict = {}       # chunk tuple -> node
    self._clock = 0
    self.pages_held = 0

  def _chunks(self, prompt):
    ps = self.page_size
    full = len(prompt) // ps
    return [tuple(int(t) for t in prompt[i * ps:(i + 1) * ps])
            for i in range(full)]

  def lookup(self, prompt) -> List[int]:
    """Pool pages for the longest cached full-page prefix of ``prompt``
    (possibly empty). Touches the matched path's LRU stamps; the caller
    refs each returned page before using it."""
    self._clock += 1
    pages, children = [], self._root
    for chunk in self._chunks(prompt):
      node = children.get(chunk)
      if node is None:
        break
      node["stamp"] = self._clock
      pages.append(node["page"])
      children = node["children"]
    return pages

  def register(self, prompt, page_ids) -> List[int]:
    """Cache ``prompt``'s full pages (``page_ids[i]`` holds tokens
    ``[i·page_size, (i+1)·page_size)``). Chunks already cached keep
    their existing page; new chunks take the request's page. Returns the
    NEWLY cached pages — the caller must take a pool ref on each (the
    cache's own ref, outliving the registering request)."""
    self._clock += 1
    new, children = [], self._root
    for i, chunk in enumerate(self._chunks(prompt)):
      node = children.get(chunk)
      if node is None:
        node = children[chunk] = {"page": int(page_ids[i]),
                                  "children": {}, "stamp": self._clock}
        new.append(node["page"])
        self.pages_held += 1
      else:
        node["stamp"] = self._clock
      children = node["children"]
    return new

  def _leaves(self, children):
    for chunk, node in children.items():
      if node["children"]:
        for leaf in self._leaves(node["children"]):
          yield leaf
      else:
        yield node["stamp"], children, chunk, node

  def evict(self, count: int = 1) -> List[int]:
    """Drop up to ``count`` least-recently-used LEAF pages (a shared
    interior page cannot go while a longer cached prefix still rides
    through it). Returns the released pages for the caller to unref.

    One trie walk evicts a whole batch of current leaves in LRU order;
    only when the batch is spent (deleting leaves exposed parents as
    NEW leaves) does it re-enumerate — so evicting E pages costs
    O(depth) walks, not E of them (eviction runs on the admission path
    whenever the pool is tight, the cache's steady state)."""
    released = []
    while len(released) < count:
      batch = heapq.nsmallest(count - len(released),
                              self._leaves(self._root),
                              key=lambda x: x[0])
      if not batch:
        break
      for _, children, chunk, node in batch:
        del children[chunk]
        self.pages_held -= 1
        released.append(node["page"])
    return released

  @property
  def over_budget(self) -> int:
    """How many pages past ``max_pages`` the cache currently holds."""
    return max(0, self.pages_held - self.max_pages)


class RequestQueue(object):
  """Thread-safe FIFO of pending requests with bounded waits, bounded
  admission, and a closed state.

  * ``push_bounded`` enforces the request-count AND queued-token-mass
    bounds (``ServingOverloaded``); an oversized request is still
    admitted when the queue is empty — it CAN be served (slots don't
    care), the bound is about backlog (the feedhub oversized-envelope
    rule).
  * ``close(error)`` atomically (under the one lock ``push`` uses)
    marks the queue dead and returns the drained backlog — the fix for
    the submit-vs-loop-death race: a push can land before or after the
    close, never between the dying loop's drain and its error mark.
  * ``push_front`` re-queues crash-replay requests ahead of the backlog
    (they were already admitted; bounds don't re-apply).
  """

  def __init__(self):
    self._items = collections.deque()
    self._cond = threading.Condition()
    self._tokens = 0                       # queued token mass
    self._closed: Optional[BaseException] = None

  def _check_open_locked(self):
    if self._closed is not None:
      raise QueueClosed("request queue is closed") from self._closed

  def push(self, request: Request) -> None:
    with self._cond:
      self._check_open_locked()
      self._items.append(request)
      self._tokens += request.token_cost
      self._cond.notify_all()

  def push_front(self, request: Request) -> None:
    """Replay re-queue: ahead of the backlog, exempt from bounds."""
    with self._cond:
      self._check_open_locked()
      self._items.appendleft(request)
      self._tokens += request.token_cost
      self._cond.notify_all()

  def push_bounded(self, request: Request, max_requests: int = 0,
                   max_tokens: int = 0) -> None:
    """Admit under the bounds (0 disables a bound) or raise
    :class:`ServingOverloaded` / :class:`QueueClosed`."""
    with self._cond:
      self._check_open_locked()
      depth, tokens = len(self._items), self._tokens
      if max_requests and depth >= max_requests:
        raise ServingOverloaded(
            "serving queue full: %d queued request(s) at the "
            "TOS_SERVE_MAX_QUEUE=%d bound" % (depth, max_requests),
            queue_depth=depth, queued_tokens=tokens)
      if max_tokens and self._items and \
          tokens + request.token_cost > max_tokens:
        raise ServingOverloaded(
            "serving queue full: %d queued tokens + %d for this request "
            "exceeds the TOS_SERVE_MAX_QUEUED_TOKENS=%d bound"
            % (tokens, request.token_cost, max_tokens),
            queue_depth=depth, queued_tokens=tokens)
      self._items.append(request)
      self._tokens += request.token_cost
      self._cond.notify_all()

  def pop_nowait(self, on_pop=None) -> Optional[Request]:
    """Pop the head; ``on_pop(req)`` runs UNDER the queue lock — the
    engine uses it to mark the request as mid-admission atomically with
    the pop, so a drain checking queue-then-admitting can never observe
    the gap between the two (the zero-shed contract)."""
    with self._cond:
      if self._items:
        req = self._items.popleft()
        self._tokens -= req.token_cost
        if on_pop is not None:
          on_pop(req)
        return req
      return None

  def reap(self, pred) -> List[Request]:
    """Remove (and return) every queued request matching ``pred`` —
    expired/cancelled requests fail without ever taking a slot."""
    with self._cond:
      kept, removed = collections.deque(), []
      for req in self._items:
        if pred(req):
          removed.append(req)
          self._tokens -= req.token_cost
        else:
          kept.append(req)
      self._items = kept
      return removed

  def wait_nonempty(self, timeout: float) -> bool:
    """Block (bounded) until at least one request is queued."""
    with self._cond:
      if self._items:
        return True
      self._cond.wait(timeout=timeout)
      return bool(self._items)

  def close(self, error: BaseException) -> List[Request]:
    """Mark closed and return the drained backlog, atomically. A queue
    closed with an earlier error stays closed with THAT error."""
    with self._cond:
      if self._closed is None:
        self._closed = error
      items = list(self._items)
      self._items.clear()
      self._tokens = 0
      self._cond.notify_all()
      return items

  def reopen(self) -> None:
    with self._cond:
      self._closed = None

  @property
  def closed(self) -> bool:
    with self._cond:
      return self._closed is not None

  @property
  def token_mass(self) -> int:
    with self._cond:
      return self._tokens

  def __len__(self) -> int:
    with self._cond:
      return len(self._items)
