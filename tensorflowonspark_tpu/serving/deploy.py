"""DeploymentController: SLO-gated canary rollout over the serving fleet.

The continuous half of the train→serve loop (ROADMAP item 5): a trainer
publishes versioned params to a :class:`~.registry.ModelRegistry`; this
controller watches the registry and drives every new version through a
small, recoverable state machine on a live :class:`~.fleet.ServingFleet`
— without shedding a single accepted request at any point, because every
replica move rides the fleet's zero-shed ``swap_replica`` drain.

State machine (docs/ROBUSTNESS.md §Continuous deployment)::

    IDLE ── registry.watch() sees version v ──▶ CANARY
    CANARY: swap ONE replica to v (zero-shed), route a configurable
            traffic slice to it (fleet.set_canary), stamp every
            request/trace with the serving model_version
    VERIFY: greedy parity spot-checks of the canary engine against a
            reference decode of the candidate params (bit-identical or
            it isn't — the TF-Replicator interchangeability argument),
            plus obs deltas over a bake window: canary-vs-baseline TTFT
            comparison from the fleet timing ledgers, ejection/shed/
            replay-mismatch counter deltas, and an ``slo_status()`` burn
            check when a source is wired
    PROMOTE: rolling zero-shed swap of the remaining replicas to v;
             the fleet factory adopts v (future ejection rebuilds and
             ``on_saturated`` scale-ups build v engines)
    ROLLBACK: swap the canary back to the baseline version and
              quarantine v in the registry with the structured verdict
              — ``watch()`` can never hand it out again

Chaos (``TOS_CHAOS_DEPLOY``, utils/chaos.py) makes the failure story
provable instead of assumed: ``kill`` at a state boundary raises
:class:`ControllerKilled` — the driver-side controller dying with the
fleet mid-transition — and :meth:`resume` must then converge every
replica to ONE consistent version with zero shed; ``poison`` corrupts
the candidate's params at the canary build, which VERIFY must catch
(parity) and quarantine, never promote. ``tools/serve_bench.py
--deploy`` (make deploy-chaos / serve-bench-deploy-smoke) gates all of
it in tier-1.

All waits are timeout-bounded (TOS001); the watch thread is a daemon
(TOS007); knobs ride registered ``TOS_DEPLOY_*`` env vars (TOS008).
"""

import logging
import os
import threading
import time
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

from tensorflowonspark_tpu.obs import metrics as obs_metrics
from tensorflowonspark_tpu.utils import chaos

logger = logging.getLogger(__name__)

#: canary traffic slice as a fraction of placement rounds (0.25 ⇒ every
#: 4th round tries the canary first)
ENV_DEPLOY_SLICE = "TOS_DEPLOY_SLICE"
#: VERIFY bake window in seconds (sampling traffic flows during it)
ENV_DEPLOY_BAKE = "TOS_DEPLOY_BAKE"
#: number of greedy parity spot-checks VERIFY runs on the canary engine
ENV_DEPLOY_SPOT_CHECKS = "TOS_DEPLOY_SPOT_CHECKS"
#: canary/baseline median-TTFT ratio above which VERIFY fails
#: (``canary_degraded``'s threshold too — generous by default: CPU test
#: boxes are noisy, and parity is the sharp gate)
ENV_DEPLOY_TTFT_RATIO = "TOS_DEPLOY_TTFT_RATIO"
#: registry poll cadence of the watch loop, seconds
ENV_DEPLOY_POLL = "TOS_DEPLOY_POLL"
#: per-replica drain bound for every zero-shed swap, seconds
ENV_DEPLOY_SWAP_TIMEOUT = "TOS_DEPLOY_SWAP_TIMEOUT"

_DEFAULT_SLICE = 0.25
_DEFAULT_BAKE = 2.0
_DEFAULT_SPOT_CHECKS = 4
_DEFAULT_TTFT_RATIO = 10.0
_DEFAULT_POLL = 0.2
_DEFAULT_SWAP_TIMEOUT = 60.0

IDLE = "idle"
CANARY = "canary"
VERIFY = "verify"
PROMOTE = "promote"
ROLLBACK = "rollback"
#: numeric codes for the ``deploy.state`` gauge (obs_top renders them)
STATE_CODES = {IDLE: 0, CANARY: 1, VERIFY: 2, PROMOTE: 3, ROLLBACK: 4}


class ControllerKilled(RuntimeError):
  """The driver-side controller died at a deploy boundary (chaos kill).
  The fleet keeps serving whatever mix of versions the death left; a
  new/restarted controller calls :meth:`DeploymentController.resume` to
  converge it."""


def _env_float(name: str, default: float) -> float:
  return float(os.environ.get(name, str(default)))


def _env_int(name: str, default: int) -> int:
  return int(os.environ.get(name, str(default)))


def _poison(params):
  """The chaos ``poison`` action: a deterministic, shape/dtype-preserving
  corruption of every leaf — the canary serves confidently wrong logits,
  exactly the failure class VERIFY's bit-parity gate exists to catch."""
  import jax
  return jax.tree_util.tree_map(
      lambda a: (-(np.asarray(a)) - 1).astype(np.asarray(a).dtype), params)


class DeploymentController(object):
  """Drive registry versions through CANARY → VERIFY → PROMOTE/ROLLBACK
  on a live fleet, zero-shed end to end.

  ``make_engine_factory(params, manifest)`` returns a zero-arg engine
  factory for a version (the caller closes over its TransformerConfig —
  the controller never imports the model). ``reference_decode(params,
  prompt, budget)`` is the parity oracle: the single-request greedy
  decode (prompt + generated, stop-truncated) the canary's output must
  equal bit-for-bit. ``probe_prompts`` is a list of ``(prompt, budget)``
  pairs used for both VERIFY spot-checks and the pre-canary baseline
  capture (the rollback bit-identity proof). ``slo_source`` (optional)
  is a zero-arg callable returning ``TPUCluster.slo_status()``-shaped
  dicts; any burning objective fails VERIFY.
  """

  def __init__(self, fleet, registry,
               make_engine_factory: Callable,
               reference_decode: Callable,
               probe_prompts: Sequence[Tuple],
               baseline_version: Optional[int] = None,
               traffic_slice: Optional[float] = None,
               bake_seconds: Optional[float] = None,
               spot_checks: Optional[int] = None,
               ttft_degrade_ratio: Optional[float] = None,
               poll: Optional[float] = None,
               swap_timeout: Optional[float] = None,
               slo_source: Optional[Callable] = None):
    if not probe_prompts:
      raise ValueError("probe_prompts must name at least one "
                       "(prompt, budget) pair — VERIFY has no parity "
                       "oracle without one")
    self.fleet = fleet
    self.registry = registry
    self.make_engine_factory = make_engine_factory
    self.reference_decode = reference_decode
    self.probe_prompts = [(np.asarray(p, np.int32).ravel(), int(b))
                          for p, b in probe_prompts]
    # explicit arguments beat the env knobs (the num_slots rule)
    self.traffic_slice = float(
        traffic_slice if traffic_slice is not None
        else _env_float(ENV_DEPLOY_SLICE, _DEFAULT_SLICE))
    if not 0.0 < self.traffic_slice <= 1.0:
      raise ValueError("traffic_slice must be in (0, 1], got %r"
                       % self.traffic_slice)
    self.bake_seconds = float(
        bake_seconds if bake_seconds is not None
        else _env_float(ENV_DEPLOY_BAKE, _DEFAULT_BAKE))
    self.spot_checks = int(
        spot_checks if spot_checks is not None
        else _env_int(ENV_DEPLOY_SPOT_CHECKS, _DEFAULT_SPOT_CHECKS))
    self.ttft_degrade_ratio = float(
        ttft_degrade_ratio if ttft_degrade_ratio is not None
        else _env_float(ENV_DEPLOY_TTFT_RATIO, _DEFAULT_TTFT_RATIO))
    self.poll = float(poll if poll is not None
                      else _env_float(ENV_DEPLOY_POLL, _DEFAULT_POLL))
    self.swap_timeout = float(
        swap_timeout if swap_timeout is not None
        else _env_float(ENV_DEPLOY_SWAP_TIMEOUT, _DEFAULT_SWAP_TIMEOUT))
    self.slo_source = slo_source
    #: the version the fleet BASELINE serves (promoted last), or None
    self.current_version = baseline_version
    #: the version currently mid-state-machine, or None
    self.candidate_version: Optional[int] = None
    self.state = IDLE
    self.last_verdict: Optional[dict] = None
    self._stats_lock = threading.Lock()
    self.stats = {"canaries": 0, "promotions": 0, "rollbacks": 0,
                  "parity_failures": 0, "resumes": 0}
    self._stop_evt = threading.Event()
    self._thread: Optional[threading.Thread] = None
    reg = obs_metrics.active()
    self._obs_m = None if reg is None else {
        k: reg.counter("deploy." + k) for k in self.stats}
    self._obs_g = None if reg is None else {
        "state": reg.gauge("deploy.state"),
        "version": reg.gauge("deploy.version"),
        "candidate": reg.gauge("deploy.candidate"),
        "canary_ttft_ratio": reg.gauge("deploy.canary_ttft_ratio"),
    }
    if baseline_version is not None:
      self.registry.acquire(baseline_version)  # tosa: ignore[TOS007] - refcount, not a lock
    self._set_state(IDLE)

  # -- bookkeeping -----------------------------------------------------------

  def _count(self, key: str, n: int = 1) -> None:
    with self._stats_lock:
      self.stats[key] += n
    if self._obs_m is not None:
      self._obs_m[key].inc(n)

  def _set_state(self, state: str) -> None:
    self.state = state
    if self._obs_g is not None:
      self._obs_g["state"].set(STATE_CODES[state])
      self._obs_g["version"].set(self.current_version or 0)
      self._obs_g["candidate"].set(self.candidate_version or 0)

  def status(self) -> dict:
    """The HEALTH-wire deploy payload (obs_top renders it)."""
    return {"state": self.state,
            "state_code": STATE_CODES[self.state],
            "version": self.current_version,
            "candidate": self.candidate_version,
            "served_versions": {str(k): v for k, v in
                                self.fleet.served_versions().items()},
            "stats": dict(self.stats),
            "last_verdict": self.last_verdict}

  def _chaos(self, point: str, index) -> Optional[str]:
    verdict = chaos.deploy_fault(point, index)
    if verdict == "kill":
      # the driver-side controller dies HERE: no cleanup, no rollback —
      # whatever version mix the fleet serves right now is what a
      # restarted controller's resume() must converge
      raise ControllerKilled(
          "chaos: deploy controller killed at %r (index %r)"
          % (point, index))
    return verdict

  # -- the state machine -----------------------------------------------------

  def deploy(self, version: int, bake_seconds: Optional[float] = None,
             bake_traffic: Optional[Sequence[Tuple]] = None) -> dict:
    """Drive one registry version through the full state machine;
    returns the structured verdict (``ok`` True ⇒ promoted, False ⇒
    rolled back + quarantined). ``bake_traffic`` (optional list of
    ``(prompt, budget)``) flows through the fleet during VERIFY so the
    canary-vs-baseline latency comparison has live samples; without it
    the probe prompts are used."""
    params, manifest = self.registry.get(version)   # fingerprint-verified
    self.registry.acquire(version)  # tosa: ignore[TOS007] - refcount, not a lock
    self.candidate_version = version
    canary_rid = None
    prev_factory = self.fleet._factory
    baseline_version = self.current_version
    try:
      # ---- CANARY ----------------------------------------------------------
      self._set_state(CANARY)
      poisoned = self._chaos("canary", version) == "poison"
      canary_params = _poison(params) if poisoned else params
      factory = self.make_engine_factory(canary_params, manifest)
      order = [rid for rid, st in
               sorted(self.fleet.replica_states().items())
               if st != "ejected"]
      if not order:
        raise RuntimeError("no live replica to canary on")
      canary_rid = order[0]
      # pre-canary baseline capture THROUGH the fleet: the outputs a
      # forced rollback must reproduce bit-identically
      baseline_out = [np.asarray(self.fleet.result(
          self.fleet.submit(p, max_new_tokens=b), timeout=120.0))
          for p, b in self.probe_prompts]
      self.fleet.swap_replica(canary_rid, self.swap_timeout,
                              engine_factory=factory, version=version)
      every = max(1, int(round(1.0 / self.traffic_slice)))
      self.fleet.set_canary(canary_rid, every)
      self._count("canaries")
      logger.info("deploy: version %d canarying on replica %d "
                  "(1/%d traffic slice)", version, canary_rid, every)

      # ---- VERIFY ----------------------------------------------------------
      self._set_state(VERIFY)
      self._chaos("verify", version)
      verdict = self._verify(version, params, canary_rid,
                             bake_seconds=bake_seconds,
                             bake_traffic=bake_traffic)
      self.last_verdict = verdict
      if not verdict["ok"]:
        # ---- ROLLBACK ------------------------------------------------------
        self._set_state(ROLLBACK)
        self._chaos("rollback", version)
        self.fleet.clear_canary()
        self.fleet.swap_replica(canary_rid, self.swap_timeout,
                                engine_factory=prev_factory,
                                version=baseline_version)
        self.registry.quarantine(version, verdict)
        self.registry.release(version)        # quarantine is the pin now
        self._count("rollbacks")
        after = [np.asarray(self.fleet.result(
            self.fleet.submit(p, max_new_tokens=b), timeout=120.0))
            for p, b in self.probe_prompts]
        verdict["rollback_bit_identical"] = all(
            a.shape == b.shape and bool((a == b).all())
            for a, b in zip(baseline_out, after))
        self.candidate_version = None
        self._set_state(IDLE)
        logger.warning("deploy: version %d rolled back and quarantined "
                       "(%s)", version, verdict["reason"])
        return verdict

      # ---- PROMOTE ---------------------------------------------------------
      self._set_state(PROMOTE)
      clean_factory = self.make_engine_factory(params, manifest)
      self.fleet.clear_canary()
      for rid, st in sorted(self.fleet.replica_states().items()):
        if st == "ejected" or rid == canary_rid:
          continue
        self._chaos("promote", rid)
        self.fleet.swap_replica(rid, self.swap_timeout,
                                engine_factory=clean_factory,
                                version=version)
      self.fleet._factory = clean_factory   # rebuilds/scale-ups serve v
      if baseline_version is not None:
        self.registry.release(baseline_version)
      self.current_version = version
      self.candidate_version = None
      self._count("promotions")
      self._set_state(IDLE)
      self.registry.gc()
      logger.info("deploy: version %d promoted fleet-wide", version)
      verdict["promoted"] = True
      return verdict
    except ControllerKilled:
      raise                 # the fleet keeps the mix; resume() converges
    except BaseException:
      self.registry.release(version)
      raise

  def _verify(self, version: int, params, canary_rid: int,
              bake_seconds: Optional[float] = None,
              bake_traffic: Optional[Sequence[Tuple]] = None) -> dict:
    """The VERIFY gate: greedy parity spot-checks + obs/SLO deltas over
    the bake window. Pure read-side — it never mutates the fleet."""
    bake = self.bake_seconds if bake_seconds is None else float(bake_seconds)
    base = self.fleet.stats_snapshot()
    t0 = time.monotonic()
    deadline = t0 + bake
    traffic = [(np.asarray(p, np.int32).ravel(), int(b))
               for p, b in (bake_traffic if bake_traffic is not None
                            else self.probe_prompts)]
    canary_ttft: List[float] = []
    baseline_ttft: List[float] = []
    # sampling traffic through the live router until the bake window
    # closes — the canary slice routes ~1/every of it to the candidate,
    # and the timing ledger's model_version stamp partitions the sides
    i = 0
    while True:
      p, b = traffic[i % len(traffic)]
      frid = self.fleet.submit(p, max_new_tokens=b)
      freq = self.fleet.request(frid)
      self.fleet.result(frid, timeout=120.0)
      t = freq.timing()
      if t["ttft"] is not None:
        if t["model_version"] == version:
          canary_ttft.append(t["ttft"])
        else:
          baseline_ttft.append(t["ttft"])
      i += 1
      if time.monotonic() >= deadline and i >= len(traffic):
        break
    # greedy parity spot-checks, submitted straight at the canary engine
    # (the router's slice must not decide whether the gate runs)
    canary_eng = self.fleet._replicas[canary_rid].engine
    checked = mismatches = 0
    for p, b in self.probe_prompts[:max(1, self.spot_checks)]:
      ref = np.asarray(self.reference_decode(params, p, b))
      out = np.asarray(canary_eng.generate([p], max_new_tokens=b,
                                           timeout=120.0)[0])
      checked += 1
      if ref.shape != out.shape or not bool((ref == out).all()):
        mismatches += 1
    if mismatches:
      self._count("parity_failures", mismatches)
    delta = base.delta()
    ratio = None
    if canary_ttft and baseline_ttft:
      ratio = (float(np.median(canary_ttft))
               / max(1e-9, float(np.median(baseline_ttft))))
      if self._obs_g is not None:
        self._obs_g["canary_ttft_ratio"].set(ratio)
    burning = []
    if self.slo_source is not None:
      slo = self.slo_source()
      for obj in (slo or {}).get("objectives", []):
        if obj.get("burning"):
          burning.append(obj.get("name", "?"))
    counters_clean = (delta.get("ejections", 0) == 0
                      and delta.get("shed", 0) == 0
                      and delta.get("replay_mismatches", 0) == 0)
    reasons = []
    if mismatches:
      reasons.append("parity: %d/%d spot-checks diverged"
                     % (mismatches, checked))
    if not counters_clean:
      reasons.append("counters: ejections/shed/replay_mismatches moved "
                     "during the bake (%r)" % (delta,))
    if ratio is not None and ratio > self.ttft_degrade_ratio:
      reasons.append("latency: canary/baseline median TTFT ratio %.2f > "
                     "%.2f" % (ratio, self.ttft_degrade_ratio))
    if burning:
      reasons.append("slo: burning objectives %s" % (burning,))
    return {"version": version, "ok": not reasons,
            "reason": "; ".join(reasons) or None,
            "parity": {"checked": checked, "mismatches": mismatches},
            "counters": delta, "ttft_ratio": ratio,
            "canary_samples": len(canary_ttft),
            "baseline_samples": len(baseline_ttft),
            "slo_burning": burning,
            "bake_s": round(time.monotonic() - t0, 3)}

  # -- recovery --------------------------------------------------------------

  def resume(self, timeout: Optional[float] = None) -> dict:
    """Converge the fleet after a controller death mid-deploy (the chaos
    ``kill`` contract): pick ONE target version — the registry's newest
    non-quarantined version if any replica already serves it (a promote
    in flight finishes), else the pre-canary baseline (an abandoned or
    quarantined candidate is swapped back out) — and zero-shed swap
    every replica that disagrees. Returns ``{"target", "swapped"}``."""
    timeout = self.swap_timeout if timeout is None else float(timeout)
    self._count("resumes")
    self.fleet.clear_canary()
    served = self.fleet.served_versions()
    latest = self.registry.latest()
    if latest is not None and latest in served.values():
      target = latest
    elif self.current_version is not None:
      target = self.current_version
    else:
      target = latest
    if target is None:
      # nothing published and nothing stamped: the fleet is consistent
      # by construction; just clear the in-flight marker
      self.candidate_version = None
      self._set_state(IDLE)
      return {"target": None, "swapped": 0}
    params, manifest = self.registry.get(target)
    factory = self.make_engine_factory(params, manifest)
    swapped = 0
    for rid, ver in sorted(served.items()):
      if ver == target:
        continue
      self.fleet.swap_replica(rid, timeout, engine_factory=factory,
                              version=target)
      swapped += 1
    self.fleet._factory = factory
    if target != self.current_version:
      self.registry.acquire(target)  # tosa: ignore[TOS007] - refcount, not a lock
      if self.current_version is not None:
        self.registry.release(self.current_version)
    if self.candidate_version is not None:
      # drop the in-flight ref deploy() took on the candidate — it is
      # either the target (now pinned as current) or abandoned (GC-able)
      self.registry.release(self.candidate_version)
    self.current_version = target
    self.candidate_version = None
    self._set_state(IDLE)
    logger.info("deploy: resume converged fleet to version %s "
                "(%d replica(s) swapped)", target, swapped)
    return {"target": target, "swapped": swapped}

  # -- the watch loop --------------------------------------------------------

  def poll_once(self, timeout: Optional[float] = None) -> Optional[dict]:
    """One watch step: wait (bounded) for a version newer than both the
    promoted and any quarantined candidate, deploy it, return the
    verdict (None when nothing new arrived)."""
    timeout = self.poll if timeout is None else float(timeout)
    seen = self.current_version or 0
    ver = self.registry.watch(timeout, last_seen=seen, poll=self.poll)
    if ver is None:
      return None
    return self.deploy(ver)

  def start(self) -> "DeploymentController":
    """Run the watch loop in a daemon thread until :meth:`stop`."""
    if self._thread is not None and self._thread.is_alive():
      return self
    self._stop_evt.clear()
    self._thread = threading.Thread(target=self._loop, daemon=True,
                                    name="tos-deploy-controller")
    self._thread.start()
    return self

  def stop(self, timeout: float = 30.0) -> None:
    self._stop_evt.set()
    t = self._thread
    if t is not None:
      t.join(timeout=timeout)

  def _loop(self) -> None:
    while not self._stop_evt.is_set():
      try:
        self.poll_once(timeout=self.poll)
      except ControllerKilled:
        raise          # chaos: the controller thread IS the casualty
      except Exception:  # noqa: BLE001 - the watch loop must outlive
        # one bad deploy (the fleet monitor rule); the failure is
        # visible: rollback counters moved, the verdict is quarantined
        logger.exception("deploy watch pass failed")
        self._stop_evt.wait(self.poll)
