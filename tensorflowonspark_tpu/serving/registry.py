"""Versioned model registry: the durable seam between train and serve.

The reference's Estimator→Model pipeline hands a trained model to
inference exactly once (PAPER.md L5); this module makes that hand-off a
durable, versioned, continuously-watchable channel. A trainer publishes
params + a manifest (step, lineage, content fingerprint) as an atomic
monotonically-numbered version; the serving side polls ``latest()`` /
``watch()`` and drives each new version through the canary state machine
(``serving.deploy``).

Publish is torn-write-proof by the SAME commit-marker protocol as
checkpoints (``utils.checkpoint.atomic_write_json`` — one shared
implementation, PR 15): the version's params file is written and fsynced
first, then the marker commits it. A publisher killed at any point
leaves either a complete marked version or an unmarked (invisible)
directory; ``latest()`` deterministically resolves to the previous
marked version, never to a tear.

Retention is ref-counted: a fleet mid-canary pins the versions it serves
via ``acquire``/``release``, and ``gc()`` never deletes a pinned
version, the newest live version, or a quarantined one (quarantine IS
the post-mortem record). Quarantine (``serving.deploy`` rollback) stamps
a structured verdict next to the version and hides it from ``latest()``
so a watcher can never re-deploy a version that already failed VERIFY.

Layout under ``root``::

    v-000007/
      params.npz        # flattened leaf arrays (path-keyed)
      .commit.json      # the marker: version/step/fingerprint/lineage
      .quarantine.json  # only after a rollback: the structured verdict
"""

import json
import logging
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from tensorflowonspark_tpu.utils.checkpoint import (
    atomic_write_json, params_fingerprint)

logger = logging.getLogger(__name__)

#: default number of non-quarantined versions ``gc()`` keeps (newest N).
ENV_REGISTRY_KEEP = "TOS_REGISTRY_KEEP"
#: ``watch()`` poll interval in seconds.
ENV_REGISTRY_POLL = "TOS_REGISTRY_POLL"

_DIR_FMT = "v-%06d"
_DIR_PREFIX = "v-"
_MARKER = ".commit.json"
_QUARANTINE = ".quarantine.json"
_PARAMS = "params.npz"

_DEFAULT_KEEP = 3
_DEFAULT_POLL = 0.1


def _flatten(tree: Any, prefix: str = "") -> Dict[str, Any]:
  """Nested dict-of-arrays → {'a/b/c': leaf}. Registry params must be
  plain nested dicts (what ``create_state().params`` is) — '/' in a key
  would corrupt the path encoding, so it is rejected loudly."""
  out = {}
  if not isinstance(tree, dict):
    raise TypeError("registry params must be a nested dict pytree, got %s"
                    % type(tree).__name__)
  for k, v in tree.items():
    k = str(k)
    if "/" in k:
      raise ValueError("registry params key %r contains '/'" % k)
    path = prefix + k
    if isinstance(v, dict):
      out.update(_flatten(v, path + "/"))
    else:
      out[path] = v
  return out


def _unflatten(flat: Dict[str, Any]) -> Dict[str, Any]:
  out: Dict[str, Any] = {}
  for path, v in flat.items():
    parts = path.split("/")
    node = out
    for p in parts[:-1]:
      node = node.setdefault(p, {})
    node[parts[-1]] = v
  return out


class ModelRegistry(object):
  """Filesystem model registry with atomic publish and ref-counted GC.

  Thread-safe; cheap to construct (a reader needs only the root path).
  Multiple processes may read one registry; publish assumes a single
  writer (the chief trainer — the same topology rule as chief-only
  checkpoint writes).
  """

  def __init__(self, root: str, keep: Optional[int] = None):
    self.root = str(root)
    os.makedirs(self.root, exist_ok=True)
    if keep is None:
      keep = int(os.environ.get(ENV_REGISTRY_KEEP, _DEFAULT_KEEP))
    self.keep = max(1, int(keep))
    self._lock = threading.Lock()
    self._refs: Dict[int, int] = {}

  # -- paths -----------------------------------------------------------------

  def _dir(self, version: int) -> str:
    return os.path.join(self.root, _DIR_FMT % version)

  def _marker_path(self, version: int) -> str:
    return os.path.join(self._dir(version), _MARKER)

  def _quarantine_path(self, version: int) -> str:
    return os.path.join(self._dir(version), _QUARANTINE)

  # -- publish ---------------------------------------------------------------

  def publish(self, params: Any, step: int, lineage: Optional[dict] = None,
              extra: Optional[dict] = None) -> int:
    """Publish ``params`` as the next version; returns the version number.

    Durability order is the commit-marker protocol: params bytes are
    written and fsynced, THEN the marker commits the version atomically.
    The manifest records the content fingerprint
    (``utils.checkpoint.params_fingerprint``) so a reader — and VERIFY in
    the deploy controller — can detect corruption-at-rest before a single
    request is routed at the version.
    """
    import numpy as np
    version = (self._newest_dir() or 0) + 1
    vdir = self._dir(version)
    os.makedirs(vdir, exist_ok=True)
    flat = _flatten(params)
    arrays = {k: np.asarray(v) for k, v in flat.items()}
    ppath = os.path.join(vdir, _PARAMS)
    with open(ppath, "wb") as f:
      np.savez(f, **arrays)
      f.flush()
      os.fsync(f.fileno())
    manifest = {
        "version": version,
        "step": int(step),
        "fingerprint": params_fingerprint(params),
        "lineage": dict(lineage or {}),
        "published_at": time.time(),
    }
    if extra:
      manifest["extra"] = dict(extra)
    atomic_write_json(self._marker_path(version), manifest)
    logger.info("registry published version %d (step %d)", version, step)
    return version

  def publish_on_checkpoint(self, manager: Any,
                            get_params: Optional[Callable] = None,
                            lineage: Optional[dict] = None) -> None:
    """Attach this registry to a ``CheckpointManager``: every COMMITTED
    checkpoint (marker durable) is published as a serving candidate on
    the existing save cadence — the trainer side of the continuous
    deployment loop. ``get_params`` extracts the params pytree from the
    saved train state (default: ``state.params``, falling back to the
    state itself for a bare params dict)."""
    def _hook(step, state, manifest):
      params = (get_params(state) if get_params is not None
                else getattr(state, "params", state))
      lin = dict(lineage or {})
      lin.setdefault("checkpoint_dir", getattr(manager, "directory", None))
      if manifest:
        lin.setdefault("checkpoint_manifest", manifest)
      self.publish(params, step=step, lineage=lin)
    manager.publish_hook = _hook

  # -- read side -------------------------------------------------------------

  def _newest_dir(self) -> Optional[int]:
    """Highest version DIRECTORY number (marked or not) — the publish
    counter must never reuse a torn version's number."""
    vs = []
    try:
      names = os.listdir(self.root)
    except OSError:
      return None
    for name in names:
      if name.startswith(_DIR_PREFIX):
        try:
          vs.append(int(name[len(_DIR_PREFIX):]))
        except ValueError:
          continue
    return max(vs) if vs else None

  def versions(self, include_quarantined: bool = False) -> List[int]:
    """Ascending COMMITTED versions (marker present and parseable). A
    version whose publish tore — any file truncated before the marker
    landed, or the marker itself unreadable — simply does not exist
    here: that is the deterministic torn-publish story."""
    out = []
    for v in sorted(set(self._all_dirs())):
      if self._manifest_or_none(v) is None:
        continue
      if not include_quarantined and self.is_quarantined(v):
        continue
      out.append(v)
    return out

  def _all_dirs(self) -> List[int]:
    vs = []
    try:
      names = os.listdir(self.root)
    except OSError:
      return []
    for name in names:
      if name.startswith(_DIR_PREFIX):
        try:
          vs.append(int(name[len(_DIR_PREFIX):]))
        except ValueError:
          continue
    return vs

  def _manifest_or_none(self, version: int) -> Optional[dict]:
    try:
      with open(self._marker_path(version)) as f:
        return json.load(f)
    except (OSError, ValueError):
      return None

  def latest(self) -> Optional[int]:
    """Newest committed, non-quarantined version, or None."""
    vs = self.versions()
    return vs[-1] if vs else None

  def manifest(self, version: int) -> dict:
    rec = self._manifest_or_none(version)
    if rec is None:
      raise FileNotFoundError("registry version %d in %s has no commit "
                              "marker (torn or missing publish)"
                              % (version, self.root))
    return rec

  def get(self, version: int, verify: bool = True):
    """(params, manifest) for a committed version.

    ``verify=True`` recomputes the content fingerprint against the
    manifest — corruption-at-rest (bit rot, a partial copy) surfaces
    here as a ``ValueError`` instead of as wrong logits in production.
    """
    import numpy as np
    manifest = self.manifest(version)
    with np.load(os.path.join(self._dir(version), _PARAMS)) as z:
      params = _unflatten({k: z[k] for k in z.files})
    if verify:
      fp = params_fingerprint(params)
      if fp != manifest.get("fingerprint"):
        raise ValueError(
            "registry version %d params fingerprint %s != manifest %s "
            "(corrupt at rest)" % (version, fp, manifest.get("fingerprint")))
    return params, manifest

  def watch(self, timeout: float, last_seen: Optional[int] = None,
            poll: Optional[float] = None) -> Optional[int]:
    """Block until a version newer than ``last_seen`` commits; returns
    it, or None on timeout. The deploy controller's main wait."""
    if poll is None:
      poll = float(os.environ.get(ENV_REGISTRY_POLL, _DEFAULT_POLL))
    deadline = time.monotonic() + timeout
    while True:
      cur = self.latest()
      if cur is not None and (last_seen is None or cur > last_seen):
        return cur
      if time.monotonic() >= deadline:
        return None
      time.sleep(min(poll, max(0.0, deadline - time.monotonic())))

  # -- quarantine ------------------------------------------------------------

  def quarantine(self, version: int, verdict: Optional[dict] = None) -> None:
    """Mark a version failed (rollback): hidden from ``latest()``/
    ``watch()`` forever, kept on disk with the structured verdict as the
    post-mortem record. Atomic (same marker protocol)."""
    atomic_write_json(self._quarantine_path(version), {
        "version": int(version),
        "verdict": dict(verdict or {}),
        "quarantined_at": time.time(),
    })
    logger.warning("registry version %d quarantined: %s", version,
                   (verdict or {}).get("reason", "unspecified"))

  def is_quarantined(self, version: int) -> bool:
    return os.path.exists(self._quarantine_path(version))

  def quarantine_record(self, version: int) -> Optional[dict]:
    try:
      with open(self._quarantine_path(version)) as f:
        return json.load(f)
    except (OSError, ValueError):
      return None

  # -- ref-counted retention -------------------------------------------------

  def acquire(self, version: int) -> None:
    """Pin a version against GC (a fleet serving or canarying it)."""
    with self._lock:
      self._refs[version] = self._refs.get(version, 0) + 1

  def release(self, version: int) -> None:
    with self._lock:
      n = self._refs.get(version, 0) - 1
      if n <= 0:
        self._refs.pop(version, None)
      else:
        self._refs[version] = n

  def refcount(self, version: int) -> int:
    with self._lock:
      return self._refs.get(version, 0)

  def gc(self, keep: Optional[int] = None) -> List[int]:
    """Delete old versions beyond the newest ``keep`` live ones; returns
    the versions removed. NEVER deletes: a version some fleet still
    serves (refcount > 0), the newest live version, a quarantined
    version (the verdict is the record), or an unmarked directory newer
    than every committed version (it may be a publish in flight)."""
    import shutil
    keep = self.keep if keep is None else max(1, int(keep))
    live = self.versions()
    removed = []
    if not live:
      return removed
    newest = live[-1]
    candidates = live[:-keep] if len(live) > keep else []
    for v in candidates:
      if v == newest or self.refcount(v) > 0:
        continue
      try:
        shutil.rmtree(self._dir(v))
        removed.append(v)
      except OSError as e:  # tosa: ignore[TOS004] - GC is best-effort
        # retention pruning must never fail a publish/deploy; the
        # version stays and the next gc() pass retries it
        logger.warning("registry gc of version %d failed: %s", v, e)
    if removed:
      logger.info("registry gc removed versions %s", removed)
    return removed
