"""L5' ML pipeline: Estimator/Model API over the cluster layer.

Capability parity with the reference's ``pipeline.py``
(/root/reference/tensorflowonspark/pipeline.py), without requiring Spark ML:

- ``Namespace`` + ``TFParams.merge_args_params`` reproduce the layered
  config merge (:299-351);
- the ``Has*`` param mixins exist with the same names and setter/getter
  surface (:52-296), generated over a lightweight Params base;
- ``TFEstimator.fit`` launches a real cluster in ENGINE input mode, feeds
  the dataset sorted by input-mapping columns, shuts down with a grace
  period, and returns a ``TFModel`` (:354-435);
- ``TFModel.transform`` runs independent single-node inference per
  executor with a per-process model cache (:438-647), loading an exported
  bundle (orbax state + cloudpickled predict fn) instead of a TF
  SavedModel signature;
- ``yield_batch`` batches rows for the predict fn (:691-713).

The model artifact is a *bundle* directory:
  ``<export_dir>/model/``     orbax checkpoint of the params pytree
  ``<export_dir>/predict.pkl`` cloudpickled ``predict_fn(params, batch)``
where ``batch`` is a dict of stacked numpy arrays keyed by input tensor
names, and the fn returns a dict keyed by output tensor names.
"""

import argparse
import logging
import os
from typing import Dict, Iterable, List, Optional, Sequence

from tensorflowonspark_tpu import cluster as cluster_lib
from tensorflowonspark_tpu.cluster import InputMode

logger = logging.getLogger(__name__)


class Namespace(dict):
  """argparse-compatible bag of arguments (parity: pipeline.py:299-339).

  Accepts a dict, an ``argparse.Namespace``, a list of argv strings, or
  another Namespace; attribute and item access are interchangeable.
  """

  def __init__(self, d=None):
    super().__init__()
    if d is None:
      return
    if isinstance(d, (list, tuple)):
      self["argv"] = list(d)
    elif isinstance(d, argparse.Namespace):
      self.update(vars(d))
    elif isinstance(d, dict):
      self.update(d)
    else:
      raise TypeError("unsupported Namespace source: %r" % type(d))

  def __getattr__(self, name):
    try:
      return self[name]
    except KeyError:
      raise AttributeError(name)

  def __setattr__(self, name, value):
    self[name] = value


# --- lightweight Spark-ML-style Params --------------------------------------


class Params(object):
  """Minimal Params base: declared params become get/set pairs."""

  _params: Dict[str, object]

  def __init__(self):
    self._params = {}

  def _declare(self, name: str, default=None):
    self._params.setdefault(name, default)

  def _set(self, **kwargs):
    for k, v in kwargs.items():
      self._params[k] = v
    return self

  def _get(self, name: str):
    return self._params.get(name)


def _mixin(name: str, param: str, default=None, cap: Optional[str] = None):
  """Build a Has<X> mixin exposing set<X>/get<X> (parity: the ~17 Has*
  mixins at reference pipeline.py:52-296)."""
  cap = cap or "".join(p.capitalize() for p in param.split("_"))

  def setter(self, value):
    self._declare(param, default)
    return self._set(**{param: value})

  def getter(self):
    self._declare(param, default)
    return self._get(param)

  return type(name, (object,), {"set" + cap: setter, "get" + cap: getter,
                                "_param_name": param,
                                "_param_default": default})


HasBatchSize = _mixin("HasBatchSize", "batch_size", 100)
HasClusterSize = _mixin("HasClusterSize", "cluster_size", 1)
HasNumPS = _mixin("HasNumPS", "num_ps", 0, cap="NumPS")
HasInputMapping = _mixin("HasInputMapping", "input_mapping")
HasOutputMapping = _mixin("HasOutputMapping", "output_mapping")
HasInputMode = _mixin("HasInputMode", "input_mode", InputMode.ENGINE)
HasMasterNode = _mixin("HasMasterNode", "master_node", "chief")
HasModelDir = _mixin("HasModelDir", "model_dir")
HasExportDir = _mixin("HasExportDir", "export_dir")
HasEpochs = _mixin("HasEpochs", "epochs", 1)
HasGraceSecs = _mixin("HasGraceSecs", "grace_secs", 30)
HasReservationTimeout = _mixin("HasReservationTimeout",
                               "reservation_timeout", 600)
HasFeedTimeout = _mixin("HasFeedTimeout", "feed_timeout", 600)
HasTensorboard = _mixin("HasTensorboard", "tensorboard", False)
HasSignatureDefKey = _mixin("HasSignatureDefKey", "signature_def_key",
                            "serving_default")
HasChipsPerNode = _mixin("HasChipsPerNode", "chips_per_node", 0)
HasProtocol = _mixin("HasProtocol", "protocol", "grpc")


class TFParams(Params, HasBatchSize, HasClusterSize, HasNumPS,
               HasInputMapping, HasOutputMapping, HasInputMode,
               HasMasterNode, HasModelDir, HasExportDir, HasEpochs,
               HasGraceSecs, HasReservationTimeout, HasFeedTimeout,
               HasTensorboard, HasSignatureDefKey, HasChipsPerNode,
               HasProtocol):
  """All pipeline params (parity: reference TFParams, pipeline.py:342-351)."""

  def merge_args_params(self, args) -> Namespace:
    """Overlay set params onto a Namespace of args."""
    merged = Namespace(args)
    merged.update(self._params)
    return merged


# --- model bundle -----------------------------------------------------------


def export_bundle(params, predict_fn, export_dir: str,
                  is_chief: bool = True, example_batch=None,
                  output_signature: Optional[Dict] = None) -> str:
  """Write the model bundle (orbax params + pickled predict fn).

  When ``example_batch`` (a dict of input arrays) is given, the predict fn
  runs once at export time and the bundle records an output SIGNATURE —
  output names, dtypes and trailing shapes — so serving derives its output
  schema from the model without the caller re-declaring it
  (parity: Scala ``TFModel.transformSchema`` deriving output columns from
  the graph, reference TFModel.scala:294-311). ``output_signature`` may
  instead declare it explicitly: ``{name: {"dtype": ..., "shape": [...]}}``.
  """
  import cloudpickle
  from tensorflowonspark_tpu.utils import compat

  target = compat.export_model(params, export_dir, is_chief)
  with open(os.path.join(target, "predict.pkl"), "wb") as f:
    cloudpickle.dump(predict_fn, f)

  signature = dict(output_signature) if output_signature else None
  inputs = None
  if example_batch is not None:
    import numpy as np
    inputs = sorted(example_batch)
    out = predict_fn(params, example_batch)
    if not isinstance(out, dict):
      out = {"output": out}
    signature = {
        name: {"dtype": str(np.asarray(a).dtype),
               # leading batch dim is caller-determined; record the rest
               "shape": [None] + list(np.asarray(a).shape[1:])}
        for name, a in out.items()}
  if signature is not None:
    with open(os.path.join(target, "signature.json"), "w") as f:
      import json
      json.dump({"inputs": inputs, "outputs": signature}, f, indent=2)
  return target


def load_signature(export_dir: str) -> Optional[Dict]:
  """The bundle's recorded IO signature, or None for pre-signature
  bundles: ``{"inputs": [names] | None, "outputs": {name: {dtype, shape}}}``.
  """
  path = os.path.join(export_dir, "signature.json")
  if not os.path.exists(path):
    return None
  import json
  with open(path) as f:
    return json.load(f)


def signature_output_names(export_dir: str) -> Optional[List[str]]:
  """The bundle signature's output columns in serving order (sorted), or
  None for pre-signature bundles. The ONE derivation both TFModel.transform
  and the inference CLI use, so column names and value order can never
  drift apart (transformSchema parity, reference TFModel.scala:294-311)."""
  sig = load_signature(export_dir)
  if sig and sig.get("outputs"):
    return sorted(sig["outputs"])
  return None


def _host_local_slot(workers_per_host: int):
  """Claim a free host-local worker slot from a flock'd slot file.

  Spark offers no guarantee that tasks co-located on one host carry
  non-congruent partition ids — ids 0 and ``workers_per_host`` landing on
  the same host would both map to slot 0 under a plain modulus. A per-host
  slot file (``fcntl.flock`` over a tmp path, keyed by uid) hands each
  claiming process a distinct free slot instead, which is disjoint
  whenever at most ``workers_per_host`` executor processes claim per host
  — the sizing the ``chips_per_node`` contract implies. Returns None when
  the slot file is unusable or exhausted; callers fall back to the
  partition-id heuristic.

  The file holds a ``{slot: claiming pid}`` map, not a bare counter:
  claims by dead processes are reclaimed, so a replacement executor after
  a task failure takes the freed slot instead of colliding with a live
  one. When every slot is held by a live process (oversubscription) the
  claim returns None. The open refuses symlinks and the lock wait is
  bounded — a wedged (or hostile) holder on the shared tmp path degrades
  placement to the heuristic, never hangs the task.
  """
  import fcntl
  import json
  import tempfile
  import time
  path = os.path.join(tempfile.gettempdir(),
                      "tos_transform_slots.%d" % os.getuid())
  try:
    fd = os.open(path,
                 os.O_RDWR | os.O_CREAT | getattr(os, "O_NOFOLLOW", 0),
                 0o600)
  except OSError:
    return None
  try:
    for _ in range(50):
      try:
        fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
        break
      except OSError:
        time.sleep(0.1)
    else:
      return None
    try:
      raw = os.read(fd, 1 << 16).strip()
      try:
        claims = {int(s): int(p) for s, p in json.loads(raw).items()} \
            if raw else {}
      except (ValueError, AttributeError):
        claims = {}

      def _alive(pid):
        try:
          os.kill(pid, 0)
          return True
        except OSError:
          return False

      claims = {s: p for s, p in claims.items()
                if 0 <= s < workers_per_host and _alive(p)}
      me = os.getpid()
      for s, p in claims.items():
        if p == me:  # idempotent under worker reuse: keep the held slot
          return s
      free = [s for s in range(workers_per_host) if s not in claims]
      if not free:
        return None
      claims[free[0]] = os.getpid()
      os.lseek(fd, 0, os.SEEK_SET)
      os.ftruncate(fd, 0)
      os.write(fd, json.dumps({str(s): p
                               for s, p in claims.items()}).encode())
      return free[0]
    finally:
      fcntl.flock(fd, fcntl.LOCK_UN)
  except OSError:
    return None
  finally:
    os.close(fd)


def _transform_worker_slot(workers_per_host: int = 0) -> int:
  """This task's host-local worker index for chip placement.

  LocalEngine executors export ``TOS_EXECUTOR_SLOT``. Spark tasks claim
  the next slot from a host-local atomic counter when ``workers_per_host``
  is known (guaranteed-disjoint, see ``_host_local_slot``), falling back
  to a deterministic slot from their partition id (the reference's
  placement-by-worker-index, gpu_info.py:80-91 — a heuristic: congruent
  partition ids co-located on one host would double-claim). Anything else
  gets slot 0.
  """
  slot = os.environ.get("TOS_EXECUTOR_SLOT")
  if slot is not None:
    return int(slot)
  try:
    from pyspark import TaskContext
    ctx = TaskContext.get()
    if ctx is not None:
      if workers_per_host > 0:
        claimed = _host_local_slot(workers_per_host)
        if claimed is not None:
          return claimed
      return ctx.partitionId()
  except ImportError:
    pass
  return 0


def _allocate_transform_chips(chips_per_node: int) -> None:
  """Claim this task's disjoint chip share before JAX initializes.

  No-op without ``chips_per_node``, in test mode, or when already
  allocated / no TPU topology is visible.
  """
  if not chips_per_node or os.environ.get("TOS_TPU_TEST_MODE"):
    return
  if os.environ.get("TOS_CHIP_ENV_APPLIED"):
    return  # a prior task on this executor process already claimed chips
  from tensorflowonspark_tpu.utils import tpu_info
  topo = tpu_info.get_topology()
  if topo is None:
    return
  workers_per_host = max(1, topo.chips_per_host // chips_per_node)
  slot = _transform_worker_slot(workers_per_host) % workers_per_host
  tpu_info.apply_chip_env(tpu_info.chip_env_for_worker(
      chips_per_node, slot, workers_per_host, generation=topo.generation))
  os.environ["TOS_CHIP_ENV_APPLIED"] = "1"


# per-executor-process bundle cache (parity: pipeline.py:495-499)
_bundle_cache: Dict[str, tuple] = {}


def load_bundle(export_dir: str):
  """Load (params, predict_fn), cached per process."""
  import cloudpickle
  from tensorflowonspark_tpu.utils import compat

  key = os.path.abspath(export_dir)
  if key not in _bundle_cache:
    params = compat.import_model(export_dir)
    with open(os.path.join(export_dir, "predict.pkl"), "rb") as f:
      predict_fn = cloudpickle.load(f)
    _bundle_cache[key] = (params, predict_fn)
    logger.info("loaded model bundle from %s", export_dir)
  return _bundle_cache[key]


def yield_batch(iterable: Iterable, batch_size: int,
                num_tensors: int = 1):
  """Group rows into lists-of-columns batches (parity: pipeline.py:691-713).

  Yields lists of ``num_tensors`` column lists.
  """
  cols: List[List] = [[] for _ in range(num_tensors)]
  count = 0
  for row in iterable:
    if num_tensors == 1 and not isinstance(row, (tuple, list)):
      row = (row,)
    for i in range(num_tensors):
      cols[i].append(row[i])
    count += 1
    if count >= batch_size:
      yield cols
      cols = [[] for _ in range(num_tensors)]
      count = 0
  if count > 0:
    yield cols


# --- Estimator / Model ------------------------------------------------------


class TFEstimator(TFParams):
  """Trains a model on a cluster and produces a TFModel.

  ``train_fn(args, ctx)`` is the user main function; it should consume the
  DataFeed and, on the chief, call ``pipeline.export_bundle`` with
  ``args.export_dir``.
  """

  def __init__(self, train_fn, tf_args=None, export_fn=None):
    super().__init__()
    self.train_fn = train_fn
    self.tf_args = tf_args if tf_args is not None else {}
    self.export_fn = export_fn

  def fit(self, engine, partitions: Sequence) -> "TFModel":
    """Launch a cluster, feed the dataset, return the trained TFModel
    (parity: TFEstimator._fit, pipeline.py:395-435)."""
    args = self.merge_args_params(self.tf_args)
    cluster_size = args.get("cluster_size") or engine.num_executors
    logger.info("fitting TFEstimator on %d executor(s)", cluster_size)

    input_mode = args.get("input_mode", InputMode.ENGINE)
    cluster = cluster_lib.run(
        engine, self.train_fn, tf_args=args,
        num_executors=cluster_size,
        num_ps=args.get("num_ps", 0),
        tensorboard=bool(args.get("tensorboard")),
        input_mode=input_mode,
        log_dir=args.get("model_dir"),
        master_node=args.get("master_node", "chief"),
        reservation_timeout=args.get("reservation_timeout", 600),
        chips_per_node=args.get("chips_per_node", 0))
    if input_mode == InputMode.ENGINE:
      cluster.train(partitions, num_epochs=args.get("epochs", 1),
                    feed_timeout=args.get("feed_timeout", 600))
    # FILES mode: the main fn reads its own data; nothing to feed
    cluster.shutdown(grace_secs=args.get("grace_secs", 30))

    model = TFModel(self.tf_args)
    model._params.update(self._params)
    return model


class TFModel(TFParams):
  """Batch inference with independent per-executor model instances
  (parity: TFModel, pipeline.py:438-647)."""

  def __init__(self, tf_args=None):
    super().__init__()
    self.tf_args = tf_args if tf_args is not None else {}

  def transform(self, engine, partitions: Sequence, collect: bool = True):
    """Run the exported bundle over partitioned rows.

    Rows are tuples ordered by ``sorted(input_mapping)`` columns; outputs
    are tuples ordered by ``sorted(output_mapping)`` tensor names
    (column-mapping parity: pipeline.py:463-492).

    ``collect=False`` returns the engine's lazy handle instead of a
    driver-side list (Spark: the uncollected result RDD — the reference's
    ``TFModel._transform`` returned a DataFrame, pipeline.py:487-492;
    LocalEngine: a streaming generator), for cluster-scale inference.
    """
    args = self.merge_args_params(self.tf_args)
    export_dir = args.get("export_dir") or args.get("model_dir")
    if not export_dir:
      raise ValueError("TFModel requires export_dir (or model_dir)")
    input_mapping = args.get("input_mapping") or {}
    output_mapping = args.get("output_mapping") or {}
    batch_size = args.get("batch_size", 100)
    chips_per_node = args.get("chips_per_node", 0) or 0

    input_tensors = [input_mapping[c] for c in sorted(input_mapping)] \
        if input_mapping else None
    output_tensors = sorted(output_mapping) if output_mapping else None
    if output_tensors is None:
      # transformSchema parity: the bundle's recorded signature declares
      # the output columns ahead of execution (TFModel.scala:294-311)
      output_tensors = signature_output_names(export_dir)

    def _transform_partition(iterator):
      import numpy as np

      def _stack_column(col):
        # variable-length rows (mixed-length generation prompts) cannot
        # stack rectangularly: hand the predict fn an object column —
        # serving predict fns route those through the continuous-batching
        # engine (models.transformer.make_serving_predict_fn)
        try:
          return np.asarray(col)
        except ValueError:
          arr = np.empty(len(col), object)
          arr[:] = col
          return arr

      # N parallel inference tasks on one TPU host must claim DISJOINT
      # chips (the same allocation parallel/runner.py does, parity
      # TFParallel.py:43-56) — before the bundle load initializes JAX
      _allocate_transform_chips(chips_per_node)
      params, predict_fn = load_bundle(export_dir)
      results = []
      n_cols = len(input_tensors) if input_tensors else 1
      for cols in yield_batch(iterator, batch_size, n_cols):
        if input_tensors:
          batch = {name: _stack_column(col)
                   for name, col in zip(input_tensors, cols)}
        else:
          batch = {"input": _stack_column(cols[0])}
        out = predict_fn(params, batch)
        if not isinstance(out, dict):
          out = {"output": out}
        names = output_tensors or sorted(out)
        arrays = [np.asarray(out[n]) for n in names]
        for i in range(len(arrays[0])):
          row = tuple(a[i].tolist() for a in arrays)
          results.append(row[0] if len(row) == 1 else row)
      return results

    if collect:
      return engine.map_partitions(partitions, _transform_partition,
                                   timeout=args.get("feed_timeout", 600))
    return engine.map_partitions_lazy(partitions, _transform_partition,
                                      timeout=args.get("feed_timeout", 600))
