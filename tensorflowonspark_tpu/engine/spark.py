"""SparkEngine: adapter mapping the Engine contract onto pyspark.

Optional — importable only where pyspark is installed. Maps each Engine
operation onto the exact Spark idiom the reference used:

- ``run_on_executors``  → ``sc.parallelize(range(n), n).foreachPartition``
  (reference TFCluster.py:301,321), launched from a daemon thread so it is
  async like the reference's ``_start`` thread (TFCluster.py:318-336);
- ``foreach_partition`` → ``rdd.foreachPartition``;
- ``map_partitions``    → ``rdd.mapPartitions(...).collect()``;
- ``barrier_run``       → ``rdd.barrier().mapPartitions`` with
  BarrierTaskContext (reference TFParallel.py:43-74).

``from_rdd`` lets callers hand existing RDDs/DataFrames to cluster.train /
cluster.inference without materializing them on the driver.
"""

import logging
import threading
from typing import Callable, Iterable, List, Optional, Sequence

from tensorflowonspark_tpu.engine.base import BarrierContext, Engine, EngineJob

logger = logging.getLogger(__name__)


class SparkEngine(Engine):
  """Engine over a live SparkContext (requires pyspark)."""

  def __init__(self, sc=None, num_executors: Optional[int] = None):
    if sc is None:
      from pyspark import SparkContext
      sc = SparkContext.getOrCreate()
    self.sc = sc
    if num_executors is None:
      num_executors = int(sc.getConf().get("spark.executor.instances", "0")) \
          or sc.defaultParallelism
    self._num_executors = num_executors

  @property
  def num_executors(self) -> int:
    return self._num_executors

  def default_fs(self) -> str:
    try:
      return self.sc._jsc.hadoopConfiguration().get("fs.defaultFS")
    except Exception:  # noqa: BLE001 - no JVM/hadoop conf
      return "file://"

  def _async_job(self, runner: Callable[[], List], num_tasks: int) -> EngineJob:
    job = EngineJob(num_tasks)
    job.job_id = -1

    def _run():
      try:
        results = runner()
        for i in range(num_tasks):
          r = results[i] if results and i < len(results) else None
          job._task_finished(i, result=r)
      except Exception:  # noqa: BLE001 - deliver driver-side traceback
        import traceback
        tb = traceback.format_exc()
        for i in range(num_tasks):
          if job.errors[i] is None and job.results[i] is None:
            job._task_finished(i, error=tb)

    threading.Thread(target=_run, daemon=True,
                     name="spark-engine-job").start()
    return job

  def run_on_executors(self, fn, num_tasks: Optional[int] = None,
                       task_payloads=None) -> EngineJob:
    n = num_tasks if num_tasks is not None else self._num_executors
    payloads = list(task_payloads) if task_payloads is not None \
        else list(range(n))
    if len(payloads) != n:
      raise ValueError("task_payloads has %d entries for %d tasks"
                       % (len(payloads), n))
    rdd = self.sc.parallelize(payloads, n)

    def _wrap(it):
      yield fn(it)  # preserve per-task return values (LocalEngine parity)

    def runner():
      return rdd.mapPartitions(_wrap).collect()

    return self._async_job(runner, n)

  def foreach_partition(self, partitions, fn) -> EngineJob:
    rdd = self._as_rdd(partitions)
    n = rdd.getNumPartitions()

    def runner():
      rdd.foreachPartition(fn)
      return [None] * n

    return self._async_job(runner, n)

  def map_partitions(self, partitions, fn, timeout=None) -> List:
    rdd = self._as_rdd(partitions)
    if timeout is None:
      return rdd.mapPartitions(fn).collect()
    # honor the bound like LocalEngine: run the collect on a worker thread
    # and fail if it exceeds the timeout
    job = self._async_job(lambda: [rdd.mapPartitions(fn).collect()], 1)
    return job.wait(timeout=timeout)[0]

  def barrier_run(self, fn, num_tasks: Optional[int] = None,
                  timeout: Optional[float] = None) -> List:
    n = num_tasks if num_tasks is not None else self._num_executors
    rdd = self.sc.parallelize(range(n), n)

    def _task(it):
      from pyspark import BarrierTaskContext
      btc = BarrierTaskContext.get()
      infos = [t.address for t in btc.getTaskInfos()]
      ctx = BarrierContext(btc.partitionId(), infos, sync_fn=btc.barrier)
      return [fn(it, ctx)]

    return rdd.barrier().mapPartitions(_task).collect()

  def _as_rdd(self, partitions):
    """Accept an existing RDD, a DataFrame, or driver-side partition lists."""
    if hasattr(partitions, "rdd"):      # DataFrame
      return partitions.rdd
    if hasattr(partitions, "mapPartitions"):  # RDD
      return partitions
    return self.sc.parallelize(
        [row for part in partitions for row in part], max(1, len(partitions)))
