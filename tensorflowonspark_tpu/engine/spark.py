"""SparkEngine: adapter mapping the Engine contract onto pyspark.

Optional — importable only where pyspark is installed. Maps each Engine
operation onto the exact Spark idiom the reference used:

- ``run_on_executors``  → ``sc.parallelize(range(n), n).mapPartitions``
  (reference TFCluster.py:301,321), launched from a daemon thread so it is
  async like the reference's ``_start`` thread (TFCluster.py:318-336);
- ``foreach_partition`` → per-partition side-effect tasks;
- ``map_partitions``    → ``rdd.mapPartitions(...).collect()``;
- ``barrier_run``       → ``rdd.barrier().mapPartitions`` with
  BarrierTaskContext (reference TFParallel.py:43-74).

``_as_rdd`` lets callers hand existing RDDs/DataFrames to cluster.train /
cluster.inference without materializing them on the driver.

Every partition function is wrapped so a failing task ships ITS OWN
traceback back through the collect (LocalEngine parity) instead of Spark
aborting the whole job with one driver-side exception for all tasks.
"""

import collections.abc
import logging
import threading
from typing import Callable, List, Optional

from tensorflowonspark_tpu.engine.base import BarrierContext, Engine, EngineJob

logger = logging.getLogger(__name__)

_OK, _ERR = "ok", "err"


def _capture(fn: Callable):
  """Wrap a partition fn so each task returns ``(status, payload)``.

  Failures are materialized per task (payload = that task's traceback),
  which preserves the per-task error attribution LocalEngine gives —
  otherwise one bad partition aborts the Spark job and every task reports
  the same driver-side exception.
  """
  def _wrap(it):
    try:
      yield (_OK, fn(it))
    except Exception:  # noqa: BLE001 - shipped back as the task's error
      import traceback
      yield (_ERR, traceback.format_exc())
  return _wrap


class SparkEngine(Engine):
  """Engine over a live SparkContext (requires pyspark)."""

  def __init__(self, sc=None, num_executors: Optional[int] = None):
    if sc is None:
      from pyspark import SparkContext
      sc = SparkContext.getOrCreate()
    self.sc = sc
    if num_executors is None:
      num_executors = int(sc.getConf().get("spark.executor.instances", "0")) \
          or sc.defaultParallelism
    self._num_executors = num_executors

  @property
  def num_executors(self) -> int:
    return self._num_executors

  def default_fs(self) -> str:
    try:
      return self.sc._jsc.hadoopConfiguration().get("fs.defaultFS")
    except Exception:  # noqa: BLE001 - no JVM/hadoop conf
      return "file://"

  def _async_job(self, runner: Callable[[], List], num_tasks: int) -> EngineJob:
    """Run ``runner`` (returning one (status, payload) pair per task) on a
    daemon thread, routing per-task results/errors into an EngineJob."""
    job = EngineJob(num_tasks)
    job.job_id = -1

    def _run():
      try:
        pairs = runner()
      except Exception:  # noqa: BLE001 - whole-job (driver-side) failure
        import traceback
        tb = traceback.format_exc()
        for i in range(num_tasks):
          job._task_finished(i, error=tb)
        return
      for i in range(num_tasks):
        status, payload = pairs[i] if i < len(pairs) else \
            (_ERR, "task %d produced no result" % i)
        if status == _OK:
          job._task_finished(i, result=payload, attempt=0)
        else:
          job._task_finished(i, error=payload, attempt=0)

    threading.Thread(target=_run, daemon=True,
                     name="spark-engine-job").start()
    return job

  def run_on_executors(self, fn, num_tasks: Optional[int] = None,
                       task_payloads=None) -> EngineJob:
    n = num_tasks if num_tasks is not None else self._num_executors
    payloads = list(task_payloads) if task_payloads is not None \
        else list(range(n))
    if len(payloads) != n:
      raise ValueError("task_payloads has %d entries for %d tasks"
                       % (len(payloads), n))
    rdd = self.sc.parallelize(payloads, n)
    job = self._async_job(rdd.mapPartitions(_capture(fn)).collect, n)
    # retained for supervised relaunch (cluster.ClusterSupervisor): a dead
    # node's bring-up task can be resubmitted as a fresh one-task job
    job._relaunch_spec = (fn, payloads)
    return job

  def relaunch_task(self, job, task_id: int, payload=None):
    """Resubmit ONE task of a run_on_executors job as a fresh single-task
    Spark job, routing its result back into the original EngineJob slot.

    Spark's own task retries cover transient in-job failures; this hook is
    for the cluster supervisor's slower path — relaunching a node whose
    executor was lost after the original job already recorded the loss.
    """
    spec = getattr(job, "_relaunch_spec", None)
    if spec is None:
      raise NotImplementedError(
          "SparkEngine can only relaunch run_on_executors tasks")
    fn, payloads = spec
    p = payload if payload is not None else payloads[task_id]
    attempt = job._task_restarted(task_id)
    rdd = self.sc.parallelize([p], 1)

    # UNcaptured: Spark cannot pin the replacement to a particular
    # executor, and a node bring-up landing on an executor that already
    # hosts a live node fails its reclaim check by design ("so the engine
    # can retry it elsewhere", node.py). Letting the exception reach Spark
    # makes spark.task.maxFailures reschedule the task on other executors
    # until placement works; only the final failure ships back here.
    def _run():
      try:
        out = rdd.mapPartitions(fn).collect()
        status, result = _OK, out
      except Exception:  # noqa: BLE001 - exhausted Spark-side retries
        import traceback
        status, result = _ERR, traceback.format_exc()
      if status == _OK:
        job._task_finished(task_id, result=result, attempt=attempt)
      else:
        job._task_finished(task_id, error=result, attempt=attempt)

    threading.Thread(target=_run, daemon=True,
                     name="spark-engine-relaunch-%d" % task_id).start()

  def foreach_partition(self, partitions, fn) -> EngineJob:
    rdd = self._as_rdd(partitions)
    n = rdd.getNumPartitions()

    def _consume(it):
      fn(it)
      return None

    return self._async_job(rdd.mapPartitions(_capture(_consume)).collect, n)

  def map_partitions(self, partitions, fn, timeout=None) -> List:
    rdd = self._as_rdd(partitions)
    n = rdd.getNumPartitions()
    # materialize inside the task so lazy/generator errors surface per task
    wrapped = rdd.mapPartitions(_capture(lambda it: list(fn(it))))
    parts = self._async_job(wrapped.collect, n).wait(timeout=timeout)
    return [row for part in parts for row in part]

  def map_partitions_lazy(self, partitions, fn, timeout=None):
    """Return the mapped RDD WITHOUT collecting (parity: reference
    TFCluster.inference returning a lazy RDD, TFCluster.py:96-115) — the
    caller saves/consumes it through Spark, never through the driver.
    ``timeout`` is ignored here: no work runs until the caller's RDD
    action, which owns its own deadline."""
    return self._as_rdd(partitions).mapPartitions(fn)

  def barrier_run(self, fn, num_tasks: Optional[int] = None,
                  timeout: Optional[float] = None) -> List:
    n = num_tasks if num_tasks is not None else self._num_executors
    if n > self._num_executors:
      raise ValueError(
          "barrier gang of %d exceeds %d executors (barrier stages need a "
          "free slot per task or Spark deadlocks)" % (n, self._num_executors))
    rdd = self.sc.parallelize(range(n), n)

    def _task(it):
      from pyspark import BarrierTaskContext
      btc = BarrierTaskContext.get()
      infos = [t.address for t in btc.getTaskInfos()]
      ctx = BarrierContext(btc.partitionId(), infos, sync_fn=btc.barrier)
      return [fn(it, ctx)]

    def _runner():
      return [(_OK, rdd.barrier().mapPartitions(_task).collect())]

    # honor the engine-contract deadline (LocalEngine parity): the collect
    # runs on a worker thread and a hung gang raises TimeoutError here
    # (the abandoned Spark job keeps running server-side; callers shut the
    # cluster down on error anyway)
    return self._async_job(_runner, 1).wait(timeout=timeout)[0]

  def _as_rdd(self, partitions):
    """Accept an existing RDD, a DataFrame, or driver-side partition lists."""
    if hasattr(partitions, "rdd"):      # DataFrame
      return partitions.rdd
    if hasattr(partitions, "mapPartitions"):  # RDD
      return partitions
    # one list element per slice keeps the caller's partition boundaries;
    # the flatten unwraps each slice's single partition-list into its rows
    was_stream = isinstance(partitions, collections.abc.Iterator)
    parts = list(partitions)

    def _is_lazy(p):   # a handle, or _wrap_lazy's [handle] partition shape
      return callable(p) or (isinstance(p, (list, tuple)) and len(p) == 1
                             and callable(p[0]))

    if was_stream and any(not _is_lazy(p) for p in parts):
      logger.warning(
          "SparkEngine: a one-shot partition stream carrying raw rows was "
          "materialized on the DRIVER (O(dataset) driver memory). Ship "
          "lazy handles (load_tfrecords(lazy=True)) or feed via "
          "train_dstream to keep rows executor-side.")
    rdd = self.sc.parallelize(parts, max(1, len(parts)))
    return rdd.mapPartitions(lambda it: (row for part in it for row in part))
