"""Engine interface: what the cluster layer needs from an executor engine.

Derived from how the reference drives Spark (TFCluster.py / TFSparkNode.py /
TFParallel.py):

- enumerate N persistent executors and run a function once on each
  (``nodeRDD.foreachPartition`` — node bring-up, shutdown jobs),
- stream partitioned data through whichever executors are free
  (``dataRDD.foreachPartition`` — feeding; ``dataRDD.mapPartitions`` —
  inference with collected results),
- gang-schedule with placement info (``rdd.barrier().mapPartitions``),
- replicate a dataset for epochs (``sc.union([rdd]*n)``).

Scheduling semantics the cluster layer RELIES on (Spark parity):

1. An executor runs one task at a time; a task that blocks keeps its executor
   busy (this is how ps/evaluator slots are kept out of feed scheduling —
   reference TFCluster.py:12-13).
2. ``run_on_executors`` places exactly one task on each distinct executor.
3. Queued tasks go to any executor that becomes free.
"""

import abc
import threading
import time
from typing import Callable, Iterable, List, Optional, Sequence

#: Error-string prefix engines use when a task died WITH its executor
#: (process killed / crashed without a traceback) — an infrastructure
#: failure, as opposed to an application exception. The cluster-layer
#: supervisor restarts the former and propagates the latter untouched.
EXECUTOR_LOST = "ExecutorLost"


def is_executor_lost(error: Optional[str]) -> bool:
  """True when a task error marks an executor-death (restartable) failure."""
  return bool(error) and error.startswith(EXECUTOR_LOST)


class EngineJob(object):
  """Handle for an asynchronously running set of tasks."""

  def __init__(self, num_tasks: int):
    self.num_tasks = num_tasks
    self.results: List[object] = [None] * num_tasks
    self.errors: List[Optional[str]] = [None] * num_tasks
    self._completed = [False] * num_tasks
    #: per-task attempt counter, bumped by _task_restarted: completions
    #: carry the attempt they belong to, so a stale report from a
    #: superseded attempt (e.g. the executor-death monitor observing the
    #: OLD process after a supervised relaunch was queued) cannot poison
    #: the replacement attempt's bookkeeping
    self._attempt = [0] * num_tasks
    self._done = 0
    self._cond = threading.Condition()

  def _task_finished(self, task_id: int, result=None,
                     error: Optional[str] = None,
                     attempt: Optional[int] = None):
    with self._cond:
      if attempt is not None and attempt != self._attempt[task_id]:
        return   # a superseded attempt reporting late: ignore
      if self._completed[task_id]:
        # late duplicate (e.g. a speculative attempt): first wins
        return
      self._completed[task_id] = True
      self.results[task_id] = result
      self.errors[task_id] = error
      self._done += 1
      self._cond.notify_all()

  def _task_restarted(self, task_id: int) -> int:
    """Reset one task's bookkeeping for a supervised relaunch: waiters go
    back to blocking until the replacement attempt finishes. Returns the
    new attempt number the replacement must report completions under."""
    with self._cond:
      self._attempt[task_id] += 1
      if self._completed[task_id]:
        self._completed[task_id] = False
        self._done -= 1
      self.results[task_id] = None
      self.errors[task_id] = None
      self._cond.notify_all()
      return self._attempt[task_id]

  def done(self) -> bool:
    with self._cond:
      return self._done >= self.num_tasks

  def first_error(self) -> Optional[str]:
    with self._cond:
      for e in self.errors:
        if e is not None:
          return e
      return None

  def wait(self, timeout: Optional[float] = None, raise_on_error: bool = True):
    """Block until all tasks finish; raise the first task error by default.

    Event-driven: waiters sleep on the condition variable and are woken by
    task completions (no polling cadence; a ``timeout`` bounds the wait).
    """
    with self._cond:
      deadline = None if timeout is None else time.monotonic() + timeout
      while self._done < self.num_tasks:
        remaining = None if deadline is None else deadline - time.monotonic()
        if remaining is not None and remaining <= 0:
          raise TimeoutError(
              "engine job timed out with %d/%d tasks finished"
              % (self._done, self.num_tasks))
        self._cond.wait(remaining)
    if raise_on_error:
      err = self.first_error()
      if err:
        raise RuntimeError("engine task failed:\n%s" % err)
    return self.results


class Engine(abc.ABC):
  """Abstract executor engine (see module docstring for the contract)."""

  @property
  @abc.abstractmethod
  def num_executors(self) -> int:
    ...

  @abc.abstractmethod
  def run_on_executors(self, fn: Callable[[Iterable], object],
                       num_tasks: Optional[int] = None,
                       task_payloads: Optional[Sequence] = None) -> EngineJob:
    """Run ``fn(iter([payload]))`` once on each of ``num_tasks`` distinct
    executors (async); payloads default to the task indices. Parity:
    nodeRDD.foreachPartition."""

  @abc.abstractmethod
  def foreach_partition(self, partitions: Sequence[Iterable],
                        fn: Callable[[Iterable], object]) -> EngineJob:
    """Run ``fn(iter(partition))`` for each partition on free executors
    (async). Parity: dataRDD.foreachPartition."""

  @abc.abstractmethod
  def map_partitions(self, partitions: Sequence[Iterable],
                     fn: Callable[[Iterable], Iterable],
                     timeout: Optional[float] = None) -> List:
    """Run ``fn`` per partition, collect and concatenate results (blocking).
    Parity: dataRDD.mapPartitions(...).collect()."""

  @abc.abstractmethod
  def map_partitions_lazy(self, partitions: Sequence[Iterable],
                          fn: Callable[[Iterable], Iterable],
                          timeout: Optional[float] = None):
    """Non-collecting ``map_partitions``: return an engine-native lazy
    handle — Spark: the mapped RDD (parity: reference TFCluster.inference
    returning an uncollected RDD, TFCluster.py:96-115); Local: a generator
    streaming per-partition results — so cluster-scale inference output
    never materializes on the driver. ``timeout`` bounds per-partition
    completion where the engine executes eagerly-on-consume (Local); on
    Spark the deadline belongs to the caller's eventual RDD action."""

  @abc.abstractmethod
  def barrier_run(self, fn: Callable[[Iterable, "BarrierContext"], object],
                  num_tasks: Optional[int] = None,
                  timeout: Optional[float] = None) -> List:
    """Gang-schedule ``fn(iter([task_id]), barrier_ctx)`` on distinct
    executors; all tasks start together and get placement info. Parity:
    rdd.barrier().mapPartitions with BarrierTaskContext (TFParallel.py:43-56).
    Raises if num_tasks exceeds available executors."""

  def preempt_task(self, job: EngineJob, task_id: int) -> bool:
    """Forcibly stop a task that is still IN FLIGHT (fault recovery).

    Used by the cluster supervisor before relaunching a node whose task
    never completed — a hung user fn keeps its executor busy forever, and
    a pinned relaunch could never schedule behind it. Returns True when
    the task's executor was killed (the engine will fail the attempt and
    recycle the slot); False when unsupported or the task is not running.
    """
    return False

  def relaunch_task(self, job: EngineJob, task_id: int, payload=None):
    """Re-run ONE task of a previously submitted job (fault recovery).

    The cluster supervisor calls this to replace a node whose executor
    died: the task's bookkeeping in ``job`` is reset (waiters block again
    until the replacement finishes) and the stored fn re-runs with the
    original payload — or ``payload`` when given (e.g. to hand the
    relaunched node its restart count). Engines that cannot resubmit
    individual tasks raise NotImplementedError.
    """
    raise NotImplementedError(
        "%s does not support supervised task relaunch" % type(self).__name__)

  #: True when every executor runs on THIS host (LocalEngine) — enables
  #: same-host-only transports like the shared-memory feed ring
  colocated_executors = False

  def default_fs(self) -> str:
    """Default filesystem URI for path normalization."""
    return "file://"

  def stop(self) -> None:
    """Release engine resources (no-op by default)."""


class BarrierContext(object):
  """Placement info + synchronization for barrier tasks.

  Parity: pyspark BarrierTaskContext — ``get_task_infos()`` lists the
  addresses of all gang members; ``barrier()`` is a global sync point.
  """

  def __init__(self, task_id: int, addresses: List[str],
               sync_fn: Optional[Callable[[], None]] = None):
    self.task_id = task_id
    self.addresses = addresses
    self._sync_fn = sync_fn

  def get_task_infos(self) -> List[str]:
    return list(self.addresses)

  def barrier(self) -> None:
    if self._sync_fn is not None:
      self._sync_fn()
