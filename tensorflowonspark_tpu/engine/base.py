"""Engine interface: what the cluster layer needs from an executor engine.

Derived from how the reference drives Spark (TFCluster.py / TFSparkNode.py /
TFParallel.py):

- enumerate N persistent executors and run a function once on each
  (``nodeRDD.foreachPartition`` — node bring-up, shutdown jobs),
- stream partitioned data through whichever executors are free
  (``dataRDD.foreachPartition`` — feeding; ``dataRDD.mapPartitions`` —
  inference with collected results),
- gang-schedule with placement info (``rdd.barrier().mapPartitions``),
- replicate a dataset for epochs (``sc.union([rdd]*n)``).

Scheduling semantics the cluster layer RELIES on (Spark parity):

1. An executor runs one task at a time; a task that blocks keeps its executor
   busy (this is how ps/evaluator slots are kept out of feed scheduling —
   reference TFCluster.py:12-13).
2. ``run_on_executors`` places exactly one task on each distinct executor.
3. Queued tasks go to any executor that becomes free.
"""

import abc
import threading
from typing import Callable, Iterable, List, Optional, Sequence


class EngineJob(object):
  """Handle for an asynchronously running set of tasks."""

  def __init__(self, num_tasks: int):
    self.num_tasks = num_tasks
    self.results: List[object] = [None] * num_tasks
    self.errors: List[Optional[str]] = [None] * num_tasks
    self._done = 0
    self._cond = threading.Condition()

  def _task_finished(self, task_id: int, result=None, error: Optional[str] = None):
    with self._cond:
      self.results[task_id] = result
      self.errors[task_id] = error
      self._done += 1
      self._cond.notify_all()

  def done(self) -> bool:
    with self._cond:
      return self._done >= self.num_tasks

  def first_error(self) -> Optional[str]:
    with self._cond:
      for e in self.errors:
        if e is not None:
          return e
      return None

  def wait(self, timeout: Optional[float] = None, raise_on_error: bool = True):
    """Block until all tasks finish; raise the first task error by default."""
    with self._cond:
      import time
      deadline = None if timeout is None else time.monotonic() + timeout
      while self._done < self.num_tasks:
        remaining = None if deadline is None else deadline - time.monotonic()
        if remaining is not None and remaining <= 0:
          raise TimeoutError(
              "engine job timed out with %d/%d tasks finished"
              % (self._done, self.num_tasks))
        self._cond.wait(remaining if remaining is not None else 1.0)
    if raise_on_error:
      err = self.first_error()
      if err:
        raise RuntimeError("engine task failed:\n%s" % err)
    return self.results


class Engine(abc.ABC):
  """Abstract executor engine (see module docstring for the contract)."""

  @property
  @abc.abstractmethod
  def num_executors(self) -> int:
    ...

  @abc.abstractmethod
  def run_on_executors(self, fn: Callable[[Iterable], object],
                       num_tasks: Optional[int] = None,
                       task_payloads: Optional[Sequence] = None) -> EngineJob:
    """Run ``fn(iter([payload]))`` once on each of ``num_tasks`` distinct
    executors (async); payloads default to the task indices. Parity:
    nodeRDD.foreachPartition."""

  @abc.abstractmethod
  def foreach_partition(self, partitions: Sequence[Iterable],
                        fn: Callable[[Iterable], object]) -> EngineJob:
    """Run ``fn(iter(partition))`` for each partition on free executors
    (async). Parity: dataRDD.foreachPartition."""

  @abc.abstractmethod
  def map_partitions(self, partitions: Sequence[Iterable],
                     fn: Callable[[Iterable], Iterable],
                     timeout: Optional[float] = None) -> List:
    """Run ``fn`` per partition, collect and concatenate results (blocking).
    Parity: dataRDD.mapPartitions(...).collect()."""

  @abc.abstractmethod
  def map_partitions_lazy(self, partitions: Sequence[Iterable],
                          fn: Callable[[Iterable], Iterable],
                          timeout: Optional[float] = None):
    """Non-collecting ``map_partitions``: return an engine-native lazy
    handle — Spark: the mapped RDD (parity: reference TFCluster.inference
    returning an uncollected RDD, TFCluster.py:96-115); Local: a generator
    streaming per-partition results — so cluster-scale inference output
    never materializes on the driver. ``timeout`` bounds per-partition
    completion where the engine executes eagerly-on-consume (Local); on
    Spark the deadline belongs to the caller's eventual RDD action."""

  @abc.abstractmethod
  def barrier_run(self, fn: Callable[[Iterable, "BarrierContext"], object],
                  num_tasks: Optional[int] = None,
                  timeout: Optional[float] = None) -> List:
    """Gang-schedule ``fn(iter([task_id]), barrier_ctx)`` on distinct
    executors; all tasks start together and get placement info. Parity:
    rdd.barrier().mapPartitions with BarrierTaskContext (TFParallel.py:43-56).
    Raises if num_tasks exceeds available executors."""

  #: True when every executor runs on THIS host (LocalEngine) — enables
  #: same-host-only transports like the shared-memory feed ring
  colocated_executors = False

  def default_fs(self) -> str:
    """Default filesystem URI for path normalization."""
    return "file://"

  def stop(self) -> None:
    """Release engine resources (no-op by default)."""


class BarrierContext(object):
  """Placement info + synchronization for barrier tasks.

  Parity: pyspark BarrierTaskContext — ``get_task_infos()`` lists the
  addresses of all gang members; ``barrier()`` is a global sync point.
  """

  def __init__(self, task_id: int, addresses: List[str],
               sync_fn: Optional[Callable[[], None]] = None):
    self.task_id = task_id
    self.addresses = addresses
    self._sync_fn = sync_fn

  def get_task_infos(self) -> List[str]:
    return list(self.addresses)

  def barrier(self) -> None:
    if self._sync_fn is not None:
      self._sync_fn()
