"""LocalEngine: a built-in multi-process executor engine with Spark semantics.

Replaces the role Spark Standalone played in the reference's test strategy
(reference tox.ini / tests/run_tests.sh: a 2-worker single-core standalone
cluster on one host, because Spark *local* mode shares one process and TFoS
assumes separate executor processes — reference tests/README.md:10).

Semantics implemented (see engine.base for why they matter):
- N persistent executor processes, spawned (not forked — safe to initialize
  JAX inside tasks), each with its own working directory and
  ``TOS_EXECUTOR_SLOT`` env var;
- one task at a time per executor; a blocked task keeps its executor busy;
- pinned tasks (node bring-up, barrier gangs) target a specific executor,
  queued tasks go to whichever executor frees up first;
- closures serialized with cloudpickle, like Spark serializes task closures.
"""

import logging
import multiprocessing as mp
import os
import shutil
import tempfile
import threading
import traceback
from collections import deque
from typing import Dict, Iterable, List, Optional, Sequence

import cloudpickle

from tensorflowonspark_tpu.control import chunkcodec
from tensorflowonspark_tpu.engine.base import (EXECUTOR_LOST, BarrierContext,
                                               Engine, EngineJob)

logger = logging.getLogger(__name__)

_STOP = "__stop_executor__"

#: exported into every executor process; consumed by pipeline transforms
#: for host-local chip placement (env registry: tools.analyze TOS008)
ENV_EXECUTOR_SLOT = "TOS_EXECUTOR_SLOT"


def _executor_main(slot: int, workdir: str, task_q, result_q, env: Dict[str, str]):
  """Executor process entry point: run one task at a time, forever."""
  os.chdir(workdir)
  os.environ.update(env)
  os.environ[ENV_EXECUTOR_SLOT] = str(slot)
  while True:
    item = task_q.get()
    if item == _STOP:
      break
    job_id, task_id, attempt, fn_bytes, data_bytes = item
    try:
      fn = cloudpickle.loads(fn_bytes)
      data = chunkcodec.decode(data_bytes)
      result = fn(iter(data))
      # mapPartitions-style fns may return generators; materialize here,
      # inside the executor, like Spark does on collect
      if result is not None and hasattr(result, "__iter__") \
          and not isinstance(result, (list, tuple, str, bytes, dict)):
        result = list(result)
      result_q.put((slot, job_id, task_id, attempt, "ok",
                    cloudpickle.dumps(result)))
    except BaseException:  # noqa: BLE001 - full traceback must reach driver
      result_q.put((slot, job_id, task_id, attempt, "err",
                    traceback.format_exc()))


class LocalEngine(Engine):
  """Multi-process engine; see module docstring."""

  colocated_executors = True

  def __init__(self, num_executors: int = 2, workdir: Optional[str] = None,
               env: Optional[Dict[str, str]] = None):
    self._num_executors = num_executors
    self._root = workdir or tempfile.mkdtemp(prefix="tos_tpu_engine_")
    self._owns_root = workdir is None
    self._ctx = mp.get_context("spawn")
    self._result_q = self._ctx.Queue()
    self._procs = []
    self._task_qs = []
    self._env = dict(env or {})
    for slot in range(num_executors):
      wd = os.path.join(self._root, "executor_%d" % slot)
      os.makedirs(wd, exist_ok=True)
      tq = self._ctx.Queue()
      # non-daemonic: executors must be able to spawn children (feed hub
      # manager processes, background node processes); cleanup is handled by
      # stop() + the atexit hook below
      p = self._ctx.Process(target=_executor_main,
                            args=(slot, wd, tq, self._result_q, self._env),
                            daemon=False, name="local-executor-%d" % slot)
      p.start()
      self._procs.append(p)
      self._task_qs.append(tq)

    # scheduler state
    self._lock = threading.Lock()
    self._idle = set(range(num_executors))
    self._pinned: List[deque] = [deque() for _ in range(num_executors)]
    self._shared: deque = deque()
    self._running: Dict[int, tuple] = {}   # slot -> (job_id, task_id)
    self._jobs: Dict[int, EngineJob] = {}
    self._next_job_id = 0
    self._stopped = threading.Event()
    self._collector = threading.Thread(target=self._collect, daemon=True,
                                       name="local-engine-collector")
    self._collector.start()
    # dead-executor supervision: a SIGKILLed/preempted executor process is
    # detected (its in-flight task failed with the ExecutorLost marker) and
    # the slot is respawned so pinned relaunches have somewhere to run
    self._monitor = threading.Thread(target=self._monitor_procs, daemon=True,
                                     name="local-engine-monitor")
    self._monitor.start()
    import atexit
    atexit.register(self.stop)

  # -- Engine interface ------------------------------------------------------

  @property
  def num_executors(self) -> int:
    return self._num_executors

  def executor_workdir(self, slot: int) -> str:
    return os.path.join(self._root, "executor_%d" % slot)

  def run_on_executors(self, fn, num_tasks: Optional[int] = None,
                       task_payloads=None) -> EngineJob:
    n = num_tasks if num_tasks is not None else self._num_executors
    if n > self._num_executors:
      raise ValueError("requested %d tasks but engine has %d executors"
                       % (n, self._num_executors))
    payloads = list(task_payloads) if task_payloads is not None \
        else list(range(n))
    if len(payloads) != n:
      raise ValueError("task_payloads has %d entries for %d tasks"
                       % (len(payloads), n))
    job = self._new_job(n)
    fn_bytes = cloudpickle.dumps(fn)
    with self._lock:
      for i in range(n):
        data_bytes = chunkcodec.encode([payloads[i]])
        job._task_specs[i] = (fn_bytes, data_bytes, i)   # pinned to slot i
        self._pinned[i].append((job.job_id, i, 0, fn_bytes, data_bytes))
      self._schedule_locked()
    return job

  def foreach_partition(self, partitions: Sequence[Iterable], fn) -> EngineJob:
    job = self._new_job(len(partitions))
    fn_bytes = cloudpickle.dumps(fn)
    with self._lock:
      for i, part in enumerate(partitions):
        # feeder side of the feed plane: homogeneous row partitions cross
        # the driver→executor task queue COLUMNAR (one buffer per column,
        # control/chunkcodec.py) instead of as a per-row pickle walk;
        # anything else falls back to cloudpickle inside the codec
        data_bytes = chunkcodec.encode(part)
        job._task_specs[i] = (fn_bytes, data_bytes, None)  # any free slot
        self._shared.append((job.job_id, i, 0, fn_bytes, data_bytes))
      self._schedule_locked()
    return job

  def preempt_task(self, job: EngineJob, task_id: int) -> bool:
    """SIGKILL the executor running one of ``job``'s tasks (see Engine
    contract): the monitor then fails the attempt with ExecutorLost and
    respawns the slot, so a queued relaunch can actually schedule."""
    with self._lock:
      for slot, running in self._running.items():
        if running[0] == getattr(job, "job_id", None) and \
            running[1] == task_id:
          pid = self._procs[slot].pid
          break
      else:
        return False
    logger.warning("preempting task %d of job %s (killing executor pid %s)",
                   task_id, job.job_id, pid)
    try:
      os.kill(pid, 9)
    except OSError:
      pass
    return True

  def relaunch_task(self, job: EngineJob, task_id: int, payload=None):
    """Re-queue one task of ``job`` (fault recovery; see Engine contract).

    Pinned tasks return to their original executor slot — which the
    monitor has respawned if its process died — so a relaunched node keeps
    its working directory (and therefore its hub-reclaim and executor-id
    state). ``payload`` (when given) replaces the task's original payload.
    """
    spec = job._task_specs.get(task_id)
    if spec is None:
      raise ValueError("job %s task %d has no stored spec to relaunch"
                       % (getattr(job, "job_id", "?"), task_id))
    fn_bytes, data_bytes, slot = spec
    if payload is not None:
      data_bytes = chunkcodec.encode([payload])
      job._task_specs[task_id] = (fn_bytes, data_bytes, slot)
    attempt = job._task_restarted(task_id)
    with self._lock:
      self._jobs[job.job_id] = job     # re-track (evicted when it finished)
      task = (job.job_id, task_id, attempt, fn_bytes, data_bytes)
      if slot is not None:
        self._pinned[slot].append(task)
      else:
        self._shared.append(task)
      self._schedule_locked()

  def map_partitions(self, partitions, fn, timeout=None) -> List:
    job = self.foreach_partition(partitions, fn)
    results = job.wait(timeout=timeout)
    out = []
    for r in results:
      if r is None:
        continue
      out.extend(r if isinstance(r, (list, tuple)) else [r])
    return out

  def map_partitions_lazy(self, partitions, fn, timeout: Optional[float] = None,
                          window: Optional[int] = None):
    """Generator of per-partition results, at most ``window`` partitions
    in flight (default: one per executor). The driver holds one window of
    results instead of the whole output — the LocalEngine analog of
    returning an uncollected RDD. ``timeout`` bounds each partition's
    completion like the eager path's deadline."""
    window = window or self._num_executors

    def _gen():
      pending: deque = deque()
      parts = iter(partitions)

      def _submit():
        try:
          part = next(parts)
        except StopIteration:
          return False
        pending.append(self.foreach_partition([part], fn))
        return True

      for _ in range(window):
        if not _submit():
          break
      while pending:
        results = pending.popleft().wait(timeout=timeout)
        _submit()
        for r in results:
          if r is None:
            continue
          for row in (r if isinstance(r, (list, tuple)) else [r]):
            yield row

    return _gen()

  def barrier_run(self, fn, num_tasks: Optional[int] = None,
                  timeout: Optional[float] = None) -> List:
    """Gang-schedule with placement info and a reusable barrier.

    Oversubscription fails fast (parity: Spark barrier mode raising when the
    gang cannot be scheduled at once — reference tests/test_TFParallel.py).
    """
    n = num_tasks if num_tasks is not None else self._num_executors
    if n > self._num_executors:
      raise ValueError(
          "barrier gang of %d cannot be scheduled on %d executors"
          % (n, self._num_executors))
    from tensorflowonspark_tpu.control.rendezvous import Client, Server
    from tensorflowonspark_tpu.utils.hostinfo import get_ip_address
    server = Server(n)
    addr = server.start()
    ip = get_ip_address()
    addresses = ["%s:%d" % (ip, slot) for slot in range(n)]

    def _barrier_task(it, _fn=fn, _addr=addr, _addresses=addresses, _n=n):
      task_id = next(iter(it))
      client = Client((_addr[0], _addr[1]))
      client.register({"executor_id": task_id, "host": _addresses[task_id]})
      client.await_reservations(timeout=60)  # gang start line

      state = {"round": 0}

      def sync():
        state["round"] += 1
        client.barrier_wait(state["round"], required=_n, timeout=600,
                            task_id=task_id)

      ctx = BarrierContext(task_id, _addresses, sync_fn=sync)
      try:
        return _fn(iter([task_id]), ctx)
      finally:
        client.close()

    try:
      job = self.run_on_executors(_barrier_task, num_tasks=n)
      return job.wait(timeout=timeout)
    finally:
      server.stop()

  def stop(self) -> None:
    if self._stopped.is_set():
      return
    self._stopped.set()
    with self._lock:
      pass   # fence: a monitor-thread respawn in flight completes first
    for tq in self._task_qs:
      try:
        # bounded: a wedged/full task queue must not hang driver shutdown
        # — the executor process is terminated below regardless
        tq.put(_STOP, timeout=5)
      except Exception:  # noqa: BLE001
        pass
    for p in self._procs:
      p.join(timeout=5)
      if p.is_alive():
        p.terminate()
        p.join(timeout=5)
    if self._owns_root:
      shutil.rmtree(self._root, ignore_errors=True)

  # -- internals -------------------------------------------------------------

  def _new_job(self, num_tasks: int) -> EngineJob:
    job = EngineJob(num_tasks)
    job._task_specs = {}   # task_id -> (fn_bytes, data_bytes, pinned_slot)
    with self._lock:
      job.job_id = self._next_job_id
      self._next_job_id += 1
      self._jobs[job.job_id] = job
    return job

  def _schedule_locked(self) -> None:
    """Assign queued tasks to idle executors (caller holds self._lock)."""
    for slot in list(self._idle):
      task = None
      if self._pinned[slot]:
        task = self._pinned[slot].popleft()
      elif self._shared:
        task = self._shared.popleft()
      if task is not None:
        self._idle.discard(slot)
        self._running[slot] = (task[0], task[1], task[2])
        self._task_qs[slot].put(task)

  def _collect(self) -> None:
    while not self._stopped.is_set():
      try:
        slot, job_id, task_id, attempt, status, payload = \
            self._result_q.get(timeout=0.25)
      except Exception:  # noqa: BLE001 - queue.Empty or closed queue
        continue
      with self._lock:
        self._running.pop(slot, None)
        self._idle.add(slot)
        self._schedule_locked()
        job = self._jobs.get(job_id)
      if job is None:
        continue
      if status == "ok":
        job._task_finished(task_id, result=cloudpickle.loads(payload),
                           attempt=attempt)
      else:
        job._task_finished(task_id, error=payload, attempt=attempt)
      if job.done():
        # evict finished jobs so the engine doesn't pin every job's results
        # forever (the lazy map path depends on this for bounded memory)
        with self._lock:
          self._jobs.pop(job_id, None)

  def _monitor_procs(self) -> None:
    """Detect executor processes that died (SIGKILL, OOM, crash): fail the
    in-flight task with the ExecutorLost marker and respawn the slot."""
    while not self._stopped.wait(0.2):
      for slot in range(self._num_executors):
        if self._stopped.is_set():
          return
        if self._procs[slot].is_alive():
          continue
        dead_job = None
        with self._lock:
          if self._stopped.is_set():
            return
          proc = self._procs[slot]
          if proc.is_alive():
            continue
          pid = proc.pid
          running = self._running.pop(slot, None)
          if running is not None:
            dead_job = self._jobs.get(running[0])
          wd = os.path.join(self._root, "executor_%d" % slot)
          # FRESH task queue: a process SIGKILLed while blocked in
          # task_q.get() dies holding the queue's reader lock, poisoning
          # it for any successor. Nothing pending is lost — the scheduler
          # dispatches at most one task per slot, and that task (if any)
          # was just failed above. (Known gap: the SHARED result_q has a
          # microsecond analogue — a kill landing mid-result-put holds its
          # write lock; fixing that needs per-slot result queues.)
          self._task_qs[slot] = self._ctx.Queue()
          new = self._ctx.Process(
              target=_executor_main,
              args=(slot, wd, self._task_qs[slot], self._result_q, self._env),
              daemon=False, name="local-executor-%d" % slot)
          new.start()
          self._procs[slot] = new
          self._idle.add(slot)
          self._schedule_locked()
        logger.warning("executor slot %d (pid %s) died; respawned as pid %d",
                       slot, pid, new.pid)
        if dead_job is not None:
          dead_job._task_finished(
              running[1],
              error="%s: executor process (slot %d, pid %s) died while "
                    "running task %d of job %d — killed or crashed without "
                    "a traceback" % (EXECUTOR_LOST, slot, pid, running[1],
                                     running[0]),
              attempt=running[2])

  def __del__(self):
    try:
      self.stop()
    except Exception:  # noqa: BLE001
      pass
