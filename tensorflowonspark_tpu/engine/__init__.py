"""Executor-engine abstraction.

The reference is hard-wired to Apache Spark: executors are enumerated by an
RDD (`sc.parallelize(range(n), n)`) and every cluster operation is a Spark job
(reference TFCluster.py:301,321). This package abstracts that contract so the
same cluster/node layers run on:

- ``SparkEngine`` — a thin adapter over pyspark (imported lazily; optional),
- ``LocalEngine`` — a built-in multi-process engine with Spark's scheduling
  semantics (persistent single-core executors, one task at a time, free
  executors pull queued tasks), used for tests and single-host runs the way
  the reference used a 2-worker Spark standalone cluster (reference tox.ini).
"""

from tensorflowonspark_tpu.engine.base import Engine, EngineJob  # noqa: F401
from tensorflowonspark_tpu.engine.local import LocalEngine  # noqa: F401


def get_engine(name: str = "local", **kwargs) -> Engine:
  """Engine factory: ``'local'`` or ``'spark'``."""
  if name == "local":
    return LocalEngine(**kwargs)
  if name == "spark":
    from tensorflowonspark_tpu.engine.spark import SparkEngine
    return SparkEngine(**kwargs)
  raise ValueError("unknown engine: %r" % name)
