"""Schema types + ``struct<name:type,...>`` hint-string parser.

Parity with the reference's Scala SimpleTypeParser
(/root/reference/src/main/scala/.../SimpleTypeParser.scala:27-64): 8 base
types plus 1-D arrays, e.g. ``struct<label:int,features:array<float>>``.
Also carries the schema model used by dfutil-style inference (binary vs
string disambiguation hint, reference dfutil.py:134-168).
"""

import re
from dataclasses import dataclass
from typing import List

BASE_TYPES = ("binary", "boolean", "double", "float", "int", "bigint",
              "long", "string")

# normalization: Spark SQL-ish names -> canonical
_ALIASES = {"bigint": "long", "int": "int", "integer": "int"}


@dataclass(frozen=True)
class Field:
  name: str
  dtype: str          # canonical base type
  is_array: bool = False

  def __str__(self):
    t = "array<%s>" % self.dtype if self.is_array else self.dtype
    return "%s:%s" % (self.name, t)


@dataclass(frozen=True)
class Schema:
  fields: tuple

  def names(self) -> List[str]:
    return [f.name for f in self.fields]

  def field(self, name: str) -> Field:
    for f in self.fields:
      if f.name == name:
        return f
    raise KeyError(name)

  def __str__(self):
    return "struct<%s>" % ",".join(str(f) for f in self.fields)


_FIELD_RE = re.compile(
    r"^\s*([A-Za-z_][A-Za-z0-9_]*)\s*:\s*"
    r"(?:array\s*<\s*([a-z]+)\s*>|([a-z]+))\s*$")


def _split_fields(body: str) -> List[str]:
  """Split on commas not nested inside array<...>."""
  parts, depth, cur = [], 0, []
  for ch in body:
    if ch == "<":
      depth += 1
    elif ch == ">":
      depth -= 1
    if ch == "," and depth == 0:
      parts.append("".join(cur))
      cur = []
    else:
      cur.append(ch)
  if cur:
    parts.append("".join(cur))
  return parts


def parse_schema(text: str) -> Schema:
  """Parse ``struct<name:type,...>`` (types: 8 base types + array<base>)."""
  text = text.strip()
  m = re.match(r"^struct\s*<(.*)>$", text, re.DOTALL)
  if not m:
    raise ValueError("schema must look like struct<name:type,...>: %r" % text)
  fields = []
  for part in _split_fields(m.group(1)):
    if not part.strip():
      continue
    fm = _FIELD_RE.match(part)
    if not fm:
      raise ValueError("unparseable schema field: %r" % part)
    name, array_type, base_type = fm.groups()
    dtype = array_type or base_type
    dtype = _ALIASES.get(dtype, dtype)   # normalize before validation
    if dtype not in BASE_TYPES:
      raise ValueError("unknown type %r in field %r (known: %s)"
                       % (dtype, name, ", ".join(BASE_TYPES)))
    fields.append(Field(name, dtype, is_array=bool(array_type)))
  if not fields:
    raise ValueError("empty schema: %r" % text)
  return Schema(tuple(fields))
